"""Chaos soak gate: drive the stack under seeded fault schedules and
assert the hardened failure semantics hold (tools/ci.sh step).

What it proves (the invariants the multi-node work assumes,
docs/RELIABILITY.md):

1. ENGINE SOAK — an LLMEngine under injected ``device.dispatch`` /
   ``device.transfer`` faults plus a deadline/priority/shed workload:
   every submitted future RESOLVES (value, DeadlineExceeded,
   AdmissionShed, RequestCancelled, or a typed error — never hangs),
   per-request device-retry budgets re-admit faulted requests with
   token-identical streams, and the injected-fault sequence matches
   the pure seeded schedule exactly (same seed → same faults).
2. CANCELLATION STORM — mass ``cancel()`` mid-generation: futures all
   resolve RequestCancelled/result, KV pages are leak-free after
   close, and (with tracing on) no ``llm.*`` span is left open.
3. CRASH-CONSISTENT CHECKPOINTS — a subprocess worker is SIGKILLed
   mid-``CheckpointManager.save``; the directory must still restore
   its latest committed step AND accept new saves. Injected
   ``ckpt.write`` faults are absorbed by the shared retry policy;
   an injected ``ckpt.rename`` (commit-stage) fault fails the save
   call but never corrupts the directory.
4. FLIGHT-RECORDER ESCALATION — a chaos-injected ``io.worker`` fault
   inside ``Model.fit`` escalates to a process crash; the PR-4 flight
   recorder must leave a JSONL dump naming the injected fault.
5. FLEET SOAK (``--fleet``) — a router over K=3 spawned replica
   subprocesses (TCPStore membership): an injected device-fault streak
   drains one replica (router stops admitting to it within a poll
   interval; POST /reset_health recovers it), a SIGKILL mid-decode
   loses ZERO requests (failover re-submits with the same nonce —
   token-identical streams, checked against a reference engine), the
   killed replica's breaker walks open → half-open → closed across a
   respawn, and an injected ``router.dispatch`` fault replays from its
   seed like any other site. Fleet observability rides the same soak:
   ``GET /fleetz`` must aggregate the replicas with per-replica data,
   and an injected DEADLINE-MISS STORM against one SLO class must move
   its multi-window burn-rate gauges on ``GET /sloz`` and latch the
   breach (visible on /healthz, cleared by the reset). On ANY fleet
   assertion failure the report attaches a MERGED cross-process trace
   (router + replicas via tools/trace_merge) next to the fault seed
   and replay command.

8. GOODPUT FORENSICS (default path) — chaos must be visible on the
   time ledger: a seeded ``device.dispatch`` storm grows the
   ``recovery`` bucket on ``GET /goodputz`` and async saves under a
   seeded ``ckpt.async_commit`` fault grow ``ckpt_stall``, with the
   reconciliation line closed throughout; a disabled ledger's
   ``note()`` costs one module-flag check (time-bounded) and records
   nothing.

Determinism: every schedule is nth/probability-based with a fixed
seed; ``faults.preview(site, N)`` recomputes the faulting call
numbers purely, and the soak asserts the observed injection log
equals that schedule.

5b. AUTOSCALE SOAK (``--autoscale``) — the SLO-driven autoscaler over
   a live subprocess fleet (ISSUE 13): a gold-class deadline-miss
   storm trips both burn windows and triggers a scale-out whose first
   spawn attempt dies on the seeded ``autoscale.spawn`` fault (the
   retry absorbs it; the replica counts toward capacity only after
   READY + a successful health probe, and a failed attempt leaves no
   ghost capacity); a SIGKILL of the autoscaled replica mid-decode
   loses ZERO requests (nonce-pinned token-identical failover) and is
   respawned as a REPLACEMENT, not a scale-out; a seeded
   ``autoscale.drain`` fault expires the scale-in drain deadline with
   stragglers in flight, which must complete token-identically on a
   sibling; the terminated replica leaves TCPStore membership
   immediately; both sites replay from the seed. (The static-K vs
   autoscaled replica-seconds/SLO comparison rides
   ``tools/llm_bench.py --ci --storm`` — together they are the
   ISSUE-13 CI gate.)

5c. OVERLOAD SOAK (``--overload``) — the brownout-controller gate
   (ISSUE 20): an in-process two-replica fleet under a seeded 3×
   burst storm of deadline-doomed bronze traffic plus protected gold.
   Every future resolves TYPED (ok / deadline / shed — never error,
   never a hang); gold loses ZERO requests at every ladder level; the
   brownout ladder walks up under burn pressure (one level per
   transition, dwell-bounded) and back to normal after the storm
   drains; a seeded ``overload.estimate`` fault distorts predictions
   1000× and degrades to visible hopeless-shed verdicts; a seeded
   ``overload.step`` fault forces a spurious escalation the
   hysteresis walks back; both sites replay from the seed.

6b. POISONED-STREAM SOAK (rides ``--train``) — the numeric-guard gate
   (ISSUE 9): under a seeded ``data.poison`` / ``grad.nonfinite``
   schedule with the on-device NumericGuard armed (skip policy), the
   final params hex must be BYTE-IDENTICAL to a clean run over the
   same stream with the tripped steps removed, at steps_per_loop ∈
   {1, 4}; the rollback policy must restore a verified checkpoint and
   complete; and guard-off must add zero device work (the lowered
   step program carries no finite-check ops — the one-flag-check
   discipline, plus a wall-clock sanity bound). Assertion failures
   print the fault seed + replay command and attach a flight dump.

6. TRAIN SOAK (``--train``) — the kill-anywhere/resume-exactly gate
   (ISSUE 8): a training worker runs ``Model.fit`` with async
   full-state checkpointing (``checkpoint_dir`` + ``resume="auto"`` +
   ``PreemptionGuard``), announcing phase markers (STEP / SNAPSHOT /
   COMMIT / GC). The parent SIGKILLs it at seeded random points —
   mid-step, mid-snapshot, mid-async-commit, mid-GC — or SIGTERMs it
   (graceful preemption: deadline-budgeted emergency flush, exit 67),
   relaunches until completion, and asserts the combined loss stream
   is BIT-IDENTICAL (float hex) to an uninterrupted baseline at
   ``steps_per_loop`` ∈ {1, 4}, including every re-run overlap step.
   Also: a byte-corrupted newest checkpoint is quarantined on restore
   (falls back to the newest verified step and never surfaces through
   ``latest_step()`` again), ``ckpt.snapshot``/``ckpt.async_commit``
   faults replay from their seed, and an async save's measured
   train-loop stall stays bounded by the device→host snapshot time
   while a (slowed) commit runs in the background.

7. FUSED-SLAB SOAK (``--slab``) — the device-resident decode loop
   (ISSUE 10): the engine scenarios replayed at
   ``decode_ticks_per_dispatch=8`` with the new ``engine.slab`` fault
   site killing slab dispatches on schedule. Every future resolves;
   budgeted retries reproduce streams TOKEN-IDENTICAL to a fault-free
   reference engine (nonce-pinned); deadline/cancel storms landing
   mid-slab resolve typed within a slab boundary with their KV pages
   reclaimed; the injected sequence replays from its seed. Rides
   along: a PAGE-PRESSURE STORM (ISSUE 14) against a tiny pool
   asserting the memory ledger's ``mem_headroom_pages`` gauge hits
   ~0 exactly when slab-shrink engages, the kv_pool attribution rows
   tile the pool at every sampled instant, and headroom recovers to
   the full usable pool after the storm drains (gauge unexported —
   a hole — once the engine closes).

Run:  python tools/chaos_soak.py            # full soak (default seed)
CI:   python tools/chaos_soak.py --ci       # fixed seeds, ~30s budget
      python tools/chaos_soak.py --ci --slab    # fused decode slabs,
                                                # ~30s budget
      python tools/chaos_soak.py --ci --fleet   # replica-kill soak,
                                                # ≤45s budget
      python tools/chaos_soak.py --ci --autoscale  # autoscaler soak,
                                                # ≤90s budget
      python tools/chaos_soak.py --ci --overload  # brownout soak,
                                                # ≤60s budget
      python tools/chaos_soak.py --ci --train   # kill-anywhere train
                                                # soak + poisoned-
                                                # stream guard gate,
                                                # ≤90s budget
Any assertion failure prints the fault seed and the one-line replay
command, so a red CI run reproduces in one copy-paste.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import threading
import time
from concurrent.futures import wait as fut_wait

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FUTURE_TIMEOUT = 240.0   # "never hangs" ceiling (compile included)


def _tiny_gpt():
    import paddle_tpu as pt
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt_config
    pt.seed(0)
    cfg = gpt_config("gpt2-small", num_layers=2, hidden_size=64,
                     num_heads=4, vocab_size=97,
                     max_position_embeddings=96, hidden_dropout=0.0,
                     attention_dropout=0.0)
    return GPTForCausalLM(cfg)


def _assert_schedule_matches(faults, sites):
    """The determinism gate: the observed injection log must equal the
    pure seeded schedule truncated to the calls each site actually
    made."""
    log = faults.injected_log()
    assert faults.injected_log_dropped() == 0, (
        "injection log overflowed its bound — raise _LOG_CAP or "
        "shorten the soak; exact-schedule comparison would be "
        "spuriously wrong")
    for site in sites:
        n = faults.call_count(site)
        want = faults.preview(site, n)
        got = [c for s, c in log if s == site]
        assert got == want, (
            f"injected-fault sequence for {site} diverged from the "
            f"seeded schedule: got {got}, schedule {want} "
            f"(over {n} calls)")


def engine_soak(seed: int) -> dict:
    """Scenarios 1 + 2 on one engine (one compile budget): fault soak
    first, then — faults disarmed — a cancellation storm, then the
    leak/span audit after close."""
    from paddle_tpu.inference.llm import (AdmissionShed, AdmissionTimeout,
                                          LLMEngine, RequestCancelled)
    from paddle_tpu.observability import tracing
    from paddle_tpu.reliability import faults
    from paddle_tpu.reliability.retry import DeadlineExceeded

    rng = np.random.RandomState(seed)
    tracing.enable()
    faults.reset()
    faults.enable(seed=seed)
    # schedule: nth/p rules only (pure → previewable). At most 4
    # injections total (2 nth calls + 1 capped p + 1 transfer), so a
    # device_retry_budget of 4 means no request may be LOST to chaos —
    # every non-shed/deadline/cancel future must still produce tokens.
    faults.inject("device.dispatch", nth=(5, 12))
    faults.inject("device.dispatch", p=0.01, times=1)
    faults.inject("device.transfer", nth=(9,))

    net = _tiny_gpt()
    eng = LLMEngine(net, max_seqs=4, page_size=4, num_pages=96,
                    prefill_buckets=(16,), max_pending=8,
                    admit_timeout=60.0, device_retry_budget=4,
                    drain_after=64)
    outcomes = {"ok": 0, "deadline": 0, "shed": 0, "cancelled": 0,
                "admission_timeout": 0, "error": 0}

    def tally(futs):
        done, not_done = fut_wait(futs, timeout=FUTURE_TIMEOUT)
        assert not not_done, (
            f"{len(not_done)} futures never resolved — the engine "
            f"hung under injected faults")
        for f in futs:
            exc = f.exception()
            if exc is None:
                assert f.result()["output_ids"] is not None
                outcomes["ok"] += 1
            elif isinstance(exc, DeadlineExceeded):
                outcomes["deadline"] += 1
            elif isinstance(exc, AdmissionShed):
                outcomes["shed"] += 1
            elif isinstance(exc, RequestCancelled):
                outcomes["cancelled"] += 1
            elif isinstance(exc, AdmissionTimeout):
                outcomes["admission_timeout"] += 1
            else:
                outcomes["error"] += 1

    try:
        # phase 1: normal service while the fault schedule fires —
        # the retry budget must make chaos invisible in the outcomes
        tally([eng.submit(
            rng.randint(0, 97, rng.randint(3, 12)).tolist(),
            max_new_tokens=int(rng.randint(6, 12)),
            priority=int(i % 3)) for i in range(6)])
        assert outcomes["ok"] == 6, (
            f"requests lost to budgeted chaos: {outcomes}")

        # phase 2: hopeless deadlines resolve typed, never hang
        tally([eng.submit(rng.randint(0, 97, 5).tolist(),
                          max_new_tokens=8, deadline=-1.0)
               for _ in range(3)])
        assert outcomes["deadline"] == 3, outcomes

        # phase 3: a burst wide enough to overflow max_pending=8 on 4
        # slots — overflow sheds, the rest completes
        tally([eng.submit(rng.randint(0, 97, 4).tolist(),
                          max_new_tokens=16) for _ in range(16)])
        assert outcomes["shed"] >= 1, outcomes
        assert outcomes["error"] == 0, (
            f"chaos leaked through the retry budget: {outcomes}")

        _assert_schedule_matches(
            faults, ("device.dispatch", "device.transfer"))
        n_injected = len(faults.injected_log())
        assert n_injected >= 3, (
            f"schedule armed but only {n_injected} faults injected — "
            f"the soak did not exercise the failure paths")

        # phase 4: cancellation storm, faults off
        faults.disable()
        eng.reset_health()
        storm = [eng.submit(rng.randint(0, 97, 6).tolist(),
                            max_new_tokens=80) for _ in range(8)]
        # half cancelled immediately (microseconds after submit — a
        # cancel can only miss if the request fully generated first,
        # impossible for 80 tokens), half after some reach decode
        for f in storm[::2]:
            eng.cancel(f.request_id)
        time.sleep(0.2)
        for f in storm[1::2]:
            eng.cancel(f.request_id)
        done, not_done = fut_wait(storm, timeout=FUTURE_TIMEOUT)
        assert not not_done, "cancellation storm left futures pending"
        n_cancelled = 0
        for f in storm:
            exc = f.exception()
            assert exc is None or isinstance(exc, RequestCancelled), exc
            n_cancelled += exc is not None
        outcomes["cancelled"] += n_cancelled
        assert n_cancelled >= 1, "storm cancelled nothing"
    finally:
        eng.close()
        faults.reset()
    # leak audit: every page back in the pool after close (the prefix
    # cache was flushed; shared pages returned)
    assert len(eng._free_pages) == eng.num_pages - 1, (
        f"KV pages leaked: {len(eng._free_pages)} free of "
        f"{eng.num_pages - 1} usable")
    # span audit: no llm.* span left open anywhere
    open_llm = [s for s in tracing.live_spans()
                if s["name"].startswith("llm.")]
    tracing.disable()
    assert not open_llm, f"span trees left open: {open_llm}"
    return outcomes


def slab_soak(seed: int, mixed: bool = False,
              kv_dtype=None) -> dict:
    """ISSUE 10 phase: the engine invariants under FUSED DECODE SLABS
    (``decode_ticks_per_dispatch=8``) — an injected ``engine.slab``
    kill storm at the slab dispatch, hopeless deadlines, and a
    cancellation storm landing mid-slab. Asserts: every future
    resolves; retried streams are TOKEN-IDENTICAL to a fault-free
    reference engine over the same prompts (device retries keep the
    nonce, and a slab re-admission replays the same sampled stream);
    deadlines/cancels resolve typed within a slab boundary; zero KV
    pages leak and no ``llm.*`` span stays open after close; the
    injected sequence equals the pure seeded schedule.

    ISSUE 15 rider (``mixed=True, kv_dtype="int8"``): the SAME storm
    through the ragged MIXED tick on an int8-quantized pool —
    ``engine.slab`` faults fire at the mixed dispatch too, and
    nonce-pinned token identity must hold against an int8+mixed
    reference (quantization is deterministic, so chaos stays
    invisible in the streams)."""
    from paddle_tpu.inference.llm import LLMEngine, RequestCancelled
    from paddle_tpu.observability import tracing
    from paddle_tpu.reliability import faults
    from paddle_tpu.reliability.retry import DeadlineExceeded

    rng = np.random.RandomState(seed)
    prompts = [rng.randint(0, 97, int(rng.randint(3, 12))).tolist()
               for _ in range(6)]
    gens = [int(rng.randint(8, 20)) for _ in range(6)]
    net = _tiny_gpt()

    def build(**kw):
        return LLMEngine(net, max_seqs=4, page_size=4, num_pages=96,
                         prefill_buckets=(16,), drain_after=64,
                         decode_ticks_per_dispatch=8,
                         mixed_tick=mixed, kv_dtype=kv_dtype, **kw)

    # fault-free reference streams: same engine seed, same submission
    # order => same nonces => the chaos run must reproduce these
    # exactly even when its slabs die and re-admit
    with build() as ref_eng:
        ref = [f.result(timeout=FUTURE_TIMEOUT) for f in
               [ref_eng.submit(p, max_new_tokens=g)
                for p, g in zip(prompts, gens)]]
    assert len(ref_eng._free_pages) == ref_eng.num_pages - 1

    tracing.enable()
    faults.reset()
    faults.enable(seed=seed)
    # at most 4 injections (2 nth + 1 capped p at the slab dispatch +
    # 1 transfer) against a retry budget of 4: chaos must be invisible
    # in the outcomes AND in the token streams
    faults.inject("engine.slab", nth=(2, 5))
    faults.inject("engine.slab", p=0.02, times=1)
    faults.inject("device.transfer", nth=(7,))
    eng = build(device_retry_budget=4, admit_timeout=60.0)
    try:
        futs = [eng.submit(p, max_new_tokens=g)
                for p, g in zip(prompts, gens)]
        done, not_done = fut_wait(futs, timeout=FUTURE_TIMEOUT)
        assert not not_done, (
            f"{len(not_done)} futures never resolved — the engine "
            f"hung under injected slab faults")
        for f, r in zip(futs, ref):
            assert f.exception() is None, (
                f"request lost to budgeted slab chaos: {f.exception()}")
            assert f.result()["output_ids"] == r["output_ids"], (
                "retried slab stream diverged from the fault-free "
                "reference (nonce-pinned token identity broken)")
        n_injected = len(faults.injected_log())
        assert n_injected >= 2, (
            f"schedule armed but only {n_injected} faults injected — "
            f"the soak did not exercise the slab failure path")
        _assert_schedule_matches(
            faults, ("engine.slab", "device.transfer"))

        # hopeless deadlines resolve typed (at a slab boundary)
        dl = [eng.submit(rng.randint(0, 97, 5).tolist(),
                         max_new_tokens=8, deadline=-1.0)
              for _ in range(3)]
        done, not_done = fut_wait(dl, timeout=FUTURE_TIMEOUT)
        assert not not_done, "deadline futures pending under slabs"
        assert all(isinstance(f.exception(), DeadlineExceeded)
                   for f in dl), [f.exception() for f in dl]

        # cancellation storm, faults off: cancels land mid-slab and
        # must resolve at the boundary with pages reclaimed
        faults.disable()
        eng.reset_health()
        storm = [eng.submit(rng.randint(0, 97, 6).tolist(),
                            max_new_tokens=80) for _ in range(8)]
        for f in storm[::2]:
            eng.cancel(f.request_id)
        time.sleep(0.2)
        for f in storm[1::2]:
            eng.cancel(f.request_id)
        done, not_done = fut_wait(storm, timeout=FUTURE_TIMEOUT)
        assert not not_done, (
            "cancellation storm left futures pending under fused "
            "slabs")
        n_cancelled = 0
        for f in storm:
            exc = f.exception()
            assert exc is None or isinstance(exc, RequestCancelled), \
                exc
            n_cancelled += exc is not None
        assert n_cancelled >= 1, "storm cancelled nothing"
    finally:
        eng.close()
        faults.reset()
    assert len(eng._free_pages) == eng.num_pages - 1, (
        f"KV pages leaked under fused slabs: "
        f"{len(eng._free_pages)} free of {eng.num_pages - 1} usable")
    open_llm = [s for s in tracing.live_spans()
                if s["name"].startswith("llm.")]
    tracing.disable()
    assert not open_llm, f"span trees left open: {open_llm}"
    return {"injected": n_injected, "cancelled": n_cancelled,
            "requests": len(futs) + len(dl) + len(storm),
            "mixed_tick": mixed, "kv_dtype": kv_dtype or "f32"}


def spec_slab_soak(seed: int) -> dict:
    """ISSUE 17 rider (rides --slab): the SAME kill/cancel/deadline
    storm with a DRAFT ENGINE running on-device speculative rounds
    (``spec_slab``, prefix cache + int8 quantized draft pool + fused
    N=8 slabs all on). Asserts: every future resolves under an
    ``engine.slab`` storm at the spec dispatch within
    ``device_retry_budget``; retried streams — greedy AND
    temperature>0 — are TOKEN-IDENTICAL to a fault-free spec
    reference (keys fold (nonce, position) only, so a re-admitted
    slot replays its rejection-sampling decisions exactly);
    rejected-draft pages cannot leak through a cancellation storm
    (the draft pool shares the target's block tables — one audit
    covers both); the injected sequence equals the pure seeded
    schedule."""
    import paddle_tpu as pt
    from paddle_tpu.inference.llm import LLMEngine, RequestCancelled
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt_config
    from paddle_tpu.observability import tracing
    from paddle_tpu.reliability import faults
    from paddle_tpu.reliability.retry import DeadlineExceeded

    rng = np.random.RandomState(seed)
    prompts = [rng.randint(0, 97, int(rng.randint(3, 12))).tolist()
               for _ in range(6)]
    gens = [int(rng.randint(8, 20)) for _ in range(6)]
    temps = [0.0, 0.0, 0.8, 0.0, 0.8, 0.0]
    net = _tiny_gpt()
    pt.seed(321)
    dcfg = gpt_config("gpt2-small", num_layers=1, hidden_size=32,
                      num_heads=2, vocab_size=97,
                      max_position_embeddings=96, hidden_dropout=0.0,
                      attention_dropout=0.0)
    draft = GPTForCausalLM(dcfg)

    def build(**kw):
        return LLMEngine(net, max_seqs=4, page_size=4, num_pages=96,
                         prefill_buckets=(16,), drain_after=64,
                         decode_ticks_per_dispatch=8,
                         draft_net=draft, spec_tokens=3,
                         kv_dtype="int8", **kw)

    # fault-free spec reference: same engine seed, same submission
    # order => same nonces => the chaos run must reproduce these
    # exactly even when its spec slabs die and re-admit
    with build() as ref_eng:
        ref = [f.result(timeout=FUTURE_TIMEOUT) for f in
               [ref_eng.submit(p, max_new_tokens=g, temperature=t)
                for p, g, t in zip(prompts, gens, temps)]]
        assert ref_eng.n_spec_rounds > 0, \
            "spec reference never ran a speculative round"

    tracing.enable()
    faults.reset()
    faults.enable(seed=seed)
    faults.inject("engine.slab", nth=(2, 5))
    faults.inject("engine.slab", p=0.02, times=1)
    faults.inject("device.transfer", nth=(7,))
    eng = build(device_retry_budget=4, admit_timeout=60.0)
    try:
        futs = [eng.submit(p, max_new_tokens=g, temperature=t)
                for p, g, t in zip(prompts, gens, temps)]
        done, not_done = fut_wait(futs, timeout=FUTURE_TIMEOUT)
        assert not not_done, (
            f"{len(not_done)} futures never resolved — the spec "
            f"engine hung under injected slab faults")
        for f, r in zip(futs, ref):
            assert f.exception() is None, (
                f"request lost to budgeted spec-slab chaos: "
                f"{f.exception()}")
            assert f.result()["output_ids"] == r["output_ids"], (
                "retried spec stream diverged from the fault-free "
                "reference (nonce-pinned token identity broken)")
        n_injected = len(faults.injected_log())
        assert n_injected >= 2, (
            f"schedule armed but only {n_injected} faults injected — "
            f"the soak did not exercise the spec-slab failure path")
        _assert_schedule_matches(
            faults, ("engine.slab", "device.transfer"))

        # hopeless deadlines resolve typed (at a slab boundary)
        dl = [eng.submit(rng.randint(0, 97, 5).tolist(),
                         max_new_tokens=8, deadline=-1.0)
              for _ in range(3)]
        done, not_done = fut_wait(dl, timeout=FUTURE_TIMEOUT)
        assert not not_done, "deadline futures pending under spec slabs"
        assert all(isinstance(f.exception(), DeadlineExceeded)
                   for f in dl), [f.exception() for f in dl]

        # cancellation storm, faults off: cancels land mid-slab with
        # rejected draft KV in flight — pages must all come back
        faults.disable()
        eng.reset_health()
        storm = [eng.submit(rng.randint(0, 97, 6).tolist(),
                            max_new_tokens=80) for _ in range(8)]
        for f in storm[::2]:
            eng.cancel(f.request_id)
        time.sleep(0.2)
        for f in storm[1::2]:
            eng.cancel(f.request_id)
        done, not_done = fut_wait(storm, timeout=FUTURE_TIMEOUT)
        assert not not_done, (
            "cancellation storm left futures pending under spec "
            "slabs")
        n_cancelled = 0
        for f in storm:
            exc = f.exception()
            assert exc is None or isinstance(exc, RequestCancelled), \
                exc
            n_cancelled += exc is not None
        assert n_cancelled >= 1, "storm cancelled nothing"
    finally:
        eng.close()
        faults.reset()
    assert len(eng._free_pages) == eng.num_pages - 1, (
        f"KV pages leaked through rejected-draft rounds: "
        f"{len(eng._free_pages)} free of {eng.num_pages - 1} usable")
    open_llm = [s for s in tracing.live_spans()
                if s["name"].startswith("llm.")]
    tracing.disable()
    assert not open_llm, f"span trees left open: {open_llm}"
    return {"injected": n_injected, "cancelled": n_cancelled,
            "requests": len(futs) + len(dl) + len(storm),
            "spec_rounds": eng.n_spec_rounds,
            "accept_rate": round(eng.n_spec_accepted /
                                 max(1, eng.n_spec_proposed), 3)}


def page_pressure_soak(seed: int, kv_dtype=None) -> dict:
    """ISSUE 14 phase (rides --slab): a PAGE-PRESSURE STORM against a
    deliberately tiny KV pool, polling the memory ledger's headroom
    while fused slabs fight the allocator. Asserts the accounting
    closes the loop: the ``mem_headroom_pages`` gauge hits ~0 exactly
    when slab-shrink engages (a slab truncating at ``covered == 0``
    IS the allocator returning None, i.e. headroom 0 at that entry —
    witnessed here by truncated results + a shrunk ``decode_loop``
    signature + the polled gauge minimum), the kv_pool ledger rows
    tile the pool exactly at every sampled instant, and headroom
    RECOVERS to the full usable pool after the storm drains.

    ISSUE 15 rider (``kv_dtype="int8"``): the SAME storm at the SAME
    pool HBM budget — int8 pages (scale tables included) must buy
    >= 1.8x the f32 pages, the kv_pool rows now include the distinct
    ``scale_table`` kind and STILL tile the pool exactly, and the
    headroom gauge semantics re-pin unchanged (the storm is doubled
    so the bigger pool still runs dry and slab-shrink engages)."""
    from paddle_tpu.inference.llm import LLMEngine
    from paddle_tpu.observability import memory as memobs
    from paddle_tpu.observability.metrics import default_registry

    rng = np.random.RandomState(seed)
    net = _tiny_gpt()
    N = 8
    # 17 usable pages of 4 tokens: 4 slots x (2 prompt pages + up to
    # 2 slab pages per dispatch) oversubscribes the pool by design.
    # The int8 rider holds the HBM BUDGET fixed (18 f32 pages' worth)
    # and lets the quantized pool claim however many pages fit.
    num_pages, n_requests = 18, 8
    if kv_dtype is not None:
        probe = LLMEngine(net, max_seqs=2, page_size=4, num_pages=8,
                          prefill_buckets=(16,), max_len=64)
        budget = 18 * probe._page_bytes
        probe.close()
        probe = LLMEngine(net, max_seqs=2, page_size=4, num_pages=8,
                          prefill_buckets=(16,), max_len=64,
                          kv_dtype=kv_dtype)
        num_pages = int(budget // probe._page_bytes)
        probe.close()
        assert num_pages - 1 >= 1.8 * 17, (
            f"kv_dtype={kv_dtype} bought only {num_pages - 1} usable "
            f"pages at the 17-page f32 HBM budget (<1.8x)")
        n_requests = 16   # double the storm: the bigger pool must
        #                   still run dry for the shrink pin to hold
    # the ~2x-occupancy witness: the int8 run serves DOUBLE the
    # concurrent slots at the same pool HBM — 4 f32 slots' full need
    # oversubscribes 17 pages, 8 int8 slots' oversubscribes its ~2x
    # pool, so slab-shrink engages at twice the occupancy
    max_seqs = 4 if kv_dtype is None else 8
    eng = LLMEngine(net, max_seqs=max_seqs, page_size=4,
                    num_pages=num_pages, prefill_buckets=(16,),
                    max_len=64, decode_ticks_per_dispatch=N,
                    admit_timeout=120.0, kv_dtype=kv_dtype)
    led = memobs.instance()
    usable = eng.num_pages - 1
    samples = []
    stop = threading.Event()

    def poll():
        while not stop.is_set():
            h = led.headroom()
            rows = {r["kind"]: r["bytes"] for r in led.rows()
                    if r["owner"] == "kv_pool"}
            if h is not None and rows:
                led.update_gauges()
                g = default_registry().get("mem_headroom_pages")
                samples.append((h["kv_pages_addable"],
                                g.value if g is not None else None,
                                sum(rows.values())))
            time.sleep(0.001)

    poller = threading.Thread(target=poll, daemon=True)
    poller.start()
    try:
        # int8 rider: a 10-token prompt leaves decode mid-page, so a
        # dry pool yields a PARTIAL coverage (slab shrink) rather
        # than only boundary truncations — the shrink pin stays
        # deterministic at the doubled occupancy
        plen = 8 if kv_dtype is None else 10
        futs = [eng.submit(rng.randint(0, 97, plen).tolist(),
                           max_new_tokens=40)
                for _ in range(n_requests)]
        done, not_done = fut_wait(futs, timeout=FUTURE_TIMEOUT)
        assert not not_done, "futures pending under page pressure"
        outs = [f.result() for f in futs]
    finally:
        stop.set()
        poller.join(timeout=10)
    n_trunc = sum(o["truncated"] for o in outs)
    assert n_trunc >= 1, (
        "the storm never hit page pressure — shrink/truncation path "
        "unexercised (grow max_new_tokens or shrink num_pages)")
    shrunk = any(k[0] == "decode_loop" and k[1] < N
                 for k in eng._shape_signatures)
    assert shrunk, (
        f"no shrunk decode_loop signature compiled — the slab never "
        f"hit the coverable boundary: {sorted(eng._shape_signatures)}")
    assert samples, "ledger poller captured nothing"
    min_head = min(s[0] for s in samples)
    min_gauge = min(s[1] for s in samples if s[1] is not None)
    assert min_head <= 1, (
        f"headroom never approached 0 under a pool-exhausting storm "
        f"(min {min_head} of {usable} usable)")
    assert min_gauge <= 1, (
        f"mem_headroom_pages gauge never approached 0 (min "
        f"{min_gauge})")
    # attribution exactness held at EVERY sampled instant: the
    # free/private/shared/scratch (+ scale_table under int8) rows
    # tile the pool — page bytes INCLUDE the scale tables
    pool_bytes = eng.num_pages * eng._page_bytes
    bad = [s for s in samples if s[2] != pool_bytes]
    assert not bad, (
        f"kv_pool ledger rows stopped tiling the pool at "
        f"{len(bad)}/{len(samples)} samples: {bad[:3]}")
    if kv_dtype == "int8":
        rows = {r["kind"] for r in led.rows()
                if r["owner"] == "kv_pool"}
        assert "scale_table" in rows, (
            f"int8 pool reported no scale_table ledger row: {rows}")
    # drained: every page is free or an evictable cache resident again
    h = led.headroom()
    assert h is not None and h["kv_pages_addable"] == usable, (
        f"headroom did not recover after drain: {h} vs {usable}")
    eng.close()
    assert led.headroom() is None, \
        "closed engine still reports pool headroom (stale provider)"
    led.update_gauges()
    assert default_registry().get("mem_headroom_pages") is None, \
        "mem_headroom_pages gauge survived the last pool's close"
    return {"requests": len(outs), "truncated": n_trunc,
            "min_headroom": min_head, "samples": len(samples),
            "kv_dtype": kv_dtype or "f32", "usable_pages": usable}


def ckpt_crash(seed: int, workdir: str) -> dict:
    """Scenario 3: SIGKILL a worker mid-save, then prove the directory
    restores cleanly and still accepts saves; then the injected-fault
    variants of the same invariant."""
    from paddle_tpu.io.checkpoint import CheckpointManager
    from paddle_tpu.reliability import faults
    from paddle_tpu.reliability.faults import FaultInjected

    rng = np.random.RandomState(seed)
    ckpt_dir = os.path.join(workdir, "ckpt_kill")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    kill_at = int(rng.randint(2, 5))
    p = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--ckpt-worker",
         ckpt_dir, "12"],
        env=env, cwd=REPO, stdout=subprocess.PIPE, text=True)
    killed_during = None
    for line in p.stdout:
        if line.startswith("SAVING "):
            k = int(line.split()[1])
            if k >= kill_at:
                # land the SIGKILL inside the save window (the worker
                # announces, then saves); a seeded jitter moves the
                # kill around within it across seeds
                time.sleep(float(rng.uniform(0.0, 0.05)))
                p.kill()
                killed_during = k
                break
    p.wait(timeout=60)
    assert killed_during is not None, "worker finished before the kill"

    mgr = CheckpointManager(ckpt_dir, async_save=False)
    latest = mgr.latest_step()
    assert latest is not None and latest >= killed_during - 1, (
        f"mid-save SIGKILL lost committed steps: latest={latest}, "
        f"killed during save of {killed_during}")
    tree = mgr.restore(latest)
    np.testing.assert_array_equal(
        tree["w"], np.arange(2048, dtype=np.int64) + latest)
    # the survivor directory still accepts new saves (tmp-dir debris
    # from the kill must not wedge the next incarnation)
    assert mgr.save(latest + 1, {"w": np.arange(2048) + latest + 1,
                                 "step": np.asarray(latest + 1)})
    mgr.wait_until_finished()
    mgr.close()

    # injected ckpt.write faults: absorbed by the shared retry policy
    faults.reset()
    faults.enable(seed=seed)
    faults.inject("ckpt.write", nth=(1,), times=1)
    retry_dir = os.path.join(workdir, "ckpt_retry")
    with CheckpointManager(retry_dir, async_save=False) as m2:
        assert m2.save(0, {"w": np.arange(16)})
        m2.wait_until_finished()
        assert m2.latest_step() == 0
    assert ("ckpt.write", 1) in faults.injected_log()

    # injected ckpt.rename (commit-stage) fault: the save CALL fails,
    # the directory stays restorable
    faults.inject("ckpt.rename", nth=(faults.call_count("ckpt.rename")
                                      + 1,), times=1)
    with CheckpointManager(retry_dir, async_save=False) as m3:
        try:
            m3.save(1, {"w": np.arange(16) + 1})
            raised = False
        except FaultInjected:
            raised = True
        assert raised, "ckpt.rename fault did not surface"
        m3.wait_until_finished()
        latest = m3.latest_step()
        assert latest is not None
        np.testing.assert_array_equal(
            m3.restore(latest)["w"], np.arange(16) + latest)
    faults.reset()
    return {"killed_during": killed_during, "latest": int(latest)}


def flight_escalation(seed: int, workdir: str) -> dict:
    """Scenario 4: an injected io.worker fault inside Model.fit goes
    uncaught, the process dies, and the flight recorder dumps."""
    crash_dir = os.path.join(workdir, "flight")
    code = f"""
import jax; jax.config.update("jax_platforms", "cpu")
import numpy as np
import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.io import TensorDataset
from paddle_tpu.observability import flight, tracing
from paddle_tpu.reliability import faults
tracing.enable()
flight.install_flight_recorder({crash_dir!r})
faults.enable(seed={seed})
faults.inject("io.worker", nth=(3,))
pt.seed(0)
net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
model = pt.Model(net)
model.prepare(optimizer=pt.optimizer.SGD(learning_rate=0.1,
                                         parameters=net),
              loss=nn.CrossEntropyLoss())
x = np.zeros((64, 8), np.float32)
y = np.zeros((64, 1), np.int64)
model.fit(TensorDataset([x, y]), batch_size=8, epochs=2, verbose=0)
raise SystemExit("unreachable: the injected fault must escalate")
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    p = subprocess.run([sys.executable, "-c", code], env=env, cwd=REPO,
                       capture_output=True, text=True, timeout=300)
    assert p.returncode != 0, (
        "chaos-injected io.worker fault did not crash the run:\n"
        + p.stdout[-400:] + p.stderr[-400:])
    assert "injected fault at io.worker" in p.stderr, p.stderr[-800:]
    dumps = sorted(f for f in os.listdir(crash_dir)
                   if f.endswith(".jsonl"))
    assert dumps, "flight recorder wrote no dump for the chaos crash"
    rows = [json.loads(ln)
            for ln in open(os.path.join(crash_dir, dumps[0]))]
    assert rows[0]["kind"] == "header", rows[0]
    assert rows[0]["reason"] == "exception", rows[0]
    return {"dump": dumps[0], "rows": len(rows)}


def goodput_soak(seed: int, workdir: str) -> dict:
    """Scenario 8: goodput-ledger forensics under chaos. The seeded
    fault storms must be VISIBLE on ``GET /goodputz``: a
    ``device.dispatch`` storm grows the ``recovery`` bucket (the
    window spent on a failed device call is recovery badput), and an
    async-checkpoint run under a seeded ``ckpt.async_commit`` fault
    still grows ``ckpt_stall`` by its snapshot windows (the only
    phase the train loop waits on — the commit fault surfaces at the
    barrier, never in the stall accounting). The reconciliation line
    must stay closed throughout. Then the off-switch pin: with the
    ledger disabled, ``note()`` must cost one module-flag check
    (time-bounded, the PR-4 tracing discipline) and record nothing."""
    from urllib.request import urlopen

    from paddle_tpu.inference.llm import LLMEngine
    from paddle_tpu.io.checkpoint import CheckpointManager
    from paddle_tpu.observability import goodput
    from paddle_tpu.observability.server import DebugServer
    from paddle_tpu.reliability import faults
    from paddle_tpu.reliability.faults import FaultInjected

    assert goodput.enabled(), "goodput ledger disabled in the soak env"
    rng = np.random.RandomState(seed)
    dbg = DebugServer(port=0).start()
    base = f"http://127.0.0.1:{dbg.port}"

    def goodputz():
        with urlopen(base + "/goodputz", timeout=10) as r:
            return json.loads(r.read())

    out = {}
    try:
        g0 = goodputz()["buckets"]

        # -- phase A: device.dispatch storm → recovery badput ---------
        faults.reset()
        faults.enable(seed=seed)
        # faults land AFTER the first fetches (the recovery window is
        # measured from the last drained fetch — a fault before any
        # fetch has no attributable start)
        faults.inject("device.dispatch", nth=(5, 12))
        net = _tiny_gpt()
        with LLMEngine(net, max_seqs=4, page_size=4, num_pages=96,
                       prefill_buckets=(16,), device_retry_budget=4,
                       admit_timeout=60.0) as eng:
            futs = [eng.submit(rng.randint(0, 97, 8).tolist(),
                               max_new_tokens=8) for _ in range(6)]
            done, not_done = fut_wait(futs, timeout=FUTURE_TIMEOUT)
            assert not not_done, "futures pending under the storm"
            for f in futs:
                assert f.exception() is None, f.exception()
        n_dispatch = sum(1 for s, _ in faults.injected_log()
                         if s == "device.dispatch")
        assert n_dispatch >= 2, faults.injected_log()
        faults.reset()
        g1 = goodputz()["buckets"]
        assert g1["recovery"] > g0["recovery"], (
            f"a {n_dispatch}-fault dispatch storm left the recovery "
            f"bucket flat: {g0} -> {g1}")
        assert g1["productive"] > g0["productive"], (g0, g1)

        # -- phase B: async saves under a seeded commit fault →
        # ckpt_stall moves by the snapshot windows
        faults.enable(seed=seed)
        faults.inject("ckpt.async_commit", nth=(2,), times=1)
        d = os.path.join(workdir, "goodput_ck")
        mgr = CheckpointManager(d, async_save=True)
        try:
            mgr.save(1, {"w": np.zeros((256, 256), np.float32)})
            mgr.wait_until_finished()
            try:
                mgr.save(2, {"w": np.zeros((256, 256), np.float32)})
                mgr.wait_until_finished()
                raised = False
            except FaultInjected:
                raised = True
            assert raised, "ckpt.async_commit fault did not surface"
        finally:
            mgr.close()
            faults.reset()
        gz = goodputz()
        g2 = gz["buckets"]
        assert g2["ckpt_stall"] > g1["ckpt_stall"], (
            f"two async saves left the ckpt_stall bucket flat: "
            f"{g1} -> {g2}")
        rec = gz["reconciliation"]
        assert abs(rec["residual_s"]) < 1e-6, rec
        out["buckets"] = {k: round(v, 4) for k, v in g2.items() if v}

        # -- phase C: ledger-off = one module-flag check --------------
        goodput.disable()
        try:
            led = goodput.instance()
            before = led.totals()["productive"]
            n_calls = 200_000
            t0 = time.perf_counter()
            for _ in range(n_calls):
                goodput.note("productive", 1.0)
            per_call = (time.perf_counter() - t0) / n_calls
            assert per_call < 5e-6, (
                f"disabled goodput.note costs "
                f"{per_call * 1e6:.2f}us/call — more than a flag "
                f"check")
            assert led.totals()["productive"] == before, (
                "disabled ledger still recorded intervals")
            assert goodputz()["enabled"] is False
        finally:
            goodput.enable()
        out["off_ns_per_call"] = round(per_call * 1e9)
    finally:
        faults.reset()
        dbg.stop()
    return out


def _poll_until(fn, timeout: float, what: str):
    """Poll ``fn`` (returns falsy to keep waiting) with a bounded
    budget; returns its first truthy value."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = fn()
        if v:
            return v
        time.sleep(0.05)
    raise AssertionError(f"timed out ({timeout}s) waiting for {what}")


def _attach_fleet_trace(workdir: str, infos: dict):
    """Best-effort failure attachment: merge the router's span table,
    every reachable replica's /tracez, and any flight dumps under the
    soak's obs_dir into one chrome trace. Never raises — the original
    assertion is the story; this is the supporting evidence."""
    try:
        from paddle_tpu.observability import tracing
        from tools.trace_merge import load_source, merge_chrome_trace
        wall = tracing.perf_to_wall
        sources = {"router": (
            [dict(s, ts_wall=wall(s["ts"]), live=False)
             for s in tracing.finished_spans()]
            + [dict(s, ts_wall=wall(s["ts"]), live=True)
               for s in tracing.live_spans()])}
        for n, info in infos.items():
            url = info.get("tracez")
            if not url:
                continue
            try:
                sources[n] = load_source(url, timeout=5)
            except Exception:  # noqa: BLE001 — a dead replica's live
                pass           # table is gone; its flight dump below
        obs_dir = os.path.join(workdir, "obs")
        if os.path.isdir(obs_dir):
            for root, _dirs, files in os.walk(obs_dir):
                for fn in files:
                    if fn.startswith("flight_") and \
                            fn.endswith(".jsonl"):
                        tag = f"{os.path.basename(root)}:{fn}"
                        try:
                            sources[tag] = load_source(
                                os.path.join(root, fn))
                        except Exception:  # noqa: BLE001
                            pass
        path = os.path.join(workdir, "fleet_failure_trace.json")
        return path, merge_chrome_trace(sources, path)
    except Exception:  # noqa: BLE001 — never mask the real failure
        return None, None


def fleet_soak(seed: int, workdir: str) -> dict:
    """Scenario 5: the serving fleet under replica-level chaos.
    Asserts the ISSUE-6 acceptance invariants: zero lost requests
    across a SIGKILL (token-identical failover within budget), breaker
    open → half-open → closed across a respawn, draining replicas
    receiving no new admissions within one health-poll interval, and
    seed-replayable router fault sites — plus the ISSUE-7 fleet
    observability invariants (/fleetz aggregation, /sloz burn rates
    moving under a deadline-miss storm, cross-process traces)."""
    from paddle_tpu.distributed.tcp_store import TCPStoreServer
    from paddle_tpu.observability import tracing
    from paddle_tpu.reliability import faults
    from paddle_tpu.serving import (LocalReplica, Router, SLOClass,
                                    make_engine_from_spec,
                                    spawn_replica)
    from paddle_tpu.serving.router import affinity_key, rendezvous_pick

    rng = np.random.RandomState(seed)
    faults.reset()
    tracing.enable()   # router-side spans: the failure report's raw data
    store = TCPStoreServer("127.0.0.1", 0)
    endpoint = f"127.0.0.1:{store.port}"
    obs_dir = os.path.join(workdir, "obs")
    model = {"vocab": 97, "layers": 2, "hidden": 64, "heads": 4,
             "max_pos": 96, "model_seed": 0,
             # every replica traces and collects its dumps under ONE
             # tree the soak can merge on failure
             "tracing": True, "obs_dir": obs_dir}
    engine_kw = {"device_retry_budget": 2, "drain_after": 2,
                 "max_pending": 64, "seed": 0}
    names = ("r0", "r1", "r2")
    cache_dir = os.path.join(workdir, "xla_cache")
    os.makedirs(cache_dir, exist_ok=True)
    specs = {n: dict(model, name=n, store=endpoint,
                     cache_dir=cache_dir,
                     engine=dict(engine_kw)) for n in names}
    # r2's schedule: dispatch calls 3 and 4 fault back-to-back — two
    # CONSECUTIVE device errors at drain_after=2 latch it DRAINING
    # while its first request is in flight (the request survives via
    # the engine retry budget → draining shed → router rebalance)
    specs["r2"]["faults"] = {"seed": seed, "rules": [
        {"site": "device.dispatch", "nth": [3, 4]}]}

    procs, infos = {}, {}

    def _spawn(name):
        procs[name], infos[name] = spawn_replica(specs[name],
                                                 timeout=180)

    # STAGGERED spawn: r0 comes up alone and serves one warm request,
    # populating the shared persistent compile cache; r1/r2 (and the
    # parent's reference engine) then hit its artifacts instead of
    # compiling the same programs 3x on a contended host
    _spawn("r0")
    from paddle_tpu.serving import HTTPReplica
    HTTPReplica(infos["r0"]["generate"],
                infos["r0"]["healthz"]).submit([1, 2, 3],
                                               max_new_tokens=2)
    threads = [threading.Thread(target=_spawn, args=(n,))
               for n in ("r1", "r2")]
    for t in threads:
        t.start()
    # the reference engine (same weights/seed as every replica)
    # replays failover'd requests to pin token identity; it reads the
    # same compile cache
    import jax
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      0.0)
    ref = LocalReplica(make_engine_from_spec(dict(model,
                                                  engine=engine_kw)))
    ref_warm = threading.Thread(
        target=lambda: ref.submit([1, 2, 3], max_new_tokens=1))
    ref_warm.start()
    for t in threads:
        t.join(timeout=240)
    assert set(infos) == set(names), f"replicas failed to spawn: " \
        f"{sorted(set(names) - set(infos))}"

    router = Router(store_endpoint=endpoint, page_size=16,
                    affinity_pages=2, failover_budget=2,
                    health_poll_interval=0.2,
                    membership_stale_after=1.5,
                    breaker_fail_threshold=3, breaker_open_for=1.0,
                    # the SLO class the phase-D deadline-miss storm
                    # burns: tight windows so a ~45s soak spans them
                    slo_classes={"gold": SLOClass(
                        "gold", deadline_s=60.0, target=0.99)},
                    slo_windows=(2.0, 8.0), slo_min_samples=5,
                    slo_breach_threshold=5.0)
    out = {"spawn_ok": True}
    try:
        _poll_until(lambda: set(router.replica_names()) == set(names),
                    30, "membership convergence to 3 replicas")

        def affine_prompt(target, length):
            # rejection-sample a prompt whose rendezvous choice is
            # `target` (deterministic from the run's RandomState)
            while True:
                p = rng.randint(0, 97, length).tolist()
                key = affinity_key(p, router.page_size,
                                   router.affinity_pages)
                if rendezvous_pick(key, names) == target:
                    return p

        def status(name):
            return router._status()["replicas"][name]

        # -- phase A: injected faults drain r2; the router rebalances.
        # One request per replica, concurrently: r0/r1 warm their
        # compiles while r2's request trips its fault schedule
        warm = [router.submit(affine_prompt(n, 12), max_new_tokens=8,
                              temperature=0.9) for n in names]
        for f in warm:
            assert f.result(timeout=240)["output_ids"]
        _poll_until(lambda: status("r2")["health"] == "draining", 10,
                    "router marking r2 draining")
        d2 = status("r2")["dispatched"]
        time.sleep(2 * router.health_poll_interval)
        futs = [router.submit(affine_prompt("r2", 12),
                              max_new_tokens=8) for _ in range(2)]
        for f in futs:
            assert f.result(timeout=240)["output_ids"]
        assert status("r2")["dispatched"] == d2, (
            "a draining replica received new admissions: "
            f"{status('r2')}")
        out["drain"] = {"rebalanced": router.n_rebalanced}
        assert router.n_rebalanced >= 1, router._status()

        # -- phase A2: POST /reset_health recovers r2 over HTTP
        from urllib.request import Request, urlopen
        base = infos["r2"]["healthz"].rsplit("/healthz", 1)[0]
        with urlopen(Request(base + "/reset_health", data=b"{}"),
                     timeout=10) as resp:
            assert resp.status == 200, resp.status
        _poll_until(lambda: status("r2")["health"] == "healthy", 10,
                    "r2 healthy after /reset_health")
        f = router.submit(affine_prompt("r2", 12), max_new_tokens=8)
        assert f.result(timeout=240)["output_ids"]
        assert status("r2")["dispatched"] > d2, (
            "recovered replica got no traffic back: "
            f"{status('r2')}")

        # -- phase B: SIGKILL r0 mid-decode — zero lost requests,
        # token-identical failover, breaker opens
        prompts = [affine_prompt("r0", 16) for _ in range(4)]
        futs = [router.submit(p, max_new_tokens=32, temperature=0.9)
                for p in prompts]
        _poll_until(lambda: status("r0")["inflight"] > 0, 60,
                    "r0 taking traffic before the kill")
        os.kill(procs["r0"].pid, signal.SIGKILL)
        procs["r0"].wait(timeout=30)
        # respawn starts NOW, overlapped with the zero-loss and
        # token-identity checks below (both take seconds — exactly the
        # boot window)
        respawned = {}

        def _respawn():
            respawned["proc"], respawned["info"] = spawn_replica(
                specs["r0"], timeout=180)

        respawn_t = threading.Thread(target=_respawn)
        respawn_t.start()
        # the breaker must trip well before the respawn can re-close
        # it (health polls hit connection-refused within ~3 intervals)
        _poll_until(lambda: status("r0")["breaker"] == "open", 15,
                    "r0 breaker opening after the kill")
        results = [f.result(timeout=240) for f in futs]
        assert all(r["output_ids"] for r in results), results
        flipped = [(p, r) for p, r in zip(prompts, results)
                   if r["failovers"] > 0]
        assert flipped, (
            "SIGKILL mid-decode caused no failover — the kill missed "
            f"the in-flight window: {[r['replica'] for r in results]}")
        for p, r in flipped[:2]:
            ref_out = ref.submit(p, max_new_tokens=32, temperature=0.9,
                                 nonce=r["request_id"])
            assert ref_out["output_ids"] == r["output_ids"], (
                "failover was not token-identical: "
                f"{ref_out['output_ids']} != {r['output_ids']}")
        out["kill"] = {"failovers": router.n_failovers,
                       "failover_requests": len(flipped)}

        # -- phase B2: r0 respawned (same name, new endpoints) — the
        # breaker must re-close through a half-open probe, and traffic
        # must return
        respawn_t.join(timeout=240)
        assert "proc" in respawned, "r0 respawn failed"
        procs["r0"], infos["r0"] = respawned["proc"], respawned["info"]
        _poll_until(lambda: status("r0")["breaker"] == "closed", 30,
                    "r0 breaker re-closing after respawn")
        assert status("r0")["breaker_opens"] >= 1
        d0 = status("r0")["dispatched"]
        f = router.submit(affine_prompt("r0", 16), max_new_tokens=8)
        assert f.result(timeout=240)["output_ids"]
        assert status("r0")["dispatched"] > d0, status("r0")
        assert router._aggregate_health() == "healthy", \
            router._status()

        # -- phase C: router-side fault sites replay from the seed
        faults.enable(seed=seed)
        faults.inject("router.dispatch", nth=(1,), times=1)
        futs = [router.submit(affine_prompt("r1", 12),
                              max_new_tokens=8) for _ in range(2)]
        for f in futs:
            assert f.result(timeout=240)["output_ids"]
        assert ("router.dispatch", 1) in faults.injected_log(), \
            faults.injected_log()
        _assert_schedule_matches(faults, ("router.dispatch",))
        faults.reset()
        out["router_faults"] = {"injected": 1}

        # -- phase D: fleet observability. /fleetz must aggregate all
        # three replicas with per-replica data; a deadline-miss storm
        # against the "gold" SLO class must move its burn-rate gauges
        # on /sloz and latch the breach (cleared by /reset_health)
        from urllib.request import Request, urlopen

        from paddle_tpu.observability.server import DebugServer
        from paddle_tpu.reliability.retry import DeadlineExceeded
        dbg = DebugServer(port=0).start()
        base = f"http://127.0.0.1:{dbg.port}"

        def get_json(path):
            with urlopen(base + path, timeout=10) as r:
                return json.loads(r.read())

        try:
            def fleetz_all_up():
                fz = next(iter(get_json("/fleetz")["fleets"].values()))
                reps = fz["replicas"]
                ok = all(n in reps and (reps[n].get("metrics") or {})
                         .get("up") for n in names) \
                    and fz["aggregates"]["tokens_generated"] > 0
                return fz if ok else None

            fz = _poll_until(fleetz_all_up, 15,
                             "/fleetz aggregating all 3 replicas")
            assert fz["aggregates"]["replicas_scraped"] == 3, fz
            sz = next(iter(get_json("/sloz")["slo"].values()))
            burn0 = sz["classes"].get("gold", {}).get(
                "windows", {}).get("short", {}).get("burn_rate", 0.0)
            assert burn0 == 0.0, f"gold budget burning before the " \
                f"storm: {sz}"
            storm = [router.submit(affine_prompt("r1", 8),
                                   max_new_tokens=4, slo="gold",
                                   deadline=0.001) for _ in range(8)]
            n_missed = 0
            for f in storm:
                try:
                    f.result(timeout=120)
                except DeadlineExceeded:
                    n_missed += 1
            assert n_missed == 8, f"storm deadlines not hopeless " \
                f"enough: {n_missed}/8 missed"
            sz = next(iter(get_json("/sloz")["slo"].values()))
            gold = sz["classes"]["gold"]
            assert gold["windows"]["short"]["burn_rate"] > 5.0, gold
            assert gold["windows"]["long"]["burn_rate"] > 5.0, gold
            assert "gold" in sz["breached"], sz
            hz = get_json("/healthz")
            slo_comp = [v for k, v in hz.get("components", {}).items()
                        if k.endswith("_slo")]
            assert slo_comp == ["degraded"], hz
            # operator acknowledgment clears the latch over HTTP
            with urlopen(Request(base + "/reset_health", data=b"{}"),
                         timeout=10) as resp:
                assert resp.status == 200, resp.status
            sz = next(iter(get_json("/sloz")["slo"].values()))
            assert sz["breached"] == [], sz
            out["slo"] = {"missed": n_missed,
                          "burn_short": gold["windows"]["short"]
                          ["burn_rate"]}
        finally:
            dbg.stop()
    except AssertionError:
        # the failure report attaches the merged cross-process trace:
        # every span table in the fleet (router + replica /tracez +
        # any flight dumps under obs_dir) on one ts_wall-aligned
        # timeline — the "which process ate the latency / dropped the
        # request" question answered next to the replay command
        path, summary = _attach_fleet_trace(workdir, infos)
        if path is not None:
            print(f"merged cross-process trace attached: {path} "
                  f"({summary['spans']} spans from "
                  f"{summary['processes']} processes)",
                  file=sys.stderr, flush=True)
        raise
    finally:
        faults.reset()
        tracing.disable()
        router.close()
        ref.engine.close()
        for p in procs.values():
            if p.poll() is None:
                p.kill()
                p.wait(timeout=30)
        store.close()
    return out


def disagg_soak(seed: int, workdir: str) -> dict:
    """Scenario 5c (rides ``--fleet``, ISSUE 18): the disaggregated
    prefill/decode fleet under migration-path chaos. One SPAWNED
    prefill replica (real HTTP /kv_pages) feeds two in-process decode
    replicas over int8 KV-page migration; asserts: the happy path is
    token-identical to a unified reference; a seeded router.migrate
    fault replays from the seed and falls back to local recompute
    (token-identical, request never lost); a page corrupted in flight
    is REJECTED by digest verification and recomputed locally
    (token-identical); SIGKILLing the prefill replica mid-migration
    degrades the same way; and the decode pools leak zero pages
    through all of it."""
    from paddle_tpu.inference import kv_transfer as kvt
    from paddle_tpu.reliability import faults
    from paddle_tpu.serving import (HTTPReplica, LocalReplica, Router,
                                    make_engine_from_spec,
                                    spawn_replica)

    rng = np.random.RandomState(seed + 1)
    faults.reset()
    cache_dir = os.path.join(workdir, "xla_cache")
    os.makedirs(cache_dir, exist_ok=True)
    model = {"vocab": 97, "layers": 2, "hidden": 64, "heads": 4,
             "max_pos": 96, "model_seed": 0}
    engine_kw = {"page_size": 4, "num_pages": 96, "max_seqs": 4,
                 "prefill_buckets": (32,), "seed": 0,
                 "kv_dtype": "int8"}
    spec = dict(model, name="pre0", role="prefill",
                cache_dir=cache_dir, engine=dict(engine_kw))
    proc, info = spawn_replica(spec, timeout=180)
    import jax
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      0.0)
    dec = [make_engine_from_spec(dict(model, engine=dict(engine_kw)))
           for _ in range(2)]
    ref = make_engine_from_spec(dict(model, engine=dict(engine_kw)))
    prefill_client = HTTPReplica(info["generate"], info["healthz"],
                                 metrics_url=info.get("metrics"))

    class _TamperedPrefill:
        """Client wrapper that sabotages export_pages: 'corrupt'
        flips one KV byte in flight (digest verification must catch
        it); 'kill' SIGKILLs the prefill process first (the transfer
        must degrade to ReplicaUnavailable → local recompute)."""

        def __init__(self, inner):
            self.inner = inner
            self.mode = None

        def __getattr__(self, name):
            return getattr(self.inner, name)

        def export_pages(self, digests, trace_context=None):
            if self.mode == "kill":
                os.kill(proc.pid, signal.SIGKILL)
                proc.wait(timeout=30)
            payload = self.inner.export_pages(
                digests, trace_context=trace_context)
            if self.mode == "corrupt" and payload["pages"]:
                rec = payload["pages"][1]
                k = bytearray(kvt._unb64(rec["k"]))
                k[0] ^= 0x40
                rec["k"] = kvt._b64(bytes(k))
            return payload

    tampered = _TamperedPrefill(prefill_client)
    router = Router(page_size=4, disagg_threshold_tokens=8,
                    failover_budget=2, health_poll_interval=0.25)
    router.attach("pre0", tampered, role="prefill")
    router.attach("dec0", LocalReplica(dec[0]), role="decode")
    router.attach("dec1", LocalReplica(dec[1]), role="decode")
    out = {}

    def prompt_of(n=24):
        return rng.randint(0, 97, n).tolist()

    def check_identity(p, r, temperature=0.0):
        want = ref.submit(p, max_new_tokens=16,
                          temperature=temperature,
                          nonce=r["request_id"]).result(timeout=240)
        assert want["output_ids"] == r["output_ids"], (
            "disagg stream diverged from the unified reference: "
            f"{want['output_ids']} != {r['output_ids']}")

    try:
        # -- phase A: happy-path migration, greedy AND seeded
        p = prompt_of()
        r = router.submit(p, max_new_tokens=16).result(timeout=240)
        assert r["replica"].startswith("dec"), r
        assert r.get("migrated_pages", 0) > 0, (
            "long uncached prompt did not migrate: "
            f"{router._status()['migrations']}")
        check_identity(p, r)
        p = prompt_of()
        r = router.submit(p, max_new_tokens=16,
                          temperature=0.9).result(timeout=240)
        assert r.get("migrated_pages", 0) > 0, r
        check_identity(p, r, temperature=0.9)
        assert router.n_migrations == 2, router._status()
        out["happy"] = dict(router._status()["migrations"])

        # -- phase B: seeded router.migrate fault — fallback to local
        # recompute, seed-replayable schedule, request never lost
        faults.enable(seed=seed)
        faults.inject("router.migrate", nth=(1,), times=1)
        p = prompt_of()
        r = router.submit(p, max_new_tokens=16).result(timeout=240)
        assert "migrate_s" not in r, r
        check_identity(p, r)
        assert ("router.migrate", 1) in faults.injected_log(), \
            faults.injected_log()
        _assert_schedule_matches(faults, ("router.migrate",))
        faults.reset()
        assert router.n_migrate_failed == 1, router._status()
        out["fault_fallback"] = {"failed": router.n_migrate_failed}

        # -- phase C: one page corrupted in flight — digest
        # verification rejects it, the decode replica recomputes the
        # gap locally, the stream stays identical, nothing leaks
        tampered.mode = "corrupt"
        p = prompt_of()
        r = router.submit(p, max_new_tokens=16).result(timeout=240)
        tampered.mode = None
        assert r.get("migrated_pages", 5) < 5, (
            "corrupt page was not rejected: "
            f"{router._status()['migrations']}")
        assert router.n_pages_rejected >= 1, router._status()
        check_identity(p, r)
        out["corruption"] = {
            "rejected": router.n_pages_rejected,
            "installed": r.get("migrated_pages")}

        # -- phase D: prefill replica SIGKILLed mid-migration — the
        # pull fails, the request falls back and completes locally
        tampered.mode = "kill"
        p = prompt_of()
        r = router.submit(p, max_new_tokens=16).result(timeout=240)
        assert "migrate_s" not in r, r
        check_identity(p, r)
        assert router.n_migrate_failed == 2, router._status()
        out["kill"] = {"failed": router.n_migrate_failed}

        # -- leak audit: idle decode pools must account for every
        # page (free + shared residents + the scratch page)
        for eng in dec:
            free = len(eng._free_pages)
            shared = eng._cache.shared_page_count
            assert free + shared + 1 == eng.num_pages, (
                f"page leak: free={free} shared={shared} "
                f"of {eng.num_pages}")
        out["pages_leaked"] = 0
    finally:
        faults.reset()
        router.close()
        for eng in dec + [ref]:
            eng.close()
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
    for eng in dec:
        assert len(eng._free_pages) == eng.num_pages - 1, (
            "decode pool did not return to full size at close")
    return out


def drift_soak(seed: int, workdir: str) -> dict:
    """Scenario 5d (rides ``--fleet``, ISSUE 19): the stream-integrity
    auditor under a drift storm. Asserts the acceptance invariants:
    a fault-free shadow storm (audit_shadow_rate=1.0) verifies every
    stream with ZERO divergences; a seeded ``audit.flip`` — one token
    XOR-flipped BEFORE the digest chain extends over it, so the
    corrupted stream is self-consistent and only chain-vs-chain
    comparison can see it — is caught by the shadow re-execution at
    the EXACT divergent position, with a one-shot stream_divergence
    flight dump carrying both chain heads and both knob fingerprints;
    the same flip under an engine device-retry is caught by the
    retry's prefix check (``kind="failover"``, exact position); the
    fault schedule replays from the seed; and a final clean storm
    records zero NEW divergences (a tripped auditor must not keep
    crying wolf)."""
    from paddle_tpu.core import flags as flags_mod
    from paddle_tpu.observability import audit, flight
    from paddle_tpu.reliability import faults
    from paddle_tpu.serving import (LocalReplica, Router,
                                    make_engine_from_spec)

    rng = np.random.RandomState(seed + 2)
    faults.reset()
    audit.reset()
    audit.enable()
    old_rate = flags_mod.get_flag("audit_shadow_rate")
    flags_mod.set_flags({"audit_shadow_rate": 1.0})
    fdir = os.path.join(workdir, "drift_flight")
    rec = flight.FlightRecorder(fdir)
    rec.install()
    model = {"vocab": 97, "layers": 2, "hidden": 64, "heads": 4,
             "max_pos": 96, "model_seed": 0}
    engine_kw = {"max_seqs": 4, "page_size": 4, "num_pages": 64,
                 "prefill_buckets": (32,), "seed": 0,
                 "device_retry_budget": 2}
    engs = [make_engine_from_spec(dict(model, engine=dict(engine_kw)))
            for _ in range(2)]
    router = Router({"a": LocalReplica(engs[0]),
                     "b": LocalReplica(engs[1])},
                    failover_budget=2, health_poll_interval=0.25)
    out = {}

    def counts():
        return audit.instance().counts()

    try:
        # -- phase A: fault-free shadow storm — every served stream is
        # re-executed off-path and chain-diffed; zero divergences
        futs = [router.submit(rng.randint(0, 97, 12).tolist(),
                              max_new_tokens=8, temperature=0.9)
                for _ in range(6)]
        for f in futs:
            assert f.result(timeout=240)["stream_digest"]
        _poll_until(lambda: counts()["verified"] >= 6, 120,
                    "clean-storm shadows verifying")
        assert counts()["diverged"] == 0, audit.driftz_payload()
        out["clean"] = dict(counts())

        # -- phase B: seeded audit.flip — flip the 4th delivered
        # token; the served stream is self-consistent (its digest
        # matches its tokens) so only the shadow's chain-vs-chain
        # diff can catch it, at EXACTLY position 3 (0-based)
        faults.enable(seed=seed)
        faults.inject("audit.flip", nth=(4,), times=1)
        r = router.submit(rng.randint(0, 97, 12).tolist(),
                          max_new_tokens=8,
                          temperature=0.9).result(timeout=240)
        assert r["stream_digest"]          # self-consistent: served
        _poll_until(lambda: counts()["diverged"] >= 1, 120,
                    "shadow catching the flipped token")
        div = audit.driftz_payload()["scopes"]["router"][
            "last_divergence"]
        assert div["kind"] == "shadow", div
        assert div["position"] == 3, (
            f"divergence not at the flipped token: {div}")
        assert div["chain_ours"] != div["chain_theirs"], div
        assert div["knobs_ours"] is not None, div
        assert ("audit.flip", 4) in faults.injected_log(), \
            faults.injected_log()
        _assert_schedule_matches(faults, ("audit.flip",))
        dumps = [f for f in os.listdir(fdir)
                 if "stream_divergence" in f]
        assert len(dumps) == 1, (
            f"expected exactly one one-shot divergence dump: {dumps}")
        rows = [json.loads(line)
                for line in open(os.path.join(fdir, dumps[0]))]
        extra = [x for x in rows if x.get("kind") == "extra"]
        assert extra and extra[0]["divergence"]["position"] == 3, rows
        out["flip"] = {"position": div["position"],
                       "dump": dumps[0]}

        # -- phase C: the flip under an engine device-retry — the
        # retry re-admits with the same nonce and must re-emit the
        # exact prefix the failed incarnation delivered; the flipped
        # token #2 makes the prefixes differ at position 1
        faults.reset()
        faults.enable(seed=seed)
        faults.inject("audit.flip", nth=(2,), times=1)
        eng = engs[0]
        real = eng._decode_fn
        state = {"n": 0}

        def flaky(*a, **kw):
            state["n"] += 1
            if state["n"] == 5:        # die after ~4 clean ticks
                raise RuntimeError("transient PJRT failure")
            return real(*a, **kw)

        eng._decode_fn = flaky
        try:
            r = eng.submit([5, 6, 7, 8], max_new_tokens=8,
                           temperature=0.8).result(timeout=240)
        finally:
            eng._decode_fn = real
        assert r["output_ids"] and r["stream_digest"]
        sc = audit.driftz_payload()["scopes"]
        escope = next((s for n, s in sc.items() if n != "router"
                       and s["by_kind"]["failover"]), None)
        assert escope is not None, sc
        ediv = escope["last_divergence"]
        assert ediv["kind"] == "failover" and ediv["position"] == 1, \
            ediv
        _assert_schedule_matches(faults, ("audit.flip",))
        faults.reset()
        out["device_retry"] = {"position": ediv["position"]}

        # -- phase D: clean storm after the incident — divergence
        # counts must NOT move (the auditor detects drift, it does
        # not manufacture it)
        before = counts()["diverged"]
        futs = [router.submit(rng.randint(0, 97, 12).tolist(),
                              max_new_tokens=8, temperature=0.9)
                for _ in range(4)]
        for f in futs:
            assert f.result(timeout=240)["stream_digest"]
        _poll_until(
            lambda: counts()["verified"] >= out["clean"]["verified"]
            + 4, 120, "post-incident clean storm verifying")
        assert counts()["diverged"] == before, audit.driftz_payload()
        out["post_clean"] = dict(counts())
    finally:
        faults.reset()
        flags_mod.set_flags({"audit_shadow_rate": old_rate})
        rec.uninstall()
        router.close()
        for eng in engs:
            eng.close()
    return out


def autoscale_soak(seed: int, workdir: str) -> dict:
    """Scenario 5b (``--autoscale``, ISSUE 13): the SLO-driven
    autoscaler over a LIVE subprocess fleet. Asserts the acceptance
    invariants: a deadline-miss storm trips the gold class's burn
    windows and triggers a scale-out whose FIRST spawn attempt dies on
    the seeded ``autoscale.spawn`` fault (the retry must absorb it and
    never double-count capacity; the replica counts only after READY +
    a successful health probe); a SIGKILL of the autoscaled replica
    mid-decode loses ZERO requests (nonce-pinned token-identical
    failover, checked against a reference engine) and is respawned as
    a REPLACEMENT, not a scale-out; a seeded ``autoscale.drain`` fault
    expires the scale-in drain deadline with stragglers in flight,
    which must complete token-identically on a sibling; the terminated
    replica is withdrawn from TCPStore membership immediately (no
    stale-record re-attach); and both autoscale fault sites replay
    from the seed. Failures attach the merged cross-process trace
    next to the fault seed + replay command, like every fleet phase."""
    from paddle_tpu.distributed.tcp_store import (TCPMembership,
                                                  TCPStoreClient,
                                                  TCPStoreServer)
    from paddle_tpu.observability import tracing
    from paddle_tpu.reliability import faults
    from paddle_tpu.reliability.retry import DeadlineExceeded
    from paddle_tpu.serving import (Autoscaler, HTTPReplica,
                                    LocalReplica, Router, SLOClass,
                                    make_engine_from_spec,
                                    make_subprocess_spawner,
                                    spawn_replica)
    from paddle_tpu.serving.router import affinity_key, rendezvous_pick

    rng = np.random.RandomState(seed)
    faults.reset()
    tracing.enable()
    store = TCPStoreServer("127.0.0.1", 0)
    endpoint = f"127.0.0.1:{store.port}"
    obs_dir = os.path.join(workdir, "obs")
    model = {"vocab": 97, "layers": 2, "hidden": 64, "heads": 4,
             "max_pos": 96, "model_seed": 0,
             "tracing": True, "obs_dir": obs_dir}
    engine_kw = {"device_retry_budget": 2, "max_pending": 64,
                 "seed": 0}
    cache_dir = os.path.join(workdir, "xla_cache")
    os.makedirs(cache_dir, exist_ok=True)
    # the seed replica (unmanaged — the autoscaler can only kill what
    # it spawned) boots first and warms the shared compile cache
    procs, infos = {}, {}
    spec0 = dict(model, name="r0", store=endpoint,
                 cache_dir=cache_dir, engine=dict(engine_kw))
    procs["r0"], infos["r0"] = spawn_replica(spec0, timeout=180)
    HTTPReplica(infos["r0"]["generate"],
                infos["r0"]["healthz"]).submit([1, 2, 3],
                                               max_new_tokens=2)
    # reference engine: same weights/seed/cache — replays any
    # failover'd stream nonce-pinned to pin token identity
    import jax
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      0.0)
    ref = LocalReplica(make_engine_from_spec(dict(model,
                                                  engine=engine_kw)))
    ref.submit([1, 2, 3], max_new_tokens=1)

    router = Router(store_endpoint=endpoint, page_size=16,
                    affinity_pages=2, failover_budget=2,
                    health_poll_interval=0.2,
                    membership_stale_after=1.5,
                    breaker_fail_threshold=3, breaker_open_for=1.0,
                    slo_classes={"gold": SLOClass(
                        "gold", deadline_s=60.0, target=0.99)},
                    slo_windows=(2.0, 8.0), slo_min_samples=5,
                    slo_breach_threshold=5.0)
    auto_spec = dict(model, store=endpoint, cache_dir=cache_dir,
                     engine=dict(engine_kw))
    scaler = Autoscaler(
        router, make_subprocess_spawner(auto_spec, timeout=180),
        min_replicas=1, max_replicas=2, replica_slots=4,
        # scale-in disarmed until phase C flips low_water — the
        # phases need the fleet to HOLD at 2 through the SIGKILL
        low_water=-1.0, dwell_s=3.0,
        backoff_base_s=0.5, backoff_cap_s=8.0,
        drain_deadline_s=30.0, spawn_backoff_s=0.2,
        ready_timeout_s=180.0, name_prefix="auto")
    out = {}
    client = TCPStoreClient(endpoint)

    def affine_prompt(target, names, length):
        while True:
            p = rng.randint(0, 97, length).tolist()
            key = affinity_key(p, router.page_size,
                               router.affinity_pages)
            if rendezvous_pick(key, names) == target:
                return p

    try:
        _poll_until(lambda: router.replica_names() == ["r0"], 30,
                    "r0 membership convergence")
        scaler.start()
        faults.enable(seed=seed)
        # the FIRST spawn attempt of the storm's scale-out must die
        # and be retried without ghost capacity
        faults.inject("autoscale.spawn", nth=(1,))

        # -- phase A: gold deadline-miss storm trips both burn
        # windows → scale-out (1 → 2), spawn fault absorbed
        storm = [router.submit(rng.randint(0, 97, 8).tolist(),
                               max_new_tokens=4, slo="gold",
                               deadline=0.001) for _ in range(8)]
        n_missed = 0
        for f in storm:
            try:
                f.result(timeout=120)
            except DeadlineExceeded:
                n_missed += 1
        assert n_missed == 8, (
            f"storm deadlines not hopeless enough: {n_missed}/8")
        _poll_until(lambda: scaler.n_scale_out >= 1, 240,
                    "burn-tripped scale-out")
        _poll_until(
            lambda: router.fleet_load(4)["ready"] == 2, 240,
            "spawned replica READY + healthy and counted")
        d_out = [d for d in scaler.decisions()
                 if d["action"] == "scale_out"][0]
        assert d_out["reason"].startswith("slo_burn:gold"), d_out
        assert d_out["attempts"] == 2, (
            f"autoscale.spawn fault was not retried: {d_out}")
        load = router.fleet_load(4)
        assert load["attached"] == 2 and load["warming"] == 0, (
            f"failed spawn attempt left ghost capacity: {load}")
        auto1 = d_out["replica"]
        h1 = scaler._managed[auto1].handle
        infos[auto1] = dict(h1.info)
        out["scale_out"] = {"replica": auto1,
                            "attempts": d_out["attempts"],
                            "missed": n_missed}

        # -- phase B: SIGKILL the autoscaled replica mid-decode —
        # zero lost requests (token-identical failover), respawned as
        # a REPLACEMENT (not a scale-out)
        names = ("r0", auto1)
        prompts = [affine_prompt(auto1, names, 16) for _ in range(4)]
        futs = [router.submit(p, max_new_tokens=32, temperature=0.9)
                for p in prompts]
        _poll_until(lambda: (router.inflight_of(auto1) or 0) > 0, 60,
                    "autoscaled replica taking traffic")
        os.kill(h1.proc.pid, signal.SIGKILL)
        h1.proc.wait(timeout=30)
        results = [f.result(timeout=240) for f in futs]
        assert all(r["output_ids"] for r in results), results
        flipped = [(p, r) for p, r in zip(prompts, results)
                   if r["failovers"] > 0]
        assert flipped, (
            "SIGKILL mid-decode caused no failover — the kill missed "
            f"the in-flight window: {[r['replica'] for r in results]}")
        for p, r in flipped[:2]:
            ref_out = ref.submit(p, max_new_tokens=32,
                                 temperature=0.9,
                                 nonce=r["request_id"])
            assert ref_out["output_ids"] == r["output_ids"], (
                "failover was not token-identical: "
                f"{ref_out['output_ids']} != {r['output_ids']}")
        _poll_until(lambda: scaler.n_replaced >= 1, 240,
                    "replacement spawn after the SIGKILL")
        _poll_until(
            lambda: router.fleet_load(4)["ready"] == 2, 240,
            "replacement READY + healthy")
        assert scaler.n_scale_out == 1, (
            "a SIGKILL respawn was counted as a scale-out: "
            f"{scaler.decisions()}")
        d_rep = [d for d in scaler.decisions()
                 if d["action"] == "replace"][-1]
        auto2 = d_rep["replica"]
        h2 = scaler._managed[auto2].handle
        infos[auto2] = dict(h2.info)
        _poll_until(
            lambda: auto1 not in TCPMembership.list_members(client),
            15, "dead replica withdrawn from the roster")
        out["kill"] = {"failovers": len(flipped),
                       "replacement": auto2}

        # -- phase C: scale-in under the seeded drain fault — the
        # drain deadline expires with stragglers in flight, the kill
        # proceeds, and the stragglers complete token-identically on
        # the sibling. Zero lost requests across the scale-in.
        faults.inject("autoscale.drain", nth=(1,))
        names = ("r0", auto2)
        c_prompts = [affine_prompt(auto2, names, 16)
                     for _ in range(6)]
        c_futs = [router.submit(p, max_new_tokens=64,
                                temperature=0.9) for p in c_prompts]
        _poll_until(lambda: (router.inflight_of(auto2) or 0) > 0, 60,
                    "victim holding in-flight work")
        scaler.low_water = 0.8      # arm the scale-in trigger
        _poll_until(lambda: scaler.n_scale_in >= 1, 120,
                    "fault-forced scale-in")
        c_results = [f.result(timeout=240) for f in c_futs]
        assert all(r["output_ids"] for r in c_results), c_results
        d_in = [d for d in scaler.decisions()
                if d["action"] == "scale_in"][-1]
        assert d_in["replica"] == auto2, d_in
        assert d_in["stragglers"] >= 1, (
            f"the drain fault should have expired the deadline with "
            f"stragglers in flight: {d_in}")
        moved = [(p, r) for p, r in zip(c_prompts, c_results)
                 if r["replica"] != auto2]
        assert moved, (
            "no straggler finished on a sibling — the drain kill "
            f"lost its in-flight work? {c_results}")
        for p, r in moved[:2]:
            ref_out = ref.submit(p, max_new_tokens=64,
                                 temperature=0.9,
                                 nonce=r["request_id"])
            assert ref_out["output_ids"] == r["output_ids"], (
                "straggler failover was not token-identical: "
                f"{ref_out['output_ids']} != {r['output_ids']}")
        _poll_until(
            lambda: router.fleet_load(4)["ready"] == 1, 60,
            "fleet back at min_replicas after the scale-in")
        _poll_until(
            lambda: set(TCPMembership.list_members(client)) == {"r0"},
            15, "scaled-in replica withdrawn from the roster")
        out["scale_in"] = {"stragglers": d_in["stragglers"],
                           "drain_s": d_in["drain_s"],
                           "moved": len(moved)}

        # -- determinism: both autoscale sites replay from the seed
        _assert_schedule_matches(
            faults, ("autoscale.spawn", "autoscale.drain"))
        out["decisions"] = len(scaler.decisions())
    except AssertionError:
        path, summary = _attach_fleet_trace(workdir, infos)
        if path is not None:
            print(f"merged cross-process trace attached: {path} "
                  f"({summary['spans']} spans from "
                  f"{summary['processes']} processes)",
                  file=sys.stderr, flush=True)
        raise
    finally:
        faults.reset()
        tracing.disable()
        scaler.close(terminate_managed=True)
        router.close()
        ref.engine.close()
        for p in procs.values():
            if p.poll() is None:
                p.kill()
                p.wait(timeout=30)
        store.close()
    return out


def overload_soak(seed: int, workdir: str) -> dict:
    """Scenario 5c (``--overload``, ISSUE 20): the brownout controller
    under a seeded burst storm. Two in-process replicas behind a
    Router with an :class:`OverloadController`; three rounds of
    deadline-doomed bronze bursts (plus protected gold) trip the
    bronze burn windows and walk the ladder up; the storm draining
    walks it back to normal within its dwell bounds. Asserts: every
    future resolves TYPED, gold loses zero requests, the ladder moves
    one level per transition, the seeded ``overload.estimate``
    distortion surfaces as hopeless-shed verdicts (never a hang), the
    seeded ``overload.step`` escalation is walked back by hysteresis,
    and both sites replay from the seed."""
    from paddle_tpu.inference.llm import (AdmissionShed, LLMEngine,
                                          OverloadShed)
    from paddle_tpu.reliability import faults
    from paddle_tpu.reliability.retry import DeadlineExceeded
    from paddle_tpu.serving import (AIMDLimiter, BrownoutLadder,
                                    LocalReplica, OverloadController,
                                    Router, SLOClass,
                                    ServiceTimeEstimator)

    rng = np.random.RandomState(seed)
    faults.reset()

    def build_engine():
        return LLMEngine(_tiny_gpt(), max_seqs=4, page_size=4,
                         num_pages=96, prefill_buckets=(16,),
                         max_pending=64, admit_timeout=60.0, seed=0)

    engines = [build_engine(), build_engine()]
    for e in engines:           # shared in-process compile warmup
        e.generate([[1, 2, 3]], max_new_tokens=2)
    # injected rate source: deterministic predictions (the perf-
    # registry path is the bench's job; the soak pins CONTROL flow)
    ctrl = OverloadController(
        estimator=ServiceTimeEstimator(source=lambda: (4000.0, 800.0)),
        limiter=AIMDLimiter(floor=1, ceiling=8),
        ladder=BrownoutLadder(up_dwell_s=0.2, down_dwell_s=0.3,
                              backoff_base_s=0.2, backoff_cap_s=1.0),
        bronze_max_new_tokens=8)
    router = Router({"r0": LocalReplica(engines[0]),
                     "r1": LocalReplica(engines[1])},
                    health_poll_interval=0.1, scrape_metrics=False,
                    slo_classes={
                        "gold": SLOClass("gold", deadline_s=60.0,
                                         target=0.99),
                        "bronze": SLOClass("bronze", deadline_s=0.08,
                                           target=0.99)},
                    slo_windows=(1.0, 4.0), slo_min_samples=4,
                    slo_breach_threshold=5.0, overload=ctrl)
    outcomes = {"ok": 0, "deadline": 0, "shed": 0, "error": 0}
    gold_lost, max_level = [], [0]
    stop_watch = threading.Event()

    def watch_level():
        while not stop_watch.is_set():
            max_level[0] = max(max_level[0], ctrl.level)
            time.sleep(0.02)

    watcher = threading.Thread(target=watch_level, daemon=True)
    watcher.start()

    def tally(futs):
        done, not_done = fut_wait([f for _s, f in futs],
                                  timeout=FUTURE_TIMEOUT)
        assert not not_done, (
            f"{len(not_done)} futures never resolved — the overload "
            f"controller hung the router")
        for slo, f in futs:
            exc = f.exception()
            if exc is None:
                outcomes["ok"] += 1
            elif isinstance(exc, DeadlineExceeded):
                outcomes["deadline"] += 1
                if slo == "gold":
                    gold_lost.append(("deadline", str(exc)))
            elif isinstance(exc, AdmissionShed):
                outcomes["shed"] += 1
                if slo == "gold":
                    gold_lost.append(("shed", str(exc)))
            else:
                outcomes["error"] += 1
                gold_lost.append((type(exc).__name__, str(exc)))

    try:
        faults.enable(seed=seed)
        # 2nd + 7th predictions distort 1000× (→ hopeless sheds); the
        # overload.step escalation is armed LATER, once the ladder is
        # back at normal — forced at max level it would clamp to a
        # no-op and the walk-back assertion would test nothing
        faults.inject("overload.estimate", nth=(2, 7))

        # -- 3× burst storm: bronze is deadline-doomed (0.08 s for a
        # 24-token decode), gold is generously budgeted and PROTECTED
        for _round in range(3):
            futs = [("bronze",
                     router.submit(rng.randint(0, 97, 12).tolist(),
                                   max_new_tokens=24, slo="bronze"))
                    for _ in range(12)]
            futs += [("gold",
                      router.submit(rng.randint(0, 97, 8).tolist(),
                                    max_new_tokens=4, slo="gold"))
                     for _ in range(4)]
            tally(futs)
            time.sleep(0.3)     # let ticks see the burn windows
        _poll_until(lambda: ctrl.level >= 1 or max_level[0] >= 1, 30,
                    "ladder engaging under the bronze burn signal")

        # -- quiet: bronze samples age out of the (1 s, 4 s) windows,
        # the ladder walks back down one dwell-bounded level at a time
        _poll_until(lambda: ctrl.level == 0, 60,
                    "ladder walking back to normal after the storm")

        # -- spurious escalation: force the ladder UP from normal on a
        # seeded tick (2 calls out — ticks ride the 0.1 s poll, so the
        # fault lands while the fleet is demonstrably calm) and assert
        # the hysteresis walks it back without any real burn signal
        faults.inject("overload.step",
                      nth=(faults.call_count("overload.step") + 2,))
        _poll_until(
            lambda: any(t["reason"].startswith("fault_injected")
                        for t in ctrl.ladder.transitions()), 30,
            "seeded overload.step escalation landing")
        _poll_until(lambda: ctrl.level == 0, 60,
                    "hysteresis walking back the spurious escalation")
        stop_watch.set()
        watcher.join(timeout=5)

        assert outcomes["error"] == 0, (
            f"untyped resolutions under overload chaos: {outcomes}, "
            f"first: {gold_lost[:3]}")
        assert not gold_lost, (
            f"gold lost {len(gold_lost)} request(s) — the protected "
            f"class must never be shed or missed: {gold_lost[:3]}")
        assert outcomes["shed"] + outcomes["deadline"] > 0, (
            f"the storm was not a storm: {outcomes}")
        shed_counts = dict(ctrl.n_shed)
        assert shed_counts.get("hopeless", 0) >= 1, (
            "the seeded overload.estimate distortion never surfaced "
            f"as a hopeless shed: {shed_counts}")
        trans = ctrl.ladder.transitions()
        assert max_level[0] >= 1 and any(
            t["to"] > t["from"] for t in trans), (
            f"the ladder never engaged: max={max_level[0]}, {trans}")
        assert all(abs(t["to"] - t["from"]) == 1
                   for t in trans), (
            f"a transition jumped more than one level: {trans}")
        assert any(t["reason"].startswith("fault_injected")
                   for t in trans), (
            "the seeded overload.step escalation never landed: "
            f"{trans}")
        assert len(trans) <= 24, (
            f"ladder flapped {len(trans)} transitions — hysteresis "
            f"is not damping: {trans}")
        assert ctrl.level == 0, f"ladder stuck at {ctrl.level}"

        # -- determinism: both overload sites replay from the seed
        _assert_schedule_matches(
            faults, ("overload.estimate", "overload.step"))
        return {"outcomes": outcomes, "max_level": max_level[0],
                "transitions": len(trans), "shed": shed_counts,
                "limits": ctrl.limiter.state()}
    finally:
        stop_watch.set()
        faults.reset()
        router.close()
        for e in engines:
            e.close()


TRAIN_STEPS = 16          # 2 epochs × 8 steps (32 samples / batch 4)
TRAIN_EPOCH_STEPS = TRAIN_STEPS // 2
TRAIN_CKPT_FREQ = 5


def train_soak(seed: int, workdir: str) -> dict:
    """Scenario 6: kill-anywhere / resume-exactly. For steps_per_loop
    ∈ {1, 4}: an uninterrupted baseline, then seeded kills (SIGKILL in
    the STEP/SNAPSHOT/COMMIT/GC windows, SIGTERM for the graceful
    emergency-flush path), then relaunch-to-completion — the combined
    loss stream must be bit-identical to the baseline at every step,
    including steps re-run after resuming from an older checkpoint.
    Plus in-process: corrupt-checkpoint quarantine + fallback, seeded
    replay of the ckpt.snapshot/ckpt.async_commit fault sites, and the
    async-save stall bound (snapshot time, not commit time)."""
    rng = np.random.RandomState(seed)
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)

    def launch(run_dir, k):
        os.makedirs(run_dir, exist_ok=True)
        return subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--train-worker",
             run_dir, str(k), str(TRAIN_CKPT_FREQ)],
            env=env, cwd=REPO, stdout=subprocess.PIPE, text=True)

    def read_losses(run_dir):
        out = {}
        path = os.path.join(run_dir, "losses.txt")
        if os.path.exists(path):
            for ln in open(path):
                s, h = ln.split()
                out.setdefault(int(s), []).append(h)
        return out

    def run_complete(run_dir, k):
        p = launch(run_dir, k)
        out_text, _ = p.communicate(timeout=300)
        assert p.returncode == 0, out_text[-800:]
        assert "DONE" in out_text, out_text[-400:]

    def run_and_kill(run_dir, k, kind, occurrence, jitter):
        """Kill the worker at the chosen marker occurrence (seeded
        jitter inside the window). kind="TERM" sends SIGTERM at a STEP
        marker instead — the graceful-preemption path — and asserts
        the deadline-budgeted flush exits RESTART_EXIT_CODE. Returns
        the window the worker died in, or None if it finished first."""
        from paddle_tpu.distributed.elastic import RESTART_EXIT_CODE
        p = launch(run_dir, k)
        target = "STEP" if kind == "TERM" else kind
        seen = 0
        died_in = None
        for line in p.stdout:
            parts = line.split()
            if not parts:
                continue
            if parts[0] == "DONE":
                break
            if parts[0] == target:
                seen += 1
                if seen >= occurrence:
                    time.sleep(jitter)
                    if kind == "TERM":
                        p.send_signal(signal.SIGTERM)
                    else:
                        p.kill()
                    died_in = kind
                    break
        p.wait(timeout=180)
        if died_in == "TERM":
            assert p.returncode == RESTART_EXIT_CODE, (
                f"SIGTERM mid-training exited {p.returncode}, not "
                f"{RESTART_EXIT_CODE} — the PreemptionGuard emergency "
                f"flush path is broken")
        return died_in

    # pre-draw every seeded choice, then run the two independent
    # steps_per_loop lanes CONCURRENTLY (each is mostly subprocess
    # startup + pipe waits): determinism stays a pure function of the
    # seed while the wall clock halves toward the CI budget
    kinds = ["SNAPSHOT", "COMMIT", "GC", "TERM", "STEP"]
    order = [kinds[int(i)] for i in rng.permutation(len(kinds))]
    plans = []
    for ki, k in enumerate((1, 4)):
        lane = []
        for kind in order[2 * ki: 2 * ki + 2]:
            occurrence = int(rng.randint(2, 14)
                             if kind in ("STEP", "TERM")
                             else rng.randint(1, 3))
            lane.append((kind, occurrence,
                         float(rng.uniform(0.0, 0.02))))
        plans.append((k, lane))
    out = {"kills": []}

    # both uninterrupted baselines ride ONE subprocess (one jax
    # import, shared warm caches) before the kill lanes fan out
    base1 = os.path.join(workdir, "train_base_k1")
    base4 = os.path.join(workdir, "train_base_k4")
    os.makedirs(base1, exist_ok=True)
    os.makedirs(base4, exist_ok=True)
    p = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--train-baseline",
         base1, base4, str(TRAIN_CKPT_FREQ)],
        env=env, cwd=REPO, stdout=subprocess.PIPE, text=True,
        timeout=300)
    assert p.returncode == 0 and p.stdout.count("DONE") == 2, (
        f"baseline run failed rc={p.returncode}: {p.stdout[-800:]}")

    def lane_run(k, lane):
        baseline = read_losses(os.path.join(workdir,
                                            f"train_base_k{k}"))
        assert sorted(baseline) == list(range(TRAIN_STEPS)), (
            f"k={k} baseline incomplete: {sorted(baseline)}")
        ref = {s: v[0] for s, v in baseline.items()}

        run_dir = os.path.join(workdir, f"train_kill_k{k}")
        kills = []
        for kind, occurrence, jitter in lane:
            died_in = run_and_kill(run_dir, k, kind, occurrence, jitter)
            kills.append({"k": k, "kind": kind,
                          "occurrence": occurrence,
                          "landed": bool(died_in)})
        run_complete(run_dir, k)  # final incarnation finishes the range
        got = read_losses(run_dir)
        assert sorted(got) == list(range(TRAIN_STEPS)), (
            f"k={k}: killed/resumed run lost steps: {sorted(got)}")
        for s in range(TRAIN_STEPS):
            for h in got[s]:
                assert h == ref[s], (
                    f"k={k} step {s}: resumed loss {h} != baseline "
                    f"{ref[s]} — resume is not bit-identical")
        return kills, sum(len(v) for v in got.values())

    lane_res: dict = {}

    def lane_thread(k, lane):
        try:
            lane_res[k] = lane_run(k, lane)
        except BaseException as e:  # noqa: BLE001 — re-raised below
            lane_res[k] = e

    threads = [threading.Thread(target=lane_thread, args=(k, lane))
               for k, lane in plans]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for k, _lane in plans:
        res = lane_res.get(k)
        if isinstance(res, BaseException):
            raise res
        kills, loss_lines = res
        out["kills"].extend(kills)
        out[f"k{k}"] = {"loss_lines": loss_lines}
    landed = sum(1 for kl in out["kills"] if kl["landed"])
    assert landed >= 2, (
        f"only {landed}/4 seeded kills landed inside the run — the "
        f"soak under-exercised the kill windows: {out['kills']}")
    out.update(_train_soak_inprocess(seed, workdir))
    out["guard"] = _train_soak_guard(seed, workdir)
    return out


def _train_soak_guard(seed: int, workdir: str) -> dict:
    """Scenario 6b: the poisoned-stream numeric-guard gate. Any
    assertion failure prints the fault seed + replay command and
    attaches a flight-recorder dump (same contract as the fleet/train
    phases)."""
    import hashlib

    from paddle_tpu import Model, nn, optimizer as pt_opt, seed as pt_seed
    from paddle_tpu.io import TensorDataset, stack_batches
    from paddle_tpu.io.checkpoint import CheckpointManager
    from paddle_tpu.observability import flight
    from paddle_tpu.reliability import faults
    from paddle_tpu.reliability import guard as nguard

    rng = np.random.RandomState(seed)
    n_batches, batch = 16, 4
    batches = [(rng.randn(batch, 8).astype(np.float32),
                rng.randint(0, 4, (batch, 1)))
               for _ in range(n_batches)]

    def build(policy):
        pt_seed(0)
        net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(),
                            nn.Linear(16, 4))
        m = Model(net)
        # constant-LR Adam, no dropout: the exactness scope of skip ≡
        # clean-minus (per-step keys / LR schedules would key on the
        # shifted step index)
        m.prepare(optimizer=pt_opt.Adam(learning_rate=1e-2,
                                        parameters=net),
                  loss=nn.CrossEntropyLoss(), numeric_guard=policy)
        return m

    def params_hex(m):
        m.sync_weights()
        h = hashlib.blake2b(digest_size=16)
        for name, v in sorted(m.network.state_dict().items()):
            h.update(name.encode())
            h.update(np.ascontiguousarray(np.asarray(v)).tobytes())
        return h.hexdigest()

    def run(m, k, skip_idx=()):
        kept = [b for i, b in enumerate(batches) if i not in skip_idx]
        if k == 1:
            for x, y in kept:
                m.train_batch([x], [y])
        else:
            for lo in range(0, len(kept), k):
                slab = stack_batches(kept[lo:lo + k])
                m.train_loop_batch([slab[0]], [slab[1]])
        m.drain_metrics()
        return m

    rec = flight.install_flight_recorder(
        os.path.join(workdir, "guard_flight"))
    out = {}
    try:
        # -- phase A: skip-policy determinism at K ∈ {1, 4}, both
        # fault sites. Poisoned final params hex must equal the clean
        # run over the stream minus the scheduled steps.
        for site in ("data.poison", "grad.nonfinite"):
            for k in (1, 4):
                faults.reset()
                faults.enable(seed=seed)
                faults.inject(site, nth=(4, 11))
                m = run(build(nguard.GuardPolicy(on_nonfinite="skip",
                                                 budget=8)), k)
                assert m._guard.n_skipped == 2, m._guard.status()
                schedule = faults.preview(site, n_batches)
                assert schedule == [4, 11], schedule
                _assert_schedule_matches(faults, (site,))
                poisoned = params_hex(m)
                faults.reset()
                clean = params_hex(run(
                    build(nguard.GuardPolicy(on_nonfinite="skip")),
                    k, skip_idx={c - 1 for c in schedule}))
                assert poisoned == clean, (
                    f"{site} k={k}: skip-policy params {poisoned} != "
                    f"clean-minus params {clean} — skip is not an "
                    f"exact no-op")
                out[f"{site}.k{k}"] = poisoned
        # -- phase B: rollback restores a verified step and completes
        faults.reset()
        faults.enable(seed=seed)
        faults.inject("data.poison", nth=(10,))
        pol = nguard.GuardPolicy(on_nonfinite="rollback",
                                 max_rollbacks=3)
        m = build(pol)
        x = np.concatenate([b[0] for b in batches])
        y = np.concatenate([b[1] for b in batches])
        ck_dir = os.path.join(workdir, "guard_ck")
        m.fit(TensorDataset([x, y]), batch_size=batch, epochs=2,
              shuffle=False, verbose=0, checkpoint_dir=ck_dir,
              checkpoint_freq=3, keep_checkpoints=4)
        assert pol.n_rollbacks >= 1, pol.status()
        mgr = CheckpointManager(ck_dir, async_save=False)
        steps = mgr.verified_steps()
        mgr.close()
        assert steps and steps[-1] == m._step_count, (
            f"rollback run did not finish with a verified final "
            f"checkpoint: {steps} vs step {m._step_count}")
        faults.reset()
        out["rollback"] = {"rollbacks": pol.n_rollbacks,
                           "final_step": int(m._step_count)}
        # -- phase C: guard-off zero overhead — the lowered program
        # has no finite-check ops (the one-flag-check discipline made
        # structural), plus a wall-clock sanity bound vs guard-on
        moff = build(None)
        x0, y0 = batches[0]
        moff.train_batch([x0], [y0])
        lowered = moff._train_step_fn.lower(
            moff._params, moff._frozen, moff._opt_state,
            moff._buffers, moff._step_count, jax.random.key(0),
            (x0,), (y0,)).as_text()
        assert "is_finite" not in lowered, (
            "guard-off train step still contains finite-check ops — "
            "the disabled path is not zero-overhead")
        assert moff._guard is None and not moff._guard_pending
        mon = build(nguard.GuardPolicy(on_nonfinite="skip"))
        mon.train_batch([x0], [y0])

        def med_step(m):
            ts = []
            for _ in range(30):
                t0 = time.perf_counter()
                m.train_batch([x0], [y0])
                ts.append(time.perf_counter() - t0)
            return float(np.median(ts))

        t_off, t_on = med_step(moff), med_step(mon)
        moff.drain_metrics()
        mon.drain_metrics()
        assert t_off <= t_on * 1.5 + 2e-3, (
            f"guard-OFF per-step time {t_off * 1e3:.2f}ms vs guard-on "
            f"{t_on * 1e3:.2f}ms — the disabled path must not cost "
            f"more than one flag check")
        out["bench"] = {"off_ms": round(t_off * 1e3, 3),
                        "on_ms": round(t_on * 1e3, 3)}
    except AssertionError as e:
        path = rec.dump("guard_soak_failure",
                        extra={"what": "guard_soak_assertion",
                               "seed": seed, "error": str(e),
                               "injected": faults.injected_log()})
        print(f"GUARD SOAK FAILED under fault seed {seed}\n"
              f"replay: python tools/chaos_soak.py --train "
              f"--seed {seed}\nflight dump: {path}",
              file=sys.stderr, flush=True)
        raise
    finally:
        faults.reset()
        rec.uninstall()
    return out


def _train_soak_inprocess(seed: int, workdir: str) -> dict:
    """Train-soak invariants that don't need a subprocess."""
    import glob

    from paddle_tpu.io.checkpoint import (CheckpointManager,
                                          latest_manifest_step)
    from paddle_tpu.reliability import faults
    from paddle_tpu.reliability.faults import FaultInjected

    out = {}
    # -- async stall bound: slow the commit path 0.4s; save() must
    # return in snapshot time while the barrier sees the full commit
    d = os.path.join(workdir, "stall_ck")
    mgr = CheckpointManager(d, async_save=True)
    orig_commit = mgr._commit
    mgr._commit = lambda *a, **kw: (time.sleep(0.4),
                                    orig_commit(*a, **kw))[-1]
    t0 = time.perf_counter()
    mgr.save(1, {"w": np.zeros((128, 128), np.float32)},
             state={"step": 1})
    stall = time.perf_counter() - t0
    t0 = time.perf_counter()
    mgr.wait_until_finished()
    commit_wall = time.perf_counter() - t0
    assert stall < 0.2 and commit_wall >= 0.3, (
        f"async save stalled the train loop {stall:.3f}s against a "
        f"{commit_wall:.3f}s commit — the stall must be bounded by "
        f"the device→host snapshot, not the write")
    mgr._commit = orig_commit
    mgr.close()
    out["stall"] = {"save_call_s": round(stall, 4),
                    "commit_s": round(commit_wall, 3)}

    # -- corrupt newest checkpoint: quarantined on restore, falls back
    # to the newest VERIFIED step, never surfaces via latest_step again
    ckdir = os.path.join(workdir, "train_base_k1", "ckpt")
    mgr = CheckpointManager(ckdir, async_save=False)
    newest = mgr.latest_step()
    # flip a byte every 32 across EVERY file of the step: a single
    # mid-file flip can (correctly) be invisible when it lands in
    # ocdbt btree dead space a restore never reads — rot THIS thorough
    # must either corrupt restored values (digest mismatch) or break
    # the read outright (also quarantined)
    corrupted = 0
    for f in glob.glob(os.path.join(ckdir, str(newest), "**"),
                       recursive=True):
        if not os.path.isfile(f):
            continue
        blob = bytearray(open(f, "rb").read())
        for i in range(0, len(blob), 32):
            blob[i] ^= 0xFF
        open(f, "wb").write(bytes(blob))
        corrupted += 1
    assert corrupted, f"no payload files found under step {newest}"
    _tree, state = mgr.restore_with_state()
    fallback = mgr.latest_step()
    assert fallback is not None and fallback < newest, (
        f"corrupt step {newest} still surfaced: latest={fallback}")
    assert int(state["step"]) == fallback, state
    assert latest_manifest_step(ckdir) == fallback, (
        "quarantined step still visible to the elastic launcher")
    mgr.close()
    out["corrupt"] = {"newest": int(newest), "fallback": int(fallback)}

    # -- seeded replay at the new checkpoint fault sites
    faults.reset()
    faults.enable(seed=seed)
    faults.inject("ckpt.snapshot", nth=(2,), times=1)
    faults.inject("ckpt.async_commit", nth=(2,), times=1)
    d2 = os.path.join(workdir, "site_ck")
    m2 = CheckpointManager(d2, async_save=True)
    try:
        m2.save(1, {"w": np.arange(8)})
        m2.wait_until_finished()
        try:
            m2.save(2, {"w": np.arange(8)})
            raised = False
        except FaultInjected:
            raised = True   # snapshot fault hits the CALLER, in-line
        assert raised, "ckpt.snapshot fault did not surface"
        m2.save(3, {"w": np.arange(8)})
        try:
            m2.wait_until_finished()
            raised = False
        except FaultInjected:
            raised = True   # commit fault surfaces at the barrier
        assert raised, "ckpt.async_commit fault did not surface"
        assert m2.latest_step() == 1, (
            f"a faulted commit surfaced: {m2.latest_step()}")
        _assert_schedule_matches(
            faults, ("ckpt.snapshot", "ckpt.async_commit"))
    finally:
        m2.close()
        faults.reset()
    out["fault_sites"] = {"injected": 2}
    return out


def _train_worker(run_dir: str, k: int, freq: int) -> int:
    """Subprocess body for the train soak: fit with async full-state
    checkpointing + resume="auto" + PreemptionGuard, announcing phase
    markers so the parent can land kills inside specific windows.
    Appends one "step loss-hex" line per optimizer step to losses.txt
    (hex floats: the bit-identity assertion needs exact values)."""
    import paddle_tpu as pt
    from paddle_tpu import nn
    from paddle_tpu.core import flags
    from paddle_tpu.distributed import elastic
    from paddle_tpu.io import TensorDataset
    from paddle_tpu.io import checkpoint as ckpt_mod

    # shared persistent compile cache: relaunches (the whole point of
    # this soak) skip the XLA compile after the first incarnation
    flags.set_flags({"compilation_cache_dir":
                     os.path.join(os.path.dirname(run_dir), "xla_cache")})

    # phase markers for the parent's kill targeting (patch ONCE — the
    # merged-baseline mode calls this body twice in one process)
    Mgr = ckpt_mod.CheckpointManager
    if not getattr(Mgr, "_soak_markers", False):
        orig_save, orig_commit, orig_gc = Mgr.save, Mgr._commit, Mgr._gc

        def save(self, step, tree, force=False, async_=None, state=None):
            print(f"SNAPSHOT {step}", flush=True)
            return orig_save(self, step, tree, force=force,
                             async_=async_, state=state)

        def commit(self, step, tree, force, state):
            print(f"COMMIT {step}", flush=True)
            return orig_commit(self, step, tree, force, state)

        def gc(self):
            print("GC 0", flush=True)
            return orig_gc(self)

        Mgr.save, Mgr._commit, Mgr._gc = save, commit, gc
        Mgr._soak_markers = True

    pt.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 4))
    model = pt.Model(net)
    model.prepare(
        optimizer=pt.optimizer.AdamW(learning_rate=1e-2, parameters=net),
        loss=nn.CrossEntropyLoss(), metrics=pt.metric.Accuracy())
    rng = np.random.RandomState(3)
    n = TRAIN_EPOCH_STEPS * 4
    x = rng.randn(n, 8).astype(np.float32)
    y = rng.randint(0, 4, (n, 1))
    loss_path = os.path.join(run_dir, "losses.txt")

    class LossWriter(pt.callbacks.Callback):
        """One "global-step loss-hex" line per optimizer step. fit's
        in-epoch ``step`` is resume-aware (a mid-epoch resume starts at
        the restored cursor), so epoch*steps + step IS the global
        step."""

        def on_epoch_begin(self, epoch, logs=None):
            self._epoch = epoch

        def on_train_batch_end(self, step, logs=None):
            g = self._epoch * TRAIN_EPOCH_STEPS + step
            with open(loss_path, "a") as f:
                f.write(f"{g} {float(logs['loss']).hex()}\n")
            print(f"STEP {g}", flush=True)

    guard = elastic.PreemptionGuard()
    model.fit(TensorDataset([x, y]), batch_size=4, epochs=2,
              shuffle=True, verbose=0, steps_per_loop=k,
              callbacks=[LossWriter()],
              checkpoint_dir=os.path.join(run_dir, "ckpt"),
              checkpoint_freq=freq, resume="auto", keep_checkpoints=3,
              preemption_guard=guard, preemption_flush_budget=20.0)
    print("DONE", flush=True)
    return 0


def _ckpt_worker(directory: str, n_steps: int) -> int:
    """Subprocess body for the SIGKILL scenario: announce, then save —
    the parent kills inside an announced window."""
    from paddle_tpu.io.checkpoint import CheckpointManager
    mgr = CheckpointManager(directory, async_save=False, max_to_keep=4)
    for step in range(n_steps):
        print(f"SAVING {step}", flush=True)
        mgr.save(step, {"w": np.arange(2048, dtype=np.int64) + step,
                        "step": np.asarray(step)})
        print(f"SAVED {step}", flush=True)
    mgr.close()
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--ci", action="store_true",
                    help="fixed seeds, one pass per scenario "
                         "(~30s compute budget; ~50s with --fleet)")
    ap.add_argument("--fleet", action="store_true",
                    help="run ONLY the fleet scenario (router + K=3 "
                         "replica subprocesses, SIGKILL mid-decode)")
    ap.add_argument("--train", action="store_true",
                    help="run ONLY the train scenario (kill-anywhere "
                         "fit workers, bit-identical resume)")
    ap.add_argument("--slab", action="store_true",
                    help="run ONLY the fused-decode-slab scenario "
                         "(decode_ticks_per_dispatch=8 under an "
                         "engine.slab kill/cancel/deadline storm)")
    ap.add_argument("--autoscale", action="store_true",
                    help="run ONLY the autoscaler scenario (burn-"
                         "tripped scale-out with a seeded spawn "
                         "fault, SIGKILL → replacement, fault-forced "
                         "straggler drain → token-identical failover)")
    ap.add_argument("--overload", action="store_true",
                    help="run ONLY the brownout scenario (3× burst "
                         "storm, typed resolution, gold zero loss, "
                         "dwell-bounded ladder walk, seeded "
                         "overload.estimate/step faults)")
    ap.add_argument("--seed", type=int, default=1234)
    ap.add_argument("--workdir", default=None)
    ap.add_argument("--ckpt-worker", nargs=2, metavar=("DIR", "STEPS"),
                    help=argparse.SUPPRESS)
    ap.add_argument("--train-worker", nargs=3,
                    metavar=("DIR", "K", "FREQ"),
                    help=argparse.SUPPRESS)
    ap.add_argument("--train-baseline", nargs=3,
                    metavar=("DIR_K1", "DIR_K4", "FREQ"),
                    help=argparse.SUPPRESS)
    args = ap.parse_args(argv)
    if args.ckpt_worker:
        return _ckpt_worker(args.ckpt_worker[0],
                            int(args.ckpt_worker[1]))
    if args.train_worker:
        return _train_worker(args.train_worker[0],
                             int(args.train_worker[1]),
                             int(args.train_worker[2]))
    if args.train_baseline:
        # both uninterrupted baselines in one process: pays the jax
        # import once; each _train_worker call re-seeds and rebuilds
        # its model from scratch
        freq = int(args.train_baseline[2])
        _train_worker(args.train_baseline[0], 1, freq)
        return _train_worker(args.train_baseline[1], 4, freq)
    seed = 1234 if args.ci else args.seed
    workdir = args.workdir or os.path.join(
        "/tmp", f"pt_chaos_{os.getpid()}")
    os.makedirs(workdir, exist_ok=True)

    t0 = time.monotonic()
    out = {"seed": seed}
    try:
        if args.fleet:
            out["fleet"] = fleet_soak(seed, workdir)
            # ISSUE 18: the disaggregated prefill/decode fleet under
            # migration chaos (corrupt page in flight, prefill killed
            # mid-pull, seeded router.migrate fault) — every mode
            # falls back to token-identical local recompute
            out["disagg"] = disagg_soak(seed, workdir)
            # ISSUE 19: the stream-integrity auditor under a drift
            # storm — seeded audit.flip caught at the exact divergent
            # position (shadow + device-retry prefix), one-shot
            # flight dump, clean storms record zero divergences
            out["drift"] = drift_soak(seed, workdir)
        elif args.autoscale:
            out["autoscale"] = autoscale_soak(seed, workdir)
        elif args.overload:
            out["overload"] = overload_soak(seed, workdir)
        elif args.train:
            out["train"] = train_soak(seed, workdir)
        elif args.slab:
            out["slab"] = slab_soak(seed)
            # ISSUE 15: the same kill/cancel/deadline storm through
            # the ragged MIXED tick on an int8-quantized pool —
            # nonce-pinned identity vs an int8+mixed reference
            out["slab_mixed_int8"] = slab_soak(seed, mixed=True,
                                               kv_dtype="int8")
            out["page_pressure"] = page_pressure_soak(seed)
            # ISSUE 15: same storm, same pool HBM, int8 pages —
            # >=1.8x usable pages, scale_table row, headroom re-pin
            out["page_pressure_int8"] = page_pressure_soak(
                seed, kv_dtype="int8")
            # ISSUE 17: the storm again with on-device speculative
            # rounds (spec_slab + int8 draft pool + cache + N=8) —
            # nonce-pinned identity incl. temperature>0 rejection
            # sampling, rejected-draft pages leak-free
            out["slab_spec"] = spec_slab_soak(seed)
        else:
            out["engine"] = engine_soak(seed)
            out["ckpt"] = ckpt_crash(seed, workdir)
            out["flight"] = flight_escalation(seed, workdir)
            out["goodput"] = goodput_soak(seed, workdir)
    except AssertionError:
        # make a red CI run reproducible in one copy-paste: the seed
        # IS the fault schedule (docs/RELIABILITY.md determinism)
        replay = (f"python tools/chaos_soak.py --seed {seed}"
                  + (" --fleet" if args.fleet else "")
                  + (" --autoscale" if args.autoscale else "")
                  + (" --overload" if args.overload else "")
                  + (" --train" if args.train else "")
                  + (" --slab" if args.slab else ""))
        print(f"CHAOS SOAK FAILED under fault seed {seed}\n"
              f"replay: {replay}", file=sys.stderr, flush=True)
        raise
    out["wall_s"] = round(time.monotonic() - t0, 1)
    print("chaos soak OK: " + json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
