"""Shared fresh-subprocess runner for the measurement tools.

tpu_sweep.py and feasibility_1p3b.py both isolate each measurement in
a fresh interpreter (device-buffer hygiene / per-process device
counts). One copy of the harness: run the tool script with a flag +
JSON spec, parse the last stdout line as the result, degrade failures
(including hangs) to an {"error": ...} record instead of killing the
whole sweep.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
from typing import Callable, Optional


def run_spec(tool_path: str, flag: str, spec: dict, timeout: int,
             retries: int = 1,
             retry_if: Optional[Callable[[str], bool]] = None) -> dict:
    """Run ``python tool_path <flag> <json-spec>`` in a fresh process.

    Returns the last stdout line parsed as JSON on success, else an
    ``{"error": ...}`` record (spec included). ``retry_if(err)`` gates
    re-running on transient failures; the final attempt never sleeps.
    """
    import time
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(tool_path)))
    last = None
    for attempt in range(retries + 1):
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(tool_path), flag,
                 json.dumps(spec)],
                capture_output=True, text=True, timeout=timeout,
                cwd=repo_root)
        except subprocess.TimeoutExpired:
            last = {"spec": spec, "error": f"timeout {timeout}s"}
            break  # a hang is not transient; don't re-hang
        if proc.returncode == 0:
            try:
                return json.loads(proc.stdout.strip().splitlines()[-1])
            except (ValueError, IndexError):
                last = {"spec": spec,
                        "error": "no JSON on child stdout: "
                                 + proc.stdout.strip()[-300:]}
                break
        err = (proc.stderr.strip() or "nonzero exit")[-800:]
        last = {"spec": spec, "error": err}
        if retry_if is None or not retry_if(err) or attempt == retries:
            break
        time.sleep(10)
    return last
