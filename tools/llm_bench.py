"""Serving micro-benchmark: shared-prefix workload through LLMEngine.

The workload the prefix cache exists for: N requests sharing one long
system prompt (page-aligned) with unique user tails. Runs the engine
with the cache ON and OFF over the same prompts and reports, per mode:
TTFT p50/p99, prompt tokens recomputed vs reused, and burst
END-TO-END tokens/sec (submit -> last future, prefill included — the
cache-on gain is largely the skipped prefill; the steady-state decode
rate lives in the `llm_decode_tokens_per_second` histogram, which
excludes prefill fetches). Emits ONE BENCH-style JSON row whose
headline is the fraction of prompt-token recomputation eliminated.
Everything runs on the CPU backend (recompute savings and cache hit
rate are device-independent; tpu_sweep.py owns on-chip rounds).

FLEET MODE (``--fleet``): the same shared-prefix observation at K=3
engine replicas behind the serving router. Routing policy is the
variable: PREFIX AFFINITY (rendezvous-hash the prompt's first KV-page
digests to one replica per prefix family) vs ROUND-ROBIN (the naive
balancer, which dilutes every replica's cache by 1/K). Reports the
aggregate fleet prefix-cache hit rate per policy; the CI gate asserts
affinity ≥ 1.5× round-robin (ISSUE 6 acceptance).

DECODE-TICKS MODE (``--decode-ticks``, and part of ``--ci``): the
device-resident decode loop sweep (ISSUE 10). N ∈ {1, 4, 8, 16}
decode ticks fused into one lax.scan dispatch; per N and per batch
size it records decode tokens/sec and HOST DISPATCHES PER 100 TOKENS
(the quantity the fusion divides by N). The CI gate asserts N=8
decode tokens/sec ≥ 1.2× N=1 at batch 1 and 4 on CPU, and that
streams are token-identical across every swept N (greedy and seeded).

STORM MODE (``--storm``, ISSUE 13): the autoscaling gate's workload —
a synthetic DIURNAL + BURST load in the millions-of-users shape
(heavy shared prefixes, mixed tenants mapped to gold/bronze SLO
classes) replayed twice over identical pre-warmed engines: once
against a STATIC K=3 fleet, once against a min=1/max=3 fleet run by
the serving :class:`Autoscaler` (burn-trip scale-out, drain →
verify-empty → kill scale-in). Appends ONE ``bench_ledger/v1`` row
carrying both runs' REPLICA-SECONDS and gold-class deadline-hit
ratios, so static-vs-autoscaled stays comparable across the
trajectory. The ``--ci`` gate asserts the ISSUE-13 acceptance: ≥1
scale-out and ≥1 scale-in, zero lost requests (every outcome is ok or
a typed deadline miss — scale-ins drain to verified-empty), the
gold-class deadline-hit ratio no worse than static K, and STRICTLY
fewer replica-seconds.

Run:    python tools/llm_bench.py [--out BENCH_LLM.jsonl]
        python tools/llm_bench.py --fleet [--out BENCH_LLM.jsonl]
        python tools/llm_bench.py --decode-ticks [--out ...]
        python tools/llm_bench.py --storm [--out ...]
CI:     python tools/llm_bench.py --ci
        (tools/ci.sh gate: tiny model, 4 shared-prefix prompts;
        asserts nonzero cache hits, token-identical outputs with the
        cache on vs off, a clean shutdown — then the decode-ticks
        sweep gate above)
        python tools/llm_bench.py --ci --fleet
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

try:  # run as `python tools/llm_bench.py` OR imported as tools.llm_bench
    from tools import bench_ledger as _ledger  # noqa: E402
except ImportError:  # script dir (tools/) leads sys.path
    import bench_ledger as _ledger  # noqa: E402


def _peak_mem_bytes():
    """The memory ledger's attributed high-watermark for this run —
    the optional ``peak_mem_bytes`` every ledger row carries (None
    when the ledger is disabled or never saw an owner)."""
    try:
        from paddle_tpu.observability import memory as _memobs
        if _memobs.enabled():
            # watermarks advance at read boundaries; a ledger row IS
            # a read boundary (the perf-gauge discipline)
            _memobs.instance().update_gauges()
        peak = _memobs.instance().watermark_bytes()
        return peak or None
    except Exception:  # noqa: BLE001 — a row beats no row
        return None


def _verdict_row_fields():
    """The observability ledgers' verdicts on this run — the optional
    ``goodput_fraction`` + ``badput_top`` (time ledger) and
    ``drift_divergences`` (stream auditor) every ledger row carries
    ({} per ledger when disabled or never armed, the
    ``_peak_mem_bytes`` discipline). Canonical implementations live
    with the schema (tools/bench_ledger.py)."""
    return {**_ledger.goodput_row_fields(),
            **_ledger.drift_row_fields()}


def _goodput_productive_s():
    """Cumulative productive seconds on the process-wide time ledger
    (None when disabled; 0.0 before arming). ``run_storm`` differences
    this across a replay to goodput-weight that run's
    replica-seconds — provisioned capacity discounted by the fraction
    of wall clock the devices actually computed."""
    try:
        from paddle_tpu.observability import goodput as _goodput
        if not _goodput.enabled():
            return None
        return _goodput.instance().totals()["productive"]
    except Exception:  # noqa: BLE001
        return None


def build_net(vocab=211, layers=2, hidden=128, heads=4, max_pos=512):
    import paddle_tpu as pt
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt_config

    pt.seed(0)
    cfg = gpt_config("gpt2-small", num_layers=layers,
                     hidden_size=hidden, num_heads=heads,
                     vocab_size=vocab, max_position_embeddings=max_pos,
                     hidden_dropout=0.0, attention_dropout=0.0)
    return GPTForCausalLM(cfg)


def make_prompts(n_requests, prefix_len, tail_len, vocab, seed=0):
    rng = np.random.RandomState(seed)
    prefix = rng.randint(0, vocab, prefix_len).tolist()
    return [prefix + rng.randint(0, vocab, tail_len).tolist()
            for _ in range(n_requests)]


def phase_rollup():
    """Per-phase span rollup for the BENCH row: where each request's
    wall time went (queue vs prefill vs first-token drain vs decode),
    as totals + shares of the summed phase time. Excluding the
    ``llm.request`` root keeps the shares over the phases that tile it
    (they sum to 1). This is what lets the perf trajectory say WHERE a
    TTFT regression lives, not just that totals moved."""
    from paddle_tpu.observability import tracing
    return tracing.rollup(prefix="llm.", exclude=("llm.request",))


def run_mode(net, prompts, gen_len, prefix_cache, page_size=16,
             prefill_chunk=64, max_seqs=4, mixed_tick=False,
             kv_dtype=None, decode_ticks=1):
    """One engine pass over the workload. The FIRST request runs alone
    (it populates the cache — and doubles as compile warmup), the rest
    arrive as a concurrent burst, which is where prefix reuse pays.
    Tracing is ON for the pass (span bookkeeping is host-side dict
    ops, noise against a model forward) so the row carries the
    per-phase breakdown. ``mixed_tick``/``kv_dtype``/``decode_ticks``
    pass the ISSUE-15 knobs through (ragged mixed slab, int8 pool)."""
    from paddle_tpu.inference.llm import LLMEngine
    from paddle_tpu.observability import tracing

    tracing.clear()
    tracing.enable()

    total = max(len(p) for p in prompts) + gen_len
    pages = -(-total // page_size) * max_seqs + 8
    eng = LLMEngine(net, max_seqs=max_seqs, page_size=page_size,
                    num_pages=pages, max_len=total,
                    prefill_buckets=(max(len(p) for p in prompts),),
                    prefill_chunk=prefill_chunk,
                    prefix_cache=prefix_cache, mixed_tick=mixed_tick,
                    kv_dtype=kv_dtype,
                    decode_ticks_per_dispatch=decode_ticks)
    with eng:
        outs = [eng.submit(prompts[0],
                           max_new_tokens=gen_len).result(timeout=600)]
        t0 = time.perf_counter()
        futs = [eng.submit(p, max_new_tokens=gen_len)
                for p in prompts[1:]]
        outs += [f.result(timeout=600) for f in futs]
        wall = time.perf_counter() - t0
        reused = eng.n_cached_tokens
        prompt_toks = eng.n_prompt_tokens
        ticks = (eng.n_prefill_ticks, eng.n_decode_ticks,
                 eng.n_mixed_slabs)
        dispatches = eng.n_host_dispatches
    rollup = phase_rollup()
    tracing.disable()
    gen_tokens = sum(len(o["output_ids"]) for o in outs[1:])
    ttfts = sorted(o["ttft_s"] for o in outs[1:])

    def pct(q):
        return ttfts[min(len(ttfts) - 1, int(q * len(ttfts)))]

    return outs, {
        "prefix_cache": prefix_cache,
        "ttft_p50_s": round(pct(0.50), 4),
        "ttft_p99_s": round(pct(0.99), 4),
        "prompt_tokens": prompt_toks,
        "tokens_reused": reused,
        "tokens_recomputed": prompt_toks - reused,
        "e2e_tokens_per_sec": round(gen_tokens / wall, 1),
        "prefill_ticks": ticks[0],
        "decode_ticks": ticks[1],
        "mixed_slabs": ticks[2],
        "host_dispatches": dispatches,
        "span_rollup": rollup,
    }


def make_group_prompts(groups, per_group, prefix_len, tail_len, vocab,
                       seed=0):
    """``groups`` prefix families × ``per_group`` requests each: one
    warm request per family first, then the rest SHUFFLED (seeded) —
    interleaved arrival is the realistic case, and it also keeps a
    round-robin balancer from accidentally achieving affinity when
    the family cycle length divides the replica count."""
    rng = np.random.RandomState(seed)
    prefixes = [rng.randint(0, vocab, prefix_len).tolist()
                for _ in range(groups)]
    warm = [p + rng.randint(0, vocab, tail_len).tolist()
            for p in prefixes]
    burst = [p + rng.randint(0, vocab, tail_len).tolist()
             for _ in range(per_group - 1) for p in prefixes]
    rng.shuffle(burst)
    return warm + burst


def run_fleet_mode(net_fn, prompts, gen_len, policy, n_replicas=3,
                   page_size=16, warm_first=None):
    """One router pass over the workload at K replicas. The first
    ``warm_first`` requests (one per prefix family) run to completion
    before the burst — each family's pages are registered wherever its
    warm request landed, which is exactly the state the two policies
    then exploit differently.

    ``net_fn`` builds one net PER replica (identically seeded →
    identical weights): engines run concurrent traces, and
    ``functional_call`` temporarily rebinds layer state, so replicas
    must not share one Layer tree."""
    from paddle_tpu.inference.llm import LLMEngine
    from paddle_tpu.serving import LocalReplica, Router

    total = max(len(p) for p in prompts) + gen_len
    engines = [
        LLMEngine(net_fn(), max_seqs=4, page_size=page_size,
                  num_pages=-(-total // page_size) * 4 + 24,
                  max_len=total,
                  prefill_buckets=(max(len(p) for p in prompts),),
                  prefill_chunk=64, prefix_cache=True)
        for _ in range(n_replicas)]
    router = Router({f"r{i}": LocalReplica(e)
                     for i, e in enumerate(engines)},
                    page_size=page_size, affinity_pages=2,
                    policy=policy, health_poll_interval=0.1)
    t0 = time.perf_counter()
    try:
        warm_first = warm_first or 0
        warm, burst = prompts[:warm_first], prompts[warm_first:]
        outs = [f.result(timeout=600) for f in
                [router.submit(p, max_new_tokens=gen_len)
                 for p in warm]]
        futs = [router.submit(p, max_new_tokens=gen_len)
                for p in burst]
        outs += [f.result(timeout=600) for f in futs]
        wall = time.perf_counter() - t0
        reused = sum(e.n_cached_tokens for e in engines)
        prompt_toks = sum(e.n_prompt_tokens for e in engines)
        per_replica = {f"r{i}": {
            "prompt_tokens": e.n_prompt_tokens,
            "cache_hit_tokens": e.n_cached_tokens,
        } for i, e in enumerate(engines)}
    finally:
        router.close()
        for e in engines:
            e.close()
    return outs, {
        "policy": policy,
        "replicas": n_replicas,
        "hit_rate": round(reused / max(1, prompt_toks), 4),
        "tokens_reused": reused,
        "prompt_tokens": prompt_toks,
        "e2e_wall_s": round(wall, 2),
        "per_replica": per_replica,
    }


def fleet_main(args):
    if args.ci:
        def net_fn():
            return build_net(vocab=97, hidden=64, max_pos=256)
        groups, per_group = 4, 4
        prompts = make_group_prompts(groups, per_group, prefix_len=32,
                                     tail_len=16, vocab=97)
        gen_len = 8
    else:
        net_fn = build_net
        groups, per_group = 4, 8
        prompts = make_group_prompts(groups, per_group,
                                     prefix_len=args.prefix_len,
                                     tail_len=args.tail_len, vocab=211)
        gen_len = args.gen_len

    aff_outs, aff = run_fleet_mode(net_fn, prompts, gen_len,
                                   "affinity", warm_first=groups)
    rr_outs, rr = run_fleet_mode(net_fn, prompts, gen_len,
                                 "round_robin", warm_first=groups)
    ratio = aff["hit_rate"] / max(1e-9, rr["hit_rate"])
    row = {
        "metric": "llm_fleet_affinity_hit_ratio",
        "value": round(ratio, 2),
        "unit": "affinity_hit_rate_over_round_robin",
        "device": "cpu",
        "workload": {"groups": groups, "per_group": per_group,
                     "prompt_len": len(prompts[0]),
                     "gen_len": gen_len, "replicas": 3},
        "affinity": aff,
        "round_robin": rr,
    }
    print(json.dumps(row))
    if args.out:
        with open(args.out, "a") as f:
            f.write(json.dumps(row) + "\n")
    # canonical trajectory row (PERF.md "The perf ledger")
    _ledger.append("llm_bench", row["metric"], row["value"],
                   row["unit"], peak_mem_bytes=_peak_mem_bytes(),
 **_verdict_row_fields(),
                   extra={"affinity_hit_rate": aff["hit_rate"],
                          "round_robin_hit_rate": rr["hit_rate"],
                          "workload": row["workload"]})
    if args.ci:
        assert [o["output_ids"] for o in aff_outs] == \
            [o["output_ids"] for o in rr_outs], \
            "generations differ across routing policies"
        assert ratio >= 1.5, (
            f"prefix-affinity routing must beat round-robin by >=1.5x "
            f"on aggregate fleet cache hit rate; got "
            f"{aff['hit_rate']} vs {rr['hit_rate']} ({ratio:.2f}x)")
        print("LLM FLEET SMOKE OK")
    return 0


# ---------------------------------------------------------------------------
# disagg mode: prefill/decode pools + int8 KV-page migration (ISSUE 18)
# ---------------------------------------------------------------------------


def make_disagg_storm(n_long=6, n_short=12, long_len=160,
                      short_len=12, long_gen=8, short_gen=16,
                      vocab=97, seed=0):
    """Mixed storm for the disaggregation TTFT gate: a front of LONG
    unique uncached prompts (heavy prefill slabs, no prefix-cache
    bailout) with a tail of SHORT decode-class requests queued right
    behind them — the TTFT victims. The unified fleet must chew each
    slab before the shorts' first tokens; the disagg fleet detours
    the longs through the prefill pool, so its decode replicas reach
    the shorts immediately. Returns ``[(kind, prompt_ids, gen_len),
    ...]``, longs first (both fleets see the identical sequence)."""
    rng = np.random.RandomState(seed)
    reqs = [("long", rng.randint(0, vocab, long_len).tolist(),
             long_gen) for _ in range(n_long)]
    reqs += [("short", rng.randint(0, vocab, short_len).tolist(),
              short_gen) for _ in range(n_short)]
    return reqs


def run_disagg_mode(net_fn, storm, disagg, page_size=16,
                    threshold=48, vocab=97):
    """One K=3 fleet pass over the mixed storm on int8 KV pools.
    ``disagg=False``: three unified replicas. ``disagg=True``: one
    prefill replica + two decode replicas, long uncached prompts
    migrated as digest-verified page runs. Greedy everywhere, so the
    two fleets must emit token-identical generations. Every engine is
    warmed through the same long+short shapes before the clock starts
    (XLA compile must not masquerade as queueing). Equal capacity
    means equal AGGREGATE admission slots (12): the unified fleet
    spreads them 4/4/4, the disagg fleet allocates them the way a
    disaggregated deployment exists to allocate them — a thin
    prefill replica (2: it holds requests only for the one-token
    fill) and fat decode replicas (5/5: every decode in the storm
    lands there). Returns ``(outs-in-storm-order, stats)`` with the
    shorts' raw TTFTs."""
    from paddle_tpu.inference.llm import LLMEngine
    from paddle_tpu.serving import LocalReplica, Router

    long_len = max(len(p) for _, p, _ in storm)
    total = long_len + max(g for _, _, g in storm)
    slots = (2, 5, 5) if disagg else (4, 4, 4)
    engines = [
        LLMEngine(net_fn(), max_seqs=ms, page_size=page_size,
                  num_pages=-(-total // page_size) * 6 + 32,
                  max_len=total, prefill_buckets=(long_len,),
                  prefill_chunk=32, prefix_cache=True,
                  kv_dtype="int8")
        for ms in slots]
    # warmup: the prefill bucket + the decode slab at a few batch
    # widths, identical shapes on every engine in both fleets
    warm_long = [(7 * i + 3) % vocab for i in range(long_len)]
    warm_short = [(5 * i + 1) % vocab for i in range(12)]
    for eng in engines:
        futs = [eng.submit(warm_long, max_new_tokens=4)]
        futs += [eng.submit(warm_short, max_new_tokens=4)
                 for _ in range(2)]
        for f in futs:
            f.result(timeout=600)

    roles = ("prefill", "decode", "decode") if disagg else (None,) * 3
    router = Router(page_size=page_size, affinity_pages=2,
                    policy="affinity", health_poll_interval=0.1,
                    disagg_threshold_tokens=(threshold if disagg
                                             else None))
    for i, (eng, role) in enumerate(zip(engines, roles)):
        router.attach(f"r{i}", LocalReplica(eng), role=role)
    t0 = time.perf_counter()
    try:
        futs = [router.submit(p, max_new_tokens=g)
                for _, p, g in storm]
        outs = [f.result(timeout=600) for f in futs]
        wall = time.perf_counter() - t0
        short_ttfts = [o["ttft_s"] for (kind, _, _), o
                       in zip(storm, outs) if kind == "short"]
        migrations = {"completed": router.n_migrations,
                      "failed": router.n_migrate_failed,
                      "pages": router.n_pages_migrated,
                      "pages_rejected": router.n_pages_rejected}
    finally:
        router.close()
        for e in engines:
            e.close()
    stats = {
        "fleet": "1_prefill_2_decode" if disagg else "unified_k3",
        "e2e_wall_s": round(wall, 2),
        "migrations": migrations,
        "_short_ttfts": short_ttfts,
    }
    return outs, stats


def run_decode_probe(net_fn, disagg, n_victims=4, n_long=6,
                     long_len=160, victim_gen=56, page_size=16,
                     vocab=97):
    """The decode-tick jitter probe: ONE replica under an identical
    decode load, paying for the long prompts the way its pool role
    dictates. ``disagg=False`` is the unified-replica experience —
    the longs prefill LOCALLY, their chunk slabs interleaved into the
    victims' decode ticks. ``disagg=True`` is the decode-pool-replica
    experience — the same longs arrive as pre-staged int8 KV-page
    payloads (a prefill replica filled and exported them before the
    clock started) and only the digest-verified import rides the
    engine loop. Same engine config, same victims, same page bytes —
    the ONLY difference between the passes is prefill compute vs page
    install, which is precisely the disaggregation claim, and it
    holds on a single shared core where fleet-level wall-clock
    attribution cannot (total compute is conserved there, so a
    separate prefill replica's slabs still stall the decode pool's
    host). Victim inter-token gaps come from ``llm.decode`` span
    fetch timestamps: a raw gap between token n and n+1 hides
    nothing, unlike per-request means or the engine's step histogram
    (which excludes prefill-fetch intervals by design). Returns
    ``(victim_outs, gaps)``."""
    from paddle_tpu.inference.llm import LLMEngine
    from paddle_tpu.inference.prefix_cache import page_digests
    from paddle_tpu.observability import tracing as _tracing

    _tracing.enable()      # the gaps come from llm.decode spans
    rng = np.random.RandomState(1)
    victims = [rng.randint(0, vocab, 12).tolist()
               for _ in range(n_victims)]
    longs = [rng.randint(0, vocab, long_len).tolist()
             for _ in range(n_long)]

    def mk():
        return LLMEngine(net_fn(), max_seqs=n_victims + 2,
                         page_size=page_size,
                         num_pages=-(-long_len // page_size)
                         * (n_long + 2) + 48,
                         max_len=long_len + victim_gen,
                         prefill_buckets=(long_len,),
                         prefill_chunk=32, prefix_cache=True,
                         kv_dtype="int8")

    def staged_export(src, prompt):
        src.submit(prompt, max_new_tokens=1).result(timeout=600)
        digs = page_digests(prompt, page_size)
        digs = digs[:(len(prompt) - 1) // page_size]
        return src.export_pages([d.hex() for d in digs])

    warm_imp = [(11 * i + 5) % vocab for i in range(long_len)]
    payloads = []
    warm_payload = None
    if disagg:
        # the prefill pool's work, done OFF the probe's clock: fill
        # each long prompt's pages and export the digest-chained runs
        pre = mk()
        try:
            for p in longs:
                payloads.append(staged_export(pre, p))
            warm_payload = staged_export(pre, warm_imp)
        finally:
            pre.close()

    eng = mk()
    try:
        # warmup: compile the decode slab and the prefill bucket,
        # and (disagg) pay the import path's one-time lazy-init cost
        # on a throwaway payload — both passes must enter the window
        # with their long-arrival path already hot
        warm_long = [(7 * i + 3) % vocab for i in range(long_len)]
        warm_short = [(5 * i + 1) % vocab for i in range(12)]
        for f in [eng.submit(warm_long, max_new_tokens=4),
                  eng.submit(warm_short, max_new_tokens=4)]:
            f.result(timeout=600)
        if disagg:
            eng.import_pages(warm_payload)

        t0 = time.perf_counter()
        vic_futs = [eng.submit(p, max_new_tokens=victim_gen)
                    for p in victims]
        time.sleep(0.08)          # victims reach their decode loop
        if disagg:
            for pl in payloads:
                eng.import_pages(pl)
                time.sleep(0.02)
        else:
            long_futs = [eng.submit(p, max_new_tokens=1)
                         for p in longs]
        vic_outs = [f.result(timeout=600) for f in vic_futs]
        if not disagg:
            for f in long_futs:
                f.result(timeout=600)
    finally:
        eng.close()

    gaps = []
    for sp in _tracing.finished_spans():
        if sp["name"] != "llm.decode" or sp["ts"] < t0:
            continue
        fetches = [e for e in sp["events"] if e["name"] == "fetch"]
        if not fetches or fetches[-1].get("attrs", {}).get(
                "n_tokens") != victim_gen:
            continue
        ts = [sp["ts"]] + [e["ts"] for e in fetches]
        gaps += [b - a for a, b in zip(ts, ts[1:])]
    return vic_outs, gaps


def _pooled(samples, lo=50, hi=99):
    p50 = float(np.percentile(samples, lo))
    p99 = float(np.percentile(samples, hi))
    return p50, p99


def _fleet_stats(runs):
    """Pool the raw per-request samples across repeats (fresh engines
    each repeat) before taking percentiles — N repeats populate the
    tail instead of letting one lucky run erase it."""
    ttfts = [t for _, r in runs for t in r["_short_ttfts"]]
    p50, p99 = _pooled(ttfts)
    out = {k: v for k, v in runs[0][1].items()
           if not k.startswith("_")}
    out.update({
        "repeats": len(runs),
        "short_ttft_p50_s": round(p50, 4),
        "short_ttft_p99_s": round(p99, 4),
    })
    return out


def disagg_main(args, repeats=2):
    if args.ci:
        def net_fn():
            return build_net(vocab=97, hidden=64, max_pos=256)
        vocab = 97
        storm = make_disagg_storm(vocab=vocab)
    else:
        net_fn = build_net
        vocab = 211
        storm = make_disagg_storm(n_long=6, n_short=24, vocab=vocab)
    n_long = sum(1 for kind, _, _ in storm if kind == "long")

    uni_runs = [run_disagg_mode(net_fn, storm, disagg=False,
                                vocab=vocab) for _ in range(repeats)]
    dis_runs = [run_disagg_mode(net_fn, storm, disagg=True,
                                vocab=vocab) for _ in range(repeats)]
    uni_outs, uni = uni_runs[0][0], _fleet_stats(uni_runs)
    dis_outs, dis = dis_runs[0][0], _fleet_stats(dis_runs)

    # the jitter gate runs on ONE replica under an identical decode
    # load — local long prefills (the unified replica's experience)
    # vs pre-staged page imports (the disagg decode replica's) — so
    # it measures the per-replica claim directly instead of fleet
    # wall-clock, which a single shared core cannot attribute. The
    # probe net is wider than the storm net on purpose: prefill
    # compute must dominate the host's scheduling-noise floor for
    # the tick-gap tail to measure contention and not the OS
    if args.ci:
        def probe_net():
            return build_net(vocab=vocab, hidden=256, max_pos=256)
    else:
        probe_net = net_fn
    probe_u = [run_decode_probe(probe_net, disagg=False, vocab=vocab)
               for _ in range(repeats + 1)]
    probe_d = [run_decode_probe(probe_net, disagg=True, vocab=vocab)
               for _ in range(repeats + 1)]
    gaps_u = [g for _, gs in probe_u for g in gs]
    gaps_d = [g for _, gs in probe_d for g in gs]
    u50, u99 = _pooled(gaps_u)
    d50, d99 = _pooled(gaps_d)
    uni["decode_tick_p50_s"] = round(u50, 5)
    uni["decode_tick_p99_s"] = round(u99, 5)
    uni["decode_tick_spread_s"] = round(u99 - u50, 5)
    dis["decode_tick_p50_s"] = round(d50, 5)
    dis["decode_tick_p99_s"] = round(d99, 5)
    dis["decode_tick_spread_s"] = round(d99 - d50, 5)

    speedup = uni["short_ttft_p99_s"] / max(1e-9,
                                            dis["short_ttft_p99_s"])
    # the gated jitter stat is the p99 inter-token gap itself — the
    # worst stall a victim's reader actually feels. The p99-p50
    # spread is reported but not gated: the unified pass lifts its
    # OWN median (prefill rows riding every mixed tick), which eats
    # its tail from below and turns the spread into a coin flip
    jitter_ratio = d99 / max(1e-9, u99)
    row = {
        "metric": "llm_disagg_ttft_p99_speedup",
        "value": round(speedup, 2),
        "unit": "unified_short_ttft_p99_over_disagg",
        "device": "cpu",
        "workload": {"n_long": n_long,
                     "n_short": len(storm) - n_long,
                     "replicas": 3, "kv_dtype": "int8"},
        "unified": uni,
        "disagg": dis,
        "decode_jitter_ratio": round(jitter_ratio, 3),
    }
    print(json.dumps(row))
    if args.out:
        with open(args.out, "a") as f:
            f.write(json.dumps(row) + "\n")
    _ledger.append("llm_bench", row["metric"], row["value"],
                   row["unit"], peak_mem_bytes=_peak_mem_bytes(),
                   kv_dtype="int8", **_verdict_row_fields(),
                   extra={"unified_short_ttft_p99_s":
                              uni["short_ttft_p99_s"],
                          "disagg_short_ttft_p99_s":
                              dis["short_ttft_p99_s"],
                          "pages_migrated":
                              dis["migrations"]["pages"],
                          "workload": row["workload"]})
    _ledger.append("llm_bench", "llm_disagg_decode_jitter_ratio",
                   round(jitter_ratio, 3),
                   "disagg_tick_p99_over_unified",
                   direction="lower", kv_dtype="int8",
                   peak_mem_bytes=_peak_mem_bytes(),
                   **_verdict_row_fields(),
                   extra={"unified_tick_p99_s":
                              uni["decode_tick_p99_s"],
                          "disagg_tick_p99_s":
                              dis["decode_tick_p99_s"],
                          "workload": row["workload"]})
    if args.ci:
        want = [o["output_ids"] for o in uni_outs]
        for outs, _ in uni_runs + dis_runs:
            assert [o["output_ids"] for o in outs] == want, \
                "disagg fleet generations diverged from the " \
                "unified fleet on a greedy storm — migrated pages " \
                "are not token-identical to local recompute"
        for _, r in dis_runs:
            assert r["migrations"]["completed"] == n_long and \
                r["migrations"]["failed"] == 0, (
                f"every long uncached prompt must migrate exactly "
                f"once: {r['migrations']} (wanted {n_long} "
                f"completed)")
        assert uni["migrations"]["completed"] == 0, \
            "unified fleet must not migrate (no prefill pool)"
        pwant = [o["output_ids"] for o in probe_u[0][0]]
        for outs, _ in probe_u + probe_d:
            assert [o["output_ids"] for o in outs] == pwant, \
                "probe victims must decode token-identically " \
                "whether the longs arrive as local prefills or as " \
                "imported int8 pages"
        assert speedup > 1.0, (
            f"disagg fleet must IMPROVE short-request TTFT p99 over "
            f"unified: {uni['short_ttft_p99_s']}s vs "
            f"{dis['short_ttft_p99_s']}s ({speedup:.2f}x)")
        assert jitter_ratio < 1.0, (
            f"a decode replica fed imported pages must tick with "
            f"a strictly lower p99 inter-token gap than one "
            f"prefilling the same longs locally: "
            f"{dis['decode_tick_p99_s']}s vs "
            f"{uni['decode_tick_p99_s']}s ({jitter_ratio:.3f}x)")
        print("LLM DISAGG SMOKE OK")
    return 0


# ---------------------------------------------------------------------------
# storm mode: the autoscaling gate (ISSUE 13)
# ---------------------------------------------------------------------------


def make_storm_schedule(vocab=97, seed=0):
    """The millions-of-users shape, compressed: alternating TROUGHS
    (light, deadline-generous traffic) and BURSTS (a stampede of
    tight-deadline bronze work plus steady gold), over a handful of
    shared prefix families with mixed tenants. Returns a list of
    ``(t_offset_s, submit_kwargs)`` sorted by offset; the bronze
    burst deadlines are chosen to be unmeetable behind a one-replica
    backlog — the burn signal the autoscaler scales out on — while
    gold deadlines have fleet-wide headroom (the SLO the gate holds
    constant)."""
    rng = np.random.RandomState(seed)
    families = [rng.randint(0, vocab, 32).tolist() for _ in range(3)]

    def req(fam, tenant, slo, gen, deadline):
        prompt = families[fam] + rng.randint(0, vocab, 8).tolist()
        return {"prompt_ids": prompt, "max_new_tokens": gen,
                "tenant": tenant, "slo": slo, "deadline": deadline}

    sched = []

    def trough(t0, dur, rate=1.6):
        n = max(2, int(dur * rate))
        for i in range(n):
            fam = int(rng.randint(0, len(families)))
            gold = i % 3 == 0
            sched.append((t0 + dur * i / n, req(
                fam, "acme" if gold else "hobby",
                "gold" if gold else "bronze", 8, 20.0)))
        return t0 + dur

    def burst(t0, dur=0.8, n_bronze=48, n_gold=8):
        # ~n_bronze·48 generated tokens land inside ``dur``: far more
        # work than one replica clears inside the 0.35s bronze
        # deadline, by construction on any host — the misses ARE the
        # burn signal
        for i in range(n_bronze):
            sched.append((t0 + dur * rng.random(), req(
                int(rng.randint(0, len(families))), "hobby",
                "bronze", 48, 0.35)))
        for i in range(n_gold):
            sched.append((t0 + dur * rng.random(), req(
                int(rng.randint(0, len(families))), "acme",
                "gold", 8, 25.0)))
        return t0 + dur

    t = trough(0.0, 2.5)
    t = burst(t)
    t = trough(t + 0.3, 4.5)         # the sag the scale-in needs
    t = burst(t)
    trough(t + 0.3, 4.0)
    sched.sort(key=lambda x: x[0])
    return sched


class _PooledEngineHandle:
    """In-process lifecycle handle for the storm bench: 'terminate'
    returns the (verified-empty) engine to the warm pool instead of
    closing it, so a later scale-out reuses it — the bench measures
    the CONTROLLER, not process boot. A straggler drain takes the
    ``kill`` path instead: the engine is ABANDONED (its in-flight
    requests still complete — zero loss — but it never re-enters the
    pool holding live work as a 'fresh' replica); storm_main closes
    every engine at the end either way."""

    def __init__(self, eng, pool):
        self.eng = eng
        self.pool = pool

    def alive(self):
        return not getattr(self.eng, "_closed", False)

    def terminate(self, grace_s=0.0):
        self.pool.append(self.eng)

    def kill(self):
        pass


def _storm_router(replicas, **kw):
    from paddle_tpu.serving import Router, SLOClass
    return Router(
        replicas,
        page_size=16, affinity_pages=2,
        health_poll_interval=0.05, max_workers=96,
        scrape_metrics=False,
        slo_classes={
            "gold": SLOClass("gold", deadline_s=25.0, target=0.99),
            "bronze": SLOClass("bronze", deadline_s=1.0,
                               target=0.99),
        },
        slo_windows=(1.5, 6.0), slo_min_samples=5,
        slo_breach_threshold=5.0, **kw)


def run_storm(engines, schedule, autoscale: bool):
    """Replay the schedule against a fleet built from ``engines``
    (all pre-warmed, identical weights). ``autoscale=False``: every
    engine serves for the whole run (static K). ``autoscale=True``:
    one seed replica plus an Autoscaler over the rest as a warm spawn
    pool. Returns the comparison row for this run."""
    from paddle_tpu.reliability.retry import DeadlineExceeded
    from paddle_tpu.serving import Autoscaler, LocalReplica

    k = len(engines)
    scaler = None
    if autoscale:
        router = _storm_router({"seed-0": LocalReplica(engines[0])})
        pool = list(engines[1:])

        def spawner(name):
            if not pool:
                raise RuntimeError("storm spawn pool exhausted")
            eng = pool.pop()
            return LocalReplica(eng), _PooledEngineHandle(eng, pool)

        scaler = Autoscaler(
            router, spawner, min_replicas=1, max_replicas=k,
            replica_slots=engines[0].max_seqs,
            low_water=0.2, dwell_s=2.0,
            backoff_base_s=0.5, backoff_cap_s=8.0,
            drain_deadline_s=10.0, name_prefix="storm",
            name="storm_scaler")
        scaler.start()
    else:
        router = _storm_router({f"r{i}": LocalReplica(e)
                                for i, e in enumerate(engines)})
    outcomes = {"ok": 0, "deadline": 0, "other": 0}
    gp0 = _goodput_productive_s()
    t0 = time.perf_counter()
    futs = []
    try:
        for t_off, kw in schedule:
            dt = t0 + t_off - time.perf_counter()
            if dt > 0:
                time.sleep(dt)
            futs.append((kw["slo"], router.submit(**kw)))
        for slo, f in futs:
            try:
                out = f.result(timeout=600)
                assert out["output_ids"] is not None
                outcomes["ok"] += 1
            except DeadlineExceeded:
                outcomes["deadline"] += 1
            except Exception:  # noqa: BLE001 — shed/unavailable/error:
                outcomes["other"] += 1   # all count as LOST for the gate
        wall = time.perf_counter() - t0
        if scaler is not None:
            scaler.tick()        # close the replica-seconds integral
            replica_seconds = scaler.replica_seconds()
            actions = {"scale_out": scaler.n_scale_out,
                       "scale_in": scaler.n_scale_in,
                       "replace": scaler.n_replaced}
        else:
            replica_seconds = k * wall
            actions = {}
        report = router.slo.report()["classes"]
        gold = report.get("gold", {})
        bronze = report.get("bronze", {})
    finally:
        if scaler is not None:
            scaler.close()
        router.close()
    gp1 = _goodput_productive_s()
    if gp0 is not None and gp1 is not None and wall > 0:
        # run-window goodput: productive ledger seconds this replay
        # earned per wall second; weighting replica-seconds by it
        # prices provisioned capacity in USEFUL seconds
        run_goodput = max(0.0, min(1.0, (gp1 - gp0) / wall))
        goodput_rs = replica_seconds * run_goodput
    else:
        run_goodput = None
        goodput_rs = None
    return {
        "mode": "autoscaled" if autoscale else f"static_k{k}",
        "wall_s": round(wall, 2),
        "replica_seconds": round(replica_seconds, 2),
        "goodput_fraction": (round(run_goodput, 4)
                             if run_goodput is not None else None),
        "goodput_replica_seconds": (round(goodput_rs, 2)
                                    if goodput_rs is not None else None),
        "gold_deadline_hit_ratio": gold.get("deadline_hit_ratio"),
        "bronze_deadline_hit_ratio": bronze.get("deadline_hit_ratio"),
        "outcomes": outcomes,
        "failovers": router.n_failovers,
        "actions": actions,
    }


def storm_main(args):
    """Static K=3 vs autoscaled min=1/max=3 over the same schedule and
    the same pre-warmed engines. One ledger row carries both."""
    import tempfile

    # persistent compile cache: engine 2..6 reuse engine 1's programs
    jax.config.update("jax_compilation_cache_dir",
                      tempfile.mkdtemp(prefix="pt_storm_xla_"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      0.0)
    from paddle_tpu.inference.llm import LLMEngine

    schedule = make_storm_schedule()
    max_len = 32 + 8 + 48

    def build_engine():
        net = build_net(vocab=97, hidden=64, max_pos=96)
        return LLMEngine(net, max_seqs=2, page_size=16,
                         num_pages=3 * (-(-max_len // 16)) + 16,
                         max_len=max_len, prefill_buckets=(40,),
                         prefill_chunk=64, prefix_cache=True,
                         max_pending=256, admit_timeout=120.0,
                         seed=0)

    def warmed_fleet():
        engines = [build_engine() for _ in range(3)]
        for e in engines:
            # compile + a first token off the clock, on a prompt no
            # storm family shares (the prefix cache starts cold)
            e.generate([[96, 95, 94]], max_new_tokens=2)
        return engines

    runs = {}
    for mode, autoscale in (("static", False), ("autoscaled", True)):
        engines = warmed_fleet()
        try:
            runs[mode] = run_storm(engines, schedule, autoscale)
        finally:
            for e in engines:
                e.close()
    rs_static = runs["static"]["replica_seconds"]
    rs_auto = runs["autoscaled"]["replica_seconds"]
    saved = 1.0 - rs_auto / max(1e-9, rs_static)
    row = {
        "metric": "llm_storm_autoscale_replica_seconds_saved",
        "value": round(saved, 4),
        "unit": "fraction_of_static_k3_replica_seconds",
        "device": "cpu",
        "workload": {"requests": len(schedule), "families": 3,
                     "phases": "trough/burst x2/trough"},
        "static": runs["static"],
        "autoscaled": runs["autoscaled"],
    }
    print(json.dumps(row))
    if args.out:
        with open(args.out, "a") as f:
            f.write(json.dumps(row) + "\n")
    _ledger.append(
        "llm_bench", row["metric"], row["value"], row["unit"],
        peak_mem_bytes=_peak_mem_bytes(),
        **_verdict_row_fields(),
        extra={"replica_seconds_static": rs_static,
               "replica_seconds_autoscaled": rs_auto,
               # replica-seconds discounted to USEFUL seconds: each
               # run's provisioned capacity weighted by the fraction
               # of its wall clock the time ledger scored productive
               "goodput_replica_seconds_static":
                   runs["static"]["goodput_replica_seconds"],
               "goodput_replica_seconds_autoscaled":
                   runs["autoscaled"]["goodput_replica_seconds"],
               "gold_hit_static":
                   runs["static"]["gold_deadline_hit_ratio"],
               "gold_hit_autoscaled":
                   runs["autoscaled"]["gold_deadline_hit_ratio"],
               "actions": runs["autoscaled"]["actions"],
               "workload": row["workload"]})
    if args.ci:
        auto = runs["autoscaled"]
        static = runs["static"]
        acts = auto["actions"]
        assert acts.get("scale_out", 0) >= 1, (
            f"storm never triggered a scale-out: {auto}")
        assert acts.get("scale_in", 0) >= 1, (
            f"storm never triggered a scale-in: {auto}")
        for r in (static, auto):
            assert r["outcomes"]["other"] == 0, (
                f"requests lost in {r['mode']}: {r['outcomes']} — "
                f"every outcome must be ok or a typed deadline miss")
        g_static = static["gold_deadline_hit_ratio"]
        g_auto = auto["gold_deadline_hit_ratio"]
        assert g_static is not None and g_auto is not None, runs
        assert g_auto >= g_static, (
            f"autoscaled fleet dropped the gold SLO: hit ratio "
            f"{g_auto} vs static {g_static}")
        assert rs_auto < rs_static, (
            f"autoscaled fleet must spend STRICTLY fewer "
            f"replica-seconds than static K=3: {rs_auto} vs "
            f"{rs_static}")
        print("LLM STORM AUTOSCALE SMOKE OK")
    return 0


# ---------------------------------------------------------------------------
# overload mode: the brownout gate (ISSUE 20)
# ---------------------------------------------------------------------------


def make_overload_schedules(vocab=97, seed=0):
    """The brownout gate's two request tapes: an UN-OVERLOADED
    baseline (one generous trough — the gold hit ratio the gate holds
    the brownout run to) and the OVERLOAD tape — the storm bench's
    burst, tripled back-to-back over a static fleet that cannot scale
    out of it. Same families, tenants, and deadline structure as
    :func:`make_storm_schedule`."""
    rng = np.random.RandomState(seed)
    families = [rng.randint(0, vocab, 32).tolist() for _ in range(3)]

    def req(fam, tenant, slo, gen, deadline):
        prompt = families[fam] + rng.randint(0, vocab, 8).tolist()
        return {"prompt_ids": prompt, "max_new_tokens": gen,
                "tenant": tenant, "slo": slo, "deadline": deadline}

    def trough(sched, t0, dur, rate=1.6):
        n = max(2, int(dur * rate))
        for i in range(n):
            fam = int(rng.randint(0, len(families)))
            gold = i % 3 == 0
            sched.append((t0 + dur * i / n, req(
                fam, "acme" if gold else "hobby",
                "gold" if gold else "bronze", 8, 20.0)))
        return t0 + dur

    def burst(sched, t0, dur=0.8, n_bronze=48, n_gold=8):
        for _ in range(n_bronze):
            sched.append((t0 + dur * rng.random(), req(
                int(rng.randint(0, len(families))), "hobby",
                "bronze", 48, 0.35)))
        for _ in range(n_gold):
            sched.append((t0 + dur * rng.random(), req(
                int(rng.randint(0, len(families))), "acme",
                "gold", 8, 25.0)))
        return t0 + dur

    baseline = []
    trough(baseline, 0.0, 3.0)
    overload = []
    t = trough(overload, 0.0, 1.5)
    for _ in range(3):               # 3× the storm burst, no sag
        t = burst(overload, t)
    trough(overload, t + 0.2, 1.5)
    baseline.sort(key=lambda x: x[0])
    overload.sort(key=lambda x: x[0])
    return baseline, overload


def run_overload(engines, schedule, brownout: bool):
    """Replay ``schedule`` against a static fleet, optionally under an
    :class:`OverloadController`. Counts outcomes with shed as its own
    TYPED column (the storm bench's 'other = lost' rule would hide the
    controller's entire mechanism) and returns the comparison row:
    gold/bronze hit ratios plus the wasted-work fraction — deadline
    misses burned full service cost and delivered nothing; sheds cost
    one admission check."""
    from paddle_tpu.inference.llm import AdmissionShed
    from paddle_tpu.reliability.retry import DeadlineExceeded
    from paddle_tpu.serving import LocalReplica, OverloadController

    ctrl = OverloadController() if brownout else None
    router = _storm_router(
        {f"r{i}": LocalReplica(e) for i, e in enumerate(engines)},
        **({"overload": ctrl} if ctrl is not None else {}))
    outcomes = {"ok": 0, "deadline": 0, "shed": 0, "other": 0}
    t0 = time.perf_counter()
    futs = []
    try:
        for t_off, kw in schedule:
            dt = t0 + t_off - time.perf_counter()
            if dt > 0:
                time.sleep(dt)
            futs.append((kw["slo"], router.submit(**kw)))
        gold_lost = 0
        for slo, f in futs:
            try:
                out = f.result(timeout=600)
                assert out["output_ids"] is not None
                outcomes["ok"] += 1
            except DeadlineExceeded:
                outcomes["deadline"] += 1
                gold_lost += slo == "gold"
            except AdmissionShed:
                outcomes["shed"] += 1
                gold_lost += slo == "gold"
            except Exception:  # noqa: BLE001 — untyped = lost
                outcomes["other"] += 1
                gold_lost += slo == "gold"
        wall = time.perf_counter() - t0
        report = router.slo.report()["classes"]
        gold = report.get("gold", {})
        bronze = report.get("bronze", {})
    finally:
        router.close()
    served = outcomes["ok"] + outcomes["deadline"]
    trans = ctrl.ladder.transitions() if ctrl is not None else []
    return {
        "mode": "brownout" if brownout else "uncontrolled",
        "wall_s": round(wall, 2),
        "outcomes": outcomes,
        "gold_lost": gold_lost,
        # of the requests that consumed full service time, the
        # fraction whose tokens were thrown away at the deadline
        "wasted_work_fraction": (round(outcomes["deadline"] / served, 4)
                                 if served else 0.0),
        "gold_deadline_hit_ratio": gold.get("deadline_hit_ratio"),
        "bronze_deadline_hit_ratio": bronze.get("deadline_hit_ratio"),
        "shed_reasons": dict(ctrl.n_shed) if ctrl is not None else {},
        "max_brownout_level": max([t["to"] for t in trans] or [0]),
        "transitions": len(trans),
    }


def overload_main(args):
    """Un-overloaded baseline, then the 3× burst tape twice over the
    same static K=2 fleet — brownout OFF vs ON. The gate: the
    controller must hold gold at the baseline hit ratio AND strictly
    cut the wasted-work fraction (misses converted to cheap typed
    sheds)."""
    import tempfile

    jax.config.update("jax_compilation_cache_dir",
                      tempfile.mkdtemp(prefix="pt_overload_xla_"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      0.0)
    from paddle_tpu.inference.llm import LLMEngine

    base_sched, over_sched = make_overload_schedules()
    max_len = 32 + 8 + 48

    def build_engine():
        net = build_net(vocab=97, hidden=64, max_pos=96)
        return LLMEngine(net, max_seqs=2, page_size=16,
                         num_pages=3 * (-(-max_len // 16)) + 16,
                         max_len=max_len, prefill_buckets=(40,),
                         prefill_chunk=64, prefix_cache=True,
                         max_pending=256, admit_timeout=120.0,
                         seed=0)

    runs = {}
    for key, sched, brownout in (("baseline", base_sched, False),
                                 ("off", over_sched, False),
                                 ("on", over_sched, True)):
        engines = [build_engine() for _ in range(2)]
        for e in engines:
            e.generate([[96, 95, 94]], max_new_tokens=2)
        try:
            runs[key] = run_overload(engines, sched, brownout)
        finally:
            for e in engines:
                e.close()
    w_off = runs["off"]["wasted_work_fraction"]
    w_on = runs["on"]["wasted_work_fraction"]
    row = {
        "metric": "llm_overload_wasted_work_fraction",
        "value": w_on,
        "unit": "deadline_missed_fraction_of_served",
        "device": "cpu",
        "workload": {"requests": len(over_sched), "families": 3,
                     "replicas": 2, "phases": "trough/burst x3/trough"},
        "baseline": runs["baseline"],
        "uncontrolled": runs["off"],
        "brownout": runs["on"],
    }
    print(json.dumps(row))
    if args.out:
        with open(args.out, "a") as f:
            f.write(json.dumps(row) + "\n")
    _ledger.append(
        "llm_bench", row["metric"], row["value"], row["unit"],
        direction="lower", peak_mem_bytes=_peak_mem_bytes(),
        **_verdict_row_fields(),
        extra={"uncontrolled_wasted_work_fraction": w_off,
               "shed_reasons": runs["on"]["shed_reasons"],
               "max_brownout_level": runs["on"]["max_brownout_level"],
               "workload": row["workload"]})
    _ledger.append(
        "llm_bench", "llm_overload_gold_hit_ratio",
        runs["on"]["gold_deadline_hit_ratio"],
        "gold_deadline_hit_ratio_brownout_on",
        peak_mem_bytes=_peak_mem_bytes(),
        **_verdict_row_fields(),
        extra={"baseline_gold_hit_ratio":
                   runs["baseline"]["gold_deadline_hit_ratio"],
               "uncontrolled_gold_hit_ratio":
                   runs["off"]["gold_deadline_hit_ratio"],
               "workload": row["workload"]})
    if args.ci:
        base, off, on = runs["baseline"], runs["off"], runs["on"]
        for r in runs.values():
            assert r["outcomes"]["other"] == 0, (
                f"untyped losses in {r['mode']}: {r['outcomes']}")
        assert base["outcomes"]["shed"] == 0, (
            f"the un-overloaded baseline shed: {base['outcomes']}")
        g_base = base["gold_deadline_hit_ratio"]
        g_on = on["gold_deadline_hit_ratio"]
        assert g_base is not None and g_on is not None, runs
        assert on["gold_lost"] == 0, (
            f"brownout lost {on['gold_lost']} gold request(s) — the "
            f"protected class must ride through the storm untouched")
        assert g_on >= g_base, (
            f"brownout dropped the gold SLO below the un-overloaded "
            f"baseline: {g_on} vs {g_base}")
        assert sum(on["shed_reasons"].values()) >= 1 \
            and on["max_brownout_level"] >= 1, (
            f"the controller never engaged under a 3× burst: {on}")
        assert w_on < w_off, (
            f"brownout must strictly cut the wasted-work fraction: "
            f"{w_on} (on) vs {w_off} (off)")
        print("LLM OVERLOAD BROWNOUT SMOKE OK")
    return 0


def run_decode_ticks(net, prompts, gen_len, n_ticks, temperature=0.0,
                     page_size=16):
    """One engine pass at ``decode_ticks_per_dispatch=n_ticks``:
    submit the prompts as one concurrent burst and measure decode
    throughput end to end (prompts are tiny — a couple of prefill
    chunks — so the wall is decode ticks + dispatch overhead, the
    thing the fused slab attacks). Returns (outputs, stats); the
    dispatch counter is read from the engine itself
    (``llm_host_dispatches_total``)."""
    from paddle_tpu.inference.llm import LLMEngine

    total = max(len(p) for p in prompts) + gen_len
    pages = -(-total // page_size) * max(4, len(prompts)) + 8
    eng = LLMEngine(net, max_seqs=max(4, len(prompts)),
                    page_size=page_size, num_pages=pages,
                    max_len=total,
                    prefill_buckets=(max(len(p) for p in prompts),),
                    decode_ticks_per_dispatch=n_ticks)
    with eng:
        # warmup: compile prefill + the slab program off the clock
        eng.generate([prompts[0]], max_new_tokens=max(2, 2 * n_ticks),
                     temperature=temperature)
        d0, t0 = eng.n_host_dispatches, time.perf_counter()
        futs = [eng.submit(p, max_new_tokens=gen_len,
                           temperature=temperature) for p in prompts]
        outs = [f.result(timeout=600) for f in futs]
        wall = time.perf_counter() - t0
        dispatches = eng.n_host_dispatches - d0
    tokens = sum(len(o["output_ids"]) for o in outs)
    return outs, {
        "decode_ticks_per_dispatch": n_ticks,
        "batch": len(prompts),
        "tokens": tokens,
        "tokens_per_sec": round(tokens / wall, 1),
        "host_dispatches_per_100_tokens": round(
            100.0 * dispatches / max(1, tokens), 2),
    }


def decode_ticks_main(args, net=None, assert_ci=False):
    """The --decode-ticks sweep (and the --ci gate's second half):
    N ∈ {1, 4, 8, 16} × batch {1, 4}, token identity across N for
    greedy AND seeded sampling, and the perf gate N=8 ≥ 1.2× N=1."""
    ns = (1, 4, 8) if args.ci else (1, 4, 8, 16)
    if net is None:
        net = build_net(vocab=97, hidden=64, max_pos=256) if args.ci \
            else build_net()
    gen_len = 96 if args.ci else args.gen_len
    rng = np.random.RandomState(0)
    batches = {
        1: [rng.randint(0, 97, 8).tolist()],
        4: [rng.randint(0, 97, 8).tolist() for _ in range(4)],
    }
    sweep = {}
    ratios = {}
    for bsz, prompts in batches.items():
        rows = {}
        streams = {}
        for n in ns:
            outs, stats = run_decode_ticks(net, prompts, gen_len, n)
            # seeded sampling identity rides the same engines: a
            # short temperature>0 pass whose streams must also match
            souts, _ = run_decode_ticks(net, prompts, 16, n,
                                        temperature=0.8)
            streams[n] = ([o["output_ids"] for o in outs],
                          [o["output_ids"] for o in souts])
            rows[n] = stats
        for n in ns[1:]:
            assert streams[n] == streams[ns[0]], (
                f"decode streams diverged between N={ns[0]} and "
                f"N={n} at batch {bsz}")
        ratio = rows[8]["tokens_per_sec"] / max(
            1e-9, rows[1]["tokens_per_sec"])
        if assert_ci and ratio < 1.2:
            # one re-measure absorbs a noisy-neighbor CI wall clock;
            # token identity above is never re-tried
            _, retry = run_decode_ticks(net, prompts, gen_len, 8)
            rows[8] = max(rows[8], retry, key=lambda r:
                          r["tokens_per_sec"])
            ratio = rows[8]["tokens_per_sec"] / max(
                1e-9, rows[1]["tokens_per_sec"])
        ratios[bsz] = round(ratio, 2)
        sweep[f"batch_{bsz}"] = [rows[n] for n in ns]
    row = {
        "metric": "llm_decode_ticks_speedup",
        "value": min(ratios.values()),
        "unit": "n8_tokens_per_sec_over_n1",
        "device": "cpu",
        "workload": {"gen_len": gen_len, "prompt_len": 8,
                     "batches": sorted(batches)},
        "ratios": ratios,
        "sweep": sweep,
    }
    print(json.dumps(row))
    if args.out:
        with open(args.out, "a") as f:
            f.write(json.dumps(row) + "\n")
    n8_b1 = next(r for r in sweep["batch_1"]
                 if r["decode_ticks_per_dispatch"] == 8)
    _ledger.append("llm_bench", row["metric"], row["value"],
                   row["unit"],
                   tokens_per_sec=n8_b1["tokens_per_sec"],
                   dispatches=n8_b1["host_dispatches_per_100_tokens"],
                   peak_mem_bytes=_peak_mem_bytes(),
                   **_verdict_row_fields(),
                   extra={"ratios": ratios,
                          "workload": row["workload"]})
    if assert_ci:
        for bsz, ratio in ratios.items():
            assert ratio >= 1.2, (
                f"fused decode slab must deliver >=1.2x decode "
                f"tokens/sec at N=8 vs N=1 (batch {bsz}); got "
                f"{ratio:.2f}x — sweep: {sweep[f'batch_{bsz}']}")
        print("LLM DECODE-TICKS SMOKE OK")
    return 0


def mixed_tick_main(args, net=None, assert_ci=False):
    """The MIXED-TICK gate (ISSUE 15): the shared-prefix workload
    through the legacy alternating prefill-tick/decode-slab loop vs
    ONE ragged mixed slab — BOTH at ``decode_ticks_per_dispatch=8``,
    so the headline isolates what mixed-tick ADMISSION saves (the
    prefill dispatches and the slab boundaries around them), not the
    already-shipped PR-10 slab fusion. Token identity is the hard
    gate."""
    if net is None:
        net = build_net(vocab=97, hidden=64, max_pos=256) if args.ci \
            else build_net()
    prompts = make_prompts(4, prefix_len=32, tail_len=8, vocab=97) \
        if args.ci else make_prompts(args.n_requests, args.prefix_len,
                                     args.tail_len, vocab=211)
    gen_len = 16 if args.ci else args.gen_len
    # prefill_chunk=16: the burst's uncached suffixes span SEVERAL
    # chunks, so the legacy loop pays one dispatch per chunk (plus
    # the slab boundaries around them) while the mixed slab folds
    # them into its ticks — the quantity this gate isolates
    legacy_outs, legacy = run_mode(net, prompts, gen_len,
                                   prefix_cache=True, decode_ticks=8,
                                   prefill_chunk=16)
    mixed_outs, mixed = run_mode(net, prompts, gen_len,
                                 prefix_cache=True, mixed_tick=True,
                                 decode_ticks=8, prefill_chunk=16)
    reduction = legacy["host_dispatches"] / max(
        1, mixed["host_dispatches"])
    row = {
        "metric": "llm_mixed_tick_dispatch_reduction",
        "value": round(reduction, 2),
        "unit": "legacy_n8_host_dispatches_over_mixed_n8",
        "device": "cpu",
        "workload": {"n_requests": len(prompts),
                     "prompt_len": len(prompts[0]),
                     "gen_len": gen_len, "decode_ticks": 8},
        "legacy": legacy,
        "mixed": mixed,
    }
    print(json.dumps(row))
    if args.out:
        with open(args.out, "a") as f:
            f.write(json.dumps(row) + "\n")
    # no tokens_per_sec on this row: the tiny CI window is dominated
    # by the mixed programs' one-time compile ladder (sizes 1/2/4/8),
    # which would gate future runs on compiler wall clock, not the
    # engine. Dispatch counts are deterministic — they are the metric.
    _ledger.append("llm_bench", row["metric"], row["value"],
                   row["unit"],
                   dispatches=mixed["host_dispatches"],
                   peak_mem_bytes=_peak_mem_bytes(),
                   **_verdict_row_fields(),
                   extra={"legacy_dispatches":
                              legacy["host_dispatches"],
                          "mixed_slabs": mixed["mixed_slabs"],
                          "workload": row["workload"]})
    if assert_ci:
        assert [o["output_ids"] for o in mixed_outs] == \
            [o["output_ids"] for o in legacy_outs], \
            "mixed-tick generations diverged from the legacy " \
            "two-op tick path"
        assert mixed["mixed_slabs"] > 0, \
            f"the mixed path never engaged: {mixed}"
        assert mixed["host_dispatches"] < legacy["host_dispatches"], (
            f"one mixed slab must dispatch less than the alternating "
            f"loop: {mixed['host_dispatches']} vs "
            f"{legacy['host_dispatches']}")
        print("LLM MIXED-TICK SMOKE OK")
    return 0


def build_draft_net(vocab=211, hidden=32, heads=2, max_pos=512,
                    seed=123):
    import paddle_tpu as pt
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt_config

    pt.seed(seed)
    cfg = gpt_config("gpt2-small", num_layers=1, hidden_size=hidden,
                     num_heads=heads, vocab_size=vocab,
                     max_position_embeddings=max_pos,
                     hidden_dropout=0.0, attention_dropout=0.0)
    return GPTForCausalLM(cfg)


def run_spec(net, draft, prompts, gen_len, spec_tokens,
             spec_slab=True, kv_dtype=None, prefix_cache=True,
             decode_ticks=8, page_size=4, temperature=0.0):
    """One speculative engine pass (slab or legacy) over the
    workload: the first request warms the compile caches off the
    clock, the rest arrive as a concurrent burst. Returns
    (outputs, stats) with the tentpole quantities: acceptance rate,
    accepted tokens per host dispatch, and host dispatches per
    emitted token."""
    from paddle_tpu.inference.llm import LLMEngine

    total = max(len(p) for p in prompts) + gen_len + spec_tokens
    pages = -(-total // page_size) * max(4, len(prompts)) + 16
    eng = LLMEngine(net, max_seqs=4, page_size=page_size,
                    num_pages=pages, max_len=total,
                    prefill_buckets=(max(len(p) for p in prompts),),
                    draft_net=draft, spec_tokens=spec_tokens,
                    spec_slab=spec_slab, kv_dtype=kv_dtype,
                    prefix_cache=prefix_cache,
                    decode_ticks_per_dispatch=(
                        1 if not spec_slab else decode_ticks))
    with eng:
        outs = [eng.generate([prompts[0]], max_new_tokens=gen_len,
                             temperature=temperature)[0]]
        d0, t0 = eng.n_host_dispatches, time.perf_counter()
        futs = [eng.submit(p, max_new_tokens=gen_len,
                           temperature=temperature)
                for p in prompts[1:]]
        outs += [f.result(timeout=600) for f in futs]
        wall = time.perf_counter() - t0
        dispatches = eng.n_host_dispatches - d0
        rounds = eng.n_spec_rounds
        proposed = eng.n_spec_proposed
        accepted = eng.n_spec_accepted
    tokens = sum(len(o["output_ids"]) for o in outs[1:])
    return outs, {
        "spec_tokens": spec_tokens,
        "mode": "slab" if spec_slab else "legacy",
        "kv_dtype": kv_dtype or "f32",
        "prefix_cache": prefix_cache,
        "tokens": tokens,
        "tokens_per_sec": round(tokens / wall, 1),
        "rounds": rounds,
        "accept_rate": round(accepted / max(1, proposed), 4),
        "accepted_tokens_per_dispatch": round(
            tokens / max(1, dispatches), 3),
        "host_dispatches_per_token": round(
            dispatches / max(1, tokens), 4),
    }


def spec_main(args, net=None, assert_ci=False):
    """The --spec sweep (tentpole gate): on-device speculative slab
    over draft K in {2,4,8} x kv_dtype {f32,int8} x prefix cache
    on/off, one bench_ledger/v1 row per combination (K, kv_dtype and
    cache state join the series key so K=2 never regression-gates
    against K=8). The --ci gate asserts >=2x fewer host dispatches
    per emitted token than the LEGACY inline spec path at K=4, and
    greedy token-identity against a target-only engine."""
    from paddle_tpu.inference.llm import LLMEngine

    Ks = (2, 4) if args.ci else (2, 4, 8)
    if net is None:
        net = build_net(vocab=97, hidden=64, max_pos=256) if args.ci \
            else build_net()
    vocab = net.cfg.vocab_size
    draft = build_draft_net(vocab=vocab,
                            max_pos=net.cfg.max_position_embeddings)
    prompts = make_prompts(4, prefix_len=16, tail_len=8, vocab=vocab) \
        if args.ci else make_prompts(args.n_requests, args.prefix_len,
                                     args.tail_len, vocab=vocab)
    gen_len = 12 if args.ci else args.gen_len

    # greedy token-identity references, one per pool dtype (int8
    # quantization moves logits, so it gets an int8 reference)
    refs = {}
    for kv in (None, "int8"):
        total = max(len(p) for p in prompts) + gen_len + 8
        pages = -(-total // 4) * max(4, len(prompts)) + 16
        with LLMEngine(net, max_seqs=4, page_size=4, num_pages=pages,
                       max_len=total,
                       prefill_buckets=(max(len(p)
                                            for p in prompts),),
                       kv_dtype=kv) as ref:
            refs[kv or "f32"] = [
                o["output_ids"]
                for o in ref.generate(prompts,
                                      max_new_tokens=gen_len)]

    sweep = []
    mismatches = []
    for K in Ks:
        for kv in (None, "int8"):
            for cache in (True, False):
                outs, stats = run_spec(net, draft, prompts, gen_len,
                                       K, kv_dtype=kv,
                                       prefix_cache=cache)
                got = [o["output_ids"] for o in outs]
                ok = got == refs[kv or "f32"]
                if not ok:
                    mismatches.append((K, kv, cache))
                stats["token_identity"] = ok
                sweep.append(stats)
                series = (f"llm_spec_accepted_per_dispatch_k{K}_"
                          f"{'cache' if cache else 'nocache'}")
                _ledger.append(
                    "llm_bench", series,
                    stats["accepted_tokens_per_dispatch"],
                    "accepted_tokens_per_host_dispatch",
                    tokens_per_sec=stats["tokens_per_sec"],
                    dispatches=stats["host_dispatches_per_token"],
                    peak_mem_bytes=_peak_mem_bytes(),
                    kv_dtype=kv,
                    **_verdict_row_fields(),
                    extra={"spec_tokens": K,
                           "accept_rate": stats["accept_rate"],
                           "prefix_cache": cache,
                           "gen_len": gen_len})

    # the legacy inline path at K=4 — the dispatch baseline the
    # tentpole's >=2x claim is measured against
    _, legacy = run_spec(net, draft, prompts, gen_len, 4,
                         spec_slab=False)
    slab4 = next(s for s in sweep
                 if s["spec_tokens"] == 4 and s["kv_dtype"] == "f32"
                 and s["prefix_cache"])
    reduction = legacy["host_dispatches_per_token"] / max(
        1e-9, slab4["host_dispatches_per_token"])
    row = {
        "metric": "llm_spec_slab_dispatch_reduction",
        "value": round(reduction, 2),
        "unit": "legacy_k4_dispatches_per_token_over_slab_k4",
        "device": "cpu",
        "workload": {"n_requests": len(prompts),
                     "prompt_len": len(prompts[0]),
                     "gen_len": gen_len, "spec_tokens": list(Ks)},
        "legacy_k4": legacy,
        "sweep": sweep,
    }
    print(json.dumps(row))
    if args.out:
        with open(args.out, "a") as f:
            f.write(json.dumps(row) + "\n")
    _ledger.append("llm_bench", row["metric"], row["value"],
                   row["unit"],
                   dispatches=slab4["host_dispatches_per_token"],
                   peak_mem_bytes=_peak_mem_bytes(),
                   **_verdict_row_fields(),
                   extra={"legacy_dispatches_per_token":
                              legacy["host_dispatches_per_token"],
                          "slab_accept_rate": slab4["accept_rate"],
                          "workload": row["workload"]})
    if assert_ci:
        assert not mismatches, (
            f"greedy spec slab diverged from the target-only engine "
            f"at (K, kv_dtype, cache) = {mismatches}")
        assert reduction >= 2.0, (
            f"the spec slab must emit tokens at >=2x fewer host "
            f"dispatches than the legacy inline path at K=4; got "
            f"{reduction:.2f}x ({slab4['host_dispatches_per_token']} "
            f"vs {legacy['host_dispatches_per_token']} per token)")
        print("LLM SPEC-SLAB SMOKE OK")
    return 0


def run_kv_capacity(net, kv_dtype, hbm_budget_bytes, prompts, gen_len,
                    page_size=4):
    """One serial pass of DISTINCT prompts through an engine whose
    pool is sized to ``hbm_budget_bytes`` at ``kv_dtype`` (probe
    engine reads the true per-page bytes, scale tables included).
    Returns stats: usable pages at the budget, prefix-cache resident
    pages after the pass (the eviction-bounded capacity the ~2x is
    measured on), streams, and occupancy figures."""
    from paddle_tpu.inference.llm import LLMEngine

    total = max(len(p) for p in prompts) + gen_len
    probe = LLMEngine(net, max_seqs=2, page_size=page_size,
                      num_pages=8, max_len=total,
                      prefill_buckets=(64,), kv_dtype=kv_dtype)
    page_bytes = probe._page_bytes
    probe.close()
    num_pages = max(8, int(hbm_budget_bytes // page_bytes))
    eng = LLMEngine(net, max_seqs=2, page_size=page_size,
                    num_pages=num_pages, max_len=total,
                    prefill_buckets=(64,), prefill_chunk=64,
                    prefix_cache=True, kv_dtype=kv_dtype)
    outs = []
    with eng:
        for p in prompts:      # serial: deterministic LRU pressure
            outs += eng.generate([p], max_new_tokens=gen_len)
        resident = eng._cache.shared_page_count
        evicted = eng._cache.n_evicted
    return [o["output_ids"] for o in outs], {
        "kv_dtype": kv_dtype,
        "page_bytes": page_bytes,
        "usable_pages": num_pages - 1,
        "pool_bytes": num_pages * page_bytes,
        "resident_prefix_pages": resident,
        "evicted_pages": evicted,
        "resident_tokens": resident * page_size,
    }


def kv_dtype_main(args, net=None, assert_ci=False):
    """The ``--kv-dtype`` sweep (ISSUE 15): bf16 vs int8 KV pools at
    FIXED pool HBM. The capacity workload streams more distinct
    prefix pages than either pool can hold, so each pool's resident
    prefix-cache page count settles at its eviction bound — the gate
    asserts int8 retains >= 1.8x bf16's pages at the same byte
    budget (the acceptance criterion's "2x effective prefix cache /
    decode occupancy at fixed HBM" lens). The QUANTIZED-TOLERANCE
    mode extends the token-identity gate: int8 streams must be
    INTERNALLY exact (cache on/off identical — quantization is
    deterministic) and agree with the f32 pool's greedy streams at
    >= the documented tolerance (PERF.md)."""
    page_size = 4
    if net is None:
        net = build_net(vocab=97, hidden=64, max_pos=256)
    rng = np.random.RandomState(7)
    n_prompts = 24 if args.ci else 40
    # 3 FULL pages register per prompt (the 13th token keeps the last
    # position computed, per the cache's n-1 cap)
    cap_prompts = [rng.randint(0, 97, 3 * page_size + 1).tolist()
                   for _ in range(n_prompts)]
    # budget: 24 bf16 pages' worth of HBM — far fewer than the
    # n_prompts*3 distinct pages the workload streams, so BOTH pools
    # run eviction-bounded and the ratio reads pure capacity
    from paddle_tpu.inference.llm import LLMEngine
    probe = LLMEngine(net, max_seqs=2, page_size=page_size,
                      num_pages=8, prefill_buckets=(64,),
                      kv_dtype="bf16")
    budget = 24 * probe._page_bytes
    probe.close()
    gen_len = 4
    stats = {}
    streams = {}
    for kv in ("bf16", "int8"):
        streams[kv], stats[kv] = run_kv_capacity(
            net, kv, budget, cap_prompts, gen_len,
            page_size=page_size)
    ratio = stats["int8"]["resident_prefix_pages"] / max(
        1, stats["bf16"]["resident_prefix_pages"])
    # quantized tolerance: int8 exact vs itself (cache off), within
    # tolerance vs the f32 pool
    tol_prompts = cap_prompts[:6]
    int8_on, _ = run_mode(net, tol_prompts, 12, prefix_cache=True,
                          kv_dtype="int8", page_size=page_size)
    int8_off, _ = run_mode(net, tol_prompts, 12, prefix_cache=False,
                           kv_dtype="int8", page_size=page_size)
    f32_on, _ = run_mode(net, tol_prompts, 12, prefix_cache=True,
                         page_size=page_size)
    agree = float(np.mean([
        np.mean([a == b for a, b in zip(x["output_ids"],
                                        y["output_ids"])])
        for x, y in zip(int8_on, f32_on)]))
    row = {
        "metric": "llm_int8_kv_capacity_ratio",
        "value": round(ratio, 2),
        "unit": "int8_resident_prefix_pages_over_bf16_at_fixed_hbm",
        "device": "cpu",
        "workload": {"n_prompts": n_prompts,
                     "prompt_len": len(cap_prompts[0]),
                     "hbm_budget_bytes": budget, "gen_len": gen_len},
        "int8_greedy_agreement_vs_f32": round(agree, 4),
        "sweep": stats,
    }
    print(json.dumps(row))
    if args.out:
        with open(args.out, "a") as f:
            f.write(json.dumps(row) + "\n")
    # one ledger row PER dtype (series keyed by kv_dtype — int8 and
    # bf16 never gate against each other) + the ratio headline
    for kv in ("bf16", "int8"):
        _ledger.append("llm_bench", "llm_kv_capacity_at_fixed_hbm",
                       stats[kv]["resident_prefix_pages"],
                       "prefix_cache_resident_pages",
                       kv_dtype=kv,
                       peak_mem_bytes=_peak_mem_bytes(),
                       **_verdict_row_fields(),
                       extra={"usable_pages": stats[kv][
                                  "usable_pages"],
                              "page_bytes": stats[kv]["page_bytes"],
                              "hbm_budget_bytes": budget})
    _ledger.append("llm_bench", row["metric"], row["value"],
                   row["unit"], kv_dtype="int8",
                   peak_mem_bytes=_peak_mem_bytes(),
                   **_verdict_row_fields(),
                   extra={"int8_greedy_agreement_vs_f32": agree,
                          "workload": row["workload"]})
    if assert_ci:
        assert ratio >= 1.8, (
            f"kv_dtype=int8 must retain >=1.8x bf16's prefix-cache "
            f"pages at fixed pool HBM; got {ratio:.2f}x "
            f"({stats['int8']['resident_prefix_pages']} vs "
            f"{stats['bf16']['resident_prefix_pages']} of "
            f"{stats['int8']['usable_pages']}/"
            f"{stats['bf16']['usable_pages']} usable)")
        assert [o["output_ids"] for o in int8_on] == \
            [o["output_ids"] for o in int8_off], (
            "int8 streams must be IDENTICAL cache-on vs cache-off "
            "(quantization is deterministic)")
        assert agree >= 0.9, (
            f"int8 greedy agreement vs the f32 pool fell below the "
            f"documented tolerance: {agree:.3f} < 0.9")
        print("LLM KV-DTYPE SMOKE OK")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--ci", action="store_true",
                    help="fast smoke + assertions (tools/ci.sh gate)")
    ap.add_argument("--fleet", action="store_true",
                    help="K=3 router benchmark: prefix-affinity vs "
                         "round-robin aggregate cache hit rate")
    ap.add_argument("--decode-ticks", action="store_true",
                    help="device-resident decode loop sweep: "
                         "N in {1,4,8,16} ticks per dispatch, "
                         "tokens/sec + host dispatches per 100 tokens")
    ap.add_argument("--disagg", action="store_true",
                    help="disaggregated prefill/decode gate: mixed "
                         "storm on int8 pools, unified K=3 vs "
                         "1-prefill/2-decode with KV-page migration "
                         "— short-request TTFT p99 must improve and "
                         "decode-tick p99 jitter must drop, token-"
                         "identical generations")
    ap.add_argument("--storm", action="store_true",
                    help="diurnal+burst autoscaling gate: static K=3 "
                         "vs Autoscaler min=1/max=3 — replica-seconds "
                         "and gold-class deadline-hit ratio")
    ap.add_argument("--overload", action="store_true",
                    help="brownout gate: 3x burst over static K=2, "
                         "controller off vs on — gold hit ratio held "
                         "at the un-overloaded baseline, wasted-work "
                         "fraction strictly lower")
    ap.add_argument("--kv-dtype", action="store_true",
                    help="bf16 vs int8 KV pools at fixed pool HBM: "
                         "resident prefix-cache pages (>=1.8x gate) "
                         "+ the quantized-tolerance token gate")
    ap.add_argument("--mixed-tick", action="store_true",
                    help="legacy alternating prefill/decode ticks vs "
                         "ONE ragged mixed slab: token identity + "
                         "host-dispatch reduction")
    ap.add_argument("--spec", action="store_true",
                    help="on-device speculative slab sweep: draft K "
                         "in {2,4,8} x kv_dtype {f32,int8} x prefix "
                         "cache on/off — acceptance rate + accepted "
                         "tokens per dispatch, >=2x dispatch gate vs "
                         "the legacy inline path at K=4")
    ap.add_argument("--out", default=None,
                    help="append the BENCH row to this JSONL file")
    ap.add_argument("--n-requests", type=int, default=8)
    ap.add_argument("--prefix-len", type=int, default=64,
                    help="shared prefix length (page-aligned by "
                         "default: 4 pages of 16)")
    ap.add_argument("--tail-len", type=int, default=16)
    ap.add_argument("--gen-len", type=int, default=32)
    args = ap.parse_args(argv)

    if args.disagg:
        return disagg_main(args)
    if args.fleet:
        return fleet_main(args)
    if args.storm:
        return storm_main(args)
    if args.overload:
        return overload_main(args)
    if args.decode_ticks:
        return decode_ticks_main(args, assert_ci=args.ci)
    if args.kv_dtype:
        return kv_dtype_main(args, assert_ci=args.ci)
    if args.mixed_tick:
        return mixed_tick_main(args, assert_ci=args.ci)
    if args.spec:
        return spec_main(args, assert_ci=args.ci)

    if args.ci:
        net = build_net(vocab=97, hidden=64, max_pos=256)
        prompts = make_prompts(4, prefix_len=32, tail_len=8, vocab=97)
        gen_len = 8
    else:
        net = build_net()
        prompts = make_prompts(args.n_requests, args.prefix_len,
                               args.tail_len, vocab=211)
        gen_len = args.gen_len

    on_outs, on = run_mode(net, prompts, gen_len, prefix_cache=True)
    off_outs, off = run_mode(net, prompts, gen_len, prefix_cache=False)

    saved = 1.0 - on["tokens_recomputed"] / max(1,
                                                off["tokens_recomputed"])
    row = {
        "metric": "llm_shared_prefix_recompute_savings",
        "value": round(saved, 4),
        "unit": "fraction_of_prompt_tokens",
        "device": "cpu",
        "workload": {"n_requests": len(prompts),
                     "prompt_len": len(prompts[0]),
                     "gen_len": gen_len},
        "cache_on": on,
        "cache_off": off,
    }
    print(json.dumps(row))
    if args.out:
        with open(args.out, "a") as f:
            f.write(json.dumps(row) + "\n")
    _ledger.append("llm_bench", row["metric"], row["value"],
                   row["unit"],
                   tokens_per_sec=on["e2e_tokens_per_sec"],
                   peak_mem_bytes=_peak_mem_bytes(),
                   **_verdict_row_fields(),
                   extra={"ttft_p50_s": on["ttft_p50_s"],
                          "cache_off_ttft_p50_s": off["ttft_p50_s"],
                          "workload": row["workload"]})

    if args.ci:
        assert on["tokens_reused"] > 0, \
            "prefix cache produced zero hits on a shared-prefix " \
            "workload"
        for mode in (on, off):
            r = mode["span_rollup"]
            assert r.get("llm.prefill", {}).get("count", 0) > 0 and \
                r.get("llm.decode", {}).get("count", 0) > 0, \
                f"span rollup missing phases: {r}"
            assert abs(sum(v["share"] for v in r.values()) - 1.0) \
                < 0.01, r
        assert [o["output_ids"] for o in on_outs] == \
            [o["output_ids"] for o in off_outs], \
            "generations differ with prefix cache on vs off"
        assert saved >= 0.5, \
            f"expected >=50% recompute savings at page-aligned " \
            f"prefixes, got {saved:.1%}"
        print("LLM SERVING SMOKE OK")
        # second half of the gate: the device-resident decode loop
        # sweep (N=8 >= 1.2x N=1 decode tokens/sec at batch 1 and 4,
        # streams token-identical across N, greedy and seeded)
        rc = decode_ticks_main(args, net=net, assert_ci=True)
        if rc:
            return rc
        # third: the ragged MIXED tick must be token-identical to the
        # legacy two-op tick loop and strictly cheaper in dispatches
        return mixed_tick_main(args, net=net, assert_ci=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
