"""Extended TPU benchmark sweep (VERDICT r3 item 1a): run the headline
configs the moment the chip is reachable and append one JSON line per
config to PERF_SWEEP.jsonl — GPT-2-small batch 8/16/32 with and without
the fused vocab path, a GPT-2-medium and (OOM-guarded) GPT-2-large
point, ResNet-50 and BERT batch scaling. Each entry is the same
compiled hapi train step bench.py times (framework end-to-end).

Run: python tools/tpu_sweep.py [out.jsonl]   (single TPU client!)
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402


def main(out_path="PERF_SWEEP.jsonl"):
    import jax
    dev = jax.devices()[0]
    print(f"device: {dev.device_kind}", file=sys.stderr)

    runs = []
    for b in (8, 16, 32):
        runs.append(("gpt2s_fused", lambda b=b: bench.bench_gpt(batch=b)))
    for b in (8, 16, 32):
        runs.append(("gpt2s_dense",
                     lambda b=b: bench.bench_gpt(batch=b, fused=False)))
    runs.append(("gpt2_medium", lambda: bench.bench_gpt(
        batch=8, model_name="gpt2-medium")))
    runs.append(("gpt2_medium", lambda: bench.bench_gpt(
        batch=16, model_name="gpt2-medium")))
    runs.append(("gpt2_large", lambda: bench.bench_gpt(
        batch=4, model_name="gpt2-large")))
    runs.append(("gpt2_large", lambda: bench.bench_gpt(
        batch=8, model_name="gpt2-large")))
    runs.append(("resnet50", lambda: bench.bench_resnet(batch=128)))
    runs.append(("resnet50", lambda: bench.bench_resnet(batch=256)))
    runs.append(("bert", lambda: bench.bench_bert(batch=64)))
    runs.append(("bert", lambda: bench.bench_bert(batch=128)))

    with open(out_path, "a") as f:
        for tag, fn in runs:
            t0 = time.time()
            try:
                rec = fn()
                rec["tag"] = tag
            except Exception as e:  # OOM on the big points is expected
                rec = {"tag": tag, "error": str(e)[:200]}
            rec["device"] = dev.device_kind
            rec["wall_s"] = round(time.time() - t0, 1)
            f.write(json.dumps(rec) + "\n")
            f.flush()
            print(json.dumps(rec), file=sys.stderr)


if __name__ == "__main__":
    main(*sys.argv[1:])
