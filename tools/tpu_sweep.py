"""Extended TPU benchmark sweep (VERDICT r3 item 1a): run the headline
configs the moment the chip is reachable and append one JSON line per
config to PERF_SWEEP.jsonl — GPT-2-small batch 8/16/32 with and without
the fused vocab path, a GPT-2-medium and (OOM-guarded) GPT-2-large
point, ResNet-50 and BERT batch scaling. Each entry is the same
compiled hapi train step bench.py times (framework end-to-end).

Each config runs in a FRESH subprocess: one long-lived client
accumulates device buffers across configs (a prior model's donated
state is not reliably freed before the next model uploads), which
turned the r4 first pass's ResNet/BERT points into instant
RESOURCE_EXHAUSTED. Fresh-process isolation costs ~9s of tunnel init
per config and makes every point independent; it also retries
transient remote-compile 500s once.

Run: python tools/tpu_sweep.py [out.jsonl]        (the whole sweep)
     python tools/tpu_sweep.py --one '{"kind":"gpt","batch":8,...}'
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

RUNS = [
    {"tag": "gpt2s_fused", "kind": "gpt", "batch": 8},
    {"tag": "gpt2s_fused", "kind": "gpt", "batch": 16},
    {"tag": "gpt2s_fused", "kind": "gpt", "batch": 32},
    {"tag": "gpt2s_dense", "kind": "gpt", "batch": 8, "fused": False},
    {"tag": "gpt2s_dense", "kind": "gpt", "batch": 16, "fused": False},
    {"tag": "gpt2s_dense", "kind": "gpt", "batch": 32, "fused": False},
    {"tag": "gpt2_medium", "kind": "gpt", "batch": 8,
     "model_name": "gpt2-medium"},
    {"tag": "gpt2_medium", "kind": "gpt", "batch": 16,
     "model_name": "gpt2-medium"},
    {"tag": "gpt2_large", "kind": "gpt", "batch": 4,
     "model_name": "gpt2-large"},
    {"tag": "gpt2_large", "kind": "gpt", "batch": 8,
     "model_name": "gpt2-large"},
    {"tag": "resnet50", "kind": "resnet", "batch": 128},
    {"tag": "resnet50", "kind": "resnet", "batch": 256},
    {"tag": "bert", "kind": "bert", "batch": 64},
    {"tag": "bert", "kind": "bert", "batch": 128},
    # config 5: CTR — device table and PS-analog host table
    {"tag": "widedeep", "kind": "widedeep", "batch": 16384},
    {"tag": "widedeep", "kind": "widedeep", "batch": 65536},
    {"tag": "widedeep_host", "kind": "widedeep", "batch": 8192,
     "table": "host"},
    # decode serving: 16 concurrent greedy generations, 8 slots;
    # the lookahead row amortizes the tunnel's per-step dispatch fetch
    {"tag": "llm_decode", "kind": "llm_decode", "n_requests": 16},
    {"tag": "llm_decode_la", "kind": "llm_decode", "n_requests": 16,
     "lookahead": 4},
    # config 4 family at single-chip max: GPT-2-XL 1.56B, Adafactor
    # factored state + scan/remat (VERDICT r4 item 3)
    # pure-bf16 + Adafactor: the configuration FEASIBILITY_XL.json
    # shows fitting 16 GiB (fp32 params+grads alone overflow)
    {"tag": "gpt2_xl", "kind": "gpt", "batch": 8, "model_name": "gpt2-xl",
     "optimizer": "adafactor", "scan_layers": True, "remat": True,
     "param_dtype": "bfloat16", "iters": 10},
    {"tag": "gpt2_xl", "kind": "gpt", "batch": 4, "model_name": "gpt2-xl",
     "optimizer": "adafactor", "scan_layers": True, "remat": True,
     "param_dtype": "bfloat16", "iters": 10},
]


def _connect_device():
    import jax
    return jax.devices()[0]


def run_one(spec: dict) -> dict:
    import bench
    from paddle_tpu.reliability.retry import RetryPolicy
    # the tunnel connect is the flakiest step of a sweep row (BENCH
    # r02–r05 all carry tpu_error): absorb transient socket failures
    # through the SHARED retry policy instead of failing the row on
    # the first OSError — a real compile/OOM error is not retryable
    # and still propagates immediately
    dev = RetryPolicy(max_attempts=4, base_delay=3.0, max_delay=20.0,
                      jitter=0.25, retry_on=(OSError,),
                      scope="tpu_tunnel").call(
        _connect_device, describe="tpu tunnel connect")
    kind = spec["kind"]
    kw = {k: v for k, v in spec.items() if k not in ("tag", "kind")}
    if kind == "gpt":
        rec = bench.bench_gpt(**kw)
    elif kind == "resnet":
        rec = bench.bench_resnet(**kw)
    elif kind == "bert":
        rec = bench.bench_bert(**kw)
    elif kind == "widedeep":
        rec = bench.bench_widedeep(**kw)
    elif kind == "llm_decode":
        rec = bench.bench_llm_decode(**kw)
    else:
        raise ValueError(kind)
    rec["tag"] = spec["tag"]
    rec["device"] = dev.device_kind
    rec["metrics"] = _metrics_snapshot()
    _emit_ledger(rec, spec)
    return rec


def _emit_ledger(rec: dict, spec: dict) -> None:
    """Append the canonical trajectory row (tools/bench_ledger.py)
    beside the legacy PERF_SWEEP.jsonl shape — the legacy row keeps
    being written for one release; the field mapping is documented in
    PERF.md ("The perf ledger"). Best-effort: a ledger hiccup must not
    cost the sweep its hardware row."""
    try:
        try:
            from tools import bench_ledger
        except ImportError:
            import bench_ledger
        bench_ledger.append(
            "tpu_sweep", rec.get("tag", spec.get("tag", "?")),
            rec["value"], rec["unit"],
            tokens_per_sec=(rec["value"]
                            if rec.get("unit") == "tokens/sec"
                            else None),
            mfu=rec.get("mfu"),
            backend=rec.get("device"),
            **bench_ledger.goodput_row_fields(),
            # the full registry snapshot already rides the legacy row;
            # the ledger row carries the bounded counters/gauges view
            extra={k: rec.get(k) for k in
                   ("batch", "seq", "params", "model", "fused",
                    "optimizer", "lookahead", "n_requests")
                   if rec.get(k) is not None})
    except Exception as e:  # noqa: BLE001
        print(f"tpu_sweep: ledger append failed: {e}", file=sys.stderr)


def _metrics_snapshot() -> dict:
    """Observability snapshot riding every BENCH row: step/TTFT/token
    histogram summaries, restart counters, and a device-memory sample —
    a tunnel that died mid-round shows up as zero counts or a stale
    memory gauge IN the row instead of needing 8 hours of hindsight
    (VERDICT r5)."""
    from paddle_tpu import observability
    observability.sample_device_memory()
    snap = observability.default_registry().snapshot()
    # zeros stay IN: a zero step count is the dead-round signal itself
    return {k: (round(v, 6) if isinstance(v, float) else v)
            for k, v in snap.items()}


def _transient(err: str) -> bool:
    # retry only the tunnel's compile-helper 500s and connection-level
    # socket failures that escaped the in-process retry; a real OOM or
    # crash must not hammer the chip (match specific tokens, not a
    # bare "500" that could appear in byte counts or line numbers)
    if "remote_compile" in err and "HTTP 500" in err:
        return True
    # the exception CLASS names socket code actually raises (a
    # subclass traceback never contains the literal base-class name)
    return any(tok in err for tok in (
        "OSError", "ConnectionResetError", "ConnectionRefusedError",
        "ConnectionAbortedError", "BrokenPipeError", "socket.timeout"))


def main(out_path="PERF_SWEEP.jsonl", only=None):
    from _subproc import run_spec
    with open(out_path, "a") as f:
        for spec in RUNS:
            if only and spec["tag"] not in only:
                continue
            t0 = time.time()
            rec = run_spec(__file__, "--one", spec, timeout=1800,
                           retries=1, retry_if=_transient)
            rec.setdefault("tag", spec["tag"])
            rec["wall_s"] = round(time.time() - t0, 1)
            f.write(json.dumps(rec) + "\n")
            f.flush()
            print(json.dumps(rec), file=sys.stderr)


if __name__ == "__main__":
    if len(sys.argv) > 2 and sys.argv[1] == "--one":
        print(json.dumps(run_one(json.loads(sys.argv[2]))))
    elif len(sys.argv) > 2:
        main(sys.argv[1], only=set(sys.argv[2].split(",")))
    else:
        main(*sys.argv[1:])
