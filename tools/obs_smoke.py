"""Observability smoke gate (tools/ci.sh step): run a tiny instrumented
train loop under the profiler, dump every exporter, and assert the
artifacts parse — Prometheus text exposition, the chrome://tracing JSON
(≥1 complete "X" event per recorded host annotation), and the JSONL
reporter stream. Exits non-zero on any missing signal so a refactor
that silently unhooks an instrument fails CI, not a 3am bench round.

Run: python tools/obs_smoke.py [outdir]
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def main(outdir: str = "/tmp/pt_obs_smoke") -> int:
    import paddle_tpu as pt
    from paddle_tpu import nn, observability
    from paddle_tpu.io import TensorDataset
    from paddle_tpu.profiler import Profiler, export_chrome_tracing

    os.makedirs(outdir, exist_ok=True)
    pt.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
    model = pt.Model(net)
    model.prepare(optimizer=pt.optimizer.SGD(learning_rate=0.1,
                                             parameters=net),
                  loss=nn.CrossEntropyLoss())
    x = np.random.RandomState(0).randn(64, 8).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 2, (64, 1))

    jsonl_path = os.path.join(outdir, "metrics.jsonl")
    prof = Profiler(log_dir=os.path.join(outdir, "xprof"))
    with observability.JSONLReporter(jsonl_path, interval=0.2):
        prof.start()
        model.fit(TensorDataset([x, y]), batch_size=16, epochs=2,
                  verbose=0)
        prof.stop()
    observability.sample_device_memory()

    # -- chrome trace: loads, and covers every recorded annotation ------
    trace_path = export_chrome_tracing(prof,
                                       os.path.join(outdir, "trace.json"))
    with open(trace_path) as f:
        trace = json.load(f)
    events = trace["traceEvents"]
    assert events, "empty chrome trace"
    assert all(ev["ph"] == "X" and ev["dur"] >= 0 for ev in events)
    names = {ev["name"] for ev in events}
    for bucket in ("Dataloader", "TrainStep", "Callbacks"):
        assert bucket in names, (bucket, names)

    # -- prometheus text: parses line-by-line, has the train signals ----
    prom_path = observability.write_prometheus(
        os.path.join(outdir, "metrics.prom"))
    with open(prom_path) as f:
        text = f.read()
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        name_part, value = line.rsplit(" ", 1)
        float(value)            # every sample value is a number
        assert name_part[0].isalpha() or name_part[0] == "_", line
    assert "train_step_seconds_count" in text
    assert "dataloader_batches" in text

    # -- jsonl stream: every line self-contained JSON with metrics ------
    with open(jsonl_path) as f:
        lines = [json.loads(ln) for ln in f if ln.strip()]
    assert lines, "JSONL reporter wrote nothing"
    assert any(rec["metrics"].get("train_step_seconds_count", 0) > 0
               for rec in lines), "no step metrics reached the JSONL dump"

    print(f"observability smoke OK: {len(events)} trace events, "
          f"{len(text.splitlines())} prom lines, {len(lines)} jsonl rows "
          f"-> {outdir}")
    return 0


if __name__ == "__main__":
    sys.exit(main(*sys.argv[1:]))
