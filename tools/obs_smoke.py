"""Observability smoke gate (tools/ci.sh step): run a tiny instrumented
train loop under the profiler WITH TRACING ON, dump every exporter, and
assert the artifacts parse — Prometheus text exposition, the
chrome://tracing JSON (≥1 complete "X" event per recorded host
annotation, plus span events with parent links and row-label metadata),
and the JSONL reporter stream. Then exercise the live surfaces: start
the debug server on an ephemeral port and scrape /metrics, /healthz,
/statusz and /tracez; finally force-crash a subprocess with the flight
recorder installed and assert the JSONL dump was written. Exits
non-zero on any missing signal so a refactor that silently unhooks an
instrument fails CI, not a 3am bench round.

Run: python tools/obs_smoke.py [outdir]
"""

import json
import os
import subprocess
import sys
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def main(outdir: str = "/tmp/pt_obs_smoke") -> int:
    import paddle_tpu as pt
    from paddle_tpu import nn, observability
    from paddle_tpu.io import TensorDataset
    from paddle_tpu.observability import server as debug_server
    from paddle_tpu.observability import tracing
    from paddle_tpu.profiler import Profiler, export_chrome_tracing

    os.makedirs(outdir, exist_ok=True)
    pt.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
    model = pt.Model(net)
    model.prepare(optimizer=pt.optimizer.SGD(learning_rate=0.1,
                                             parameters=net),
                  loss=nn.CrossEntropyLoss())
    x = np.random.RandomState(0).randn(64, 8).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 2, (64, 1))

    jsonl_path = os.path.join(outdir, "metrics.jsonl")
    prof = Profiler(log_dir=os.path.join(outdir, "xprof"))
    tracing.enable()
    with observability.JSONLReporter(jsonl_path, interval=0.2):
        prof.start()
        model.fit(TensorDataset([x, y]), batch_size=16, epochs=2,
                  verbose=0, steps_per_loop=2)
        prof.stop()
    observability.sample_device_memory()

    # -- chrome trace: loads, covers every annotation AND the spans -----
    trace_path = export_chrome_tracing(prof,
                                       os.path.join(outdir, "trace.json"))
    with open(trace_path) as f:
        trace = json.load(f)
    events = trace["traceEvents"]
    assert events, "empty chrome trace"
    xs = [ev for ev in events if ev["ph"] == "X"]
    assert all(ev["dur"] >= 0 for ev in xs)
    names = {ev["name"] for ev in xs}
    for bucket in ("Dataloader", "TrainStep", "Callbacks"):
        assert bucket in names, (bucket, names)
    # spans merged onto the same timeline with parent links + metadata
    span_evs = [ev for ev in xs if ev.get("cat") == "span"]
    span_names = {ev["name"] for ev in span_evs}
    for want in ("train.epoch", "train.dispatch"):
        assert want in span_names, (want, span_names)
    epoch_ids = {ev["args"]["span_id"] for ev in span_evs
                 if ev["name"] == "train.epoch"}
    step_parents = {ev["args"]["parent_id"] for ev in span_evs
                    if ev["name"] == "train.dispatch"}
    assert step_parents <= epoch_ids, \
        "train.dispatch not parented to epoch"
    meta = {ev["name"] for ev in events if ev["ph"] == "M"}
    assert {"process_name", "thread_name"} <= meta, meta

    # -- prometheus text: parses line-by-line, has the train signals ----
    prom_path = observability.write_prometheus(
        os.path.join(outdir, "metrics.prom"))
    with open(prom_path) as f:
        text = f.read()
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        name_part, value = line.rsplit(" ", 1)
        float(value)            # every sample value is a number
        assert name_part[0].isalpha() or name_part[0] == "_", line
    assert "train_step_seconds_count" in text
    assert "train_loop_slabs" in text     # fused-loop feed instrumented
    assert "train_loop_dispatch_seconds" in text

    # -- jsonl stream: every line self-contained JSON with metrics ------
    with open(jsonl_path) as f:
        lines = [json.loads(ln) for ln in f if ln.strip()]
    assert lines, "JSONL reporter wrote nothing"
    assert any(rec["metrics"].get("train_step_seconds_count", 0) > 0
               for rec in lines), "no step metrics reached the JSONL dump"

    # -- debug server: live /metrics + /statusz + /tracez round-trip ----
    srv = debug_server.DebugServer(port=0).start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        with urllib.request.urlopen(base + "/healthz", timeout=30) as r:
            assert json.loads(r.read())["status"] == "ok"
        with urllib.request.urlopen(base + "/metrics", timeout=30) as r:
            scraped = r.read().decode()
            assert "version=0.0.4" in r.headers["Content-Type"]
        for fam in ("train_step_seconds", "train_compile_count",
                    "train_loop_slabs", "train_loop_dispatch_seconds"):
            assert fam in scraped, f"{fam} missing from /metrics scrape"
        for line in scraped.splitlines():     # scrape parses too
            if not line or line.startswith("#"):
                continue
            float(line.rsplit(" ", 1)[1].replace("+Inf", "inf"))
        with urllib.request.urlopen(base + "/statusz", timeout=30) as r:
            st = json.loads(r.read())
        assert any(k.startswith("train_model_") for k in st["providers"])
        with urllib.request.urlopen(base + "/tracez?limit=8",
                                    timeout=30) as r:
            tz = json.loads(r.read())
        assert tz["finished_total"] > 0
    finally:
        srv.stop()
    tracing.disable()

    # -- flight recorder: forced crash leaves a JSONL dump --------------
    crash_dir = os.path.join(outdir, "flight")
    crash_code = f"""
import jax; jax.config.update("jax_platforms", "cpu")
from paddle_tpu.observability import tracing, flight
tracing.enable()
flight.install_flight_recorder({crash_dir!r})
tracing.start_span("doomed.work", attrs={{"step": 7}})
raise RuntimeError("forced crash for the obs smoke gate")
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.run([sys.executable, "-c", crash_code], env=env,
                       capture_output=True, text=True, timeout=300,
                       cwd=os.path.dirname(os.path.dirname(
                           os.path.abspath(__file__))))
    assert p.returncode != 0, "forced crash exited 0"
    assert "forced crash" in p.stderr, p.stderr[-500:]
    dumps = [f for f in os.listdir(crash_dir) if f.endswith(".jsonl")]
    assert dumps, "flight recorder wrote no dump on unhandled exception"
    rows = [json.loads(ln)
            for ln in open(os.path.join(crash_dir, dumps[0]))]
    assert rows[0]["kind"] == "header" and rows[0]["reason"] == "exception"
    assert any(r.get("kind") == "span" and r.get("live") and
               r["name"] == "doomed.work" for r in rows), \
        "in-flight span missing from the crash dump"

    print(f"observability smoke OK: {len(events)} trace events "
          f"({len(span_evs)} spans), {len(text.splitlines())} prom "
          f"lines, {len(lines)} jsonl rows, debug server scraped, "
          f"crash dump {dumps[0]} -> {outdir}")
    return 0


if __name__ == "__main__":
    sys.exit(main(*sys.argv[1:]))
