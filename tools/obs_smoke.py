"""Observability smoke gate (tools/ci.sh step): run a tiny instrumented
train loop under the profiler WITH TRACING ON, dump every exporter, and
assert the artifacts parse — Prometheus text exposition, the
chrome://tracing JSON (≥1 complete "X" event per recorded host
annotation, plus span events with parent links and row-label metadata),
and the JSONL reporter stream. Then exercise the live surfaces: start
the debug server on an ephemeral port and scrape /metrics, /healthz,
/statusz, /tracez and /perfz — the perf gate asserts nonzero live MFU
after the fit run, resolved XLA program costs for the fused train
loop AND a decode-slab LLMEngine pass, breakdown phases that
reproduce the dispatch/drain histogram totals, and the per-tenant
served-FLOPs counter; finally force-crash a subprocess with the
flight recorder installed and assert the JSONL dump was written. Exits
non-zero on any missing signal so a refactor that silently unhooks an
instrument fails CI, not a 3am bench round.

FLEET MODE (``--fleet``): spawn K=2 replica subprocesses behind a
Router and assert the fleet-wide observability holds — ``GET /fleetz``
aggregates both replicas with per-replica data, the router's
``/metrics`` re-exports replica-labeled ``fleet_llm_*`` series, a
request's spans form ONE cross-process trace (router.request →
router.dispatch here, llm.request in the replica, fetched back via
``/tracez?trace_id=``), ``tools/trace_merge.py`` joins the tables onto
one timeline, and — the PR-4 regression criterion — DISABLED tracing
still costs one flag check (start_span returns the shared noop, time-
bounded).

Run: python tools/obs_smoke.py [outdir] [--fleet]
"""

import json
import os
import subprocess
import sys
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def main(outdir: str = "/tmp/pt_obs_smoke") -> int:
    import paddle_tpu as pt
    from paddle_tpu import nn, observability
    from paddle_tpu.io import TensorDataset
    from paddle_tpu.observability import server as debug_server
    from paddle_tpu.observability import tracing
    from paddle_tpu.profiler import Profiler, export_chrome_tracing

    os.makedirs(outdir, exist_ok=True)
    pt.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
    model = pt.Model(net)
    model.prepare(optimizer=pt.optimizer.SGD(learning_rate=0.1,
                                             parameters=net),
                  loss=nn.CrossEntropyLoss())
    x = np.random.RandomState(0).randn(64, 8).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 2, (64, 1))

    jsonl_path = os.path.join(outdir, "metrics.jsonl")
    prof = Profiler(log_dir=os.path.join(outdir, "xprof"))
    tracing.enable()
    with observability.JSONLReporter(jsonl_path, interval=0.2):
        prof.start()
        model.fit(TensorDataset([x, y]), batch_size=16, epochs=2,
                  verbose=0, steps_per_loop=2)
        prof.stop()
    observability.sample_device_memory()

    # -- chrome trace: loads, covers every annotation AND the spans -----
    trace_path = export_chrome_tracing(prof,
                                       os.path.join(outdir, "trace.json"))
    with open(trace_path) as f:
        trace = json.load(f)
    events = trace["traceEvents"]
    assert events, "empty chrome trace"
    xs = [ev for ev in events if ev["ph"] == "X"]
    assert all(ev["dur"] >= 0 for ev in xs)
    names = {ev["name"] for ev in xs}
    for bucket in ("Dataloader", "TrainStep", "Callbacks"):
        assert bucket in names, (bucket, names)
    # spans merged onto the same timeline with parent links + metadata
    span_evs = [ev for ev in xs if ev.get("cat") == "span"]
    span_names = {ev["name"] for ev in span_evs}
    for want in ("train.epoch", "train.dispatch"):
        assert want in span_names, (want, span_names)
    epoch_ids = {ev["args"]["span_id"] for ev in span_evs
                 if ev["name"] == "train.epoch"}
    step_parents = {ev["args"]["parent_id"] for ev in span_evs
                    if ev["name"] == "train.dispatch"}
    assert step_parents <= epoch_ids, \
        "train.dispatch not parented to epoch"
    meta = {ev["name"] for ev in events if ev["ph"] == "M"}
    assert {"process_name", "thread_name"} <= meta, meta

    # -- prometheus text: parses line-by-line, has the train signals ----
    prom_path = observability.write_prometheus(
        os.path.join(outdir, "metrics.prom"))
    with open(prom_path) as f:
        text = f.read()
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        name_part, value = line.rsplit(" ", 1)
        float(value)            # every sample value is a number
        assert name_part[0].isalpha() or name_part[0] == "_", line
    assert "train_step_seconds_count" in text
    assert "train_loop_slabs" in text     # fused-loop feed instrumented
    assert "train_loop_dispatch_seconds" in text

    # -- jsonl stream: every line self-contained JSON with metrics ------
    with open(jsonl_path) as f:
        lines = [json.loads(ln) for ln in f if ln.strip()]
    assert lines, "JSONL reporter wrote nothing"
    assert any(rec["metrics"].get("train_step_seconds_count", 0) > 0
               for rec in lines), "no step metrics reached the JSONL dump"

    # -- debug server: live /metrics + /statusz + /tracez round-trip ----
    srv = debug_server.DebugServer(port=0).start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        with urllib.request.urlopen(base + "/healthz", timeout=30) as r:
            assert json.loads(r.read())["status"] == "ok"
        with urllib.request.urlopen(base + "/metrics", timeout=30) as r:
            scraped = r.read().decode()
            assert "version=0.0.4" in r.headers["Content-Type"]
        for fam in ("train_step_seconds", "train_compile_count",
                    "train_loop_slabs", "train_loop_dispatch_seconds"):
            assert fam in scraped, f"{fam} missing from /metrics scrape"
        for line in scraped.splitlines():     # scrape parses too
            if not line or line.startswith("#"):
                continue
            float(line.rsplit(" ", 1)[1].replace("+Inf", "inf"))
        with urllib.request.urlopen(base + "/statusz", timeout=30) as r:
            st = json.loads(r.read())
        assert any(k.startswith("train_model_") for k in st["providers"])
        # CPU backends export no memory_stats: /statusz must show the
        # documented host-RSS fallback, never a bare misleading {}
        devmem = st["device_memory"]
        assert devmem, "/statusz device_memory is an empty dict"
        if not any(isinstance(v, dict) for v in devmem.values()):
            assert devmem.get("host_rss_bytes"), devmem
        assert st.get("memory", {}).get("enabled") is True, \
            st.get("memory")
        assert st.get("goodput", {}).get("enabled") is True, \
            st.get("goodput")
        with urllib.request.urlopen(base + "/tracez?limit=8",
                                    timeout=30) as r:
            tz = json.loads(r.read())
        assert tz["finished_total"] > 0

        # -- /perfz: live MFU + step-time breakdown for the fit run ----
        # (the continuous-perf acceptance: nonzero MFU after a few
        # steps, and the breakdown phases reproduce the step-time
        # totals the histograms measured — same clocks, no drift)
        assert st.get("perf", {}).get("enabled") is True, st.get("perf")
        with urllib.request.urlopen(base + "/perfz", timeout=60) as r:
            pz = json.loads(r.read())
        assert pz["enabled"], pz
        assert pz["mfu"] > 0, f"zero MFU after a fit run: {pz}"
        assert pz["peaks"]["flops"] > 0
        train_progs = [p for p in pz["programs"]
                       if p["component"] == "train"]
        assert train_progs and any(
            p["cost_resolved"] and p["flops"] and p["dispatches"] > 0
            for p in train_progs), train_progs
        ph = pz["breakdown"]["train"]["phases"]
        assert ph.get("dispatch", 0) > 0, ph
        reg = observability.default_registry()
        loop_hist = reg.get("train_loop_dispatch_seconds")
        dispatched = loop_hist.sum if loop_hist is not None else 0.0
        phase_sum = ph.get("dispatch", 0.0) + ph.get("compile", 0.0)
        # the fit ran entirely through the fused loop: compile+dispatch
        # phases are the SAME dt values the dispatch histogram observed
        assert dispatched > 0 and \
            abs(phase_sum - dispatched) / dispatched < 0.05, \
            (phase_sum, dispatched, ph)
        drain_hist = reg.get("train_loop_drain_seconds")
        if drain_hist is not None and drain_hist.sum > 0:
            assert abs(ph.get("drain", 0.0) - drain_hist.sum) \
                / drain_hist.sum < 0.05, (ph, drain_hist.sum)
        with urllib.request.urlopen(base + "/metrics", timeout=30) as r:
            rescraped = r.read().decode()
        assert "perf_mfu" in rescraped and \
            "perf_flops_per_second" in rescraped, \
            "perf gauges missing from /metrics"

        # -- /memz after the fit: train trees attributed ---------------
        # (the engine half of the /memz acceptance — kv_pool split,
        # headroom, pool-exactness — runs in _engine_perf_section
        # while the engine is LIVE)
        with urllib.request.urlopen(base + "/memz", timeout=30) as r:
            mz = json.loads(r.read())
        assert mz["enabled"], mz
        assert mz["attributed_device_bytes"] > 0, \
            f"nothing attributed after a fit run: {mz}"
        owners = {r["owner"] for r in mz["owners"]}
        assert "train_params" in owners, owners
        # the residual line must EXIST either way: a real number on
        # backends with memory_stats, an explicit null + note on CPU
        assert "unattributed_bytes" in mz, sorted(mz)
        if mz["device"] is not None:
            assert mz["attributed_device_bytes"] <= \
                mz["device"]["bytes_in_use"], mz
            assert abs(mz["attributed_device_bytes"]
                       + mz["unattributed_bytes"]
                       - mz["device"]["bytes_in_use"]) < 1, mz
        else:
            assert mz["unattributed_bytes"] is None
            assert mz["unattributed_note"], mz
        assert mz["watermarks"], "no phase watermark recorded"

        # -- /perfz + /memz for a decode-slab LLMEngine run ------------
        _engine_perf_section(base)

        # -- /goodputz: the time ledger after fit + engine pass --------
        _goodput_section(base)
    finally:
        srv.stop()
    tracing.disable()

    # -- flight recorder: forced crash leaves a JSONL dump --------------
    crash_dir = os.path.join(outdir, "flight")
    crash_code = f"""
import jax; jax.config.update("jax_platforms", "cpu")
from paddle_tpu.observability import tracing, flight
tracing.enable()
flight.install_flight_recorder({crash_dir!r})
tracing.start_span("doomed.work", attrs={{"step": 7}})
raise RuntimeError("forced crash for the obs smoke gate")
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.run([sys.executable, "-c", crash_code], env=env,
                       capture_output=True, text=True, timeout=300,
                       cwd=os.path.dirname(os.path.dirname(
                           os.path.abspath(__file__))))
    assert p.returncode != 0, "forced crash exited 0"
    assert "forced crash" in p.stderr, p.stderr[-500:]
    dumps = [f for f in os.listdir(crash_dir) if f.endswith(".jsonl")]
    assert dumps, "flight recorder wrote no dump on unhandled exception"
    rows = [json.loads(ln)
            for ln in open(os.path.join(crash_dir, dumps[0]))]
    assert rows[0]["kind"] == "header" and rows[0]["reason"] == "exception"
    assert any(r.get("kind") == "span" and r.get("live") and
               r["name"] == "doomed.work" for r in rows), \
        "in-flight span missing from the crash dump"

    print(f"observability smoke OK: {len(events)} trace events "
          f"({len(span_evs)} spans), {len(text.splitlines())} prom "
          f"lines, {len(lines)} jsonl rows, debug server scraped, "
          f"/perfz mfu={pz['mfu']:.4g} (train+llm programs costed), "
          f"crash dump {dumps[0]} -> {outdir}")
    return 0


def _engine_perf_section(base: str) -> None:
    """Decode-slab half of the /perfz acceptance: a tiny LLMEngine at
    decode_ticks_per_dispatch=4 serves a couple of requests, then
    /perfz must show the fused-slab program with resolved cost, a
    nonzero llm MFU contribution, the decode phase in the breakdown,
    and the per-tenant served-FLOPs counter."""
    import paddle_tpu as pt
    from paddle_tpu import observability
    from paddle_tpu.inference.llm import LLMEngine
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt_config

    pt.seed(0)
    cfg = gpt_config("gpt2-small", num_layers=2, hidden_size=64,
                     num_heads=4, vocab_size=97,
                     max_position_embeddings=128,
                     hidden_dropout=0.0, attention_dropout=0.0)
    net = GPTForCausalLM(cfg)
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, 97, 8).tolist() for _ in range(3)]
    with LLMEngine(net, max_seqs=4, page_size=8, num_pages=32,
                   max_len=64, prefill_buckets=(8,),
                   decode_ticks_per_dispatch=4) as eng:
        outs = [eng.submit(p, max_new_tokens=24,
                           tenant="smoke").result(timeout=240)
                for p in prompts]
        # /perfz while the engine is LIVE (close() drops its program
        # entries from the registry; the windowed rates persist)
        with urllib.request.urlopen(base + "/perfz", timeout=60) as r:
            pz = json.loads(r.read())
        # /memz while the engine is LIVE: the kv_pool split must tile
        # the pool exactly (free + private + prefix_shared + scratch
        # == num_pages x page_bytes) and sit under the device total
        # where the backend reports one
        with urllib.request.urlopen(base + "/memz", timeout=60) as r:
            mz = json.loads(r.read())
        kv = {r["kind"]: r["bytes"] for r in mz["owners"]
              if r["owner"] == "kv_pool"}
        assert set(kv) == {"free", "private", "prefix_shared",
                           "scratch"}, kv
        page_bytes = eng._page_bytes
        assert sum(kv.values()) == eng.num_pages * page_bytes, \
            (kv, eng.num_pages, page_bytes)
        assert mz["headroom"] is not None and \
            mz["headroom"]["kv_pages_addable"] > 0, mz["headroom"]
        if mz["device"] is not None:
            assert mz["attributed_device_bytes"] <= \
                mz["device"]["bytes_in_use"], mz
        # the gauges ride the same read: the federation scrape path
        with urllib.request.urlopen(base + "/metrics", timeout=30) as r:
            scraped = r.read().decode()
        assert "mem_headroom_pages" in scraped and \
            "mem_bytes{" in scraped and \
            "mem_watermark_bytes" in scraped, \
            "mem gauges missing from /metrics"
    assert all(o["output_ids"] for o in outs)
    assert all(o.get("served_flops", 0) > 0 for o in outs), outs
    slabs = [p for p in pz["programs"]
             if p["component"] == "llm" and p["kind"] == "decode_loop"]
    assert slabs and any(p["cost_resolved"] and p["dispatches"] > 0
                         for p in slabs), pz["programs"]
    llm_ph = pz["breakdown"].get("llm", {}).get("phases", {})
    assert llm_ph.get("decode", 0) > 0, pz["breakdown"]
    snap = observability.default_registry().snapshot()
    assert snap.get('llm_served_flops_total{tenant="smoke"}', 0) > 0, \
        {k: v for k, v in snap.items() if "served" in k}


def _goodput_section(base: str) -> None:
    """Tentpole acceptance for the time ledger: after the fit run AND
    the decode-slab engine pass, ``/goodputz`` must show nonzero
    productive seconds, a reconciliation line whose buckets +
    unattributed sum exactly to elapsed, and device-time buckets that
    reproduce the totals the perf instruments measured — the ledger
    rides the SAME dt values (train: the fused-loop dispatch
    histogram; llm: the /perfz breakdown phases), so on this serial
    workload the interval union equals the sums."""
    from paddle_tpu import observability

    code, gz = _get_json(base + "/goodputz")
    assert code == 200
    assert gz["enabled"] and gz["armed"], gz
    assert gz["buckets"]["productive"] > 0, \
        f"zero productive time after a fit + engine run: {gz['buckets']}"
    rec = gz["reconciliation"]
    assert abs(rec["attributed_s"] + rec["unattributed_s"]
               - rec["elapsed_s"]) < 1e-6, rec
    assert abs(rec["residual_s"]) < 1e-6, rec
    # device-time buckets vs the perf instruments' totals
    reg = observability.default_registry()
    loop_hist = reg.get("train_loop_dispatch_seconds")
    dispatched = loop_hist.sum if loop_hist is not None else 0.0
    code, pz = _get_json(base + "/perfz")
    llm_ph = pz["breakdown"].get("llm", {}).get("phases", {})
    expect = dispatched + sum(llm_ph.values())
    got = gz["buckets"]["productive"] + gz["buckets"]["compile"]
    assert expect > 0 and abs(got - expect) / expect < 0.05, \
        (got, expect, gz["buckets"], llm_ph)
    # the gauges ride the /metrics prescrape (the federation surface)
    with urllib.request.urlopen(base + "/metrics", timeout=30) as r:
        scraped = r.read().decode()
    assert "goodput_fraction" in scraped, \
        "goodput_fraction gauge missing from /metrics"
    assert 'badput_seconds_total{cause=' in scraped, \
        "badput_seconds_total counters missing from /metrics"


def _get_json(url: str, timeout: float = 30.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def fleet_main(outdir: str = "/tmp/pt_obs_fleet_smoke") -> int:
    import time

    from paddle_tpu.observability import server as debug_server
    from paddle_tpu.observability import tracing
    from paddle_tpu.serving import HTTPReplica, Router, spawn_replica
    from tools.trace_merge import load_source, merge_chrome_trace

    os.makedirs(outdir, exist_ok=True)
    obs_dir = os.path.join(outdir, "obs")
    cache_dir = os.path.join(outdir, "xla_cache")
    os.makedirs(cache_dir, exist_ok=True)
    model = {"vocab": 97, "layers": 2, "hidden": 64, "heads": 4,
             "max_pos": 96, "model_seed": 0, "tracing": True,
             "obs_dir": obs_dir, "cache_dir": cache_dir,
             "engine": {"seed": 0, "max_pending": 64}}
    names = ("r0", "r1")
    tracing.enable()
    # setup happens INSIDE the try: a spawn/warm-up failure must
    # still kill whatever replica subprocesses already exist
    procs, infos = {}, {}
    router, srv = None, None
    try:
        # staggered spawn: r0 warms the shared compile cache for r1
        procs["r0"], infos["r0"] = spawn_replica(
            dict(model, name="r0"), timeout=240)
        HTTPReplica(infos["r0"]["generate"],
                    infos["r0"]["healthz"]).submit([1, 2, 3],
                                                   max_new_tokens=2)
        procs["r1"], infos["r1"] = spawn_replica(
            dict(model, name="r1"), timeout=240)
        router = Router(
            {n: HTTPReplica(infos[n]["generate"], infos[n]["healthz"],
                            metrics_url=infos[n]["metrics"])
             for n in names},
            health_poll_interval=0.2, page_size=4, affinity_pages=2)
        srv = debug_server.DebugServer(port=0).start()
        base = f"http://127.0.0.1:{srv.port}"
        # hole-not-zero over HTTP: before any stream verification this
        # process has no drift table — /driftz must 404, not serve
        # an all-zero (falsely clean) body
        try:
            _get_json(base + "/driftz")
            raise AssertionError("/driftz answered before any stream "
                                 "verification armed the auditor")
        except urllib.error.HTTPError as e:
            assert e.code == 404, f"/driftz pre-arm status {e.code}"
        # shadow every request below so the drift surfaces have data
        # (the replicas themselves never record a verdict here — their
        # /driftz stays a 404 hole, pinned further down)
        from paddle_tpu.core import flags as _flags
        _flags.set_flags({"audit_shadow_rate": 1.0})
        from paddle_tpu.serving.router import (affinity_key,
                                               rendezvous_pick)
        import numpy as np

        def prompt_for(target, length=12, seed=0):
            # rejection-sample a prompt whose affinity preference is
            # `target` — BOTH replicas must serve traffic for the
            # per-replica federation assertions to mean anything
            rng = np.random.RandomState(seed)
            while True:
                p = rng.randint(0, 97, length).tolist()
                key = affinity_key(p, router.page_size,
                                   router.affinity_pages)
                if rendezvous_pick(key, names) == target:
                    return p

        outs = [router.submit(prompt_for(n, seed=i), max_new_tokens=4)
                .result(timeout=240)
                for i, n in enumerate(names * 2)]
        assert all(o["output_ids"] for o in outs)
        assert {o["replica"] for o in outs} == set(names), outs
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            code, fz = _get_json(base + "/fleetz")
            fleet = next(iter(fz["fleets"].values()))
            reps = fleet["replicas"]
            # wait for a scrape taken AFTER the traffic: EACH
            # replica's own completed work must be visible (an "up"
            # verdict can come from a pre-traffic scrape cycle)
            if all(n in reps and (reps[n].get("metrics") or {})
                   .get("requests_completed") for n in names):
                break
            time.sleep(0.2)
        else:
            raise AssertionError(f"/fleetz never aggregated both "
                                 f"replicas' traffic: {fz}")
        # -- /fleetz: per-replica data + computed aggregates ------------
        agg = fleet["aggregates"]
        assert agg["replicas_scraped"] == 2, agg
        assert any((reps[n]["metrics"] or {}).get("requests_completed")
                   for n in names), reps
        # -- /metrics: replica-labeled federated series -----------------
        with urllib.request.urlopen(base + "/metrics", timeout=30) as r:
            scraped = r.read().decode()
        for n in names:
            assert f'fleet_llm_requests_completed{{replica="{n}"}}' \
                in scraped, f"federated series for {n} missing"
        assert "fleet_prefix_cache_hit_rate" in scraped
        assert "router_dispatches_total" in scraped
        # perf federation: replica perf_* gauges ride the same scrape
        # and aggregate into fleet_mfu (holes for down replicas —
        # pinned unit-side in tests/test_perf_observability.py)
        assert 'fleet_perf_mfu{replica=' in scraped, \
            "replica perf gauges not federated"
        assert "fleet_mfu " in scraped or "fleet_mfu{" in scraped, \
            "fleet_mfu aggregate missing"
        # memory federation: each replica's pool headroom rides the
        # same scrape and sums into fleet_mem_headroom_pages (holes
        # for down replicas — pinned unit-side in
        # tests/test_memory_observability.py)
        assert 'fleet_mem_headroom_pages{replica=' in scraped, \
            "replica mem_headroom_pages not federated"
        assert "fleet_headroom_pages " in scraped, \
            "fleet_headroom_pages aggregate missing"
        # goodput federation: both replicas served traffic, so both
        # time ledgers armed and export goodput_fraction — the fleet
        # aggregate must be a mean over BOTH (auditable denominator),
        # with the per-replica badput causes federated alongside
        assert "fleet_goodput_fraction " in scraped, \
            "fleet_goodput_fraction aggregate missing"
        assert "fleet_goodput_replicas 2" in scraped, \
            "fleet_goodput_fraction mean must cover both replicas"
        assert 'fleet_badput_seconds_total{replica=' in scraped, \
            "replica badput causes not federated"
        for n in names:
            assert (reps[n].get("metrics") or {}).get(
                "goodput_fraction") is not None, \
                f"/fleetz missing {n}'s goodput_fraction: {reps[n]}"
        # warming-replica-is-a-hole: a replica that is UP but has not
        # armed its time ledger (no goodput_fraction series yet) must
        # be ABSENT from the fleet mean, never a zero dragging it down
        from paddle_tpu.observability.metrics import MetricRegistry
        from paddle_tpu.serving.fleet import FleetScraper
        with urllib.request.urlopen(infos["r0"]["metrics"],
                                    timeout=30) as r:
            r0_text = r.read().decode()
        assert "goodput_fraction" in r0_text, \
            "armed replica exports no goodput_fraction"
        fs = FleetScraper(registry=MetricRegistry())
        fs.record("armed", r0_text)
        fs.record("warming", "llm_requests_completed 0\n")
        hole_agg = fs.aggregates()
        assert hole_agg["goodput_replicas"] == 1, hole_agg
        armed_frac = hole_agg["goodput_fraction"]
        assert armed_frac is not None and armed_frac > 0, hole_agg
        # -- stream-integrity drift surfaces ----------------------------
        # every request above was shadow re-executed (rate 1.0): the
        # router-side drift table armed, /driftz serves it, and the
        # fleet must prove itself CLEAN (zero divergences)
        deadline = time.monotonic() + 90
        dz = None
        while time.monotonic() < deadline:
            try:
                _code, dz = _get_json(base + "/driftz")
                if dz["drift"]["audit"]["totals"]["verified"] \
                        >= len(outs):
                    break
            except urllib.error.HTTPError:
                pass
            time.sleep(0.2)
        else:
            raise AssertionError(
                f"/driftz never accumulated {len(outs)} shadow "
                f"verdicts: {dz}")
        assert dz["drift"]["audit"]["enabled"] is True, dz
        assert dz["drift"]["audit"]["totals"]["diverged"] == 0, dz
        # the drift counters mint at first record and export locally…
        with urllib.request.urlopen(base + "/metrics", timeout=30) as r:
            scraped = r.read().decode()
        assert "drift_verified_total" in scraped, \
            "drift_verified_total missing after shadow verdicts"
        # …but NEITHER replica ever recorded a verdict: their /driftz
        # is a 404 and the fleet_drift_* aggregate reads them as holes
        # (denominator 0), never as zero-divergence evidence
        for n in names:
            try:
                _get_json(infos[n]["driftz"])
                raise AssertionError(
                    f"replica {n} served /driftz without recording")
            except urllib.error.HTTPError as e:
                assert e.code == 404, f"{n} /driftz status {e.code}"
        assert "fleet_drift_replicas 0" in scraped, \
            "never-armed replicas must be a hole in fleet_drift_*"
        assert 'fleet_drift_verified_total{replica=' not in scraped, \
            "replica exported drift series it never recorded"
        # an ARMED replica's counters do federate — and a replica
        # without them stays out of both sums and the denominator
        fs2 = FleetScraper(registry=MetricRegistry())
        fs2.record("armed", "drift_verified_total 5\n"
                   'drift_divergence_total{kind="shadow"} 1\n')
        fs2.record("hole", "llm_requests_completed 0\n")
        agg2 = fs2.aggregates()
        assert agg2["drift_replicas"] == 1, agg2
        assert agg2["drift_verified"] == 5, agg2
        assert agg2["drift_divergences"] == 1, agg2
        # -- brownout federation is hole-not-zero ------------------------
        # no replica in this smoke runs an overload controller, so the
        # fleet MAX has an explicitly empty denominator — a fleet that
        # exports level 0 here would be claiming "all clear" on the
        # strength of replicas that never took the measurement
        assert "fleet_brownout_replicas 0" in scraped, \
            "controller-less replicas must be a hole in " \
            "fleet_brownout_level, never level-0 evidence"
        fs3 = FleetScraper(registry=MetricRegistry())
        fs3.record("browned", "brownout_level 2\n")
        fs3.record("hole", "llm_requests_completed 0\n")
        agg3 = fs3.aggregates()
        assert agg3["brownout_replicas"] == 1, agg3
        assert agg3["brownout_level"] == 2, agg3   # MAX over UP, not mean
        _flags.set_flags({"audit_shadow_rate": 0.0})
        # -- ONE cross-process trace ------------------------------------
        out = outs[0]
        tid = out["trace_id"]
        assert tid and len(tid) == 32, out
        local = [s for s in tracing.finished_spans()
                 if s["trace_id"] == tid]
        lnames = {s["name"] for s in local}
        assert {"router.request", "router.dispatch"} <= lnames, lnames
        dispatch = [s for s in local if s["name"] == "router.dispatch"]
        replica = out["replica"]
        code, tz = _get_json(
            infos[replica]["tracez"] + f"?trace_id={tid}")
        rspans = {s["name"]: s for s in tz["finished"]}
        assert "llm.request" in rspans, (
            f"replica {replica} has no llm.request for trace {tid}: "
            f"{sorted(rspans)}")
        req_span = rspans["llm.request"]
        assert req_span["trace_id"] == tid
        assert req_span["parent_id"] in {d["span_id"] for d in dispatch}
        assert req_span["attrs"].get("remote_parent") is True
        # the replica-side phases share the trace too
        assert any(n.startswith("llm.") and n != "llm.request"
                   for n in rspans), sorted(rspans)
        # -- merged timeline via trace_merge ----------------------------
        sources = {"router": load_source(base + "/tracez"),
                   **{n: load_source(infos[n]["tracez"])
                      for n in names}}
        merged = merge_chrome_trace(
            sources, os.path.join(outdir, "merged.json"), trace_id=tid)
        assert merged["spans"] >= 3, merged
        assert merged["trace_ids"] == 1, merged
        with open(merged["path"]) as f:
            chrome = json.load(f)
        pnames = {e["args"]["name"] for e in chrome["traceEvents"]
                  if e["name"] == "process_name"}
        assert {"router", "r0", "r1"} <= pnames, pnames
        # -- /sloz answers (burn-rate movement is chaos-soak-asserted) --
        code, sz = _get_json(base + "/sloz")
        assert code == 200
        classes = next(iter(sz["slo"].values()))["classes"]
        assert "default" in classes, classes
        assert classes["default"]["windows"]["short"]["requests"] > 0
        # -- flight/JSONL artifacts landed under the obs_dir knob -------
        for n in names:
            jl = os.path.join(obs_dir, n, "metrics.jsonl")
            assert os.path.exists(jl), f"{n} JSONL reporter wrote nothing"
        # -- PR-4 regression criterion: disabled tracing = one flag
        # check. Structural half: the shared noop comes back (no Span,
        # no table write). Timing half: a generous per-call bound that
        # still catches accidentally creating real spans.
        tracing.disable()
        sp = tracing.start_span("ghost")
        assert sp is tracing.NOOP_SPAN
        n_calls = 200_000
        t0 = time.perf_counter()
        for _ in range(n_calls):
            tracing.start_span("ghost")
        per_call = (time.perf_counter() - t0) / n_calls
        assert per_call < 5e-6, (
            f"disabled start_span costs {per_call * 1e6:.2f}us/call — "
            f"more than a flag check")
    finally:
        tracing.disable()
        if router is not None:
            router.close()
        if srv is not None:
            srv.stop()
        for p in procs.values():
            if p.poll() is None:
                p.kill()
                p.wait(timeout=30)
    print(f"fleet observability smoke OK: 2 replicas federated, "
          f"cross-process trace {tid} merged "
          f"({merged['spans']} spans), disabled tracing "
          f"{per_call * 1e9:.0f}ns/call -> {outdir}")
    return 0


if __name__ == "__main__":
    argv = sys.argv[1:]
    fleet = "--fleet" in argv
    argv = [a for a in argv if a != "--fleet"]
    sys.exit(fleet_main(*argv) if fleet else main(*argv))
