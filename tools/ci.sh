#!/usr/bin/env bash
# CI gate (analog of the reference's paddle_build.sh test stages +
# tools/ci_model_benchmark.sh): suite on the virtual 8-device CPU mesh,
# the driver's multichip dry-runs, a CPU bench smoke, and an
# install-from-wheel import check.
set -euo pipefail
cd "$(dirname "$0")/.."

# --smoke: fast tier only — skips @pytest.mark.slow except tests ALSO
# marked @pytest.mark.smoke (representative picks inside all-slow files,
# so pipeline/optest keep smoke coverage); full suite remains the merge gate.
PYTEST_ARGS=()
TIER=""
if [[ "${1:-}" == "--smoke" ]]; then
  PYTEST_ARGS=(-m "not slow or smoke")
  TIER=" [smoke]"
fi

echo "== unit + integration suite (8-device CPU mesh)${TIER}"
python -m pytest tests/ -q -o faulthandler_timeout=300 "${PYTEST_ARGS[@]}"

echo "== multichip dryrun (n=8 and n=4)"
python -c "import jax; jax.config.update('jax_platforms','cpu'); \
jax.config.update('jax_num_cpu_devices', 8); \
import __graft_entry__ as g; g.dryrun_multichip(8)"
python -c "import jax; jax.config.update('jax_platforms','cpu'); \
jax.config.update('jax_num_cpu_devices', 8); \
import __graft_entry__ as g; g.dryrun_multichip(4)"

echo "== observability smoke (train loop -> prometheus + chrome trace"
echo "   + jsonl + debug-server scrape + flight-recorder crash dump)"
python tools/obs_smoke.py "$(mktemp -d)"

echo "== fleet observability smoke (K=2 replicas -> /fleetz federation"
echo "   + one cross-process trace + disabled-tracing flag-check bound)"
# router + 2 spawned replicas: /fleetz aggregates replica-labeled
# series, a request's router.dispatch -> llm.request spans share ONE
# trace_id over real HTTP (fetched back via /tracez?trace_id=),
# trace_merge joins the tables, and disabled tracing still costs one
# flag check (time-bounded). ISSUE-19 rider: the stream auditor arms
# on router traffic — /driftz 404s pre-arm, reports verified chains
# post-traffic, and fleet_drift_* federates with hole-not-zero
# semantics (a never-armed replica is a hole, not a clean zero)
python tools/obs_smoke.py "$(mktemp -d)" --fleet

echo "== llm serving smoke (prefix cache + chunked ragged prefill"
echo "   + decode-ticks sweep + ragged MIXED-TICK gate)"
# 4 shared-prefix prompts through the engine: asserts nonzero cache
# hits, cache-on == cache-off generations, a clean shutdown, the
# fused decode-slab sweep, and the mixed-tick gate (one ragged
# prefill+decode slab token-identical to the legacy two-op tick loop
# at strictly fewer host dispatches)
python tools/llm_bench.py --ci

echo "== kv-dtype bench (bf16 vs int8 KV pool at fixed HBM)"
# quantized-tolerance gate: int8 retains >=1.8x bf16's prefix-cache
# pages at the same pool HBM budget, int8 streams are internally
# exact (cache on/off identical — deterministic quantization) and
# agree with the f32 pool within the documented tolerance; ledger
# rows are kv_dtype-keyed so int8/bf16 never gate against each other
python tools/llm_bench.py --ci --kv-dtype

echo "== speculative slab bench (on-device draft-K/verify-1 rounds)"
# tentpole gate: the spec slab sweep (K x kv_dtype x prefix cache)
# must emit greedy tokens identical to a target-only engine in every
# combination and pay >=2x fewer host dispatches per emitted token
# than the legacy inline spec path at K=4; per-combination
# bench_ledger/v1 rows key draft K + cache state into the series so
# K=2 never gates against K=8
python tools/llm_bench.py --ci --spec

echo "== chaos soak (seeded fault injection -> hardened semantics)"
# engine under injected device faults + deadlines/shed/cancel storm,
# SIGKILL mid-checkpoint-save, and an io.worker fault escalating to a
# flight-recorder dump; fails on any hung future, leaked KV page,
# unreplayable fault schedule, or unrestorable checkpoint
python tools/chaos_soak.py --ci

echo "== fused-slab chaos soak (decode_ticks_per_dispatch=8"
echo "   + mixed-tick/int8 riders)"
# engine.slab kill storm at the fused slab dispatch + cancel/deadline
# storms landing mid-slab: every future resolves, retried streams are
# token-identical to a fault-free reference engine, zero KV-page
# leaks, fault schedule replays from seed. ISSUE-15 riders: the same
# storm through the ragged MIXED tick on an int8 pool, and the
# page-pressure storm repeated at fixed HBM with kv_dtype=int8
# (>=1.8x usable pages, 2x slots before slab-shrink engages,
# scale_table ledger row, headroom gauge semantics re-pinned)
python tools/chaos_soak.py --ci --slab

echo "== fleet chaos soak (K=3 replicas, SIGKILL mid-decode -> failover)"
# router + 3 spawned replica subprocesses over TCPStore membership:
# injected faults drain one replica (no new admissions within a poll
# interval; POST /reset_health recovers it), SIGKILL mid-decode loses
# zero requests (token-identical failover), the breaker walks
# open -> half-open -> closed across a respawn; /fleetz aggregates the
# fleet and a deadline-miss storm moves /sloz burn rates + latches the
# breach; failures attach a merged cross-process trace. Then the
# disagg phase: a prefill-pool replica feeds two decode replicas via
# KV-page migration — a SIGKILLed prefill replica and a corrupted
# in-flight page both degrade to local recompute (token-identical,
# zero pages leaked). Then the ISSUE-19 drift storm: a seeded
# audit.flip corrupts one emitted token BEFORE chain extension (the
# corrupted stream is self-consistent, so only chain-vs-chain checks
# catch it) — the shadow re-execution names the exact divergent
# position, fires ONE flight dump carrying both digests + knob
# fingerprints, a mid-decode device retry is verified prefix-intact,
# clean storms report zero divergences, and the fault schedule
# replays from seed
python tools/chaos_soak.py --ci --fleet

echo "== autoscale chaos soak (SLO-driven scale-out/in over a live fleet)"
# the ISSUE-13 gate, half 1: a gold-class deadline-miss storm trips
# both burn windows -> scale-out (first spawn attempt dies on the
# seeded autoscale.spawn fault; the retry absorbs it with no ghost
# capacity); SIGKILL of the autoscaled replica mid-decode loses zero
# requests (nonce-pinned token-identical failover) and respawns as a
# REPLACEMENT, not a scale-out; a seeded autoscale.drain fault expires
# the scale-in drain deadline with stragglers in flight, which
# complete token-identically on a sibling; membership is withdrawn
# immediately; both sites replay from seed. Failures attach the
# merged cross-process trace next to the seed + replay command.
python tools/chaos_soak.py --ci --autoscale

echo "== overload chaos soak (seeded 3x burst storm -> brownout ladder)"
# the ISSUE-20 gate, half 1: a burst storm over a static K=2 fleet
# engages the brownout ladder (level >= 1, one-level moves only),
# bronze is shed TYPED (OverloadShed with retry_after_s) while gold
# loses ZERO requests, a seeded overload.estimate fault turns a
# wildly-wrong prediction into visible shed/miss verdicts (never a
# hang), a seeded overload.step fault forces a spurious transition the
# hysteresis walks back, and the ladder returns to level 0 after the
# storm; both fault sites replay from seed
python tools/chaos_soak.py --ci --overload

echo "== overload bench (3x burst over static K=2: brownout off vs on)"
# the ISSUE-20 gate, half 2: the same un-scalable burst tape with the
# controller off and on — brownout must hold the gold deadline-hit
# ratio at the UN-overloaded baseline (zero gold lost) and STRICTLY
# cut the wasted-work fraction (deadline misses that burned full
# service time, converted into cheap typed sheds); the comparison
# lands in BENCH_LEDGER.jsonl as llm_overload_* rows
python tools/llm_bench.py --ci --overload

echo "== storm bench (diurnal+burst: static K=3 vs autoscaled fleet)"
# the ISSUE-13 gate, half 2: the millions-of-users-shaped storm
# (shared prefixes, mixed tenants/SLO classes) must trigger >=1
# scale-out and >=1 scale-in with zero lost requests, hold the
# gold-class deadline-hit ratio at least as well as static K=3, and
# spend STRICTLY fewer replica-seconds; the comparison lands in
# BENCH_LEDGER.jsonl as one bench_ledger/v1 row
python tools/llm_bench.py --ci --storm

echo "== train chaos soak (kill-anywhere -> bit-identical resume"
echo "   + poisoned-stream numeric-guard gate)"
# Model.fit with async full-state checkpoints + resume="auto":
# seeded SIGKILLs in the STEP/SNAPSHOT/COMMIT/GC windows plus a
# SIGTERM emergency-flush pass, relaunch to completion, combined loss
# stream bit-identical to the uninterrupted baseline at
# steps_per_loop 1 and 4; async-save stall bounded by snapshot time;
# a byte-rotted newest checkpoint quarantines and falls back without
# ever surfacing through latest_step(); ckpt.* fault sites replay
# from seed. Then the poisoned-stream phase: seeded data.poison /
# grad.nonfinite schedules against the on-device NumericGuard —
# skip-policy final params byte-identical to a clean run minus the
# tripped steps at K in {1,4}, rollback restores a verified step and
# completes, guard-off program carries zero guard ops (failures print
# the seed + replay command and attach a flight dump)
python tools/chaos_soak.py --ci --train

echo "== fleet serving bench (prefix-affinity vs round-robin at K=3)"
# asserts aggregate prefix-cache hit rate with affinity routing is
# >= 1.5x round-robin on the shared-prefix workload
python tools/llm_bench.py --ci --fleet

echo "== disaggregated prefill/decode bench (unified K=3 vs 1P/2D)"
# mixed storm on int8 pools: long uncached prompts migrate as
# digest-verified KV-page runs to the decode pool — short-request
# TTFT p99 must improve at equal aggregate slots, a single-replica
# probe's p99 inter-token gap must be strictly lower with imported
# pages than with local prefills, and generations stay
# token-identical across fleets and probe passes
python tools/llm_bench.py --ci --fleet --disagg

echo "== fused train-loop parity smoke (K=1 vs K=4 bit-identical)"
python tools/train_loop_smoke.py

echo "== fused train-loop dispatch sweep (CPU)"
PT_BENCH_FORCE_CPU=1 python bench.py --steps-per-loop 1,8

echo "== bench smoke (CPU backend)"
# PT_BENCH_FORCE_CPU: run the measuring child directly on CPU — the
# default orchestrator mode would spend its TPU probe windows first
PT_BENCH_FORCE_CPU=1 python bench.py

echo "== perf ledger regression gate (BENCH_LEDGER.jsonl trajectory)"
# the bench steps above appended this run's canonical rows; the gate
# fails LOUDLY if the trajectory is empty/unreadable or any series
# regressed past tolerance (wide on CPU, tight on real chips). Rows
# carry the optional drift_divergences field when the stream auditor
# armed during a bench (absent = nobody checked, 0 = checked clean)
python tools/bench_ledger.py --ci

echo "== wheel build + import smoke"
tmp=$(mktemp -d)
pip wheel . --no-deps --no-build-isolation -w "$tmp" -q
ls "$tmp"/*.whl
echo "CI OK"
