"""Long-sequence pipeline memory study (VERDICT r4 item 8).

Question: the SPMD pipeline is GPipe-with-remat — its backward holds
one BOUNDARY activation per tick, (m·v + pp - 1) of them, each
[mb, s, h]. At s >= 8k does that beat a 1F1B-style bounded schedule?

Answer measured here: the bounded-activation schedule is ALREADY
EXPRESSIBLE as wave-accumulation — run the pipeline scan on a WAVE of
w microbatches, jax.grad per wave, accumulate grads across m/w waves
inside one jitted step (lax.fori or an unrolled loop; the trainer's
gradient-accumulation facility composes the same way across steps).
Per-wave backward residuals are freed before the next wave, so the
boundary set is (w·v + pp - 1) per rank — independent of the total
microbatch count, which is exactly 1F1B's bounded-memory property
(1F1B holds <= pp in-flight microbatches; a wave of w = pp matches it)
— while the bubble grows from (pp-1)/(m·v+pp-1) to per-wave
(pp-1)/(w·v+pp-1), the same memory/bubble trade 1F1B's schedule makes
against steady-state GPipe.

Run: python tools/pp_longseq_memory.py  (8-device CPU mesh)
Prints per-device temp bytes per (s, schedule) and the ratio.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                           " --xla_force_host_platform_device_count=8"
                           ).strip()
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import paddle_tpu as pt  # noqa: E402
from paddle_tpu import nn, parallel  # noqa: E402
from paddle_tpu.cost_model import memory_profile  # noqa: E402
from paddle_tpu.nn.layer import functional_call, split_state  # noqa: E402
from paddle_tpu.parallel.pipeline import (LayerDesc,  # noqa: E402
                                          PipelineLayer,
                                          PipelineParallel)

H = 64
PP = 4


class SeqBlock(nn.Layer):
    """[mb, s, H] -> [mb, s, H] MLP block: internals are recomputed by
    the chunk remat, so compiled temps expose exactly the BOUNDARY
    activation story the schedules differ on."""

    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(H, 4 * H)
        self.fc2 = nn.Linear(4 * H, H)

    def forward(self, x):
        return x + self.fc2(jax.nn.gelu(self.fc1(x)))


def temp_bytes(s: int, total_mb: int, wave: int) -> int:
    """Per-device temp bytes of one compiled train step processing
    ``total_mb`` microbatches of [1, s, H] through a pp=4 pipeline,
    ``wave`` microbatches per pipeline scan, grads accumulated across
    waves inside the step."""
    pt.seed(0)
    mesh = parallel.init_mesh(pp=PP, dp=8 // PP)
    try:
        pipe = PipelineLayer([LayerDesc(SeqBlock) for _ in range(PP)],
                             num_stages=PP)
        pp_layer = PipelineParallel(pipe, num_microbatches=wave,
                                    mesh=mesh)
        params, buffers = split_state(pp_layer)
        x = jnp.zeros((total_mb, s, H), jnp.float32)
        n_waves = total_mb // wave

        def wave_loss(p, xw):
            out, _ = functional_call(pp_layer, p, buffers, xw)
            return (out ** 2).mean()

        def step(p, x):
            def body(i, acc):
                xw = jax.lax.dynamic_slice_in_dim(x, i * wave, wave, 0)
                g = jax.grad(wave_loss)(p, xw)
                return jax.tree_util.tree_map(jnp.add, acc, g)
            zero = jax.tree_util.tree_map(jnp.zeros_like, p)
            g = jax.lax.fori_loop(0, n_waves, body, zero)
            return jax.tree_util.tree_map(
                lambda gg: gg / n_waves, g)

        prof = memory_profile(step, (params, x))
        return prof.temp_bytes
    finally:
        parallel.set_mesh(None)


def main():
    total_mb = 16
    rows = []
    for s in (4096, 8192, 16384):
        full = temp_bytes(s, total_mb, wave=total_mb)  # one scan
        waved = temp_bytes(s, total_mb, wave=PP)       # bounded
        rows.append((s, full, waved, waved / full))
        print(f"s={s:6d}  single-scan {full / 2**20:9.1f} MiB   "
              f"wave={PP} accum {waved / 2**20:9.1f} MiB   "
              f"ratio {waved / full:.2f}", flush=True)
    print("\nboundary model: single scan holds (m*v+pp-1)="
          f"{total_mb + PP - 1} boundaries; wave={PP} holds "
          f"(w*v+pp-1)={2 * PP - 1} per wave -> predicted ratio "
          f"{(2 * PP - 1) / (total_mb + PP - 1):.2f}")
    return rows


if __name__ == "__main__":
    main()
