"""BASELINE config-4 feasibility study, compile-only (VERDICT r3 ask #7).

AOT-compiles the REAL GPT-3-1.3B training step (seq 2048, remat'd
trunk, fused vocab loss, AdamW) on virtual CPU meshes for candidate
dp/fsdp/tp/pp layouts and tables XLA's compiled per-device memory
analysis against the v5e HBM budget (16 GiB x 0.85 headroom). This is
the measured counterpart of parallel/planner.py's analytic search —
the same closed loop verify_plan runs per-model, here swept across the
layout space at the baseline's flagship scale (ref: BASELINE config 4
"GPT-3 1.3B Fleet hybrid TP+PP+DP";
/root/reference/python/paddle/distributed/auto_parallel/planner_v2.py
searches dist-attrs analytically and never compiles candidates).

Each layout runs in a fresh subprocess so the virtual device count can
differ (jax_num_cpu_devices is a pre-first-use config). Compiling 1.3B
on one CPU core takes minutes per layout — run in background:

    python tools/feasibility_1p3b.py [--out FEASIBILITY_1P3B.json]
    python tools/feasibility_1p3b.py --child '{"devices":8,...}'
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_GiB = float(1 << 30)
V5E_BUDGET = 16 * _GiB * 0.85

# (devices, axes, global_batch, microbatches-for-pp)
# global batch keeps 8 sequences per data-parallel shard, the
# batch-sweep's best-throughput point at seq 2048 scale
LAYOUTS = [
    # v5e-8
    (8, {"fsdp": 8}, 64, 0),
    (8, {"fsdp": 4, "tp": 2}, 64, 0),
    (8, {"dp": 2, "fsdp": 4}, 64, 0),
    (8, {"tp": 8}, 8, 0),            # pure-TP: one data shard
    (8, {"dp": 8}, 64, 0),           # expected OOM: full state per chip
    (8, {"pp": 2, "tp": 2, "dp": 2}, 16, 2),   # config-4 hybrid shape
    # v5e-16
    (16, {"fsdp": 8, "tp": 2}, 128, 0),
    (16, {"pp": 2, "fsdp": 4, "dp": 2}, 32, 2),
    # v5e-64
    (64, {"dp": 4, "fsdp": 8, "tp": 2}, 512, 0),
]

# The scale ladder (PERF.md "the scale ladder, measured"): abstract
# rows — ShapeDtypeStruct state, so any model size compiles without
# materializing weights. Rerun with --ladder.
LADDER = [
    {"devices": 8, "axes": {"pp": 4, "tp": 2}, "global_batch": 16,
     "microbatches": 4, "model": "gpt3-1.3b", "abstract": True},
    {"devices": 64, "axes": {"pp": 4, "tp": 2, "dp": 8},
     "global_batch": 64, "microbatches": 4, "model": "gpt3-1.3b",
     "abstract": True},
    {"devices": 8, "axes": {"pp": 4, "tp": 2}, "global_batch": 8,
     "microbatches": 4, "model": "gpt3-6.7b", "abstract": True},
    {"devices": 16, "axes": {"pp": 8, "tp": 2}, "global_batch": 8,
     "microbatches": 8, "model": "gpt3-6.7b", "abstract": True},
    {"devices": 16, "axes": {"fsdp": 8, "tp": 2}, "global_batch": 32,
     "microbatches": 0, "model": "gpt3-6.7b", "abstract": True},
    {"devices": 64, "axes": {"pp": 8, "tp": 2, "dp": 4},
     "global_batch": 32, "microbatches": 8, "model": "gpt3-6.7b",
     "abstract": True},
    {"devices": 64, "axes": {"pp": 8, "tp": 8}, "global_batch": 8,
     "microbatches": 8, "model": "gpt3-13b", "abstract": True},
]


def _abstract_state(model, net, mesh):
    """Shape-only state trees with the REAL shardings attached — the
    study's big-model rows must not materialize 10s of GB of f32 state
    on the build host (the 6.7B/16-device row hit 99% of host RAM and
    had to be killed; the reference plans on the static Program, which
    never materializes weights either). jax.jit.lower accepts
    ShapeDtypeStructs, so compilation + memory analysis are identical
    to the materialized path."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec
    from jax.tree_util import DictKey, tree_map_with_path

    from paddle_tpu.nn.layer import split_state
    from paddle_tpu.parallel.sharding import shard_spec

    meta = net.param_meta()

    def shard_of(name, shape):
        return shard_spec(name, shape, meta, mesh)

    params_all, buffers = split_state(net)
    trainable = {k: v for k, v in params_all.items()
                 if meta[k].trainable}
    frozen = {k: v for k, v in params_all.items()
              if not meta[k].trainable}

    def sds_tree(tree):
        return {k: jax.ShapeDtypeStruct(tuple(v.shape), v.dtype,
                                        sharding=shard_of(k, v.shape))
                for k, v in tree.items()}

    p_sds = sds_tree(trainable)
    f_sds = sds_tree(frozen)
    b_sds = sds_tree(buffers)
    opt_shape = jax.eval_shape(model._optimizer.init_state, p_sds)

    def reshard(path, leaf):
        # moments are keyed by the param name they mirror; eval_shape
        # drops shardings, so re-attach from the matching param
        name = None
        for k in reversed(path):
            if isinstance(k, DictKey) and k.key in p_sds:
                name = k.key
                break
        sh = shard_of(name, leaf.shape) if name else \
            NamedSharding(mesh.mesh, PartitionSpec())
        return jax.ShapeDtypeStruct(tuple(leaf.shape), leaf.dtype,
                                    sharding=sh)

    o_sds = tree_map_with_path(reshard, opt_shape)
    return p_sds, f_sds, o_sds, b_sds


def run_child(spec: dict) -> dict:
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", int(spec["devices"]))

    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu import parallel
    from paddle_tpu.core import rng as rng_mod
    from paddle_tpu.models.gpt import (GPTForCausalLM, GPTForCausalLMPipe,
                                       GPTFusedPretrainingCriterion,
                                       gpt_config)

    axes = dict(spec["axes"])
    gb = int(spec["global_batch"])
    micro = int(spec.get("microbatches", 0))
    seq = 2048
    # refinement knobs (base sweep: f32 activations, dense attention —
    # the conservatively-compilable proxy; the real TPU config runs
    # bf16 AMP + flash, which the refined variants measure):
    use_flash = bool(spec.get("use_flash", False))
    amp = spec.get("amp")  # e.g. "O1"
    remat = bool(spec.get("remat", True))

    # scan_layers: structural remat — REQUIRED for honest CPU-compiled
    # memory numbers (the CPU pipeline strips jax.checkpoint's
    # optimization barriers and CSEs the recompute away, so the
    # unrolled-remat trunk measures as if remat were off: the r4 first
    # pass read 188 GiB/device for fsdp=8 that way); scan carries are
    # real buffers no pass can elide, on any backend
    # pp rows: the pipe trunk scans over schedule ticks and
    # checkpoints the tick body — already structural remat; its own
    # depth loop ignores scan_layers (the Pipe model warns on it)
    cfg = gpt_config(spec.get("model", "gpt3-1.3b"), hidden_dropout=0.0,
                     attention_dropout=0.0, use_flash=use_flash,
                     remat=remat, fused_loss=True,
                     scan_layers=not micro)
    abstract = bool(spec.get("abstract"))
    if abstract and amp == "O2":
        raise ValueError(
            "abstract mode cannot compose with amp O2: amp.decorate "
            "casts the net's concrete params, and the abstract net has "
            "shape-only (eval_shape) params — measure O2 rows "
            "materialized")
    mesh = parallel.init_mesh(**axes)
    try:
        pt.seed(0)
        t0 = time.time()

        def build_net():
            if micro:
                return GPTForCausalLMPipe(cfg, num_microbatches=micro,
                                          mesh=mesh)
            return GPTForCausalLM(cfg)

        if abstract:
            from paddle_tpu.parallel.planner import abstract_model
            net = abstract_model(build_net)
        else:
            net = build_net()
        if amp == "O2":
            # O2 = bf16 parameter storage (amp.decorate): activations
            # inherit bf16 through the trunk, so the stored boundary
            # buffers halve — a dtype effect the CPU compile measures
            # honestly (unlike O1 compute-casting, which leaves
            # storage f32, or interpret-mode flash, which is not
            # representative)
            from paddle_tpu import amp as amp_mod
            net = amp_mod.decorate(net, level="O2")
        model = pt.Model(net)
        model.prepare(optimizer=pt.optimizer.AdamW(
            learning_rate=1e-4, parameters=net, weight_decay=0.01),
            loss=GPTFusedPretrainingCriterion(),
            **({"amp_configs": amp} if amp else {}))
        parallel.distributed_model(model, mesh=mesh)
        if abstract:
            state = _abstract_state(model, net, mesh)
        else:
            model._sync_state_in()
            state = (model._params, model._frozen, model._opt_state,
                     model._buffers)
        build_s = time.time() - t0

        model._train_step_fn = model._build_train_step()
        ids = np.zeros((gb, seq), np.int32)
        inputs = model._shard_batch((ids,))
        labels = model._shard_batch((ids,))
        key = rng_mod.split_for_step(0)
        t0 = time.time()
        lowered = model._train_step_fn.lower(
            *state, 0, key, inputs, labels)
        mem = lowered.compile().memory_analysis()
        compile_s = time.time() - t0

        # planner prediction for the same layout (pp is outside the
        # planner's search space by design — planner.py module doc)
        predicted = None
        if not micro:
            from paddle_tpu.parallel import planner
            plan = planner.evaluate(net, axes, global_batch=gb,
                                    seq_len=seq)
            predicted = plan.hbm_bytes

        total = float(mem.temp_size_in_bytes + mem.argument_size_in_bytes)
        return {
            "devices": spec["devices"], "axes": axes,
            "global_batch": gb, "seq_len": seq,
            "microbatches": micro or None,
            "use_flash": use_flash, "amp": amp, "remat": remat,
            "abstract": abstract or None,
            "model_name": spec.get("model", "gpt3-1.3b"),
            "argument_bytes": float(mem.argument_size_in_bytes),
            "temp_bytes": float(mem.temp_size_in_bytes),
            "output_bytes": float(mem.output_size_in_bytes),
            "total_bytes": total,
            "total_gib": total / _GiB,
            "fits_v5e": total <= V5E_BUDGET,
            "planner_predicted_bytes": predicted,
            "planner_ratio": (total / predicted) if predicted else None,
            "build_s": round(build_s, 1),
            "compile_s": round(compile_s, 1),
        }
    finally:
        parallel.set_mesh(None)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="FEASIBILITY_1P3B.json")
    ap.add_argument("--child", default=None)
    ap.add_argument("--timeout", type=int, default=3600)
    ap.add_argument("--ladder", action="store_true",
                    help="run the abstract scale-ladder specs instead "
                         "of the 1.3B base layouts")
    args = ap.parse_args()

    if args.child:
        print(json.dumps(run_child(json.loads(args.child))))
        return

    # append to an existing artifact — a rerun must not clobber rows
    # another sweep (base vs ladder vs hand refinements) produced
    rows = []
    if os.path.exists(args.out):
        try:
            rows = json.load(open(args.out)).get("rows", [])
        except ValueError:
            pass
    specs = LADDER if args.ladder else [
        {"devices": d, "axes": a, "global_batch": g, "microbatches": m}
        for d, a, g, m in LAYOUTS]
    for spec in specs:
        print(f"[feasibility] {spec}", file=sys.stderr, flush=True)
        from _subproc import run_spec
        rec = run_spec(__file__, "--child", spec, timeout=args.timeout)
        if "error" in rec:
            rec = {**spec, "error": rec["error"]}
        rows.append(rec)
        with open(args.out, "w") as f:  # checkpoint after every layout
            json.dump({"budget_gib": V5E_BUDGET / _GiB, "rows": rows},
                      f, indent=1)
        last = rows[-1]
        if "error" in last:
            print(f"  ERROR: {last['error'][:200]}", file=sys.stderr)
        else:
            print(f"  {last['total_gib']:.2f} GiB/device "
                  f"(fits={last['fits_v5e']}, compile "
                  f"{last['compile_s']}s)", file=sys.stderr, flush=True)
    print(json.dumps({"rows": len(rows), "out": args.out}))


if __name__ == "__main__":
    main()
