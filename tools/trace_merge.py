"""Merge span tables from router + replicas onto ONE chrome-trace
timeline.

A fleet request now shares one trace_id across processes
(observability.propagation), but the evidence still lives in K+1
separate tables: each process's /tracez ring and, after a crash, its
flight-recorder dump. This tool joins them: every source becomes a
chrome://tracing PROCESS (a ``process_name`` metadata row labeled with
the replica/router name), spans land at their wall-clock time
(``ts_wall``, which both /tracez and flight dumps carry exactly so
independently-booted processes line up), and parent/link ids ride in
``args`` — so "the router dispatched at t, the replica prefilled at
t+2ms, the failover re-dispatch linked back at t+40ms" reads as one
story in Perfetto.

Sources (``name=target``), auto-detected by shape:

- a live debug server:  ``r0=http://127.0.0.1:8080/tracez``
  (``?trace_id=`` and ``?limit=`` pass through if you add them;
  ``limit=0`` is appended by default so the whole ring ships);
- a saved /tracez snapshot: ``r0=r0_tracez.json``;
- a flight-recorder dump:   ``r0=flight_123_sigterm.jsonl``.

Run::

    python tools/trace_merge.py -o merged.json \
        router=http://127.0.0.1:8080/tracez \
        r0=obs/r0/flight_4242_exception.jsonl r1=r1_tracez.json \
        [--trace-id <32-hex id>]

The fleet chaos soak calls :func:`merge_chrome_trace` directly to
attach a merged timeline to its failure reports.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional

# span dicts flow through as produced by observability.tracing, plus
# "ts_wall" (required for alignment) and "live" (still-open spans)


def _spans_from_tracez(payload: dict) -> List[dict]:
    out = []
    for sp in payload.get("finished", []):
        out.append(dict(sp, live=False))
    for sp in payload.get("live", []):
        out.append(dict(sp, live=True))
    return out


def _spans_from_flight(lines) -> List[dict]:
    out = []
    for ln in lines:
        ln = ln.strip()
        if not ln:
            continue
        try:
            row = json.loads(ln)
        except ValueError:
            continue        # a torn tail line in a crash dump is fine
        if row.get("kind") == "span":
            out.append(row)
    return out


def load_source(target: str, timeout: float = 10.0) -> List[dict]:
    """Load spans from a /tracez URL, a /tracez JSON snapshot file, or
    a flight-recorder JSONL dump."""
    if target.startswith(("http://", "https://")):
        from urllib.request import urlopen
        url = target
        if "limit=" not in url:
            url += ("&" if "?" in url else "?") + "limit=0"
        with urlopen(url, timeout=timeout) as r:
            return _spans_from_tracez(json.loads(r.read()))
    with open(target) as f:
        if target.endswith(".jsonl"):
            return _spans_from_flight(f)
        payload = json.load(f)
    if isinstance(payload, dict) and (
            "finished" in payload or "live" in payload):
        return _spans_from_tracez(payload)
    raise ValueError(f"unrecognized source shape: {target}")


def merge_chrome_trace(sources: Dict[str, List[dict]], path: str,
                       trace_id: Optional[str] = None) -> dict:
    """Write one chrome-trace JSON from ``{process_name: spans}``.
    Timestamps are ``ts_wall``-aligned: the earliest span across ALL
    sources becomes t=0, so cross-process ordering is real ordering
    (clock skew bounded by the hosts' wall clocks — exact on the
    single-host fleets the soak spawns). Returns a summary dict."""
    t0 = None
    for spans in sources.values():
        for sp in spans:
            w = sp.get("ts_wall")
            if w is not None and (t0 is None or w < t0):
                t0 = w
    t0 = t0 or 0.0
    events, n_spans, n_links = [], 0, 0
    trace_ids = set()
    for pid, (pname, spans) in enumerate(sorted(sources.items())):
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "args": {"name": pname}})
        tnames = {}
        for sp in spans:
            if trace_id is not None and sp.get("trace_id") != trace_id:
                continue
            if sp.get("ts_wall") is None:
                continue        # can't place it on the shared axis
            tnames.setdefault(sp.get("tid"), sp.get("tname"))
        for tid, tname in sorted(tnames.items(),
                                 key=lambda kv: kv[0] or 0):
            events.append({"name": "thread_name", "ph": "M",
                           "pid": pid, "tid": tid,
                           "args": {"name": tname or f"thread-{tid}"}})
        for sp in spans:
            if trace_id is not None and sp.get("trace_id") != trace_id:
                continue
            wall = sp.get("ts_wall")
            if wall is None:
                continue
            trace_ids.add(sp.get("trace_id"))
            n_spans += 1
            args = {"trace_id": sp.get("trace_id"),
                    "span_id": sp.get("span_id"),
                    "parent_id": sp.get("parent_id"),
                    "status": sp.get("status"),
                    **(sp.get("attrs") or {})}
            links = sp.get("links") or []
            if links:
                n_links += len(links)
                args["links"] = links
            if sp.get("live"):
                args["live"] = True
            events.append({
                "name": sp["name"], "ph": "X", "cat": "span",
                "ts": round((wall - t0) * 1e6, 3),
                "dur": round((sp.get("dur") or 0.0) * 1e6, 3),
                "pid": pid, "tid": sp.get("tid"),
                "args": args,
            })
            # span events ride as thread-scoped instants; their perf
            # timestamps convert through THIS span's wall offset
            offset = wall - sp["ts"] if sp.get("ts") is not None \
                else None
            for ev in sp.get("events", []):
                if offset is None or ev.get("ts") is None:
                    continue
                events.append({
                    "name": f"{sp['name']}:{ev['name']}",
                    "ph": "i", "s": "t", "cat": "span_event",
                    "ts": round((ev["ts"] + offset - t0) * 1e6, 3),
                    "pid": pid, "tid": sp.get("tid"),
                    "args": {"span_id": sp.get("span_id"),
                             **(ev.get("attrs") or {})},
                })
    payload = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "producer": "paddle_tpu tools/trace_merge.py",
            "t0_wall": t0,
            "trace_id_filter": trace_id,
        },
    }
    with open(path, "w") as f:
        json.dump(payload, f)
    return {"path": path, "processes": len(sources), "spans": n_spans,
            "links": n_links, "trace_ids": len(trace_ids)}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
        epilog="sources: name=path-or-url (flight .jsonl, /tracez "
               ".json snapshot, or live /tracez URL)")
    ap.add_argument("-o", "--out", default="merged_trace.json")
    ap.add_argument("--trace-id", default=None,
                    help="keep only this trace's spans")
    ap.add_argument("sources", nargs="+", metavar="NAME=TARGET")
    args = ap.parse_args(argv)
    sources: Dict[str, List[dict]] = {}
    for item in args.sources:
        name, _, target = item.partition("=")
        if not target:
            ap.error(f"source {item!r} is not NAME=TARGET")
        try:
            sources[name] = load_source(target)
        except Exception as e:  # noqa: BLE001 — partial fleets merge
            print(f"warning: source {name} ({target}) skipped: {e}",
                  file=sys.stderr)
            sources[name] = []
    summary = merge_chrome_trace(sources, args.out,
                                 trace_id=args.trace_id)
    print("merged: " + json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
