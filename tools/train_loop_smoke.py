"""CI smoke for the fused multi-step train loop (tools/ci.sh).

Asserts the load-bearing invariant from ISSUE 3: a K=4 scanned slab
produces a loss stream BIT-IDENTICAL to four K=1 ``train_batch``
dispatches on a tiny model, through the real ``Model.fit`` path
(superbatch prefetch iterator included), plus the ragged tail and the
recompile-guard accounting. Fast (seconds on CPU); the full property
suite lives in tests/test_train_loop.py.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _make_model():
    import paddle_tpu as pt
    import paddle_tpu.nn as nn
    from paddle_tpu.optimizer import Adam

    pt.seed(11)
    net = nn.Sequential(nn.Flatten(), nn.Linear(12, 32), nn.ReLU(),
                        nn.Linear(32, 4))
    model = pt.Model(net)
    model.prepare(optimizer=Adam(learning_rate=1e-3, parameters=net),
                  loss=nn.CrossEntropyLoss())
    return model


def main() -> int:
    import jax
    jax.config.update("jax_platforms", "cpu")
    from paddle_tpu.hapi.callbacks import Callback
    from paddle_tpu.io import TensorDataset

    rs = np.random.RandomState(0)
    # 9 batches of 8 → K=4 slabs of 4+4+1 (ragged tail covered)
    x = rs.randn(72, 12).astype(np.float32)
    y = rs.randint(0, 4, 72).astype(np.int64)
    ds = TensorDataset([x, y])

    class Rec(Callback):
        def __init__(self, sink):
            super().__init__()
            self.sink = sink

        def on_train_batch_end(self, step, logs=None):
            self.sink.append(float(logs["loss"]))

    ref, fused = [], []
    m1 = _make_model()
    m1.fit(ds, batch_size=8, epochs=2, verbose=0, shuffle=False,
           callbacks=[Rec(ref)], steps_per_loop=1)
    m2 = _make_model()
    m2.fit(ds, batch_size=8, epochs=2, verbose=0, shuffle=False,
           callbacks=[Rec(fused)], steps_per_loop=4)

    assert len(ref) == len(fused) == 18, (len(ref), len(fused))
    if ref != fused:
        bad = [(i, a, b) for i, (a, b) in enumerate(zip(ref, fused))
               if a != b]
        print(f"FAIL: K=4 loss stream diverged from K=1 at {bad[:3]}")
        return 1
    # guard accounting: the [4,...] slab program + the per-step program
    # (ragged tail) = 2 signatures; K=1 run sees 1
    assert m1.compiled_shape_count == 1, m1.compiled_shape_count
    assert m2.compiled_shape_count == 2, m2.compiled_shape_count
    print(f"train-loop smoke OK: {len(ref)} steps bit-identical "
          f"(K=1 vs K=4, ragged tail included)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
