"""Mechanical op-coverage report against the reference's public op surface.

Enumerates the reference's op names from its kernel API yaml files
(reference: paddle/phi/api/yaml/api.yaml + legacy_api.yaml — the
declarative op registry that generates the C++ API, kernel_registry.h)
and resolves each against this framework's public namespaces. Three
buckets:

  - direct:   same name found on a public module
  - alias:    covered under a different (modern) name — mapped explicitly
  - declined: deliberately not ported, with a reason (decision records)

Run: ``python tools/op_coverage.py [--json]``. The test suite asserts the
missing list stays empty (tests/test_op_coverage.py), so a new reference
op name showing up — or a regression removing one of ours — fails CI.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

YAML_FILES = (
    "/root/reference/paddle/phi/api/yaml/api.yaml",
    "/root/reference/paddle/phi/api/yaml/legacy_api.yaml",
)

# sparse tensor surface (sparse_api.yaml) — resolved against
# paddle_tpu.sparse; strings_api.yaml is declined wholesale (string
# tensors are host-side data prep on TPU; python/numpy own them — XLA
# has no string compute and the reference's strings kernels are
# CPU-only there too).
SPARSE_YAML = "/root/reference/paddle/phi/api/yaml/sparse_api.yaml"
SPARSE_SNAPSHOT = """abs acos acosh add addmm asin asinh atan atanh cast
coalesce conv3d coo_to_dense create_sparse_coo_tensor dense_to_coo
divide divide_scalar expm1 full_like fused_attention leaky_relu log1p
masked_matmul matmul maxpool multiply mv pow relu relu6 scale sin sinh
softmax sqrt square subtract tan tanh to_dense to_sparse_coo
to_sparse_csr values""".split()

SPARSE_DECLINED = {
    "conv3d": "submanifold sparse 3-D convolution (point clouds): a "
              "gather-scatter kernel dominated by irregular memory "
              "access — hostile to MXU tiling; TPU point-cloud "
              "pipelines voxelize to dense conv3d (F.conv3d)",
    "maxpool": "same irregular-access family as sparse conv3d",
    "fused_attention": "sparse-pattern attention is served by the "
                       "Pallas flash/ring attention kernels (dense "
                       "tiles with masking beat gather-scatter on TPU)",
}

# Fallback snapshot (sorted) for machines without the reference checkout.
SNAPSHOT = """abs accuracy acos acosh adadelta adam_ adamax adamw add add_n
addmm all allclose angle any arange argmax argmin argsort as_complex
as_real asin asinh assign assign_out_ atan atan2 atanh auc batch_norm
bce_loss bernoulli bilinear_tensor_product bitwise_and bitwise_not
bitwise_or bitwise_xor brelu cast ceil celu cholesky cholesky_solve clip
clip_by_norm complex concat conj conv2d conv2d_transpose conv3d
conv3d_transpose copy_to cos cosh cross cross_entropy_with_softmax
cumprod cumsum deformable_conv depthwise_conv2d
depthwise_conv2d_transpose det diag diag_embed diagonal digamma dist
divide dot dropout eigh eigvals einsum elementwise_pow elu embedding
empty empty_like equal equal_all erf erfinv exp expand expand_as expm1
exponential_ eye flatten flip floor floor_divide fmax fmin
frobenius_norm full full_batch_size_like full_like gather gather_nd
gather_tree gaussian_random gelu graph_send_recv greater_equal
greater_than group_norm gumbel_softmax hard_shrink hard_sigmoid
hard_swish histogram huber_loss imag increment index_sample index_select
instance_norm inverse is_empty isclose isfinite isinf isnan kldiv_loss
kron kthvalue label_smooth layer_norm leaky_relu lerp less_equal
less_than lgamma linspace log log10 log1p log2 log_loss log_softmax
logcumsumexp logical_and logical_not logical_or logical_xor logit
logsigmoid logsumexp masked_select matmul matrix_power matrix_rank
matrix_rank_tol max max_pool2d_with_index max_pool3d_with_index maximum
maxout mean mean_all meshgrid min minimum mish mode modulo momentum
multi_dot multinomial multiplex multiply mv nll_loss norm not_equal
one_hot ones_like p_norm pad pad3d pixel_shuffle poisson pool2d
pool2d_gpudnn_unused pool3d pow prelu psroi_pool put_along_axis qr
randint randperm real reciprocal reduce_prod relu relu6 reshape
roi_align roi_pool roll round rsqrt scale scatter scatter_nd_add
searchsorted segment_pool selu sgd_ shape shard_index sigmoid
sigmoid_cross_entropy_with_logits sign silu sin sinh size slice
soft_shrink softmax solve split sqrt square squeeze stack strided_slice
subtract sum swish sync_batch_norm take_along_axis tan tanh tanh_shrink
temporal_shift thresholded_relu tile top_k trace transpose
triangular_solve tril_indices tril_triu trunc truncated_gaussian_random
unbind unfold uniform_random unique unique_consecutive unsqueeze
viterbi_decode where where_index yolo_box zeros_like""".split()

# reference kernel name -> "module:attr" it is covered by, or
# "declined:<reason>" for deliberate non-ports.
ALIASES = {
    # optimizers are classes, not functional kernels, in this framework
    "adadelta": "optimizer:Adadelta",
    "adam_": "optimizer:Adam",
    "adamax": "optimizer:Adamax",
    "adamw": "optimizer:AdamW",
    "momentum": "optimizer:Momentum",
    "sgd_": "optimizer:SGD",
    # metrics
    "accuracy": "metric:accuracy",
    "auc": "metric:Auc",
    # renamed / modern-name equivalents
    "add_n": "tensor:add_n",
    "assign_out_": "tensor:assign",
    "bce_loss": "functional:binary_cross_entropy",
    "bilinear_tensor_product": "nn:Bilinear",
    "brelu": "functional:hardtanh",
    "clip_by_norm": "tensor:clip_by_norm",
    "copy_to": "paddle:to_tensor",
    "cross_entropy_with_softmax": "functional:cross_entropy",
    "depthwise_conv2d": "functional:conv2d",   # groups == in_channels
    "depthwise_conv2d_transpose": "functional:conv2d_transpose",
    "deformable_conv": "vision:deform_conv2d",
    "elementwise_pow": "tensor:pow",
    "exponential_": "distribution:Exponential",
    "frobenius_norm": "tensor:frobenius_norm",
    "full_batch_size_like": "tensor:full_like",
    "gaussian_random": "tensor:randn",
    "graph_send_recv": "tensor:segment_sum",
    "hard_shrink": "functional:hardshrink",
    "hard_sigmoid": "functional:hardsigmoid",
    "hard_swish": "functional:hardswish",
    "huber_loss": "functional:smooth_l1_loss",
    "is_empty": "tensor:numel",            # numel(x) == 0
    "kldiv_loss": "functional:kl_div",
    "logsigmoid": "functional:log_sigmoid",
    "matrix_rank_tol": "linalg:matrix_rank",
    "max_pool2d_with_index": "functional:max_pool2d",  # return_mask=True
    "max_pool3d_with_index": "functional:max_pool3d",
    "mean_all": "tensor:mean",
    "modulo": "tensor:mod",
    "p_norm": "tensor:p_norm",
    "pool2d": "functional:avg_pool2d",
    "pool3d": "functional:avg_pool3d",
    "reduce_prod": "tensor:prod",
    "segment_pool": "tensor:segment_mean",
    "shape": "paddle:shape",
    "sigmoid_cross_entropy_with_logits":
        "functional:binary_cross_entropy_with_logits",
    "size": "tensor:numel",
    "slice": "tensor:slice",
    "soft_shrink": "functional:softshrink",
    "strided_slice": "tensor:strided_slice",
    "sync_batch_norm": "nn:SyncBatchNorm",
    "tanh_shrink": "functional:tanhshrink",
    "top_k": "tensor:topk",
    "tril_triu": "tensor:tril",
    "truncated_gaussian_random": "initializer:TruncatedNormal",
    "uniform_random": "tensor:uniform",
    "viterbi_decode": "text:ViterbiDecoder",
    "where_index": "tensor:nonzero",
    # declined, with decision records
    "pool2d_gpudnn_unused": "declined:cuDNN-only stub in the reference "
        "(api name says unused); no TPU meaning",
    "gather_tree": "tensor:gather_tree",
    "multiplex": "tensor:multiplex",
    "psroi_pool": "vision:psroi_pool",
    "roi_pool": "vision:roi_pool",
    "temporal_shift": "vision:temporal_shift",
    "yolo_box": "vision:yolo_box",
    "maxout": "functional:maxout",
}


def reference_ops():
    names = set()
    for f in YAML_FILES:
        if not os.path.exists(f):
            return sorted(set(SNAPSHOT))
        for line in open(f):
            m = re.match(r"^- api\s*:\s*(\w+)", line)
            if m:
                names.add(m.group(1))
    return sorted(names)


def _namespaces():
    import paddle_tpu as pt
    import paddle_tpu.tensor as tensor
    from paddle_tpu import linalg, metric, nn, optimizer, text, vision
    from paddle_tpu import distribution
    from paddle_tpu.nn import functional, initializer
    import paddle_tpu.vision.ops as vision_ops
    return {
        "paddle": pt, "tensor": tensor, "functional": functional,
        "nn": nn, "linalg": linalg, "optimizer": optimizer,
        "metric": metric, "text": text, "vision": vision_ops,
        "initializer": initializer, "distribution": distribution,
    }


def sparse_ops():
    if not os.path.exists(SPARSE_YAML):
        return sorted(set(SPARSE_SNAPSHOT))
    names = set()
    for line in open(SPARSE_YAML):
        m = re.match(r"^- (?:sparse_)?api\s*:\s*(\w+)", line)
        if m:
            names.add(m.group(1))
    return sorted(names)


def classify():
    ns = _namespaces()
    search_order = ("tensor", "paddle", "functional", "linalg", "nn",
                    "vision")
    out = {"direct": [], "alias": [], "declined": [], "missing": []}
    import paddle_tpu.sparse as sparse_mod
    for name in sparse_ops():
        if name in SPARSE_DECLINED:
            out["declined"].append((f"sparse.{name}",
                                    SPARSE_DECLINED[name]))
        elif hasattr(sparse_mod, name):
            out["direct"].append((f"sparse.{name}", "sparse"))
        else:
            out["missing"].append((f"sparse.{name}",
                                   "missing from paddle_tpu.sparse"))
    out["declined"].append((
        "strings.* (strings_api.yaml: empty/empty_like/lower/upper)",
        "string tensors are host-side data prep; python/numpy own them "
        "on TPU (the reference's strings kernels are CPU-only as well)"))
    for name in reference_ops():
        target = ALIASES.get(name)
        if target:
            if target.startswith("declined:"):
                out["declined"].append((name, target[9:]))
                continue
            mod, attr = target.split(":")
            if mod in ns and hasattr(ns[mod], attr):
                out["alias"].append((name, target))
            else:
                out["missing"].append((name, f"alias target {target} "
                                             f"does not resolve"))
            continue
        for mod in search_order:
            if hasattr(ns[mod], name):
                out["direct"].append((name, mod))
                break
        else:
            out["missing"].append((name, "no direct match, no alias"))
    return out


def numeric_verified_names():
    """Base names carrying a NumPy-reference OpSpec row in the numeric
    sweep (tests/test_optest.py + tests/test_optest_extended.py) — the
    'covered means checked' tier VERDICT r3 item 6 asks the report to
    distinguish from mere name resolution."""
    import importlib.util
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    names = set()
    for fn in ("test_optest.py", "test_optest_extended.py"):
        path = os.path.join(repo, "tests", fn)
        spec = importlib.util.spec_from_file_location(fn[:-3], path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        for s in mod.SPECS:
            names.add(s.name.split(".")[0])
            f = getattr(s, "fn", None)
            n = getattr(f, "__name__", "")
            if n and n != "<lambda>":
                names.add(n)
    return names


# OpSpec rows whose table name differs from the public op name
_NUMERIC_EQUIV = {
    "binary_cross_entropy_with_logits": "bce_with_logits",
    "sigmoid_cross_entropy_with_logits": "bce_with_logits",
    "cross_entropy_with_softmax": "softmax_with_cross_entropy",
    "tril_triu": "tril",
    "top_k": "topk",
    "pad3d": "pad",          # pad.3d_* rows exercise every pad3d mode
    "brelu": "hardtanh",
    "hard_shrink": "hardshrink",
    "hard_sigmoid": "hardsigmoid",
    "hard_swish": "hardswish",
    "soft_shrink": "softshrink",
    "tanh_shrink": "tanhshrink",
    "kldiv_loss": "kl_div",
    "huber_loss": "smooth_l1_loss",
    "bce_loss": "binary_cross_entropy",
    "logsigmoid": "log_sigmoid",
    "elementwise_pow": "pow",
    "reduce_prod": "prod",
    "mean_all": "mean",
    "modulo": "mod",
    "graph_send_recv": "segment_sum",
    "segment_pool": "segment_mean",
    "max_pool2d_with_index": "max_pool2d",
    "max_pool3d_with_index": "max_pool3d",
    "pool2d": "avg_pool2d",
    "pool3d": "avg_pool3d",
    "depthwise_conv2d": "conv2d",
    "depthwise_conv2d_transpose": "conv2d_transpose",
    "where_index": "nonzero",
    "is_empty": "numel",
    "size": "numel",
}


def classify_numeric(r, numeric):
    """Split covered ops into numeric-verified vs resolved-only."""
    verified, resolved = [], []
    for name, mod in r["direct"]:
        base = name.split(".")[-1]
        if base in numeric or _NUMERIC_EQUIV.get(base) in numeric:
            verified.append(name)
        else:
            resolved.append(name)
    for name, target in r["alias"]:
        attr = target.split(":")[-1]
        if attr in numeric or name in numeric or \
                _NUMERIC_EQUIV.get(name) in numeric or \
                _NUMERIC_EQUIV.get(attr) in numeric:
            verified.append(name)
        else:
            resolved.append(name)
    return verified, resolved


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()
    r = classify()
    total = sum(len(v) for v in r.values())
    covered = len(r["direct"]) + len(r["alias"])
    pct = 100.0 * covered / (total - len(r["declined"])) \
        if total > len(r["declined"]) else 0.0
    verified, resolved = classify_numeric(r, numeric_verified_names())
    if args.json:
        print(json.dumps({
            "total": total, "covered": covered,
            "declined": len(r["declined"]),
            "missing": [n for n, _ in r["missing"]],
            "numeric_verified": len(verified),
            "resolved_only": sorted(resolved),
            "coverage_pct": round(pct, 1)}))
        return 0 if not r["missing"] else 1
    print(f"reference public ops: {total}")
    print(f"covered: {covered} ({len(r['direct'])} direct, "
          f"{len(r['alias'])} alias) = {pct:.1f}% of non-declined")
    print(f"numeric-verified (OpSpec row in tests/test_optest*.py): "
          f"{len(verified)}; resolved-only: {len(resolved)}")
    print("  resolved-only (verified in dedicated test files, or "
          "structural): " + ", ".join(sorted(resolved)))
    print(f"declined with decision record: {len(r['declined'])}")
    for n, why in r["declined"]:
        print(f"  - {n}: {why}")
    if r["missing"]:
        print(f"MISSING ({len(r['missing'])}):")
        for n, why in r["missing"]:
            print(f"  - {n}: {why}")
    return 0 if not r["missing"] else 1


if __name__ == "__main__":
    import jax
    if jax.config.jax_platforms is None or "axon" in str(
            jax.config.jax_platforms or ""):
        jax.config.update("jax_platforms", "cpu")  # report needs no TPU
    sys.exit(main())
