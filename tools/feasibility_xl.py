"""GPT-2-XL (1.56B) SINGLE-CHIP feasibility, compile-only (VERDICT r4
item 3's chip-independent half: does the 1.5B configuration — Adafactor
factored state + scan/remat + fused vocab loss — fit a 16 GiB v5e?).

Methodology identical to tools/feasibility_1p3b.py: AOT-compile the
REAL train step on one virtual CPU device with abstract
(ShapeDtypeStruct) state and read XLA's compiled memory analysis.
The contrast rows show WHY Adafactor is the lever: AdamW's m+v are
12.5 GiB of fp32 state on top of 6.2 GiB params — no batch fits;
Adafactor's factored second moments are ~MBs.

INTERPRETATION CAVEAT (r5, single-device rows only): the CPU
backend's temp accounting is an UPPER BOUND on the TPU footprint —
it ignores buffer donation entirely (params cannot alias their
updates) and its scheduler optimizes thread parallelism, not peak
memory. Calibration: a gpt2-small forward whose true activation peak
is ~0.6 GiB reads 1.31 GiB here (~2.2x). The bf16+Adafactor rows
reading ~19-20 GiB therefore predict a REAL footprint around
9-12 GiB once donation (-3.1 GiB params alias) and memory-aware
scheduling apply — the single-chip b4/b8 attempts stay queued in
tools/tpu_sweep.py as the decider. The fp32/AdamW rows are
conclusive the other way: their ARGUMENT bytes alone (state that
must exist, no scheduling involved) exceed the budget.

Run: python tools/feasibility_xl.py [--out FEASIBILITY_XL.json]
     python tools/feasibility_xl.py --child '{"batch":4,...}'
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_GiB = float(1 << 30)
V5E_BUDGET = 16 * _GiB * 0.85

RUNS = [
    {"batch": 4, "optimizer": "adafactor"},
    {"batch": 8, "optimizer": "adafactor"},
    {"batch": 4, "optimizer": "adamw"},   # the contrast: must NOT fit
    # the fitting configuration: bf16 parameter storage (pure-bf16 +
    # Adafactor, the T5-lineage single-chip recipe; factored state
    # needs no fp32 master copies to stay sublinear)
    {"batch": 4, "optimizer": "adafactor", "param_dtype": "bfloat16"},
    {"batch": 8, "optimizer": "adafactor", "param_dtype": "bfloat16"},
    {"batch": 16, "optimizer": "adafactor", "param_dtype": "bfloat16"},
]


def run_child(spec: dict) -> dict:
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 1)

    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu import parallel
    from paddle_tpu.core import rng as rng_mod
    from paddle_tpu.models.gpt import (GPTForCausalLM,
                                       GPTFusedPretrainingCriterion,
                                       gpt_config)
    from paddle_tpu.parallel.planner import abstract_model
    from feasibility_1p3b import _abstract_state

    b = int(spec["batch"])
    seq = int(spec.get("seq", 1024))
    pdt = spec.get("param_dtype")
    cfg = gpt_config("gpt2-xl", hidden_dropout=0.0,
                     attention_dropout=0.0, use_flash=False,
                     remat=True, fused_loss=True, scan_layers=True,
                     max_position_embeddings=seq)
    mesh = parallel.init_mesh(dp=1)
    try:
        pt.seed(0)
        if pdt:
            # bf16 parameter STORAGE from construction (abstract-safe,
            # unlike amp.decorate which casts concrete params); grads
            # and boundary activations inherit the dtype
            from paddle_tpu.core import dtype as dtype_mod
            dtype_mod.set_default_dtype(pdt)
        t0 = time.time()
        net = abstract_model(lambda: GPTForCausalLM(cfg))
        model = pt.Model(net)
        if spec["optimizer"] == "adafactor":
            # factored state is sublinear only without fp32 master
            # copies; Adafactor's own update runs f32 per-tensor
            opt = pt.optimizer.Adafactor(learning_rate=1e-4,
                                         parameters=net,
                                         multi_precision=False)
        else:
            opt = pt.optimizer.AdamW(learning_rate=1e-4,
                                     parameters=net, weight_decay=0.01)
        model.prepare(optimizer=opt,
                      loss=GPTFusedPretrainingCriterion(),
                      amp_configs="O1")
        parallel.distributed_model(model, mesh=mesh)
        state = _abstract_state(model, net, mesh)
        build_s = time.time() - t0

        model._train_step_fn = model._build_train_step()
        ids = np.zeros((b, seq), np.int32)
        inputs = model._shard_batch((ids,))
        labels = model._shard_batch((ids,))
        key = rng_mod.split_for_step(0)
        t0 = time.time()
        lowered = model._train_step_fn.lower(
            *state, 0, key, inputs, labels)
        mem = lowered.compile().memory_analysis()
        compile_s = time.time() - t0
        total = float(mem.temp_size_in_bytes +
                      mem.argument_size_in_bytes)
        opt_bytes = sum(
            int(np.prod(l.shape)) * l.dtype.itemsize
            for l in jax.tree_util.tree_leaves(state[2]))
        return {
            "model": "gpt2-xl", "params": 1557611200,
            "batch": b, "seq": seq,
            "optimizer": spec["optimizer"],
            "opt_state_bytes": float(opt_bytes),
            "argument_bytes": float(mem.argument_size_in_bytes),
            "temp_bytes": float(mem.temp_size_in_bytes),
            "total_bytes": total, "total_gib": total / _GiB,
            "fits_v5e": total <= V5E_BUDGET,
            "build_s": round(build_s, 1),
            "compile_s": round(compile_s, 1),
        }
    finally:
        parallel.set_mesh(None)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="FEASIBILITY_XL.json")
    ap.add_argument("--child", default=None)
    args = ap.parse_args()
    if args.child:
        print(json.dumps(run_child(json.loads(args.child))))
        return
    rows = []
    for spec in RUNS:
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child",
             json.dumps(spec)],
            capture_output=True, text=True, timeout=3600)
        line = [l for l in p.stdout.splitlines()
                if l.startswith("{")]
        if p.returncode == 0 and line:
            rows.append(json.loads(line[-1]))
        else:
            rows.append({"spec": spec,
                         "error": (p.stderr or "")[-400:]})
        print(json.dumps(rows[-1]), file=sys.stderr)
    with open(args.out, "w") as f:
        json.dump({"budget_gib": V5E_BUDGET / _GiB, "rows": rows}, f,
                  indent=1)


if __name__ == "__main__":
    main()
