"""The perf ledger: ONE canonical bench-row schema + a regression gate.

Before this tool the perf trajectory was unreadable: bench.py printed
driver rows (``BENCH_rNN.json``: ``{n, cmd, rc, tail, parsed}``),
tpu_sweep.py appended a second shape to ``PERF_SWEEP.jsonl``,
llm_bench.py a third — no shared keys, no git anchoring, nothing a
gate could diff. This module defines the one row every bench tool now
appends to ``BENCH_LEDGER.jsonl``:

    {"schema": "bench_ledger/v1", "run_id": ..., "ts": ...,
     "git_rev": ..., "backend": ..., "tool": ..., "workload": ...,
     "value": ..., "unit": ..., "tokens_per_sec": ..., "mfu": ...,
     "dispatches": ..., "metrics": {...}, "extra": {...}}

``workload`` + ``backend`` identify a comparable series; ``value`` is
the headline number in ``unit`` (direction: higher is better unless
the row says ``"direction": "lower"``). ``metrics`` carries a bounded
snapshot of the live registry (counters/gauges under the serving and
perf prefixes) so a dead round is visible IN the row.

CLI:
  python tools/bench_ledger.py --compare   # newest row vs trajectory
  python tools/bench_ledger.py --ci        # regression gate (ci.sh)
  python tools/bench_ledger.py --show      # dump the grouped ledger

The ``--ci`` gate fails LOUDLY on an empty/unreadable ledger and on
any series whose newest row regresses below ``(1 - tolerance) x
baseline`` (baseline = median of the prior rows in the series, up to
``--baseline-window``). The default tolerance is deliberately wide on
CPU backends (CI wall clocks are noisy neighbors) and tight on real
chips. The mapping from the legacy row shapes is documented in
PERF.md ("The perf ledger").

Emitters: ``tools/llm_bench.py`` (serving benches), ``bench.py``
(train headline), ``tools/tpu_sweep.py`` (hardware sweep rows —
legacy PERF_SWEEP.jsonl rows are still written alongside for one
release). Path override: ``PT_BENCH_LEDGER`` env (tests point it at a
tmp file; ``PT_BENCH_LEDGER=0`` disables appends entirely).
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import subprocess
import sys
import time
import uuid
from typing import Dict, List, Optional

SCHEMA = "bench_ledger/v1"
REQUIRED = ("schema", "run_id", "ts", "git_rev", "backend", "tool",
            "workload", "value", "unit")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_PATH = os.path.join(_REPO_ROOT, "BENCH_LEDGER.jsonl")

# default tolerances for the --ci gate: fractional regression allowed
# before the gate fails. CPU CI boxes share cores with neighbors, so
# the CPU bound is wide by design — it catches "fell off a cliff"
# (an accidental host sync, a lost fusion), not 5% noise.
CPU_TOLERANCE = 0.45
HW_TOLERANCE = 0.10
BASELINE_WINDOW = 8

# registry snapshot prefixes a ledger row carries (counters/gauges
# only — histogram percentiles would bloat every row)
METRIC_PREFIXES = ("llm_", "perf_", "mem_", "host_rss_bytes",
                   "train_compile_count", "train_step_count", "fleet_",
                   "goodput_", "badput_", "drift_")


def ledger_path(path: Optional[str] = None) -> Optional[str]:
    """Resolve the ledger path: explicit arg > PT_BENCH_LEDGER env >
    repo-root default. Returns None when appends are disabled
    (``PT_BENCH_LEDGER=0``)."""
    if path:
        return path
    env = os.environ.get("PT_BENCH_LEDGER")
    if env == "0":
        return None
    return env or DEFAULT_PATH


def git_rev() -> str:
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], cwd=_REPO_ROOT,
            capture_output=True, text=True, timeout=10)
        rev = (out.stdout or "").strip()
        return rev if out.returncode == 0 and rev else "unknown"
    except Exception:  # noqa: BLE001 — a revless row beats no row
        return "unknown"


def host_fingerprint() -> str:
    """A machine-class token keying CPU series: wall-clock throughput
    varies 2-5x across hosts, so the regression gate only compares a
    row against prior rows from the SAME class — a slower contributor
    laptop starts its own trajectory instead of failing CI against
    the committed machine's numbers. ``PT_BENCH_HOST`` pins an
    explicit stable name (recommended for long-lived CI fleets whose
    container hostnames are ephemeral)."""
    env = os.environ.get("PT_BENCH_HOST")
    if env:
        return env
    import platform
    return f"{platform.machine()}-{os.cpu_count()}c"


def current_backend() -> str:
    try:
        import jax
        return getattr(jax.devices()[0], "device_kind", "") or \
            jax.default_backend()
    except Exception:  # noqa: BLE001
        return "unknown"


def metrics_snapshot(prefixes=METRIC_PREFIXES) -> Dict[str, float]:
    """Bounded counters/gauges snapshot from the live registry (the
    dead-round witness each row carries). Refreshes the perf_* roofline
    gauges first — they update at read boundaries, and a ledger row IS
    a read boundary."""
    try:
        from paddle_tpu.observability import (default_registry, goodput,
                                              memory, perf)
        if perf.enabled():
            perf.instance().update_gauges()
        if memory.enabled():
            memory.instance().update_gauges()
        if goodput.enabled():
            goodput.instance().update_gauges()
    except Exception:  # noqa: BLE001 — emitters must not need jax up
        return {}
    out: Dict[str, float] = {}
    for fam in default_registry().families():
        if not fam.name.startswith(tuple(prefixes)):
            continue
        if fam.kind == "histogram":
            continue
        for child in fam.children():
            key = fam.name
            if fam.label_names:
                inner = ",".join(
                    f'{n}="{v}"' for n, v in zip(fam.label_names,
                                                 child.label_values))
                key += "{" + inner + "}"
            out[key] = round(float(child.value), 6)
    return out


def goodput_row_fields() -> Dict[str, object]:
    """The time ledger's verdict on the current process — the optional
    ``goodput_fraction`` + ``badput_top`` kwargs a bench row carries
    ({} when the ledger is disabled or never armed, so old-schema rows
    simply lack the keys). All three emitters splat this into
    :func:`append` (the ``peak_mem_bytes`` discipline)."""
    try:
        from paddle_tpu.observability import goodput
        if not goodput.enabled():
            return {}
        led = goodput.instance()
        if not led.armed:
            return {}
        totals = led.totals()
        frac = led.goodput_fraction()
        top = led.top_badput(totals)
        return {
            "goodput_fraction": (round(frac, 4)
                                 if frac is not None else None),
            "badput_top": top["cause"] if top else None,
        }
    except Exception:  # noqa: BLE001 — a row beats no row
        return {}


def drift_row_fields() -> Dict[str, object]:
    """The stream auditor's verdict on the current process — the
    optional ``drift_divergences`` kwarg a bench row carries ({} when
    the auditor is disabled or never armed, so rows keep the
    hole-not-zero semantics: absent means "nobody was checking", 0
    means "checked and clean"). Emitters splat this into
    :func:`append` like :func:`goodput_row_fields`."""
    try:
        from paddle_tpu.observability import audit
        if not audit.enabled():
            return {}
        counts = audit.instance().counts()
        if not counts.get("verified") and not counts.get("diverged"):
            return {}
        return {"drift_divergences": int(counts.get("diverged", 0))}
    except Exception:  # noqa: BLE001 — a row beats no row
        return {}


def make_row(tool: str, workload: str, value: float, unit: str,
             tokens_per_sec: Optional[float] = None,
             mfu: Optional[float] = None,
             dispatches: Optional[float] = None,
             peak_mem_bytes: Optional[float] = None,
             goodput_fraction: Optional[float] = None,
             badput_top: Optional[str] = None,
             drift_divergences: Optional[int] = None,
             backend: Optional[str] = None,
             direction: str = "higher",
             kv_dtype: Optional[str] = None,
             extra: Optional[dict] = None,
             metrics: Optional[dict] = None) -> dict:
    """Build one canonical ledger row (see module docstring).
    ``peak_mem_bytes`` (optional, schema-tolerated when absent — old
    rows predate it) carries the memory ledger's attributed
    high-watermark so capacity changes (int8 KV pages halving pool
    bytes) are visible IN the perf trajectory, next to the
    throughput they bought. ``kv_dtype`` (optional, same absent-field
    tolerance) records the engine KV-pool dtype a serving bench ran
    at AND joins the series key, so an int8 run never regression-
    gates against a bf16 baseline (different storage = different
    trajectory). ``goodput_fraction`` / ``badput_top`` (optional, same
    absent-field tolerance) carry the time ledger's verdict on the
    run — the fraction of bench wall clock the device actually
    computed, and the dominant badput cause — so a throughput number
    bought by hiding stalls outside the timed region is visible IN
    the trajectory row. ``drift_divergences`` (optional, same
    absent-field tolerance) carries the stream auditor's verdict —
    how many audited streams diverged during the run — with hole
    semantics: absent means the auditor never armed, 0 means it
    checked the run and found it clean."""
    return {
        "schema": SCHEMA,
        "run_id": uuid.uuid4().hex[:12],
        "ts": round(time.time(), 3),
        "git_rev": git_rev(),
        "backend": backend if backend is not None else current_backend(),
        "host": host_fingerprint(),
        "tool": str(tool),
        "workload": str(workload),
        "value": float(value),
        "unit": str(unit),
        "tokens_per_sec": (float(tokens_per_sec)
                           if tokens_per_sec is not None else None),
        "mfu": float(mfu) if mfu is not None else None,
        "dispatches": (float(dispatches)
                       if dispatches is not None else None),
        "peak_mem_bytes": (float(peak_mem_bytes)
                          if peak_mem_bytes is not None else None),
        "goodput_fraction": (float(goodput_fraction)
                             if goodput_fraction is not None else None),
        "badput_top": str(badput_top) if badput_top is not None else None,
        "drift_divergences": (int(drift_divergences)
                              if drift_divergences is not None else None),
        "kv_dtype": str(kv_dtype) if kv_dtype is not None else None,
        "direction": direction,
        "metrics": metrics if metrics is not None else metrics_snapshot(),
        "extra": extra or {},
    }


def append_row(row: dict, path: Optional[str] = None) -> Optional[str]:
    """Validate + append one row. Returns the path written (None when
    appends are disabled). Raises ValueError on a malformed row —
    emitting a row the gate can't read is the bug this schema
    exists to kill."""
    missing = [k for k in REQUIRED if row.get(k) is None]
    if missing:
        raise ValueError(f"ledger row missing required fields "
                         f"{missing}: {row}")
    if row["schema"] != SCHEMA:
        raise ValueError(f"unknown ledger schema {row['schema']!r}")
    p = ledger_path(path)
    if p is None:
        return None
    with open(p, "a") as f:
        f.write(json.dumps(row, sort_keys=True) + "\n")
    return p


def append(tool: str, workload: str, value: float, unit: str,
           path: Optional[str] = None, **kw) -> Optional[str]:
    """One-call emitter the bench tools use. Never raises on I/O —
    a failed append must not fail the measurement (schema errors
    still do: those are bugs)."""
    row = make_row(tool, workload, value, unit, **kw)
    try:
        return append_row(row, path=path)
    except OSError as e:
        print(f"bench_ledger: append failed: {e}", file=sys.stderr)
        return None


def read_ledger(path: Optional[str] = None) -> List[dict]:
    """Parse the ledger, skipping malformed lines (reported to
    stderr — a half-written row degrades, never crashes a reader)."""
    p = ledger_path(path)
    if p is None or not os.path.exists(p):
        return []
    rows = []
    with open(p) as f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            try:
                d = json.loads(line)
            except ValueError:
                print(f"bench_ledger: line {i + 1} unparseable, "
                      f"skipped", file=sys.stderr)
                continue
            if d.get("schema") == SCHEMA and \
                    all(d.get(k) is not None for k in REQUIRED):
                rows.append(d)
            else:
                print(f"bench_ledger: line {i + 1} not a v1 row, "
                      f"skipped", file=sys.stderr)
    return rows


def _series(rows: List[dict]) -> Dict[tuple, List[dict]]:
    """Group by (workload, backend, host, kv_dtype) in file (= time)
    order — host-keying keeps a slower machine's rows from reading as
    a regression of a faster machine's baseline (rows predating the
    host field group under "legacy"), and kv_dtype-keying keeps int8
    and bf16 serving runs in SEPARATE trajectories (rows predating
    the field, or train rows, carry None and group together as
    before)."""
    out: Dict[tuple, List[dict]] = {}
    for r in rows:
        out.setdefault((r["workload"], r["backend"],
                        r.get("host", "legacy"),
                        r.get("kv_dtype")), []).append(r)
    return out


def _tolerance_for(backend: str, override: Optional[float]) -> float:
    if override is not None:
        return override
    b = (backend or "").lower()
    return HW_TOLERANCE if "tpu" in b or "gpu" in b else CPU_TOLERANCE


def compare(rows: List[dict],
            tolerance: Optional[float] = None) -> List[dict]:
    """Per-series verdicts: newest row vs the median of its prior
    rows (up to BASELINE_WINDOW). Single-row series report "new"."""
    verdicts = []
    for (workload, backend, host, kv_dtype), series in sorted(
            _series(rows).items(),
            key=lambda kv: tuple(str(x) for x in kv[0])):
        newest = series[-1]
        prior = series[:-1][-BASELINE_WINDOW:]
        v = {
            "workload": workload,
            "backend": backend,
            "host": host,
            "kv_dtype": kv_dtype,
            "unit": newest["unit"],
            "rows": len(series),
            "newest": newest["value"],
            "newest_rev": newest["git_rev"],
            "newest_mfu": newest.get("mfu"),
            # optional fields (rows predating them have no key at all —
            # .get keeps --compare/--ci tolerant of the old schema)
            "newest_peak_mem_bytes": newest.get("peak_mem_bytes"),
            "newest_goodput_fraction": newest.get("goodput_fraction"),
            "newest_badput_top": newest.get("badput_top"),
            "newest_drift_divergences": newest.get("drift_divergences"),
        }
        if not prior:
            v.update(status="new", baseline=None, ratio=None)
        else:
            baseline = statistics.median(r["value"] for r in prior)
            ratio = newest["value"] / baseline if baseline else None
            tol = _tolerance_for(backend, tolerance)
            lower_better = newest.get("direction") == "lower"
            if ratio is None:
                status = "ok"
            elif lower_better:
                status = "regressed" if ratio > 1.0 + tol else "ok"
            else:
                status = "regressed" if ratio < 1.0 - tol else "ok"
            v.update(status=status, baseline=round(baseline, 4),
                     ratio=round(ratio, 4) if ratio is not None
                     else None, tolerance=tol)
        verdicts.append(v)
    return verdicts


def ci_gate(path: Optional[str] = None,
            tolerance: Optional[float] = None) -> int:
    """The ci.sh regression gate. Exit codes: 0 ok, 2 empty/unreadable
    trajectory (fails LOUDLY — a perf story that reads as [] is itself
    the regression), 3 a series regressed past tolerance."""
    p = ledger_path(path)
    rows = read_ledger(path)
    if not rows:
        print(f"bench_ledger --ci FAIL: no readable rows in "
              f"{p or '(appends disabled)'} — the perf trajectory is "
              f"empty. Run the bench tools (llm_bench.py / bench.py / "
              f"tpu_sweep.py) so the ledger has a baseline.",
              file=sys.stderr)
        return 2
    verdicts = compare(rows, tolerance=tolerance)
    bad = [v for v in verdicts if v["status"] == "regressed"]
    for v in verdicts:
        mark = {"ok": "OK ", "new": "NEW", "regressed": "REG"}[
            v["status"]]
        base = (f" baseline {v['baseline']} ratio {v['ratio']}"
                if v.get("baseline") is not None else "")
        kvd = f" kv={v['kv_dtype']}" if v.get("kv_dtype") else ""
        print(f"[{mark}] {v['workload']} @ {v['backend']} "
              f"[{v['host']}]{kvd}: {v['newest']} {v['unit']}{base} "
              f"({v['rows']} rows)")
    if bad:
        print(f"bench_ledger --ci FAIL: {len(bad)} series regressed "
              f"past tolerance:", file=sys.stderr)
        for v in bad:
            print(f"  {v['workload']} @ {v['backend']}: "
                  f"{v['newest']} vs baseline {v['baseline']} "
                  f"(ratio {v['ratio']}, tolerance "
                  f"{v['tolerance']})", file=sys.stderr)
        return 3
    print(f"bench_ledger --ci OK: {len(verdicts)} series, "
          f"{len(rows)} rows, newest rev "
          f"{rows[-1]['git_rev']}")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--path", default=None,
                    help="ledger file (default: repo BENCH_LEDGER.jsonl "
                         "or $PT_BENCH_LEDGER)")
    ap.add_argument("--compare", action="store_true",
                    help="diff the newest row of each series against "
                         "its trajectory baseline (JSON verdicts)")
    ap.add_argument("--ci", action="store_true",
                    help="regression gate: nonzero exit on an empty "
                         "trajectory or a regressed series")
    ap.add_argument("--show", action="store_true",
                    help="dump the parsed ledger grouped by series")
    ap.add_argument("--tolerance", type=float, default=None,
                    help="override the fractional regression tolerance "
                         "(default: 0.45 CPU, 0.10 TPU/GPU)")
    args = ap.parse_args(argv)

    if args.ci:
        return ci_gate(path=args.path, tolerance=args.tolerance)
    rows = read_ledger(args.path)
    if args.show:
        for key, series in sorted(
                _series(rows).items(),
                key=lambda kv: tuple(str(x) for x in kv[0])):
            kvd = f" kv={key[3]}" if key[3] else ""
            print(f"== {key[0]} @ {key[1]} [{key[2]}]{kvd} "
                  f"({len(series)} rows)")
            for r in series:
                print(f"  {r['git_rev']} {r['value']} {r['unit']} "
                      f"mfu={r.get('mfu')} ts={r['ts']}")
        return 0
    # default + --compare: verdict dump
    print(json.dumps(compare(rows, tolerance=args.tolerance), indent=2))
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:     # `--show | head` is a fine way to read
        sys.exit(0)
