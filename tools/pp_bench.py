"""Pipeline-parallel measurement (VERDICT r2 item 6): quantify the pp
bubble + remat overhead vs dense, and the pp memory win, on the
8-device virtual CPU mesh (wall-clock proxy — relative numbers; the
absolute story needs the real chip, bench.py).

Run: python tools/pp_bench.py [--steps 8] [--json]
Writes nothing; prints a table + one JSON line for PERF.md.

Also benchmarks the beyond-HBM host-offloaded embedding lookup against
the dense mesh-sharded table (VERDICT r2 item 4's measurement ask).
"""

from __future__ import annotations

import argparse
import json
import time

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)

import numpy as np  # noqa: E402


def _time_steps(fn, args, steps):
    out = fn(*args)                      # compile + warm
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / steps


def gpt_pp_vs_dense(steps: int, quiet: bool = False):
    import paddle_tpu as pt
    from paddle_tpu import parallel
    from paddle_tpu.models.gpt import (GPTConfig, GPTForCausalLM,
                                       GPTForCausalLMPipe,
                                       GPTPretrainingCriterion)

    cfg_kw = dict(vocab_size=512, hidden_size=128, num_layers=8,
                  num_heads=4, max_position_embeddings=128,
                  hidden_dropout=0.0, attention_dropout=0.0,
                  use_flash=False)
    batch, seq = 16, 128
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 512, (batch, seq))
    results = {}

    def build(pipe: bool, mesh, **pipe_kw):
        pt.seed(0)
        cfg = GPTConfig(**cfg_kw)
        net = (GPTForCausalLMPipe(cfg, mesh=mesh, **pipe_kw)
               if pipe else GPTForCausalLM(cfg))
        model = pt.Model(net)
        model.prepare(optimizer=pt.optimizer.AdamW(
            learning_rate=1e-4, parameters=net, weight_decay=0.01),
            loss=GPTPretrainingCriterion())
        parallel.distributed_model(model, mesh=mesh)
        return model

    def measure(name, model, quiet=False):
        model._sync_state_in()
        if model._train_step_fn is None:
            model._train_step_fn = model._build_train_step()
        from paddle_tpu.core import rng as rng_mod
        inputs, labels = ([ids], [ids])
        inputs = model._shard_batch(tuple(inputs))
        labels = model._shard_batch(tuple(labels))
        key = rng_mod.split_for_step(0)
        step_args = (model._params, model._frozen, model._opt_state,
                     model._buffers, 0, key, inputs, labels)
        # ONE AOT compilation serves both the memory analysis and the
        # timing loop (donated state threads output -> input each step)
        compiled = model._train_step_fn.lower(*step_args).compile()
        m = compiled.memory_analysis()
        mem = float(m.temp_size_in_bytes + m.argument_size_in_bytes)
        params, opt, bufs = (model._params, model._opt_state,
                             model._buffers)
        loss, params, opt, bufs, _ = compiled(
            params, model._frozen, opt, bufs, 0, key, inputs, labels)
        bufs = dict(bufs)  # step returns OrderedDict; AOT pytree is dict
        jax.block_until_ready(loss)
        t0 = time.perf_counter()
        for _ in range(steps):
            loss, params, opt, bufs, _ = compiled(
                params, model._frozen, opt, bufs, 0, key, inputs, labels)
            bufs = dict(bufs)
        float(np.asarray(loss))
        dt = (time.perf_counter() - t0) / steps
        results[name] = {"step_s": round(dt, 4),
                         "mem_mib_per_dev": round(mem / 2**20, 1)}
        if not quiet:
            print(f"{name:28s} step {dt*1e3:8.1f} ms   "
                  f"mem/dev {mem/2**20:8.1f} MiB")

    try:
        mesh = parallel.init_mesh(dp=8)
        measure("dense dp=8", build(False, mesh), quiet)
        parallel.set_mesh(None)

        for pp, v, m in ((2, 1, 8), (2, 2, 8), (4, 1, 8), (4, 2, 8)):
            mesh = parallel.init_mesh(pp=pp, dp=8 // pp)
            measure(f"pp={pp} v={v} m={m} dp={8//pp}",
                    build(True, mesh, num_microbatches=m,
                          virtual_pp_degree=v), quiet)
            parallel.set_mesh(None)

        # tp inside pp (the round-3 capability)
        mesh = parallel.init_mesh(pp=2, tp=2, dp=2)
        measure("pp=2 tp=2 dp=2 v=1 m=8",
                build(True, mesh, num_microbatches=8), quiet)
        parallel.set_mesh(None)
    finally:
        parallel.set_mesh(None)
    return results


def host_embedding_vs_dense(steps: int, quiet: bool = False):
    import paddle_tpu as pt
    from paddle_tpu.nn.layers.host_embedding import HostOffloadedEmbedding
    from paddle_tpu.nn.layers.sparse_embedding import SparseEmbedding

    pt.seed(0)
    n, d, batch, k = 200_000, 64, 256, 16
    rng = np.random.RandomState(0)
    ids = rng.randint(1, n, (batch, k))

    dense = SparseEmbedding(n, d)
    f_dense = jax.jit(lambda i: dense(i).sum())
    t_dense = _time_steps(f_dense, (ids,), steps)

    host = HostOffloadedEmbedding(n, d)
    f_host = jax.jit(lambda i: host(i).sum())
    t_host = _time_steps(f_host, (ids,), steps)

    # first-touch: every pull lazy-inits ~4096 fresh rows (the
    # cold-epoch regime VERDICT r3 weak #3 flagged as Python-bound)
    cold = HostOffloadedEmbedding(50_000_000, d)
    rng2 = np.random.RandomState(1)
    t0 = time.perf_counter()
    n_cold = 16
    for i in range(n_cold):
        cold._pull(rng2.randint(1, 50_000_000, (batch, k)))
    t_cold = (time.perf_counter() - t0) / n_cold

    res = {"dense_lookup_s": round(t_dense, 5),
           "host_lookup_s": round(t_host, 5),
           "host_overhead_x": round(t_host / t_dense, 2),
           "lookups_per_s_host": round(batch * k / t_host, 0),
           "first_touch_s_per_batch": round(t_cold, 5),
           "first_touch_rows_per_s": round(batch * k / t_cold, 0)}
    if not quiet:
        print(f"embedding lookup  dense {t_dense*1e3:.2f} ms   "
              f"host-offloaded {t_host*1e3:.2f} ms   "
              f"({res['host_overhead_x']}x)   first-touch "
              f"{t_cold*1e3:.2f} ms/batch "
              f"({res['first_touch_rows_per_s']:.0f} rows/s)")
    return res


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()
    pp = gpt_pp_vs_dense(args.steps, quiet=args.json)
    emb = host_embedding_vs_dense(max(args.steps, 16), quiet=args.json)
    if args.json:
        print(json.dumps({"pp": pp, "embedding": emb}))


if __name__ == "__main__":
    main()
