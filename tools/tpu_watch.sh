#!/bin/bash
# Round-long TPU watcher (VERDICT r4 item 1: the chip must be caught
# whenever it comes up, not only at one end-of-round attempt).
#
# Probes device init in a FRESH subprocess each time — a wedged PJRT
# backend init never recovers in-process, but a new process can succeed
# once the tunnel frees up. On success, runs the requested sweep tags
# (each tag itself a fresh subprocess, tools/tpu_sweep.py) and exits.
#
# Usage: tools/tpu_watch.sh [comma-tags] [probe_timeout_s] [sleep_s]
cd "$(dirname "$0")/.." || exit 1
TAGS="${1:-resnet50,bert,widedeep,widedeep_host,gpt2_xl}"
PROBE_TIMEOUT="${2:-300}"
SLEEP_S="${3:-90}"
LOG=PERF_SWEEP_WATCH.log
while true; do
  if timeout "$PROBE_TIMEOUT" python -c "import jax; jax.devices()" \
      >/dev/null 2>&1; then
    echo "$(date -u +%FT%TZ) chip up; sweeping $TAGS" >> "$LOG"
    BEFORE=$(grep -c '"value"' PERF_SWEEP.jsonl 2>/dev/null || echo 0)
    python tools/tpu_sweep.py PERF_SWEEP.jsonl "$TAGS" 2>> "$LOG"
    RC=$?
    AFTER=$(grep -c '"value"' PERF_SWEEP.jsonl 2>/dev/null || echo 0)
    echo "$(date -u +%FT%TZ) sweep done rc=$RC rows=$((AFTER - BEFORE))" \
      >> "$LOG"
    # only stand down once the sweep actually landed a measurement —
    # a chip that answers the probe but flakes mid-sweep must not
    # cost the rest of the round's benchmark window
    if [ "$RC" -eq 0 ] && [ "$AFTER" -gt "$BEFORE" ]; then
      exit 0
    fi
  fi
  echo "$(date -u +%FT%TZ) probe failed/timed out" >> "$LOG"
  sleep "$SLEEP_S"
done
