"""Public Python-API parity report against the reference's ``paddle.*``
surface (VERDICT r3 ask #4 — the yaml op registries measured by
op_coverage.py are not the whole user-facing surface).

Enumerates the reference's public names from its package ``__all__``
lists (reference: python/paddle/__init__.py:269-name export list;
nn/tensor/static/distribution/... ``__all__``s; tensor_method_func —
python/paddle/tensor/__init__.py:281 — the Tensor-method surface) and
resolves each against this framework's namespaces. Buckets:

  - direct:   same name importable at the mirrored paddle_tpu path
  - alias:    served under a different (modern) name — mapped explicitly
  - declined: deliberately not carried, with a recorded reason

Run: ``python tools/api_coverage.py [--json] [--missing]``. The suite
gates the missing list empty (tests/test_api_coverage.py) so any new
reference export — or a regression dropping one of ours — fails CI.
"""

from __future__ import annotations

import argparse
import importlib
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # repo root, when run as a script

REF = "/root/reference/python/paddle"

# (label, reference file, resolver module paths in paddle_tpu)
SURFACES = [
    ("paddle", "__init__.py", ["paddle_tpu"]),
    ("paddle.Tensor", "tensor/__init__.py",
     ["paddle_tpu.tensor", "paddle_tpu"]),
    ("paddle.nn", "nn/__init__.py", ["paddle_tpu.nn"]),
    ("paddle.nn.functional", "nn/functional/__init__.py",
     ["paddle_tpu.nn.functional"]),
    ("paddle.nn.initializer", "nn/initializer/__init__.py",
     ["paddle_tpu.nn.initializer"]),
    ("paddle.static", "static/__init__.py", ["paddle_tpu.static"]),
    ("paddle.static.nn", "static/nn/__init__.py",
     ["paddle_tpu.static.nn"]),
    ("paddle.distribution", "distribution/__init__.py",
     ["paddle_tpu.distribution"]),
    ("paddle.linalg", "linalg.py", ["paddle_tpu.linalg"]),
    ("paddle.fft", "fft.py", ["paddle_tpu.fft"]),
    ("paddle.signal", "signal.py", ["paddle_tpu.signal"]),
    ("paddle.vision", "vision/__init__.py", ["paddle_tpu.vision"]),
    ("paddle.vision.models", "vision/models/__init__.py",
     ["paddle_tpu.vision.models", "paddle_tpu.models"]),
    ("paddle.vision.ops", "vision/ops.py", ["paddle_tpu.vision.ops"]),
    ("paddle.vision.transforms", "vision/transforms/__init__.py",
     ["paddle_tpu.vision.transforms"]),
    ("paddle.optimizer", "optimizer/__init__.py",
     ["paddle_tpu.optimizer"]),
    ("paddle.optimizer.lr", "optimizer/lr.py",
     ["paddle_tpu.optimizer.lr"]),
    ("paddle.metric", "metric/__init__.py", ["paddle_tpu.metric"]),
    ("paddle.io", "io/__init__.py", ["paddle_tpu.io"]),
    ("paddle.amp", "amp/__init__.py", ["paddle_tpu.amp"]),
    ("paddle.jit", "jit/__init__.py", ["paddle_tpu.jit"]),
    ("paddle.distributed", "distributed/__init__.py",
     ["paddle_tpu.distributed", "paddle_tpu.parallel"]),
    ("paddle.text", "text/__init__.py", ["paddle_tpu.text"]),
    ("paddle.onnx", "onnx/__init__.py", ["paddle_tpu.onnx"]),
    ("paddle.autograd", "autograd/__init__.py",
     ["paddle_tpu.autograd"]),
    ("paddle.device", "device/__init__.py", ["paddle_tpu.device"]),
    ("paddle.regularizer", "regularizer.py",
     ["paddle_tpu.regularizer"]),
    ("paddle.sysconfig", "sysconfig.py", ["paddle_tpu.sysconfig"]),
    ("paddle.hub", "hapi/hub.py", ["paddle_tpu.hub"]),
    ("paddle.sparse", "incubate/sparse/__init__.py",
     ["paddle_tpu.sparse"]),
]

# Covered under a different, deliberately-modern name. Keys are
# "<label>.<name>"; values say where the capability lives.
ALIASES: dict[str, str] = {}

# Deliberately not carried — decision records. Keys "<label>.<name>".
DECLINED: dict[str, str] = {
    "paddle.static.IpuCompiledProgram":
        "Graphcore IPU vendor runtime (reference: "
        "python/paddle/static/__init__ → fluid/compiler.py "
        "IpuCompiledProgram over the popart backend). This build "
        "targets PJRT:TPU; vendor-accelerator compilation lives "
        "behind PJRT plugins, not per-vendor compile classes — the "
        "device/ module's plugin story is the analog.",
    "paddle.static.IpuStrategy":
        "IPU vendor config object — same decision as "
        "IpuCompiledProgram.",
    "paddle.static.ipu_shard_guard":
        "IPU pipeline-stage pinning context — stage placement here is "
        "mesh sharding (parallel.pipeline), not per-op device pins.",
    "paddle.static.set_ipu_shard":
        "same decision as ipu_shard_guard.",
    "paddle.onnx.export":
        "ONNX interchange (reference: python/paddle/onnx/export.py → "
        "external paddle2onnx). The deployment IR here is serialized "
        "StableHLO (jit.save → native/predictor.cc serving, "
        "quantized artifacts) — a second interchange format would "
        "duplicate that path; StableHLO is itself an open interchange "
        "consumed beyond XLA.",
}


def _extract_all(path: str) -> list[str]:
    try:
        src = open(path).read()
    except OSError:
        return []
    names: list[str] = []
    m = re.search(r"^__all__\s*=\s*\[(.*?)\]", src, re.S | re.M)
    if m:
        names += re.findall(r"['\"]([^'\"]+)['\"]", m.group(1))
    for extra in re.finditer(r"__all__\s*\+=\s*\[(.*?)\]", src, re.S):
        names += re.findall(r"['\"]([^'\"]+)['\"]", extra.group(1))
    if not names and "tensor/__init__" in path:
        m = re.search(r"tensor_method_func\s*=\s*\[(.*?)\]", src, re.S)
        if m:
            names = re.findall(r"['\"]([^'\"]+)['\"]", m.group(1))
    return sorted(set(n for n in names
                      if not n.startswith("_")))


def _resolve(mods: list[object], name: str) -> bool:
    for mod in mods:
        if mod is not None and hasattr(mod, name):
            return True
    return False


def collect() -> dict:
    out = {"surfaces": {}, "totals": {}}
    tot = {"direct": 0, "alias": 0, "declined": 0, "missing": 0}
    missing_list = []
    for label, rel, mod_paths in SURFACES:
        names = _extract_all(os.path.join(REF, rel))
        if not names:
            continue
        mods = []
        for mp in mod_paths:
            try:
                mods.append(importlib.import_module(mp))
            except Exception:
                mods.append(None)
        res = {"direct": [], "alias": [], "declined": [], "missing": []}
        for n in names:
            key = f"{label}.{n}"
            if _resolve(mods, n):
                res["direct"].append(n)
            elif key in ALIASES:
                res["alias"].append(n)
            elif key in DECLINED:
                res["declined"].append(n)
            else:
                res["missing"].append(n)
                missing_list.append(key)
        out["surfaces"][label] = {k: len(v) for k, v in res.items()}
        out["surfaces"][label]["missing_names"] = res["missing"]
        for k in tot:
            tot[k] += len(res[k])
    total = sum(tot.values())
    out["totals"] = dict(tot, total=total,
                         covered_pct=round(
                             100 * (total - tot["missing"])
                             / max(total, 1), 2))
    out["missing_keys"] = missing_list
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--missing", action="store_true")
    args = ap.parse_args()
    rep = collect()
    if args.json:
        print(json.dumps(rep))
        return
    t = rep["totals"]
    print(f"{'surface':28s} {'direct':>6} {'alias':>6} "
          f"{'declined':>8} {'missing':>7}")
    for label, r in rep["surfaces"].items():
        print(f"{label:28s} {r['direct']:6d} {r['alias']:6d} "
              f"{r['declined']:8d} {r['missing']:7d}")
    print(f"{'TOTAL':28s} {t['direct']:6d} {t['alias']:6d} "
          f"{t['declined']:8d} {t['missing']:7d}   "
          f"({t['covered_pct']}% adjudicated)")
    if args.missing:
        for k in rep["missing_keys"]:
            print("MISSING", k)


if __name__ == "__main__":
    main()
