"""Benchmark harness — prints ONE JSON line for the driver.

Covers the operative BASELINE.md configs on the available hardware
(real TPU chip under the driver; CPU smoke otherwise):

  - GPT-2-small causal-LM training  (BASELINE config 4 family; headline)
  - ResNet-50 ImageNet-shape training (BASELINE config 2)
  - BERT-base pretraining            (BASELINE config 3)

Each sub-benchmark reports throughput AND MFU (model FLOPs per second /
chip bf16 peak), so the number carries its own context. The measured
step is the same compiled step `paddle_tpu.Model.fit` runs — framework
end-to-end, not a kernel in isolation. Timing loops enqueue steps
asynchronously and block once on the final result (the trainer no longer
syncs per step).

FLOPs accounting (standard MFU conventions, PaLM appendix B):
  transformer train FLOPs/token = 6*N_params + attention term
    (causal GPT: 6*L*s*H; bidirectional BERT: 12*L*s*H)
  resnet: 3x forward FLOPs, forward measured analytically per conv.

``vs_baseline`` compares the headline GPT tokens/sec against round 1's
measured 47224.8 (BENCH_r01.json) — the reference publishes no in-tree
numbers (BASELINE.md: `published == {}`).
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Optional

import numpy as np

ROUND1_GPT_TOKENS_PER_SEC = 47224.8


def _ledger_append(workload: str, value: float, unit: str, **kw):
    """Append the canonical trajectory row (tools/bench_ledger.py).
    Best-effort by contract: the measurement already printed; a ledger
    hiccup must never cost the driver its line. Every row also carries
    the time ledger's goodput verdict on the run (absent when that
    ledger is off — old-schema tolerance)."""
    try:
        from tools import bench_ledger
        for k, v in bench_ledger.goodput_row_fields().items():
            kw.setdefault(k, v)
        bench_ledger.append("bench", workload, value, unit, **kw)
    except Exception as e:  # noqa: BLE001
        print(f"bench: ledger append failed: {e}", file=sys.stderr)

def chip_peak_flops():
    """bf16 peak FLOP/s of the attached chip, or None (CPU/unknown —
    mfu reads null). One table for the whole repo: the live roofline
    gauges and the bench MFU column must agree on the denominator
    (observability/perf.py PEAK_TABLE; FLAGS.perf_peak_flops
    overrides both)."""
    import jax
    from paddle_tpu.core import flags as _flags
    from paddle_tpu.observability.perf import peak_flops_for
    override = float(_flags.get_flag("perf_peak_flops") or 0.0)
    if override > 0:
        return override
    d = jax.devices()[0]
    return peak_flops_for(getattr(d, "device_kind", ""))


def param_count(net) -> int:
    from paddle_tpu.nn.layer import split_state
    params, _ = split_state(net)
    return int(sum(np.prod(v.shape) for v in params.values()))


def _device_feed(feed):
    """Pre-place the synthetic batch on device and force arrival.

    The input pipeline is benchmarked separately (io tests); feeding
    host arrays here would measure the host→device link, not the
    training step. A tiny reduction FETCHED to host proves arrival —
    on tunneled PJRT backends `block_until_ready` can signal at enqueue,
    so only a host value fetch is a true synchronization point."""
    import jax
    import jax.numpy as jnp
    placed = jax.tree_util.tree_map(
        lambda x: jax.device_put(np.asarray(x)), feed)
    for leaf in jax.tree_util.tree_leaves(placed):
        float(jnp.sum(leaf.astype(jnp.float32)))
    return placed


def _timed_steps(model, feed, warmup: int, iters: int) -> float:
    """Warmup, then time `iters` chained steps. The device queue is
    drained by FETCHING the final loss to host inside the timed region
    (see _device_feed: block_until_ready is not a reliable sync here)."""
    feed = _device_feed(feed)
    logs = None
    for _ in range(warmup):
        logs = model.train_batch(*feed)
    float(np.asarray(logs["loss"]))  # true sync
    t0 = time.perf_counter()
    for _ in range(iters):
        logs = model.train_batch(*feed)
    val = np.asarray(logs["loss"])   # true sync, inside the timing
    dt = time.perf_counter() - t0
    assert np.isfinite(val), logs
    return dt


def _mfu(model_flops_per_sec) -> float | None:
    peak = chip_peak_flops()
    if peak is None or model_flops_per_sec is None:
        return None
    return round(model_flops_per_sec / peak, 4)


# ---------------------------------------------------------------------------
# config 4 family: GPT-2-small (headline)
# ---------------------------------------------------------------------------

def bench_gpt(batch: int = 8, seq: int = 1024, warmup: int = 3,
              iters: int = 20, cpu_smoke: bool = False,
              model_name: str = "gpt2-small", fused: bool = True,
              scan_layers: bool = False, remat: bool = False,
              optimizer: str = "adamw", param_dtype: str = None):
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import (GPTForCausalLM,
                                       GPTFusedPretrainingCriterion,
                                       GPTPretrainingCriterion,
                                       gpt_config)

    paddle.seed(0)
    # fused vocab path: loss streams over vocab chunks, [b,s,V] logits
    # never hit HBM (ops/fused_xent.py; equality with the dense path is
    # asserted in tests/test_fused_xent.py); fused=False measures the
    # dense-logits path for the ± comparison
    if cpu_smoke:
        cfg = gpt_config("gpt2-small", num_layers=2, hidden_size=256,
                         num_heads=4, max_position_embeddings=seq,
                         hidden_dropout=0.0, attention_dropout=0.0,
                         fused_loss=True)
        batch, iters = 2, 5
    else:
        cfg = gpt_config(model_name, max_position_embeddings=seq,
                         hidden_dropout=0.0, attention_dropout=0.0,
                         fused_loss=fused, scan_layers=scan_layers,
                         remat=remat)
    import contextlib
    if param_dtype:
        # the single-chip 1.5B recipe needs bf16 PARAM STORAGE
        # (FEASIBILITY_XL.json: fp32 params+grads alone overflow 16 GiB);
        # scoped so a later bench in this process builds fp32 again
        from paddle_tpu.core.dtype import default_dtype_guard
        guard = default_dtype_guard(param_dtype)
    else:
        guard = contextlib.nullcontext()
    with guard:
        net = GPTForCausalLM(cfg)
    model = paddle.Model(net)
    if optimizer == "adafactor":
        # the single-chip big-model configuration: factored second
        # moments keep optimizer state ~0 bytes/param vs AdamW's 8,
        # which is what lets GPT-2-XL (1.56B) train on one 16 GB chip
        opt = paddle.optimizer.Adafactor(
            learning_rate=1e-4, parameters=net,
            multi_precision=param_dtype is None)
    elif optimizer == "adamw":
        opt = paddle.optimizer.AdamW(learning_rate=1e-4, parameters=net,
                                     weight_decay=0.01)
    else:  # a typo must not stamp a wrong optimizer into the record
        raise ValueError(f"unknown optimizer {optimizer!r}")
    model.prepare(
        optimizer=opt,
        loss=(GPTFusedPretrainingCriterion() if cfg.fused_loss
              else GPTPretrainingCriterion()),
        amp_configs="O1")
    n_params = param_count(net)

    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (batch, seq))
    dt = _timed_steps(model, ([ids], [ids]), warmup, iters)
    tps = batch * seq * iters / dt
    # causal attention: 6*L*s*H train FLOPs per token
    flops_per_token = 6 * n_params + \
        6 * cfg.num_layers * seq * cfg.hidden_size
    return {"metric": "gpt2s_train_tokens_per_sec",
            "value": round(tps, 1), "unit": "tokens/sec",
            "batch": batch, "seq": seq, "params": n_params,
            "model": model_name, "fused": cfg.fused_loss,
            "scan": cfg.scan_layers, "remat": cfg.remat,
            "optimizer": optimizer, "param_dtype": param_dtype or "float32",
            "mfu": _mfu(tps * flops_per_token)}


def bench_steps_per_loop(ks=(1, 8, 32), cpu_smoke: bool = True):
    """Dispatch-overhead sweep (ISSUE 3 / PERF.md "dispatch overhead"):
    the SAME train step run K optimizer steps per XLA dispatch through
    the fused lax.scan loop (`Model.train_loop_batch`). K=1 pays one
    Python→XLA dispatch + one prefetch handoff per step; K>1 amortizes
    both across the slab. Losses are bit-identical across K (pinned by
    tests/test_train_loop.py), so the per-step wall-time delta IS the
    dispatch overhead. Feed is pre-placed on device (`_device_feed`),
    warmup slab excluded (compile), final loss fetched inside the timed
    region (true sync)."""
    import jax
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import (GPTForCausalLM,
                                       GPTFusedPretrainingCriterion,
                                       gpt_config)

    if cpu_smoke:
        # seq 64 stays under the flash-kernel block threshold: the XLA
        # attention path keeps the step itself cheap, so the per-step
        # delta is dominated by what this sweep measures — dispatch
        batch, seq, total_steps = 2, 64, 32
        cfg_kw = dict(num_layers=2, hidden_size=256, num_heads=4)
    else:
        batch, seq, total_steps = 8, 1024, 32
        cfg_kw = {}
    from paddle_tpu.observability import tracing
    rs = np.random.RandomState(0)
    rows = []
    for k in ks:
        n = total_steps - (total_steps % k)
        if n == 0:
            continue
        paddle.seed(0)
        cfg = gpt_config("gpt2-small", max_position_embeddings=seq,
                         hidden_dropout=0.0, attention_dropout=0.0,
                         fused_loss=True, **cfg_kw)
        net = GPTForCausalLM(cfg)
        model = paddle.Model(net)
        model.prepare(
            optimizer=paddle.optimizer.AdamW(learning_rate=1e-4,
                                             parameters=net,
                                             weight_decay=0.01),
            loss=GPTFusedPretrainingCriterion(), amp_configs="O1")
        ids = rs.randint(0, cfg.vocab_size, (batch, seq))
        # tracing ON for the timed region (span bookkeeping is a few
        # host dict ops per DISPATCH — noise against the XLA step) so
        # the row says where wall time went, not just the total
        tracing.clear()
        tracing.enable()
        if k == 1:
            feed = _device_feed(([ids], [ids]))
            logs = model.train_batch(*feed)          # warmup + compile
            float(np.asarray(logs["loss"]))
            tracing.clear()                          # drop the warmup
            t0 = time.perf_counter()
            for _ in range(n):
                logs = model.train_batch(*feed)
            float(np.asarray(logs["loss"]))          # true sync
            dt = time.perf_counter() - t0
        else:
            slab = np.broadcast_to(ids, (k,) + ids.shape).copy()
            feed = _device_feed(([slab], [slab]))
            logs = model.train_loop_batch(*feed)     # warmup + compile
            float(np.asarray(logs[-1]["loss"]))
            tracing.clear()                          # drop the warmup
            t0 = time.perf_counter()
            for _ in range(n // k):
                logs = model.train_loop_batch(*feed)
            float(np.asarray(logs[-1]["loss"]))      # true sync
            dt = time.perf_counter() - t0
        rollup = {name: {"total_s": v["total_s"], "count": v["count"],
                         "share_of_wall": round(v["total_s"] / dt, 4)}
                  for name, v in tracing.rollup(prefix="train.").items()}
        tracing.disable()
        rows.append({"steps_per_loop": k, "steps": n,
                     "per_step_ms": round(dt / n * 1e3, 3),
                     "tokens_per_sec": round(batch * seq * n / dt, 1),
                     "span_rollup": rollup})
    base = next((r for r in rows if r["steps_per_loop"] == 1), None)
    if base:
        for r in rows:
            r["speedup_vs_k1"] = round(
                base["per_step_ms"] / r["per_step_ms"], 3)
    return {"metric": "train_loop_dispatch_sweep", "batch": batch,
            "seq": seq, "rows": rows}


# ---------------------------------------------------------------------------
# config 5: Wide&Deep CTR (sparse embedding + PS-analog host table)
# ---------------------------------------------------------------------------

def bench_widedeep(batch: int = 16384, warmup: int = 3, iters: int = 30,
                   cpu_smoke: bool = False, table: str = "hbm"):
    """Criteo-shape CTR training: 13 dense + 26 categorical slots into a
    shared table, wide+deep towers, BCE loss. ``table="hbm"`` keeps a
    1M-row table on device (pure-SPMD CTR); ``table="host"`` trains
    against a 100M-id HOST-RAM table pulled/pushed per step — the
    parameter-server workload the reference ran on CPU clusters
    (BASELINE config 5). Metric: samples/sec (CTR is lookup/bandwidth
    bound; MFU is not meaningful)."""
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.models.widedeep import WideDeep, WideDeepHostTable

    paddle.seed(0)
    if cpu_smoke:
        batch, iters = 256, 3
    if table == "host":
        net = WideDeepHostTable(vocab_size=100 * 1000 * 1000,
                                embedding_dim=16)
    else:
        net = WideDeep(vocab_size=1000 * 1000, embedding_dim=16)
    model = paddle.Model(net)
    model.prepare(
        optimizer=paddle.optimizer.Adam(learning_rate=1e-3,
                                        parameters=net),
        loss=nn.BCEWithLogitsLoss())
    rng = np.random.RandomState(0)
    dense = rng.randn(batch, 13).astype(np.float32)
    # raw 2^31-range ids, hash-folded by the table (the Criteo regime:
    # ids far exceed any dense table range)
    sparse = rng.randint(0, 1 << 31, (batch, 26)).astype(np.int64)
    labels = (rng.rand(batch) < 0.3).astype(np.float32)
    dt = _timed_steps(model, ([dense, sparse], [labels]), warmup, iters)
    sps = batch * iters / dt
    return {"metric": f"widedeep_{table}_train_samples_per_sec",
            "value": round(sps, 1), "unit": "samples/sec",
            "batch": batch, "table": table,
            "lookups_per_sec": round(sps * 26, 1), "mfu": None}


# ---------------------------------------------------------------------------
# LLM decode serving (continuous batching; VERDICT r4 item 4)
# ---------------------------------------------------------------------------

def bench_llm_decode(n_requests: int = 16, max_seqs: int = 8,
                     prompt_len: int = 128, gen_len: int = 128,
                     cpu_smoke: bool = False,
                     model_name: str = "gpt2-small",
                     lookahead: int = 0):
    """Multi-client decode throughput through LLMEngine: n_requests
    greedy generations (prompt_len ctx, gen_len new tokens) share one
    engine with max_seqs slots. Metrics: aggregate generated tokens/sec
    (the serving headline), mean per-request latency, mean TTFT."""
    import paddle_tpu as paddle
    from paddle_tpu.inference.llm import LLMEngine
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt_config

    paddle.seed(0)
    if cpu_smoke:
        cfg = gpt_config("gpt2-small", num_layers=2, hidden_size=128,
                         num_heads=4, vocab_size=503,
                         max_position_embeddings=256,
                         hidden_dropout=0.0, attention_dropout=0.0)
        n_requests, prompt_len, gen_len = 4, 16, 16
    else:
        cfg = gpt_config(model_name, hidden_dropout=0.0,
                         attention_dropout=0.0)
    from paddle_tpu.observability import tracing
    net = GPTForCausalLM(cfg)
    total = prompt_len + gen_len
    pages = -(-total // 16) * max_seqs + 8
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, cfg.vocab_size, prompt_len).tolist()
               for _ in range(n_requests)]
    with LLMEngine(net, max_seqs=max_seqs, page_size=16,
                   num_pages=pages, max_len=total,
                   prefill_buckets=(prompt_len,),
                   lookahead=lookahead) as eng:
        # warmup compiles prefill + decode
        eng.generate([prompts[0]], max_new_tokens=2)
        tracing.clear()
        tracing.enable()           # per-phase rollup for the BENCH row
        t0 = time.perf_counter()
        futs = [eng.submit(p, max_new_tokens=gen_len) for p in prompts]
        outs = [f.result() for f in futs]
        dt = time.perf_counter() - t0
    # phases tile llm.request, so excluding the root gives shares
    # over where each request's wall time actually went
    rollup = tracing.rollup(prefix="llm.", exclude=("llm.request",))
    tracing.disable()
    gen_tokens = sum(len(o["output_ids"]) for o in outs)
    assert not any(o["truncated"] for o in outs)
    return {"metric": "llm_decode_tokens_per_sec",
            "value": round(gen_tokens / dt, 1), "unit": "tokens/sec",
            "model": model_name, "n_requests": n_requests,
            "max_seqs": max_seqs, "prompt_len": prompt_len,
            "gen_len": gen_len, "lookahead": lookahead,
            "mean_latency_s": round(float(np.mean(
                [o["latency_s"] for o in outs])), 3),
            "mean_ttft_s": round(float(np.mean(
                [o["ttft_s"] for o in outs])), 3),
            "span_rollup": rollup,
            "mfu": None}


# ---------------------------------------------------------------------------
# config 2: ResNet-50 ImageNet-shape
# ---------------------------------------------------------------------------

RESNET50_FWD_FLOPS = 4.09e9   # per 224x224 image, 2*MACs convention


def bench_resnet(batch: int = 128, warmup: int = 3, iters: int = 30,
                 cpu_smoke: bool = False):
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.models.resnet import resnet50

    paddle.seed(0)
    size = 32 if cpu_smoke else 224
    if cpu_smoke:
        batch, iters = 4, 3
    net = resnet50()
    model = paddle.Model(net)
    model.prepare(
        optimizer=paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                            parameters=net),
        loss=nn.CrossEntropyLoss(),
        amp_configs="O1")
    rng = np.random.RandomState(0)
    imgs = rng.randn(batch, 3, size, size).astype(np.float32)
    labels = rng.randint(0, 1000, (batch, 1))
    dt = _timed_steps(model, ([imgs], [labels]), warmup, iters)
    ips = batch * iters / dt
    flops_per_img = 3 * RESNET50_FWD_FLOPS * (size / 224.0) ** 2
    return {"metric": "resnet50_train_images_per_sec",
            "value": round(ips, 1), "unit": "images/sec",
            "batch": batch, "image_size": size,
            "mfu": _mfu(ips * flops_per_img) if size == 224 else None}


# ---------------------------------------------------------------------------
# config 3: BERT-base pretraining
# ---------------------------------------------------------------------------

def bench_bert(batch: int = 64, seq: int = 128, warmup: int = 3,
               iters: int = 30, cpu_smoke: bool = False,
               scan_layers: bool = False, remat: bool = False):
    import paddle_tpu as paddle
    from paddle_tpu.models.bert import (BertForPretraining,
                                        BertFusedPretrainingCriterion,
                                        bert_config)

    paddle.seed(0)
    if cpu_smoke:
        cfg = bert_config("bert-base", num_layers=2, hidden_size=128,
                          num_heads=2, hidden_dropout=0.0,
                          attention_dropout=0.0, fused_loss=True)
        batch, iters = 2, 3
    else:
        cfg = bert_config("bert-base", hidden_dropout=0.0,
                          attention_dropout=0.0, fused_loss=True,
                          scan_layers=scan_layers, remat=remat)
    net = BertForPretraining(cfg)
    model = paddle.Model(net)
    model.prepare(
        optimizer=paddle.optimizer.AdamW(learning_rate=1e-4, parameters=net,
                                         weight_decay=0.01),
        loss=BertFusedPretrainingCriterion(),
        amp_configs="O1")
    n_params = param_count(net)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (batch, seq))
    mlm_labels = np.where(rng.rand(batch, seq) < 0.15, ids, -100)
    nsp = rng.randint(0, 2, (batch,))

    dt = _timed_steps(model, ([ids], [mlm_labels, nsp]), warmup, iters)
    sps = batch * iters / dt
    flops_per_token = 6 * n_params + \
        12 * cfg.num_layers * seq * cfg.hidden_size
    return {"metric": "bertbase_train_samples_per_sec",
            "value": round(sps, 1), "unit": "samples/sec",
            "batch": batch, "seq": seq, "params": n_params,
            "scan": cfg.scan_layers, "remat": cfg.remat,
            "mfu": _mfu(sps * seq * flops_per_token)}


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default  # malformed env must not kill the bench


def _run_child(env_extra: dict, timeout: float):
    """Run this file in a child with extra env; return
    (rc_or_None_on_timeout, stdout, stderr)."""
    import subprocess
    env = dict(os.environ, **env_extra)
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__)], env=env,
            capture_output=True, text=True, timeout=timeout)
        return out.returncode, out.stdout, out.stderr
    except subprocess.TimeoutExpired as e:
        return None, (e.stdout or b"").decode("utf-8", "replace") \
            if isinstance(e.stdout, bytes) else (e.stdout or ""), \
            (e.stderr or b"").decode("utf-8", "replace") \
            if isinstance(e.stderr, bytes) else (e.stderr or "")


def _orchestrate():
    """Round-long windowed device acquisition (VERDICT r4 'weak' #1:
    one 300 s window then CPU fallback loses the round's hardware
    evidence whenever the tunnel is busy at that one moment).

    This process NEVER touches jax: it probes device init in fresh
    child processes (a wedged PJRT init never recovers in-process, but
    a new process can succeed once the tunnel frees), and when a probe
    lands it runs the measuring child on the TPU. Partial sub-bench
    results persist to BENCH_PARTIAL.jsonl as they complete, so a
    mid-bench tunnel death still leaves rows. Only after every window
    fails does the CPU-smoke child run — carrying the round's best
    hardware rows (PERF_SWEEP.jsonl) in the record."""
    import subprocess

    probe_timeout = _env_float("PT_BENCH_DEVICE_TIMEOUT", 240)
    windows = int(_env_float("PT_BENCH_WINDOWS", 3))
    worker_timeout = _env_float("PT_BENCH_WORKER_TIMEOUT", 3600)
    window_span = _env_float("PT_BENCH_WINDOW_SPAN", 240)
    probe_src = "import jax; print(jax.devices()[0].device_kind)"
    # fresh run, fresh partial log: stale rows from an earlier round
    # must not masquerade as this run's hardware evidence
    try:
        open(_PARTIAL_PATH, "w").close()
    except OSError:
        pass
    err = ""
    transient = ("RESOURCE_EXHAUSTED", "remote_compile", "UNAVAILABLE",
                 "wedged", "DEADLINE")
    for w in range(windows):
        t0 = time.time()
        try:
            p = subprocess.run([sys.executable, "-c", probe_src],
                               capture_output=True, text=True,
                               timeout=probe_timeout)
            # a fast-failing plugin falls back to the CPU backend with
            # rc 0 — that is NOT TPU acquisition; check the device kind
            ok = p.returncode == 0 and "TPU" in (p.stdout or "")
            err = "" if ok else (
                f"probe rc {p.returncode}, device "
                f"{(p.stdout or '').strip()[:40]!r}: "
                f"{(p.stderr or '')[-200:]}")
        except subprocess.TimeoutExpired:
            ok = False
            err = (f"device init exceeded {probe_timeout:.0f}s — TPU "
                   f"tunnel busy or wedged")
        if ok:
            rc, stdout, stderr = _run_child({"PT_BENCH_CHILD": "1"},
                                            worker_timeout)
            lines = [l for l in stdout.splitlines()
                     if l.startswith("{")]
            if rc == 0 and lines:
                print(lines[-1])
                sys.stdout.flush()
                return 0
            if lines:
                payload = None
                try:
                    payload = json.loads(lines[-1])
                except ValueError:
                    pass
                bench_err = (payload or {}).get("error", "")
                if bench_err and not any(t in bench_err
                                         for t in transient):
                    # a deterministic bench bug: the worker's error
                    # record IS the honest output — re-running the
                    # whole suite `windows` times would not change it
                    print(lines[-1])
                    sys.stdout.flush()
                    return 0
            err = (f"tpu worker rc {rc}; stderr tail: "
                   f"{(stderr or '')[-300:]!r}")
            print(f"bench: worker window {w + 1}/{windows} failed: "
                  f"{err}", file=sys.stderr)
        else:
            print(f"bench: probe window {w + 1}/{windows} failed: "
                  f"{err}", file=sys.stderr)
        # a window spans real time even when the probe fails FAST
        # (connection refused) — otherwise 3 windows burn in seconds
        # and the round-long acquisition never happens
        if w < windows - 1:
            remaining = window_span - (time.time() - t0)
            if remaining > 0:
                time.sleep(remaining)
    # every window failed: CPU smoke, carrying partial + sweep evidence
    rc, stdout, stderr = _run_child({"PT_BENCH_FORCE_CPU": "1"}, 1800)
    lines = [l for l in stdout.splitlines() if l.startswith("{")]
    try:
        payload = json.loads(lines[-1])
        if rc != 0 or "error" in payload:
            raise RuntimeError(f"child rc {rc}, "
                               f"error {payload.get('error')!r:.200}")
        payload["tpu_error"] = err or "no probe window succeeded"
        partial = _read_partial()
        if partial:
            payload["tpu_partial"] = partial
        print(json.dumps(payload))
        sys.stdout.flush()
        return 0
    except Exception as e:  # fallback failed too: keep the honest error
        err += f"; cpu fallback failed: {e!r:.200}"
        if stderr:
            err += f"; child stderr tail: {stderr[-300:]!r}"
    print(json.dumps({"metric": "bench_error", "value": 0.0,
                      "unit": "none", "vs_baseline": 0.0, "error": err}))
    sys.stdout.flush()
    return 3


_PARTIAL_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "BENCH_PARTIAL.jsonl")


def _persist_partial(name: str, rec: dict) -> None:
    try:
        with open(_PARTIAL_PATH, "a") as f:
            f.write(json.dumps({"bench": name, **rec,
                                "ts": time.time()}) + "\n")
    except OSError:
        pass  # persistence must never fail the measurement


def _read_partial():
    """Best row per bench from this round's partial log."""
    if not os.path.exists(_PARTIAL_PATH):
        return None
    best = {}
    for line in open(_PARTIAL_PATH):
        try:
            d = json.loads(line)
        except ValueError:
            continue
        name = d.get("bench")
        if name and "value" in d and (
                name not in best or d["value"] > best[name]["value"]):
            best[name] = d
    return best or None


def _last_hw_sweep():
    """Best per-tag hardware rows from PERF_SWEEP.jsonl, if present."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "PERF_SWEEP.jsonl")
    if not os.path.exists(path):
        return None
    best = {}
    for line in open(path):
        try:
            d = json.loads(line)
        except ValueError:
            continue
        if "error" in d or "value" not in d:
            continue
        tag = d.get("tag", d.get("metric", "?"))
        if tag not in best or d["value"] > best[tag]["value"]:
            best[tag] = d
    return {t: {"value": r["value"], "unit": r["unit"],
                "mfu": r.get("mfu"), "batch": r.get("batch"),
                "device": r.get("device")}
            for t, r in best.items()} or None


def main():
    if not os.environ.get("PT_BENCH_FORCE_CPU") and \
            not os.environ.get("PT_BENCH_CHILD"):
        # orchestrator: probes/benches run in children; this process
        # never initializes a backend, so it cannot wedge
        raise SystemExit(_orchestrate())
    import jax
    if os.environ.get("PT_BENCH_FORCE_CPU"):
        # pin CPU before ANY device query (env vars are too late once
        # sitecustomize imported jax; in-code config is not)
        jax.config.update("jax_platforms", "cpu")
    else:
        # TPU worker: the orchestrator's probe just succeeded, but the
        # tunnel can wedge between processes — bound OUR init too and
        # exit nonzero (the orchestrator retries its windows) instead
        # of eating the whole worker timeout
        import threading
        done = threading.Event()
        box = {}

        def _probe():
            try:
                jax.devices()
            except BaseException as e:  # report the real cause below
                box["exc"] = e
            finally:
                done.set()

        threading.Thread(target=_probe, daemon=True).start()
        if not done.wait(_env_float("PT_BENCH_DEVICE_TIMEOUT", 240)):
            print("bench worker: device init wedged", file=sys.stderr)
            os._exit(7)
        if "exc" in box:
            print(f"bench worker: device init failed: "
                  f"{box['exc']!r:.300}", file=sys.stderr)
            os._exit(7)
        if jax.default_backend() == "cpu":
            # plugin fell back between the orchestrator's probe and us:
            # a TPU worker must not silently produce a CPU record
            print("bench worker: backend fell back to CPU",
                  file=sys.stderr)
            os._exit(7)
    cpu_smoke = jax.default_backend() == "cpu"
    extra = {}
    for name, fn in (("resnet50", bench_resnet), ("bert", bench_bert),
                     ("widedeep", bench_widedeep)):
        try:
            extra[name] = fn(cpu_smoke=cpu_smoke)
            if not cpu_smoke:
                _persist_partial(name, extra[name])
        except Exception as e:  # noqa: BLE001 — report, keep the line
            extra[name] = {"error": str(e)[:200]}
            print(f"bench {name} failed: {e}", file=sys.stderr)

    metric = "gpt2s_train_tokens_per_sec"
    try:
        if cpu_smoke:
            gpt = bench_gpt(cpu_smoke=True)
        else:
            # batch is NOT monotone in throughput on this chip (r4
            # sweep, PERF.md: b8 88.4k > b16 85.7k > b32 78.0k tok/s —
            # the fused vocab path's HBM traffic grows with batch), so
            # time each candidate and report the best; OOM just drops
            # a candidate
            gpt = None
            last_msg = None
            for b in (8, 16, 32):
                try:
                    cand = bench_gpt(batch=b)
                except Exception as e:  # noqa: BLE001
                    msg = str(e)
                    if "RESOURCE_EXHAUSTED" not in msg and \
                            "out of memory" not in msg.lower():
                        raise
                    # drop the exception (its traceback pins the failed
                    # attempt's on-device buffers) before retrying
                    last_msg = msg[:300]
                    del e
                    print(f"bench gpt batch {b} OOM; skipping",
                          file=sys.stderr)
                    continue
                _persist_partial("gpt", cand)
                if gpt is None or cand["value"] > gpt["value"]:
                    gpt = cand
            if gpt is None:
                raise RuntimeError(f"all gpt batches OOMed: {last_msg}")
        if cpu_smoke:
            metric = "gpt2s_smoke_cpu_tokens_per_sec"
        vs = round(gpt["value"] / ROUND1_GPT_TOKENS_PER_SEC, 3) \
            if not cpu_smoke else 1.0
        rec = {"metric": metric,
               "value": gpt["value"],
               "unit": "tokens/sec",
               "vs_baseline": vs,
               "mfu": gpt.get("mfu"),
               "device": jax.devices()[0].device_kind,
               "extra": extra}
        if cpu_smoke:
            # the chip was unreachable for THIS run; carry the round's
            # real hardware evidence (tools/tpu_sweep.py) in the record
            # so a wedged end-of-round tunnel doesn't erase it
            hw = _last_hw_sweep()
            if hw:
                rec["last_hw_sweep"] = hw
        print(json.dumps(rec))
        _ledger_append(metric, gpt["value"], "tokens/sec",
                       tokens_per_sec=gpt["value"],
                       mfu=gpt.get("mfu"),
                       backend=jax.devices()[0].device_kind,
                       extra={"batch": gpt.get("batch"),
                              "model": gpt.get("model"),
                              "vs_baseline": vs})
    except Exception as e:  # never leave the driver without a line
        print(json.dumps({"metric": metric, "value": 0.0,
                          "unit": "tokens/sec", "vs_baseline": 0.0,
                          "error": str(e)[:200], "extra": extra}))
        print(f"bench failed: {e}", file=sys.stderr)
        raise


def _steps_per_loop_cli():
    """`python bench.py --steps-per-loop [1,8,32]`: run the fused-loop
    dispatch-overhead sweep on whatever backend is available (pin CPU
    with PT_BENCH_FORCE_CPU=1) and print one JSON line."""
    i = sys.argv.index("--steps-per-loop")
    ks = (1, 8, 32)
    if len(sys.argv) > i + 1 and not sys.argv[i + 1].startswith("-"):
        ks = tuple(int(v) for v in sys.argv[i + 1].split(","))
    import jax
    if os.environ.get("PT_BENCH_FORCE_CPU"):
        jax.config.update("jax_platforms", "cpu")
    rec = bench_steps_per_loop(ks=ks,
                               cpu_smoke=jax.default_backend() == "cpu")
    rec["device"] = jax.devices()[0].device_kind
    print(json.dumps(rec))
    sys.stdout.flush()
    best = max(rec["rows"], key=lambda r: r["tokens_per_sec"])
    _ledger_append("train_loop_dispatch_sweep",
                   best["tokens_per_sec"], "tokens/sec",
                   tokens_per_sec=best["tokens_per_sec"],
                   backend=rec["device"],
                   extra={"steps_per_loop": best["steps_per_loop"],
                          "speedup_vs_k1": best.get("speedup_vs_k1"),
                          "ks": [r["steps_per_loop"]
                                 for r in rec["rows"]]})


if __name__ == "__main__":
    if "--steps-per-loop" in sys.argv:
        _steps_per_loop_cli()
    else:
        main()
