"""Benchmark harness — prints ONE JSON line for the driver.

Headline: GPT-2-small causal-LM training throughput (tokens/sec) on the
available hardware (real TPU chip under the driver; CPU otherwise) —
the flagship transformer path: Pallas flash attention, bf16 AMP (O1),
fused AdamW step, donated buffers. The measured step is the same
compiled step `paddle_tpu.Model.fit` runs — framework end-to-end, not a
kernel in isolation. `vs_baseline` is 1.0: the reference publishes no
in-tree numbers (BASELINE.md — `published == {}`), so the baseline is
this framework's own first measurement.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def bench_gpt(batch: int = 8, seq: int = 1024, warmup: int = 3,
              iters: int = 20):
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import (GPTForCausalLM,
                                       GPTPretrainingCriterion, gpt_config)

    paddle.seed(0)
    # dropouts off so the flash kernel dispatches (throughput config)
    cpu_smoke = jax.default_backend() == "cpu"
    if cpu_smoke:  # no-TPU smoke config — reported under a distinct metric
        cfg = gpt_config("gpt2-small", num_layers=2, hidden_size=256,
                         num_heads=4, max_position_embeddings=seq,
                         hidden_dropout=0.0, attention_dropout=0.0)
        batch, iters = 2, 5
    else:
        cfg = gpt_config("gpt2-small", max_position_embeddings=seq,
                         hidden_dropout=0.0, attention_dropout=0.0)
    net = GPTForCausalLM(cfg)
    model = paddle.Model(net)
    model.prepare(
        optimizer=paddle.optimizer.AdamW(learning_rate=1e-4, parameters=net,
                                         weight_decay=0.01),
        loss=GPTPretrainingCriterion(),
        amp_configs="O1")

    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (batch, seq))

    for _ in range(warmup):
        model.train_batch([ids], [ids])
    t0 = time.perf_counter()
    for _ in range(iters):
        logs = model.train_batch([ids], [ids])
    dt = time.perf_counter() - t0
    assert np.isfinite(logs["loss"]), logs
    return batch * seq * iters / dt


def main():
    metric = "gpt2s_train_tokens_per_sec"
    try:
        import jax
        if jax.default_backend() == "cpu":  # tiny smoke config, not GPT-2s
            metric = "gpt2s_smoke_cpu_tokens_per_sec"
        tps = bench_gpt()
        print(json.dumps({"metric": metric,
                          "value": round(float(tps), 1),
                          "unit": "tokens/sec",
                          "vs_baseline": 1.0}))
    except Exception as e:  # never leave the driver without a line
        print(json.dumps({"metric": metric,
                          "value": 0.0, "unit": "tokens/sec",
                          "vs_baseline": 0.0, "error": str(e)[:200]}))
        print(f"bench failed: {e}", file=sys.stderr)
        raise


if __name__ == "__main__":
    main()
