"""Benchmark harness — prints ONE JSON line for the driver.

Measures flagship train-step throughput on the available hardware
(real TPU chip under the driver; CPU otherwise). Config: BASELINE.json
config 1 (MNIST LeNet, Model.fit path) — the compiled train step is the
same one `paddle_tpu.Model.fit` runs, so this measures the framework's
end-to-end step (forward+backward+optimizer on device), not a kernel in
isolation. `vs_baseline` is 1.0: the reference publishes no in-tree
numbers (BASELINE.md — `published == {}`), so the baseline is this
framework's own first measurement.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def bench_lenet(batch: int = 256, warmup: int = 5, iters: int = 30):
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.models import LeNet

    paddle.seed(0)
    net = LeNet(num_classes=10)
    model = paddle.Model(net)
    model.prepare(
        optimizer=paddle.optimizer.Adam(learning_rate=1e-3, parameters=net),
        loss=nn.CrossEntropyLoss())

    rng = np.random.RandomState(0)
    xs = rng.randn(batch, 1, 28, 28).astype(np.float32)
    ys = rng.randint(0, 10, (batch, 1))

    for _ in range(warmup):
        model.train_batch([xs], [ys])
    t0 = time.perf_counter()
    for _ in range(iters):
        logs = model.train_batch([xs], [ys])
    dt = time.perf_counter() - t0
    assert np.isfinite(logs["loss"])
    return batch * iters / dt


def main():
    try:
        ips = bench_lenet()
        print(json.dumps({"metric": "lenet_mnist_train_images_per_sec",
                          "value": round(float(ips), 1),
                          "unit": "images/sec",
                          "vs_baseline": 1.0}))
    except Exception as e:  # never leave the driver without a line
        print(json.dumps({"metric": "lenet_mnist_train_images_per_sec",
                          "value": 0.0, "unit": "images/sec",
                          "vs_baseline": 0.0, "error": str(e)[:200]}))
        print(f"bench failed: {e}", file=sys.stderr)
        raise


if __name__ == "__main__":
    main()
