"""paddle.version parity (ref: python/paddle/version.py, generated at
build time). Single source of truth for the version string —
``paddle_tpu.__version__`` reads from here."""

full_version = "0.2.0"
major, minor, patch = (int(x) for x in full_version.split("."))
rc = 0
istaged = False
commit = "unknown"


def show() -> None:
    print(f"paddle-tpu {full_version} (commit {commit})")
