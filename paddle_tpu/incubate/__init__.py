"""paddle_tpu.incubate — reference-parity namespace
(ref: python/paddle/incubate/ — MoE under distributed/models/moe,
fused transformer layers under nn/layer/fused_transformer.py, functional
autograd, sparse utils). The implementations live in their TPU-native
homes; this package re-exports them under the familiar paths."""

from ..autograd import Hessian, Jacobian, jvp, vjp  # noqa
from ..nn.layers.moe import (GShardGate, MoELayer, NaiveGate,  # noqa
                             SwitchGate)
from ..nn.layers.sparse_embedding import (MultiSlotEmbedding,  # noqa
                                          SparseEmbedding)

# Fused-layer names (ref: incubate/nn/layer/fused_transformer.py):
# on TPU "fused" is the compiler's job — these alias the standard layers
# whose attention already dispatches to the Pallas flash kernel.
from ..nn.layers.transformer import (  # noqa
    MultiHeadAttention as FusedMultiHeadAttention,
    TransformerEncoderLayer as FusedTransformerEncoderLayer)

from . import asp  # noqa  (n:m structured sparsity)
from . import nn  # noqa  (fused-layer namespace)
from . import autotune  # noqa  (kernel/layout/dataloader tuning facade)
from . import data_generator  # noqa  (PS MultiSlot authoring protocol)
