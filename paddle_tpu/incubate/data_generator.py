"""PS training-data authoring API (ref: python/paddle/fluid/incubate/
data_generator/__init__.py — ``MultiSlotDataGenerator``: user code
yields (slot_name, values) pairs per sample; the base class serializes
the MultiSlot text protocol ``<n> v1 .. vn`` per slot that the C++
DataFeed parses on the training side).

TPU-native context: the CTR path here trains from dense/CSV batches
through the native GIL-free feed (io/native_feed.py) into
SparseEmbedding tables, so this module serves two jobs — byte-level
parity for the reference's authoring protocol (write + parse), and a
``to_csv`` emitter targeting the in-repo native feed."""

from __future__ import annotations

import sys
from typing import Iterable, List, Sequence, Tuple

Slot = Tuple[str, Sequence]


class DataGenerator:
    """ref: data_generator/__init__.py DataGenerator."""

    def __init__(self):
        self._line_limit = None

    # -- user hooks -----------------------------------------------------
    def generate_sample(self, line):
        """Override: return an iterator yielding one sample — a list of
        (slot_name, values) — per call (the reference's contract)."""
        raise NotImplementedError

    def generate_batch(self, samples):
        """Optional batch-level hook (ref: local_iter batching)."""
        def local_iter():
            for s in samples:
                yield s
        return local_iter

    # -- serialization --------------------------------------------------
    def _gen_str(self, sample: List[Slot]) -> str:
        raise NotImplementedError

    def run_from_stdin(self):
        """stdin lines → protocol lines on stdout (the MapReduce shape
        the reference documents)."""
        for line in sys.stdin:
            for sample in self.generate_sample(line)():
                sys.stdout.write(self._gen_str(sample))

    def run_from_files(self, paths: Iterable[str], out_path: str):
        with open(out_path, "w") as out:
            for p in paths:
                with open(p) as f:
                    for line in f:
                        for sample in self.generate_sample(line)():
                            out.write(self._gen_str(sample))


class MultiSlotDataGenerator(DataGenerator):
    """Serializes the MultiSlot text protocol: per sample one line of
    ``<count> v1 .. vcount`` per slot, space-joined
    (ref: MultiSlotDataGenerator._gen_str)."""

    def _gen_str(self, sample: List[Slot]) -> str:
        parts = []
        for _, values in sample:
            parts.append(str(len(values)))
            parts.extend(str(v) for v in values)
        return " ".join(parts) + "\n"

    def to_csv(self, sample: List[Slot]) -> str:
        """Flatten dense slots into one CSV row for the in-repo native
        feed (io/native_feed.FileDataFeed)."""
        flat = [str(v) for _, values in sample for v in values]
        return ",".join(flat) + "\n"


def parse_multislot_line(line: str, slot_names: Sequence[str]
                         ) -> List[Slot]:
    """Training-side parser for the protocol (the role the reference's
    C++ MultiSlotDataFeed plays, framework/data_feed.cc)."""
    toks = line.split()
    out: List[Slot] = []
    i = 0
    for name in slot_names:
        if i >= len(toks):
            raise ValueError(f"line ended before slot {name!r}")
        n = int(toks[i])
        vals = toks[i + 1:i + 1 + n]
        if len(vals) != n:
            raise ValueError(
                f"slot {name!r} declares {n} values, found {len(vals)}")
        numeric = [float(v) if ("." in v or "e" in v or "E" in v)
                   else int(v) for v in vals]
        out.append((name, numeric))
        i += 1 + n
    if i != len(toks):
        raise ValueError(f"{len(toks) - i} trailing tokens")
    return out
