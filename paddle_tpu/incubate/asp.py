"""ASP — automatic structured (n:m) sparsity.

Reference being replaced: ``paddle.incubate.asp`` / ``static.sparsity``
(python/paddle/fluid/contrib/sparsity/asp.py — ``prune_model`` computes
n:m masks per supported weight with mask-1D/2D-best algorithms,
``decorate`` wraps the optimizer so masks are re-applied after each
``step``, keeping pruned weights at zero through fine-tuning;
utils.py ``create_mask``/``check_sparsity``).

TPU-native decision: the 2:4 pattern exists for NVIDIA's sparse tensor
cores; the TPU MXU has no n:m hardware path, so ASP here serves what it
serves everywhere else in the reference's own workflow — model
compression and sparsity-aware FINE-TUNING with exactly the same API
and mask semantics. The mask math is vectorized instead of the
reference's per-group Python loops: reshape to [groups, m], top-n by
magnitude per group (one sort on device), scatter a boolean mask.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

# registry of per-layer masks keyed by parameter path, mirroring the
# reference's ASPHelper.__asp_info masks map (sparsity/asp.py)
_masks: Dict[int, Dict[str, jax.Array]] = {}


def create_mask(w, n: int = 2, m: int = 4):
    """Boolean keep-mask with the n:m pattern: in every group of ``m``
    consecutive weights, keep the ``n`` largest by magnitude
    (ref: sparsity/utils.py create_mask, MaskAlgo_MASK_1D). Conv
    weights [O, I, kh, kw] are viewed as 2D [O, I*kh*kw] first, the
    reference's reshape-then-mask convention. Returns ``None`` when the
    grouped axis does not divide by ``m`` (not prunable) — callers must
    not count such weights as pruned."""
    w = jnp.asarray(w)
    if w.ndim < 1:
        return None
    view = w.reshape(w.shape[0], -1) if w.ndim > 2 else w
    if view.shape[-1] % m:
        return None
    flat = jnp.abs(view).reshape(-1, m)
    # positions of the n largest magnitudes per group
    keep_idx = jnp.argsort(flat, axis=-1)[:, m - n:]
    keep = jnp.zeros(flat.shape, bool).at[
        jnp.arange(flat.shape[0])[:, None], keep_idx].set(True)
    return keep.reshape(w.shape)


def check_sparsity(w, n: int = 2, m: int = 4) -> bool:
    """True iff every m-group has at most n non-zeros
    (ref: sparsity/utils.py check_sparsity)."""
    w = np.asarray(w)
    view = w.reshape(w.shape[0], -1) if w.ndim > 2 else w
    if view.shape[-1] % m:
        return False
    groups = view.reshape(-1, m)
    return bool(((groups != 0).sum(axis=-1) <= n).all())


def calculate_density(w) -> float:
    """ref: paddle.incubate.asp.calculate_density."""
    w = np.asarray(w)
    return float((w != 0).sum() / w.size)


def _prunable(net) -> List[str]:
    """Weights ASP prunes: 2D+ matmul/conv weights, skipping norms,
    biases and embeddings (ref: ASPHelper._is_supported_layer)."""
    from ..nn.layers.common import Embedding
    emb = {id(l.weight) for l in net.sublayers(include_self=True)
           if isinstance(l, Embedding)}
    out = []
    for name, p in net.named_parameters():
        if p.ndim >= 2 and id(p) not in emb and \
                not name.endswith("bias"):
            out.append(name)
    return out


def prune_model(net, n: int = 2, m: int = 4,
                mask_algo: str = "mask_1d") -> Dict[str, jax.Array]:
    """Compute + apply n:m masks to every prunable weight in place;
    returns the masks (ref: paddle.incubate.asp.prune_model)."""
    if mask_algo not in ("mask_1d",):
        raise NotImplementedError(
            f"mask_algo={mask_algo!r}: the 2D permutation search "
            "(mask_2d_greedy/best) buys accuracy for NVIDIA's sparse "
            "tensor cores' layout; without that hardware the 1D mask "
            "is the right default")
    masks = {}
    for name in _prunable(net):
        w = net._get_by_path(name)
        mask = create_mask(w, n=n, m=m)
        if mask is None:  # grouped axis not divisible by m
            continue
        masks[name] = mask
        net._assign_by_path(name, jnp.where(mask, w, 0.0))
    _masks[id(net)] = masks
    return masks


def decorate(optimizer, net=None):
    """Wrap ``optimizer.step`` so masks are re-applied after every
    update — pruned weights stay exactly zero through fine-tuning
    (ref: paddle.incubate.asp.decorate → OptimizerWithSparsityGuarantee).
    """
    net = net or optimizer._layer
    if net is None:
        raise ValueError("asp.decorate needs the optimizer bound to a "
                         "Layer (parameters=net) or an explicit net=")
    orig_step = optimizer.step
    orig_apply = optimizer.apply_gradients

    def step(grads):
        orig_step(grads)
        masks = _masks.get(id(net), {})
        for name, mask in masks.items():
            w = net._get_by_path(name)
            net._assign_by_path(name, jnp.where(mask, w, 0.0))

    def apply_gradients(params, grads, state, step_idx):
        # the hapi Model's compiled step calls apply_gradients directly
        # (hapi/model.py train step), bypassing .step — re-apply masks
        # inside the traced update so sparsity survives jit training;
        # masks are trace-time constants (jnp.where fuses into the
        # optimizer's elementwise update)
        new_params, new_state = orig_apply(params, grads, state,
                                           step_idx)
        masks = _masks.get(id(net), {})
        new_params = {
            name: (jnp.where(masks[name], v, 0.0)
                   if name in masks else v)
            for name, v in new_params.items()}
        return new_params, new_state

    optimizer.step = step
    optimizer.apply_gradients = apply_gradients
    return optimizer


def reset(net) -> None:
    _masks.pop(id(net), None)
