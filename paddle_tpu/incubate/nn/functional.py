"""incubate.nn.functional — fused functional ops (ref: python/paddle/
incubate/nn/functional/: fused_multi_head_attention.py,
fused_feedforward.py, fused_linear.py, fused_matmul_bias.py — each a
hand-written CUDA kernel chain). Here each is the same math expressed
as jnp/flash composition; XLA's fusion pass produces the fused kernel
the reference hand-writes, and the attention core is the Pallas flash
kernel."""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ...nn import functional as F


def fused_linear(x, weight, bias=None, transpose_weight: bool = False):
    """ref: incubate/nn/functional/fused_linear.py."""
    if transpose_weight:
        weight = weight.T
    return F.linear(x, weight, bias)


fused_matmul_bias = fused_linear  # ref: fused_matmul_bias.py


def fused_feedforward(x, linear1_weight, linear2_weight,
                      linear1_bias=None, linear2_bias=None,
                      ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None,
                      dropout1_rate: float = 0.5,
                      dropout2_rate: float = 0.5,
                      activation: str = "relu",
                      ln_epsilon: float = 1e-5,
                      pre_layer_norm: bool = False,
                      training: bool = True):
    """ref: incubate/nn/functional/fused_feedforward.py — the
    residual+LN+MLP block as one fused region."""
    residual = x
    d = x.shape[-1]
    if pre_layer_norm:
        x = F.layer_norm(x, d, ln1_scale, ln1_bias, ln_epsilon)
    h = F.linear(x, linear1_weight, linear1_bias)
    h = getattr(F, activation)(h)
    h = F.dropout(h, dropout1_rate, training=training)
    h = F.linear(h, linear2_weight, linear2_bias)
    h = F.dropout(h, dropout2_rate, training=training)
    out = residual + h
    if not pre_layer_norm:
        out = F.layer_norm(out, d, ln2_scale, ln2_bias, ln_epsilon)
    return out


def fused_multi_head_attention(x, qkv_weight, linear_weight,
                               pre_layer_norm: bool = False,
                               pre_ln_scale=None, pre_ln_bias=None,
                               ln_scale=None, ln_bias=None,
                               pre_ln_epsilon: float = 1e-5,
                               qkv_bias=None, linear_bias=None,
                               attn_mask=None,
                               dropout_rate: float = 0.5,
                               attn_dropout_rate: float = 0.5,
                               ln_epsilon: float = 1e-5,
                               training: bool = True,
                               num_heads: Optional[int] = None):
    """ref: incubate/nn/functional/fused_multi_head_attention.py
    (fused_attention_op.cu). qkv_weight: [3, H, h, hd] reference layout
    or [D, 3D]; attention runs through the flash-dispatching SDPA."""
    residual = x
    b, s, d = x.shape
    if pre_layer_norm:
        x = F.layer_norm(x, d, pre_ln_scale, pre_ln_bias,
                         pre_ln_epsilon)
    if qkv_weight.ndim == 4:  # [3, heads, head_dim, D] reference layout
        n_heads = qkv_weight.shape[1]
        hd = qkv_weight.shape[2]
        w = jnp.moveaxis(qkv_weight, 3, 0).reshape(d, 3 * n_heads * hd)
        if qkv_bias is not None and qkv_bias.ndim == 3:
            qkv_bias = qkv_bias.reshape(3 * n_heads * hd)  # [3,H,hd]
    else:
        w = qkv_weight
        n_heads = num_heads
        if n_heads is None:
            raise ValueError("num_heads required for 2D qkv_weight")
        hd = d // n_heads
    qkv = F.linear(x, w, qkv_bias)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(b, s, n_heads, hd)
    k = k.reshape(b, s, n_heads, hd)
    v = v.reshape(b, s, n_heads, hd)
    out = F.scaled_dot_product_attention(
        q, k, v, attn_mask=attn_mask, dropout_p=attn_dropout_rate,
        training=training)
    out = F.linear(out.reshape(b, s, d), linear_weight, linear_bias)
    out = F.dropout(out, dropout_rate, training=training)
    out = residual + out
    if not pre_layer_norm:
        out = F.layer_norm(out, d, ln_scale, ln_bias, ln_epsilon)
    return out
