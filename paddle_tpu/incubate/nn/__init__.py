"""incubate.nn — fused-layer namespace (ref: python/paddle/incubate/nn/
layer/fused_transformer.py). On TPU "fused" is the compiler's job: the
classes alias the standard layers (whose attention dispatches to the
Pallas flash kernel) and the functionals compose ops XLA fuses into
single kernels — there is no separate fused-op registry to maintain."""

from ...nn.layers.transformer import (  # noqa
    MultiHeadAttention as FusedMultiHeadAttention,
    TransformerEncoderLayer as FusedTransformerEncoderLayer)
from . import functional  # noqa
