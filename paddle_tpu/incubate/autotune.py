"""incubate.autotune — kernel/layout/dataloader tuning config facade.

Reference being replaced: ``paddle.incubate.autotune.set_config``
(python/paddle/incubate/autotune.py) toggling three tuners: "kernel"
(exhaustive cuDNN algo search over warmup steps, phi/kernels/autotune/),
"layout" (NCHW<->NHWC switch pass), and "dataloader" (num_workers
tuning).

TPU-native decision record, per tuner:
- kernel: XLA's TPU backend autotunes fusion/tiling during compilation,
  always on — there is no runtime algo search to toggle. Accepted and
  reported as already-enabled.
- layout: conv layouts are chosen by the XLA layout assignment pass
  per-op; the dimension-numbers API (nn/functional conv_nd) leaves the
  internal layout free. Accepted as already-enabled.
- dataloader: forwarded to a module-level hint that DataLoader reads
  when ``num_workers='auto'`` (tune between 1 and cpu_count like the
  reference's range).
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional

_config: Dict = {"kernel": {"enable": True, "tuning_range": None},
                 "layout": {"enable": True},
                 "dataloader": {"enable": False}}


def set_config(config: Optional[Dict] = None) -> None:
    """ref: paddle.incubate.autotune.set_config(config=None|dict|file).

    Accepts the reference's schema; "kernel"/"layout" are records of
    intent (XLA always autotunes both), "dataloader" enables worker
    autotuning for DataLoader(num_workers='auto')."""
    global _config
    if config is None:
        _config = {k: {**v, "enable": True} for k, v in _config.items()}
        return
    if isinstance(config, str):
        with open(config) as f:
            config = json.load(f)
    for key, val in config.items():
        if key not in _config:
            raise ValueError(f"unknown autotune section {key!r}")
        _config[key].update(val)


def get_config() -> Dict:
    return {k: dict(v) for k, v in _config.items()}


def suggested_num_workers() -> int:
    if not _config["dataloader"].get("enable"):
        return 0
    return min(4, os.cpu_count() or 1)
