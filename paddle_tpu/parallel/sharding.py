"""Logical-axis sharding rules → NamedShardings.

This is the GSPMD-shaped replacement for the reference's entire
dist-attr machinery (reference: python/paddle/distributed/auto_parallel/
— ``ProcessMesh`` process_mesh.py:39, ``shard_tensor`` interface.py:34,
``Completer`` dist-attr propagation completion.py:140, ``Partitioner``
partitioner.py:37, ``Resharder`` reshard.py:600). On TPU the compiler does
completion/partition/reshard; the framework's job reduces to mapping each
parameter's *logical* axes (declared once at layer definition, e.g.
``("embed", "mlp")``) onto *mesh* axes through a rule table — the
Flax/T5X "logical axis rules" idiom.

Default rules implement the reference's strategies in one table:
 - Megatron TP (mp_layers.py:30/95/171): ``mlp``/``heads``/``vocab`` → tp
 - ZeRO param sharding (group_sharded_stage3.py:60): ``embed`` → fsdp
 - expert parallel (moe_layer.py:244): ``expert`` → ep
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import jax
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from .mesh import DeviceMesh, get_mesh

# (logical axis, mesh axis) — first matching rule whose mesh axis is live
# and evenly divides the dimension wins.
DEFAULT_RULES: Tuple[Tuple[str, str], ...] = (
    ("batch", "dp"),
    ("batch", "fsdp"),
    ("pp_stage", "pp"),
    ("expert", "ep"),
    ("vocab", "tp"),
    ("mlp", "tp"),
    ("heads", "tp"),
    ("kv", "tp"),
    ("embed", "fsdp"),
    ("seq", "sp"),
)


class LogicalRules:
    def __init__(self, rules: Sequence[Tuple[str, str]] = DEFAULT_RULES):
        self.rules = tuple(rules)

    def mesh_axes(self, logical: Optional[Tuple[Optional[str], ...]],
                  shape: Tuple[int, ...], mesh: DeviceMesh) -> P:
        """Resolve logical dim names to a PartitionSpec, skipping mesh axes
        already taken by another dim (a mesh axis may shard only one dim).
        A logical name matching several rules collects ALL its live mesh
        axes (e.g. ``batch`` → ``("dp", "fsdp")``), so activation
        constraints agree with :func:`shard_batch`'s placement — the
        disagreement used to force an involuntary full rematerialization
        in the SPMD partitioner."""
        if logical is None:
            return P()
        used = set()
        out = []
        for dim, name in enumerate(logical):
            picks = []
            if name is not None and dim < len(shape):
                prod = 1
                for lname, maxis in self.rules:
                    if (lname == name and maxis not in used
                            and mesh.has_axis(maxis)
                            and shape[dim] % (
                                prod * mesh.axis_size(maxis)) == 0):
                        picks.append(maxis)
                        used.add(maxis)
                        prod *= mesh.axis_size(maxis)
            out.append(tuple(picks) if len(picks) > 1
                       else (picks[0] if picks else None))
        while out and out[-1] is None:
            out.pop()
        return P(*out)


def named_sharding(axes, shape, mesh: Optional[DeviceMesh] = None,
                   rules: Optional[LogicalRules] = None) -> NamedSharding:
    mesh = mesh or get_mesh()
    rules = rules or LogicalRules()
    return NamedSharding(mesh.mesh, rules.mesh_axes(axes, tuple(shape), mesh))


def shard_spec(name: Optional[str], shape, meta,
               mesh: Optional[DeviceMesh] = None,
               rules: Optional[LogicalRules] = None) -> NamedSharding:
    """THE sharding a parameter gets at runtime — the single source of
    truth shared by ``shard_params`` (materialized placement) and the
    compile-only planning paths (ShapeDtypeStruct rows must carry
    exactly what the runtime would do, or the study lies)."""
    mesh = mesh or get_mesh()
    rules = rules or LogicalRules()
    axes = getattr(meta.get(name), "axes", None)         if (meta and name is not None) else None
    return NamedSharding(mesh.mesh,
                         rules.mesh_axes(axes, tuple(shape), mesh))


def shard_params(params: Dict[str, jax.Array],
                 meta: Dict[str, Any],
                 mesh: Optional[DeviceMesh] = None,
                 rules: Optional[LogicalRules] = None
                 ) -> Dict[str, jax.Array]:
    """Place each param with the sharding derived from its logical axes.
    Params with no annotation are replicated (the reference's default for
    non-distributed attrs, completion.py fallback)."""
    mesh = mesh or get_mesh()
    rules = rules or LogicalRules()
    return {name: jax.device_put(
                v, shard_spec(name, v.shape, meta, mesh, rules))
            for name, v in params.items()}


def shard_batch(batch, mesh: Optional[DeviceMesh] = None):
    """Split the leading (batch) dim over the data axes — the DP half of the
    reference's ``DataParallel`` (fluid/dygraph/parallel.py:419): instead
    of replicating the model and all-reducing grads, the batch axis is
    sharded and XLA inserts the gradient all-reduce where the sharded and
    replicated program parts meet."""
    mesh = mesh or get_mesh()
    spec = mesh.batch_spec()
    ndata = 1
    for a in mesh.data_axes:
        ndata *= mesh.axis_size(a)

    def put(x):
        if not hasattr(x, "shape"):
            if not isinstance(x, (int, float, complex, bool)):
                return x  # strings/None/config leaves pass through
            x = jax.numpy.asarray(x)
        if getattr(x, "ndim", 0) == 0 or (
                ndata and x.shape[0] % ndata):
            # scalar, or a final partial batch (DataLoader drop_last=False)
            # whose leading dim doesn't divide the data axes: replicate —
            # correct, just unsharded for that one step.
            return jax.device_put(x, NamedSharding(mesh.mesh, P()))
        return jax.device_put(x, NamedSharding(mesh.mesh, spec))

    return jax.tree_util.tree_map(put, batch)


def shard_superbatch(batch, mesh: Optional[DeviceMesh] = None):
    """``shard_batch`` for the fused train loop's [K, batch, ...]
    superbatches: dim 0 is the per-slab STEP axis (scanned sequentially
    on every device — replicated), dim 1 is the batch axis split over
    the data axes exactly like ``shard_batch`` splits dim 0."""
    mesh = mesh or get_mesh()
    spec = P(None, *mesh.batch_spec())
    ndata = 1
    for a in mesh.data_axes:
        ndata *= mesh.axis_size(a)

    def put(x):
        if not hasattr(x, "shape"):
            if not isinstance(x, (int, float, complex, bool)):
                return x
            x = jax.numpy.asarray(x)
        if getattr(x, "ndim", 0) < 2 or (
                ndata and x.shape[1] % ndata):
            # scalar/per-step vector, or a partial batch whose dim 1
            # doesn't divide the data axes: replicate (correct, just
            # unsharded for that slab)
            return jax.device_put(x, NamedSharding(mesh.mesh, P()))
        return jax.device_put(x, NamedSharding(mesh.mesh, spec))

    return jax.tree_util.tree_map(put, batch)


def replicate(tree, mesh: Optional[DeviceMesh] = None):
    mesh = mesh or get_mesh()
    s = NamedSharding(mesh.mesh, P())
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, s), tree)


def with_logical_constraint(x, logical: Tuple[Optional[str], ...],
                            mesh: Optional[DeviceMesh] = None,
                            rules: Optional[LogicalRules] = None):
    """In-graph activation sharding hint (the ``shard_op``/
    ``shard_tensor`` analog, auto_parallel/interface.py:34/73). Safe to
    call outside jit (no-op placement) and on unknown axes (replicates)."""
    mesh = mesh or get_mesh(required=False)
    if mesh is None:
        return x
    rules = rules or LogicalRules()
    spec = rules.mesh_axes(logical, tuple(x.shape), mesh)
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh.mesh, spec))
