"""Activation recomputation + gradient accumulation.

Recompute replaces the reference's PyLayer-based re-forward
(reference: python/paddle/distributed/fleet/utils/recompute.py:350
``recompute`` / :207 ``RecomputeFunction`` — saves inputs + RNG states,
re-runs forward inside backward) and the static-graph rewrite pass
(fleet/meta_optimizers/recompute_optimizer.py,
passes/auto_parallel_recompute.py). On TPU the same trade is
``jax.checkpoint`` (rematerialisation): XLA re-runs the checkpointed
subgraph during the backward pass instead of keeping activations in HBM.
RNG state restore falls out for free — dropout keys are pure function
inputs, so the recomputed forward reproduces identical masks.

Gradient merge replaces the reference's gradient_merge_optimizer
(fleet/meta_optimizers/gradient_merge_optimizer.py) and
GradMergeAllReduceOpHandle (framework/details/) — here a pure optimizer
wrapper: accumulate k microbatch grads in the optimizer state and step
once every k calls (a ``lax.cond`` on the on-device counter, so the
merged step stays inside one compiled program).
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp

from ..nn.layer import Layer
from ..optimizer.optimizer import Optimizer


def recompute(function, *args, preserve_rng_state: bool = True, **kwargs):
    """Run ``function`` normally in forward; re-run it during backward
    instead of saving its activations (ref: fleet/utils/recompute.py:350).

    ``function`` may be a Layer or any callable of traced arrays.
    ``preserve_rng_state`` is accepted for API parity; PRNG keys are
    explicit functional inputs here, so recomputation is always
    bit-identical — there is no CUDA RNG state to snapshot/restore.
    """
    del preserve_rng_state
    fn = function.__call__ if isinstance(function, Layer) else function
    return jax.checkpoint(fn)(*args, **kwargs)


class RecomputeSequential(Layer):
    """Sequential container whose segments are rematerialised
    (analog of applying the reference's recompute to chunks of a
    Sequential; segments = number of checkpoint boundaries)."""

    def __init__(self, *layers, segments: int = 1):
        super().__init__()
        from ..nn.layer import Sequential
        if len(layers) == 1 and isinstance(layers[0], (list, tuple)):
            layers = tuple(layers[0])
        self.body = Sequential(*layers)
        self.segments = max(1, segments)

    def forward(self, x):
        layers = list(self.body)
        n = len(layers)
        per = -(-n // self.segments)  # ceil: never more chunks than asked
        i = 0
        while i < n:
            chunk = layers[i:i + per]

            def run(v, chunk=chunk):
                for l in chunk:
                    v = l(v)
                return v
            x = jax.checkpoint(run)(x)
            i += per
        return x


class GradientMerge:
    """Optimizer wrapper: step every ``k_steps`` calls, accumulating
    grads in between (ref: gradient_merge_optimizer.py; dygraph analog
    is manual `accumulate + step every k`).

    Wraps the pure `init_state/apply_gradients` API, so it composes with
    Model's compiled train step and with sharded optimizers.
    ``avg=True`` divides the merged grad by k (matches the reference's
    GradientMergeOptimizer(avg=True) default).
    """

    def __init__(self, inner: Optimizer, k_steps: int, avg: bool = True):
        self.inner = inner
        self.k_steps = int(k_steps)
        self.avg = avg
        # lr_fn/grad_clip etc. delegate to inner via __getattr__

    def __getattr__(self, name):
        if name == "inner":  # not yet set (unpickling) — avoid recursion
            raise AttributeError(name)
        return getattr(self.inner, name)

    def init_state(self, params):
        acc = jax.tree_util.tree_map(jnp.zeros_like, params)
        return {"inner": self.inner.init_state(params),
                "acc": acc,
                "count": jnp.zeros((), jnp.int32)}

    def apply_gradients(self, params, grads, state, step):
        acc = jax.tree_util.tree_map(jnp.add, state["acc"], grads)
        count = state["count"] + 1
        k = self.k_steps

        def do_step(operands):
            params, acc, inner = operands
            merged = acc
            if self.avg:
                merged = jax.tree_util.tree_map(lambda g: g / k, merged)
            # LR schedule advances per *merged* step, not per microbatch
            new_params, new_inner = self.inner.apply_gradients(
                params, merged, inner, jnp.asarray(step) // k)
            zeros = jax.tree_util.tree_map(jnp.zeros_like, acc)
            return new_params, zeros, new_inner

        def skip(operands):
            return operands

        params, acc, inner = jax.lax.cond(
            count >= k, do_step, skip,
            (params, acc, state["inner"]))
        count = jnp.where(count >= k, 0, count)
        return params, {"inner": inner, "acc": acc, "count": count}
