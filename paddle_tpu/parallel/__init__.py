"""Parallelism core (SURVEY.md §2.3): mesh topology, sharding rules,
distributed layers. Populated incrementally; see mesh.py / api.py."""
