"""paddle_tpu.parallel — distributed training on a named device mesh.

TPU-native rebuild of the reference's distributed stack (SURVEY.md §2.3,
§2.4): `python/paddle/distributed/` fleet + collective + auto_parallel,
the C++ ProcessGroup/Reducer runtime, and the NCCL comm bootstrap all
collapse into: a :class:`DeviceMesh` with named axes, logical-axis
sharding rules, and XLA collectives.
"""

from .mesh import (AXIS_ORDER, DeviceMesh, get_mesh, init_mesh,  # noqa
                   set_mesh)
from .sharding import (DEFAULT_RULES, LogicalRules, named_sharding,  # noqa
                       replicate, shard_batch, shard_params,
                       with_logical_constraint)
from .strategy import (AMPConfig, DistributedStrategy,  # noqa
                       GradientMergeConfig, HybridConfig, MoEConfig,
                       PipelineConfig, RecomputeConfig, ShardingConfig)
from .api import (DataParallel, all_gather, all_reduce, barrier,  # noqa
                  broadcast, distributed_model, get_rank, get_world_size,
                  init_parallel_env)
from .pipeline import (LayerDesc, PipelineLayer, PipelineParallel,  # noqa
                       SharedLayerDesc, pipeline_spmd)
from .recompute import (GradientMerge, RecomputeSequential,  # noqa
                        recompute)
from .planner import ChipSpec, Plan, evaluate, plan  # noqa
from .localsgd import (build_local_sgd_step, replicate_params,  # noqa
                       unreplicate_params)
from . import collective  # noqa
from . import planner  # noqa
