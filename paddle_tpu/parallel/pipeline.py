"""Pipeline parallelism: circular SPMD microbatch pipelining over ``pp``.

Reference implementation being replaced:
- dygraph: ``PipelineLayer`` with LayerDesc/SharedLayerDesc
  (python/paddle/distributed/fleet/meta_parallel/parallel_layers/
  pp_layers.py:162/:58/:77) and ``PipelineParallel.forward_backward_pipeline``
  — an explicit 1F1B schedule (meta_parallel/pipeline_parallel.py:82-150)
  over point-to-point sends (pp_utils/p2p_communication.py), with
  interleaved scheduling selected by ``virtual_pp_degree``
  (pp_layers.py get_stage_from_index).
- static: ``PipelineTrainer``/``SectionWorker`` (framework/trainer.h:307)
  and the FleetExecutor actor runtime (distributed/fleet_executor/).

TPU-native design: there is no per-rank program — one SPMD program runs
on every pp rank inside ``shard_map``. Stage weights live as ONE tensor
per parameter with a leading stage dim sharded over the ``pp`` mesh axis,
so each rank holds only its own stages' weights (the pp memory win is in
the sharding, not in per-rank code). The schedule is a ``lax.scan`` over
ticks; each tick every rank runs one stage-chunk on one microbatch and
passes the activation to the next rank with ``lax.ppermute`` over the ICI
ring — the compiled analog of partial_send/recv.

Scheduling: with ``virtual_pp_degree = v`` each rank holds ``v``
stage-chunks assigned round-robin (rank r owns chunks r, r+pp, r+2pp, …),
the Megatron "interleaved" layout the reference selects with
virtual_pp_degree (pp_layers.py:390). Microbatches are injected in waves
of ``pp``; a microbatch circulates the ring ``v`` times. Total ticks are
``m*v + pp - 1`` chunk-times versus ``(m + pp - 1)*v`` for the naive
schedule — the fill/drain bubble shrinks by ``v``. During bubble ticks a
rank computes on a zero/garbage activation whose result is never written
anywhere; that compute is inherent to SPMD pipelining (every device runs
the same program each tick — a hand-scheduled rank would be idle, not
faster).

Outputs: the last chunk's results accumulate into a carried buffer via
``dynamic_update_slice`` (no per-tick stacked activations), and after the
scan one ring scatter (``ppermute`` from the last rank to each rank)
leaves the output sharded over pp on the microbatch dim — the head/loss
downstream runs data-parallel over pp for free. There is no broadcast:
total comm is one activation per rank per tick plus ``m/pp`` microbatches
scattered once, versus the reference's P2P sends plus its separate
embedding-grad allreduce.

Memory profile (honest): this is GPipe-with-rematerialisation, not 1F1B.
``jax.checkpoint`` around the chunk body makes the backward residual one
boundary activation per tick (``m*v + pp - 1`` boundaries per rank),
where true 1F1B holds at most ``pp`` full per-stage activation sets.
With remat the per-rank residual is smaller than 1F1B's whenever
``(m*v + pp)·|boundary| < pp·|stage internals|``, which holds for
transformer blocks at realistic microbatch counts; the recompute cost is
one extra forward, the standard TPU trade.

LONG-SEQUENCE DECISION RECORD (r5; boundary = [mb, s, h] grows with s):
the 1F1B-style bounded-activation schedule is expressed here as
WAVE-ACCUMULATION, not a new schedule: run the pipeline with
``num_microbatches = w`` (a wave, e.g. w = pp) and accumulate grads
across ``m/w`` waves — per jitted step with an inner fori/grad loop, or
across trainer steps with the existing gradient-accumulation facility.
Each wave's backward residuals are freed before the next wave, so the
per-rank boundary set is ``w·v + pp - 1`` — independent of total
microbatch count, which is 1F1B's bounded-memory property (1F1B keeps
<= pp microbatches in flight; w = pp matches it). Measured with XLA's
compiled memory analysis (tools/pp_longseq_memory.py, pp=4, 16
microbatches, wave=4; per-device temp bytes, CPU-mesh compile):

    s=4096   single-scan  58.0 MiB   wave=4  30.1 MiB   ratio 0.52
    s=8192   single-scan 116.1 MiB   wave=4  60.1 MiB   ratio 0.52
    s=16384  single-scan 232.1 MiB   wave=4 120.1 MiB   ratio 0.52

(boundaries alone predict 7/19 = 0.37; the measured 0.52 includes the
fori carry of accumulated grads and the input slice.) The trade is the
per-wave fill/drain bubble, (pp-1)/(w·v+pp-1) vs the single scan's
(pp-1)/(m·v+pp-1) — exactly the bubble 1F1B's schedule pays against
steady-state GPipe. Pinned by tests/test_pipeline.py
test_wave_accumulation_bounds_boundary_memory.

Tensor parallelism INSIDE the pipeline (the reference's mp×pp hybrid,
fleet/meta_optimizers/sharding_optimizer.py:123-135 wrap order): the
shard_map is *partially manual* — manual over ``pp`` only
(``axis_names={"pp"}``), every other mesh axis stays in GSPMD "auto"
mode. The stacked stage params keep their per-dim logical shardings
(``mlp``/``heads``/``vocab`` → tp) on the non-stage dims, and XLA's SPMD
partitioner inserts the Megatron-style tp collectives inside the scan
body exactly as it does in the dense path; microbatch dp sharding rides
the same way. No hand-written tp collectives, no nested shard_map — the
pipeline schedule is manual where it must be (the ppermute ring) and
compiler-partitioned everywhere else.

Constraints (same as GSPMD-style pipelining everywhere): all stage-chunks
run one shared computation graph, so chunks must be structurally
identical, and the trunk must be buffer-free (no BatchNorm running
stats). Embedding/head layers stay outside the pipelined trunk
(pp-replicated), which is how ``models.gpt.GPTForCausalLMPipe`` composes
it.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..nn.layer import Layer, LayerList, Parameter, functional_call
from .mesh import DeviceMesh, get_mesh


# ---------------------------------------------------------------------------
# declarative stage description (API parity with pp_layers.py)
# ---------------------------------------------------------------------------

class LayerDesc:
    """Deferred layer construction (ref: pp_layers.py:58 LayerDesc)."""

    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build(self) -> Layer:
        return self.layer_cls(*self.args, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    """Weight-tied layer appearing in several stages (ref: pp_layers.py:77).
    In the SPMD design tied weights live outside the pipelined trunk, so
    this is kept for API parity: shared layers are hoisted out of the
    stage list by PipelineLayer and must appear first/last."""

    def __init__(self, key: str, layer_cls, *args, **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.key = key


class PipelineLayer(Layer):
    """Groups a flat layer list into ``num_stages`` equal stage-chunks
    (ref: pp_layers.py:162 PipelineLayer(layers=[...], num_stages=N)).

    The SPMD executor requires equal, structurally identical chunks —
    enforced here at construction. With interleaving, ``num_stages`` is
    the TOTAL chunk count ``pp * virtual_pp_degree``."""

    def __init__(self, layers: Sequence, num_stages: int):
        super().__init__()
        built: List[Layer] = []
        for l in layers:
            built.append(l.build() if isinstance(l, LayerDesc) else l)
        if len(built) % num_stages != 0:
            raise ValueError(
                f"{len(built)} layers do not split evenly into "
                f"{num_stages} stages")
        per = len(built) // num_stages
        self.num_stages = num_stages
        self.layers_per_stage = per
        stages = []
        for s in range(num_stages):
            from ..nn.layer import Sequential
            stages.append(Sequential(*built[s * per:(s + 1) * per]))
        self.stages = LayerList(stages)

    def forward(self, x):
        """Dense (non-pipelined) execution — correctness reference and
        single-device fallback."""
        for stage in self.stages:
            x = stage(x)
        return x


# ---------------------------------------------------------------------------
# the SPMD pipelining primitive
# ---------------------------------------------------------------------------

def pipeline_spmd(stage_fn: Callable, stacked_params, x,
                  num_microbatches: int,
                  mesh: Optional[DeviceMesh] = None,
                  axis: str = "pp",
                  virtual: int = 1,
                  mb_spec: P = P(),
                  remat: bool = True):
    """Run ``y = chunk_{S-1}(… chunk_0(x))`` pipelined over mesh axis
    ``axis`` with the circular schedule described in the module docstring.

    ``stage_fn(params_one_chunk, mb) -> mb_out`` — every rank runs this
    same function (SPMD). ``stacked_params``: pytree whose leaves have a
    leading dim ``S = pp * virtual`` in ROUND-ROBIN order: position
    ``r*virtual + c`` holds chunk ``c*pp + r`` (so sharding dim 0 over pp
    in equal blocks gives rank r exactly its chunks). ``x``: [batch, ...]
    global input, split into ``num_microbatches``. ``mb_spec``:
    PartitionSpec of one microbatch over the OTHER mesh axes (e.g.
    P("dp") keeps data parallelism inside the pipeline).
    """
    mesh = mesh or get_mesh()
    pp = mesh.axis_size(axis)
    v = virtual
    S = pp * v
    m = num_microbatches
    b = x.shape[0]
    if b % m:
        raise ValueError(f"batch {b} not divisible by {m} microbatches")
    mb_size = b // m
    xm = x.reshape(m, mb_size, *x.shape[1:])
    m_pad = -(-m // pp) * pp  # output buffer rounded up to a pp multiple
    c_sz = m_pad // pp

    # Partial-manual shard_map: only ``axis`` (pp) is manual, so in/out
    # specs may reference only it. The microbatch dims' dp sharding and
    # the params' tp shardings live on the AUTO axes — they flow in from
    # the arguments' shardings and GSPMD partitions the body over them.
    # ``mb_spec`` is applied as a constraint to anchor the intended
    # microbatch layout rather than as a manual split.
    param_specs = jax.tree_util.tree_map(lambda _: P(axis), stacked_params)
    if tuple(mb_spec) != ():
        from jax.sharding import NamedSharding
        xm = lax.with_sharding_constraint(
            xm, NamedSharding(mesh.mesh, P(None, *mb_spec)))

    # Per-tick randomness: the scan body is traced ONCE, so an ambient
    # next_key() inside it would freeze one dropout mask for every tick/
    # microbatch/chunk. Instead fold the tick index into a base key drawn
    # here (from the enclosing step's key_guard stream) — unique per
    # (microbatch, chunk) since each occupies a unique tick — and route
    # the chunk body's implicit RNG through it. Folding inside the
    # (rematerialised) body keeps forward and backward masks identical.
    from ..core import rng as _rng
    base_key = _rng.next_key()

    def chunk_body(params_local, mb, t):
        with _rng.key_guard(jax.random.fold_in(base_key, t)):
            return stage_fn(params_local, mb)

    body = jax.checkpoint(chunk_body) if remat else chunk_body

    # injection time of microbatch j: waves of pp, one wave per ring lap
    # (ref schedule: meta_parallel/pipeline_parallel.py:82 1F1B loop;
    # interleaving per pp_layers.py virtual_pp_degree)
    t0_last = ((m - 1) // pp) * S + ((m - 1) % pp)
    ticks = t0_last + S

    def per_shard(params, xm_local):
        # params: leading dim S/pp == v on this rank (its chunk-group)
        r = lax.axis_index(axis)
        state0 = jnp.zeros_like(xm_local[0])
        out0 = jnp.zeros((m_pad,) + xm_local.shape[1:], xm_local.dtype)

        def tick(carry, t):
            state, out_buf = carry
            # which of this rank's v chunks runs this tick
            c = ((t - r) % S) // pp
            params_c = jax.tree_util.tree_map(
                (lambda a: a[0]) if v == 1 else
                (lambda a: lax.dynamic_index_in_dim(a, c, 0, keepdims=False)),
                params)
            # chunk 0 on rank 0 injects a fresh microbatch when one is due
            tm = t % S
            j_in = (t // S) * pp + tm
            inject = (r == 0) & (c == 0) & (tm < pp) & (j_in < m)
            first_in = lax.dynamic_index_in_dim(
                xm_local, jnp.clip(j_in, 0, m - 1), 0, keepdims=False)
            x_in = jnp.where(inject, first_in, state)
            y = body(params_c, x_in, t)
            # chunk S-1 on the last rank finishes microbatch j_out this
            # tick iff one was injected S-1 ticks ago
            t0o = t - (S - 1)
            j_out = (t0o // S) * pp + (t0o % S)
            emit = (r == pp - 1) & (t0o >= 0) & ((t0o % S) < pp) & (j_out < m)
            jc = jnp.clip(j_out, 0, m_pad - 1)
            cur = lax.dynamic_slice_in_dim(out_buf, jc, 1, 0)
            val = jnp.where(emit, y[None], cur)
            out_buf = lax.dynamic_update_slice_in_dim(out_buf, val, jc, 0)
            # shift activations one rank down the ICI ring; the wraparound
            # edge feeds chunk k back in as chunk k+1's input (circular);
            # with v == 1 nothing consumes it, so skip the send
            if v == 1:
                perm = [(i, i + 1) for i in range(pp - 1)]
            else:
                perm = [(i, (i + 1) % pp) for i in range(pp)]
            nxt = lax.ppermute(y, axis, perm)
            return (nxt, out_buf), None

        (_, out_buf), _ = lax.scan(tick, (state0, out0), jnp.arange(ticks))
        # one ring scatter: rank pp-1 holds all outputs; send chunk k to
        # rank k so the result leaves sharded over pp on the mb dim
        local = jnp.zeros((c_sz,) + xm_local.shape[1:], xm_local.dtype)
        for k in range(pp):
            chunk = lax.dynamic_slice_in_dim(out_buf, k * c_sz, c_sz, 0)
            local = local + lax.ppermute(chunk, axis, [(pp - 1, k)])
        return local

    mapped = jax.shard_map(
        per_shard, mesh=mesh.mesh,
        in_specs=(param_specs, P()),
        out_specs=P(axis),
        axis_names=frozenset({axis}),
        check_vma=False,
    )
    ym = mapped(stacked_params, xm)
    ym = ym[:m] if m_pad != m else ym
    return ym.reshape(b, *ym.shape[2:])


def _round_robin_order(pp: int, v: int) -> List[int]:
    """Stacking order: position r*v + c holds chunk c*pp + r."""
    return [c * pp + r for r in range(pp) for c in range(v)]


class PipelineParallel(Layer):
    """Wraps a PipelineLayer for pipelined execution under the current
    mesh (ref: meta_parallel/pipeline_parallel.py PipelineParallel;
    interleaving ref: pp_layers.py virtual_pp_degree).

    The stage-chunks' weights are re-registered HERE as stacked
    parameters with a leading ``pp_stage`` logical axis (one tensor per
    parameter, dim 0 of size ``num_stages`` in round-robin order), so
    ``shard_params`` places each rank's chunks on that rank — the pp
    memory partition is a sharding, not per-rank code. ``forward(x)``
    pipelines the trunk with ``num_microbatches`` microbatches; on a mesh
    without a pp axis it falls back to dense execution.

    CHECKPOINT LAYOUT NOTE: for homogeneous chunks (every in-chunk
    layer structurally equal — transformers) parameters are suffix-keyed
    ``[S, per, ...]`` stacks (e.g. ``attn__qkv__weight``); earlier
    revisions stored flat per-layer keys (``0__attn__qkv__weight`` of
    shape ``[S, ...]``). ``load_flat_state_dict`` maps the old layout
    onto the stacked one.
    """

    def __init__(self, pipe: PipelineLayer, num_microbatches: int = 1,
                 virtual_pp_degree: int = 1,
                 mesh: Optional[DeviceMesh] = None,
                 mb_spec: P = P(), remat: bool = True):
        super().__init__()
        if pipe.num_stages % virtual_pp_degree:
            raise ValueError(
                f"num_stages {pipe.num_stages} not divisible by "
                f"virtual_pp_degree {virtual_pp_degree}")
        self.num_stages = pipe.num_stages
        self.virtual_pp_degree = virtual_pp_degree
        self.num_microbatches = num_microbatches
        self._mesh = mesh
        self._mb_spec = mb_spec
        self._remat = remat

        pp = pipe.num_stages // virtual_pp_degree
        chunks = list(pipe.stages)
        for i, ch in enumerate(chunks):
            if any(True for _ in ch.named_buffers()):
                raise ValueError(
                    "pipelined trunk must be buffer-free (stage "
                    f"{i} registers buffers, e.g. BatchNorm stats)")
        # structural prototype for one chunk; NOT a sublayer — its own
        # concrete params are shadowed by the stacked ones below
        object.__setattr__(self, "_proto", chunks[0])
        metas = chunks[0].param_meta()
        keys = sorted(dict(chunks[0].named_parameters()).keys())
        for ch in chunks[1:]:
            if sorted(dict(ch.named_parameters()).keys()) != keys:
                raise ValueError("pipeline stages are not structurally "
                                 "identical; SPMD pipelining requires it")
        order = _round_robin_order(pp, virtual_pp_degree)
        self._keys = keys
        self._per = pipe.layers_per_stage
        # Homogeneous chunks (every in-chunk layer structurally equal —
        # the transformer case) additionally stack the LAYER dim:
        # [S, per, ...] leaves, so the stage applies its layers with an
        # inner lax.scan whose checkpointed body gives STRUCTURAL
        # remat — the chunk-level jax.checkpoint alone is an
        # optimization barrier some backend pipelines (XLA:CPU) strip
        # and CSE away, which made pp memory measure as no-remat
        # (r4 feasibility study). Scan carries are real buffers
        # everywhere, and the block lowers once per stage.
        self._layer_suffixes = self._detect_homogeneous(chunks[0], keys,
                                                        metas)
        chunk_params = [dict(c.named_parameters()) for c in chunks]
        if self._layer_suffixes:
            for suffix in self._layer_suffixes:
                stacked = jnp.stack([
                    jnp.stack([chunk_params[i][f"{j}.{suffix}"]
                               for j in range(self._per)])
                    for i in order])
                meta = metas[f"0.{suffix}"]
                axes = meta.axes
                if axes is None:
                    axes = (None,) * (stacked.ndim - 2)
                self.add_parameter(
                    suffix.replace(".", "__"),
                    Parameter(stacked, trainable=meta.trainable,
                              axes=("pp_stage", None, *axes)))
        else:
            for key in keys:
                stacked = jnp.stack(
                    [chunk_params[i][key] for i in order])
                axes = metas[key].axes
                if axes is None:
                    axes = (None,) * (stacked.ndim - 1)
                self.add_parameter(
                    key.replace(".", "__"),
                    Parameter(stacked, trainable=metas[key].trainable,
                              axes=("pp_stage", *axes)))

    def _detect_homogeneous(self, chunk, keys, metas):
        """Suffix list when every layer in the chunk is structurally
        identical (same param suffixes, shapes, AND meta — trainable
        flag + logical axes — per layer index); None otherwise
        (heterogeneous chunks keep the flat per-key layout, which
        preserves per-layer meta like partially-frozen stages)."""
        import re
        per = self._per
        if per <= 1:
            return None
        by_idx: dict = {}
        for key in keys:
            m = re.match(r"^(\d+)\.(.+)$", key)
            if not m:
                return None
            by_idx.setdefault(int(m.group(1)), set()).add(m.group(2))
        if sorted(by_idx) != list(range(per)):
            return None
        suffixes = by_idx[0]
        if any(s != suffixes for s in by_idx.values()):
            return None
        params = dict(chunk.named_parameters())
        for sfx in suffixes:
            shapes = {tuple(params[f"{j}.{sfx}"].shape)
                      for j in range(per)}
            if len(shapes) != 1:
                return None
            meta0 = metas[f"0.{sfx}"]
            for j in range(1, per):
                mj = metas[f"{j}.{sfx}"]
                if (mj.trainable != meta0.trainable
                        or mj.axes != meta0.axes):
                    return None  # e.g. a frozen layer inside the stage
        return sorted(suffixes)

    def load_flat_state_dict(self, sd):
        """Load a pre-stacking checkpoint (flat ``{j}__{suffix}`` keys,
        each ``[S, ...]``) into the homogeneous stacked layout
        (``{suffix}`` keys, ``[S, per, ...]``) by re-stacking the layer
        dim. Already-stacked dicts pass through unchanged."""
        if self._layer_suffixes:
            out = dict(sd)
            for sfx in self._layer_suffixes:
                name = sfx.replace(".", "__")
                flat = [f"{j}__{name}" for j in range(self._per)]
                if name not in out and all(k in out for k in flat):
                    out[name] = jnp.stack(
                        [jnp.asarray(out.pop(k)) for k in flat], axis=1)
            sd = out
        return self.set_state_dict(sd)

    def _stacked(self):
        if self._layer_suffixes:
            return {s: self._parameters[s.replace(".", "__")]
                    for s in self._layer_suffixes}
        return {k: self._parameters[k.replace(".", "__")]
                for k in self._keys}

    def _chunk_params(self, stacked, pos: int):
        if self._layer_suffixes:
            return {s: stacked[s][pos] for s in self._layer_suffixes}
        return {k: stacked[k][pos] for k in self._keys}

    def forward(self, x):
        mesh = self._mesh or get_mesh(required=False)
        stacked = self._stacked()
        v = self.virtual_pp_degree
        if mesh is None or mesh.axis_size("pp") <= 1:
            # dense fallback: run chunks in logical order
            pp = self.num_stages // v
            for k in range(self.num_stages):
                pos = (k % pp) * v + (k // pp)
                p = self._chunk_params(stacked, pos)
                if self._layer_suffixes:
                    for j in range(self._per):
                        x, _ = functional_call(
                            self._proto[0],
                            {s: p[s][j] for s in self._layer_suffixes},
                            {}, x, training=self.training)
                else:
                    x, _ = functional_call(self._proto, p, {}, x,
                                           training=self.training)
            return x
        pp = mesh.axis_size("pp")
        if pp * v != self.num_stages:
            raise ValueError(
                f"mesh pp={pp} x virtual_pp_degree={v} != "
                f"{self.num_stages} pipeline stages")

        # _proto is not a registered sublayer, so train()/eval() on this
        # wrapper never reach it — propagate the mode explicitly per call
        if self._layer_suffixes:
            template = self._proto[0]
            suffixes = self._layer_suffixes
            from ..nn.utils import scan_stacked_apply

            def stage_fn(params_local, mb):
                # params_local: {suffix: [per, ...]} — inner scan over
                # the chunk's layers; checkpointed body = structural
                # remat (residuals are the per-layer boundaries only)
                return scan_stacked_apply(
                    template, {s: params_local[s] for s in suffixes},
                    mb, remat=self._remat, rng_tag="stage_layers",
                    training=self.training)

            # the inner scan already remats per layer — an outer
            # chunk-level checkpoint on top would re-run every layer's
            # forward a third time in backward for nothing
            chunk_remat = False
        else:
            def stage_fn(params_local, mb):
                out, _ = functional_call(self._proto, params_local, {},
                                         mb, training=self.training)
                return out

            chunk_remat = self._remat

        return pipeline_spmd(stage_fn, stacked, x,
                             self.num_microbatches, mesh,
                             virtual=v, mb_spec=self._mb_spec,
                             remat=chunk_remat)
