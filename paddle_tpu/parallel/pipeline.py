"""Pipeline parallelism: SPMD microbatch pipelining over a ``pp`` mesh axis.

Reference implementation being replaced:
- dygraph: ``PipelineLayer`` with LayerDesc/SharedLayerDesc
  (python/paddle/distributed/fleet/meta_parallel/parallel_layers/
  pp_layers.py:162/:58/:77) and ``PipelineParallel.forward_backward_pipeline``
  — an explicit 1F1B schedule (meta_parallel/pipeline_parallel.py:82-150)
  over point-to-point sends (pp_utils/p2p_communication.py, partial_send/
  recv ops).
- static: ``PipelineTrainer``/``SectionWorker`` (framework/trainer.h:307)
  and the FleetExecutor actor runtime (distributed/fleet_executor/).

TPU-native design: there is no per-rank program — one SPMD program runs on
every pp rank. The schedule is a ``lax.scan`` over M + P - 1 ticks inside
``shard_map``; each tick every stage computes one microbatch (or a masked
dummy in the fill/drain bubble) and passes its activation to the next
stage with ``lax.ppermute`` over the ICI ring — the compiled analog of the
reference's partial_send/recv + 1F1B loop. The backward pass is jax's
transpose of the scan: activations flow backward through the reversed
ppermute, giving the same bubble shape as the hand-written schedule, and
``jax.checkpoint`` around the stage body keeps only per-tick boundary
activations live (the 1F1B memory trade).

Constraints (same as GSPMD-style pipelining everywhere): all stages run
one shared computation graph, so stages must be structurally identical.
Embedding/head layers stay outside the pipelined trunk (replicated over
pp), which is how the flagship GPT composes it.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..nn.layer import Layer, LayerList, functional_call
from .mesh import DeviceMesh, get_mesh


# ---------------------------------------------------------------------------
# declarative stage description (API parity with pp_layers.py)
# ---------------------------------------------------------------------------

class LayerDesc:
    """Deferred layer construction (ref: pp_layers.py:58 LayerDesc)."""

    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build(self) -> Layer:
        return self.layer_cls(*self.args, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    """Weight-tied layer appearing in several stages (ref: pp_layers.py:77).
    In the SPMD design tied weights live outside the pipelined trunk, so
    this is kept for API parity: shared layers are hoisted out of the
    stage list by PipelineLayer and must appear first/last."""

    def __init__(self, key: str, layer_cls, *args, **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.key = key


class PipelineLayer(Layer):
    """Groups a flat layer list into ``num_stages`` equal stages
    (ref: pp_layers.py:162 PipelineLayer(layers=[...], num_stages=N)).

    The SPMD executor requires equal, structurally identical stages —
    enforced here at construction."""

    def __init__(self, layers: Sequence, num_stages: int):
        super().__init__()
        built: List[Layer] = []
        for l in layers:
            built.append(l.build() if isinstance(l, LayerDesc) else l)
        if len(built) % num_stages != 0:
            raise ValueError(
                f"{len(built)} layers do not split evenly into "
                f"{num_stages} stages")
        per = len(built) // num_stages
        self.num_stages = num_stages
        self.layers_per_stage = per
        stages = []
        for s in range(num_stages):
            from ..nn.layer import Sequential
            stages.append(Sequential(*built[s * per:(s + 1) * per]))
        self.stages = LayerList(stages)

    def forward(self, x):
        """Dense (non-pipelined) execution — correctness reference and
        single-device fallback."""
        for stage in self.stages:
            x = stage(x)
        return x


# ---------------------------------------------------------------------------
# the SPMD pipelining primitive
# ---------------------------------------------------------------------------

def _stack_stage_params(pipe: PipelineLayer):
    """[stage0 params, ...] → one pytree with leading stage dim, plus the
    treedef/keys needed to rebind inside stage_fn."""
    stage_params = []
    for stage in pipe.stages:
        params = dict(stage.named_parameters())
        stage_params.append(params)
    keys = sorted(stage_params[0].keys())
    for sp in stage_params[1:]:
        if sorted(sp.keys()) != keys:
            raise ValueError("pipeline stages are not structurally "
                             "identical; SPMD pipelining requires it")
    stacked = {k: jnp.stack([sp[k] for sp in stage_params]) for k in keys}
    return stacked


def pipeline_spmd(stage_fn: Callable, stacked_params, x,
                  num_microbatches: int,
                  mesh: Optional[DeviceMesh] = None,
                  axis: str = "pp",
                  mb_spec: P = P(),
                  remat: bool = True):
    """Run ``y = stage_{P-1}(... stage_0(x))`` pipelined over the mesh
    axis ``axis``.

    stage_fn(params_one_stage, mb) -> mb_out; every stage runs this same
    function (SPMD). ``stacked_params``: pytree with leading dim P.
    ``x``: [batch, ...] global input, split into ``num_microbatches``.
    ``mb_spec``: PartitionSpec of one microbatch over the OTHER mesh axes
    (e.g. P("dp") to keep data parallelism inside the pipeline).
    """
    mesh = mesh or get_mesh()
    pp = mesh.axis_size(axis)
    m = num_microbatches
    b = x.shape[0]
    if b % m:
        raise ValueError(f"batch {b} not divisible by {m} microbatches")
    mb_size = b // m
    xm = x.reshape(m, mb_size, *x.shape[1:])

    param_specs = jax.tree_util.tree_map(lambda _: P(axis), stacked_params)
    in_mb_spec = P(None, *mb_spec)

    body = stage_fn
    if remat:
        body = jax.checkpoint(stage_fn)

    def per_shard(params, xm_local):
        # params: leading dim P/pp == 1 on this rank
        params_local = jax.tree_util.tree_map(lambda a: a[0], params)
        rank = lax.axis_index(axis)
        ticks = m + pp - 1
        state0 = jnp.zeros_like(xm_local[0])

        def tick(carry, t):
            state = carry  # activation received from the previous stage
            # stage 0 consumes microbatch t (clamped in the drain phase)
            mb_idx = jnp.clip(t, 0, m - 1)
            first_in = lax.dynamic_index_in_dim(xm_local, mb_idx, 0,
                                                keepdims=False)
            x_in = jnp.where(rank == 0, first_in, state)
            y = body(params_local, x_in)
            # shift activations one stage down the ring (last stage's
            # output falls off — it is collected below)
            nxt = lax.ppermute(y, axis,
                               [(i, i + 1) for i in range(pp - 1)])
            return nxt, y

        _, ys = lax.scan(tick, state0, jnp.arange(ticks))
        # last stage's valid outputs are ticks P-1 .. P-1+m
        outs = lax.dynamic_slice_in_dim(ys, pp - 1, m, axis=0)
        # broadcast them from the last rank to every pp rank so the head/
        # loss (outside the pipeline, pp-replicated) sees real values
        outs = jnp.where(rank == pp - 1, outs, jnp.zeros_like(outs))
        outs = lax.psum(outs, axis)
        return outs

    mapped = jax.shard_map(
        per_shard, mesh=mesh.mesh,
        in_specs=(param_specs, in_mb_spec),
        out_specs=in_mb_spec,
        check_vma=False,
    )
    ym = mapped(stacked_params, xm)
    return ym.reshape(b, *ym.shape[2:])


class PipelineParallel(Layer):
    """Wraps a PipelineLayer for pipelined execution under the current
    mesh (ref: meta_parallel/pipeline_parallel.py PipelineParallel).

    forward(x) pipelines the trunk over the pp axis with
    ``num_microbatches`` microbatches; on a mesh without a pp axis it
    falls back to dense execution.
    """

    def __init__(self, pipe: PipelineLayer, num_microbatches: int = 1,
                 mesh: Optional[DeviceMesh] = None,
                 mb_spec: P = P(), remat: bool = True):
        super().__init__()
        self.pipe = pipe
        self.num_microbatches = num_microbatches
        self._mesh = mesh
        self._mb_spec = mb_spec
        self._remat = remat

    def forward(self, x):
        mesh = self._mesh or get_mesh(required=False)
        if mesh is None or mesh.axis_size("pp") <= 1:
            return self.pipe(x)
        if mesh.axis_size("pp") != self.pipe.num_stages:
            raise ValueError(
                f"mesh pp={mesh.axis_size('pp')} != "
                f"{self.pipe.num_stages} pipeline stages")
        stacked = _stack_stage_params(self.pipe)
        proto = self.pipe.stages[0]

        def stage_fn(params_local, mb):
            out, _ = functional_call(proto, params_local, {}, mb)
            return out

        return pipeline_spmd(stage_fn, stacked, x,
                             self.num_microbatches, mesh,
                             mb_spec=self._mb_spec, remat=self._remat)
