"""paddle_tpu.parallel user API — fleet/init_parallel_env analog.

Replaces the reference's distributed bring-up chain
(reference: python/paddle/distributed/parallel.py:94 ``init_parallel_env``
→ TCPStore rendezvous distributed/store/tcp_store.h → ProcessGroupNCCL
ProcessGroup.h:53; fleet facade fleet/base/fleet_base.py:211 ``init`` /
:947 ``distributed_model``). On TPU, rendezvous is the JAX coordination
service (``jax.distributed.initialize``), process groups are mesh axes,
and wrapping a model for DP/TP/FSDP means attaching shardings — the
backward all-reduce the reference's EagerReducer performs
(distributed/collective/reducer.h:88, bucketed fused allreduce) is
inserted by XLA at the sharded/replicated boundary of the compiled step.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..nn.layer import Layer, split_state
from .mesh import DeviceMesh, get_mesh, init_mesh, set_mesh
from .sharding import (LogicalRules, named_sharding, replicate,
                       shard_batch, shard_params, shard_superbatch,
                       with_logical_constraint)
from .strategy import DistributedStrategy

_initialized = False


def init_parallel_env(coordinator_address: Optional[str] = None,
                      num_processes: Optional[int] = None,
                      process_id: Optional[int] = None) -> None:
    """Multi-host bring-up (ref: distributed/parallel.py:94).

    Single-host (or driver-managed TPU pods, where PJRT discovers the
    topology) needs no rendezvous; explicit args or PADDLE_* env vars
    trigger ``jax.distributed.initialize`` — the TCPStore replacement
    (ref: distributed/parallel.py:240 creating core.TCPStore from
    PADDLE_MASTER / PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM).
    """
    global _initialized
    if _initialized:
        return
    addr = coordinator_address or os.environ.get("PADDLE_MASTER") \
        or os.environ.get("MASTER_ADDR")
    nproc = num_processes or int(os.environ.get("PADDLE_TRAINERS_NUM", 0))
    pid = process_id if process_id is not None else \
        int(os.environ.get("PADDLE_TRAINER_ID", 0))
    if addr and nproc > 1:
        jax.distributed.initialize(coordinator_address=addr,
                                   num_processes=nproc, process_id=pid)
    # under an elastic launcher, start the liveness heartbeat (the
    # lease-keepalive the reference's ElasticManager expects;
    # fleet/elastic/manager.py) — manual progress beats can be layered
    # on via distributed.elastic.Heartbeat(mode="manual")
    from ..distributed import elastic as _elastic
    if os.environ.get(_elastic.HB_DIR_ENV):
        _elastic.Heartbeat()
    _initialized = True


def get_world_size() -> int:
    return jax.device_count()


def get_rank() -> int:
    return jax.process_index()


def barrier() -> None:
    """Host-level barrier (ref: operators/collective/barrier_op.cc): a
    tiny all-device psum forces every process to sync — jit + shard_map
    over a throwaway 1-axis mesh (pmap is the deprecated path)."""
    from jax.sharding import Mesh, NamedSharding
    from jax.sharding import PartitionSpec as P

    devs = jax.devices()
    mesh = Mesh(np.asarray(devs), ("all",))
    x = jax.device_put(jnp.ones((len(devs),)),
                       NamedSharding(mesh, P("all")))
    out = jax.jit(jax.shard_map(
        lambda v: jax.lax.psum(v, "all"), mesh=mesh,
        in_specs=P("all"), out_specs=P()))(x)
    out.block_until_ready()


# ---------------------------------------------------------------------------
# eager host-level collectives (ref: python/paddle/distributed/collective.py
# all_reduce/all_gather/broadcast). In compiled SPMD steps collectives are
# implicit; these eager forms serve host-side coordination (metric
# aggregation). A "per-rank tensor" is a stacked [group, ...] array.
# ---------------------------------------------------------------------------

def all_reduce(stacked, op: str = "sum"):
    from . import collective
    return collective.host_all_reduce(stacked, op)


def all_gather(x, mesh: Optional[DeviceMesh] = None):
    """Gather a sharded array to a fully-replicated one."""
    mesh = mesh or get_mesh()
    return jax.device_put(jnp.asarray(x),
                          named_sharding(None, x.shape, mesh))


def broadcast(stacked, src: int = 0, mesh: Optional[DeviceMesh] = None):
    """ref: c_broadcast — on a stacked [group, ...] array, every slice
    takes src's value. (For already-global arrays there is nothing to
    broadcast in the single-controller model — use ``replicate``.)
    With ``mesh``, the result is placed replicated on that mesh."""
    x = jnp.asarray(stacked)
    out = jnp.broadcast_to(x[src], x.shape)
    if mesh is not None:
        out = jax.device_put(out, named_sharding(None, out.shape, mesh))
    return out


# ---------------------------------------------------------------------------
# model wrapping
# ---------------------------------------------------------------------------

class DataParallel(Layer):
    """Eager DP wrapper (ref: paddle.DataParallel
    fluid/dygraph/parallel.py:419). Forward shards the batch over the data
    axes and replicates params; when used inside Model/jit the gradient
    all-reduce is compiled in, replacing the Reducer's bucketed NCCL
    all-reduce (imperative/reducer.h:129)."""

    def __init__(self, layers: Layer, mesh: Optional[DeviceMesh] = None):
        super().__init__()
        self._layers = layers
        self._mesh = mesh or get_mesh()
        # replicate params onto the mesh once at wrap time
        params, buffers = split_state(layers)
        for name, v in {**params, **buffers}.items():
            layers._assign_by_path(name, jax.device_put(
                v, named_sharding(None, v.shape, self._mesh)))

    def forward(self, *args, **kwargs):
        # shard_batch tree-maps over nested inputs; non-array leaves
        # (strings/None/config) pass through untouched
        args = shard_batch(args, self._mesh)
        kwargs = shard_batch(kwargs, self._mesh)
        return self._layers(*args, **kwargs)

    def state_dict(self, *a, **kw):
        return self._layers.state_dict(*a, **kw)

    def set_state_dict(self, *a, **kw):
        return self._layers.set_state_dict(*a, **kw)


def distributed_model(model, strategy: Optional[DistributedStrategy] = None,
                      mesh: Optional[DeviceMesh] = None,
                      rules: Optional[LogicalRules] = None,
                      global_batch: Optional[int] = None,
                      seq_len: Optional[int] = None,
                      act_dtype_bytes: Optional[int] = None):
    """Attach sharding to a hapi ``Model`` (ref: fleet_base.py:947
    ``distributed_model`` wrapping TP→PP→Sharding→DP; here one call
    installs param/batch placement hooks and the compiled step becomes the
    full hybrid-parallel program).

    With no explicit ``mesh``/``strategy``, passing ``global_batch``
    invokes the auto-parallel planner (ref: auto_parallel/engine.py:53
    Engine auto mode): the cost model picks (dp, fsdp, tp) for the
    current device count and the chosen layout is recorded on the
    returned model as ``model._plan``. ``seq_len`` defaults to the
    model's ``max_position_embeddings`` hint for sequence models."""
    if strategy is not None and global_batch is not None:
        raise ValueError(
            "pass either strategy (manual layout) or global_batch "
            "(auto-planned layout), not both — the planner would be "
            "silently skipped")
    if mesh is None:
        mesh = get_mesh(required=False)
        if mesh is not None and global_batch is not None:
            import warnings
            warnings.warn(
                "distributed_model(global_batch=...) found a mesh already "
                "installed; the auto-parallel planner was skipped and the "
                "existing mesh is used as-is")
        if mesh is None:
            if strategy is None and global_batch is not None:
                from . import planner
                best = planner.plan(model.network, jax.device_count(),
                                    global_batch=global_batch,
                                    seq_len=seq_len, rules=rules,
                                    act_dtype_bytes=act_dtype_bytes)
                if not best.fits:
                    import warnings
                    warnings.warn(
                        "auto-parallel planner predicts an OOM on every "
                        f"layout; using the smallest footprint: "
                        f"{best.describe()}")
                from .mesh import init_mesh_from_axes
                mesh = init_mesh_from_axes(best.axes)
                model._plan = best
                # context for verify_plan's measured-memory re-plan loop
                model._planner_ctx = {
                    "n_devices": jax.device_count(),
                    "global_batch": global_batch, "seq_len": seq_len,
                    "rules": rules, "chip": None,
                    "act_dtype_bytes": act_dtype_bytes}
            else:
                axes = strategy.mesh_axes() if strategy else {"dp": -1}
                mesh = init_mesh(**(axes or {"dp": -1}))
    rules = rules or LogicalRules()
    meta = model.network.param_meta()

    def _shard_params(tree):
        return shard_params(tree, meta, mesh, rules)

    def _shard_batch(tree):
        return shard_batch(tree, mesh)

    def _shard_superbatch(tree):
        return shard_superbatch(tree, mesh)

    model._shard_params = _shard_params
    model._shard_batch = _shard_batch
    model._shard_superbatch = _shard_superbatch
    model._mesh = mesh
    return model
