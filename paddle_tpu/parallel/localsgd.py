"""LocalSGD: k local steps per replica, then parameter averaging.

Reference being replaced: the LocalSGD / adaptive LocalSGD meta
optimizers (python/paddle/distributed/fleet/meta_optimizers/
localsgd_optimizer.py — program rewrite inserting c_allreduce on
params every k steps instead of per-step gradient allreduce).

TPU-native design: standard SPMD data parallelism bakes the gradient
all-reduce into the compiled step, so "skip the sync" cannot be a
graph rewrite — it is a different program. Here each dp rank holds its
OWN parameter copy (leading replica dim sharded over ``dp``), the
train step runs per-shard inside ``shard_map`` with NO gradient
collective, and every ``sync_every`` steps a single ``lax.pmean`` over
the params replaces k gradient all-reduces — the comm saving LocalSGD
exists for, riding ICI only 1/k as often. ``lax.cond`` keeps the sync
decision on-device (no host round-trip), and the whole thing stays one
jitted function.

DGC (dgc_optimizer.py) is deliberately NOT implemented — decision
recorded in paddle_tpu/quant/__init__.py's module docstring.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .mesh import DeviceMesh, get_mesh


def replicate_params(params, mesh: Optional[DeviceMesh] = None,
                     axis: str = "dp"):
    """Give every dp rank its own copy: tile a leading replica dim of
    size dp, sharded over ``axis`` (each rank's slice is its local
    model)."""
    mesh = mesh or get_mesh()
    n = mesh.axis_size(axis)
    tiled = jax.tree_util.tree_map(
        lambda a: jnp.broadcast_to(a[None], (n,) + a.shape), params)
    from jax.sharding import NamedSharding
    sharding = NamedSharding(mesh.mesh, P(axis))
    return jax.tree_util.tree_map(
        lambda a: jax.device_put(a, sharding), tiled)


def unreplicate_params(params_stacked):
    """Average the replica dim away (e.g. for evaluation/export)."""
    return jax.tree_util.tree_map(
        lambda a: a.mean(axis=0), params_stacked)


def build_local_sgd_step(grad_fn: Callable, update_fn: Callable,
                         sync_every: int,
                         mesh: Optional[DeviceMesh] = None,
                         axis: str = "dp",
                         batch_spec: P = P("dp")):
    """Build the jitted LocalSGD step.

    grad_fn(params, batch) -> (loss, grads) for ONE replica's params
    (no leading dim) on its local batch shard; update_fn(params, grads)
    -> new params (plain SGD/optimizer update, replica-local). The
    returned step(params_stacked, batch, step_idx) runs per-shard and
    averages params across dp only when ``step_idx % sync_every ==
    sync_every - 1``.
    """
    mesh = mesh or get_mesh()

    def per_shard(params, batch, step_idx):
        local = jax.tree_util.tree_map(lambda a: a[0], params)
        loss, grads = grad_fn(local, batch)
        new = update_fn(local, grads)
        due = (step_idx % sync_every) == sync_every - 1
        new = lax.cond(
            due,
            lambda t: jax.tree_util.tree_map(
                lambda a: lax.pmean(a, axis), t),
            lambda t: t,
            new)
        # loss is reported averaged over replicas (cheap scalar psum)
        loss = lax.pmean(loss, axis)
        return jax.tree_util.tree_map(lambda a: a[None], new), loss

    def step(params_stacked, batch, step_idx):
        specs = jax.tree_util.tree_map(lambda _: P(axis), params_stacked)
        mapped = jax.shard_map(
            per_shard, mesh=mesh.mesh,
            in_specs=(specs, batch_spec, P()),
            out_specs=(specs, P()),
            check_vma=False)
        return mapped(params_stacked, batch, step_idx)

    return jax.jit(step)
