"""Device mesh + hybrid topology.

TPU-native replacement for the reference's communicator topology stack
(reference: python/paddle/distributed/fleet/base/topology.py:52
``CommunicateTopology`` / :134 ``HybridCommunicateGroup`` — orthogonal
dp×mp×pp×sharding process groups built from rank arithmetic) and the
per-backend comm contexts (paddle/fluid/platform/collective_helper.h:71
``NCCLCommContext``). On TPU there is no comm-id bootstrap and no ring
management: a :class:`jax.sharding.Mesh` over the PJRT device topology IS
the communicator; XLA lowers collectives onto ICI/DCN from sharding
annotations. What remains of "topology" is naming the axes and answering
rank/group queries, which this module provides.

Canonical axis names (SURVEY.md §7 step 4): ``dp`` (data), ``fsdp``
(sharded-data / ZeRO), ``tp`` (tensor), ``pp`` (pipeline), ``sp``
(sequence/context), ``ep`` (expert).
"""

from __future__ import annotations

import math
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_ORDER = ("pp", "dp", "fsdp", "ep", "sp", "tp")

_current_mesh: Optional["DeviceMesh"] = None


class DeviceMesh:
    """A named device mesh (the HybridCommunicateGroup analog).

    ``DeviceMesh(dp=2, tp=4)`` lays 8 devices out as a 2×4 grid. Axis
    order follows :data:`AXIS_ORDER`: ``tp`` innermost so tensor-parallel
    collectives ride the fastest ICI links, ``pp`` outermost so pipeline
    p2p tolerates the slowest (DCN) links — mirroring the reference's
    fleet order mp-innermost (fleet/base/topology.py:160).

    An axis size of ``-1`` absorbs the remaining devices (like a reshape
    wildcard).
    """

    def __init__(self, devices: Optional[Sequence] = None,
                 **axis_sizes: int):
        if devices is None:
            devices = jax.devices()
        devices = list(devices)
        n = len(devices)
        sizes: Dict[str, int] = {}
        wildcard = None
        for name in AXIS_ORDER:
            s = int(axis_sizes.pop(name, 1))
            if s == -1:
                if wildcard is not None:
                    raise ValueError("only one axis may be -1")
                wildcard = name
                s = 1
            sizes[name] = s
        if axis_sizes:
            raise ValueError(
                f"unknown mesh axes {sorted(axis_sizes)}; "
                f"valid: {AXIS_ORDER}")
        fixed = math.prod(sizes.values())
        if wildcard is not None:
            if n % fixed:
                raise ValueError(
                    f"{n} devices not divisible by {fixed}")
            sizes[wildcard] = n // fixed
            fixed = n
        if fixed != n:
            raise ValueError(
                f"mesh {sizes} needs {fixed} devices, have {n}")
        # Drop degenerate (size-1) axes from the physical mesh but remember
        # them so sharding specs referring to them resolve to replication.
        self.axis_sizes: Dict[str, int] = dict(sizes)
        live = [a for a in AXIS_ORDER if sizes[a] > 1]
        if not live:  # single device: keep a 1-wide dp axis for uniformity
            live = ["dp"]
        shape = tuple(sizes[a] for a in live)
        arr = np.asarray(devices).reshape(shape)
        self.mesh = Mesh(arr, axis_names=tuple(live))
        self.axis_names: Tuple[str, ...] = tuple(live)

    # -- queries (HybridCommunicateGroup parity) ---------------------------
    @property
    def size(self) -> int:
        return self.mesh.size

    def axis_size(self, name: str) -> int:
        return self.axis_sizes.get(name, 1)

    def has_axis(self, name: str) -> bool:
        return self.axis_sizes.get(name, 1) > 1

    @property
    def data_axes(self) -> Tuple[str, ...]:
        """Axes the global batch is split over (dp + fsdp)."""
        return tuple(a for a in ("dp", "fsdp") if self.has_axis(a))

    def batch_spec(self, extra: Tuple[str, ...] = ()):
        from jax.sharding import PartitionSpec as P
        axes = self.data_axes
        lead = axes[0] if len(axes) == 1 else axes if axes else None
        return P(lead, *extra)

    def local_rank(self, axis: str) -> int:
        """Rank of this process's first device along ``axis`` (host view;
        analog of topology.py get_rank_from_stage)."""
        dev = jax.local_devices()[0]
        idx = np.argwhere(self.mesh.devices == dev)
        if idx.size == 0:
            return 0
        pos = dict(zip(self.mesh.axis_names, idx[0]))
        return int(pos.get(axis, 0))

    def __enter__(self):
        self._ctx = self.mesh.__enter__()
        self._prev_mesh = _current_mesh
        _set_current(self)
        return self

    def __exit__(self, *exc):
        _set_current(self._prev_mesh)
        return self.mesh.__exit__(*exc)

    def __repr__(self):
        live = {a: s for a, s in self.axis_sizes.items() if s > 1}
        return f"DeviceMesh({live or {'dp': 1}}, {self.size} devices)"


def _set_current(m: Optional[DeviceMesh]) -> None:
    global _current_mesh
    _current_mesh = m


def init_mesh(devices: Optional[Sequence] = None,
              **axis_sizes: int) -> DeviceMesh:
    """Create and install the global mesh (fleet.init analog — ref:
    python/paddle/distributed/fleet/base/fleet_base.py:211; the
    degree knobs mirror DistributedStrategy's
    {sharding,mp,pp,dp}_degree, fleet/meta_optimizers/
    sharding_optimizer.py:123-135).

    ``devices`` optionally restricts the mesh to a subset of
    ``jax.devices()`` (e.g. a 4-device mesh on an 8-device host)."""
    global _current_mesh
    m = DeviceMesh(devices=devices, **axis_sizes)
    _current_mesh = m
    return m


def init_mesh_from_axes(axes: Dict[str, int]) -> DeviceMesh:
    """Install a mesh from a planner-style axes dict, dropping size-1
    axes (falls back to a full-width dp axis when nothing is >1)."""
    live = {k: v for k, v in axes.items() if v > 1}
    return init_mesh(**(live or {"dp": -1}))


def get_mesh(required: bool = True) -> Optional[DeviceMesh]:
    if _current_mesh is None and required:
        raise RuntimeError(
            "no DeviceMesh installed; call parallel.init_mesh(...) first")
    return _current_mesh


def set_mesh(m: Optional[DeviceMesh]) -> None:
    global _current_mesh
    _current_mesh = m
