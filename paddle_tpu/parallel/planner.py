"""Auto-parallel planner: choose mesh axis sizes from a cost model.

Reference implementation being replaced: the auto_parallel planner stack —
``Planner``/``ParallelTuner`` searching dist-attr configurations
(python/paddle/distributed/auto_parallel/planner_v2.py:30), the measured
per-op cost model (python/paddle/cost_model/cost_model.py,
static_op_benchmark.json) and the comm/comp cost classes
(auto_parallel/cost/base_cost.py), driven from ``Engine``
(auto_parallel/engine.py:53).

TPU-native design: the reference searches per-op process meshes and
dims_mappings because every op can be placed differently; under GSPMD the
placement degrees of freedom collapse to the MESH FACTORIZATION — XLA
propagates a consistent sharding once axis sizes are fixed. So the search
space here is factorizations of ``n_devices`` into (dp, fsdp, tp), scored
by an analytic cost model with two parts:

- **HBM footprint per chip** (the hard constraint): params + grads +
  optimizer moments, each divided by the mesh axes the runtime's
  ``LogicalRules`` would actually shard them over (the SAME rule table
  ``shard_params`` uses — the plan predicts exactly what the runtime
  does), plus an activation/logits estimate from model hints.
- **Step time** (the objective): MXU compute time (model FLOPs / peak)
  plus ICI time for the collectives each axis implies — dp/fsdp gradient
  reduce-scatter+all-gather (ring cost 2·(n-1)/n·bytes), fsdp param
  all-gather at use (ZeRO-3), tp's per-block activation all-reduces.

Chip constants default to TPU v5e (16 GiB HBM, 197 bf16 TFLOP/s,
~45 GB/s ICI per link) and are overridable via ``ChipSpec``.

Pipeline parallelism is not part of the automatic search: pp changes the
program (microbatching, a stage-splittable trunk), not just placement —
callers opt in via ``models.gpt.GPTForCausalLMPipe`` and a ``pp`` mesh
axis. The planner plans the data/model axes which compose with it.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .sharding import LogicalRules

_GiB = float(1 << 30)


@dataclass(frozen=True)
class ChipSpec:
    """Per-chip hardware envelope (defaults: TPU v5e)."""
    hbm_bytes: float = 16 * _GiB
    peak_flops: float = 197e12          # bf16 MXU
    ici_bytes_per_s: float = 45e9       # per-direction ring bandwidth
    hbm_headroom: float = 0.85          # usable fraction (XLA workspace)


@dataclass
class ModelStats:
    """What the cost model needs to know about one training step."""
    param_bytes_sharded: float   # per chip, after rule-table sharding
    param_bytes_total: float
    grad_bytes_sharded: float
    opt_bytes_sharded: float
    act_bytes: float             # activations + logits, per chip
    flops_per_chip: float
    comm_bytes: float            # ICI bytes per step per chip


@dataclass
class Plan:
    axes: Dict[str, int]
    fits: bool
    hbm_bytes: float
    hbm_limit: float
    step_time_s: float
    compute_time_s: float
    comm_time_s: float
    breakdown: Dict[str, float] = field(default_factory=dict)

    def describe(self) -> str:
        ax = " x ".join(f"{k}={v}" for k, v in self.axes.items() if v > 1) \
            or "single-device"
        return (f"{ax}: {self.hbm_bytes / _GiB:.2f} GiB/chip "
                f"(limit {self.hbm_limit / _GiB:.2f}), "
                f"step {self.step_time_s * 1e3:.1f} ms "
                f"(compute {self.compute_time_s * 1e3:.1f} + "
                f"comm {self.comm_time_s * 1e3:.1f})"
                f"{'' if self.fits else '  [OOM]'}")


def abstract_model(ctor):
    """Construct a Layer whose parameters are shape-only (no HBM/RAM):
    the constructor runs under ``jax.eval_shape`` so initializers never
    execute — plan models too big to materialize (the reference plans on
    the static Program, which never materializes weights either;
    engine.py prepares before parameter allocation)."""
    import jax
    import jax.numpy as jnp

    box = {}

    def build():
        box["net"] = ctor()
        return jnp.zeros(())

    jax.eval_shape(build)
    return box["net"]


class _AxisSizes:
    """Duck-typed stand-in for DeviceMesh inside LogicalRules.mesh_axes —
    planning must not require the devices to exist yet."""

    def __init__(self, sizes: Dict[str, int]):
        self.axis_sizes = dict(sizes)

    def axis_size(self, name: str) -> int:
        return self.axis_sizes.get(name, 1)

    def has_axis(self, name: str) -> bool:
        return self.axis_sizes.get(name, 1) > 1


def _factorizations(n: int, axes: Tuple[str, ...]) -> List[Dict[str, int]]:
    """All ordered factorizations of n over the given axes."""
    if len(axes) == 1:
        return [{axes[0]: n}]
    out = []
    for d in range(1, n + 1):
        if n % d == 0:
            for rest in _factorizations(n // d, axes[1:]):
                out.append({axes[0]: d, **rest})
    return out


def _model_hints(net) -> Dict[str, float]:
    """Pull transformer-shaped hints off the model config if present."""
    cfg = getattr(net, "cfg", None)
    hints = {}
    for name in ("hidden_size", "num_layers", "vocab_size",
                 "max_position_embeddings"):
        v = getattr(cfg, name, None)
        if v is not None:
            hints[name] = float(v)
    return hints


def _extract(net):
    """One tree walk: (shapes, logical axes, hints) — reused across every
    candidate the search evaluates."""
    meta = net.param_meta()
    shapes = {name: tuple(p.shape) for name, p in net.named_parameters()}
    logical = {name: getattr(meta.get(name), "axes", None)
               for name in shapes}
    return shapes, logical, _model_hints(net)


def _stats_for(shapes, logical, hints, axes: Dict[str, int],
               global_batch: int, seq_len: int,
               rules: LogicalRules, param_dtype_bytes: int,
               act_dtype_bytes: int) -> ModelStats:
    mesh = _AxisSizes(axes)

    n_data = axes.get("dp", 1) * axes.get("fsdp", 1)
    tp = axes.get("tp", 1)
    b_local = max(1, global_batch // n_data)

    param_total = 0.0
    param_sharded = 0.0
    used_axes = set()
    for name, shape in shapes.items():
        size = math.prod(shape) or 1
        spec = rules.mesh_axes(logical[name], shape, mesh)
        div = 1
        for entry in spec:
            for ax in (entry if isinstance(entry, tuple) else (entry,)):
                if ax is not None:
                    div *= axes.get(ax, 1)
                    used_axes.add(ax)
        param_total += size * param_dtype_bytes
        param_sharded += size * param_dtype_bytes / div

    # grads mirror param sharding; Adam-family moments are 2 extra copies
    # in f32 (optimizer state inherits the param sharding)
    grad_sharded = param_sharded
    opt_sharded = 2.0 * param_sharded * (4 / param_dtype_bytes)

    h = hints.get("hidden_size", 0.0)
    layers = hints.get("num_layers", 0.0)
    vocab = hints.get("vocab_size", 0.0)
    if h and layers:
        # remat'd transformer: one boundary activation [b,s,h] per block
        # (boundaries are not tp-sharded; +2 blocks of working set) plus
        # logits [b,s,V/tp] (vocab-sharded over tp by the rule table)
        act = (layers + 2.0) * b_local * seq_len * h * act_dtype_bytes
        logits = b_local * seq_len * (vocab / tp) * act_dtype_bytes \
            if vocab else 0.0
        act_bytes = act + logits
    else:
        # non-transformer fallback: assume activations ~ 2x sharded params
        act_bytes = 2.0 * param_sharded

    n_params = param_total / param_dtype_bytes
    tokens_local = b_local * seq_len
    # tp divides compute ONLY when the rule table actually sharded a
    # param over it — a tp axis no rule binds replicates work, it does
    # not split it (the old unconditional /tp made tp look free on
    # models it cannot shard)
    tp_eff = tp if "tp" in used_axes else 1
    flops_per_chip = 6.0 * n_params * tokens_local / tp_eff

    # ICI bytes per step per chip (ring costs):
    comm = 0.0
    dp, fsdp = axes.get("dp", 1), axes.get("fsdp", 1)
    red = dp * fsdp  # gradients reduce over all data axes
    if red > 1:
        # reduce-scatter + all-gather of grads (allreduce ring identity)
        comm += 2.0 * (red - 1) / red * (param_total / max(tp, 1))
    if fsdp > 1:
        # ZeRO-3: params all-gathered at use, forward + backward
        comm += 2.0 * (fsdp - 1) / fsdp * (param_total / max(tp, 1))
    if tp > 1 and layers:
        # Megatron blocks: 2 activation allreduces per block forward,
        # 2 in backward, on [b_local, s, h]
        act_blk = b_local * seq_len * h * act_dtype_bytes
        comm += 4.0 * layers * 2.0 * (tp - 1) / tp * act_blk
    elif tp_eff > 1:
        # non-transformer fallback: row-parallel matmuls still allreduce
        # their activations; charge one fwd+bwd pair on the act estimate
        comm += 4.0 * (tp - 1) / tp * act_bytes

    return ModelStats(param_sharded, param_total, grad_sharded,
                      opt_sharded, act_bytes, flops_per_chip, comm)


def _infer_seq_len(seq_len: Optional[int], hints: Dict[str, float]) -> int:
    """seq_len=None: read the model's max_position_embeddings hint — a
    default of 1 on a sequence model would understate activations,
    logits, FLOPs, and tp comm by the whole sequence length."""
    if seq_len is not None:
        return seq_len
    return int(hints.get("max_position_embeddings", 1))


def _evaluate(shapes, logical, hints, axes: Dict[str, int],
              global_batch: int, seq_len: int, chip: ChipSpec,
              rules: LogicalRules, param_dtype_bytes: int,
              act_dtype_bytes: int, hbm_scale: float = 1.0) -> Plan:
    s = _stats_for(shapes, logical, hints, axes, global_batch, seq_len,
                   rules, param_dtype_bytes, act_dtype_bytes)
    hbm = (s.param_bytes_sharded + s.grad_bytes_sharded +
           s.opt_bytes_sharded + s.act_bytes) * hbm_scale
    limit = chip.hbm_bytes * chip.hbm_headroom
    compute_t = s.flops_per_chip / chip.peak_flops
    comm_t = s.comm_bytes / chip.ici_bytes_per_s
    # TPU overlaps collectives with compute only partially; summing ranks
    # conservatively (the relative order of candidates is what matters)
    return Plan(axes=dict(axes), fits=hbm <= limit, hbm_bytes=hbm,
                hbm_limit=limit, step_time_s=compute_t + comm_t,
                compute_time_s=compute_t, comm_time_s=comm_t,
                breakdown={
                    "params": s.param_bytes_sharded,
                    "grads": s.grad_bytes_sharded,
                    "opt_state": s.opt_bytes_sharded,
                    "activations": s.act_bytes,
                    "comm_bytes": s.comm_bytes,
                })


def evaluate(net, axes: Dict[str, int], global_batch: int,
             seq_len: Optional[int] = None,
             chip: Optional[ChipSpec] = None,
             rules: Optional[LogicalRules] = None,
             param_dtype_bytes: int = 4,
             act_dtype_bytes: int = 2) -> Plan:
    """Cost one candidate mesh factorization (the reference's
    ``CostEstimator.estimate`` analog, auto_parallel/cost/estimate_cost)."""
    shapes, logical, hints = _extract(net)
    return _evaluate(shapes, logical, hints, axes, global_batch,
                     _infer_seq_len(seq_len, hints), chip or ChipSpec(),
                     rules or LogicalRules(), param_dtype_bytes,
                     act_dtype_bytes)


def plan(net, n_devices: int, global_batch: int,
         seq_len: Optional[int] = None,
         chip: Optional[ChipSpec] = None,
         rules: Optional[LogicalRules] = None,
         param_dtype_bytes: int = 4,
         act_dtype_bytes: Optional[int] = None,
         return_all: bool = False,
         hbm_scale: float = 1.0):
    """Choose (dp, fsdp, tp) for ``net`` on ``n_devices`` chips.

    Enumerates every factorization, drops layouts that exceed HBM or that
    shard dims unevenly (a tp that does not divide the head count would
    fall back to replication at runtime — the cost model sees that
    through the rule table), and returns the feasible Plan with the
    lowest predicted step time. If nothing fits, returns the
    smallest-footprint plan with ``fits=False`` so the caller can report
    an honest OOM prediction. Ref: planner_v2.py Planner.plan.

    ``act_dtype_bytes`` is an explicit CONFIG (VERDICT r4 'weak' #5):
    the default resolves to 2 (this framework is bf16-first — hapi
    amp_configs="O1" is the dominant training path, and plans are
    usually made at setup time, OUTSIDE any auto_cast scope, so the
    live amp flag is not a reliable signal). Pass 4 when planning an
    fp32-activation run; ``distributed_model``/``verify_plan`` plumb
    the same knob through.
    """
    if act_dtype_bytes is None:
        act_dtype_bytes = 2
    chip = chip or ChipSpec()
    rules = rules or LogicalRules()
    shapes, logical, hints = _extract(net)  # one tree walk for all cands
    seq = _infer_seq_len(seq_len, hints)
    cands = []
    for axes in _factorizations(n_devices, ("dp", "fsdp", "tp")):
        if global_batch % (axes["dp"] * axes["fsdp"]):
            continue
        cands.append(_evaluate(shapes, logical, hints, axes,
                               global_batch, seq, chip, rules,
                               param_dtype_bytes, act_dtype_bytes,
                               hbm_scale))
    if not cands:
        raise ValueError(
            f"no mesh factorization of {n_devices} devices divides "
            f"global batch {global_batch}")
    feasible = [p for p in cands if p.fits]
    if feasible:
        best = min(feasible, key=lambda p: p.step_time_s)
    else:
        best = min(cands, key=lambda p: p.hbm_bytes)
    return (best, cands) if return_all else best


# ---------------------------------------------------------------------------
# closing the loop: analytic plan vs XLA's compiled memory analysis
# (ref: auto_parallel/cost_model.py — the reference calibrates its cost
# model from measured op benchmarks; here the calibration source is the
# compiler's own memory analysis of the ACTUAL compiled step)
# ---------------------------------------------------------------------------

def measured_step_bytes(model, inputs, labels=()) -> float:
    """Per-device bytes of the compiled train step (arguments + XLA
    temporaries; outputs alias donated inputs and are not re-counted).
    Compiles (cached) without executing."""
    from ..core import rng
    model._sync_state_in()
    if model._train_step_fn is None:
        model._train_step_fn = model._build_train_step()
    inputs = tuple(inputs)
    labels = tuple(labels)
    if model._shard_batch is not None:
        inputs = model._shard_batch(inputs)
        labels = model._shard_batch(labels)
    key = rng.split_for_step(0)
    lowered = model._train_step_fn.lower(
        model._params, model._frozen, model._opt_state, model._buffers,
        0, key, inputs, labels)
    mem = lowered.compile().memory_analysis()
    # memory_analysis reports PER-DEVICE sizes (replicated arguments
    # count at full size on each device, sharded ones at shard size)
    return float(mem.temp_size_in_bytes + mem.argument_size_in_bytes)


def verify_plan(model, inputs, labels=(), tolerance: float = 2.0,
                replan: bool = True, chip: Optional[ChipSpec] = None):
    """Check the auto-parallel plan against the compiled step and
    re-plan if the analytic estimate was badly off.

    Compares ``model._plan.hbm_bytes`` (prediction) with the compiled
    step's measured per-device bytes. If measured exceeds
    ``tolerance × predicted`` or the chip budget, the planner re-runs
    with ``hbm_scale = measured/predicted`` (every candidate's footprint
    corrected by the observed calibration factor); a changed layout is
    re-installed on the model (state re-shards on the next step).
    Returns (report dict, plan-in-effect)."""
    import warnings

    plan_obj = getattr(model, "_plan", None)
    ctx = getattr(model, "_planner_ctx", None)
    if plan_obj is None or ctx is None:
        raise ValueError(
            "model has no auto-parallel plan; use "
            "distributed_model(model, global_batch=...) first")
    chip = chip or ctx.get("chip") or ChipSpec()
    measured = measured_step_bytes(model, inputs, labels)
    predicted = max(plan_obj.hbm_bytes, 1.0)
    ratio = measured / predicted
    report = {"predicted_bytes": predicted, "measured_bytes": measured,
              "ratio": ratio, "replanned": False}
    over_budget = measured > chip.hbm_bytes * chip.hbm_headroom
    if ratio <= tolerance and not over_budget:
        return report, plan_obj
    warnings.warn(
        f"auto-parallel plan mis-estimate: predicted "
        f"{predicted / _GiB:.2f} GiB/chip, compiled step uses "
        f"{measured / _GiB:.2f} GiB/chip (x{ratio:.1f})"
        + ("; over the HBM budget" if over_budget else "")
        + ("; re-planning with the measured calibration"
           if replan else ""))
    if not replan:
        return report, plan_obj
    from . import api as _api
    from .mesh import init_mesh_from_axes
    new = plan(model.network, n_devices=ctx["n_devices"],
               global_batch=ctx["global_batch"], seq_len=ctx["seq_len"],
               chip=chip, rules=ctx["rules"], hbm_scale=ratio,
               act_dtype_bytes=ctx.get("act_dtype_bytes"))
    report["replanned"] = True
    report["new_axes"] = dict(new.axes)
    if not new.fits:
        warnings.warn(
            "re-planned layout still exceeds the calibrated HBM budget "
            f"on every factorization (best: {new.describe()}); "
            "installing the smallest footprint — expect OOM unless the "
            "model shrinks or devices are added")
    model._plan = new
    if new.axes == plan_obj.axes:
        return report, new
    # install the corrected layout; device state re-shards lazily
    model._sync_state_out()
    model._params = None
    model._opt_state = None
    model._train_step_fn = None
    model._eval_step_fn = None
    _api.distributed_model(model, mesh=init_mesh_from_axes(new.axes),
                           rules=ctx["rules"])
    model._plan = new
    return report, new
