"""DistributedStrategy — the typed strategy config.

Rebuild of the reference's strategy proto + wrapper
(reference: paddle/fluid/framework/distributed_strategy.proto:278 with
per-feature sub-messages at :320+; Python facade
python/paddle/distributed/fleet/base/distributed_strategy.py:110).
The reference toggles graph-rewrite passes; here each knob either picks a
mesh axis size, a jit option, or a training-loop behavior. Dataclasses
replace protobuf — serializable via to_dict/from_dict (JSON) for parity
with proto text format.
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple


@dataclass
class AMPConfig:
    """ref: distributed_strategy.proto AMPConfig (:320s); bf16-first on
    TPU so no loss scaling by default (dtype='bfloat16'); fp16 + dynamic
    loss scaling kept for parity."""
    enable: bool = False
    dtype: str = "bfloat16"
    level: str = "O1"
    init_loss_scaling: float = 32768.0
    incr_every_n_steps: int = 1000
    decr_every_n_nan_or_inf: int = 2
    incr_ratio: float = 2.0
    decr_ratio: float = 0.5
    use_dynamic_loss_scaling: bool = True
    custom_white_list: Tuple[str, ...] = ()
    custom_black_list: Tuple[str, ...] = ()


@dataclass
class RecomputeConfig:
    """ref: RecomputeConfig proto; maps to jax.checkpoint policies."""
    enable: bool = False
    checkpoints: Tuple[str, ...] = ()     # layer-name prefixes to remat
    policy: str = "nothing_saveable"      # jax.checkpoint policy name


@dataclass
class ShardingConfig:
    """ZeRO stages (ref: GroupShardedStage2/3
    distributed/fleet/meta_parallel/sharding/group_sharded_stage2.py:49,
    group_sharded_stage3.py:60). stage>=3 shards params on the fsdp axis;
    on TPU stages 1/2 (optimizer/grad shard) also express as fsdp-axis
    sharding of the respective trees."""
    enable: bool = False
    stage: int = 3
    degree: int = 1


@dataclass
class PipelineConfig:
    """ref: PipelineConfig proto + meta_parallel/pipeline_parallel.py."""
    enable: bool = False
    degree: int = 1
    micro_batches: int = 1
    schedule: str = "1F1B"


@dataclass
class MoEConfig:
    enable: bool = False
    degree: int = 1  # expert-parallel group size


@dataclass
class HybridConfig:
    """ref: fleet/base/distributed_strategy.py hybrid_configs
    {dp,mp,pp,sharding}_degree."""
    dp_degree: int = -1
    mp_degree: int = 1
    pp_degree: int = 1
    sharding_degree: int = 1
    sp_degree: int = 1
    ep_degree: int = 1


@dataclass
class GradientMergeConfig:
    """ref: gradient_merge_optimizer.py — microbatch grad accumulation."""
    enable: bool = False
    k_steps: int = 1
    avg: bool = True


@dataclass
class DistributedStrategy:
    amp: AMPConfig = field(default_factory=AMPConfig)
    recompute: RecomputeConfig = field(default_factory=RecomputeConfig)
    sharding: ShardingConfig = field(default_factory=ShardingConfig)
    pipeline: PipelineConfig = field(default_factory=PipelineConfig)
    moe: MoEConfig = field(default_factory=MoEConfig)
    hybrid_configs: HybridConfig = field(default_factory=HybridConfig)
    gradient_merge: GradientMergeConfig = field(
        default_factory=GradientMergeConfig)
    # loose knobs (proto scalars)
    gradient_scale: bool = True          # mean-reduce grads over dp
    find_unused_parameters: bool = False  # parity no-op (trace finds all)

    def mesh_axes(self) -> Dict[str, int]:
        h = self.hybrid_configs
        axes = {"dp": h.dp_degree, "tp": h.mp_degree, "pp": h.pp_degree,
                "fsdp": h.sharding_degree, "sp": h.sp_degree,
                "ep": h.ep_degree}
        if self.sharding.enable and self.sharding.degree > 1:
            axes["fsdp"] = self.sharding.degree
        if self.pipeline.enable and self.pipeline.degree > 1:
            axes["pp"] = self.pipeline.degree
        if self.moe.enable and self.moe.degree > 1:
            axes["ep"] = self.moe.degree
        return {k: v for k, v in axes.items() if v != 1}

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2, default=list)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "DistributedStrategy":
        kw = {}
        for f in dataclasses.fields(cls):
            if f.name not in d:
                continue
            v = d[f.name]
            if dataclasses.is_dataclass(f.type) or (
                    isinstance(f.default_factory, type)
                    and dataclasses.is_dataclass(f.default_factory)):
                sub = f.default_factory
                v = sub(**{k: (tuple(x) if isinstance(x, list) else x)
                           for k, x in v.items()})
            kw[f.name] = v
        return cls(**kw)
