"""Collective primitives over mesh axes.

Replaces the reference's collective op zoo (reference:
paddle/fluid/operators/collective/ — c_allreduce_{sum,max,min,prod},
c_allgather, c_reducescatter, c_broadcast, send_v2/recv_v2,
global_scatter/global_gather; eager side distributed/collective/
ProcessGroup.h:85-181). Two registers:

1. **In-SPMD** (inside ``shard_map`` over a mesh): thin wrappers on
   ``jax.lax`` collectives keyed by mesh-axis name. These lower straight
   to XLA all-reduce/all-gather/collective-permute on ICI — no comm-id
   bootstrap, no streams, no `c_sync_comm_stream` ordering (XLA
   schedules them; ref needed c_sync_calc/comm_stream ops for this).
2. **Host-level** on stacked arrays: a "per-rank tensor" in the
   single-controller model is one array with a leading group dim; the
   collective is an ordinary reduction/reshape over dim 0 and XLA emits
   the communication if the array is sharded. This replaces the eager
   ProcessGroup calls used for metric aggregation.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

AxisName = Union[str, Sequence[str]]

# -- register 1: inside shard_map / pmap ------------------------------------

def psum(x, axis: AxisName):
    return lax.psum(x, axis)


def pmean(x, axis: AxisName):
    return lax.pmean(x, axis)


def pmax(x, axis: AxisName):
    return lax.pmax(x, axis)


def pmin(x, axis: AxisName):
    return lax.pmin(x, axis)


def all_gather(x, axis: AxisName, *, tiled: bool = True, gather_dim: int = 0):
    """ref: c_allgather_op.cc — concatenate shards along ``gather_dim``."""
    return lax.all_gather(x, axis, axis=gather_dim, tiled=tiled)


def reduce_scatter(x, axis: AxisName, *, scatter_dim: int = 0):
    """ref: c_reducescatter_op.cc."""
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_dim,
                            tiled=True)


def all_to_all(x, axis: AxisName, *, split_dim: int, concat_dim: int):
    """ref: alltoall_op.cc / the MoE global_scatter primitive
    (operators/collective/global_scatter_op.cc)."""
    return lax.all_to_all(x, axis, split_axis=split_dim,
                          concat_axis=concat_dim, tiled=True)


def ppermute(x, axis: AxisName, perm):
    """ref: send_v2/recv_v2 + partial_send/recv p2p pairs
    (pp_utils/p2p_communication.py) — one collective-permute expresses a
    pipeline shift."""
    return lax.ppermute(x, axis, perm)


def shift(x, axis: AxisName, offset: int = 1):
    """Ring shift: rank i sends to (i+offset) mod n."""
    n = lax.psum(1, axis)
    perm = [(i, (i + offset) % n) for i in range(n)]
    return lax.ppermute(x, axis, perm)


def axis_index(axis: AxisName):
    return lax.axis_index(axis)


def axis_size(axis: AxisName):
    return lax.psum(1, axis)


def broadcast(x, axis: AxisName, src: int = 0):
    """ref: c_broadcast_op.cc — everyone takes src's value. (ppermute
    can't multicast — one source, many destinations — so this is a
    masked psum.)"""
    mask = (lax.axis_index(axis) == src).astype(x.dtype)
    return lax.psum(x * mask, axis)


# -- register 2: host-level on stacked arrays -------------------------------

_REDUCERS = {"sum": jnp.sum, "max": jnp.max, "min": jnp.min,
             "prod": jnp.prod, "mean": jnp.mean, "avg": jnp.mean}


def host_all_reduce(stacked, op: str = "sum"):
    """``stacked``: [group, ...] array, one slice per rank (sharded or
    not); returns the elementwise reduction over the group dim."""
    if op not in _REDUCERS:
        raise ValueError(f"unknown reduce op {op!r}")
    return _REDUCERS[op](jnp.asarray(stacked), axis=0)


def quantized_ring_allreduce(x, axis: AxisName, bits: int = 8):
    """Bandwidth-compressed gradient all-reduce: a hand-rolled ring
    whose wire format is int8 blocks + one f32 scale per hop, ~1/4 the
    bytes of a dense f32 ring (technique shape: EQuARX — quantized
    all-reduce inside XLA; here expressed AS jax collectives since the
    XLA implementation is not user-extensible).

    Use inside shard_map on the gradient axis when ICI/DCN bandwidth —
    not latency — dominates (multi-host DCN reductions; the in-repo
    decision record for DGC explains why SPARSE compression is the
    wrong trade on TPU, parallel/localsgd.py). Accumulation stays f32;
    each hop requantizes, so error grows O(hops * q_eps) — bounded and
    tested against the exact psum.

    reduce-scatter phase: each rank accumulates one block; all-gather
    phase: the reduced blocks circulate once more, quantized once.
    """
    if not 2 <= bits <= 8:
        raise ValueError(
            f"bits={bits}: the wire dtype is int8, so 2..8 bits only")
    n = jax.lax.axis_size(axis)
    if n == 1:
        return x
    qmax = float(2 ** (bits - 1) - 1)
    flat = x.reshape(-1)
    pad = (-flat.shape[0]) % n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    blocks = flat.reshape(n, -1).astype(jnp.float32)
    rank = jax.lax.axis_index(axis)
    ring = [(i, (i + 1) % n) for i in range(n)]  # == shift()'s perm

    def quant(b):
        scale = jnp.maximum(jnp.max(jnp.abs(b)), 1e-20) / qmax
        q = jnp.clip(jnp.round(b / scale), -qmax, qmax).astype(jnp.int8)
        return q, scale

    def dequant(q, scale):
        return q.astype(jnp.float32) * scale

    # reduce-scatter: at step s, send block (rank - s) and accumulate
    # the incoming block (rank - s - 1)
    acc = blocks
    for s in range(n - 1):
        send_idx = (rank - s) % n
        q, scale = quant(jnp.take(acc, send_idx, axis=0))
        q_in = jax.lax.ppermute(q, axis, ring)
        s_in = jax.lax.ppermute(scale, axis, ring)
        recv_idx = (rank - s - 1) % n
        updated = jnp.take(acc, recv_idx, axis=0) + dequant(q_in, s_in)
        acc = jax.lax.dynamic_update_index_in_dim(
            acc, updated, recv_idx, 0)
    # all-gather: each fully-reduced block is quantized ONCE at its
    # owner and the SAME payload circulates the ring, so every rank —
    # including the owner, which adopts its own dequantized broadcast —
    # ends with bit-identical values (replicated params must not
    # diverge across replicas)
    own_idx = (rank + 1) % n
    q_send, s_send = quant(jnp.take(acc, own_idx, axis=0))
    acc = jax.lax.dynamic_update_index_in_dim(
        acc, dequant(q_send, s_send), own_idx, 0)
    for s in range(n - 1):
        q_in = jax.lax.ppermute(q_send, axis, ring)
        s_in = jax.lax.ppermute(s_send, axis, ring)
        recv_idx = (rank - s) % n
        acc = jax.lax.dynamic_update_index_in_dim(
            acc, dequant(q_in, s_in), recv_idx, 0)
        q_send, s_send = q_in, s_in
    out = acc.reshape(-1)
    if pad:
        out = out[:-pad]
    return out.reshape(x.shape).astype(x.dtype)
