"""Collective primitives over mesh axes.

Replaces the reference's collective op zoo (reference:
paddle/fluid/operators/collective/ — c_allreduce_{sum,max,min,prod},
c_allgather, c_reducescatter, c_broadcast, send_v2/recv_v2,
global_scatter/global_gather; eager side distributed/collective/
ProcessGroup.h:85-181). Two registers:

1. **In-SPMD** (inside ``shard_map`` over a mesh): thin wrappers on
   ``jax.lax`` collectives keyed by mesh-axis name. These lower straight
   to XLA all-reduce/all-gather/collective-permute on ICI — no comm-id
   bootstrap, no streams, no `c_sync_comm_stream` ordering (XLA
   schedules them; ref needed c_sync_calc/comm_stream ops for this).
2. **Host-level** on stacked arrays: a "per-rank tensor" in the
   single-controller model is one array with a leading group dim; the
   collective is an ordinary reduction/reshape over dim 0 and XLA emits
   the communication if the array is sharded. This replaces the eager
   ProcessGroup calls used for metric aggregation.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

AxisName = Union[str, Sequence[str]]

# -- register 1: inside shard_map / pmap ------------------------------------

def psum(x, axis: AxisName):
    return lax.psum(x, axis)


def pmean(x, axis: AxisName):
    return lax.pmean(x, axis)


def pmax(x, axis: AxisName):
    return lax.pmax(x, axis)


def pmin(x, axis: AxisName):
    return lax.pmin(x, axis)


def all_gather(x, axis: AxisName, *, tiled: bool = True, gather_dim: int = 0):
    """ref: c_allgather_op.cc — concatenate shards along ``gather_dim``."""
    return lax.all_gather(x, axis, axis=gather_dim, tiled=tiled)


def reduce_scatter(x, axis: AxisName, *, scatter_dim: int = 0):
    """ref: c_reducescatter_op.cc."""
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_dim,
                            tiled=True)


def all_to_all(x, axis: AxisName, *, split_dim: int, concat_dim: int):
    """ref: alltoall_op.cc / the MoE global_scatter primitive
    (operators/collective/global_scatter_op.cc)."""
    return lax.all_to_all(x, axis, split_axis=split_dim,
                          concat_axis=concat_dim, tiled=True)


def ppermute(x, axis: AxisName, perm):
    """ref: send_v2/recv_v2 + partial_send/recv p2p pairs
    (pp_utils/p2p_communication.py) — one collective-permute expresses a
    pipeline shift."""
    return lax.ppermute(x, axis, perm)


def shift(x, axis: AxisName, offset: int = 1):
    """Ring shift: rank i sends to (i+offset) mod n."""
    n = lax.psum(1, axis)
    perm = [(i, (i + offset) % n) for i in range(n)]
    return lax.ppermute(x, axis, perm)


def axis_index(axis: AxisName):
    return lax.axis_index(axis)


def axis_size(axis: AxisName):
    return lax.psum(1, axis)


def broadcast(x, axis: AxisName, src: int = 0):
    """ref: c_broadcast_op.cc — everyone takes src's value. (ppermute
    can't multicast — one source, many destinations — so this is a
    masked psum.)"""
    mask = (lax.axis_index(axis) == src).astype(x.dtype)
    return lax.psum(x * mask, axis)


# -- register 2: host-level on stacked arrays -------------------------------

_REDUCERS = {"sum": jnp.sum, "max": jnp.max, "min": jnp.min,
             "prod": jnp.prod, "mean": jnp.mean, "avg": jnp.mean}


def host_all_reduce(stacked, op: str = "sum"):
    """``stacked``: [group, ...] array, one slice per rank (sharded or
    not); returns the elementwise reduction over the group dim."""
    if op not in _REDUCERS:
        raise ValueError(f"unknown reduce op {op!r}")
    return _REDUCERS[op](jnp.asarray(stacked), axis=0)
