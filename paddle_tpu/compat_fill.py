"""Top-level ``paddle.*`` surface completion (VERDICT r3 ask #4; the
remaining names of python/paddle/__init__.py's __all__ after the
tensor/nn/static fills). Mostly identity/compat records whose real
machinery lives elsewhere in this package — each cites where.
"""

from __future__ import annotations

import builtins
import contextlib
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .core import rng as _rng
from .device import (CPUPlace, CUDAPinnedPlace, CUDAPlace,  # noqa: F401
                     NPUPlace)

# the tensor type itself (ref: paddle.Tensor — the pybind VarBase/
# eager Tensor class). jax.Array is the tensor here; isinstance checks
# and annotations against paddle.Tensor keep working.
Tensor = jax.Array

# dtype alias (ref: paddle.bool)
from .core.dtype import bool_ as bool  # noqa: E402,F401


class ParamAttr:
    """Parameter config carrier (ref: fluid/param_attr.py ParamAttr —
    name/initializer/lr/regularizer/trainable). Consumed by
    create_parameter and accepted (name + initializer + trainable
    honored; per-param lr scaling is the optimizer's _param_groups
    job) anywhere a weight_attr/bias_attr is taken."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=True,
                 need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip


def create_parameter(shape, dtype="float32", name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """Eager free-standing parameter (ref: paddle.create_parameter →
    LayerHelper): returns an initialized array; layers own their
    parameters via Layer.create_parameter."""
    from .nn import initializer as I
    init = default_initializer
    if init is None and isinstance(attr, ParamAttr):
        init = attr.initializer
    if init is None:
        if is_bias:
            init = I.get_global_bias_initializer() or I.Constant(0.0)
        else:
            init = I.get_global_initializer() or I.XavierUniform()
    return init(list(shape), jnp.dtype(dtype))


def batch(reader, batch_size, drop_last=False):
    """Reader-decorator batching (ref: python/paddle/batch.py)."""

    def batched():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return batched


def check_shape(shape, op_name="", expected_shape_type=(list, tuple),
                expected_element_type=(int,),
                expected_tensor_dtype=("int32", "int64")):
    """Shape-argument validator (ref: tensor/random.py check_shape via
    fluid/data_feeder.py:153). Arrays are accepted as dynamic shapes
    when integer-typed."""
    if isinstance(shape, (jax.Array, np.ndarray)):
        if str(np.asarray(shape).dtype) not in expected_tensor_dtype:
            raise TypeError(
                f"{op_name}: Tensor shape must be "
                f"{expected_tensor_dtype}, got {np.asarray(shape).dtype}")
        return
    if not isinstance(shape, expected_shape_type):
        raise TypeError(f"{op_name}: shape must be "
                        f"{expected_shape_type}, got {type(shape)}")
    for item in shape:
        if not isinstance(item, expected_element_type) \
                and not isinstance(item, (jax.Array, np.integer)):
            raise TypeError(
                f"{op_name}: shape element must be int, got "
                f"{type(item)}")


def disable_signal_handler():
    """ref: paddle.disable_signal_handler (the C++ layer's SIGSEGV
    dumpers). The crash handlers here belong to the Python runtime and
    absl; nothing to uninstall — kept for script compat."""


# -- static/dynamic mode toggles (ref: paddle.enable_static — the
# dual-world switch). One world here: the static API (paddle.static)
# works regardless; the flag is tracked so in_dynamic_mode() answers
# faithfully for scripts that branch on it.

_static_mode = False


def enable_static():
    global _static_mode
    _static_mode = True


def disable_static():
    global _static_mode
    _static_mode = False


def in_dynamic_mode() -> bool:
    return not _static_mode


# -- grad-enabled flag (ref: paddle.set_grad_enabled/is_grad_enabled;
# fluid/dygraph/base.py). Gradients are functional (jax.grad), so the
# flag gates the Model/PyLayer paths' willingness to build backwards —
# and no_grad() uses it.

_grad_enabled = True


class set_grad_enabled:
    """Applies at construction (usable as a statement, the reference's
    torch-style semantics) AND restores on context exit."""

    def __init__(self, mode: bool):
        global _grad_enabled
        self._old = _grad_enabled
        _grad_enabled = builtins.bool(mode)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        global _grad_enabled
        _grad_enabled = self._old
        return False


def _set_grad_flag(mode) -> None:
    """Internal: no_grad() (framework.py) flips this so
    is_grad_enabled() answers faithfully inside the context."""
    global _grad_enabled
    _grad_enabled = builtins.bool(mode)


def is_grad_enabled() -> bool:
    return _grad_enabled


def get_cuda_rng_state():
    """ref: paddle.get_cuda_rng_state — generator state snapshot. The
    accelerator RNG here is the counter-based global KeyStream
    (core/rng.py); its state is the key + named sub-streams."""
    stream = _rng.current_stream()
    return {"key": np.asarray(jax.random.key_data(stream._key)),
            "streams": {k: np.asarray(jax.random.key_data(v))
                        for k, v in stream._streams.items()}}


def set_cuda_rng_state(state):
    stream = _rng.current_stream()
    stream._key = jax.random.wrap_key_data(jnp.asarray(state["key"]))
    stream._streams = {
        k: jax.random.wrap_key_data(jnp.asarray(v))
        for k, v in state.get("streams", {}).items()}
