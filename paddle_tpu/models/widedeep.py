"""Wide&Deep + DeepFM CTR models (BASELINE config 5: Wide&Deep CTR,
replacing the reference's PS-trained recommendation path — the model the
Dataset/DataFeed/PS machinery existed to train; ref example pattern:
train_from_dataset with distributed_lookup_table, SURVEY.md §3.5).

Criteo-style input: dense [batch, 13] float features + sparse
[batch, 26] categorical ids hashed into one shared table. On a mesh the
table rows shard over fsdp (SparseEmbedding's "vocab" axis) — multi-host
scale without a parameter server."""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp

from .. import nn
from ..nn.layer import Layer
from ..nn.layers.sparse_embedding import MultiSlotEmbedding


def _deep_tower(in_dim: int, hidden: Sequence[int]) -> "nn.Sequential":
    """[in_dim] -> hidden MLP (ReLU) -> scalar logit, shared by every
    CTR model here."""
    dims = [in_dim, *hidden]
    mlp = []
    for i in range(len(dims) - 1):
        mlp += [nn.Linear(dims[i], dims[i + 1]), nn.ReLU()]
    mlp.append(nn.Linear(dims[-1], 1))
    return nn.Sequential(*mlp)


class WideDeep(Layer):
    """ref model family: wide (linear over sparse) + deep (embeddings +
    MLP), joint logit (Cheng et al. 2016; the canonical PS workload)."""

    def __init__(self, num_dense: int = 13, num_slots: int = 26,
                 vocab_size: int = 1000 * 1000, embedding_dim: int = 16,
                 hidden: Sequence[int] = (256, 128, 64)):
        super().__init__()
        self.num_dense = num_dense
        # wide: 1-dim embedding = per-feature scalar weight (sparse LR);
        # hash_ids folds raw 2^32-range ids into the table
        self.wide = MultiSlotEmbedding(vocab_size, 1, hash_ids=True)
        self.wide_dense = nn.Linear(num_dense, 1)
        # deep: shared table + MLP over [dense | slot embeddings]
        self.embedding = MultiSlotEmbedding(vocab_size, embedding_dim,
                                            hash_ids=True)
        self.deep = _deep_tower(num_dense + num_slots * embedding_dim,
                                hidden)

    def forward(self, dense, sparse_ids):
        wide_logit = self.wide(sparse_ids).sum(-1, keepdims=True) + \
            self.wide_dense(dense)
        deep_in = jnp.concatenate(
            [dense, self.embedding(sparse_ids)], axis=-1)
        deep_logit = self.deep(deep_in)
        return (wide_logit + deep_logit)[:, 0]  # [batch] logits


class DeepFM(Layer):
    """Factorization-machine + deep tower sharing one embedding table
    (the other canonical CTR model in the reference's PS examples)."""

    def __init__(self, num_dense: int = 13, num_slots: int = 26,
                 vocab_size: int = 1000 * 1000, embedding_dim: int = 16,
                 hidden: Sequence[int] = (128, 64)):
        super().__init__()
        self.first_order = MultiSlotEmbedding(vocab_size, 1,
                                              hash_ids=True)
        self.dense_w = nn.Linear(num_dense, 1)
        self.embedding = MultiSlotEmbedding(vocab_size, embedding_dim,
                                            hash_ids=True)
        self.num_slots = num_slots
        self.embedding_dim = embedding_dim
        self.deep = _deep_tower(num_dense + num_slots * embedding_dim,
                                hidden)

    def forward(self, dense, sparse_ids):
        b = dense.shape[0]
        first = self.first_order(sparse_ids).sum(-1, keepdims=True) + \
            self.dense_w(dense)
        flat = self.embedding(sparse_ids)            # [b, slots*dim]
        v = flat.reshape(b, self.num_slots, self.embedding_dim)
        # FM second order: 0.5 * ((Σv)² - Σv²)
        sum_sq = v.sum(axis=1) ** 2
        sq_sum = (v ** 2).sum(axis=1)
        second = 0.5 * (sum_sq - sq_sum).sum(-1, keepdims=True)
        deep = self.deep(jnp.concatenate([dense, flat], axis=-1))
        return (first + second + deep)[:, 0]


class WideDeepHostTable(Layer):
    """WideDeep with both tables in HOST RAM — the parameter-server
    workload proper (BASELINE config 5; ref: train_from_dataset over
    distributed_lookup_table, fluid/distributed/ps/table/
    memory_sparse_table.h). Table capacity is bounded by host memory,
    not HBM: rows are pulled into the jitted step per batch and row
    gradients pushed back with a per-row accessor rule, so the device
    footprint is O(batch) regardless of vocabulary size.

    Per-slot layout is preserved (the deep tower sees
    [dense | slot_0 emb | ... | slot_25 emb]) by looking up ids as
    [b*slots, 1] single-id bags — sum pooling over a bag of one is the
    identity, and the host gather vectorizes over the flattened batch
    the same way."""

    def __init__(self, num_dense: int = 13, num_slots: int = 26,
                 vocab_size: int = 100 * 1000 * 1000,
                 embedding_dim: int = 16,
                 hidden: Sequence[int] = (256, 128, 64),
                 optimizer: str = "adagrad", learning_rate: float = 0.05,
                 async_push: bool = False):
        super().__init__()
        from ..nn.layers.host_embedding import HostOffloadedEmbedding
        self.num_dense = num_dense
        self.num_slots = num_slots
        self.embedding_dim = embedding_dim
        kw = dict(hash_ids=True, optimizer=optimizer,
                  learning_rate=learning_rate, async_push=async_push)
        self.wide = HostOffloadedEmbedding(vocab_size, 1, **kw)
        self.wide_dense = nn.Linear(num_dense, 1)
        self.embedding = HostOffloadedEmbedding(vocab_size, embedding_dim,
                                                **kw)
        self.deep = _deep_tower(num_dense + num_slots * embedding_dim,
                                hidden)

    def forward(self, dense, sparse_ids):
        b, k = sparse_ids.shape
        flat = sparse_ids.reshape(b * k, 1)
        wide_logit = self.wide(flat).reshape(b, k).sum(-1, keepdims=True) \
            + self.wide_dense(dense)
        emb = self.embedding(flat).reshape(b, k * self.embedding_dim)
        deep_logit = self.deep(jnp.concatenate([dense, emb], axis=-1))
        return (wide_logit + deep_logit)[:, 0]


def synthetic_criteo(n: int = 1024, num_dense: int = 13,
                     num_slots: int = 26, vocab_size: int = 10000,
                     seed: int = 0):
    """Synthetic click data with learnable structure: the click
    probability depends on a few 'magic' feature ids and one dense
    column, so models can demonstrably fit it."""
    import numpy as np
    rs = np.random.RandomState(seed)
    dense = rs.randn(n, num_dense).astype(np.float32)
    sparse = rs.randint(1, vocab_size, (n, num_slots)).astype(np.int64)
    magic = (sparse[:, 0] % 5 == 0).astype(np.float32)
    logit = 2.0 * magic + dense[:, 0] - 0.5
    p = 1.0 / (1.0 + np.exp(-logit))
    labels = (rs.rand(n) < p).astype(np.float32)
    return dense, sparse, labels
