"""Checkpoint interop: load HuggingFace / torch weights into the zoo.

The reference's ecosystem ships model converters (PaddleNLP
``convert.py`` per model family, mapping HF torch checkpoints onto
paddle Layers); this is the same capability for the TPU zoo — a user
switching frameworks brings their trained weights along.

Mappings are pure name/layout tables: HF GPT-2's Conv1D stores
weights [in, out], exactly our ``nn.Linear`` convention, so tensors
copy through without transposes; BERT's ``nn.Linear`` stores
[out, in] and transposes on the way in.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np


def _to_numpy(t) -> np.ndarray:
    if hasattr(t, "detach"):  # torch tensor
        return t.detach().cpu().numpy()
    return np.asarray(t)


def _state_dict(model_or_sd) -> Dict[str, np.ndarray]:
    sd = model_or_sd.state_dict() if hasattr(model_or_sd, "state_dict") \
        else model_or_sd
    return {k: _to_numpy(v) for k, v in sd.items()}


def gpt2_from_huggingface(model_or_state_dict, config=None):
    """Build a :class:`~paddle_tpu.models.gpt.GPTForCausalLM` carrying
    the weights of a HF ``GPT2LMHeadModel`` (or its state_dict).

    ``config`` overrides the inferred GPTConfig fields (e.g. to enable
    ``scan_layers``/``fused_loss`` on the converted model). Returns the
    converted model; logits match HF within float tolerance
    (tests/test_convert.py).
    """
    from .gpt import GPTConfig, GPTForCausalLM

    hf_cfg = getattr(model_or_state_dict, "config", None)
    sd = _state_dict(model_or_state_dict)
    sd = {k[len("transformer."):] if k.startswith("transformer.") else k:
          v for k, v in sd.items()}

    n_layer = 1 + max(int(k.split(".")[1]) for k in sd
                      if k.startswith("h."))
    wte = sd["wte.weight"]
    wpe = sd["wpe.weight"]
    n_head = None
    if isinstance(config, dict):
        n_head = config.get("num_heads")
    if n_head is None and hf_cfg is not None:
        # the source model knows its own head count — never guess when
        # it's available (a 48-dim-head checkpoint converts silently
        # wrong under any divisor heuristic)
        n_head = getattr(hf_cfg, "n_head", None) or \
            getattr(hf_cfg, "num_attention_heads", None)
    if n_head is None:
        # bare state_dict fallback: head_dim 64 GPT-2 family invariant.
        # A non-64 head_dim checkpoint would build a silently-wrong
        # model, so say what was guessed and how to override it.
        n_head = max(1, wte.shape[1] // 64)
        import warnings
        warnings.warn(
            f"gpt2_from_huggingface: bare state_dict with no hf config "
            f"and no config={{'num_heads': ...}} override — guessed "
            f"num_heads={n_head} from the GPT-2 head_dim-64 invariant; "
            f"pass num_heads explicitly if this checkpoint differs",
            stacklevel=2)

    kw = dict(vocab_size=wte.shape[0], hidden_size=wte.shape[1],
              num_layers=n_layer, num_heads=n_head,
              max_position_embeddings=wpe.shape[0],
              activation="gelu_tanh",  # HF "gelu_new"
              tie_word_embeddings=True)
    if config is not None and not isinstance(config, dict):
        raise TypeError(
            "config must be a dict of GPTConfig field overrides (a "
            "full config object would silently drop inferred fields "
            "like activation='gelu_tanh')")
    kw.update(config or {})
    cfg = GPTConfig(**kw)

    import paddle_tpu as pt
    pt.seed(0)
    net = GPTForCausalLM(cfg)

    state = {"gpt.embeddings.word_embeddings.weight": wte,
             "gpt.embeddings.position_embeddings.weight": wpe,
             "gpt.ln_f.weight": sd["ln_f.weight"],
             "gpt.ln_f.bias": sd["ln_f.bias"]}
    for i in range(n_layer):
        src, dst = f"h.{i}", f"gpt.layers.{i}"
        state.update({
            # HF Conv1D is [in, out] — our Linear convention; no T
            f"{dst}.ln_1.weight": sd[f"{src}.ln_1.weight"],
            f"{dst}.ln_1.bias": sd[f"{src}.ln_1.bias"],
            f"{dst}.attn.qkv_proj.weight": sd[f"{src}.attn.c_attn.weight"],
            f"{dst}.attn.qkv_proj.bias": sd[f"{src}.attn.c_attn.bias"],
            f"{dst}.attn.out_proj.weight": sd[f"{src}.attn.c_proj.weight"],
            f"{dst}.attn.out_proj.bias": sd[f"{src}.attn.c_proj.bias"],
            f"{dst}.ln_2.weight": sd[f"{src}.ln_2.weight"],
            f"{dst}.ln_2.bias": sd[f"{src}.ln_2.bias"],
            f"{dst}.mlp.fc_in.weight": sd[f"{src}.mlp.c_fc.weight"],
            f"{dst}.mlp.fc_in.bias": sd[f"{src}.mlp.c_fc.bias"],
            f"{dst}.mlp.fc_out.weight": sd[f"{src}.mlp.c_proj.weight"],
            f"{dst}.mlp.fc_out.bias": sd[f"{src}.mlp.c_proj.bias"],
        })
    net.set_state_dict(state)
    return net


def bert_from_huggingface(model_or_state_dict, config=None,
                          with_pooler: bool = True):
    """Build a :class:`~paddle_tpu.models.bert.BertModel` carrying the
    weights of a HF ``BertModel`` (or its state_dict). HF torch Linear
    stores [out, in]: weights transpose on the way in."""
    from .bert import BertConfig, BertModel

    hf_cfg = getattr(model_or_state_dict, "config", None)
    sd = _state_dict(model_or_state_dict)
    sd = {k[len("bert."):] if k.startswith("bert.") else k: v
          for k, v in sd.items()}

    n_layer = 1 + max(int(k.split(".")[2]) for k in sd
                      if k.startswith("encoder.layer."))
    tok = sd["embeddings.word_embeddings.weight"]
    pos = sd["embeddings.position_embeddings.weight"]
    typ = sd["embeddings.token_type_embeddings.weight"]
    inter0 = sd["encoder.layer.0.intermediate.dense.weight"]
    n_head = None
    if isinstance(config, dict):
        n_head = config.get("num_heads")
    if n_head is None and hf_cfg is not None:
        n_head = getattr(hf_cfg, "num_attention_heads", None)
    if n_head is None:
        n_head = max(1, tok.shape[1] // 64)
        import warnings
        warnings.warn(
            f"bert_from_huggingface: bare state_dict with no hf config "
            f"and no config={{'num_heads': ...}} override — guessed "
            f"num_heads={n_head} from the head_dim-64 invariant; pass "
            f"num_heads explicitly if this checkpoint differs",
            stacklevel=2)

    kw = dict(vocab_size=tok.shape[0], hidden_size=tok.shape[1],
              num_layers=n_layer, num_heads=n_head,
              intermediate_size=inter0.shape[0],
              max_position_embeddings=pos.shape[0],
              type_vocab_size=typ.shape[0])
    if config is not None and not isinstance(config, dict):
        raise TypeError(
            "config must be a dict of BertConfig field overrides")
    kw.update(config or {})
    cfg = BertConfig(**kw)

    import paddle_tpu as pt
    pt.seed(0)
    net = BertModel(cfg, with_pooler=with_pooler)

    def lin(dst, src):
        return {f"{dst}.weight": sd[f"{src}.weight"].T,
                f"{dst}.bias": sd[f"{src}.bias"]}

    state = {
        "embeddings.word_embeddings.weight": tok,
        "embeddings.position_embeddings.weight": pos,
        "embeddings.token_type_embeddings.weight": typ,
        "embeddings.layer_norm.weight":
            sd["embeddings.LayerNorm.weight"],
        "embeddings.layer_norm.bias": sd["embeddings.LayerNorm.bias"],
    }
    for i in range(n_layer):
        src = f"encoder.layer.{i}"
        dst = f"encoder.{i}"
        state.update(lin(f"{dst}.attn.q_proj",
                         f"{src}.attention.self.query"))
        state.update(lin(f"{dst}.attn.k_proj",
                         f"{src}.attention.self.key"))
        state.update(lin(f"{dst}.attn.v_proj",
                         f"{src}.attention.self.value"))
        state.update(lin(f"{dst}.attn.out_proj",
                         f"{src}.attention.output.dense"))
        state[f"{dst}.ln_1.weight"] = \
            sd[f"{src}.attention.output.LayerNorm.weight"]
        state[f"{dst}.ln_1.bias"] = \
            sd[f"{src}.attention.output.LayerNorm.bias"]
        state.update(lin(f"{dst}.fc_in", f"{src}.intermediate.dense"))
        state.update(lin(f"{dst}.fc_out", f"{src}.output.dense"))
        state[f"{dst}.ln_2.weight"] = sd[f"{src}.output.LayerNorm.weight"]
        state[f"{dst}.ln_2.bias"] = sd[f"{src}.output.LayerNorm.bias"]
    if with_pooler and "pooler.dense.weight" in sd:
        state.update(lin("pooler.dense", "pooler.dense"))
    net.set_state_dict(state)
    return net


def llama_from_huggingface(model_or_state_dict, config=None):
    """Build a LLaMA-style :class:`~paddle_tpu.models.gpt.GPTForCausalLM`
    (RoPE + RMSNorm + SwiGLU + GQA, ``llama_config``) carrying the
    weights of a HF ``LlamaForCausalLM`` (or its state_dict).

    HF torch Linears store [out, in] (transposed in); the fused
    projections concatenate on the out dim — qkv as [q | k | v], the
    SwiGLU input as [gate | up] (our ``F.swiglu`` silus the first
    half). HF's rotary is the same half-split convention as
    ``ops/rotary.py``, so weights copy through unpermuted.
    """
    from .gpt import GPTForCausalLM, llama_config

    hf_cfg = getattr(model_or_state_dict, "config", None)
    sd = _state_dict(model_or_state_dict)
    sd = {k[len("model."):] if k.startswith("model.") else k: v
          for k, v in sd.items()}

    n_layer = 1 + max(int(k.split(".")[1]) for k in sd
                      if k.startswith("layers."))
    tok = sd["embed_tokens.weight"]
    hidden = tok.shape[1]
    kq = sd["layers.0.self_attn.q_proj.weight"]     # [H, H]
    kk = sd["layers.0.self_attn.k_proj.weight"]     # [kv*hd, H]
    gate0 = sd["layers.0.mlp.gate_proj.weight"]     # [ffn, H]

    n_head = getattr(hf_cfg, "num_attention_heads", None) \
        if hf_cfg is not None else None
    n_kv = getattr(hf_cfg, "num_key_value_heads", None) \
        if hf_cfg is not None else None
    if isinstance(config, dict):
        n_head = config.get("num_heads", n_head)
        n_kv = config.get("num_kv_heads", n_kv)
    if n_head is None:
        raise ValueError(
            "pass the HF model (not a bare state_dict) or "
            "config={'num_heads': ..., 'num_kv_heads': ...} — the "
            "head grouping is not inferable from weight shapes alone")
    if n_kv is None:
        n_kv = max(1, n_head * kk.shape[0] // kq.shape[0])

    rope_theta = getattr(hf_cfg, "rope_theta", 10000.0) \
        if hf_cfg is not None else 10000.0
    max_pos = getattr(hf_cfg, "max_position_embeddings", 2048) \
        if hf_cfg is not None else 2048
    # tie_word_embeddings=True checkpoints (Llama-3.2 family;
    # safetensors drops the shared lm_head tensor) have no
    # lm_head.weight — tie the built model instead of KeyError-ing.
    # The hf config's flag wins when present: a tied model passed as a
    # live HF module DOES expose the shared tensor in state_dict(), so
    # key presence alone would silently untie it.
    tied = "lm_head.weight" not in sd
    if hf_cfg is not None:
        tied = bool(getattr(hf_cfg, "tie_word_embeddings", tied))
    kw = dict(hidden_size=hidden, num_layers=n_layer,
              num_heads=n_head, num_kv_heads=n_kv,
              vocab_size=tok.shape[0],
              max_position_embeddings=max_pos,
              ffn_hidden_size=gate0.shape[0], rope_base=rope_theta,
              layer_norm_epsilon=getattr(hf_cfg, "rms_norm_eps", 1e-6)
              if hf_cfg is not None else 1e-6,
              tie_word_embeddings=tied)
    if config is not None and not isinstance(config, dict):
        raise TypeError(
            "config must be a dict of llama_config overrides")
    kw.update(config or {})
    cfg = llama_config(**kw)

    import paddle_tpu as pt
    pt.seed(0)
    net = GPTForCausalLM(cfg)

    state = {"gpt.embeddings.word_embeddings.weight": tok,
             "gpt.ln_f.weight": sd["norm.weight"]}
    if not cfg.tie_word_embeddings:
        state["lm_head.weight"] = sd["lm_head.weight"].T
    for i in range(n_layer):
        src, dst = f"layers.{i}", f"gpt.layers.{i}"
        qkv = np.concatenate(
            [sd[f"{src}.self_attn.q_proj.weight"].T,
             sd[f"{src}.self_attn.k_proj.weight"].T,
             sd[f"{src}.self_attn.v_proj.weight"].T], axis=1)
        fc_in = np.concatenate(
            [sd[f"{src}.mlp.gate_proj.weight"].T,
             sd[f"{src}.mlp.up_proj.weight"].T], axis=1)
        state.update({
            f"{dst}.ln_1.weight": sd[f"{src}.input_layernorm.weight"],
            f"{dst}.attn.qkv_proj.weight": qkv,
            f"{dst}.attn.out_proj.weight":
                sd[f"{src}.self_attn.o_proj.weight"].T,
            f"{dst}.ln_2.weight":
                sd[f"{src}.post_attention_layernorm.weight"],
            f"{dst}.mlp.fc_in.weight": fc_in,
            f"{dst}.mlp.fc_out.weight":
                sd[f"{src}.mlp.down_proj.weight"].T,
            # HF llama projections are bias-free; our Linears carry
            # biases — zero them so the math matches
            f"{dst}.attn.qkv_proj.bias":
                np.zeros(qkv.shape[1], qkv.dtype),
            f"{dst}.attn.out_proj.bias":
                np.zeros(hidden, qkv.dtype),
            f"{dst}.mlp.fc_in.bias":
                np.zeros(fc_in.shape[1], fc_in.dtype),
            f"{dst}.mlp.fc_out.bias":
                np.zeros(hidden, fc_in.dtype),
        })
    net.set_state_dict(state)
    return net
