"""VGG family (ref: python/paddle/vision/models/vgg.py — make_layers +
vgg11/13/16/19 with optional batch_norm)."""

from __future__ import annotations

from .. import nn

CFGS = {
    "A": [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "B": [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M",
          512, 512, "M"],
    "D": [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512,
          "M", 512, 512, 512, "M"],
    "E": [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M", 512, 512,
          512, 512, "M", 512, 512, 512, 512, "M"],
}


def make_layers(cfg, batch_norm: bool = False) -> nn.Sequential:
    layers = []
    in_channels = 3
    for v in cfg:
        if v == "M":
            layers.append(nn.MaxPool2D(2, 2))
        else:
            layers.append(nn.Conv2D(in_channels, v, 3, padding=1))
            if batch_norm:
                layers.append(nn.BatchNorm2D(v))
            layers.append(nn.ReLU())
            in_channels = v
    return nn.Sequential(*layers)


class VGG(nn.Layer):
    """ref: vision/models/vgg.py VGG(features, num_classes)."""

    def __init__(self, features: nn.Layer, num_classes: int = 1000,
                 with_pool: bool = True):
        super().__init__()
        self.features = features
        self.num_classes = num_classes
        self.with_pool = with_pool
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((7, 7))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(512 * 7 * 7, 4096),
                nn.ReLU(),
                nn.Dropout(),
                nn.Linear(4096, 4096),
                nn.ReLU(),
                nn.Dropout(),
                nn.Linear(4096, num_classes),
            )

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = nn.Flatten()(x)
            x = self.classifier(x)
        return x


def _vgg(cfg, batch_norm=False, **kwargs):
    return VGG(make_layers(CFGS[cfg], batch_norm=batch_norm), **kwargs)


def vgg11(batch_norm=False, **kwargs):
    return _vgg("A", batch_norm, **kwargs)


def vgg13(batch_norm=False, **kwargs):
    return _vgg("B", batch_norm, **kwargs)


def vgg16(batch_norm=False, **kwargs):
    return _vgg("D", batch_norm, **kwargs)


def vgg19(batch_norm=False, **kwargs):
    return _vgg("E", batch_norm, **kwargs)
