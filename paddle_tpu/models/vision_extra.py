"""Additional vision families: AlexNet, SqueezeNet, DenseNet, GoogLeNet,
ShuffleNetV2 (ref: python/paddle/vision/models/{alexnet,squeezenet,
densenet,googlenet,shufflenetv2}.py — same topologies, same constructor
surface).

TPU notes: all convs route through F.conv2d (XLA picks MXU layouts);
channel-shuffle is a reshape-transpose pair XLA fuses to a relayout;
DenseNet's concatenations are pure layout ops under XLA."""

from __future__ import annotations

from typing import List

import jax.numpy as jnp

from .. import nn
from ..nn import functional as F


# ---------------------------------------------------------------------------
# AlexNet (ref: vision/models/alexnet.py)
# ---------------------------------------------------------------------------

class AlexNet(nn.Layer):
    def __init__(self, num_classes: int = 1000):
        super().__init__()
        self.features = nn.Sequential(
            nn.Conv2D(3, 64, 11, stride=4, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, stride=2),
            nn.Conv2D(64, 192, 5, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, stride=2),
            nn.Conv2D(192, 384, 3, padding=1), nn.ReLU(),
            nn.Conv2D(384, 256, 3, padding=1), nn.ReLU(),
            nn.Conv2D(256, 256, 3, padding=1), nn.ReLU(),
            nn.MaxPool2D(3, stride=2),
        )
        self.avgpool = nn.AdaptiveAvgPool2D((6, 6))
        self.classifier = nn.Sequential(
            nn.Dropout(0.5), nn.Linear(256 * 6 * 6, 4096), nn.ReLU(),
            nn.Dropout(0.5), nn.Linear(4096, 4096), nn.ReLU(),
            nn.Linear(4096, num_classes),
        )

    def forward(self, x):
        x = self.avgpool(self.features(x))
        return self.classifier(x.reshape(x.shape[0], -1))


def alexnet(**kw):
    return AlexNet(**kw)


# ---------------------------------------------------------------------------
# SqueezeNet (ref: vision/models/squeezenet.py)
# ---------------------------------------------------------------------------

class Fire(nn.Layer):
    def __init__(self, in_ch, squeeze, e1, e3):
        super().__init__()
        self.squeeze = nn.Conv2D(in_ch, squeeze, 1)
        self.expand1 = nn.Conv2D(squeeze, e1, 1)
        self.expand3 = nn.Conv2D(squeeze, e3, 3, padding=1)
        self.relu = nn.ReLU()

    def forward(self, x):
        s = self.relu(self.squeeze(x))
        return jnp.concatenate(
            [self.relu(self.expand1(s)), self.relu(self.expand3(s))],
            axis=1)


class SqueezeNet(nn.Layer):
    """version '1.0'/'1.1' (ref: squeezenet.py SqueezeNet)."""

    def __init__(self, version: str = "1.1", num_classes: int = 1000):
        super().__init__()
        if version == "1.0":
            self.features = nn.Sequential(
                nn.Conv2D(3, 96, 7, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, stride=2),
                Fire(96, 16, 64, 64), Fire(128, 16, 64, 64),
                Fire(128, 32, 128, 128), nn.MaxPool2D(3, stride=2),
                Fire(256, 32, 128, 128), Fire(256, 48, 192, 192),
                Fire(384, 48, 192, 192), Fire(384, 64, 256, 256),
                nn.MaxPool2D(3, stride=2), Fire(512, 64, 256, 256),
            )
        elif version == "1.1":
            self.features = nn.Sequential(
                nn.Conv2D(3, 64, 3, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, stride=2),
                Fire(64, 16, 64, 64), Fire(128, 16, 64, 64),
                nn.MaxPool2D(3, stride=2),
                Fire(128, 32, 128, 128), Fire(256, 32, 128, 128),
                nn.MaxPool2D(3, stride=2),
                Fire(256, 48, 192, 192), Fire(384, 48, 192, 192),
                Fire(384, 64, 256, 256), Fire(512, 64, 256, 256),
            )
        else:
            raise ValueError(f"unknown version {version!r}")
        self.classifier = nn.Sequential(
            nn.Dropout(0.5), nn.Conv2D(512, num_classes, 1), nn.ReLU(),
            nn.AdaptiveAvgPool2D(1))

    def forward(self, x):
        x = self.classifier(self.features(x))
        return x.reshape(x.shape[0], -1)


def squeezenet1_0(**kw):
    return SqueezeNet("1.0", **kw)


def squeezenet1_1(**kw):
    return SqueezeNet("1.1", **kw)


# ---------------------------------------------------------------------------
# DenseNet (ref: vision/models/densenet.py)
# ---------------------------------------------------------------------------

class _DenseLayer(nn.Layer):
    def __init__(self, in_ch, growth, bn_size):
        super().__init__()
        self.bn1 = nn.BatchNorm2D(in_ch)
        self.conv1 = nn.Conv2D(in_ch, bn_size * growth, 1,
                               bias_attr=False)
        self.bn2 = nn.BatchNorm2D(bn_size * growth)
        self.conv2 = nn.Conv2D(bn_size * growth, growth, 3, padding=1,
                               bias_attr=False)
        self.relu = nn.ReLU()

    def forward(self, x):
        y = self.conv1(self.relu(self.bn1(x)))
        y = self.conv2(self.relu(self.bn2(y)))
        return jnp.concatenate([x, y], axis=1)


class _Transition(nn.Layer):
    def __init__(self, in_ch, out_ch):
        super().__init__()
        self.bn = nn.BatchNorm2D(in_ch)
        self.conv = nn.Conv2D(in_ch, out_ch, 1, bias_attr=False)
        self.relu = nn.ReLU()
        self.pool = nn.AvgPool2D(2, stride=2)

    def forward(self, x):
        return self.pool(self.conv(self.relu(self.bn(x))))


class DenseNet(nn.Layer):
    """ref: densenet.py DenseNet(layers=121/161/169/201/264).
    Per-config (block layout, growth rate, stem channels) — 161 is the
    wide variant (growth 48, 96-channel stem)."""

    CONFIGS = {121: ((6, 12, 24, 16), 32, 64),
               161: ((6, 12, 36, 24), 48, 96),
               169: ((6, 12, 32, 32), 32, 64),
               201: ((6, 12, 48, 32), 32, 64),
               264: ((6, 12, 64, 48), 32, 64)}

    def __init__(self, layers: int = 121, growth_rate: int = None,
                 bn_size: int = 4, num_classes: int = 1000):
        super().__init__()
        if layers not in self.CONFIGS:
            raise ValueError(
                f"DenseNet layers must be one of "
                f"{sorted(self.CONFIGS)}, got {layers}")
        block_cfg, default_growth, ch = self.CONFIGS[layers]
        growth_rate = growth_rate or default_growth
        feats = [nn.Conv2D(3, ch, 7, stride=2, padding=3,
                           bias_attr=False),
                 nn.BatchNorm2D(ch), nn.ReLU(),
                 nn.MaxPool2D(3, stride=2, padding=1)]
        for bi, n in enumerate(block_cfg):
            for _ in range(n):
                feats.append(_DenseLayer(ch, growth_rate, bn_size))
                ch += growth_rate
            if bi != len(block_cfg) - 1:
                feats.append(_Transition(ch, ch // 2))
                ch //= 2
        feats += [nn.BatchNorm2D(ch), nn.ReLU()]
        self.features = nn.Sequential(*feats)
        self.avgpool = nn.AdaptiveAvgPool2D(1)
        self.classifier = nn.Linear(ch, num_classes)

    def forward(self, x):
        x = self.avgpool(self.features(x))
        return self.classifier(x.reshape(x.shape[0], -1))


def densenet121(**kw):
    return DenseNet(121, **kw)


def densenet161(**kw):
    return DenseNet(161, **kw)


def densenet169(**kw):
    return DenseNet(169, **kw)


def densenet201(**kw):
    return DenseNet(201, **kw)


def densenet264(**kw):
    return DenseNet(264, **kw)


# ---------------------------------------------------------------------------
# GoogLeNet / Inception v1 (ref: vision/models/googlenet.py)
# ---------------------------------------------------------------------------

class Inception(nn.Layer):
    def __init__(self, in_ch, c1, c3r, c3, c5r, c5, pp):
        super().__init__()
        self.b1 = nn.Sequential(nn.Conv2D(in_ch, c1, 1), nn.ReLU())
        self.b2 = nn.Sequential(nn.Conv2D(in_ch, c3r, 1), nn.ReLU(),
                                nn.Conv2D(c3r, c3, 3, padding=1),
                                nn.ReLU())
        self.b3 = nn.Sequential(nn.Conv2D(in_ch, c5r, 1), nn.ReLU(),
                                nn.Conv2D(c5r, c5, 5, padding=2),
                                nn.ReLU())
        self.b4 = nn.Sequential(nn.MaxPool2D(3, stride=1, padding=1),
                                nn.Conv2D(in_ch, pp, 1), nn.ReLU())

    def forward(self, x):
        return jnp.concatenate(
            [self.b1(x), self.b2(x), self.b3(x), self.b4(x)], axis=1)


class GoogLeNet(nn.Layer):
    def __init__(self, num_classes: int = 1000):
        super().__init__()
        self.stem = nn.Sequential(
            nn.Conv2D(3, 64, 7, stride=2, padding=3), nn.ReLU(),
            nn.MaxPool2D(3, stride=2, padding=1),
            nn.Conv2D(64, 64, 1), nn.ReLU(),
            nn.Conv2D(64, 192, 3, padding=1), nn.ReLU(),
            nn.MaxPool2D(3, stride=2, padding=1))
        self.blocks = nn.Sequential(
            Inception(192, 64, 96, 128, 16, 32, 32),
            Inception(256, 128, 128, 192, 32, 96, 64),
            nn.MaxPool2D(3, stride=2, padding=1),
            Inception(480, 192, 96, 208, 16, 48, 64),
            Inception(512, 160, 112, 224, 24, 64, 64),
            Inception(512, 128, 128, 256, 24, 64, 64),
            Inception(512, 112, 144, 288, 32, 64, 64),
            Inception(528, 256, 160, 320, 32, 128, 128),
            nn.MaxPool2D(3, stride=2, padding=1),
            Inception(832, 256, 160, 320, 32, 128, 128),
            Inception(832, 384, 192, 384, 48, 128, 128))
        self.avgpool = nn.AdaptiveAvgPool2D(1)
        self.dropout = nn.Dropout(0.2)
        self.fc = nn.Linear(1024, num_classes)

    def forward(self, x):
        x = self.avgpool(self.blocks(self.stem(x)))
        return self.fc(self.dropout(x.reshape(x.shape[0], -1)))


def googlenet(**kw):
    return GoogLeNet(**kw)


# ---------------------------------------------------------------------------
# ShuffleNetV2 (ref: vision/models/shufflenetv2.py)
# ---------------------------------------------------------------------------

def channel_shuffle(x, groups: int):
    b, c, h, w = x.shape
    x = x.reshape(b, groups, c // groups, h, w)
    x = x.transpose(0, 2, 1, 3, 4)
    return x.reshape(b, c, h, w)


class _ShuffleUnit(nn.Layer):
    def __init__(self, in_ch, out_ch, stride, act: str = "relu"):
        super().__init__()
        act_layer = nn.Swish if act == "swish" else nn.ReLU
        self.stride = stride
        branch_ch = out_ch // 2
        if stride > 1:
            self.branch1 = nn.Sequential(
                nn.Conv2D(in_ch, in_ch, 3, stride=stride, padding=1,
                          groups=in_ch, bias_attr=False),
                nn.BatchNorm2D(in_ch),
                nn.Conv2D(in_ch, branch_ch, 1, bias_attr=False),
                nn.BatchNorm2D(branch_ch), act_layer())
            b2_in = in_ch
        else:
            self.branch1 = None
            b2_in = in_ch // 2
        self.branch2 = nn.Sequential(
            nn.Conv2D(b2_in, branch_ch, 1, bias_attr=False),
            nn.BatchNorm2D(branch_ch), act_layer(),
            nn.Conv2D(branch_ch, branch_ch, 3, stride=stride, padding=1,
                      groups=branch_ch, bias_attr=False),
            nn.BatchNorm2D(branch_ch),
            nn.Conv2D(branch_ch, branch_ch, 1, bias_attr=False),
            nn.BatchNorm2D(branch_ch), act_layer())

    def forward(self, x):
        if self.stride == 1:
            half = x.shape[1] // 2
            x1, x2 = x[:, :half], x[:, half:]
            out = jnp.concatenate([x1, self.branch2(x2)], axis=1)
        else:
            out = jnp.concatenate([self.branch1(x), self.branch2(x)],
                                  axis=1)
        return channel_shuffle(out, 2)


class ShuffleNetV2(nn.Layer):
    # ref: shufflenetv2.py stage_out_channels per scale (x0_25 ... x2_0)
    SCALES = {0.25: (24, 48, 96, 512), 0.33: (32, 64, 128, 512),
              0.5: (48, 96, 192, 1024), 1.0: (116, 232, 464, 1024),
              1.5: (176, 352, 704, 1024), 2.0: (244, 488, 976, 2048)}

    def __init__(self, scale: float = 1.0, num_classes: int = 1000,
                 act: str = "relu"):
        super().__init__()
        c2, c3, c4, c5 = self.SCALES[scale]
        act_layer = nn.Swish if act == "swish" else nn.ReLU
        self.stem = nn.Sequential(
            nn.Conv2D(3, 24, 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(24), act_layer(),
            nn.MaxPool2D(3, stride=2, padding=1))
        stages = []
        in_ch = 24
        for out_ch, repeat in ((c2, 4), (c3, 8), (c4, 4)):
            stages.append(_ShuffleUnit(in_ch, out_ch, 2, act=act))
            for _ in range(repeat - 1):
                stages.append(_ShuffleUnit(out_ch, out_ch, 1, act=act))
            in_ch = out_ch
        self.stages = nn.Sequential(*stages)
        self.head = nn.Sequential(
            nn.Conv2D(in_ch, c5, 1, bias_attr=False),
            nn.BatchNorm2D(c5), act_layer(), nn.AdaptiveAvgPool2D(1))
        self.fc = nn.Linear(c5, num_classes)

    def forward(self, x):
        x = self.head(self.stages(self.stem(x)))
        return self.fc(x.reshape(x.shape[0], -1))


def shufflenet_v2_x1_0(**kw):
    return ShuffleNetV2(1.0, **kw)


def shufflenet_v2_x0_5(**kw):
    return ShuffleNetV2(0.5, **kw)


def shufflenet_v2_x0_25(**kw):
    return ShuffleNetV2(0.25, **kw)


def shufflenet_v2_x0_33(**kw):
    return ShuffleNetV2(0.33, **kw)


def shufflenet_v2_x1_5(**kw):
    return ShuffleNetV2(1.5, **kw)


def shufflenet_v2_x2_0(**kw):
    return ShuffleNetV2(2.0, **kw)


def shufflenet_v2_swish(**kw):
    return ShuffleNetV2(1.0, act="swish", **kw)


# ---------------------------------------------------------------------------
# InceptionV3 (ref: vision/models/inceptionv3.py — factorized
# convolutions, 299x299 input, 2048-d head)
# ---------------------------------------------------------------------------

class _BasicConv2d(nn.Layer):
    def __init__(self, in_c, out_c, kernel, stride=1, padding=0):
        super().__init__()
        self.conv = nn.Conv2D(in_c, out_c, kernel, stride=stride,
                              padding=padding, bias_attr=False)
        self.bn = nn.BatchNorm2D(out_c)

    def forward(self, x):
        from ..nn import functional as F
        return F.relu(self.bn(self.conv(x)))


class _InceptionA(nn.Layer):
    def __init__(self, in_c, pool_features):
        super().__init__()
        self.b1 = _BasicConv2d(in_c, 64, 1)
        self.b5 = nn.Sequential(_BasicConv2d(in_c, 48, 1),
                                _BasicConv2d(48, 64, 5, padding=2))
        self.b3 = nn.Sequential(_BasicConv2d(in_c, 64, 1),
                                _BasicConv2d(64, 96, 3, padding=1),
                                _BasicConv2d(96, 96, 3, padding=1))
        self.pool_proj = _BasicConv2d(in_c, pool_features, 1)
        self.pool = nn.AvgPool2D(3, stride=1, padding=1)

    def forward(self, x):
        return jnp.concatenate(
            [self.b1(x), self.b5(x), self.b3(x),
             self.pool_proj(self.pool(x))], axis=1)


class _InceptionB(nn.Layer):
    def __init__(self, in_c):
        super().__init__()
        self.b3 = _BasicConv2d(in_c, 384, 3, stride=2)
        self.b3dbl = nn.Sequential(_BasicConv2d(in_c, 64, 1),
                                   _BasicConv2d(64, 96, 3, padding=1),
                                   _BasicConv2d(96, 96, 3, stride=2))
        self.pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return jnp.concatenate(
            [self.b3(x), self.b3dbl(x), self.pool(x)], axis=1)


class _InceptionC(nn.Layer):
    def __init__(self, in_c, c7):
        super().__init__()
        self.b1 = _BasicConv2d(in_c, 192, 1)
        self.b7 = nn.Sequential(
            _BasicConv2d(in_c, c7, 1),
            _BasicConv2d(c7, c7, (1, 7), padding=(0, 3)),
            _BasicConv2d(c7, 192, (7, 1), padding=(3, 0)))
        self.b7dbl = nn.Sequential(
            _BasicConv2d(in_c, c7, 1),
            _BasicConv2d(c7, c7, (7, 1), padding=(3, 0)),
            _BasicConv2d(c7, c7, (1, 7), padding=(0, 3)),
            _BasicConv2d(c7, c7, (7, 1), padding=(3, 0)),
            _BasicConv2d(c7, 192, (1, 7), padding=(0, 3)))
        self.pool_proj = _BasicConv2d(in_c, 192, 1)
        self.pool = nn.AvgPool2D(3, stride=1, padding=1)

    def forward(self, x):
        return jnp.concatenate(
            [self.b1(x), self.b7(x), self.b7dbl(x),
             self.pool_proj(self.pool(x))], axis=1)


class _InceptionD(nn.Layer):
    def __init__(self, in_c):
        super().__init__()
        self.b3 = nn.Sequential(_BasicConv2d(in_c, 192, 1),
                                _BasicConv2d(192, 320, 3, stride=2))
        self.b7x3 = nn.Sequential(
            _BasicConv2d(in_c, 192, 1),
            _BasicConv2d(192, 192, (1, 7), padding=(0, 3)),
            _BasicConv2d(192, 192, (7, 1), padding=(3, 0)),
            _BasicConv2d(192, 192, 3, stride=2))
        self.pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return jnp.concatenate(
            [self.b3(x), self.b7x3(x), self.pool(x)], axis=1)


class _InceptionE(nn.Layer):
    def __init__(self, in_c):
        super().__init__()
        self.b1 = _BasicConv2d(in_c, 320, 1)
        self.b3_stem = _BasicConv2d(in_c, 384, 1)
        self.b3_a = _BasicConv2d(384, 384, (1, 3), padding=(0, 1))
        self.b3_b = _BasicConv2d(384, 384, (3, 1), padding=(1, 0))
        self.b3dbl_stem = nn.Sequential(
            _BasicConv2d(in_c, 448, 1),
            _BasicConv2d(448, 384, 3, padding=1))
        self.b3dbl_a = _BasicConv2d(384, 384, (1, 3), padding=(0, 1))
        self.b3dbl_b = _BasicConv2d(384, 384, (3, 1), padding=(1, 0))
        self.pool_proj = _BasicConv2d(in_c, 192, 1)
        self.pool = nn.AvgPool2D(3, stride=1, padding=1)

    def forward(self, x):
        s = self.b3_stem(x)
        d = self.b3dbl_stem(x)
        return jnp.concatenate(
            [self.b1(x),
             self.b3_a(s), self.b3_b(s),
             self.b3dbl_a(d), self.b3dbl_b(d),
             self.pool_proj(self.pool(x))], axis=1)


class InceptionV3(nn.Layer):
    """ref: vision/models/inceptionv3.py InceptionV3(num_classes,
    with_pool). 299x299 input canonical; any size >= 75 works."""

    def __init__(self, num_classes: int = 1000, with_pool: bool = True):
        super().__init__()
        self.stem = nn.Sequential(
            _BasicConv2d(3, 32, 3, stride=2),
            _BasicConv2d(32, 32, 3),
            _BasicConv2d(32, 64, 3, padding=1),
            nn.MaxPool2D(3, stride=2),
            _BasicConv2d(64, 80, 1),
            _BasicConv2d(80, 192, 3),
            nn.MaxPool2D(3, stride=2))
        self.blocks = nn.Sequential(
            _InceptionA(192, 32), _InceptionA(256, 64),
            _InceptionA(288, 64),
            _InceptionB(288),
            _InceptionC(768, 128), _InceptionC(768, 160),
            _InceptionC(768, 160), _InceptionC(768, 192),
            _InceptionD(768),
            _InceptionE(1280), _InceptionE(2048))
        self.with_pool = with_pool
        self.num_classes = num_classes
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = nn.Dropout(0.5)
            self.fc = nn.Linear(2048, num_classes)

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.dropout(x.reshape(x.shape[0], -1))
            x = self.fc(x)
        return x


def inception_v3(**kw):
    return InceptionV3(**kw)
