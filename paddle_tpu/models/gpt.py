"""GPT: decoder-only transformer LM (flagship model, BASELINE config 4).

The reference ships GPT via its ecosystem (fleetx/PaddleNLP) built on the
incubate fused transformer layers
(reference: python/paddle/incubate/nn/layer/fused_transformer.py:176
FusedMultiHeadAttention, :437 FusedFeedForward, :641
FusedTransformerEncoderLayer; CUDA kernels
paddle/fluid/operators/fused/fused_multi_transformer_op.cu) and the
Megatron tensor-parallel layers (VocabParallelEmbedding /
ColumnParallelLinear / RowParallelLinear,
python/paddle/distributed/fleet/meta_parallel/parallel_layers/mp_layers.py:30).

TPU-native design: one model definition carries logical sharding axes on
its weights ("vocab", "embed", "heads", "mlp"); the same code runs dense
on one chip or TP/FSDP/DP-sharded under a mesh — XLA inserts the
identity/allreduce pairs the reference hand-codes in mp_layers.py.
Attention dispatches to the Pallas flash kernel (paddle_tpu.ops).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from .. import nn
from ..core import rng
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer import Layer, LayerList


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    num_kv_heads: Optional[int] = None   # grouped-query; None = num_heads
    ffn_hidden_size: Optional[int] = None  # None = 4*hidden
    max_position_embeddings: int = 1024
    hidden_dropout: float = 0.1
    attention_dropout: float = 0.1
    activation: str = "gelu"   # "swiglu" selects the gated MLP
    norm_type: str = "layer"   # "rms" selects RMSNorm (LLaMA-style)
    use_rope: bool = False     # rotary positions instead of learned
    rope_base: float = 10000.0
    initializer_range: float = 0.02
    layer_norm_epsilon: float = 1e-5
    tie_word_embeddings: bool = True
    use_flash: bool = True
    remat: bool = False  # rematerialize each block (jax.checkpoint)
    # context parallelism: attention runs as ring attention over the
    # mesh's sp axis (ops/ring_attention — K/V chunks rotate the ICI
    # ring; exact numerics). Composes with dp/fsdp/tp (partial-manual
    # over sp only); NOT with the pp trunk (nested manual axes) or the
    # decode cache. ring_chunk_size additionally streams each block's
    # K/V in tiles (flash-in-block) for true long-context footprints.
    sequence_parallel: bool = False
    ring_chunk_size: Optional[int] = None
    # lax.scan over the (identical-structure) decoder blocks instead of
    # a Python loop: the block lowers ONCE (compile time ~O(1) in depth
    # — the lever that makes 24-48-layer configs compile fast), and
    # with remat=True the recompute is structural (scan carries are the
    # only saved activations; XLA cannot CSE recomputation across scan
    # iterations, so the memory win survives every backend's pipeline).
    # Per-layer params are stacked to [L, ...] leaves at trace time —
    # one extra params-sized HBM copy per step, paid for depth>=12 by
    # the compile/memory wins. Decode caches fall back to the loop.
    scan_layers: bool = False
    # fused vocab path: forward returns (hidden, tied weight) and
    # GPTFusedPretrainingCriterion streams the loss over vocab chunks —
    # the [b, s, vocab] logits never exist in the train graph (PERF.md)
    fused_loss: bool = False

    def __post_init__(self):
        if self.ffn_hidden_size is None:
            self.ffn_hidden_size = 4 * self.hidden_size
        if self.num_kv_heads is None:
            self.num_kv_heads = self.num_heads

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


# named presets; "gpt3-1.3b" is BASELINE config 4's hybrid-parallel target
PRESETS = {
    "gpt2-small": dict(hidden_size=768, num_layers=12, num_heads=12,
                       max_position_embeddings=1024),
    "gpt2-medium": dict(hidden_size=1024, num_layers=24, num_heads=16,
                        max_position_embeddings=1024),
    "gpt2-large": dict(hidden_size=1280, num_layers=36, num_heads=20,
                       max_position_embeddings=1024),
    "gpt2-xl": dict(hidden_size=1600, num_layers=48, num_heads=25,
                    max_position_embeddings=1024),
    "gpt3-1.3b": dict(hidden_size=2048, num_layers=24, num_heads=16,
                      max_position_embeddings=2048),
    "gpt3-6.7b": dict(hidden_size=4096, num_layers=32, num_heads=32,
                      max_position_embeddings=2048),
    "gpt3-13b": dict(hidden_size=5120, num_layers=40, num_heads=40,
                     max_position_embeddings=2048),
}


def llama_config(hidden_size: int = 2048, num_layers: int = 22,
                 num_heads: int = 16, num_kv_heads: int = 4,
                 vocab_size: int = 32000,
                 max_position_embeddings: int = 2048,
                 **overrides) -> GPTConfig:
    """LLaMA-style decoder: RoPE + RMSNorm + SwiGLU + GQA + untied
    head — the modern-LLM configuration of the same GPT skeleton."""
    base = dict(vocab_size=vocab_size, hidden_size=hidden_size,
                num_layers=num_layers, num_heads=num_heads,
                num_kv_heads=num_kv_heads,
                ffn_hidden_size=int(hidden_size * 8 / 3) // 128 * 128,
                max_position_embeddings=max_position_embeddings,
                hidden_dropout=0.0, attention_dropout=0.0,
                activation="swiglu", norm_type="rms", use_rope=True,
                tie_word_embeddings=False)
    base.update(overrides)
    return GPTConfig(**base)


def gpt_config(name: str, **overrides) -> GPTConfig:
    cfg = dict(PRESETS[name])
    cfg.update(overrides)
    return GPTConfig(**cfg)


def _norm(cfg: GPTConfig):
    if cfg.norm_type == "rms":
        return nn.RMSNorm(cfg.hidden_size, epsilon=cfg.layer_norm_epsilon)
    return nn.LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_epsilon)


class GPTAttention(Layer):
    """Causal self-attention with fused QKV and optional KV cache.

    Unlike nn.MultiHeadAttention (API-parity layer), the QKV projection
    is a single matmul — one big MXU op instead of three — and supports
    grouped-query heads. Column-parallel in, row-parallel out."""

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        h, hd = cfg.hidden_size, cfg.head_dim
        self.num_heads = cfg.num_heads
        self.num_kv_heads = cfg.num_kv_heads
        qkv_out = h + 2 * cfg.num_kv_heads * hd
        init = I.Normal(0.0, cfg.initializer_range)
        self.qkv_proj = nn.Linear(h, qkv_out, weight_attr=init,
                                  axes=("embed", "heads"),
                                  bias_axes=("heads",))
        self.out_proj = nn.Linear(h, h, weight_attr=I.Normal(
            0.0, cfg.initializer_range / math.sqrt(2 * cfg.num_layers)),
            axes=("heads", "embed"), bias_axes=(None,))

    def _sp_mesh(self):
        """The installed mesh when it has a real sp axis, else None
        (sequence_parallel degrades to plain attention off-mesh, so
        the same config runs single-device tests unchanged)."""
        from ..parallel.mesh import get_mesh
        mesh = get_mesh(required=False)
        if mesh is not None and mesh.axis_size("sp") > 1:
            return mesh
        return None

    def forward(self, x, attn_mask=None, cache=None,
                position_ids=None):
        b, s, h = x.shape
        hd = self.cfg.head_dim
        # [b, s] KEY-padding masks (the sp contract) are accepted by
        # every branch: the dense paths expand them to the additive
        # [b, 1, 1, s] broadcast form, so an sp-trained padded-batch
        # config still evaluates on a single device unchanged. The
        # sentinel is FINITE (softmax over an all--inf row is NaN) and
        # rows whose whole causal window is padded are zeroed after
        # attention — exactly what the ring path's fully-masked
        # handling produces (ops/ring_attention.py), keeping
        # dense/sp numerics interchangeable even for left-padding.
        dense_mask = attn_mask
        row_has_key = None
        if attn_mask is not None and attn_mask.ndim == 2:
            kpm_bool = attn_mask if attn_mask.dtype == jnp.bool_ \
                else attn_mask > -1e29
            am = jnp.where(kpm_bool, 0.0, -1e30).astype(jnp.float32)
            dense_mask = am[:, None, None, :]
            # causal: query r has a valid key iff any kpm[:, :r+1]
            row_has_key = jnp.cumsum(kpm_bool, axis=1) > 0   # [b, s]
        qkv = self.qkv_proj(x)
        q, k, v = jnp.split(
            qkv, [h, h + self.num_kv_heads * hd], axis=-1)
        q = q.reshape(b, s, self.num_heads, hd)
        k = k.reshape(b, s, self.num_kv_heads, hd)
        v = v.reshape(b, s, self.num_kv_heads, hd)
        if self.cfg.use_rope:
            # rotate BEFORE the cache write so cached keys carry their
            # absolute positions (decode-offset contract,
            # ops/rotary.py); tables fold to trace-time constants
            from ..ops.rotary import apply_rotary_pos_emb, rope_tables
            cos, sin = rope_tables(hd, self.cfg.max_position_embeddings,
                                   self.cfg.rope_base)
            if position_ids is None:
                start = cache[2] if cache is not None else 0
                position_ids = jnp.broadcast_to(
                    start + jnp.arange(s)[None, :], (b, s))
            q, k = apply_rotary_pos_emb(q, k, cos, sin,
                                        position_ids=position_ids)
        if cache is not None:
            k_cache, v_cache, idx = cache
            k_cache = jax.lax.dynamic_update_slice_in_dim(
                jnp.asarray(k_cache), k, idx, axis=1)
            v_cache = jax.lax.dynamic_update_slice_in_dim(
                jnp.asarray(v_cache), v, idx, axis=1)
            cache = (k_cache, v_cache, idx + s)
            k, v = k_cache, v_cache
            # causal within the new window AND only written cache slots:
            # query t (absolute idx+t) may attend keys at positions <= idx+t
            kl = k.shape[1]
            key_pos = jnp.arange(kl)[None, None, None, :]
            qry_pos = (idx + jnp.arange(s))[None, None, :, None]
            causal_mask = jnp.where(key_pos <= qry_pos, 0.0, -jnp.inf)
            if dense_mask is not None:  # e.g. padded-prompt mask
                if dense_mask.dtype == jnp.bool_:
                    dense_mask = jnp.where(dense_mask, 0.0, -jnp.inf)
                causal_mask = causal_mask + dense_mask
            out = F.scaled_dot_product_attention(
                q, k, v, attn_mask=causal_mask,
                dropout_p=self.cfg.attention_dropout,
                training=self.training, use_flash=False)
        elif self.cfg.sequence_parallel and \
                (sp_mesh := self._sp_mesh()) is not None:
            from ..ops.ring_attention import ring_attention
            if attn_mask is not None and attn_mask.shape != (b, s):
                # a general [.., sq, sk] mask would have to be
                # materialized per ring block pair; the serving/training
                # case is padded batches, which is a KEY-padding mask —
                # sharded and rotated with K/V, never fully materialized
                raise NotImplementedError(
                    "sequence_parallel attention takes a KEY-padding "
                    f"attn_mask of shape [batch, seq] = {(b, s)} (bool "
                    "True=attend, or additive float); got "
                    f"{attn_mask.shape}")
            if self.num_kv_heads != self.num_heads:
                # ring blocks want matching head counts; expand GQA
                # groups (correctness path — the K/V tiles are small)
                rep = self.num_heads // self.num_kv_heads
                k = jnp.repeat(k, rep, axis=2)
                v = jnp.repeat(v, rep, axis=2)
            dp = self.cfg.attention_dropout if self.training else 0.0
            out = ring_attention(
                q, k, v, causal=True, mesh=sp_mesh,
                chunk_size=self.cfg.ring_chunk_size,
                key_padding_mask=attn_mask,
                dropout_p=dp,
                # same key on every sp rank; ring_attention folds in the
                # block's global coordinates (pipeline tick-RNG trick)
                dropout_key=rng.next_key("sp_attn") if dp else None)
        else:
            # always causal (decoder-only); an extra additive mask (e.g.
            # padding) composes with it rather than replacing it
            out = F.scaled_dot_product_attention(
                q, k, v, attn_mask=dense_mask, is_causal=True,
                dropout_p=self.cfg.attention_dropout,
                training=self.training, use_flash=self.cfg.use_flash)
            if row_has_key is not None:
                out = jnp.where(row_has_key[:, :, None, None], out, 0.0)
        out = self.out_proj(out.reshape(b, s, h))
        if cache is not None:
            return out, cache
        return out


class GPTMLP(Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        init = I.Normal(0.0, cfg.initializer_range)
        init_out = I.Normal(
            0.0, cfg.initializer_range / math.sqrt(2 * cfg.num_layers))
        self._swiglu = cfg.activation == "swiglu"
        in_width = 2 * cfg.ffn_hidden_size if self._swiglu \
            else cfg.ffn_hidden_size
        self.fc_in = nn.Linear(cfg.hidden_size, in_width,
                               weight_attr=init,
                               axes=("embed", "mlp"), bias_axes=("mlp",))
        self.fc_out = nn.Linear(cfg.ffn_hidden_size, cfg.hidden_size,
                                weight_attr=init_out,
                                axes=("mlp", "embed"), bias_axes=(None,))
        self.act = F.swiglu if self._swiglu else getattr(F, cfg.activation)
        self.dropout = nn.Dropout(cfg.hidden_dropout)

    def forward(self, x):
        return self.dropout(self.fc_out(self.act(self.fc_in(x))))


class GPTDecoderLayer(Layer):
    """Pre-LN decoder block (GPT-2/3 style)."""

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.ln_1 = _norm(cfg)
        self.attn = GPTAttention(cfg)
        self.ln_2 = _norm(cfg)
        self.mlp = GPTMLP(cfg)
        self.dropout = nn.Dropout(cfg.hidden_dropout)

    def forward(self, x, attn_mask=None, cache=None,
                position_ids=None):
        a = self.attn(self.ln_1(x), attn_mask=attn_mask, cache=cache,
                      position_ids=position_ids)
        if cache is not None:
            a, cache = a
        x = x + self.dropout(a)
        x = x + self.mlp(self.ln_2(x))
        if cache is not None:
            return x, cache
        return x


class GPTEmbeddings(Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        init = I.Normal(0.0, cfg.initializer_range)
        # vocab-parallel embedding (ref: mp_layers.py:30
        # VocabParallelEmbedding): shard the vocab dim over tp
        self.word_embeddings = nn.Embedding(
            cfg.vocab_size, cfg.hidden_size, weight_attr=init,
            axes=("vocab", "embed"))
        if not cfg.use_rope:  # rotary encodes positions in attention
            self.position_embeddings = nn.Embedding(
                cfg.max_position_embeddings, cfg.hidden_size,
                weight_attr=init, axes=(None, "embed"))
        self.dropout = nn.Dropout(cfg.hidden_dropout)
        self._use_rope = cfg.use_rope
        self._max_pos = cfg.max_position_embeddings

    def forward(self, input_ids, position_ids=None):
        s = input_ids.shape[1]
        max_pos = self._max_pos
        if s > max_pos:
            raise ValueError(
                f"sequence length {s} exceeds max_position_embeddings "
                f"{max_pos} (an out-of-range gather would silently clamp)")
        from ..parallel.sharding import with_logical_constraint
        tok = with_logical_constraint(
            self.word_embeddings(input_ids), ("batch", "seq", None))
        if self._use_rope:
            return self.dropout(tok)
        if position_ids is None:
            position_ids = jnp.arange(s)[None, :]
        pos = with_logical_constraint(
            self.position_embeddings(position_ids), (None, "seq", None))
        return self.dropout(tok + pos)


class GPTModel(Layer):
    """Transformer trunk: embeddings → N decoder blocks → final LN."""

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        self.embeddings = GPTEmbeddings(cfg)
        self.layers = LayerList(
            [GPTDecoderLayer(cfg) for _ in range(cfg.num_layers)])
        self.ln_f = _norm(cfg)

    def forward(self, input_ids, position_ids=None, attn_mask=None,
                caches=None):
        from ..parallel.sharding import with_logical_constraint
        x = self.embeddings(input_ids, position_ids)
        # activation layout anchor: batch over the data axes, hidden
        # replicated — fsdp-sharded params are all-gathered at use
        # (ZeRO-3), rather than letting fsdp leak into activation hidden
        # dims (which forced full-remat reshards in the partitioner)
        x = with_logical_constraint(x, ("batch", "seq", None))
        rope_pos = position_ids if self.cfg.use_rope else None
        new_caches = [] if caches is not None else None
        if self.cfg.scan_layers and caches is None:
            x = self._scan_trunk(x, attn_mask, rope_pos)
        else:
            for i, layer in enumerate(self.layers):
                if caches is not None:
                    x, c = layer(x, attn_mask=attn_mask, cache=caches[i],
                                 position_ids=rope_pos)
                    new_caches.append(c)
                elif self.cfg.remat:
                    # trade FLOPs for HBM: recompute the block in backward
                    x = jax.checkpoint(
                        lambda x, l=layer: l(x, attn_mask=attn_mask,
                                             position_ids=rope_pos))(x)
                else:
                    x = layer(x, attn_mask=attn_mask,
                              position_ids=rope_pos)
                x = with_logical_constraint(x, ("batch", "seq", None))
        x = self.ln_f(x)
        if caches is not None:
            return x, new_caches
        return x

    def _scan_trunk(self, x, attn_mask, rope_pos):
        """lax.scan over the decoder stack (cfg.scan_layers) — see
        nn.utils.scan_layer_stack for the mechanics (single-lowering
        depth loop, stacked [L, ...] params, per-layer dropout keys,
        structural remat). ref: the reference's depth loop is
        run-to-completion eager (incubate/nn/functional teaches fused
        blocks instead); scan-over-depth is the XLA-native form."""
        from ..nn.utils import scan_layer_stack
        from ..parallel.sharding import with_logical_constraint

        return scan_layer_stack(
            self.layers, x, remat=self.cfg.remat,
            constraint=lambda o: with_logical_constraint(
                o, ("batch", "seq", None)),
            rng_tag="scan_trunk", attn_mask=attn_mask,
            position_ids=rope_pos)


def _lm_logits(cfg: GPTConfig, embeddings: GPTEmbeddings, hidden,
               lm_head=None):
    """Shared head: tied-embedding matmul (bf16 under AMP; the loss
    upcasts to f32 for its log-softmax) or a separate lm_head."""
    if cfg.tie_word_embeddings:
        from .. import amp
        w = embeddings.word_embeddings.weight  # [V, H]
        hidden, w = amp.white_cast(hidden, w)
        return jnp.einsum("bsh,vh->bsv", hidden, w)
    return lm_head(hidden)


class GPTForCausalLM(Layer):
    """GPT with a (tied) LM head and generation utilities."""

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        self.gpt = GPTModel(cfg)
        if not cfg.tie_word_embeddings:
            self.lm_head = nn.Linear(cfg.hidden_size, cfg.vocab_size,
                                     bias_attr=False,
                                     axes=("embed", "vocab"))

    def _logits(self, hidden):
        return _lm_logits(self.cfg, self.gpt.embeddings, hidden,
                          getattr(self, "lm_head", None))

    def forward(self, input_ids, position_ids=None, attn_mask=None,
                caches=None):
        out = self.gpt(input_ids, position_ids, attn_mask, caches)
        if caches is not None:
            hidden, new_caches = out
            return self._logits(hidden), new_caches
        if self.cfg.fused_loss and self.training:
            # hand (hidden, W [vocab, hidden]) to the fused criterion;
            # W rides the output so its gradient flows through
            # value_and_grad. NOTE: metrics attached to Model.prepare
            # see the hidden states during fused training — compute
            # accuracy-style metrics in eval (logits path) instead.
            if not self.cfg.tie_word_embeddings:
                return out, self.lm_head.weight.T  # Linear stores [H,V]
            return out, self.gpt.embeddings.word_embeddings.weight
        return self._logits(out)

    # -- decode-time KV cache -------------------------------------------
    def init_caches(self, batch_size: int, max_len: int, dtype=jnp.float32):
        cfg = self.cfg
        shape = (batch_size, max_len, cfg.num_kv_heads, cfg.head_dim)
        return [(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype), 0)
                for _ in range(cfg.num_layers)]

    def generate(self, input_ids, max_new_tokens: int = 20,
                 temperature: float = 0.0, top_k: int = 0, seed: int = 0):
        """Greedy (temperature=0) or top-k sampled decoding with a KV
        cache. Eager loop — the serving path AOT-compiles a scan instead."""
        self.eval()
        b, s = input_ids.shape
        max_len = s + max_new_tokens
        if max_len > self.cfg.max_position_embeddings:
            raise ValueError(
                f"prompt {s} + max_new_tokens {max_new_tokens} exceeds "
                f"max_position_embeddings "
                f"{self.cfg.max_position_embeddings}")
        caches = self.init_caches(b, max_len)
        key = jax.random.PRNGKey(seed)
        # prefill
        logits, caches = self(input_ids, caches=caches)
        tokens = input_ids
        next_logits = logits[:, -1]
        for step in range(max_new_tokens):
            if temperature > 0.0:
                key, sub = jax.random.split(key)
                lg = next_logits / temperature
                if top_k > 0:
                    kth = jnp.sort(lg, axis=-1)[:, -top_k][:, None]
                    lg = jnp.where(lg < kth, -jnp.inf, lg)
                nxt = jax.random.categorical(sub, lg, axis=-1)
            else:
                nxt = jnp.argmax(next_logits, axis=-1)
            nxt = nxt[:, None]
            tokens = jnp.concatenate([tokens, nxt], axis=1)
            if step == max_new_tokens - 1:
                break
            pos = jnp.full((b, 1), s + step)
            next_logits, caches = self(nxt, position_ids=pos, caches=caches)
            next_logits = next_logits[:, -1]
        return tokens


class GPTForCausalLMPipe(Layer):
    """GPT composed with SPMD pipeline parallelism over the decoder trunk.

    The reference builds this as ``GPTForPretrainingPipe`` — a
    PipelineLayer of embedding/decoder/head segments dispatched by the
    1F1B runtime (fleet meta_parallel pp_layers.py:162,
    pipeline_parallel.py:82). TPU-native composition: embeddings, final
    LN and the (tied) LM head stay OUTSIDE the pipelined trunk —
    pp-replicated, their grads all-reduced by XLA at the shard boundary,
    replacing the reference's shared-embedding allreduce
    (pp_layers.py SharedLayerDesc) — while the structurally identical
    decoder blocks run under ``parallel.PipelineParallel`` with the
    circular schedule. The pipeline's output arrives sharded over pp on
    the batch dim, so the head/loss run data-parallel over pp for free.
    """

    def __init__(self, cfg: GPTConfig, num_microbatches: int = 1,
                 virtual_pp_degree: int = 1, mesh=None):
        super().__init__()
        from ..parallel import get_mesh
        from ..parallel.pipeline import PipelineLayer, PipelineParallel
        self.cfg = cfg
        if cfg.scan_layers:
            import warnings
            warnings.warn(
                "GPTForCausalLMPipe ignores cfg.scan_layers: the "
                "pipeline's tick scan + checkpointed tick body already "
                "provide the structural depth loop and remat")
        if cfg.sequence_parallel:
            raise ValueError(
                "sequence_parallel cannot compose with the pipelined "
                "trunk: ring attention's shard_map would nest inside "
                "the pipeline's manual pp region. Use sp with the "
                "dense GPTForCausalLM, or pp without sp")
        mesh = mesh or get_mesh(required=False)
        pp = mesh.axis_size("pp") if mesh is not None else 1
        num_stages = pp * virtual_pp_degree
        if cfg.num_layers % num_stages:
            raise ValueError(
                f"num_layers {cfg.num_layers} not divisible by "
                f"pp*virtual_pp_degree = {num_stages}")
        self.embeddings = GPTEmbeddings(cfg)
        blocks = [GPTDecoderLayer(cfg) for _ in range(cfg.num_layers)]
        mb_spec = mesh.batch_spec() if mesh is not None else None
        from jax.sharding import PartitionSpec as P
        self.pipe = PipelineParallel(
            PipelineLayer(blocks, num_stages=num_stages),
            num_microbatches=num_microbatches,
            virtual_pp_degree=virtual_pp_degree,
            mesh=mesh, mb_spec=mb_spec if mb_spec is not None else P(),
            remat=True)
        self.ln_f = _norm(cfg)
        if not cfg.tie_word_embeddings:
            self.lm_head = nn.Linear(cfg.hidden_size, cfg.vocab_size,
                                     bias_attr=False,
                                     axes=("embed", "vocab"))

    def _logits(self, hidden):
        return _lm_logits(self.cfg, self.embeddings, hidden,
                          getattr(self, "lm_head", None))

    def forward(self, input_ids, position_ids=None):
        x = self.embeddings(input_ids, position_ids)
        x = self.pipe(x)
        x = self.ln_f(x)
        if self.cfg.fused_loss and self.training:
            # compose pp with the streaming vocab path: the pipeline's
            # output arrives batch-sharded over pp, and the fused loss
            # keeps logits out of HBM on top of it
            if not self.cfg.tie_word_embeddings:
                return x, self.lm_head.weight.T
            return x, self.embeddings.word_embeddings.weight
        return self._logits(x)


class GPTGreedyDecoder(Layer):
    """AOT-servable generation: the whole greedy decode loop — prefill,
    KV cache, ``lax.scan`` over new tokens — compiles into ONE program,
    exportable with ``jit.save`` and served by the native predictor.

    The reference serves generation by re-entering AnalysisPredictor
    once per token from host code (inference/api/analysis_predictor.h),
    paying a host round-trip each step; here the loop lives on-device
    and the artifact's signature is prompt ids → generated ids."""

    def __init__(self, model: GPTForCausalLM, max_new_tokens: int):
        super().__init__()
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1 (the prefill "
                             "always argmaxes one token)")
        self.model = model
        self.max_new_tokens = max_new_tokens

    def forward(self, input_ids):
        self.eval()  # decoding is inference (mirrors generate())
        cfg = self.model.cfg
        b, s = input_ids.shape
        max_len = s + self.max_new_tokens
        # symbolic s (shape-polymorphic export) defers this to runtime
        if isinstance(s, int) and max_len > cfg.max_position_embeddings:
            raise ValueError(
                f"prompt {s} + {self.max_new_tokens} new tokens exceeds "
                f"max_position_embeddings {cfg.max_position_embeddings}")
        caches = self.model.init_caches(b, max_len)
        logits, caches = self.model(input_ids, caches=caches)
        first = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)

        def step(carry, i):
            tok, caches = carry
            pos = jnp.full((b, 1), s, jnp.int32) + i
            lg, caches = self.model(tok[:, None], position_ids=pos,
                                    caches=caches)
            nxt = jnp.argmax(lg[:, -1], axis=-1).astype(jnp.int32)
            return (nxt, caches), tok

        (last, _), toks = jax.lax.scan(
            step, (first, caches), jnp.arange(self.max_new_tokens - 1))
        new = jnp.concatenate(
            [jnp.moveaxis(toks, 0, 1), last[:, None]], axis=1)
        return jnp.concatenate([input_ids.astype(jnp.int32), new],
                               axis=1)


class GPTPretrainingCriterion(Layer):
    """Shifted next-token cross entropy; the TP analog of the reference's
    ParallelCrossEntropy (mp_layers.py:251 / c_softmax_with_cross_entropy)
    falls out of sharding the vocab dim of logits."""

    def __init__(self, ignore_index: int = -100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, logits, labels):
        # logits [b, s, v], labels [b, s]: predict token t+1 at position t
        lg = logits[:, :-1].reshape(-1, logits.shape[-1])
        lb = labels[:, 1:].reshape(-1)
        return F.cross_entropy(lg, lb, ignore_index=self.ignore_index)


class GPTFusedPretrainingCriterion(Layer):
    """Streaming vocab-path loss for cfg.fused_loss=True models: takes
    (hidden [b, s, h], weight [v, h]) from the model's forward and
    computes shifted next-token cross entropy over vocab chunks —
    no [b, s, v] logits in HBM (ops/fused_xent.py)."""

    def __init__(self, ignore_index: int = -100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, *args):
        if len(args) == 2:
            # eval mode: the model emits dense logits — fall back to
            # the standard shifted cross entropy so evaluate()/fit with
            # eval_data works on fused_loss models
            logits, labels = args
            lg = logits[:, :-1].reshape(-1, logits.shape[-1])
            lb = labels[:, 1:].reshape(-1)
            return F.cross_entropy(lg, lb,
                                   ignore_index=self.ignore_index)
        hidden, weight, labels = args
        from .. import amp
        from ..ops.fused_xent import fused_linear_cross_entropy
        hidden, weight = amp.white_cast(hidden, weight, op="matmul")
        h = hidden[:, :-1].reshape(-1, hidden.shape[-1])
        lb = labels[:, 1:].reshape(-1)
        return fused_linear_cross_entropy(
            h, weight, lb, self.ignore_index)
