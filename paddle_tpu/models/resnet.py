"""ResNet family (ref: python/paddle/vision/models/resnet.py —
BasicBlock/BottleneckBlock + resnet18/34/50/101/152; BASELINE config 2
is ResNet-50 ImageNet).

TPU notes: NCHW public API (reference parity); convs lower through
``F.conv2d`` whose dimension-numbers let XLA pick the fastest internal
layout for the MXU's convolution tiling. BatchNorm keeps running stats
as buffers (mutated through functional_call's buffer threading)."""

from __future__ import annotations

from typing import List, Optional, Type, Union

from .. import nn


class BasicBlock(nn.Layer):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None,
                 norm_layer=nn.BatchNorm2D, groups=1, base_width=64):
        super().__init__()
        if groups != 1 or base_width != 64:
            raise ValueError(
                "BasicBlock only supports groups=1, base_width=64 "
                "(ref: vision/models/resnet.py BasicBlock)")
        self.conv1 = nn.Conv2D(inplanes, planes, 3, stride=stride,
                               padding=1, bias_attr=False)
        self.bn1 = norm_layer(planes)
        self.conv2 = nn.Conv2D(planes, planes, 3, padding=1,
                               bias_attr=False)
        self.bn2 = norm_layer(planes)
        self.relu = nn.ReLU()
        self.downsample = downsample
        self.stride = stride

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class BottleneckBlock(nn.Layer):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None,
                 norm_layer=nn.BatchNorm2D, groups=1, base_width=64):
        super().__init__()
        # grouped/widened bottleneck (ResNeXt / WideResNet; ref:
        # vision/models/resnet.py BottleneckBlock width arithmetic)
        width = int(planes * (base_width / 64.0)) * groups
        self.conv1 = nn.Conv2D(inplanes, width, 1, bias_attr=False)
        self.bn1 = norm_layer(width)
        self.conv2 = nn.Conv2D(width, width, 3, stride=stride,
                               padding=1, groups=groups, bias_attr=False)
        self.bn2 = norm_layer(width)
        self.conv3 = nn.Conv2D(width, planes * self.expansion, 1,
                               bias_attr=False)
        self.bn3 = norm_layer(planes * self.expansion)
        self.relu = nn.ReLU()
        self.downsample = downsample
        self.stride = stride

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class ResNet(nn.Layer):
    """ref: vision/models/resnet.py ResNet(Block, depth, num_classes,
    with_pool)."""

    def __init__(self, block: Type[Union[BasicBlock, BottleneckBlock]],
                 depth: int = 50, num_classes: int = 1000,
                 with_pool: bool = True, groups: int = 1,
                 width_per_group: int = 64):
        super().__init__()
        self.groups = groups
        self.base_width = width_per_group
        layer_cfg = {18: [2, 2, 2, 2], 34: [3, 4, 6, 3],
                     50: [3, 4, 6, 3], 101: [3, 4, 23, 3],
                     152: [3, 8, 36, 3]}
        layers = layer_cfg[depth]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.inplanes = 64
        self.conv1 = nn.Conv2D(3, 64, 7, stride=2, padding=3,
                               bias_attr=False)
        self.bn1 = nn.BatchNorm2D(64)
        self.relu = nn.ReLU()
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1)
        self.layer1 = self._make_layer(block, 64, layers[0])
        self.layer2 = self._make_layer(block, 128, layers[1], stride=2)
        self.layer3 = self._make_layer(block, 256, layers[2], stride=2)
        self.layer4 = self._make_layer(block, 512, layers[3], stride=2)
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, planes, blocks, stride=1):
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = nn.Sequential(
                nn.Conv2D(self.inplanes, planes * block.expansion, 1,
                          stride=stride, bias_attr=False),
                nn.BatchNorm2D(planes * block.expansion),
            )
        layers = [block(self.inplanes, planes, stride, downsample,
                        groups=self.groups, base_width=self.base_width)]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.inplanes, planes,
                                groups=self.groups,
                                base_width=self.base_width))
        return nn.Sequential(*layers)

    def forward(self, x):
        x = self.maxpool(self.relu(self.bn1(self.conv1(x))))
        x = self.layer4(self.layer3(self.layer2(self.layer1(x))))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = nn.Flatten()(x)
            x = self.fc(x)
        return x


def _resnet(block, depth, **kwargs):
    return ResNet(block, depth, **kwargs)


def resnet18(**kwargs):
    return _resnet(BasicBlock, 18, **kwargs)


def resnet34(**kwargs):
    return _resnet(BasicBlock, 34, **kwargs)


def resnet50(**kwargs):
    return _resnet(BottleneckBlock, 50, **kwargs)


def resnet101(**kwargs):
    return _resnet(BottleneckBlock, 101, **kwargs)


def resnet152(**kwargs):
    return _resnet(BottleneckBlock, 152, **kwargs)


# ResNeXt / WideResNet variants (ref: vision/models/resnet.py
# resnext50_32x4d ... wide_resnet101_2 — same trunk, grouped/widened
# bottlenecks)

def resnext50_32x4d(**kw):
    return _resnet(BottleneckBlock, 50, groups=32, width_per_group=4, **kw)


def resnext50_64x4d(**kw):
    return _resnet(BottleneckBlock, 50, groups=64, width_per_group=4, **kw)


def resnext101_32x4d(**kw):
    return _resnet(BottleneckBlock, 101, groups=32, width_per_group=4,
                   **kw)


def resnext101_64x4d(**kw):
    return _resnet(BottleneckBlock, 101, groups=64, width_per_group=4,
                   **kw)


def resnext152_32x4d(**kw):
    return _resnet(BottleneckBlock, 152, groups=32, width_per_group=4,
                   **kw)


def resnext152_64x4d(**kw):
    return _resnet(BottleneckBlock, 152, groups=64, width_per_group=4,
                   **kw)


def wide_resnet50_2(**kw):
    return _resnet(BottleneckBlock, 50, width_per_group=128, **kw)


def wide_resnet101_2(**kw):
    return _resnet(BottleneckBlock, 101, width_per_group=128, **kw)
