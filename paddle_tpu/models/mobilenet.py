"""MobileNet v1/v2/v3 (ref: python/paddle/vision/models/mobilenetv1.py,
mobilenetv2.py, mobilenetv3.py).

TPU note: depthwise convs (groups == channels) don't map to the MXU;
XLA lowers them on the VPU, which is why MobileNets bench worse per-FLOP
on TPU than ResNets — kept for API parity with the reference model zoo.
"""

from __future__ import annotations

from .. import nn
from ..nn import functional as F


def _make_divisible(v, divisor=8, min_value=None):
    if min_value is None:
        min_value = divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class ConvBNLayer(nn.Layer):
    def __init__(self, in_c, out_c, kernel, stride=1, padding=0, groups=1,
                 act="relu"):
        super().__init__()
        self.conv = nn.Conv2D(in_c, out_c, kernel, stride=stride,
                              padding=padding, groups=groups,
                              bias_attr=False)
        self.bn = nn.BatchNorm2D(out_c)
        self.act = act

    def forward(self, x):
        x = self.bn(self.conv(x))
        if self.act == "relu":
            x = F.relu(x)
        elif self.act == "relu6":
            x = F.relu6(x)
        elif self.act == "hardswish":
            x = F.hardswish(x)
        return x


class DepthwiseSeparable(nn.Layer):
    def __init__(self, in_c, mid_c, out_c, stride, scale=1.0):
        super().__init__()
        mid_c, out_c = int(mid_c * scale), int(out_c * scale)
        self.depthwise = ConvBNLayer(in_c, mid_c, 3, stride=stride,
                                     padding=1, groups=in_c)
        self.pointwise = ConvBNLayer(mid_c, out_c, 1)

    def forward(self, x):
        return self.pointwise(self.depthwise(x))


class MobileNetV1(nn.Layer):
    """ref: vision/models/mobilenetv1.py MobileNetV1(scale, num_classes)."""

    def __init__(self, scale: float = 1.0, num_classes: int = 1000,
                 with_pool: bool = True):
        super().__init__()
        self.scale = scale
        self.num_classes = num_classes
        self.with_pool = with_pool
        s = lambda c: int(c * scale)  # noqa: E731
        self.conv1 = ConvBNLayer(3, s(32), 3, stride=2, padding=1)
        cfg = [  # in, mid, out, stride
            (s(32), 32, 64, 1), (s(64), 64, 128, 2),
            (s(128), 128, 128, 1), (s(128), 128, 256, 2),
            (s(256), 256, 256, 1), (s(256), 256, 512, 2),
            (s(512), 512, 512, 1), (s(512), 512, 512, 1),
            (s(512), 512, 512, 1), (s(512), 512, 512, 1),
            (s(512), 512, 512, 1), (s(512), 512, 1024, 2),
            (s(1024), 1024, 1024, 1),
        ]
        self.blocks = nn.Sequential(*[
            DepthwiseSeparable(i, m, o, st, scale) for i, m, o, st in cfg])
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(s(1024), num_classes)

    def forward(self, x):
        x = self.blocks(self.conv1(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(nn.Flatten()(x))
        return x


class InvertedResidual(nn.Layer):
    def __init__(self, in_c, out_c, stride, expand_ratio):
        super().__init__()
        self.use_res = stride == 1 and in_c == out_c
        hidden = int(round(in_c * expand_ratio))
        layers = []
        if expand_ratio != 1:
            layers.append(ConvBNLayer(in_c, hidden, 1, act="relu6"))
        layers += [
            ConvBNLayer(hidden, hidden, 3, stride=stride, padding=1,
                        groups=hidden, act="relu6"),
            ConvBNLayer(hidden, out_c, 1, act=None),
        ]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(nn.Layer):
    """ref: vision/models/mobilenetv2.py MobileNetV2(scale, num_classes)."""

    def __init__(self, scale: float = 1.0, num_classes: int = 1000,
                 with_pool: bool = True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        cfg = [  # t (expand), c, n (repeats), s (stride)
            (1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
            (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
        in_c = _make_divisible(32 * scale)
        self.conv1 = ConvBNLayer(3, in_c, 3, stride=2, padding=1,
                                 act="relu6")
        blocks = []
        for t, c, n, s in cfg:
            out_c = _make_divisible(c * scale)
            for i in range(n):
                blocks.append(InvertedResidual(
                    in_c, out_c, s if i == 0 else 1, t))
                in_c = out_c
        self.blocks = nn.Sequential(*blocks)
        self.out_c = _make_divisible(1280 * max(1.0, scale))
        self.conv2 = ConvBNLayer(in_c, self.out_c, 1, act="relu6")
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Sequential(nn.Dropout(0.2),
                                    nn.Linear(self.out_c, num_classes))

    def forward(self, x):
        x = self.conv2(self.blocks(self.conv1(x)))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(nn.Flatten()(x))
        return x


def mobilenet_v1(scale=1.0, **kwargs):
    return MobileNetV1(scale=scale, **kwargs)


def mobilenet_v2(scale=1.0, **kwargs):
    return MobileNetV2(scale=scale, **kwargs)


# ---------------------------------------------------------------------------
# MobileNetV3 (ref: vision/models/mobilenetv3.py — inverted residuals
# with squeeze-excitation and hardswish; Small/Large configs)
# ---------------------------------------------------------------------------

class _SEModule(nn.Layer):
    """Squeeze-excitation with the MBV3 gating (relu → hardsigmoid)."""

    def __init__(self, ch, reduction=4):
        super().__init__()
        mid = _make_divisible(ch // reduction)
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(ch, mid, 1)
        self.fc2 = nn.Conv2D(mid, ch, 1)

    def forward(self, x):
        s = F.relu(self.fc1(self.pool(x)))
        return x * F.hardsigmoid(self.fc2(s))


class _InvertedResidualV3(nn.Layer):
    def __init__(self, in_c, exp_c, out_c, kernel, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and in_c == out_c
        layers = []
        if exp_c != in_c:
            layers.append(ConvBNLayer(in_c, exp_c, 1, act=act))
        layers.append(ConvBNLayer(exp_c, exp_c, kernel, stride=stride,
                                  padding=kernel // 2, groups=exp_c,
                                  act=act))
        if use_se:
            layers.append(_SEModule(exp_c))
        layers.append(ConvBNLayer(exp_c, out_c, 1, act=None))
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


# (kernel, expanded, out, SE, act, stride) — mobilenetv3.py cfg tables
_V3_LARGE = [
    (3, 16, 16, False, "relu", 1),
    (3, 64, 24, False, "relu", 2),
    (3, 72, 24, False, "relu", 1),
    (5, 72, 40, True, "relu", 2),
    (5, 120, 40, True, "relu", 1),
    (5, 120, 40, True, "relu", 1),
    (3, 240, 80, False, "hardswish", 2),
    (3, 200, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1),
    (3, 480, 112, True, "hardswish", 1),
    (3, 672, 112, True, "hardswish", 1),
    (5, 672, 160, True, "hardswish", 2),
    (5, 960, 160, True, "hardswish", 1),
    (5, 960, 160, True, "hardswish", 1),
]
_V3_SMALL = [
    (3, 16, 16, True, "relu", 2),
    (3, 72, 24, False, "relu", 2),
    (3, 88, 24, False, "relu", 1),
    (5, 96, 40, True, "hardswish", 2),
    (5, 240, 40, True, "hardswish", 1),
    (5, 240, 40, True, "hardswish", 1),
    (5, 120, 48, True, "hardswish", 1),
    (5, 144, 48, True, "hardswish", 1),
    (5, 288, 96, True, "hardswish", 2),
    (5, 576, 96, True, "hardswish", 1),
    (5, 576, 96, True, "hardswish", 1),
]


class _MobileNetV3(nn.Layer):
    def __init__(self, cfg, last_exp, hidden, scale=1.0,
                 num_classes: int = 1000):
        super().__init__()
        in_c = _make_divisible(16 * scale)
        feats = [ConvBNLayer(3, in_c, 3, stride=2, padding=1,
                             act="hardswish")]
        for k, exp, out, se, act, s in cfg:
            exp_c = _make_divisible(exp * scale)
            out_c = _make_divisible(out * scale)
            feats.append(_InvertedResidualV3(in_c, exp_c, out_c, k, s,
                                             se, act))
            in_c = out_c
        last_c = _make_divisible(last_exp * scale)
        feats.append(ConvBNLayer(in_c, last_c, 1, act="hardswish"))
        self.features = nn.Sequential(*feats)
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.head = nn.Sequential(
            nn.Linear(last_c, hidden), nn.Hardswish(),
            nn.Dropout(0.2), nn.Linear(hidden, num_classes))

    def forward(self, x):
        x = self.pool(self.features(x))
        return self.head(x.reshape(x.shape[0], -1))


class MobileNetV3Large(_MobileNetV3):
    """ref: mobilenetv3.py MobileNetV3Large(scale, num_classes)."""

    def __init__(self, scale: float = 1.0, num_classes: int = 1000):
        super().__init__(_V3_LARGE, 960, 1280, scale, num_classes)


class MobileNetV3Small(_MobileNetV3):
    """ref: mobilenetv3.py MobileNetV3Small(scale, num_classes)."""

    def __init__(self, scale: float = 1.0, num_classes: int = 1000):
        super().__init__(_V3_SMALL, 576, 1024, scale, num_classes)


def mobilenet_v3_large(scale=1.0, **kw):
    return MobileNetV3Large(scale=scale, **kw)


def mobilenet_v3_small(scale=1.0, **kw):
    return MobileNetV3Small(scale=scale, **kw)
