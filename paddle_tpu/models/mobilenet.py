"""MobileNet v1/v2/v3 (ref: python/paddle/vision/models/mobilenetv1.py,
mobilenetv2.py, mobilenetv3.py).

TPU note: depthwise convs (groups == channels) don't map to the MXU;
XLA lowers them on the VPU, which is why MobileNets bench worse per-FLOP
on TPU than ResNets — kept for API parity with the reference model zoo.
"""

from __future__ import annotations

from .. import nn
from ..nn import functional as F


def _make_divisible(v, divisor=8, min_value=None):
    if min_value is None:
        min_value = divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class ConvBNLayer(nn.Layer):
    def __init__(self, in_c, out_c, kernel, stride=1, padding=0, groups=1,
                 act="relu"):
        super().__init__()
        self.conv = nn.Conv2D(in_c, out_c, kernel, stride=stride,
                              padding=padding, groups=groups,
                              bias_attr=False)
        self.bn = nn.BatchNorm2D(out_c)
        self.act = act

    def forward(self, x):
        x = self.bn(self.conv(x))
        if self.act == "relu":
            x = F.relu(x)
        elif self.act == "relu6":
            x = F.relu6(x)
        elif self.act == "hardswish":
            x = F.hardswish(x)
        return x


class DepthwiseSeparable(nn.Layer):
    def __init__(self, in_c, mid_c, out_c, stride, scale=1.0):
        super().__init__()
        mid_c, out_c = int(mid_c * scale), int(out_c * scale)
        self.depthwise = ConvBNLayer(in_c, mid_c, 3, stride=stride,
                                     padding=1, groups=in_c)
        self.pointwise = ConvBNLayer(mid_c, out_c, 1)

    def forward(self, x):
        return self.pointwise(self.depthwise(x))


class MobileNetV1(nn.Layer):
    """ref: vision/models/mobilenetv1.py MobileNetV1(scale, num_classes)."""

    def __init__(self, scale: float = 1.0, num_classes: int = 1000,
                 with_pool: bool = True):
        super().__init__()
        self.scale = scale
        self.num_classes = num_classes
        self.with_pool = with_pool
        s = lambda c: int(c * scale)  # noqa: E731
        self.conv1 = ConvBNLayer(3, s(32), 3, stride=2, padding=1)
        cfg = [  # in, mid, out, stride
            (s(32), 32, 64, 1), (s(64), 64, 128, 2),
            (s(128), 128, 128, 1), (s(128), 128, 256, 2),
            (s(256), 256, 256, 1), (s(256), 256, 512, 2),
            (s(512), 512, 512, 1), (s(512), 512, 512, 1),
            (s(512), 512, 512, 1), (s(512), 512, 512, 1),
            (s(512), 512, 512, 1), (s(512), 512, 1024, 2),
            (s(1024), 1024, 1024, 1),
        ]
        self.blocks = nn.Sequential(*[
            DepthwiseSeparable(i, m, o, st, scale) for i, m, o, st in cfg])
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(s(1024), num_classes)

    def forward(self, x):
        x = self.blocks(self.conv1(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(nn.Flatten()(x))
        return x


class InvertedResidual(nn.Layer):
    def __init__(self, in_c, out_c, stride, expand_ratio):
        super().__init__()
        self.use_res = stride == 1 and in_c == out_c
        hidden = int(round(in_c * expand_ratio))
        layers = []
        if expand_ratio != 1:
            layers.append(ConvBNLayer(in_c, hidden, 1, act="relu6"))
        layers += [
            ConvBNLayer(hidden, hidden, 3, stride=stride, padding=1,
                        groups=hidden, act="relu6"),
            ConvBNLayer(hidden, out_c, 1, act=None),
        ]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(nn.Layer):
    """ref: vision/models/mobilenetv2.py MobileNetV2(scale, num_classes)."""

    def __init__(self, scale: float = 1.0, num_classes: int = 1000,
                 with_pool: bool = True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        cfg = [  # t (expand), c, n (repeats), s (stride)
            (1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
            (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
        in_c = _make_divisible(32 * scale)
        self.conv1 = ConvBNLayer(3, in_c, 3, stride=2, padding=1,
                                 act="relu6")
        blocks = []
        for t, c, n, s in cfg:
            out_c = _make_divisible(c * scale)
            for i in range(n):
                blocks.append(InvertedResidual(
                    in_c, out_c, s if i == 0 else 1, t))
                in_c = out_c
        self.blocks = nn.Sequential(*blocks)
        self.out_c = _make_divisible(1280 * max(1.0, scale))
        self.conv2 = ConvBNLayer(in_c, self.out_c, 1, act="relu6")
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Sequential(nn.Dropout(0.2),
                                    nn.Linear(self.out_c, num_classes))

    def forward(self, x):
        x = self.conv2(self.blocks(self.conv1(x)))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(nn.Flatten()(x))
        return x


def mobilenet_v1(scale=1.0, **kwargs):
    return MobileNetV1(scale=scale, **kwargs)


def mobilenet_v2(scale=1.0, **kwargs):
    return MobileNetV2(scale=scale, **kwargs)
