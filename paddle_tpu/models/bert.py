"""BERT / ERNIE encoder models (BASELINE config 3: ERNIE-base pretraining).

The reference ships BERT/ERNIE through its ecosystem on top of
nn.TransformerEncoder (reference: python/paddle/nn/layer/transformer.py:652)
with the fused encoder variant FusedTransformerEncoderLayer
(python/paddle/incubate/nn/layer/fused_transformer.py:641). ERNIE-base is
architecturally BERT-base (12L/768H/12A) with a different pretraining
objective; both are covered by this module — ``ernie_config`` returns the
same skeleton with ERNIE naming.

TPU-native: same logical-axis sharding story as models/gpt.py; attention
runs the Pallas flash kernel at pretraining sequence lengths.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from .. import nn
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer import Layer, LayerList


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: Optional[int] = None  # None = 4*hidden
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_dropout: float = 0.1
    attention_dropout: float = 0.1
    activation: str = "gelu"
    initializer_range: float = 0.02
    layer_norm_epsilon: float = 1e-12
    pad_token_id: int = 0
    use_flash: bool = True
    remat: bool = False        # rematerialize each encoder block
    scan_layers: bool = False  # lax.scan over the encoder stack (see
    #                            GPTConfig.scan_layers: single-lowering
    #                            depth loop + structural remat)
    # fused MLM vocab path (see ops/fused_xent.py): the pretraining
    # forward returns the transformed hidden states + tied weight +
    # decoder bias instead of [b, s, vocab] logits
    fused_loss: bool = False

    def __post_init__(self):
        if self.intermediate_size is None:
            self.intermediate_size = 4 * self.hidden_size


PRESETS = {
    "bert-base": dict(hidden_size=768, num_layers=12, num_heads=12),
    "bert-large": dict(hidden_size=1024, num_layers=24, num_heads=16),
    "ernie-base": dict(hidden_size=768, num_layers=12, num_heads=12,
                       vocab_size=18000),
    "ernie-large": dict(hidden_size=1024, num_layers=24, num_heads=16,
                        vocab_size=18000),
}


def bert_config(name: str, **overrides) -> BertConfig:
    cfg = dict(PRESETS[name])
    cfg.update(overrides)
    return BertConfig(**cfg)


ernie_config = bert_config  # ERNIE-base == BERT skeleton, ERNIE vocab


class BertEmbeddings(Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        init = I.Normal(0.0, cfg.initializer_range)
        self.word_embeddings = nn.Embedding(
            cfg.vocab_size, cfg.hidden_size, padding_idx=cfg.pad_token_id,
            weight_attr=init, axes=("vocab", "embed"))
        self.position_embeddings = nn.Embedding(
            cfg.max_position_embeddings, cfg.hidden_size, weight_attr=init,
            axes=(None, "embed"))
        self.token_type_embeddings = nn.Embedding(
            cfg.type_vocab_size, cfg.hidden_size, weight_attr=init,
            axes=(None, "embed"))
        self.layer_norm = nn.LayerNorm(cfg.hidden_size,
                                       epsilon=cfg.layer_norm_epsilon)
        self.dropout = nn.Dropout(cfg.hidden_dropout)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        s = input_ids.shape[1]
        if position_ids is None:
            position_ids = jnp.arange(s)[None, :]
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        x = (self.word_embeddings(input_ids)
             + self.position_embeddings(position_ids)
             + self.token_type_embeddings(token_type_ids))
        return self.dropout(self.layer_norm(x))


class BertEncoderLayer(Layer):
    """Post-LN encoder block (original BERT residual order)."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.attn = nn.MultiHeadAttention(
            cfg.hidden_size, cfg.num_heads, dropout=cfg.attention_dropout,
            use_flash=cfg.use_flash)
        self.ln_1 = nn.LayerNorm(cfg.hidden_size,
                                 epsilon=cfg.layer_norm_epsilon)
        self.fc_in = nn.Linear(cfg.hidden_size, cfg.intermediate_size,
                               weight_attr=I.Normal(0., cfg.initializer_range),
                               axes=("embed", "mlp"), bias_axes=("mlp",))
        self.fc_out = nn.Linear(cfg.intermediate_size, cfg.hidden_size,
                                weight_attr=I.Normal(0., cfg.initializer_range),
                                axes=("mlp", "embed"), bias_axes=(None,))
        self.ln_2 = nn.LayerNorm(cfg.hidden_size,
                                 epsilon=cfg.layer_norm_epsilon)
        self.act = getattr(F, cfg.activation)
        self.dropout = nn.Dropout(cfg.hidden_dropout)

    def forward(self, x, attn_mask=None):
        x = self.ln_1(x + self.dropout(self.attn(x, attn_mask=attn_mask)))
        h = self.fc_out(self.act(self.fc_in(x)))
        return self.ln_2(x + self.dropout(h))


class BertPooler(Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.dense = nn.Linear(cfg.hidden_size, cfg.hidden_size,
                               weight_attr=I.Normal(0.,
                                                    cfg.initializer_range))

    def forward(self, hidden):
        return F.tanh(self.dense(hidden[:, 0]))


class BertModel(Layer):
    """Trunk: embeddings → encoder stack → (sequence_output, pooled)."""

    def __init__(self, cfg: BertConfig, with_pooler: bool = True):
        super().__init__()
        self.cfg = cfg
        self.embeddings = BertEmbeddings(cfg)
        self.encoder = LayerList(
            [BertEncoderLayer(cfg) for _ in range(cfg.num_layers)])
        self.pooler = BertPooler(cfg) if with_pooler else None

    @staticmethod
    def attention_mask_from_ids(input_ids, pad_token_id: int):
        """[b, s] ids → additive [b, 1, 1, s] mask (-inf at padding)."""
        pad = (input_ids == pad_token_id)
        return jnp.where(pad, -jnp.inf, 0.0)[:, None, None, :]

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attn_mask=None):
        x = self.embeddings(input_ids, token_type_ids, position_ids)
        if self.cfg.scan_layers:
            from ..nn.utils import scan_layer_stack
            x = scan_layer_stack(list(self.encoder), x,
                                 remat=self.cfg.remat,
                                 rng_tag="bert_trunk",
                                 attn_mask=attn_mask)
        else:
            for layer in self.encoder:
                if self.cfg.remat:
                    x = jax.checkpoint(
                        lambda x, l=layer: l(x, attn_mask=attn_mask))(x)
                else:
                    x = layer(x, attn_mask=attn_mask)
        pooled = self.pooler(x) if self.pooler is not None else None
        return x, pooled


class BertLMHead(Layer):
    """MLM head: transform + LN + decode to vocab (tied to embeddings)."""

    def __init__(self, cfg: BertConfig, embeddings: BertEmbeddings):
        super().__init__()
        self.transform = nn.Linear(
            cfg.hidden_size, cfg.hidden_size,
            weight_attr=I.Normal(0., cfg.initializer_range))
        self.act = getattr(F, cfg.activation)
        self.layer_norm = nn.LayerNorm(cfg.hidden_size,
                                       epsilon=cfg.layer_norm_epsilon)
        self._embeddings = [embeddings]  # plain list: not a sublayer (tied)
        self.decoder_bias = self.create_parameter(
            [cfg.vocab_size], initializer=I.Constant(0.0), axes=("vocab",))

    def transformed(self, hidden):
        return self.layer_norm(self.act(self.transform(hidden)))

    def tied_weight(self):
        return self._embeddings[0].word_embeddings.weight  # [V, H]

    def forward(self, hidden):
        from .. import amp
        h = self.transformed(hidden)
        w = self.tied_weight()
        h, w = amp.white_cast(h, w)
        return jnp.einsum("bsh,vh->bsv", h, w,
                          preferred_element_type=jnp.float32) \
            + self.decoder_bias


class BertForPretraining(Layer):
    """MLM + next-sentence-prediction heads (BERT objective; ERNIE uses
    the same skeleton with knowledge-masking data — a data-pipeline
    difference, not a model one)."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.cfg = cfg
        self.bert = BertModel(cfg, with_pooler=True)
        self.lm_head = BertLMHead(cfg, self.bert.embeddings)
        self.nsp_head = nn.Linear(cfg.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None, attn_mask=None):
        seq, pooled = self.bert(input_ids, token_type_ids,
                                attn_mask=attn_mask)
        if self.cfg.fused_loss and self.training:
            return (self.lm_head.transformed(seq),
                    self.lm_head.tied_weight(),
                    self.lm_head.decoder_bias,
                    self.nsp_head(pooled))
        return self.lm_head(seq), self.nsp_head(pooled)


class BertPretrainingCriterion(Layer):
    def __init__(self, ignore_index: int = -100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, mlm_logits, nsp_logits, mlm_labels, nsp_labels=None):
        loss = F.cross_entropy(
            mlm_logits.reshape(-1, mlm_logits.shape[-1]),
            mlm_labels.reshape(-1), ignore_index=self.ignore_index)
        if nsp_labels is not None:
            loss = loss + F.cross_entropy(nsp_logits,
                                          nsp_labels.reshape(-1))
        return loss


class BertFusedPretrainingCriterion(Layer):
    """Streaming MLM loss for cfg.fused_loss=True models: consumes
    (hidden, tied weight, decoder bias, nsp_logits) and never builds
    the [b, s, vocab] logits (ops/fused_xent.py). Falls back to the
    dense criterion signature in eval."""

    def __init__(self, ignore_index: int = -100):
        super().__init__()
        self.ignore_index = ignore_index
        self._dense = BertPretrainingCriterion(ignore_index)

    def forward(self, *args):
        # training: (hidden, weight, bias, nsp_logits, mlm_labels
        #            [, nsp_labels]); eval: the dense criterion arity.
        # NOTE: hapi metrics attached via Model.prepare would see the
        # hidden states during fused training — compute accuracy-style
        # metrics in eval (dense logits) instead.
        if len(args) >= 5:
            hidden, weight, bias, nsp_logits, mlm_labels = args[:5]
            nsp_labels = args[5] if len(args) > 5 else None
            from .. import amp
            from ..ops.fused_xent import fused_linear_cross_entropy
            hidden, weight = amp.white_cast(hidden, weight, op="matmul")
            h = hidden.reshape(-1, hidden.shape[-1])
            loss = fused_linear_cross_entropy(
                h, weight, mlm_labels.reshape(-1), self.ignore_index,
                None, bias)
            if nsp_labels is not None:
                loss = loss + F.cross_entropy(nsp_logits,
                                              nsp_labels.reshape(-1))
            return loss
        # eval mode: dense logits path
        return self._dense(*args)


class BertForSequenceClassification(Layer):
    def __init__(self, cfg: BertConfig, num_classes: int = 2):
        super().__init__()
        self.bert = BertModel(cfg, with_pooler=True)
        self.dropout = nn.Dropout(cfg.hidden_dropout)
        self.classifier = nn.Linear(cfg.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, attn_mask=None):
        _, pooled = self.bert(input_ids, token_type_ids,
                              attn_mask=attn_mask)
        return self.classifier(self.dropout(pooled))
