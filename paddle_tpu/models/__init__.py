"""Model zoo (ref: python/paddle/vision/models, ERNIE/GPT from the
reference's fleet examples). Populated incrementally."""

from .lenet import LeNet  # noqa
