"""Model zoo (ref: python/paddle/vision/models, ERNIE/GPT from the
reference's fleet examples). Populated incrementally."""

from .bert import (BertConfig, BertForPretraining,  # noqa
                   BertForSequenceClassification, BertModel,
                   BertPretrainingCriterion, bert_config, ernie_config)
from .gpt import (GPTConfig, GPTForCausalLM, GPTModel,  # noqa
                  GPTPretrainingCriterion, gpt_config)
from .lenet import LeNet  # noqa
from .mobilenet import (MobileNetV1, MobileNetV2,  # noqa
                        MobileNetV3Large, MobileNetV3Small,
                        mobilenet_v1, mobilenet_v2,
                        mobilenet_v3_large, mobilenet_v3_small)
from .resnet import (BasicBlock, BottleneckBlock, ResNet,  # noqa
                     resnet18, resnet34, resnet50, resnet101, resnet152,
                     resnext50_32x4d, resnext50_64x4d, resnext101_32x4d,
                     resnext101_64x4d, resnext152_32x4d,
                     resnext152_64x4d, wide_resnet50_2, wide_resnet101_2)
from .vgg import VGG, vgg11, vgg13, vgg16, vgg19  # noqa
from .vision_extra import (AlexNet, DenseNet, GoogLeNet,  # noqa
                           InceptionV3, ShuffleNetV2, SqueezeNet,
                           alexnet,
                           densenet121, densenet161, densenet169,
                           densenet201, densenet264,
                           googlenet, inception_v3,
                           shufflenet_v2_x0_25, shufflenet_v2_x0_33,
                           shufflenet_v2_x0_5, shufflenet_v2_x1_0,
                           shufflenet_v2_x1_5, shufflenet_v2_x2_0,
                           shufflenet_v2_swish,
                           squeezenet1_0, squeezenet1_1)
from .widedeep import DeepFM, WideDeep, synthetic_criteo  # noqa
from .convert import (bert_from_huggingface,  # noqa
                      gpt2_from_huggingface,
                      llama_from_huggingface)
