"""Model zoo (ref: python/paddle/vision/models, ERNIE/GPT from the
reference's fleet examples). Populated incrementally."""

from .bert import (BertConfig, BertForPretraining,  # noqa
                   BertForSequenceClassification, BertModel,
                   BertPretrainingCriterion, bert_config, ernie_config)
from .gpt import (GPTConfig, GPTForCausalLM, GPTModel,  # noqa
                  GPTPretrainingCriterion, gpt_config)
from .lenet import LeNet  # noqa
from .mobilenet import (MobileNetV1, MobileNetV2,  # noqa
                        mobilenet_v1, mobilenet_v2)
from .resnet import (BasicBlock, BottleneckBlock, ResNet,  # noqa
                     resnet18, resnet34, resnet50, resnet101, resnet152)
from .vgg import VGG, vgg11, vgg13, vgg16, vgg19  # noqa
from .vision_extra import (AlexNet, DenseNet, GoogLeNet,  # noqa
                           ShuffleNetV2, SqueezeNet, alexnet,
                           densenet121, densenet161, densenet201,
                           googlenet,
                           shufflenet_v2_x0_5, shufflenet_v2_x1_0,
                           squeezenet1_0, squeezenet1_1)
from .widedeep import DeepFM, WideDeep, synthetic_criteo  # noqa
