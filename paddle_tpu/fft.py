"""paddle_tpu.fft — spectral API (ref: python/paddle/fft.py over
pocketfft C++ kernels, cmake/external/pocketfft.cmake +
phi/kernels/funcs/fft*). On TPU, FFTs lower through XLA's FFT HLO —
no external library."""

from __future__ import annotations

import jax.numpy as jnp

fft = jnp.fft.fft
ifft = jnp.fft.ifft
fft2 = jnp.fft.fft2
ifft2 = jnp.fft.ifft2
fftn = jnp.fft.fftn
ifftn = jnp.fft.ifftn
rfft = jnp.fft.rfft
irfft = jnp.fft.irfft
rfft2 = jnp.fft.rfft2
irfft2 = jnp.fft.irfft2
rfftn = jnp.fft.rfftn
irfftn = jnp.fft.irfftn
hfft = jnp.fft.hfft
ihfft = jnp.fft.ihfft
fftfreq = jnp.fft.fftfreq
rfftfreq = jnp.fft.rfftfreq
fftshift = jnp.fft.fftshift
ifftshift = jnp.fft.ifftshift
