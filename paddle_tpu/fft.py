"""paddle_tpu.fft — spectral API (ref: python/paddle/fft.py over
pocketfft C++ kernels, cmake/external/pocketfft.cmake +
phi/kernels/funcs/fft*). On TPU, FFTs lower through XLA's FFT HLO —
no external library."""

from __future__ import annotations

import jax.numpy as jnp

fft = jnp.fft.fft
ifft = jnp.fft.ifft
fft2 = jnp.fft.fft2
ifft2 = jnp.fft.ifft2
fftn = jnp.fft.fftn
ifftn = jnp.fft.ifftn
rfft = jnp.fft.rfft
irfft = jnp.fft.irfft
rfft2 = jnp.fft.rfft2
irfft2 = jnp.fft.irfft2
rfftn = jnp.fft.rfftn
irfftn = jnp.fft.irfftn
hfft = jnp.fft.hfft
ihfft = jnp.fft.ihfft
fftfreq = jnp.fft.fftfreq
rfftfreq = jnp.fft.rfftfreq
fftshift = jnp.fft.fftshift
ifftshift = jnp.fft.ifftshift


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    """2-D Hermitian FFT (ref: paddle fft.py hfft2 — hfftn over the
    last two axes)."""
    return hfftn(x, s=s, axes=axes, norm=norm)


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return ihfftn(x, s=s, axes=axes, norm=norm)


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    """N-D Hermitian FFT: complex-to-real with Hermitian-even input —
    FORWARD FFT over the leading axes + hfft on the last, all under the
    same norm (the reference composes fft_c2c forward + c2r the same
    way, fft.py hfftn; verified against torch.fft.hfftn on every
    norm)."""
    x = jnp.asarray(x)
    if axes is None:  # numpy/reference default: last len(s) axes
        axes = tuple(range(x.ndim - (len(s) if s is not None
                                     else x.ndim), x.ndim))
    axes = tuple(a % x.ndim for a in axes)
    lead, last = axes[:-1], axes[-1]
    if lead:
        lead_s = None if s is None else s[:-1]
        x = jnp.fft.fftn(x, s=lead_s, axes=lead, norm=norm)
    n_last = None if s is None else s[-1]
    return jnp.fft.hfft(x, n=n_last, axis=last, norm=norm)


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    """Inverse of :func:`hfftn`: ihfft on the last axis + INVERSE FFT
    over the leading axes, same norm throughout."""
    x = jnp.asarray(x)
    if axes is None:  # numpy/reference default: last len(s) axes
        axes = tuple(range(x.ndim - (len(s) if s is not None
                                     else x.ndim), x.ndim))
    axes = tuple(a % x.ndim for a in axes)
    lead, last = axes[:-1], axes[-1]
    n_last = None if s is None else s[-1]
    out = jnp.fft.ihfft(x, n=n_last, axis=last, norm=norm)
    if lead:
        lead_s = None if s is None else s[:-1]
        out = jnp.fft.ifftn(out, s=lead_s, axes=lead, norm=norm)
    return out
