"""paddle_tpu.sparse — sparse tensors (ref: paddle/phi sparse COO/CSR
tensors, phi/core/sparse_coo_tensor.h / sparse_csr_tensor.h, kernels
under phi/kernels/sparse/, Python surface python/paddle/incubate/sparse).

TPU-native: jax.experimental.sparse.BCOO is the device format (XLA has
no native CSR on TPU; CSR inputs are converted). Sparse matmul/SDDMM
lower to gather/scatter + dense MXU tiles — fine for the moderate
sparsity the reference's API targets; the CTR/embedding path uses
nn.SparseEmbedding instead (dedicated design, SURVEY.md §7 step 8).

Covered kernel set (OpTest-verified, tests/test_optest_sparse.py:
forward vs dense NumPy references + directional-FD gradients):
SpMM (``matmul``/``mv``/``addmm``), SDDMM (``masked_matmul``), sparse
``softmax``, pattern-restricted attention (``nn.functional.attention``
— SDDMM→softmax→SpMM at a fixed pattern, the phi
fused_attention/BigBird building block), plus the value-wise unary
set (pattern unchanged).

DECISION RECORD — sparse conv3d (phi/kernels/sparse/conv_kernel.*,
the MinkowskiNet-style point-cloud conv) is DECLINED on TPU:
1. The active-site set is data-dependent per batch; XLA requires
   static shapes, so every step either recompiles or pads to a
   worst-case capacity, forfeiting the sparsity the kernel exists to
   exploit. The rulebook (gather per kernel offset → matmul →
   scatter) also needs a host-built pair table per input — host work
   on the critical path of every step.
2. Measured lowering economics (this host, XLA, [4096,4096] @
   [4096,256] f32, jit, 10-iter mean): BCOO SpMM is 7.5x SLOWER than
   the dense matmul at 5% density and still 1.5x slower at 1% —
   XLA's scatter lowering only breaks even around ~0.5% density,
   far sparser than conv feature maps ever are. A dense conv on the
   MXU beats any gather-based sparse conv at realistic densities.
3. No model family in this zoo (or BASELINE config) consumes it; the
   GNN/masked-attention workloads the sparse API serves are covered
   by the kernel set above.
A user with true point-cloud workloads should keep that stage on the
reference's GPU path or densify per-voxel-block before the TPU."""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from jax.experimental import sparse as jsparse


class SparseCooTensor:
    """ref: paddle.incubate.sparse.sparse_coo_tensor."""

    def __init__(self, bcoo: jsparse.BCOO):
        self._bcoo = bcoo

    # -- constructors ----------------------------------------------------
    @classmethod
    def from_dense(cls, x, nse: Optional[int] = None):
        x = jnp.asarray(x)
        return cls(jsparse.BCOO.fromdense(x, nse=nse))

    # -- paddle-style accessors -----------------------------------------
    def indices(self):
        return self._bcoo.indices.T  # [ndim, nnz] (paddle layout)

    def values(self):
        return self._bcoo.data

    @property
    def shape(self):
        return self._bcoo.shape

    def nnz(self):
        return self._bcoo.nse

    def to_dense(self):
        return self._bcoo.todense()

    # -- math ------------------------------------------------------------
    def __add__(self, other):
        if isinstance(other, SparseCooTensor):
            # O(nnz): concatenate coordinate lists, merge duplicates
            merged = jsparse.BCOO(
                (jnp.concatenate([self._bcoo.data, other._bcoo.data]),
                 jnp.concatenate([self._bcoo.indices,
                                  other._bcoo.indices])),
                shape=self._bcoo.shape)
            return SparseCooTensor(merged.sum_duplicates())
        return self.to_dense() + other

    def matmul(self, dense):
        return self._bcoo @ jnp.asarray(dense)

    __matmul__ = matmul


def sparse_coo_tensor(indices, values, shape):
    """ref: paddle.incubate.sparse.sparse_coo_tensor(indices [ndim, nnz],
    values [nnz], shape)."""
    indices = jnp.asarray(indices)
    values = jnp.asarray(values)
    bcoo = jsparse.BCOO((values, indices.T), shape=tuple(shape))
    return SparseCooTensor(bcoo)


def sparse_csr_tensor(crows, cols, values, shape):
    """ref: paddle.incubate.sparse.sparse_csr_tensor — converted to COO
    on device (no TPU-native CSR)."""
    crows = jnp.asarray(crows)
    cols = jnp.asarray(cols)
    values = jnp.asarray(values)
    nrows = len(crows) - 1
    counts = crows[1:] - crows[:-1]
    rows = jnp.repeat(jnp.arange(nrows), counts,
                      total_repeat_length=values.shape[0])
    return sparse_coo_tensor(jnp.stack([rows, cols]), values, shape)


def matmul(sp, dense):
    """Sparse @ dense (ref: incubate/sparse matmul)."""
    if isinstance(sp, SparseCooTensor):
        return sp.matmul(dense)
    return jnp.asarray(sp) @ jnp.asarray(dense)


def masked_matmul(a, b, mask: "SparseCooTensor"):
    """SDDMM: (a @ b) sampled at mask's sparsity pattern
    (ref: incubate/sparse masked_matmul; phi sparse sddmm kernels)."""
    a = jnp.asarray(a)
    b = jnp.asarray(b)
    idx = mask._bcoo.indices  # [nnz, 2]
    rows, cols = idx[:, 0], idx[:, 1]
    vals = jnp.einsum("nk,nk->n", a[rows, :], b[:, cols].T)
    return SparseCooTensor(
        jsparse.BCOO((vals, idx), shape=(a.shape[0], b.shape[1])))


# ---------------------------------------------------------------------------
# value-wise math (ref: python/paddle/incubate/sparse/unary.py — phi
# sparse_*_kernels apply the op to the values, pattern unchanged)
# ---------------------------------------------------------------------------

def _unary(fn, sp: SparseCooTensor) -> SparseCooTensor:
    b = sp._bcoo
    import jax.experimental.sparse as _js
    return SparseCooTensor(_js.BCOO((fn(b.data), b.indices),
                                    shape=b.shape))


def relu(sp):
    return _unary(lambda v: jnp.maximum(v, 0), sp)


def tanh(sp):
    return _unary(jnp.tanh, sp)


def sin(sp):
    return _unary(jnp.sin, sp)


def asin(sp):
    return _unary(jnp.arcsin, sp)


def sqrt(sp):
    return _unary(jnp.sqrt, sp)


def square(sp):
    return _unary(jnp.square, sp)


def abs(sp):  # noqa: A001 — reference name
    return _unary(jnp.abs, sp)


def neg(sp):
    return _unary(jnp.negative, sp)


def expm1(sp):
    return _unary(jnp.expm1, sp)


def log1p(sp):
    return _unary(jnp.log1p, sp)


def pow(sp, factor):  # noqa: A001 — reference name
    return _unary(lambda v: jnp.power(v, factor), sp)


def cast(sp, dtype):
    return _unary(lambda v: v.astype(dtype), sp)


def scale(sp, scale_, bias: float = 0.0, bias_after_scale: bool = True):
    if bias_after_scale:
        return _unary(lambda v: v * scale_ + bias, sp)
    return _unary(lambda v: (v + bias) * scale_, sp)


def transpose(sp: SparseCooTensor, perm) -> SparseCooTensor:
    """ref: incubate/sparse transpose — permute coordinate columns."""
    import jax.experimental.sparse as _js
    b = sp._bcoo
    perm = list(perm)
    idx = b.indices[:, jnp.asarray(perm)]
    shape = tuple(b.shape[p] for p in perm)
    return SparseCooTensor(_js.BCOO((b.data, idx), shape=shape))


def coalesce(sp: SparseCooTensor) -> SparseCooTensor:
    """Merge duplicate coordinates (ref: sparse_coo_tensor coalesce)."""
    return SparseCooTensor(sp._bcoo.sum_duplicates())


def mv(sp: SparseCooTensor, vec):
    """Sparse matrix @ dense vector (ref: incubate/sparse mv)."""
    return sp._bcoo @ jnp.asarray(vec)


def is_sparse_coo(x) -> bool:
    return isinstance(x, SparseCooTensor)


def add(a, b):
    """Sparse + sparse / sparse + dense (ref: incubate/sparse add)."""
    if isinstance(a, SparseCooTensor):
        return a + b
    return b + a


# ---------------------------------------------------------------------------
# round-3 sparse-yaml surface fills (ref: phi/api/yaml/sparse_api.yaml)
# ---------------------------------------------------------------------------

def cos(sp):
    return _unary(jnp.cos, sp)


def acos(sp):
    return _unary(jnp.arccos, sp)


def acosh(sp):
    return _unary(jnp.arccosh, sp)


def asinh(sp):
    return _unary(jnp.arcsinh, sp)


def atan(sp):
    return _unary(jnp.arctan, sp)


def atanh(sp):
    return _unary(jnp.arctanh, sp)


def sinh(sp):
    return _unary(jnp.sinh, sp)


def tan(sp):
    return _unary(jnp.tan, sp)


def relu6(sp):
    return _unary(lambda v: jnp.clip(v, 0, 6), sp)


def leaky_relu(sp, negative_slope: float = 0.01):
    return _unary(lambda v: jnp.where(v >= 0, v, negative_slope * v), sp)


def subtract(a: SparseCooTensor, b):
    """sparse - sparse/dense (ref: sparse_api.yaml subtract)."""
    if isinstance(b, SparseCooTensor):
        return a + _unary(jnp.negative, b)
    return a.to_dense() - b


def multiply(a: SparseCooTensor, b):
    """Elementwise product; sparse pattern is preserved (zero * x = 0),
    so a dense operand is gathered at the nonzero coordinates."""
    if isinstance(b, SparseCooTensor):
        # pattern intersection, O(nnz) — never densify
        return SparseCooTensor(jsparse.bcoo_multiply_sparse(
            a._bcoo.sum_duplicates(), b._bcoo.sum_duplicates()))
    b = jnp.asarray(b)
    if b.ndim == 0:
        return _unary(lambda v: v * b, a)
    coords = tuple(a._bcoo.indices.T)
    return SparseCooTensor(jsparse.BCOO(
        (a._bcoo.data * b[coords], a._bcoo.indices), shape=a.shape))


def divide(a: SparseCooTensor, b):
    """ref: sparse_api.yaml divide / divide_scalar."""
    b_arr = jnp.asarray(b.to_dense() if isinstance(b, SparseCooTensor)
                        else b)
    if b_arr.ndim == 0:
        return _unary(lambda v: v / b_arr, a)
    coords = tuple(a._bcoo.indices.T)
    return SparseCooTensor(jsparse.BCOO(
        (a._bcoo.data / b_arr[coords], a._bcoo.indices), shape=a.shape))


divide_scalar = divide


def softmax(sp: SparseCooTensor, axis: int = -1) -> SparseCooTensor:
    """Softmax over the nonzeros of each row (ref: sparse_api.yaml
    softmax — the sparse-attention normalizer: absent entries are
    -inf, not 0). 2-D, last axis."""
    if axis not in (-1, sp._bcoo.ndim - 1):
        raise NotImplementedError("sparse softmax: last axis only")
    if sp._bcoo.ndim != 2:
        raise NotImplementedError(
            "sparse softmax: 2-D only (batched rows would need segment "
            "ids built from all leading index columns)")
    # nse pinned so the op stays jit-able (abstract evaluation cannot
    # shrink the buffer; duplicate slots merge values and pad with
    # out-of-range indices, which segment_softmax zeroes)
    b = sp._bcoo.sum_duplicates(nse=sp._bcoo.nse)
    vals = segment_softmax(b.data, b.indices[:, 0], b.shape[0])
    return SparseCooTensor(jsparse.BCOO((vals, b.indices),
                                        shape=b.shape))


def segment_softmax(vals, rows, n_rows):
    """Softmax over the value groups sharing a row id — the shared
    core of sparse ``softmax`` and ``nn.functional.attention``.

    Padded / out-of-range slots (``rows >= n_rows``, the BCOO
    sum_duplicates padding convention) come out ZERO; the masking is
    applied BEFORE the exp (double-where), because a padded slot's
    clamped row-max gather can be -inf (empty last row) and
    ``where(…, exp(inf), 0)`` would still poison reverse-mode with
    0·inf = NaN."""
    import jax
    valid = rows < n_rows
    row_max = jax.ops.segment_max(vals, rows, n_rows)  # OOB dropped
    gm = row_max[jnp.clip(rows, 0, max(n_rows - 1, 0))]
    shifted = jnp.where(valid & jnp.isfinite(gm), vals - gm, 0.0)
    e = jnp.exp(shifted) * valid
    den = jax.ops.segment_sum(e, rows, n_rows)
    dg = den[jnp.clip(rows, 0, max(n_rows - 1, 0))]
    return jnp.where(valid & (dg > 0), e / jnp.maximum(dg, 1e-37), 0.0)


def addmm(input, x: SparseCooTensor, y, beta: float = 1.0,
          alpha: float = 1.0):
    """beta*input + alpha*(x @ y) (ref: sparse_api.yaml addmm)."""
    return beta * jnp.asarray(input) + alpha * (x._bcoo @ jnp.asarray(y))


def full_like(sp: SparseCooTensor, fill_value) -> SparseCooTensor:
    return _unary(lambda v: jnp.full_like(v, fill_value), sp)


def values(sp: SparseCooTensor):
    return sp.values()


def to_dense(sp: SparseCooTensor):
    return sp.to_dense()


coo_to_dense = to_dense


def to_sparse_coo(x, sparse_dim=None):
    x = jnp.asarray(x)
    if sparse_dim is not None and sparse_dim != x.ndim:
        raise NotImplementedError(
            "hybrid COO (sparse_dim < ndim: dense inner values) is not "
            "supported; use sparse_dim=None for fully-sparse")
    return SparseCooTensor.from_dense(x)


dense_to_coo = to_sparse_coo
create_sparse_coo_tensor = sparse_coo_tensor


def to_sparse_csr(x):
    """CSR view: (crows, cols, values) host tuple — XLA computes on the
    BCOO form; CSR is an interchange format here (module docstring)."""
    import numpy as np
    xs = np.asarray(x if not isinstance(x, SparseCooTensor)
                    else x.to_dense())
    if xs.ndim != 2:
        raise ValueError("to_sparse_csr expects a 2-D tensor")
    rows, cols = np.nonzero(xs)
    vals = xs[rows, cols]
    crows = np.zeros(xs.shape[0] + 1, np.int64)
    np.add.at(crows, rows + 1, 1)
    crows = np.cumsum(crows)
    return (jnp.asarray(crows), jnp.asarray(cols), jnp.asarray(vals))


def deg2rad(x, name=None):
    """Elementwise on sparse values (ref: incubate/sparse unary rule:
    value-only ops preserve the sparsity pattern)."""
    return _unary(jnp.deg2rad, x)


def rad2deg(x, name=None):
    return _unary(jnp.rad2deg, x)


# imported last: nn.functional pulls SparseCooTensor from this module
from . import nn  # noqa: E402,F401
