"""paddle_tpu.sparse — sparse tensors (ref: paddle/phi sparse COO/CSR
tensors, phi/core/sparse_coo_tensor.h / sparse_csr_tensor.h, kernels
under phi/kernels/sparse/, Python surface python/paddle/incubate/sparse).

TPU-native: jax.experimental.sparse.BCOO is the device format (XLA has
no native CSR on TPU; CSR inputs are converted). Sparse matmul/SDDMM
lower to gather/scatter + dense MXU tiles — fine for the moderate
sparsity the reference's API targets; the CTR/embedding path uses
nn.SparseEmbedding instead (dedicated design, SURVEY.md §7 step 8)."""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from jax.experimental import sparse as jsparse


class SparseCooTensor:
    """ref: paddle.incubate.sparse.sparse_coo_tensor."""

    def __init__(self, bcoo: jsparse.BCOO):
        self._bcoo = bcoo

    # -- constructors ----------------------------------------------------
    @classmethod
    def from_dense(cls, x, nse: Optional[int] = None):
        x = jnp.asarray(x)
        return cls(jsparse.BCOO.fromdense(x, nse=nse))

    # -- paddle-style accessors -----------------------------------------
    def indices(self):
        return self._bcoo.indices.T  # [ndim, nnz] (paddle layout)

    def values(self):
        return self._bcoo.data

    @property
    def shape(self):
        return self._bcoo.shape

    def nnz(self):
        return self._bcoo.nse

    def to_dense(self):
        return self._bcoo.todense()

    # -- math ------------------------------------------------------------
    def __add__(self, other):
        if isinstance(other, SparseCooTensor):
            # O(nnz): concatenate coordinate lists, merge duplicates
            merged = jsparse.BCOO(
                (jnp.concatenate([self._bcoo.data, other._bcoo.data]),
                 jnp.concatenate([self._bcoo.indices,
                                  other._bcoo.indices])),
                shape=self._bcoo.shape)
            return SparseCooTensor(merged.sum_duplicates())
        return self.to_dense() + other

    def matmul(self, dense):
        return self._bcoo @ jnp.asarray(dense)

    __matmul__ = matmul


def sparse_coo_tensor(indices, values, shape):
    """ref: paddle.incubate.sparse.sparse_coo_tensor(indices [ndim, nnz],
    values [nnz], shape)."""
    indices = jnp.asarray(indices)
    values = jnp.asarray(values)
    bcoo = jsparse.BCOO((values, indices.T), shape=tuple(shape))
    return SparseCooTensor(bcoo)


def sparse_csr_tensor(crows, cols, values, shape):
    """ref: paddle.incubate.sparse.sparse_csr_tensor — converted to COO
    on device (no TPU-native CSR)."""
    crows = jnp.asarray(crows)
    cols = jnp.asarray(cols)
    values = jnp.asarray(values)
    nrows = len(crows) - 1
    counts = crows[1:] - crows[:-1]
    rows = jnp.repeat(jnp.arange(nrows), counts,
                      total_repeat_length=values.shape[0])
    return sparse_coo_tensor(jnp.stack([rows, cols]), values, shape)


def matmul(sp, dense):
    """Sparse @ dense (ref: incubate/sparse matmul)."""
    if isinstance(sp, SparseCooTensor):
        return sp.matmul(dense)
    return jnp.asarray(sp) @ jnp.asarray(dense)


def masked_matmul(a, b, mask: "SparseCooTensor"):
    """SDDMM: (a @ b) sampled at mask's sparsity pattern
    (ref: incubate/sparse masked_matmul; phi sparse sddmm kernels)."""
    a = jnp.asarray(a)
    b = jnp.asarray(b)
    idx = mask._bcoo.indices  # [nnz, 2]
    rows, cols = idx[:, 0], idx[:, 1]
    vals = jnp.einsum("nk,nk->n", a[rows, :], b[:, cols].T)
    return SparseCooTensor(
        jsparse.BCOO((vals, idx), shape=(a.shape[0], b.shape[1])))


# ---------------------------------------------------------------------------
# value-wise math (ref: python/paddle/incubate/sparse/unary.py — phi
# sparse_*_kernels apply the op to the values, pattern unchanged)
# ---------------------------------------------------------------------------

def _unary(fn, sp: SparseCooTensor) -> SparseCooTensor:
    b = sp._bcoo
    import jax.experimental.sparse as _js
    return SparseCooTensor(_js.BCOO((fn(b.data), b.indices),
                                    shape=b.shape))


def relu(sp):
    return _unary(lambda v: jnp.maximum(v, 0), sp)


def tanh(sp):
    return _unary(jnp.tanh, sp)


def sin(sp):
    return _unary(jnp.sin, sp)


def asin(sp):
    return _unary(jnp.arcsin, sp)


def sqrt(sp):
    return _unary(jnp.sqrt, sp)


def square(sp):
    return _unary(jnp.square, sp)


def abs(sp):  # noqa: A001 — reference name
    return _unary(jnp.abs, sp)


def neg(sp):
    return _unary(jnp.negative, sp)


def expm1(sp):
    return _unary(jnp.expm1, sp)


def log1p(sp):
    return _unary(jnp.log1p, sp)


def pow(sp, factor):  # noqa: A001 — reference name
    return _unary(lambda v: jnp.power(v, factor), sp)


def cast(sp, dtype):
    return _unary(lambda v: v.astype(dtype), sp)


def scale(sp, scale_, bias: float = 0.0, bias_after_scale: bool = True):
    if bias_after_scale:
        return _unary(lambda v: v * scale_ + bias, sp)
    return _unary(lambda v: (v + bias) * scale_, sp)


def transpose(sp: SparseCooTensor, perm) -> SparseCooTensor:
    """ref: incubate/sparse transpose — permute coordinate columns."""
    import jax.experimental.sparse as _js
    b = sp._bcoo
    perm = list(perm)
    idx = b.indices[:, jnp.asarray(perm)]
    shape = tuple(b.shape[p] for p in perm)
    return SparseCooTensor(_js.BCOO((b.data, idx), shape=shape))


def coalesce(sp: SparseCooTensor) -> SparseCooTensor:
    """Merge duplicate coordinates (ref: sparse_coo_tensor coalesce)."""
    return SparseCooTensor(sp._bcoo.sum_duplicates())


def mv(sp: SparseCooTensor, vec):
    """Sparse matrix @ dense vector (ref: incubate/sparse mv)."""
    return sp._bcoo @ jnp.asarray(vec)


def is_sparse_coo(x) -> bool:
    return isinstance(x, SparseCooTensor)


def add(a, b):
    """Sparse + sparse / sparse + dense (ref: incubate/sparse add)."""
    if isinstance(a, SparseCooTensor):
        return a + b
    return b + a
