"""paddle_tpu.sparse.nn (ref: python/paddle/incubate/sparse/nn)."""

from . import functional  # noqa: F401
