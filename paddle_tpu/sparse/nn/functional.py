"""Sparse-pattern attention (ref: python/paddle/incubate/sparse/nn/
functional/transformer.py ``attention`` over the phi sparse
fused_attention kernels — SDDMM → sparse softmax → SpMM at a fixed
sparsity pattern, the BigBird/sliding-window building block).

TPU-native formulation: the pattern's (rows, cols) coordinate lists
drive gathers and segment reductions — every shape is static in nnz,
so the whole pipeline jits and differentiates as ordinary dense ops on
the value vectors. The MXU sees [nnz, d]-shaped contractions; at the
moderate densities sparse attention targets (w·s nonzeros per head vs
s² dense) the gather overhead is paid back s/w times over.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from .. import SparseCooTensor


def attention(query, key, value, sparse_mask: SparseCooTensor,
              scaling: Optional[float] = None):
    """Attention restricted to ``sparse_mask``'s nonzero pattern.

    query/key/value: ``[batch, heads, seq, head_dim]``;
    ``sparse_mask``: a 2-D ``[seq, seq]`` SparseCooTensor whose
    PATTERN selects the attendable (q_pos, k_pos) pairs, shared across
    batch and heads (the reference passes one CSR per batch·head; the
    shared-pattern form covers the sliding-window/global-token
    patterns those are built from, without materializing b·h copies).
    Returns ``[batch, heads, seq, head_dim]``. Rows with no admitted
    key return zeros (matching the ring/dense fully-masked handling).
    """
    b, h, s, d = query.shape
    sp = sparse_mask._bcoo.sum_duplicates(nse=sparse_mask._bcoo.nse)
    if sp.shape != (s, s):
        raise ValueError(
            f"sparse_mask shape {sp.shape} != [seq, seq] = {(s, s)}")
    rows, cols = sp.indices[:, 0], sp.indices[:, 1]
    scale = scaling if scaling is not None else 1.0 / math.sqrt(d)

    q = query.reshape(b * h, s, d)
    k = key.reshape(b * h, s, d)
    v = value.reshape(b * h, s, d)
    # SDDMM: logits only at the pattern's coordinates
    logits = jnp.einsum("bnd,bnd->bn", q[:, rows, :],
                        k[:, cols, :]) * scale       # [bh, nnz]

    from .. import segment_softmax
    p = jax.vmap(lambda lv: segment_softmax(lv, rows, s))(
        logits)                                      # [bh, nnz]
    out = jax.vmap(
        lambda pv, vg: jax.ops.segment_sum(pv[:, None] * vg, rows, s))(
            p, v[:, cols, :])                        # [bh, s, d]
    return out.reshape(b, h, s, d)
