"""paddle_tpu.linalg — dense linear algebra (ref: python/paddle/tensor/
linalg.py exported as ``paddle.linalg``; kernels phi/kernels/*_kernel.cc
wrapping cuSOLVER/LAPACK).

TPU-native: XLA owns the factorizations (QR/SVD/eigh lower to
Householder/Jacobi routines the TPU backend implements; CPU uses
LAPACK). These wrappers exist for name/signature parity — the math is
``jnp.linalg``. Ops with no TPU lowering (nonsymmetric ``eig``) run via
jax's CPU callback path, matching the reference's CPU-only kernels."""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

# direct re-exports where paddle's signature == numpy's
cholesky = jnp.linalg.cholesky
det = jnp.linalg.det
slogdet = jnp.linalg.slogdet
inv = jnp.linalg.inv
pinv = jnp.linalg.pinv
matrix_power = jnp.linalg.matrix_power
matrix_rank = jnp.linalg.matrix_rank
multi_dot = jnp.linalg.multi_dot
qr = jnp.linalg.qr
svd = jnp.linalg.svd
svdvals = jnp.linalg.svdvals
eig = jnp.linalg.eig
eigvals = jnp.linalg.eigvals
eigh = jnp.linalg.eigh
eigvalsh = jnp.linalg.eigvalsh
solve = jnp.linalg.solve
lstsq = jnp.linalg.lstsq
cond = jnp.linalg.cond
norm = jnp.linalg.norm
cov = jnp.cov
corrcoef = jnp.corrcoef


def cholesky_solve(b, l, upper: bool = False):  # noqa: E741
    """Solve A x = b given A's Cholesky factor (ref: linalg.py
    cholesky_solve; phi cholesky_solve_kernel)."""
    y = lax.linalg.triangular_solve(l, b, left_side=True, lower=not upper,
                                    transpose_a=upper)
    return lax.linalg.triangular_solve(l, y, left_side=True,
                                       lower=not upper,
                                       transpose_a=not upper)


def triangular_solve(a, b, upper: bool = True, transpose: bool = False,
                     unitriangular: bool = False):
    """ref: linalg.py triangular_solve."""
    return lax.linalg.triangular_solve(
        a, b, left_side=True, lower=not upper, transpose_a=transpose,
        unit_diagonal=unitriangular)


def lu(a, pivot: bool = True):
    """ref: linalg.py lu → (LU packed, pivots, info). jax returns
    (lu, pivots, permutation); info is always 0 on success here."""
    lu_, piv, _ = lax.linalg.lu(a)
    info = jnp.zeros(a.shape[:-2], jnp.int32)
    # paddle returns 1-based pivots (LAPACK convention)
    return lu_, piv.astype(jnp.int32) + 1, info


def lu_unpack(lu_data, lu_pivots, unpack_ludata: bool = True,
              unpack_pivots: bool = True):
    """ref: linalg.py lu_unpack → (P, L, U); batched via vmap."""
    lu_data = jnp.asarray(lu_data)
    if lu_data.ndim > 2:
        return jax.vmap(
            lambda d, p: lu_unpack(d, p, unpack_ludata, unpack_pivots)
        )(lu_data, jnp.asarray(lu_pivots))
    n = lu_data.shape[-2]
    l = jnp.tril(lu_data, -1) + jnp.eye(n, lu_data.shape[-1],  # noqa: E741
                                        dtype=lu_data.dtype)
    u = jnp.triu(lu_data)
    # rebuild P from 1-based LAPACK row swaps
    perm = jnp.arange(n)
    piv = lu_pivots - 1

    def body(i, p):
        j = piv[i]
        pi, pj = p[i], p[j]
        return p.at[i].set(pj).at[j].set(pi)

    perm = lax.fori_loop(0, lu_pivots.shape[-1], body, perm)
    p_mat = jnp.eye(n, dtype=lu_data.dtype)[perm].T
    return p_mat, l, u


# paddle.linalg re-exports the paddle.tensor implementations — alias
# them rather than duplicating (tensor.dot is paddle's row-wise dot)
from .tensor import cross, dist, dot, matmul  # noqa: E402


def householder_product(x, tau):
    """ref: linalg.py householder_product (orgqr)."""
    return lax.linalg.householder_product(x, tau)


def pca_lowrank(x, q=None, center: bool = True, niter: int = 2):
    """ref: linalg.py pca_lowrank → (U, S, V) of the (centered) data.
    XLA's full SVD replaces the randomized iteration — at the sizes a
    TPU program handles, exact SVD of the thin dimension is cheaper
    than sketching."""
    x = jnp.asarray(x)
    if q is None:
        q = min(6, *x.shape[-2:])
    if center:
        x = x - x.mean(axis=-2, keepdims=True)
    u, s, vt = jnp.linalg.svd(x, full_matrices=False)
    return u[..., :q], s[..., :q], jnp.swapaxes(vt, -1, -2)[..., :q]
