"""paddle_tpu.hub — model hub loader (ref: python/paddle/hapi/hub.py —
torch.hub-like `paddle.hub.list/help/load` driven by a repo's
hubconf.py).

Zero-egress environment: only the ``source="local"`` path is supported —
github/gitee sources raise with a clear message instead of silently
hanging on a download."""

from __future__ import annotations

import importlib.util
import os
import sys
from typing import Any, List

HUBCONF = "hubconf.py"


def _load_hubconf(repo_dir: str):
    path = os.path.join(repo_dir, HUBCONF)
    if not os.path.exists(path):
        raise FileNotFoundError(f"no {HUBCONF} in {repo_dir!r}")
    spec = importlib.util.spec_from_file_location("hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["hubconf"] = mod
    spec.loader.exec_module(mod)
    return mod


def _check_source(source: str):
    if source != "local":
        raise NotImplementedError(
            f"hub source {source!r} needs network access; this build is "
            "zero-egress — clone the repo and use source='local'")


def list(repo_dir: str, source: str = "local") -> List[str]:  # noqa: A001
    """Entrypoints exported by the repo's hubconf (ref: hub.py list)."""
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    return sorted(n for n, v in vars(mod).items()
                  if callable(v) and not n.startswith("_"))


def help(repo_dir: str, model: str, source: str = "local") -> str:  # noqa: A001
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    return getattr(mod, model).__doc__ or ""


def load(repo_dir: str, model: str, *args, source: str = "local",
         **kwargs) -> Any:
    """Instantiate entrypoint ``model`` from the repo (ref: hub.py load)."""
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    if not hasattr(mod, model):
        raise ValueError(
            f"no entrypoint {model!r}; available: {list(repo_dir)}")
    return getattr(mod, model)(*args, **kwargs)
