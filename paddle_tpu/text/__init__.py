"""paddle_tpu.text (ref: python/paddle/text/ — NLP datasets +
ViterbiDecoder  viterbi_decode.py).

Datasets follow the vision pattern: local standard formats only
(zero-egress). The decoder is the compute piece: CRF viterbi decoding
as a lax.scan — batched, jittable, TPU-resident, replacing the
reference's viterbi_decode C++ op (paddle/fluid/operators/
viterbi_decode_op.cc)."""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..nn.layer import Layer


def viterbi_decode(potentials, transitions, lengths=None,
                   include_bos_eos_tag: bool = False):
    """Most-likely tag path per sequence.

    potentials: [batch, seq, ntags] emission scores;
    transitions: [ntags, ntags] (transitions[i, j]: score of i→j);
    lengths: [batch] valid lengths (default: full).
    include_bos_eos_tag: treat the last transition row (index n-1) as
    the start tag and the second-to-last row (n-2) as the stop tag —
    same convention as the reference kernel
    (paddle/phi/kernels/cpu/viterbi_decode_kernel.cc:222-252: rows split
    into [rest, stop_trans, start_trans]; start added at t=0, stop added
    at each sequence's final step).
    Returns (scores [batch], paths [batch, seq]).
    ref: python/paddle/text/viterbi_decode.py ViterbiDecoder.
    """
    potentials = jnp.asarray(potentials)
    transitions = jnp.asarray(transitions)
    b, s, n = potentials.shape
    if lengths is None:
        lengths = jnp.full((b,), s, jnp.int32)
    lengths = jnp.asarray(lengths, jnp.int32)
    start_row = transitions[n - 1]        # [n]
    stop_row = transitions[n - 2]         # [n]

    def step(carry, t):
        alpha = carry                     # [b, n] best score ending in tag
        emit = potentials[:, t]           # [b, n]
        # score[i, j] = alpha[i] + trans[i, j] + emit[j]
        scores = alpha[:, :, None] + transitions[None, :, :] + \
            emit[:, None, :]
        best_prev = jnp.argmax(scores, axis=1)        # [b, n]
        best_score = jnp.max(scores, axis=1)          # [b, n]
        # frozen past the sequence end
        active = (t < lengths)[:, None]
        alpha = jnp.where(active, best_score, alpha)
        if include_bos_eos_tag:
            last = (t == lengths - 1)[:, None]
            alpha = alpha + jnp.where(last, stop_row[None, :], 0.0)
        return alpha, jnp.where(active, best_prev,
                                jnp.arange(n)[None, :])

    alpha0 = potentials[:, 0]
    if include_bos_eos_tag:
        alpha0 = alpha0 + start_row[None, :] + jnp.where(
            (lengths == 1)[:, None], stop_row[None, :], 0.0)
    alpha, backps = jax.lax.scan(step, alpha0, jnp.arange(1, s))
    scores = jnp.max(alpha, axis=-1)
    last_tag = jnp.argmax(alpha, axis=-1)             # [b]

    def back(carry, bp):
        tag = carry
        prev = jnp.take_along_axis(bp, tag[:, None], axis=1)[:, 0]
        return prev, tag

    # reverse scan emits the tag at times 1..s-1 into their positions and
    # carries the time-0 tag out
    tag0, path_tail = jax.lax.scan(back, last_tag, backps, reverse=True)
    paths = jnp.concatenate([tag0[:, None],
                             path_tail.transpose(1, 0)], axis=1)  # [b, s]
    return scores, paths


class ViterbiDecoder(Layer):
    """ref: paddle.text.ViterbiDecoder(transitions,
    include_bos_eos_tag)."""

    def __init__(self, transitions, include_bos_eos_tag: bool = False):
        super().__init__()
        self.transitions = jnp.asarray(transitions)
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths=None):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)


class UCIHousing:
    """ref: text/datasets pattern — placeholder reader for the classic
    regression set; reads the standard housing.data file locally."""

    def __init__(self, root: str, mode: str = "train"):
        import os
        p = os.path.join(root, "housing.data")
        if not os.path.exists(p):
            raise FileNotFoundError(
                f"{p} not found; zero-egress environment needs the "
                "standard UCI housing.data file on disk")
        data = np.loadtxt(p)
        x, y = data[:, :-1].astype(np.float32), data[:, -1:].astype(
            np.float32)
        n = int(0.8 * len(x))
        if mode == "train":
            self.x, self.y = x[:n], y[:n]
        else:
            self.x, self.y = x[n:], y[n:]

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]
