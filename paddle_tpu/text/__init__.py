"""paddle_tpu.text (ref: python/paddle/text/ — NLP datasets +
ViterbiDecoder  viterbi_decode.py).

Datasets follow the vision pattern: local standard formats only
(zero-egress). The decoder is the compute piece: CRF viterbi decoding
as a lax.scan — batched, jittable, TPU-resident, replacing the
reference's viterbi_decode C++ op (paddle/fluid/operators/
viterbi_decode_op.cc)."""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..nn.layer import Layer


def viterbi_decode(potentials, transitions, lengths=None,
                   include_bos_eos_tag: bool = False):
    """Most-likely tag path per sequence.

    potentials: [batch, seq, ntags] emission scores;
    transitions: [ntags, ntags] (transitions[i, j]: score of i→j);
    lengths: [batch] valid lengths (default: full).
    include_bos_eos_tag: treat the last transition row (index n-1) as
    the start tag and the second-to-last row (n-2) as the stop tag —
    same convention as the reference kernel
    (paddle/phi/kernels/cpu/viterbi_decode_kernel.cc:222-252: rows split
    into [rest, stop_trans, start_trans]; start added at t=0, stop added
    at each sequence's final step).
    Returns (scores [batch], paths [batch, seq]).
    ref: python/paddle/text/viterbi_decode.py ViterbiDecoder.
    """
    potentials = jnp.asarray(potentials)
    transitions = jnp.asarray(transitions)
    b, s, n = potentials.shape
    if lengths is None:
        lengths = jnp.full((b,), s, jnp.int32)
    lengths = jnp.asarray(lengths, jnp.int32)
    start_row = transitions[n - 1]        # [n]
    stop_row = transitions[n - 2]         # [n]

    def step(carry, t):
        alpha = carry                     # [b, n] best score ending in tag
        emit = potentials[:, t]           # [b, n]
        # score[i, j] = alpha[i] + trans[i, j] + emit[j]
        scores = alpha[:, :, None] + transitions[None, :, :] + \
            emit[:, None, :]
        best_prev = jnp.argmax(scores, axis=1)        # [b, n]
        best_score = jnp.max(scores, axis=1)          # [b, n]
        # frozen past the sequence end
        active = (t < lengths)[:, None]
        alpha = jnp.where(active, best_score, alpha)
        if include_bos_eos_tag:
            last = (t == lengths - 1)[:, None]
            alpha = alpha + jnp.where(last, stop_row[None, :], 0.0)
        return alpha, jnp.where(active, best_prev,
                                jnp.arange(n)[None, :])

    alpha0 = potentials[:, 0]
    if include_bos_eos_tag:
        alpha0 = alpha0 + start_row[None, :] + jnp.where(
            (lengths == 1)[:, None], stop_row[None, :], 0.0)
    alpha, backps = jax.lax.scan(step, alpha0, jnp.arange(1, s))
    scores = jnp.max(alpha, axis=-1)
    last_tag = jnp.argmax(alpha, axis=-1)             # [b]

    def back(carry, bp):
        tag = carry
        prev = jnp.take_along_axis(bp, tag[:, None], axis=1)[:, 0]
        return prev, tag

    # reverse scan emits the tag at times 1..s-1 into their positions and
    # carries the time-0 tag out
    tag0, path_tail = jax.lax.scan(back, last_tag, backps, reverse=True)
    paths = jnp.concatenate([tag0[:, None],
                             path_tail.transpose(1, 0)], axis=1)  # [b, s]
    return scores, paths


class ViterbiDecoder(Layer):
    """ref: paddle.text.ViterbiDecoder(transitions,
    include_bos_eos_tag)."""

    def __init__(self, transitions, include_bos_eos_tag: bool = False):
        super().__init__()
        self.transitions = jnp.asarray(transitions)
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths=None):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)


class UCIHousing:
    """ref: text/datasets pattern — placeholder reader for the classic
    regression set; reads the standard housing.data file locally."""

    def __init__(self, root: str, mode: str = "train"):
        import os
        p = os.path.join(root, "housing.data")
        if not os.path.exists(p):
            raise FileNotFoundError(
                f"{p} not found; zero-egress environment needs the "
                "standard UCI housing.data file on disk")
        data = np.loadtxt(p)
        x, y = data[:, :-1].astype(np.float32), data[:, -1:].astype(
            np.float32)
        n = int(0.8 * len(x))
        if mode == "train":
            self.x, self.y = x[:n], y[:n]
        else:
            self.x, self.y = x[n:], y[n:]

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


def _build_word_idx(counts, min_freq: int, extra=("<unk>",)):
    """Frequency-cutoff vocab, deterministic order (-count, word); the
    literal special tokens are stripped from the corpus counts first so
    their appended ids stay in range (the reference deletes '<unk>'
    from word_freq the same way, text/datasets/imikolov.py)."""
    for tok in ("<unk>", "<s>", "<e>"):
        counts.pop(tok, None)
    vocab = [w for w, c in sorted(counts.items(),
                                  key=lambda t: (-t[1], t[0]))
             if c >= min_freq]
    word_idx = {w: i for i, w in enumerate(vocab)}
    for tok in extra:
        word_idx[tok] = len(word_idx)
    return word_idx


class Imikolov:
    """PTB-style n-gram language-model dataset (ref: text/datasets/
    imikolov.py — builds a word dict from train, yields n-grams).
    Reads the standard ptb.{train,valid}.txt files locally."""

    def __init__(self, root: str, data_type: str = "NGRAM", window_size:
                 int = 5, mode: str = "train", min_word_freq: int = 50):
        import collections
        import os
        train_p = os.path.join(root, "ptb.train.txt")
        path = os.path.join(
            root, "ptb.train.txt" if mode == "train" else
            "ptb.valid.txt")
        for p in {train_p, path}:
            if not os.path.exists(p):
                raise FileNotFoundError(
                    f"{p} not found; zero-egress environment needs the "
                    "ptb text files on disk")
        counts = collections.Counter()
        with open(train_p) as f:
            for line in f:
                counts.update(line.split())
        # sentinels live in the dict like the reference's word dict
        self.word_idx = _build_word_idx(
            counts, min_word_freq, extra=("<s>", "<e>", "<unk>"))
        unk = self.word_idx["<unk>"]
        self.data = []
        with open(path) as f:
            for line in f:
                ids = [self.word_idx.get(w, unk) for w in
                       ["<s>"] + line.split() + ["<e>"]]
                if data_type == "NGRAM":
                    for i in range(len(ids) - window_size + 1):
                        self.data.append(
                            np.asarray(ids[i:i + window_size],
                                       np.int64))
                else:  # SEQ
                    if len(ids) >= 2:
                        self.data.append(np.asarray(ids, np.int64))

    def __len__(self):
        return len(self.data)

    def __getitem__(self, i):
        return self.data[i]


class Imdb:
    """IMDB sentiment (ref: text/datasets/imdb.py — aclImdb directory
    tree pos/neg of .txt reviews; builds a word dict, yields
    (ids, label))."""

    def __init__(self, root: str, mode: str = "train", cutoff: int = 150):
        import collections
        import os
        import re
        base = os.path.join(root, "aclImdb")
        if not os.path.isdir(base):
            raise FileNotFoundError(
                f"{base} not found; zero-egress environment needs the "
                "extracted aclImdb tree on disk")
        tok = re.compile(r"[A-Za-z']+").findall

        def read(split, label):
            out = []
            d = os.path.join(base, split, label)
            for fn in sorted(os.listdir(d)):
                with open(os.path.join(d, fn), errors="ignore") as f:
                    out.append([w.lower() for w in tok(f.read())])
            return out

        train_pos = read("train", "pos")
        train_neg = read("train", "neg")
        counts = collections.Counter(
            w for doc in train_pos + train_neg for w in doc)
        self.word_idx = _build_word_idx(counts, cutoff)
        unk = self.word_idx["<unk>"]
        if mode == "train":  # vocab pass already read these files
            pos, neg = train_pos, train_neg
        else:
            pos, neg = read(mode, "pos"), read(mode, "neg")
        self.docs = [np.asarray([self.word_idx.get(w, unk) for w in d],
                                np.int64) for d in pos + neg]
        self.labels = np.asarray([0] * len(pos) + [1] * len(neg),
                                 np.int64)

    def __len__(self):
        return len(self.docs)

    def __getitem__(self, i):
        return self.docs[i], self.labels[i]


class Movielens:
    """MovieLens-1M ratings (ref: text/datasets/movielens.py — ::
    -separated users.dat/movies.dat/ratings.dat)."""

    def __init__(self, root: str, mode: str = "train",
                 test_ratio: float = 0.1, rand_seed: int = 0):
        import os
        base = root
        sub = os.path.join(root, "ml-1m")
        if os.path.isdir(sub):
            base = sub
        paths = {n: os.path.join(base, f"{n}.dat")
                 for n in ("users", "movies", "ratings")}
        for p in paths.values():
            if not os.path.exists(p):
                raise FileNotFoundError(
                    f"{p} not found; zero-egress environment needs the "
                    "ml-1m .dat files on disk")

        def rows(p):
            with open(p, errors="ignore") as f:
                return [ln.rstrip("\n").split("::") for ln in f if ln.strip()]

        self.users = {int(r[0]): r[1:] for r in rows(paths["users"])}
        self.movies = {int(r[0]): r[1:] for r in rows(paths["movies"])}
        ratings = rows(paths["ratings"])
        rng_ = np.random.RandomState(rand_seed)
        mask = rng_.rand(len(ratings)) < test_ratio
        keep = mask if mode == "test" else ~mask
        self.data = [(int(u), int(m), float(s))
                     for (u, m, s, _), k in zip(ratings, keep) if k]

    def __len__(self):
        return len(self.data)

    def __getitem__(self, i):
        u, m, s = self.data[i]
        return (np.int64(u), np.int64(m), np.float32(s))


class Conll05st:
    """CoNLL-2005 SRL dataset reader (ref: text/datasets/conll05.py —
    sentence/predicate/label columns). Zero-egress: reads the standard
    conll05st test file layout from ``root``: a whitespace-columns file
    ``conll05st.txt`` with word, predicate, and IOB label per line,
    blank line between sentences."""

    def __init__(self, root: str, mode: str = "test"):
        import os
        p = os.path.join(root, "conll05st.txt")
        if not os.path.exists(p):
            raise FileNotFoundError(
                f"{p} not found; place the CoNLL-05 column file there "
                "(zero-egress environment)")
        sents, cur = [], []
        for line in open(p):
            line = line.strip()
            if not line:
                if cur:
                    sents.append(cur)
                    cur = []
                continue
            cur.append(line.split())
        if cur:
            sents.append(cur)
        self.sentences = sents
        words = sorted({c[0] for s in sents for c in s})
        labels = sorted({c[-1] for s in sents for c in s})
        self.word_dict = {w: i for i, w in enumerate(words)}
        self.label_dict = {l: i for i, l in enumerate(labels)}
        # predicates are the column-1 lemmas; '-' (no predicate) gets
        # its own id so it can't collide with a real lemma's id
        lemmas = sorted({c[1] for s in sents for c in s
                         if len(c) > 2 and c[1] != "-"})
        self.predicate_dict = {w: i for i, w in enumerate(lemmas)}
        self._no_pred = len(self.predicate_dict)

    def __len__(self):
        return len(self.sentences)

    def __getitem__(self, i):
        s = self.sentences[i]
        words = np.asarray([self.word_dict[c[0]] for c in s])
        labels = np.asarray([self.label_dict[c[-1]] for c in s])
        pred = np.asarray([self.predicate_dict[c[1]]
                           if len(c) > 2 and c[1] != "-"
                           else self._no_pred for c in s])
        return words, pred, labels

    def get_dict(self):
        return self.word_dict, self.predicate_dict, self.label_dict


class _WMTBase:
    """Shared WMT parallel-corpus reader: ``root`` holds
    ``{split}.{src}`` / ``{split}.{tgt}`` line-aligned files; vocab is
    built from train with <s>/<e>/<unk> specials (ref:
    text/datasets/wmt14.py / wmt16.py BPE-tokenized readers)."""

    SRC, TGT = "en", "de"

    def __init__(self, root: str, mode: str = "train",
                 src_dict_size: int = 30000, trg_dict_size: int = 30000):
        import os
        sp = os.path.join(root, f"{mode}.{self.SRC}")
        tp = os.path.join(root, f"{mode}.{self.TGT}")
        for p in (sp, tp):
            if not os.path.exists(p):
                raise FileNotFoundError(
                    f"{p} not found; place line-aligned "
                    f"{self.SRC}/{self.TGT} files there (zero-egress)")
        self.src_lines = [l.strip().split() for l in open(sp)]
        self.tgt_lines = [l.strip().split() for l in open(tp)]
        # vocab ALWAYS comes from the train split so ids agree across
        # modes (the reference builds one dict from train); fall back
        # to this split only when no train files exist
        from collections import Counter
        vs = os.path.join(root, f"train.{self.SRC}")
        vt = os.path.join(root, f"train.{self.TGT}")
        src_corpus = ([l.strip().split() for l in open(vs)]
                      if os.path.exists(vs) else self.src_lines)
        tgt_corpus = ([l.strip().split() for l in open(vt)]
                      if os.path.exists(vt) else self.tgt_lines)
        self.src_dict = self._vocab(Counter(
            w for l in src_corpus for w in l), src_dict_size)
        self.trg_dict = self._vocab(Counter(
            w for l in tgt_corpus for w in l), trg_dict_size)

    @staticmethod
    def _vocab(counts, size):
        vocab = {"<s>": 0, "<e>": 1, "<unk>": 2}
        for w, _ in counts.most_common(max(size - 3, 0)):
            vocab.setdefault(w, len(vocab))
        return vocab

    def _ids(self, words, vocab):
        unk = vocab["<unk>"]
        return np.asarray([vocab["<s>"]]
                          + [vocab.get(w, unk) for w in words]
                          + [vocab["<e>"]])

    def __len__(self):
        return len(self.src_lines)

    def __getitem__(self, i):
        src = self._ids(self.src_lines[i], self.src_dict)
        tgt = self._ids(self.tgt_lines[i], self.trg_dict)
        return src, tgt[:-1], tgt[1:]


class WMT14(_WMTBase):
    """WMT'14 en→fr (ref: text/datasets/wmt14.py)."""

    SRC, TGT = "en", "fr"


class WMT16(_WMTBase):
    """WMT'16 en→de (ref: text/datasets/wmt16.py)."""

    SRC, TGT = "en", "de"
