"""Fused linear + cross-entropy: the LM vocab path without HBM logits.

Reference context: the reference fuses softmax+xent
(softmax_with_cross_entropy kernel, phi/kernels/gpu/cross_entropy_
kernel.cu) but still materialises the [tokens, vocab] logits produced
by the head matmul. At GPT-2-small/seq-1024 scale that buffer is the
single largest HBM tenant of the train step (PERF.md): [8192, 50304]
bf16 = 788 MB written by the matmul, read by the loss, written again as
softmax grads.

TPU-native design: the head matmul and the loss are one streaming
computation over VOCAB CHUNKS — an online logsumexp (the flash-
attention trick applied to the vocab axis):

    for each chunk c:  logits_c = h @ W_c^T        (MXU, [T, C] only)
                       m, l   <- online max/sumexp (VPU)
                       picked <- one-hot gather of label logits

so peak memory is [T, chunk] instead of [T, V]. The backward replays
the same chunks, forming softmax grads per chunk and contracting them
immediately into dh ([T, H]) and dW_c ([C, H]) — again never holding
[T, V]. Expressed with ``lax.scan`` over a reshaped [K, C, H] weight:
XLA pipelines chunk k+1's matmul against chunk k's reductions, which is
the same overlap a hand-written Pallas kernel would schedule; the
arithmetic is all MXU-shaped, so the win here is HBM footprint and
bandwidth, not issue latency.

Used by ``models.gpt.GPTFusedPretrainingCriterion`` (cfg.fused_loss):
the model returns (hidden, tied weight) and the criterion streams the
loss, so logits never exist in the training graph at all.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def _pick_chunk(v: int, target: int = 8192) -> int:
    return min(target, v)


def _chunks(weight, chunk):
    """[V, H] → [ceil(V/chunk), chunk, H]; pad rows are masked out of
    the logsumexp by the caller (chunking works for ANY vocab size —
    no divisor requirement, so GPT-2's unpadded 50257 still streams in
    full-width chunks)."""
    v, h = weight.shape
    pad = (-v) % chunk
    if pad:
        weight = jnp.pad(weight, ((0, pad), (0, 0)))
    return weight.reshape(-1, chunk, h)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def fused_linear_cross_entropy(hidden, weight, labels,
                               ignore_index: int = -100,
                               chunk: Optional[int] = None,
                               bias=None):
    """Mean cross-entropy of ``softmax(hidden @ weight.T + bias)``
    against ``labels`` without materialising the logits.

    hidden: [T, H] (callers flatten batch/seq); weight: [V, H] (the
    tied-embedding layout); labels: [T] int; bias: optional [V] logits
    bias (BERT's decoder bias). ``ignore_index`` rows are masked out of
    the mean (reference cross_entropy semantics).
    """
    loss, _ = _fwd(hidden, weight, labels, ignore_index, chunk, bias)
    return loss


def _fwd(hidden, weight, labels, ignore_index, chunk, bias=None):
    t, h = hidden.shape
    v = weight.shape[0]
    # AMP O1 hands bf16 activations + f32 params: compute in the
    # activation dtype (bf16 MXU path, half the weight-streaming
    # bytes); residuals keep the ORIGINAL weight so dW comes back in
    # the parameter's dtype. Accumulation is f32 via
    # preferred_element_type; the stats math stays f32.
    w_compute = weight if weight.dtype == hidden.dtype else \
        weight.astype(hidden.dtype)
    c = chunk or _pick_chunk(v)
    wc = _chunks(w_compute, c)
    # bias handling is a STATIC branch: None callers (GPT) pay nothing
    bc = None if bias is None else \
        _chunks(bias.astype(jnp.float32)[:, None], c)[..., 0]  # [K, C]
    labels = labels.astype(jnp.int32)

    def body(carry, args):
        m, l, picked = carry
        if bc is None:
            w_c, off = args
        else:
            w_c, b_c, off = args
        logits = lax.dot_general(
            hidden, w_c, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # [T, C] f32
        if bc is not None:
            logits = logits + b_c[None, :]
        # mask vocab-pad columns out of the statistics
        col_ok = off + jax.lax.broadcasted_iota(
            jnp.int32, (1, c), 1) < v
        logits = jnp.where(col_ok, logits, -jnp.inf)
        m_new = jnp.maximum(m, logits.max(axis=-1))
        l = l * jnp.exp(m - m_new) + \
            jnp.exp(logits - m_new[:, None]).sum(axis=-1)
        # one-hot gather of this chunk's label logits
        local = labels - off
        inside = (local >= 0) & (local < c)
        picked = picked + jnp.where(
            inside,
            jnp.take_along_axis(
                logits, jnp.clip(local, 0, c - 1)[:, None],
                axis=-1)[:, 0],
            0.0)
        return (m_new, l, picked), None

    m0 = jnp.full((t,), -jnp.inf, jnp.float32)
    carry0 = (m0, jnp.zeros((t,), jnp.float32),
              jnp.zeros((t,), jnp.float32))
    offsets = jnp.arange(wc.shape[0], dtype=jnp.int32) * c
    xs = (wc, offsets) if bc is None else (wc, bc, offsets)
    (m, l, picked), _ = lax.scan(body, carry0, xs)
    lse = m + jnp.log(l)
    valid = labels != ignore_index
    per_tok = jnp.where(valid, lse - picked, 0.0)
    n = jnp.maximum(valid.sum(), 1)
    loss = per_tok.sum() / n
    return loss, (hidden, weight, labels, bias, lse, valid, n)


def _bwd(ignore_index, chunk, res, g):
    hidden, weight, labels, bias, lse, valid, n = res
    t, h = hidden.shape
    v = weight.shape[0]
    out_w_dtype = weight.dtype
    if weight.dtype != hidden.dtype:
        weight = weight.astype(hidden.dtype)
    c = chunk or _pick_chunk(v)
    wc = _chunks(weight, c)
    bc = None if bias is None else \
        _chunks(bias.astype(jnp.float32)[:, None], c)[..., 0]
    labels = labels.astype(jnp.int32)
    # d(loss)/d(logits) = (softmax - onehot) * g / n, zeroed on ignored
    scale = (jnp.where(valid, 1.0, 0.0) * g / n).astype(jnp.float32)

    def body(dh, args):
        if bc is None:
            w_c, off = args
        else:
            w_c, b_c, off = args
        logits = lax.dot_general(
            hidden, w_c, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if bc is not None:
            logits = logits + b_c[None, :]
        col_ok = off + jax.lax.broadcasted_iota(
            jnp.int32, (1, c), 1) < v
        logits = jnp.where(col_ok, logits, -jnp.inf)
        p = jnp.exp(logits - lse[:, None])              # softmax chunk
        local = labels - off
        inside = (local >= 0) & (local < c)
        onehot_col = jnp.clip(local, 0, c - 1)
        p = p - jnp.where(
            inside[:, None] &
            (jax.lax.broadcasted_iota(jnp.int32, (t, c), 1) ==
             onehot_col[:, None]), 1.0, 0.0)
        dlog_f = p * scale[:, None]                     # [T, C] f32
        db_c = None if bc is None else dlog_f.sum(axis=0)  # [C]
        # grad matmuls run in the params' dtype (bf16 MXU path); f32
        # accumulation via preferred_element_type
        dlog = dlog_f.astype(weight.dtype)
        dh = dh + lax.dot_general(
            dlog, w_c, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)         # [T, H]
        dw_c = lax.dot_general(
            dlog, hidden.astype(weight.dtype),
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)         # [C, H]
        return dh, (dw_c if bc is None else (dw_c, db_c))

    offsets = jnp.arange(wc.shape[0], dtype=jnp.int32) * c
    xs = (wc, offsets) if bc is None else (wc, bc, offsets)
    dh, stacked = lax.scan(body, jnp.zeros((t, h), jnp.float32), xs)
    if bc is None:
        dw_chunks, dbias = stacked, None
    else:
        dw_chunks, db_chunks = stacked
        dbias = db_chunks.reshape(-1)[:v].astype(bias.dtype)
    dw = dw_chunks.reshape(-1, h)[:v]
    return (dh.astype(hidden.dtype), dw.astype(out_w_dtype), None,
            dbias)


def _fwd_rule(hidden, weight, labels, ignore_index, chunk, bias):
    loss, res = _fwd(hidden, weight, labels, ignore_index, chunk, bias)
    return loss, res


fused_linear_cross_entropy.defvjp(_fwd_rule, _bwd)
