"""Rotary position embeddings (RoPE).

Reference context: the reference ships RoPE via its ecosystem
(PaddleNLP fused_rope / incubate fused_rotary_position_embedding in
later versions); the core op rotates each head-dim pair (x_{2i},
x_{2i+1}) by position-dependent angles so attention scores depend only
on relative positions.

TPU-native notes: implemented in the half-split convention
(rotate_half, the LLaMA/NeoX layout) — two VPU multiplies and one
add per element, fused by XLA into the attention prologue; cos/sin
tables are precomputed once per max length and gathered per position
(static shapes, KV-cache offsets supported via ``position_ids``)."""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax.numpy as jnp


@functools.lru_cache(maxsize=16)
def rope_tables(head_dim: int, max_len: int, base: float = 10000.0,
                dtype=jnp.float32) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """cos/sin tables [max_len, head_dim] (half-split convention).
    Cached: eager decode loops call this per token per layer."""
    inv = 1.0 / (base ** (jnp.arange(0, head_dim, 2,
                                     dtype=jnp.float32) / head_dim))
    t = jnp.arange(max_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv)                       # [L, D/2]
    emb = jnp.concatenate([freqs, freqs], axis=-1)  # [L, D]
    return jnp.cos(emb).astype(dtype), jnp.sin(emb).astype(dtype)


def _rotate_half(x):
    half = x.shape[-1] // 2
    return jnp.concatenate([-x[..., half:], x[..., :half]], axis=-1)


def apply_rotary_pos_emb(q, k, cos, sin, position_ids=None):
    """Rotate q/k ([B, S, H, D]) by the table entries at
    ``position_ids`` ([B, S], default arange — pass the absolute
    positions when decoding with a KV cache)."""
    s = q.shape[1]
    if position_ids is None:
        cos_g = cos[None, :s, None, :]
        sin_g = sin[None, :s, None, :]
    else:
        cos_g = cos[position_ids][:, :, None, :]
        sin_g = sin[position_ids][:, :, None, :]
    q_out = q * cos_g + _rotate_half(q) * sin_g
    k_out = k * cos_g + _rotate_half(k) * sin_g
    return q_out.astype(q.dtype), k_out.astype(k.dtype)
