"""Rotary position embeddings (RoPE).

Reference context: the reference ships RoPE via its ecosystem
(PaddleNLP fused_rope / incubate fused_rotary_position_embedding in
later versions); the core op rotates each head-dim pair (x_{2i},
x_{2i+1}) by position-dependent angles so attention scores depend only
on relative positions.

TPU-native notes: implemented in the half-split convention
(rotate_half, the LLaMA/NeoX layout) — two VPU multiplies and one
add per element, fused by XLA into the attention prologue; cos/sin
tables are precomputed once per max length and gathered per position
(static shapes, KV-cache offsets supported via ``position_ids``)."""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np


@functools.lru_cache(maxsize=16)
def rope_tables(head_dim: int, max_len: int, base: float = 10000.0,
                dtype=jnp.float32) -> Tuple[np.ndarray, np.ndarray]:
    """cos/sin tables [max_len, head_dim] (half-split convention).
    Cached: eager decode loops call this per token per layer.

    Computed in NUMPY on purpose: jnp primitives bind to whatever
    trace is active, so a first call from inside a jit/scan trace
    would cache TRACERS and poison every later trace with an
    UnexpectedTracerError (order-dependent — an eager warm-up call
    masked it). numpy arrays are concrete constants under any trace."""
    inv = 1.0 / (base ** (np.arange(0, head_dim, 2,
                                    dtype=np.float32) / head_dim))
    t = np.arange(max_len, dtype=np.float32)
    freqs = np.outer(t, inv)                        # [L, D/2]
    emb = np.concatenate([freqs, freqs], axis=-1)   # [L, D]
    np_dtype = np.dtype(dtype) if dtype != jnp.bfloat16 else None
    cos, sin = np.cos(emb), np.sin(emb)
    if np_dtype is not None:
        return cos.astype(np_dtype), sin.astype(np_dtype)
    import ml_dtypes
    return (cos.astype(ml_dtypes.bfloat16),
            sin.astype(ml_dtypes.bfloat16))


def _rotate_half(x):
    half = x.shape[-1] // 2
    return jnp.concatenate([-x[..., half:], x[..., :half]], axis=-1)


def apply_rotary_pos_emb(q, k, cos, sin, position_ids=None):
    """Rotate q/k ([B, S, H, D]) by the table entries at
    ``position_ids`` ([B, S], default arange — pass the absolute
    positions when decoding with a KV cache)."""
    s = q.shape[1]
    # tables may arrive as numpy constants (rope_tables caches numpy —
    # trace-safe); gathering by a traced position_ids needs jnp
    cos, sin = jnp.asarray(cos), jnp.asarray(sin)
    if position_ids is None:
        cos_g = cos[None, :s, None, :]
        sin_g = sin[None, :s, None, :]
    else:
        cos_g = cos[position_ids][:, :, None, :]
        sin_g = sin[position_ids][:, :, None, :]
    q_out = q * cos_g + _rotate_half(q) * sin_g
    k_out = k * cos_g + _rotate_half(k) * sin_g
    return q_out.astype(q.dtype), k_out.astype(k.dtype)
