"""Ring attention: exact attention over sequence-sharded activations.

NEW capability relative to the reference (SURVEY.md §2.3: sequence/
context parallelism is ABSENT there — its long-sequence story stops at
fused/sparse attention kernels, operators/fused/fused_attention_op.cu and
python/paddle/nn/functional/sparse_attention.py). On TPU, context
parallelism is a natural fit for the ICI torus: each ``sp`` rank holds a
sequence shard of Q/K/V, and K/V chunks rotate around the ring with
``lax.ppermute`` while every rank accumulates its queries' attention over
the full sequence using online log-sum-exp merging (Ring Attention,
Liu et al. 2023 — blockwise-parallel transformer over a device ring).

Communication pattern: P-1 ppermute steps of the local K/V chunk
(overlapped with the block computation by XLA's latency-hiding
scheduler); memory per chip is O(s/P) activations — sequences scale
linearly with the ring size.

Differentiation: the scan + ppermute graph is transposed by jax autodiff
(reverse ring rotation in the backward), so no hand-written VJP is
needed; block attention math stays in f32 log-space for stability.

Training-parity lanes (r4 VERDICT item 7 — these closed the
models/gpt.py NotImplementedErrors):
- ``key_padding_mask`` [b, s_global]: sharded over sp like K and
  ROTATED around the ring with the K/V chunks, so each block masks its
  own columns — no rank ever materializes the full mask.
- ``dropout_p``/``dropout_key``: attention-weight dropout applied to
  the softmax numerator per block (the denominator/LSE stay undropped,
  which keeps the online merge exact). The per-block key is the step
  key folded with the block's GLOBAL (q_base, k_base), so the pattern
  is deterministic under jax.checkpoint recomputation and independent
  across ring steps — the same tick-folding trick as the pipeline RNG
  (parallel/pipeline.py). The realized mask depends on the (sp, chunk)
  decomposition; it is iid Bernoulli over attention weights either way.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

NEG_INF = -1e30


def _block_attention(q, k, v, sm_scale, mask, dropout_p: float = 0.0,
                     dropout_key=None, q_base=0, k_base=0):
    """Partial attention of local queries against one K/V chunk.

    q: [b, sq, h, d]; k/v: [b, sk, h, d]; mask: additive, broadcastable
    to [b, h, sq, sk], or None. Returns (out [b, sq, h, d] f32,
    lse [b, h, sq] f32) with lse = -inf rows producing out = 0 (merged
    away by the combiner). Attention-weight dropout (if any) drops
    entries of the softmax NUMERATOR only — normalization and LSE come
    from the undropped weights, exactly like dropout applied to a
    fully-materialized softmax matrix.
    """
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * sm_scale
    if mask is not None:
        logits = logits + mask
    m = jnp.max(logits, axis=-1, keepdims=True)          # [b,h,q,1]
    m_safe = jnp.maximum(m, NEG_INF)                     # avoid -inf - -inf
    p = jnp.exp(logits - m_safe)
    if mask is not None:
        # the sentinel is FINITE (-1e30): a fully-masked row would
        # otherwise softmax uniformly over its sentinels instead of
        # zeroing (surfaced when causal and padding masks stack)
        p = jnp.where(mask > NEG_INF * 0.5, p, 0.0)
    denom = jnp.sum(p, axis=-1, keepdims=True)
    lse = (m_safe + jnp.log(jnp.maximum(denom, 1e-37)))[..., 0]  # [b,h,q]
    fully_masked = denom[..., 0] <= 0.0
    lse = jnp.where(fully_masked, NEG_INF, lse)
    if dropout_p and dropout_key is not None:
        blk_key = jax.random.fold_in(
            jax.random.fold_in(dropout_key, q_base), k_base)
        keep = 1.0 - dropout_p
        keep_mask = jax.random.bernoulli(blk_key, keep, p.shape)
        p = jnp.where(keep_mask, p / keep, 0.0)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    out = out / jnp.maximum(denom, 1e-37).transpose(0, 2, 1, 3)
    out = jnp.where(fully_masked.transpose(0, 2, 1)[..., None], 0.0, out)
    return out, lse


def _merge(o1, lse1, o2, lse2):
    """Online combine of two partial attentions in log-space."""
    lse = jnp.logaddexp(lse1, lse2)                       # [b,h,q]
    w1 = jnp.exp(lse1 - lse)
    w2 = jnp.exp(lse2 - lse)
    o = o1 * w1.transpose(0, 2, 1)[..., None] + \
        o2 * w2.transpose(0, 2, 1)[..., None]
    return o, lse


def _pad_to_additive(kpm):
    """[b, sk] bool (True = attend) or additive float → additive f32."""
    if kpm is None:
        return None
    if kpm.dtype == jnp.bool_:
        return jnp.where(kpm, 0.0, NEG_INF).astype(jnp.float32)
    return kpm.astype(jnp.float32)


def _block_attention_streamed(q, k, v, sm_scale, q_base, k_base,
                              causal, chunk, kpm=None,
                              dropout_p: float = 0.0, dropout_key=None):
    """_block_attention with the K/V chunk streamed: an online-softmax
    lax.scan over ``chunk``-column tiles, so the per-device logits
    working set is [sq, chunk] instead of [sq, sk] — flash attention
    in XLA-native form (the pallas kernel serves the dedicated op;
    this form needs no kernel and composes with shard_map/ppermute).
    ``q_base``/``k_base`` are the blocks' global position offsets
    (traced scalars under shard_map) for the causal mask; the
    checkpointed scan body makes the O(chunk) claim structural.
    ``kpm``: additive key-padding [b, sk] for THIS chunk, tiled along
    with K/V. Returns (out f32, lse f32) like _block_attention."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    n = sk // chunk
    k_r = jnp.moveaxis(k.reshape(b, n, chunk, h, d), 1, 0)
    v_r = jnp.moveaxis(v.reshape(b, n, chunk, h, d), 1, 0)
    kpm_r = None if kpm is None else \
        jnp.moveaxis(kpm.reshape(b, n, chunk), 1, 0)      # [n, b, chunk]

    def body(carry, xs):
        o_acc, lse_acc = carry
        if kpm_r is None:
            k_i, v_i, i = xs
            mask = None
        else:
            k_i, v_i, kpm_i, i = xs
            mask = kpm_i[:, None, None, :]                # [b,1,1,chunk]
        if causal:
            # q_base + r >= k_base + i*chunk + c, as a _causal_mask offset
            cm = _causal_mask(sq, chunk,
                              q_base - k_base - i * chunk)[None, None]
            mask = cm if mask is None else mask + cm
        o_j, lse_j = _block_attention(
            q, k_i, v_i, sm_scale, mask, dropout_p, dropout_key,
            q_base, k_base + i * chunk)
        return _merge(o_acc, lse_acc, o_j, lse_j), None

    body = jax.checkpoint(body)
    o0 = jnp.zeros(q.shape, jnp.float32)
    lse0 = jnp.full((b, h, sq), NEG_INF, jnp.float32)
    xs = (k_r, v_r, jnp.arange(n)) if kpm_r is None else \
        (k_r, v_r, kpm_r, jnp.arange(n))
    (o, lse), _ = lax.scan(body, (o0, lse0), xs)
    return o, lse


def ring_attention(q, k, v, *, causal: bool = False,
                   sm_scale: Optional[float] = None,
                   axis: str = "sp", mesh=None,
                   chunk_size: Optional[int] = None,
                   key_padding_mask=None,
                   dropout_p: float = 0.0, dropout_key=None):
    """Exact attention with Q/K/V sequence-sharded over mesh axis ``axis``.

    q, k, v: [b, s_global, h, d] GLOBAL arrays (sharded or to-be-sharded
    over the sp axis). Returns [b, s_global, h, d] with the same
    sequence sharding. Equals full attention numerically.

    ``chunk_size``: stream each ring block's K/V through the
    online-softmax scan in tiles of this many columns — per-device
    logits drop from [s/sp, s/sp] to [s/sp, chunk_size], making the
    per-device attention memory O(s·chunk/sp) (the flash-in-block
    lever for true long context; requires chunk_size | s/sp).

    ``key_padding_mask``: [b, s_global] — bool (True = attend) or
    additive float. Sequence-sharded and rotated with the K/V ring.

    ``dropout_p`` with ``dropout_key``: attention-weight dropout (see
    module docstring for the determinism contract).
    """
    from ..parallel.mesh import get_mesh
    mesh = mesh or get_mesh()
    sp = mesh.axis_size(axis)
    b, s, h, d = q.shape
    if s % sp:
        raise ValueError(f"sequence {s} not divisible by sp={sp}")
    s_local = s // sp
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    if dropout_p and dropout_key is None:
        raise ValueError("dropout_p > 0 requires dropout_key")
    if not dropout_p:
        dropout_key = None
    kpm = _pad_to_additive(key_padding_mask)
    if kpm is not None and kpm.shape != (b, s):
        raise ValueError(
            f"key_padding_mask must be [batch, seq] = {(b, s)}, got "
            f"{kpm.shape}")

    if chunk_size is not None:
        if chunk_size <= 0:
            raise ValueError(f"chunk_size must be positive, got "
                             f"{chunk_size}")
        if s_local % chunk_size:
            raise ValueError(
                f"chunk_size {chunk_size} must divide s/sp = {s_local}")

    if sp == 1:
        if chunk_size is not None and chunk_size < s:
            out, _ = _block_attention_streamed(
                q, k, v, scale, 0, 0, causal, chunk_size, kpm,
                dropout_p, dropout_key)
        else:
            mask = None if kpm is None else kpm[:, None, None, :]
            if causal:
                cm = _causal_mask(s, s, 0)[None, None]
                mask = cm if mask is None else mask + cm
            out, _ = _block_attention(q, k, v, scale, mask,
                                      dropout_p, dropout_key, 0, 0)
        return out.astype(q.dtype)

    spec = P(None, axis, None, None)
    kpm_spec = P(None, axis)

    def per_shard(q_l, k_l, v_l, kpm_l):
        rank = lax.axis_index(axis)
        ring = [(i, (i + 1) % sp) for i in range(sp)]

        rows = jnp.arange(s_local)
        cols = jnp.arange(s_local)

        def step(carry, j):
            k_cur, v_cur, kpm_cur, o_acc, lse_acc = carry
            src = (rank - j) % sp  # which rank's chunk we now hold
            if chunk_size is not None and chunk_size < s_local:
                o_j, lse_j = _block_attention_streamed(
                    q_l, k_cur, v_cur, scale, rank * s_local,
                    src * s_local, causal, chunk_size, kpm_cur,
                    dropout_p, dropout_key)
            else:
                mask = None if kpm_cur is None else \
                    kpm_cur[:, None, None, :]
                if causal:
                    # global positions: q row r -> rank*s_local + r,
                    # k col c -> src*s_local + c; attend iff
                    # q_pos >= k_pos
                    q_pos = rank * s_local + rows[:, None]
                    k_pos = src * s_local + cols[None, :]
                    cm = jnp.where(q_pos >= k_pos, 0.0,
                                   NEG_INF)[None, None]
                    mask = cm if mask is None else mask + cm
                o_j, lse_j = _block_attention(
                    q_l, k_cur, v_cur, scale, mask, dropout_p,
                    dropout_key, rank * s_local, src * s_local)
            o_acc, lse_acc = _merge(o_acc, lse_acc, o_j, lse_j)
            k_nxt = lax.ppermute(k_cur, axis, ring)
            v_nxt = lax.ppermute(v_cur, axis, ring)
            kpm_nxt = kpm_cur if kpm_cur is None else \
                lax.ppermute(kpm_cur, axis, ring)
            return (k_nxt, v_nxt, kpm_nxt, o_acc, lse_acc), None

        o0 = jnp.zeros(q_l.shape, jnp.float32)
        lse0 = jnp.full((b, h, s_local), NEG_INF, jnp.float32)
        carry, _ = _scan_helper(step, (k_l, v_l, kpm_l, o0, lse0), sp)
        return carry[3].astype(q_l.dtype)

    # partial-manual: only the sp axis is manual (the ring's ppermute
    # needs it); batch/head dims stay in GSPMD auto mode so dp/fsdp/tp
    # shardings of the enclosing step pass through untouched — the same
    # trick the pipeline uses for tp-inside-pp (parallel/pipeline.py)
    if kpm is None:
        def no_pad(q_a, k_a, v_a):
            return per_shard(q_a, k_a, v_a, None)
        mapped = jax.shard_map(no_pad, mesh=mesh.mesh,
                               in_specs=(spec, spec, spec),
                               out_specs=spec, check_vma=False,
                               axis_names={axis})
        return mapped(q, k, v)
    mapped = jax.shard_map(per_shard, mesh=mesh.mesh,
                           in_specs=(spec, spec, spec, kpm_spec),
                           out_specs=spec, check_vma=False,
                           axis_names={axis})
    return mapped(q, k, v, kpm)


def _scan_helper(step, init, n):
    return lax.scan(step, init, jnp.arange(n))


def _causal_mask(sq, sk, offset):
    rows = jnp.arange(sq)[:, None] + offset
    cols = jnp.arange(sk)[None, :]
    return jnp.where(rows >= cols, 0.0, NEG_INF)
