"""Ring attention: exact attention over sequence-sharded activations.

NEW capability relative to the reference (SURVEY.md §2.3: sequence/
context parallelism is ABSENT there — its long-sequence story stops at
fused/sparse attention kernels, operators/fused/fused_attention_op.cu and
python/paddle/nn/functional/sparse_attention.py). On TPU, context
parallelism is a natural fit for the ICI torus: each ``sp`` rank holds a
sequence shard of Q/K/V, and K/V chunks rotate around the ring with
``lax.ppermute`` while every rank accumulates its queries' attention over
the full sequence using online log-sum-exp merging (Ring Attention,
Liu et al. 2023 — blockwise-parallel transformer over a device ring).

Communication pattern: P-1 ppermute steps of the local K/V chunk
(overlapped with the block computation by XLA's latency-hiding
scheduler); memory per chip is O(s/P) activations — sequences scale
linearly with the ring size.

Differentiation: the scan + ppermute graph is transposed by jax autodiff
(reverse ring rotation in the backward), so no hand-written VJP is
needed; block attention math stays in f32 log-space for stability.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

NEG_INF = -1e30


def _block_attention(q, k, v, sm_scale, mask):
    """Partial attention of local queries against one K/V chunk.

    q: [b, sq, h, d]; k/v: [b, sk, h, d]; mask: [sq, sk] additive or None.
    Returns (out [b, sq, h, d] f32, lse [b, h, sq] f32) with
    lse = -inf rows producing out = 0 (merged away by the combiner).
    """
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * sm_scale
    if mask is not None:
        logits = logits + mask[None, None, :, :]
    m = jnp.max(logits, axis=-1, keepdims=True)          # [b,h,q,1]
    m_safe = jnp.maximum(m, NEG_INF)                     # avoid -inf - -inf
    p = jnp.exp(logits - m_safe)
    denom = jnp.sum(p, axis=-1, keepdims=True)
    lse = (m_safe + jnp.log(jnp.maximum(denom, 1e-37)))[..., 0]  # [b,h,q]
    fully_masked = denom[..., 0] <= 0.0
    lse = jnp.where(fully_masked, NEG_INF, lse)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    out = out / jnp.maximum(denom, 1e-37).transpose(0, 2, 1, 3)
    out = jnp.where(fully_masked.transpose(0, 2, 1)[..., None], 0.0, out)
    return out, lse


def _merge(o1, lse1, o2, lse2):
    """Online combine of two partial attentions in log-space."""
    lse = jnp.logaddexp(lse1, lse2)                       # [b,h,q]
    w1 = jnp.exp(lse1 - lse)
    w2 = jnp.exp(lse2 - lse)
    o = o1 * w1.transpose(0, 2, 1)[..., None] + \
        o2 * w2.transpose(0, 2, 1)[..., None]
    return o, lse


def ring_attention(q, k, v, *, causal: bool = False,
                   sm_scale: Optional[float] = None,
                   axis: str = "sp", mesh=None):
    """Exact attention with Q/K/V sequence-sharded over mesh axis ``axis``.

    q, k, v: [b, s_global, h, d] GLOBAL arrays (sharded or to-be-sharded
    over the sp axis). Returns [b, s_global, h, d] with the same
    sequence sharding. Equals full attention numerically.
    """
    from ..parallel.mesh import get_mesh
    mesh = mesh or get_mesh()
    sp = mesh.axis_size(axis)
    b, s, h, d = q.shape
    if s % sp:
        raise ValueError(f"sequence {s} not divisible by sp={sp}")
    s_local = s // sp
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)

    if sp == 1:
        out, _ = _block_attention(
            q, k, v, scale,
            _causal_mask(s, s, 0) if causal else None)
        return out.astype(q.dtype)

    spec = P(None, axis, None, None)

    def per_shard(q_l, k_l, v_l):
        rank = lax.axis_index(axis)
        ring = [(i, (i + 1) % sp) for i in range(sp)]

        rows = jnp.arange(s_local)
        cols = jnp.arange(s_local)

        def step(carry, j):
            k_cur, v_cur, o_acc, lse_acc = carry
            src = (rank - j) % sp  # which rank's chunk we now hold
            if causal:
                # global positions: q row r -> rank*s_local + r,
                # k col c -> src*s_local + c; attend iff q_pos >= k_pos
                q_pos = rank * s_local + rows[:, None]
                k_pos = src * s_local + cols[None, :]
                mask = jnp.where(q_pos >= k_pos, 0.0, NEG_INF)
            else:
                mask = None
            o_j, lse_j = _block_attention(q_l, k_cur, v_cur, scale, mask)
            o_acc, lse_acc = _merge(o_acc, lse_acc, o_j, lse_j)
            k_nxt = lax.ppermute(k_cur, axis, ring)
            v_nxt = lax.ppermute(v_cur, axis, ring)
            return (k_nxt, v_nxt, o_acc, lse_acc), None

        o0 = jnp.zeros(q_l.shape, jnp.float32)
        lse0 = jnp.full((b, h, s_local), NEG_INF, jnp.float32)
        carry, _ = _scan_helper(step, (k_l, v_l, o0, lse0), sp)
        return carry[2].astype(q_l.dtype)

    mapped = jax.shard_map(per_shard, mesh=mesh.mesh,
                           in_specs=(spec, spec, spec),
                           out_specs=spec, check_vma=False)
    return mapped(q, k, v)


def _scan_helper(step, init, n):
    return lax.scan(step, init, jnp.arange(n))


def _causal_mask(sq, sk, offset):
    rows = jnp.arange(sq)[:, None] + offset
    cols = jnp.arange(sk)[None, :]
    return jnp.where(rows >= cols, 0.0, NEG_INF)
