"""paddle_tpu.ops — custom TPU kernels (Pallas/Mosaic).

The reference implements its fused hot-path ops as hand-written CUDA
(reference: paddle/fluid/operators/fused/fused_attention_op.cu,
fmha_ref.h, fused_multi_transformer_op.cu). The TPU-native equivalents
live here as Pallas kernels compiled by Mosaic, with `interpret=True`
fallback so the same kernels run (slowly) on CPU test meshes.
"""

from .flash_attention import flash_attention  # noqa
from .ring_attention import ring_attention  # noqa: F401
from .fused_xent import fused_linear_cross_entropy  # noqa
from .paged_attention import (PagedKVCache, QuantizedKV,  # noqa
                              paged_attention,  # noqa
                              paged_attention_ragged,  # noqa
                              ragged_paged_attention,  # noqa
                              ragged_paged_attention_reference)  # noqa
from .rotary import apply_rotary_pos_emb, rope_tables  # noqa
