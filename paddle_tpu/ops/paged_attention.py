"""Paged attention: decode-time attention over a block-paged KV cache.

Reference context: the reference's serving attention keeps one dense
[b, max_len, h, d] cache per request (fused_multi_transformer_op.cu);
continuous batching then wastes HBM on the padding between each
request's true length and max_len. The paged formulation (vLLM;
"Ragged Paged Attention" for TPU, arXiv:2604.15464 in PAPERS.md) stores
KV in fixed-size PAGES shared across requests, with a per-request block
table mapping logical positions to pages — HBM waste bounded by one
page per sequence.

TPU-native design: pages are gathered per request with one take() (XLA
lowers to a dynamic-gather the TPU does well at page granularity —
contiguous [page_size, kv_heads, d] blocks), then attention runs as
dense SDPA with a context-length mask. Static shapes throughout
(pages_per_seq is the compiled maximum; short sequences mask). The
fancy kernel in the paper fuses the gather into the attention loop —
that is a later Pallas optimization; this implementation fixes the
MEMORY model, which is the serving win, and is numerically exact.
"""

from __future__ import annotations

import math
from typing import NamedTuple, Optional, Union

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import _LANES, _on_cpu
from .flash_attention import DEFAULT_MASK_VALUE as _MASK_VALUE


# ---------------------------------------------------------------------------
# int8-quantized KV pool
# ---------------------------------------------------------------------------

# engine knob values for LLMEngine(kv_dtype=...): the storage dtype of
# the paged KV pool. "int8" stores QUANTIZED pages with a per-token
# scale table beside the pool (see QuantizedKV) — ~2x page capacity at
# fixed HBM; the rest are plain-array pools.
KV_DTYPES = {
    "f32": jnp.float32, "float32": jnp.float32,
    "bf16": jnp.bfloat16, "bfloat16": jnp.bfloat16,
    "f16": jnp.float16, "float16": jnp.float16,
    "int8": jnp.int8,
}


class QuantizedKV(NamedTuple):
    """An int8-quantized paged KV store: ``pages`` holds the quantized
    values, ``scales`` the symmetric absmax scale of every page ROW
    (one f32 per written token per layer, stored beside the pool).

    Scale granularity is per token-row, not per page, by design: a
    page FILLS INCREMENTALLY (decode writes one token per tick), so a
    page-global scale would have to rescale already-written rows
    whenever a later token's amplitude exceeds the page max —
    per-row scales make quantize-on-write local and deterministic
    (the same KV values always quantize to the same bytes, which is
    what keeps prefix-cache sharing and nonce-pinned replay exact).
    Storage overhead is 4 bytes per token per layer per K/V against
    ``kv_heads*head_dim`` 1-byte values (~6% at the smallest test
    heads, less at real widths).

    Shapes (matching the plain pool with a leading scale-free tail):
    ``pages`` [..., num_pages, page_size, kv_heads, head_dim] int8,
    ``scales`` [..., num_pages, page_size] f32."""

    pages: jax.Array
    scales: jax.Array


KVStore = Union[jax.Array, QuantizedKV]


def kv_zeros(shape, dtype) -> KVStore:
    """Allocate a zeroed KV store. ``dtype`` is a jnp dtype or a
    KV_DTYPES key; int8 yields a :class:`QuantizedKV` (scale table
    beside the pool), anything else a plain array."""
    if isinstance(dtype, str):
        dtype = KV_DTYPES[dtype]
    if dtype == jnp.int8:
        return QuantizedKV(jnp.zeros(shape, jnp.int8),
                           jnp.zeros(shape[:-2], jnp.float32))
    return jnp.zeros(shape, dtype)


def kv_layer(store: KVStore, i) -> KVStore:
    """Per-layer view of a [L, ...]-stacked store (what the attention
    entry point consumes)."""
    if isinstance(store, QuantizedKV):
        return QuantizedKV(store.pages[i], store.scales[i])
    return store[i]


def kv_page_size(store: KVStore) -> int:
    return (store.pages if isinstance(store, QuantizedKV)
            else store).shape[-3]


def kv_nbytes(store: KVStore) -> int:
    """Device bytes of the store INCLUDING the scale table — the
    honest per-pool figure the memory ledger denominates pages in."""
    if isinstance(store, QuantizedKV):
        return store.pages.nbytes + store.scales.nbytes
    return store.nbytes


def kv_scale_nbytes(store: KVStore) -> int:
    """Bytes of the scale table alone (0 for plain stores) — the
    ledger's distinct ``scale_table`` row."""
    return store.scales.nbytes if isinstance(store, QuantizedKV) else 0


def quantize_kv(rows, eps: float = 1e-8):
    """Per-token symmetric absmax int8 quantization of KV rows
    [..., kv_heads, head_dim] -> (int8 rows, f32 scales [...]).
    Deterministic (pure function of the values): identical KV always
    produces identical quantized bytes, so cache-on/off and retried
    streams stay identical under quantization."""
    x = rows.astype(jnp.float32)
    amax = jnp.max(jnp.abs(x), axis=(-2, -1))
    scale = jnp.maximum(amax, eps) / 127.0
    q = jnp.clip(jnp.round(x / scale[..., None, None]),
                 -127, 127).astype(jnp.int8)
    return q, scale


def kv_write(store: KVStore, layer, page_idx, offs, rows) -> KVStore:
    """Scatter new KV rows into the pool at (layer, page_idx, offs),
    quantizing on write for :class:`QuantizedKV` stores (the scale
    lands beside the page row). ``rows`` [..., kv_heads, head_dim]
    with ``page_idx``/``offs`` broadcast over the leading dims —
    exactly the ``.at[i, page_idx, offs].set`` contract the engine's
    layers already use, made dtype-aware in ONE place."""
    if isinstance(store, QuantizedKV):
        q, s = quantize_kv(rows)
        return QuantizedKV(
            store.pages.at[layer, page_idx, offs].set(q),
            store.scales.at[layer, page_idx, offs].set(s))
    return store.at[layer, page_idx, offs].set(rows.astype(store.dtype))


def _split_kv(store: KVStore):
    if isinstance(store, QuantizedKV):
        return store.pages, store.scales
    return store, None


class PagedKVCache:
    """Page-pool KV storage + per-request block tables.

    k/v pages: [num_pages, page_size, kv_heads, head_dim]; block table
    [max_seqs, pages_per_seq] of page ids (-1 = unallocated);
    context_lens [max_seqs]. Host-side allocation (serving control
    plane), device-side tensors."""

    def __init__(self, num_pages: int, page_size: int, kv_heads: int,
                 head_dim: int, max_seqs: int, pages_per_seq: int,
                 dtype=jnp.float32):
        self.page_size = page_size
        self.k_pages = jnp.zeros((num_pages, page_size, kv_heads,
                                  head_dim), dtype)
        self.v_pages = jnp.zeros_like(self.k_pages)
        self.block_tables = jnp.full((max_seqs, pages_per_seq), -1,
                                     jnp.int32)
        self.context_lens = jnp.zeros((max_seqs,), jnp.int32)
        self._free = list(range(num_pages - 1, -1, -1))

    def allocate(self, seq: int, n_tokens: int) -> None:
        """Reserve pages for n_tokens of sequence ``seq``."""
        need = -(-n_tokens // self.page_size)
        if need > self.block_tables.shape[1]:
            raise ValueError(
                f"sequence {seq} needs {need} pages but the block "
                f"table holds {self.block_tables.shape[1]} "
                f"(pages_per_seq); raise pages_per_seq or evict")
        have = int((self.block_tables[seq] >= 0).sum())
        for slot in range(have, need):
            if not self._free:
                raise RuntimeError("KV page pool exhausted")
            page = self._free.pop()
            self.block_tables = self.block_tables.at[seq, slot].set(page)

    def free(self, seq: int) -> None:
        for pid in [int(p) for p in self.block_tables[seq] if p >= 0]:
            self._free.append(pid)
        self.block_tables = self.block_tables.at[seq].set(-1)
        self.context_lens = self.context_lens.at[seq].set(0)

    def append(self, seq: int, k_new, v_new) -> None:
        """Write [t, kv_heads, d] new tokens at the sequence's end.
        Tokens are written one contiguous slice per TOUCHED PAGE (a
        per-token .at[].set would copy the whole pool per token)."""
        t = int(k_new.shape[0])
        start = int(self.context_lens[seq])
        self.allocate(seq, start + t)
        ps = self.page_size
        i = 0
        while i < t:
            pos = start + i
            page = int(self.block_tables[seq, pos // ps])
            off = pos % ps
            span = min(ps - off, t - i)
            self.k_pages = self.k_pages.at[page, off:off + span].set(
                k_new[i:i + span])
            self.v_pages = self.v_pages.at[page, off:off + span].set(
                v_new[i:i + span])
            i += span
        self.context_lens = self.context_lens.at[seq].set(start + t)


def paged_attention_kernel(q, k_pages, v_pages, block_tables,
                           context_lens, scale: Optional[float] = None,
                           interpret: Optional[bool] = None,
                           k_scales=None, v_scales=None):
    """Fused Pallas decode attention over paged KV (the "fancy kernel"
    the module docstring deferred; Ragged-Paged-Attention lineage).

    Same contract as :func:`paged_attention`. The difference is the
    memory traffic: the XLA path GATHERS every sequence's full padded
    context ([B, pages_per_seq*page_size, H, D]) into HBM before the
    dense attention reads it again; here the kernel's BlockSpec index
    map reads the SCALAR-PREFETCHED block table directly, so each grid
    step streams exactly one real page from the pool into VMEM —
    traffic scales with the true context length (``pl.when`` skips
    pages past it entirely), and nothing is materialized in between.

    Grid: (batch, kv_heads, pages_per_seq); the page dim is sequential
    so the online-softmax scratch (acc/m/l) carries across it. GQA is
    native: the q block per kv head is its [group, D] query rows
    (group = heads // kv_heads), matching the repeat-kv convention.

    int8 KV (``k_scales``/``v_scales`` [num_pages, page_size]):
    dequantization happens IN-KERNEL — each grid step streams the
    page's f32 scale row alongside its int8 block and multiplies in
    VMEM, so HBM traffic stays at the quantized byte count (the whole
    point of the int8 pool). NOTE: real-TPU int8 tiling wants
    (32, 128) min tiles; the decode block here is page-granular and
    validated in interpret mode (CPU) — the on-chip tile-shape sweep
    rides tpu_sweep once hardware is reachable again.
    """
    if interpret is None:
        interpret = _on_cpu()  # same convention as flash_attention
    b, n_heads, d = q.shape
    n_pages, page_size, kv_heads, _ = k_pages.shape
    pages_per_seq = block_tables.shape[1]
    group = n_heads // kv_heads
    sm_scale = scale if scale is not None else 1.0 / math.sqrt(d)
    quantized = k_scales is not None

    qg = q.reshape(b, kv_heads, group, d)
    tables = jnp.clip(block_tables, 0).astype(jnp.int32)
    lens = context_lens.astype(jnp.int32)

    def kernel(ctx_ref, tbl_ref, q_ref, k_ref, v_ref, *rest):
        if quantized:
            ks_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref = rest
        else:
            o_ref, acc_ref, m_ref, l_ref = rest
        bi = pl.program_id(0)
        j = pl.program_id(2)

        @pl.when(j == 0)
        def _init():
            acc_ref[...] = jnp.zeros_like(acc_ref)
            m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
            l_ref[...] = jnp.zeros_like(l_ref)

        ctx = ctx_ref[bi]

        @pl.when(j * page_size < ctx)
        def _compute():
            qb = q_ref[0, 0]                     # [group, d]
            k = k_ref[0, :, 0, :].astype(jnp.float32)  # [page_size, d]
            v = v_ref[0, :, 0, :].astype(jnp.float32)
            if quantized:
                # dequantize in VMEM: one scale per page row
                k = k * ks_ref[0, :][:, None]
                v = v * vs_ref[0, :][:, None]
            s = jax.lax.dot_general(
                qb.astype(jnp.float32), k,
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * sm_scale
            col = jax.lax.broadcasted_iota(
                jnp.int32, (group, page_size), 1)
            s = jnp.where(col < ctx - j * page_size, s,
                          _MASK_VALUE)           # [group, page_size]
            m_prev = m_ref[:, :1]
            l_prev = l_ref[:, :1]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1,
                                                keepdims=True))
            alpha = jnp.exp(m_prev - m_new)
            p = jnp.exp(s - m_new)
            l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
            acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
                p, v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
            l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

        @pl.when(j == pages_per_seq - 1)
        def _finalize():
            l = l_ref[:, :1]
            l_safe = jnp.where(l == 0.0, 1.0, l)  # empty slot → zeros
            o_ref[0, 0] = (acc_ref[...] / l_safe).astype(o_ref.dtype)

    page_spec = pl.BlockSpec((1, page_size, 1, d),
                             lambda bi, h, j, ctx, tbl: (tbl[bi, j], 0,
                                                         h, 0))
    in_specs = [
        pl.BlockSpec((1, 1, group, d),
                     lambda bi, h, j, ctx, tbl: (bi, h, 0, 0)),
        # the paged gather: this index map IS the block table read
        page_spec,
        page_spec,
    ]
    operands = [lens, tables, qg, k_pages, v_pages]
    if quantized:
        # the page's scale row streams beside its int8 block
        scale_spec = pl.BlockSpec(
            (1, page_size), lambda bi, h, j, ctx, tbl: (tbl[bi, j], 0))
        in_specs += [scale_spec, scale_spec]
        operands += [k_scales.astype(jnp.float32),
                     v_scales.astype(jnp.float32)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, kv_heads, pages_per_seq),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, group, d),
                               lambda bi, h, j, ctx, tbl: (bi, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((group, d), jnp.float32),
            pltpu.VMEM((group, _LANES), jnp.float32),
            pltpu.VMEM((group, _LANES), jnp.float32),
        ],
    )
    # jax renamed TPUCompilerParams -> CompilerParams across versions;
    # accept either so the kernel runs on every toolchain in the image
    _params_cls = getattr(pltpu, "CompilerParams", None) or \
        getattr(pltpu, "TPUCompilerParams")
    out_dtype = q.dtype
    out = pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, kv_heads, group, d),
                                       out_dtype),
        compiler_params=_params_cls(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*operands)
    return out.reshape(b, n_heads, d)


def ragged_paged_attention(q, kv_k: KVStore, kv_v: KVStore,
                           token_tables, token_lens,
                           scale: Optional[float] = None,
                           impl: str = "xla"):
    """THE ragged paged-attention entry point: ONE op serving every
    attention shape the engine dispatches — single-token decodes,
    chunked-prefill suffixes, speculative-verify windows, and a MIXED
    batch of all of them at once (the Ragged Paged Attention
    formulation, PAPERS.md #1) — over a plain OR int8-quantized
    (:class:`QuantizedKV`) paged pool.

    q: [T, heads, d] — T tokens drawn from ANY mix of sequences;
    token_tables: [T, pages_per_seq] — row t is the block table of
    token t's sequence (rows of the same sequence repeat it);
    token_lens: [T] — token t attends the first ``token_lens[t]``
    cached positions of its sequence (its own inclusive; 0 = padding
    or inactive slot -> zero output row). Returns [T, heads, d].
    GQA: heads may be a multiple of kv_heads.

    The T=batch single-token case IS the decode step
    (:func:`paged_attention` aliases here); the rectangular [B, K]
    case flattens to it (:func:`paged_attention_chunk`); causality
    inside a prefill chunk falls out of the per-token limit, because
    a later token of the same sequence has a strictly larger
    ``token_lens`` and earlier chunk tokens' K/V are already
    scattered into the pool. A mixed prefill+decode tick is just a
    batch whose rows happen to come from both phases — nothing in
    the contract distinguishes them, which is what lets the engine
    collapse its alternating tick loop into one dispatch.

    Pure-functional and trace-safe by contract: every input may be a
    traced value, so the op is callable from inside a ``lax.scan``
    body — the engine's fused slab carries the (possibly quantized)
    pool in its :class:`DecodeCarry` and calls this per tick.

    ``impl``: ``"xla"`` (gather + dense masked softmax, f32
    accumulate), ``"pallas"`` (fused kernel streaming one real page
    per grid step, int8 dequantized in VMEM), or ``"reference"``
    (:func:`ragged_paged_attention_reference` — full-f32 exactness
    baseline, kept callable for the int8 tolerance tests)."""
    kp, ks = _split_kv(kv_k)
    vp, vs = _split_kv(kv_v)
    if impl == "pallas":
        return paged_attention_kernel(q, kp, vp, token_tables,
                                      token_lens, scale=scale,
                                      k_scales=ks, v_scales=vs)
    if impl == "reference":
        return ragged_paged_attention_reference(
            q, kv_k, kv_v, token_tables, token_lens,
            scale=scale).astype(q.dtype)
    if impl != "xla":
        raise ValueError(f"unknown impl {impl!r}")
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    # the K=1 case of the gathered core, with limit = token_lens
    # DIRECTLY (a single cached token — limit 1 — still attends)
    out = _gathered_attention(q[:, None], kp, vp, token_tables,
                              token_lens[:, None], scale,
                              k_scales=ks, v_scales=vs)
    return out[:, 0]


def ragged_paged_attention_reference(q, kv_k: KVStore, kv_v: KVStore,
                                     token_tables, token_lens,
                                     scale: Optional[float] = None):
    """f32-accumulate reference path (the exactness baseline): same
    contract as :func:`ragged_paged_attention`, but q, the
    (dequantized) pages, and every intermediate are f32 end to end
    and the result is returned in f32. This is what the int8
    quantization TOLERANCE is measured against in tests and in
    ``llm_bench --kv-dtype``; it is deliberately simple rather than
    fast."""
    kp, ks = _split_kv(kv_k)
    vp, vs = _split_kv(kv_v)
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    out = _gathered_attention(q.astype(jnp.float32)[:, None],
                              kp, vp, token_tables,
                              token_lens[:, None], scale,
                              k_scales=ks, v_scales=vs)
    return out[:, 0]


def paged_attention_chunk(q, k_pages, v_pages, block_tables, base_lens,
                          scale: Optional[float] = None,
                          impl: str = "xla"):
    """Multi-query decode attention over paged KV (the speculative-
    verify / chunked-prefill step): ``q`` carries K NEW tokens per
    sequence whose K/V were just written at positions
    ``base_lens[b] .. base_lens[b]+K-1``; query j attends the first
    ``base_lens[b]+j+1`` cached positions (its own inclusive) —
    causal within the chunk, full context before it.

    q: [B, K, heads, d]; base_lens [B] = valid tokens BEFORE the chunk
    (0 = inactive slot → zero output rows). Returns [B, K, heads, d].

    DEPRECATED ALIAS: the rectangular [B, K] case of
    :func:`ragged_paged_attention` (rows flattened, each carrying its
    sequence's table and its own causal limit) — kept for source
    compatibility; new call sites should use the ragged entry point.
    """
    b, kq, h, d = q.shape
    limit = jnp.where(base_lens[:, None] > 0,
                      base_lens[:, None] + jnp.arange(kq)[None, :] + 1,
                      0)                                  # [B, K]
    out = ragged_paged_attention(
        q.reshape(b * kq, h, d), k_pages, v_pages,
        jnp.repeat(block_tables, kq, axis=0), limit.reshape(-1),
        scale=scale, impl=impl)
    return out.reshape(b, kq, h, d)


def paged_attention_ragged(q, k_pages, v_pages, token_tables,
                           token_lens, scale: Optional[float] = None,
                           impl: str = "xla"):
    """DEPRECATED ALIAS of :func:`ragged_paged_attention` (the entry
    point subsumed it verbatim — same contract, same shapes); kept
    for source compatibility with pre-consolidation call sites."""
    return ragged_paged_attention(q, k_pages, v_pages, token_tables,
                                  token_lens, scale=scale, impl=impl)


def paged_attention(q, k_pages, v_pages, block_tables, context_lens,
                    scale: Optional[float] = None, impl: str = "xla"):
    """Single-query attention over paged KV (the decode step).

    q: [B, heads, d]; k/v_pages: [num_pages, page_size, kv_heads, d]
    (or a :class:`QuantizedKV`); block_tables: [B, pages_per_seq]
    page ids (-1 pads); context_lens: [B] valid token counts.
    Returns [B, heads, d]. GQA: heads may be a multiple of kv_heads.

    DEPRECATED ALIAS: the T=batch single-token case of
    :func:`ragged_paged_attention` — the shapes are literally the
    ragged contract already (one table row and one limit per query
    token), so this delegates unchanged. Trace-safety contract
    unchanged: callable from inside a ``lax.scan`` body."""
    return ragged_paged_attention(q, k_pages, v_pages, block_tables,
                                  context_lens, scale=scale, impl=impl)


def _gathered_attention(q, k_pages, v_pages, block_tables, limit,
                        scale, k_scales=None, v_scales=None):
    """Shared decode-attention core: gather the block table's pages,
    dequantize (optional per-row scales), expand GQA, masked fp32
    softmax. q [B, K, H, d]; limit [B, K] = attendable cached
    positions per query (0 → zero output row)."""
    b, kq, n_heads, d = q.shape
    _, page_size, kv_heads, _ = k_pages.shape
    pages_per_seq = block_tables.shape[1]

    tables = jnp.clip(block_tables, 0)               # [B, P]
    k = jnp.take(k_pages, tables, axis=0)            # [B, P, ps, KVH, d]
    v = jnp.take(v_pages, tables, axis=0)
    if k_scales is not None:
        # int8 pool: dequantize the gathered rows (scale per page row)
        k = k.astype(jnp.float32) * \
            jnp.take(k_scales, tables, axis=0)[..., None, None]
        v = v.astype(jnp.float32) * \
            jnp.take(v_scales, tables, axis=0)[..., None, None]
    L = pages_per_seq * page_size
    k = k.reshape(b, L, kv_heads, d)
    v = v.reshape(b, L, kv_heads, d)
    if n_heads != kv_heads:
        rep = n_heads // kv_heads
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)

    logits = jnp.einsum("bqhd,blhd->bhql", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale   # [B,H,K,L]
    mask = jnp.arange(L)[None, None, :] < limit[:, :, None]  # [B,K,L]
    logits = jnp.where(mask[:, None], logits, -jnp.inf)
    p = jax.nn.softmax(logits, axis=-1)
    # fully-masked rows (limit 0, e.g. a freed slot): zeros, not NaN
    p = jnp.where(limit[:, None, :, None] > 0, p, 0.0)
    out = jnp.einsum("bhql,blhd->bqhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)
