"""Paged attention: decode-time attention over a block-paged KV cache.

Reference context: the reference's serving attention keeps one dense
[b, max_len, h, d] cache per request (fused_multi_transformer_op.cu);
continuous batching then wastes HBM on the padding between each
request's true length and max_len. The paged formulation (vLLM;
"Ragged Paged Attention" for TPU, arXiv:2604.15464 in PAPERS.md) stores
KV in fixed-size PAGES shared across requests, with a per-request block
table mapping logical positions to pages — HBM waste bounded by one
page per sequence.

TPU-native design: pages are gathered per request with one take() (XLA
lowers to a dynamic-gather the TPU does well at page granularity —
contiguous [page_size, kv_heads, d] blocks), then attention runs as
dense SDPA with a context-length mask. Static shapes throughout
(pages_per_seq is the compiled maximum; short sequences mask). The
fancy kernel in the paper fuses the gather into the attention loop —
that is a later Pallas optimization; this implementation fixes the
MEMORY model, which is the serving win, and is numerically exact.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import _LANES, _on_cpu
from .flash_attention import DEFAULT_MASK_VALUE as _MASK_VALUE


class PagedKVCache:
    """Page-pool KV storage + per-request block tables.

    k/v pages: [num_pages, page_size, kv_heads, head_dim]; block table
    [max_seqs, pages_per_seq] of page ids (-1 = unallocated);
    context_lens [max_seqs]. Host-side allocation (serving control
    plane), device-side tensors."""

    def __init__(self, num_pages: int, page_size: int, kv_heads: int,
                 head_dim: int, max_seqs: int, pages_per_seq: int,
                 dtype=jnp.float32):
        self.page_size = page_size
        self.k_pages = jnp.zeros((num_pages, page_size, kv_heads,
                                  head_dim), dtype)
        self.v_pages = jnp.zeros_like(self.k_pages)
        self.block_tables = jnp.full((max_seqs, pages_per_seq), -1,
                                     jnp.int32)
        self.context_lens = jnp.zeros((max_seqs,), jnp.int32)
        self._free = list(range(num_pages - 1, -1, -1))

    def allocate(self, seq: int, n_tokens: int) -> None:
        """Reserve pages for n_tokens of sequence ``seq``."""
        need = -(-n_tokens // self.page_size)
        if need > self.block_tables.shape[1]:
            raise ValueError(
                f"sequence {seq} needs {need} pages but the block "
                f"table holds {self.block_tables.shape[1]} "
                f"(pages_per_seq); raise pages_per_seq or evict")
        have = int((self.block_tables[seq] >= 0).sum())
        for slot in range(have, need):
            if not self._free:
                raise RuntimeError("KV page pool exhausted")
            page = self._free.pop()
            self.block_tables = self.block_tables.at[seq, slot].set(page)

    def free(self, seq: int) -> None:
        for pid in [int(p) for p in self.block_tables[seq] if p >= 0]:
            self._free.append(pid)
        self.block_tables = self.block_tables.at[seq].set(-1)
        self.context_lens = self.context_lens.at[seq].set(0)

    def append(self, seq: int, k_new, v_new) -> None:
        """Write [t, kv_heads, d] new tokens at the sequence's end.
        Tokens are written one contiguous slice per TOUCHED PAGE (a
        per-token .at[].set would copy the whole pool per token)."""
        t = int(k_new.shape[0])
        start = int(self.context_lens[seq])
        self.allocate(seq, start + t)
        ps = self.page_size
        i = 0
        while i < t:
            pos = start + i
            page = int(self.block_tables[seq, pos // ps])
            off = pos % ps
            span = min(ps - off, t - i)
            self.k_pages = self.k_pages.at[page, off:off + span].set(
                k_new[i:i + span])
            self.v_pages = self.v_pages.at[page, off:off + span].set(
                v_new[i:i + span])
            i += span
        self.context_lens = self.context_lens.at[seq].set(start + t)


def paged_attention_kernel(q, k_pages, v_pages, block_tables,
                           context_lens, scale: Optional[float] = None,
                           interpret: Optional[bool] = None):
    """Fused Pallas decode attention over paged KV (the "fancy kernel"
    the module docstring deferred; Ragged-Paged-Attention lineage).

    Same contract as :func:`paged_attention`. The difference is the
    memory traffic: the XLA path GATHERS every sequence's full padded
    context ([B, pages_per_seq*page_size, H, D]) into HBM before the
    dense attention reads it again; here the kernel's BlockSpec index
    map reads the SCALAR-PREFETCHED block table directly, so each grid
    step streams exactly one real page from the pool into VMEM —
    traffic scales with the true context length (``pl.when`` skips
    pages past it entirely), and nothing is materialized in between.

    Grid: (batch, kv_heads, pages_per_seq); the page dim is sequential
    so the online-softmax scratch (acc/m/l) carries across it. GQA is
    native: the q block per kv head is its [group, D] query rows
    (group = heads // kv_heads), matching the repeat-kv convention.
    """
    if interpret is None:
        interpret = _on_cpu()  # same convention as flash_attention
    b, n_heads, d = q.shape
    n_pages, page_size, kv_heads, _ = k_pages.shape
    pages_per_seq = block_tables.shape[1]
    group = n_heads // kv_heads
    sm_scale = scale if scale is not None else 1.0 / math.sqrt(d)

    qg = q.reshape(b, kv_heads, group, d)
    tables = jnp.clip(block_tables, 0).astype(jnp.int32)
    lens = context_lens.astype(jnp.int32)

    def kernel(ctx_ref, tbl_ref, q_ref, k_ref, v_ref, o_ref,
               acc_ref, m_ref, l_ref):
        bi = pl.program_id(0)
        j = pl.program_id(2)

        @pl.when(j == 0)
        def _init():
            acc_ref[...] = jnp.zeros_like(acc_ref)
            m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
            l_ref[...] = jnp.zeros_like(l_ref)

        ctx = ctx_ref[bi]

        @pl.when(j * page_size < ctx)
        def _compute():
            qb = q_ref[0, 0]                     # [group, d]
            k = k_ref[0, :, 0, :]                # [page_size, d]
            v = v_ref[0, :, 0, :]
            s = jax.lax.dot_general(
                qb.astype(jnp.float32), k.astype(jnp.float32),
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * sm_scale
            col = jax.lax.broadcasted_iota(
                jnp.int32, (group, page_size), 1)
            s = jnp.where(col < ctx - j * page_size, s,
                          _MASK_VALUE)           # [group, page_size]
            m_prev = m_ref[:, :1]
            l_prev = l_ref[:, :1]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1,
                                                keepdims=True))
            alpha = jnp.exp(m_prev - m_new)
            p = jnp.exp(s - m_new)
            l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
            acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
                p, v.astype(jnp.float32), (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
            l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

        @pl.when(j == pages_per_seq - 1)
        def _finalize():
            l = l_ref[:, :1]
            l_safe = jnp.where(l == 0.0, 1.0, l)  # empty slot → zeros
            o_ref[0, 0] = (acc_ref[...] / l_safe).astype(o_ref.dtype)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, kv_heads, pages_per_seq),
        in_specs=[
            pl.BlockSpec((1, 1, group, d),
                         lambda bi, h, j, ctx, tbl: (bi, h, 0, 0)),
            # the paged gather: this index map IS the block table read
            pl.BlockSpec((1, page_size, 1, d),
                         lambda bi, h, j, ctx, tbl: (tbl[bi, j], 0, h,
                                                     0)),
            pl.BlockSpec((1, page_size, 1, d),
                         lambda bi, h, j, ctx, tbl: (tbl[bi, j], 0, h,
                                                     0)),
        ],
        out_specs=pl.BlockSpec((1, 1, group, d),
                               lambda bi, h, j, ctx, tbl: (bi, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((group, d), jnp.float32),
            pltpu.VMEM((group, _LANES), jnp.float32),
            pltpu.VMEM((group, _LANES), jnp.float32),
        ],
    )
    # jax renamed TPUCompilerParams -> CompilerParams across versions;
    # accept either so the kernel runs on every toolchain in the image
    _params_cls = getattr(pltpu, "CompilerParams", None) or \
        getattr(pltpu, "TPUCompilerParams")
    out = pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, kv_heads, group, d), q.dtype),
        compiler_params=_params_cls(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(lens, tables, qg, k_pages, v_pages)
    return out.reshape(b, n_heads, d)


def paged_attention_chunk(q, k_pages, v_pages, block_tables, base_lens,
                          scale: Optional[float] = None):
    """Multi-query decode attention over paged KV (the speculative-
    verify / chunked-prefill step): ``q`` carries K NEW tokens per
    sequence whose K/V were just written at positions
    ``base_lens[b] .. base_lens[b]+K-1``; query j attends the first
    ``base_lens[b]+j+1`` cached positions (its own inclusive) —
    causal within the chunk, full context before it.

    q: [B, K, heads, d]; base_lens [B] = valid tokens BEFORE the chunk
    (0 = inactive slot → zero output rows). Returns [B, K, heads, d].
    """
    kq, d = q.shape[1], q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    limit = jnp.where(base_lens[:, None] > 0,
                      base_lens[:, None] + jnp.arange(kq)[None, :] + 1,
                      0)                                  # [B, K]
    return _gathered_attention(q, k_pages, v_pages, block_tables,
                               limit, scale)


def paged_attention_ragged(q, k_pages, v_pages, token_tables,
                           token_lens, scale: Optional[float] = None,
                           impl: str = "xla"):
    """Ragged prefill attention over paged KV: ``q`` carries T tokens
    drawn from ANY mix of sequences (a chunked-prefill tick packs one
    or more prompts' uncached suffixes into one fixed-size chunk), each
    token carrying its OWN block-table row and attendable length.

    q: [T, heads, d]; token_tables: [T, pages_per_seq] — row t is the
    block table of token t's sequence; token_lens: [T] — token t
    attends the first ``token_lens[t]`` cached positions of its
    sequence (its own inclusive; 0 = padding token -> zero output).
    Returns [T, heads, d].

    This is the ragged generalization of :func:`paged_attention` (the
    T=batch case where all of a row's tokens share one table) and of
    :func:`paged_attention_chunk` (the rectangular [B, K] case):
    causality inside a chunk falls out of the per-token limit, because
    a later token of the same sequence has a strictly larger
    ``token_lens`` and earlier chunk tokens' K/V are already scattered
    into the pool. ``impl="pallas"`` routes through the fused kernel
    (:func:`paged_attention_kernel`), whose contract is identical —
    each grid row reads its own prefetched table row."""
    if impl == "pallas":
        return paged_attention_kernel(q, k_pages, v_pages, token_tables,
                                      token_lens, scale=scale)
    return paged_attention(q, k_pages, v_pages, token_tables,
                           token_lens, scale=scale)


def paged_attention(q, k_pages, v_pages, block_tables, context_lens,
                    scale: Optional[float] = None, impl: str = "xla"):
    """Single-query attention over paged KV (the decode step).

    q: [B, heads, d]; k/v_pages: [num_pages, page_size, kv_heads, d];
    block_tables: [B, pages_per_seq] page ids (-1 pads);
    context_lens: [B] valid token counts. Returns [B, heads, d].
    GQA: heads may be a multiple of kv_heads.

    Pure-functional and trace-safe by contract: every input may be a
    traced value, so the op is callable from inside a ``lax.scan``
    body — the fused decode slab (``LLMEngine``'s device-resident
    tick loop) carries block tables and context lengths as scan
    state and calls this per tick. ``impl="pallas"`` routes through
    the fused kernel (:func:`paged_attention_kernel`) under the same
    contract, mirroring :func:`paged_attention_ragged`."""
    if impl == "pallas":
        return paged_attention_kernel(q, k_pages, v_pages,
                                      block_tables, context_lens,
                                      scale=scale)
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    # the K=1 case of the chunk core, with limit = context_lens
    # DIRECTLY (so a single cached token — limit 1 — still attends,
    # unlike the chunk's base-exclusive convention)
    out = _gathered_attention(q[:, None], k_pages, v_pages,
                              block_tables, context_lens[:, None],
                              scale)
    return out[:, 0]


def _gathered_attention(q, k_pages, v_pages, block_tables, limit,
                        scale):
    """Shared decode-attention core: gather the block table's pages,
    expand GQA, masked fp32 softmax. q [B, K, H, d]; limit [B, K] =
    attendable cached positions per query (0 → zero output row)."""
    b, kq, n_heads, d = q.shape
    _, page_size, kv_heads, _ = k_pages.shape
    pages_per_seq = block_tables.shape[1]

    tables = jnp.clip(block_tables, 0)               # [B, P]
    k = jnp.take(k_pages, tables, axis=0)            # [B, P, ps, KVH, d]
    v = jnp.take(v_pages, tables, axis=0)
    L = pages_per_seq * page_size
    k = k.reshape(b, L, kv_heads, d)
    v = v.reshape(b, L, kv_heads, d)
    if n_heads != kv_heads:
        rep = n_heads // kv_heads
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)

    logits = jnp.einsum("bqhd,blhd->bhql", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale   # [B,H,K,L]
    mask = jnp.arange(L)[None, None, :] < limit[:, :, None]  # [B,K,L]
    logits = jnp.where(mask[:, None], logits, -jnp.inf)
    p = jax.nn.softmax(logits, axis=-1)
    # fully-masked rows (limit 0, e.g. a freed slot): zeros, not NaN
    p = jnp.where(limit[:, None, :, None] > 0, p, 0.0)
    out = jnp.einsum("bhql,blhd->bqhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)
