"""Flash attention as a Pallas TPU kernel.

TPU-native replacement for the reference's fused CUDA attention
(reference: paddle/fluid/operators/fused/fused_attention_op.cu and
fmha_ref.h — a cuBLAS-batched QK^T → softmax → PV pipeline that
materialises the [b, h, s, s] probability tensor in HBM; and
python/paddle/nn/functional/sparse_attention.py for the long-seq path).

Design (flash attention v2 schedule, mapped to the MXU/VMEM model):
- online softmax: never materialise [s, s]; running (m, l, acc) live in
  VMEM scratch that persists across the innermost (sequential) grid dim.
- grid = (batch, q_heads, q_blocks, k_blocks); the k dimension is
  ``ARBITRARY`` (sequential) so scratch carries across it, the rest are
  ``PARALLEL``.
- causal masking skips fully-masked k-blocks via ``pl.when`` (no FLOPs
  issued) and applies an iota mask only on diagonal blocks.
- grouped-query attention: kv heads may divide q heads; the k/v index
  maps fold the head group in, so no materialised repeat_kv.
- backward = two kernels (dq; dk/dv) recomputing probabilities from the
  saved logsumexp — the standard recompute schedule that trades FLOPs
  for HBM bandwidth, which is the right trade on TPU. The D term
  (rowsum(do*o)) is computed in-kernel from the o/do blocks.
- the logsumexp residual is stored lane-replicated ([b, h, s, 128]) to
  satisfy the (8, 128) VMEM tiling of the vector units.

Layout: [batch, heads, seq, head_dim] inside the kernels (callers using
BSHD transpose at the boundary; XLA fuses the transposes).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed TPUCompilerParams -> CompilerParams across versions;
# accept either so the kernel runs on every toolchain in the image
_CompilerParams = getattr(pltpu, "CompilerParams", None) or \
    getattr(pltpu, "TPUCompilerParams")

DEFAULT_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)
_LANES = 128  # VPU lane width: row-statistics are stored lane-replicated


def _on_cpu() -> bool:
    return jax.default_backend() == "cpu"


def _pick_block(seq: int, target: int) -> int:
    """Largest power-of-two divisor of ``seq`` that is <= target."""
    b = 1
    while b * 2 <= min(seq, target) and seq % (b * 2) == 0:
        b *= 2
    return b


def _causal_mask(s, qi, kj, block_q, block_k, offset):
    """Bottom-right-aligned causal mask: query i attends keys <= i + offset
    where offset = s_k - s_q (matches the fallback's tril(..., kl - ql))."""
    row = qi * block_q + offset + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    col = kj * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    return jnp.where(row >= col, s, DEFAULT_MASK_VALUE)


def _dot(a, b, trans_a=False, trans_b=False):
    dims = (((0,) if trans_a else (1,), (1,) if trans_b else (0,)), ((), ()))
    return jax.lax.dot_general(a, b, dims,
                               preferred_element_type=jnp.float32)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref, *,
                sm_scale: float, causal: bool, offset: int,
                block_q: int, block_k: int,
                num_k_blocks: int):
    qi = pl.program_id(2)
    kj = pl.program_id(3)

    @pl.when(kj == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)

    # causal: a k-block strictly above the diagonal contributes nothing
    should_run = True
    if causal:
        should_run = block_q * qi + block_q - 1 + offset >= block_k * kj

    @pl.when(should_run)
    def _compute():
        q = q_ref[0, 0]  # [block_q, d]
        k = k_ref[0, 0]  # [block_k, d]
        v = v_ref[0, 0]
        s = _dot(q, k, trans_b=True) * sm_scale  # [bq, bk] f32
        if causal:
            s = _causal_mask(s, qi, kj, block_q, block_k, offset)
        m_prev = m_ref[:, :1]                          # [bq, 1]
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)                # rescale old state
        p = jnp.exp(s - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + _dot(p.astype(v.dtype), v)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(kj == num_k_blocks - 1)
    def _finalize():
        l = l_ref[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)  # fully-masked-row guard
        o_ref[0, 0] = (acc_ref[...] / l_safe).astype(o_ref.dtype)
        lse_ref[0, 0] = jnp.broadcast_to(m_ref[:, :1] + jnp.log(l_safe),
                                         lse_ref.shape[2:])


def _fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    b, hq, sq, d = q.shape
    _, hkv, sk, _ = k.shape
    group = hq // hkv
    nq = sq // block_q
    nk = sk // block_k

    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal, offset=sk - sq,
        block_q=block_q, block_k=block_k, num_k_blocks=nk)

    out, lse = pl.pallas_call(
        kernel,
        grid=(b, hq, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b_, h, i, j: (b_, h, i, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h, i, j: (b_, h // group, j, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h, i, j: (b_, h // group, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b_, h, i, j: (b_, h, i, 0)),
            pl.BlockSpec((1, 1, block_q, _LANES),
                         lambda b_, h, i, j: (b_, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, hq, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b, hq, sq, _LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(q, k, v)
    return out, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, dq_ref,
                   dq_acc, delta_ref, *, sm_scale, causal, offset,
                   block_q, block_k,
                   num_k_blocks):
    qi = pl.program_id(2)
    kj = pl.program_id(3)

    @pl.when(kj == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)
        o = o_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        delta_ref[...] = jnp.broadcast_to(
            jnp.sum(o * do, axis=-1, keepdims=True), delta_ref.shape)

    should_run = True
    if causal:
        should_run = block_q * qi + block_q - 1 + offset >= block_k * kj

    @pl.when(should_run)
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, :1]          # [bq, 1]
        delta = delta_ref[:, :1]
        s = _dot(q, k, trans_b=True) * sm_scale
        if causal:
            s = _causal_mask(s, qi, kj, block_q, block_k, offset)
        p = jnp.exp(s - lse)                # [bq, bk]
        dp = _dot(do, v.astype(jnp.float32), trans_b=True)
        ds = p * (dp - delta) * sm_scale
        dq_acc[...] += _dot(ds, k.astype(jnp.float32))

    @pl.when(kj == num_k_blocks - 1)
    def _finalize():
        dq_ref[0, 0] = dq_acc[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *,
                    sm_scale, causal, offset, block_q, block_k,
                    num_q_blocks):
    kj = pl.program_id(2)
    qi = pl.program_id(3)

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    should_run = True
    if causal:
        should_run = block_q * qi + block_q - 1 + offset >= block_k * kj

    @pl.when(should_run)
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        o = o_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, :1]
        delta = jnp.sum(o * do, axis=-1, keepdims=True)   # [bq, 1]
        s = _dot(q, k, trans_b=True) * sm_scale
        if causal:
            s = _causal_mask(s, qi, kj, block_q, block_k, offset)
        p = jnp.exp(s - lse)                 # [bq, bk]
        dv_acc[...] += _dot(p, do, trans_a=True)
        dp = _dot(do, v.astype(jnp.float32), trans_b=True)
        ds = p * (dp - delta) * sm_scale     # [bq, bk]
        dk_acc[...] += _dot(ds, q.astype(jnp.float32), trans_a=True)

    @pl.when(qi == num_q_blocks - 1)
    def _finalize():
        dk_ref[0, 0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[...].astype(dv_ref.dtype)


def _bwd(q, k, v, out, lse, do, sm_scale, causal, block_q, block_k,
         interpret):
    b, hq, sq, d = q.shape
    _, hkv, sk, _ = k.shape
    group = hq // hkv
    nq = sq // block_q
    nk = sk // block_k

    qspec = pl.BlockSpec((1, 1, block_q, d), lambda b_, h, i, j: (b_, h, i, 0))
    kvspec = pl.BlockSpec((1, 1, block_k, d),
                          lambda b_, h, i, j: (b_, h // group, j, 0))
    lspec = pl.BlockSpec((1, 1, block_q, _LANES),
                         lambda b_, h, i, j: (b_, h, i, 0))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
                          offset=sk - sq, block_q=block_q, block_k=block_k,
                          num_k_blocks=nk),
        grid=(b, hq, nq, nk),
        in_specs=[qspec, kvspec, kvspec, qspec, qspec, lspec],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32),
                        pltpu.VMEM((block_q, _LANES), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(q, k, v, out, do, lse)

    # dk/dv: grid iterates q-blocks sequentially per (q-head, k-block);
    # per-q-head partials are reduced over the GQA group afterwards.
    qspec_t = pl.BlockSpec((1, 1, block_q, d),
                           lambda b_, h, j, i: (b_, h, i, 0))
    kvspec_t = pl.BlockSpec((1, 1, block_k, d),
                            lambda b_, h, j, i: (b_, h // group, j, 0))
    lspec_t = pl.BlockSpec((1, 1, block_q, _LANES),
                           lambda b_, h, j, i: (b_, h, i, 0))
    okv_t = pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h, j, i: (b_, h, j, 0))

    dk_g, dv_g = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, sm_scale=sm_scale, causal=causal,
                          offset=sk - sq, block_q=block_q, block_k=block_k,
                          num_q_blocks=nq),
        grid=(b, hq, nk, nq),
        in_specs=[qspec_t, kvspec_t, kvspec_t, qspec_t, qspec_t, lspec_t],
        out_specs=[okv_t, okv_t],
        out_shape=[
            jax.ShapeDtypeStruct((b, hq, sk, d), jnp.float32),
            jax.ShapeDtypeStruct((b, hq, sk, d), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(q, k, v, out, do, lse)

    if group > 1:
        dk_g = dk_g.reshape(b, hkv, group, sk, d).sum(axis=2)
        dv_g = dv_g.reshape(b, hkv, group, sk, d).sum(axis=2)
    return dq, dk_g.astype(k.dtype), dv_g.astype(v.dtype)


# ---------------------------------------------------------------------------
# public API with custom VJP
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    out, _ = _fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret)
    return out


def _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    out, lse = _fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd(sm_scale, causal, block_q, block_k, interpret, res, do):
    q, k, v, out, lse = res
    return _bwd(q, k, v, out, lse, do, sm_scale, causal, block_q, block_k,
                interpret)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, *, causal: bool = False,
                    sm_scale: Optional[float] = None,
                    block_q: int = 512, block_k: int = 512,
                    interpret: Optional[bool] = None):
    """Memory-efficient attention. q: [b, s_q, h, d]; k/v: [b, s_k, h_kv, d]
    with h % h_kv == 0 (grouped-query). Returns [b, s_q, h, d].

    Differentiable (custom VJP with flash backward kernels). BSHD in/out;
    internally runs BHSD tiles on the MXU.
    """
    if interpret is None:
        interpret = _on_cpu()
    b, sq, hq, d = q.shape
    _, sk, hkv, _ = k.shape
    if hq % hkv != 0:
        raise ValueError(f"q heads {hq} not a multiple of kv heads {hkv}")
    if causal and sq > sk:
        raise ValueError(
            f"causal flash attention requires s_q <= s_k, got {sq} > {sk}: "
            "leading query rows would have no visible keys")
    sm_scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    bq = _pick_block(sq, block_q)
    bk = _pick_block(sk, block_k)
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    out = _flash(qt, kt, vt, sm_scale, causal, bq, bk, interpret)
    return out.transpose(0, 2, 1, 3)


def flash_attention_available(q_shape, k_shape, attn_mask, dropout_p,
                              training, is_causal: bool = False) -> bool:
    """Whether the Pallas path handles this configuration."""
    if attn_mask is not None:
        return False
    if dropout_p > 0.0 and training:
        return False
    if len(q_shape) != 4:
        return False
    b, sq, hq, d = q_shape
    sk, hkv = k_shape[1], k_shape[2]
    if hq % hkv != 0:
        return False
    if is_causal and sq > sk:
        # degenerate: leading query rows have no visible keys (the
        # reference math yields NaN rows); keep that on the XLA path
        return False
    # tiny shapes: the reference path is cheaper than kernel launch; odd
    # lengths would force sub-(8,128) tiles that Mosaic rejects — require
    # that a full-size power-of-two block divides both sequence lengths
    return (d >= 64 and d % 8 == 0 and
            _pick_block(sq, 512) >= 128 and _pick_block(sk, 512) >= 128)
