"""Minimal Program/Variable world for the ``paddle.static`` surface.

Reference being replaced: python/paddle/fluid/framework.py ``Program``
(:4865) / ``Variable`` and executor.py ``Executor.run`` — a
ProgramDesc/OpDesc IR interpreted by C++ executors. The TPU redesign
keeps ONE world (SURVEY.md L5: tracing → XLA HLO is the IR); this
module provides the static API *shape* on top of it: ``static.data``
makes symbolic Variables, static ops build a closure DAG, and
``Executor.run`` evaluates requested fetches under ``jax.jit`` with the
feed dict — so a reference static-graph script runs unchanged, but the
"program" compiles through exactly the same XLA path as everything
else. Parameters live on the Program (the Scope analog) and persist
across run() calls, giving static-graph training the same state
semantics the reference's scope-owned persistables had.
"""

from __future__ import annotations

import contextlib
import pickle
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np


class Variable:
    """Symbolic node: either a feed placeholder (``name``), a parameter
    handle, or an op output (``fn`` over ``deps``). Ref:
    fluid/framework.py Variable."""

    _ctr = 0

    def __init__(self, name=None, shape=None, dtype=None, fn=None,
                 deps=(), param=False):
        if name is None:
            Variable._ctr += 1
            name = f"_var_{Variable._ctr}"
        self.name = name
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.fn = fn
        self.deps = tuple(deps)
        self.is_parameter = param
        self.persistable = param
        self.stop_gradient = not param

    # -- evaluation ---------------------------------------------------------
    def _eval(self, feeds: Dict[str, Any], prog: "Program",
              cache: Dict[int, Any]):
        if id(self) in cache:
            return cache[id(self)]
        if self.is_parameter:
            val = prog.state[self.name]
        elif self.fn is not None:
            args = [d._eval(feeds, prog, cache) if isinstance(d, Variable)
                    else d for d in self.deps]
            val = self.fn(*args)
        else:
            if self.name not in feeds:
                raise KeyError(f"feed missing for '{self.name}'")
            val = jnp.asarray(feeds[self.name])
        cache[id(self)] = val
        return val

    def __repr__(self):
        kind = ("param" if self.is_parameter
                else "op" if self.fn else "data")
        return f"Variable({self.name!r}, {kind}, shape={self.shape})"


def _op(fn: Callable, *deps, shape=None, dtype=None) -> Variable:
    """Register an op node in the current program."""
    v = Variable(shape=shape, dtype=dtype, fn=fn, deps=deps)
    default_main_program()._vars.append(v)
    return v


class Program:
    """ref: fluid/framework.py:4865. Holds parameters (the Scope
    analog), symbolic vars, and the RNG for initializers."""

    def __init__(self):
        self.state: Dict[str, jnp.ndarray] = {}
        self._vars: List[Variable] = []
        self.random_seed = 0

    def global_block(self):
        return self

    # Block-API compat: iterate vars
    def all_parameters(self):
        return [v for v in self._vars if v.is_parameter]

    def list_vars(self):
        return list(self._vars)

    def clone(self, for_test: bool = False) -> "Program":
        p = Program()
        p.state = self.state          # shared persistables (ref semantics)
        p._vars = list(self._vars)
        return p


_main_program = Program()
_startup_program = Program()


def default_main_program() -> Program:
    return _main_program


def default_startup_program() -> Program:
    return _startup_program


@contextlib.contextmanager
def program_guard(main_program: Program, startup_program=None):
    global _main_program, _startup_program
    old_m, old_s = _main_program, _startup_program
    _main_program = main_program
    if startup_program is not None:
        _startup_program = startup_program
    try:
        yield
    finally:
        _main_program, _startup_program = old_m, old_s


@contextlib.contextmanager
def name_scope(prefix: str = None):
    """ref: framework.py name_scope — naming only; HLO metadata via
    jax.named_scope."""
    with jax.named_scope(prefix or "scope"):
        yield


@contextlib.contextmanager
def device_guard(device: str = None):
    """ref: framework.py device_guard. Placement is XLA's job on TPU;
    the guard is accepted and recorded as a no-op (decision: SURVEY §7
    — no per-op device pinning inside one XLA program)."""
    yield


class Scope(dict):
    def find_var(self, name):
        return self.get(name)

    def var(self, name):
        return self.setdefault(name, None)


_global_scope = Scope()


def global_scope() -> Scope:
    return _global_scope


@contextlib.contextmanager
def scope_guard(scope: Scope):
    global _global_scope
    old = _global_scope
    _global_scope = scope
    try:
        yield
    finally:
        _global_scope = old


# -- graph-building primitives ----------------------------------------------

def data(name: str, shape, dtype="float32", lod_level=0) -> Variable:
    """Feed placeholder (ref: static/input.py data)."""
    v = Variable(name=name, shape=shape, dtype=dtype)
    default_main_program()._vars.append(v)
    return v


def _initialize(shape, initializer, seed_name: str):
    from ..core import rng as _rng
    from ..nn import initializer as I
    init = initializer or I.XavierUniform()
    return init(list(shape), jnp.float32)


def create_parameter(shape, dtype="float32", name=None,
                     initializer=None, attr=None,
                     is_bias=False, default_initializer=None) -> Variable:
    """ref: static/__init__ create_parameter → LayerHelper. The value
    initializes eagerly into the program state."""
    prog = default_main_program()
    v = Variable(name=name, shape=shape, dtype=dtype, param=True)
    prog._vars.append(v)
    prog.state[v.name] = jnp.asarray(
        _initialize(shape, initializer or default_initializer, v.name),
        dtype)
    return v


def create_global_var(shape, value, dtype="float32", persistable=False,
                      name=None) -> Variable:
    prog = default_main_program()
    v = Variable(name=name, shape=shape, dtype=dtype, param=True)
    v.persistable = persistable
    prog._vars.append(v)
    prog.state[v.name] = jnp.full(tuple(shape), value, dtype)
    return v


def Print(input: Variable, first_n=-1, message=None, summarize=20,
          **_kw) -> Variable:
    """Debug print at evaluation (ref: layers/control_flow.py Print →
    here jax.debug.print inside the compiled program)."""
    msg = message or input.name

    def fn(x):
        jax.debug.print(msg + ": {}", x)
        return x

    return _op(fn, input, shape=input.shape, dtype=input.dtype)


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Host-python op inside the graph (ref:
    fluid/layers/nn.py py_func) via jax.pure_callback."""
    xs = x if isinstance(x, (list, tuple)) else [x]
    out_spec = out if isinstance(out, (list, tuple)) else [out]

    def fn(*vals):
        shapes = [jax.ShapeDtypeStruct(tuple(o.shape),
                                       jnp.dtype(o.dtype or "float32"))
                  for o in out_spec]
        res = jax.pure_callback(
            lambda *a: func(*a), shapes[0] if len(shapes) == 1
            else tuple(shapes), *vals)
        return res

    v = _op(fn, *xs, shape=out_spec[0].shape, dtype=out_spec[0].dtype)
    return v


# -- gradients --------------------------------------------------------------

def gradients(targets, inputs, target_gradients=None,
              no_grad_set=None) -> List[Variable]:
    """Symbolic grads d(targets)/d(inputs) (ref: fluid/backward.py
    gradients): a grad node per input, evaluated by one jax.grad over
    the closure DAG."""
    tgt = targets if isinstance(targets, (list, tuple)) else [targets]
    ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    prog = default_main_program()

    def make(i):
        def fn(*_ignored):
            # re-evaluate the target as a function of the input values
            raise RuntimeError("grad vars evaluate via Executor.run")
        g = Variable(name=f"{ins[i].name}@GRAD", shape=ins[i].shape,
                     dtype=ins[i].dtype)
        g._grad_spec = (tuple(tgt), ins[i])
        prog._vars.append(g)
        return g

    return [make(i) for i in range(len(ins))]


def append_backward(loss: Variable, parameter_list=None,
                    no_grad_set=None, callbacks=None):
    """ref: fluid/backward.py:1555. Returns [(param_var, grad_var)]."""
    prog = default_main_program()
    params = parameter_list or [v for v in prog._vars if v.is_parameter]
    grads = []
    for p in params:
        g = Variable(name=f"{p.name}@GRAD", shape=p.shape, dtype=p.dtype)
        g._grad_spec = ((loss,), p)
        prog._vars.append(g)
        grads.append((p, g))
    return grads


# -- executor over the closure DAG ------------------------------------------

class StaticExecutor:
    """Evaluate fetches of a Program with feeds (ref:
    fluid/executor.py:621 Executor; the interpretation is one jitted
    closure instead of an op-by-op C++ loop)."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program: Optional[Program] = None, feed=None,
            fetch_list: Sequence[Variable] = (), return_numpy=True):
        prog = program or default_main_program()
        feed = feed or {}
        outs = []
        cache: Dict[int, Any] = {}
        # ONE backward pass serves every grad fetch with the same
        # targets (fetching append_backward's P grads must not cost P
        # backward passes)
        grad_cache: Dict[tuple, Dict[str, Any]] = {}
        for f in fetch_list:
            if isinstance(f, Variable) and hasattr(f, "_grad_spec"):
                targets, wrt = f._grad_spec
                key = tuple(id(t) for t in targets)
                if key not in grad_cache:
                    def loss_fn(state, feeds=feed, targets=targets):
                        tmp = Program()
                        tmp.state = state
                        tmp._vars = prog._vars
                        c: Dict[int, Any] = {}
                        vals = [t._eval(feeds, tmp, c) for t in targets]
                        return sum(jnp.sum(v) for v in vals)

                    grad_cache[key] = jax.grad(loss_fn)(dict(prog.state))
                if wrt.is_parameter:
                    val = grad_cache[key][wrt.name]
                else:
                    raise ValueError(
                        "gradients w.r.t. non-parameter feeds: use "
                        "paddle.grad on a traced function instead")
            elif isinstance(f, Variable):
                val = f._eval(feed, prog, cache)
            else:
                val = f
            outs.append(np.asarray(val) if return_numpy else val)
        return outs


# -- serialization (ref: static/io.py serialize_* / save/load) --------------

def serialize_program(feed_vars=None, fetch_vars=None,
                      program: Optional[Program] = None) -> bytes:
    prog = program or default_main_program()
    meta = [(v.name, v.shape, str(v.dtype), v.is_parameter)
            for v in prog._vars]
    return pickle.dumps(meta)


def deserialize_program(data: bytes) -> Program:
    prog = Program()
    for name, shape, dtype, is_param in pickle.loads(data):
        v = Variable(name=name, shape=shape, dtype=dtype, param=is_param)
        prog._vars.append(v)
    return prog


def serialize_persistables(feed_vars=None, fetch_vars=None,
                           program: Optional[Program] = None) -> bytes:
    prog = program or default_main_program()
    return pickle.dumps({k: np.asarray(v)
                         for k, v in prog.state.items()})


def deserialize_persistables(program: Program, data: bytes,
                             executor=None) -> None:
    program.state.update({k: jnp.asarray(v)
                          for k, v in pickle.loads(data).items()})


def save_to_file(path: str, content: bytes) -> None:
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path: str) -> bytes:
    with open(path, "rb") as f:
        return f.read()


def save(program: Program, model_prefix: str) -> None:
    """ref: static/io.py save — params + program structure."""
    save_to_file(model_prefix + ".pdmodel", serialize_program(
        program=program))
    save_to_file(model_prefix + ".pdiparams", serialize_persistables(
        program=program))


def load(program: Program, model_prefix: str, executor=None,
         var_list=None) -> None:
    deserialize_persistables(
        program, load_from_file(model_prefix + ".pdiparams"))


def load_program_state(model_prefix: str, var_list=None):
    return {k: np.asarray(v) for k, v in pickle.loads(
        load_from_file(model_prefix + ".pdiparams")).items()}


def set_program_state(program: Program, state_dict) -> None:
    program.state.update({k: jnp.asarray(v)
                          for k, v in state_dict.items()})


def normalize_program(program: Program, feed_vars, fetch_vars):
    """ref: static/io.py normalize_program — prune to the fetch
    closure. The closure DAG is already minimal: evaluation only ever
    touches the fetched subgraph, so this returns the program."""
    return program


# -- strategy/compat shells -------------------------------------------------

class BuildStrategy:
    """ref: framework/details/build_strategy.h. Every knob the
    reference exposes (fusion, memory optimize, reduce strategy) is an
    XLA pass decision on TPU — the object exists so configs parse; the
    compiler owns the choices (decision record)."""

    class ReduceStrategy:
        AllReduce, Reduce = 0, 1

    class GradientScaleStrategy:
        CoeffNumDevice, One, Customized = 0, 1, 2

    def __init__(self):
        self.reduce_strategy = self.ReduceStrategy.AllReduce
        self.gradient_scale_strategy = \
            self.GradientScaleStrategy.CoeffNumDevice
        self.memory_optimize = None
        self.enable_inplace = None
        self.fuse_all_reduce_ops = None
        self.fuse_elewise_add_act_ops = None


class ExecutionStrategy:
    """ref: details/execution_strategy.h — thread counts for the SSA
    executors. XLA owns scheduling; kept for config parity."""

    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 100


class CompiledProgram:
    """ref: fluid/compiler.py CompiledProgram — with_data_parallel etc.
    Every Program here is compiled (jit) at run; this wrapper keeps
    scripts working."""

    def __init__(self, program, build_strategy=None):
        self.program = program
        self.build_strategy = build_strategy or BuildStrategy()

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, places=None):
        return self


class ParallelExecutor:
    """ref: framework/parallel_executor.cc. Single-process multi-device
    DP is mesh sharding on TPU (parallel.init_mesh(dp=N)); this shell
    delegates to StaticExecutor for API compat."""

    def __init__(self, use_cuda=False, loss_name=None,
                 main_program=None, build_strategy=None,
                 exec_strategy=None, **_kw):
        self._exe = StaticExecutor()
        self._program = main_program

    def run(self, fetch_list=(), feed=None, return_numpy=True):
        return self._exe.run(self._program, feed=feed,
                             fetch_list=fetch_list,
                             return_numpy=return_numpy)


class WeightNormParamAttr:
    """ref: fluid/param_attr.py WeightNormParamAttr — config carrier;
    the actual reparameterization is nn.utils.weight_norm."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        self.dim = dim
        self.name = name
        self.initializer = initializer
        self.trainable = trainable


class ExponentialMovingAverage:
    """EMA of parameter values (ref: fluid/optimizer.py
    ExponentialMovingAverage, with apply/restore guards). Works on the
    Program state or any dict of arrays."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self.decay = decay
        self._ema: Dict[str, jnp.ndarray] = {}
        self._backup: Dict[str, jnp.ndarray] = {}
        self._step = 0

    def update(self, program: Optional[Program] = None):
        prog = program or default_main_program()
        self._step += 1
        d = min(self.decay, (1.0 + self._step) / (10.0 + self._step))
        for k, v in prog.state.items():
            prev = self._ema.get(k, v)
            self._ema[k] = d * prev + (1.0 - d) * v

    @contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        prog = default_main_program()
        self._backup = dict(prog.state)
        prog.state.update(self._ema)
        try:
            yield
        finally:
            if need_restore:
                prog.state.update(self._backup)

    def restore(self, executor=None):
        default_main_program().state.update(self._backup)


# -- places (ref: static/__init__ cpu_places/cuda_places/...) ---------------

def cpu_places(device_count=None):
    n = device_count or len(jax.devices())
    from ..device import CPUPlace
    return [CPUPlace() for _ in range(n)]


def _accelerator_places(kind):
    """cuda/xpu/npu/mlu places: none exist on a TPU build (the
    reference's is_compiled_with_* story); empty list, not an error."""
    return []


def cuda_places(device_ids=None):
    return _accelerator_places("cuda")


def xpu_places(device_ids=None):
    return _accelerator_places("xpu")


def npu_places(device_ids=None):
    return _accelerator_places("npu")


def mlu_places(device_ids=None):
    return _accelerator_places("mlu")


def exponential_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    """ref: fluid/layers/learning_rate_scheduler.py exponential_decay →
    the modern optimizer.lr.ExponentialDecay."""
    from ..optimizer.lr import ExponentialDecay
    return ExponentialDecay(learning_rate=learning_rate,
                            gamma=decay_rate)


def accuracy(input, label, k=1, correct=None, total=None):
    """Graph-node accuracy (ref: static/__init__ accuracy →
    metrics.accuracy)."""
    def fn(x, y):
        topk = jnp.argsort(x, axis=-1)[..., -k:]
        hit = (topk == y.reshape(-1, 1)).any(-1)
        return hit.astype(jnp.float32).mean()

    return _op(fn, input, label, shape=(), dtype="float32")


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1):
    """Graph-node AUC via the thresholded Riemann sum the metric
    module implements (ref: static/__init__ auc → metrics.auc)."""
    def fn(x, y):
        from ..metric import Auc
        m = Auc(num_thresholds=num_thresholds)
        m.update(np.asarray(x), np.asarray(y))
        return jnp.asarray(m.accumulate(), jnp.float32)

    def host(x, y):
        return jax.pure_callback(
            lambda a, b: np.asarray(fn(a, b), np.float32),
            jax.ShapeDtypeStruct((), jnp.float32), x, y)

    v = _op(host, input, label, shape=(), dtype="float32")
    return v, None, [v]


def ctr_metric_bundle(input, label):
    """ref: static/__init__ ctr_metric_bundle (AUC + MAE/RMSE bundle
    for CTR): returns (auc_var, mae_var, rmse_var)."""
    a, _, _ = auc(input, label)
    mae = _op(lambda x, y: jnp.abs(x - y.astype(x.dtype)).mean(),
              input, label, shape=(), dtype="float32")
    rmse = _op(lambda x, y: jnp.sqrt(
        ((x - y.astype(x.dtype)) ** 2).mean()),
        input, label, shape=(), dtype="float32")
    return a, mae, rmse
