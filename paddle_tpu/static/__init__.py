"""paddle_tpu.static — static-graph facade.

Reference: python/paddle/static/ — Program/Executor world with
``save/load_inference_model`` (static/io.py:435/685), static ``nn``
layers, ``InputSpec``. SURVEY.md §7's design stance: the reference's
dual dygraph/static worlds collapse into ONE traced definition here, so
this module is a thin compatibility facade:

- ``InputSpec`` — shared with paddle_tpu.jit;
- ``save_inference_model`` / ``load_inference_model`` — the deployment
  artifact is jit.save's serialized StableHLO + params;
- ``Executor`` — runs loaded inference programs (the NaiveExecutor-style
  serving loop; the training Executor is Model's compiled step).

There is deliberately no ProgramDesc/BlockDesc IR: XLA HLO is the IR,
produced by tracing (SURVEY.md L5 → jit mapping)."""

from __future__ import annotations

from typing import Sequence

from .. import jit as _jit
from ..jit import InputSpec  # noqa: F401
from . import program as _program
from .program import (  # noqa: F401
    BuildStrategy, CompiledProgram, ExecutionStrategy,
    ExponentialMovingAverage, ParallelExecutor, Print, Program, Scope,
    Variable, WeightNormParamAttr, accuracy, append_backward, auc,
    cpu_places, create_global_var, create_parameter, ctr_metric_bundle,
    cuda_places, data, default_main_program, default_startup_program,
    deserialize_persistables, deserialize_program, device_guard,
    exponential_decay, global_scope, gradients, load, load_from_file,
    load_program_state, mlu_places, name_scope, normalize_program,
    npu_places, program_guard, py_func, save, save_to_file,
    scope_guard, serialize_persistables, serialize_program,
    set_program_state, xpu_places)
from . import nn  # noqa: F401


def save_inference_model(path_prefix: str, feed_vars, fetch_vars=None,
                         executor=None, layer=None,
                         input_spec: Sequence = None, **_ignored):
    """ref: static/io.py:435. TPU form: pass the Layer (or let
    ``feed_vars`` be the Layer for convenience) + input_spec."""
    target = layer if layer is not None else feed_vars
    spec = input_spec or fetch_vars
    if not hasattr(target, "forward") and not callable(target):
        raise ValueError(
            "save_inference_model needs the model Layer: "
            "save_inference_model(path, layer, input_spec=[...])")
    _jit.save(target, path_prefix, input_spec=spec)


def load_inference_model(path_prefix: str, executor=None, **_ignored):
    """ref: static/io.py:685 → returns the loaded callable program."""
    return _jit.load(path_prefix)


class Executor:
    """ref: fluid/executor.py:621 Executor.run. Dispatches on the
    program kind: a static ``Program`` (closure-DAG evaluation, the
    training direction) or a TranslatedLayer from
    load_inference_model (the serving direction)."""

    def __init__(self, place=None):
        self.place = place
        self._static = _program.StaticExecutor(place)

    def run(self, program=None, feed=None, fetch_list=None,
            return_numpy=True):
        if program is None or isinstance(program, _program.Program) or \
                isinstance(program, _program.CompiledProgram):
            if isinstance(program, _program.CompiledProgram):
                program = program.program
            return self._static.run(program, feed=feed,
                                    fetch_list=fetch_list or (),
                                    return_numpy=return_numpy)
        if feed is None:
            raise ValueError("feed required")
        inputs = list(feed.values()) if isinstance(feed, dict) else \
            list(feed)
        out = program(*inputs)
        return out if isinstance(out, (list, tuple)) else [out]


# ref: paddle.static.sparsity re-exports the ASP API (static/sparsity)
from ..incubate import asp as sparsity  # noqa: E402
