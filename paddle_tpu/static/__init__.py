"""paddle_tpu.static — static-graph facade.

Reference: python/paddle/static/ — Program/Executor world with
``save/load_inference_model`` (static/io.py:435/685), static ``nn``
layers, ``InputSpec``. SURVEY.md §7's design stance: the reference's
dual dygraph/static worlds collapse into ONE traced definition here, so
this module is a thin compatibility facade:

- ``InputSpec`` — shared with paddle_tpu.jit;
- ``save_inference_model`` / ``load_inference_model`` — the deployment
  artifact is jit.save's serialized StableHLO + params;
- ``Executor`` — runs loaded inference programs (the NaiveExecutor-style
  serving loop; the training Executor is Model's compiled step).

There is deliberately no ProgramDesc/BlockDesc IR: XLA HLO is the IR,
produced by tracing (SURVEY.md L5 → jit mapping)."""

from __future__ import annotations

from typing import Sequence

from .. import jit as _jit
from ..jit import InputSpec  # noqa: F401


def save_inference_model(path_prefix: str, feed_vars, fetch_vars=None,
                         executor=None, layer=None,
                         input_spec: Sequence = None, **_ignored):
    """ref: static/io.py:435. TPU form: pass the Layer (or let
    ``feed_vars`` be the Layer for convenience) + input_spec."""
    target = layer if layer is not None else feed_vars
    spec = input_spec or fetch_vars
    if not hasattr(target, "forward") and not callable(target):
        raise ValueError(
            "save_inference_model needs the model Layer: "
            "save_inference_model(path, layer, input_spec=[...])")
    _jit.save(target, path_prefix, input_spec=spec)


def load_inference_model(path_prefix: str, executor=None, **_ignored):
    """ref: static/io.py:685 → returns the loaded callable program."""
    return _jit.load(path_prefix)


class Executor:
    """Serving-run facade (ref: fluid/executor.py Executor.run — the
    inference direction only; training goes through Model/jit)."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program, feed=None, fetch_list=None):
        """``program`` is a TranslatedLayer from load_inference_model;
        ``feed`` a dict or list of input arrays (ordered)."""
        if feed is None:
            raise ValueError("feed required")
        inputs = list(feed.values()) if isinstance(feed, dict) else \
            list(feed)
        out = program(*inputs)
        return out if isinstance(out, (list, tuple)) else [out]


# ref: paddle.static.sparsity re-exports the ASP API (static/sparsity)
from ..incubate import asp as sparsity  # noqa: E402
