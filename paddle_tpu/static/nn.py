"""``paddle.static.nn`` — static-graph layer functions + the public
control-flow and sequence-op surface (VERDICT r3 asks #3/#4: the
legacy-op families beyond the phi yamls).

Reference being replaced: python/paddle/static/nn/__init__.py (fc,
conv2d, batch_norm, ... — LayerHelper programs appending OpDescs),
paddle/fluid/operators/controlflow/ (cond/while/case/switch_case), and
paddle/fluid/operators/sequence_ops/ (the LoD sequence family).

TPU redesign decisions, recorded here:

- **Dual mode**: every function works EAGERLY on arrays (the one-world
  stance — usable under jit/grad like any jnp code) and SYMBOLICALLY on
  ``static.Variable``s (building the closure DAG Executor.run
  evaluates). The reference needed two codebases for this; tracing
  needs none.
- **Control flow** lowers to ``lax.cond`` / ``lax.while_loop`` /
  ``lax.switch`` — compiled, not Python-unrolled, matching the
  reference ops' semantics (operators/controlflow/conditional_block_op,
  while_op).
- **Sequence ops and LoD**: there is no LoDTensor. The TPU-native
  carrier for ragged data is (padded [B, T, ...], lengths [B]) — the
  dynamic-shape policy of io/sequence.py. Each sequence op takes an
  optional ``length=None`` argument where the reference read LoD
  (None = all rows full length). This is the recorded redesign of
  paddle/fluid/operators/sequence_ops/.
"""

from __future__ import annotations

import math
from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .program import Variable, _op, create_parameter

__all__ = [
    "fc", "conv2d", "conv2d_transpose", "conv3d", "conv3d_transpose",
    "batch_norm", "layer_norm", "instance_norm", "group_norm",
    "data_norm", "embedding", "sparse_embedding", "prelu",
    "spectral_norm", "bilinear_tensor_product", "deform_conv2d", "nce",
    "multi_box_head", "crf_decoding", "row_conv", "py_func", "case",
    "cond", "switch_case", "while_loop", "StaticRNN",
    "sequence_concat", "sequence_conv", "sequence_enumerate",
    "sequence_expand", "sequence_expand_as", "sequence_first_step",
    "sequence_last_step", "sequence_pad", "sequence_pool",
    "sequence_reshape", "sequence_reverse", "sequence_scatter",
    "sequence_slice", "sequence_softmax", "sequence_unpad",
]


def _is_sym(*args) -> bool:
    return any(isinstance(a, Variable) for a in args)


def _lift(fn: Callable, *args, shape=None, dtype="float32"):
    """Apply eagerly, or emit a DAG node if any arg is symbolic."""
    if _is_sym(*args):
        return _op(fn, *args, shape=shape, dtype=dtype)
    return fn(*[jnp.asarray(a) if not isinstance(a, (int, float, tuple,
                                                     list, type(None)))
                else a for a in args])


# ---------------------------------------------------------------------------
# layer functions (ref: python/paddle/static/nn/common.py)
# ---------------------------------------------------------------------------

def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    """ref: static/nn/common.py fc."""
    in_dim = int(np.prod(x.shape[num_flatten_dims:]))
    w = create_parameter([in_dim, size], name=name and name + ".w_0")
    b = None if bias_attr is False else create_parameter(
        [size], name=name and name + ".b_0")
    act = {"relu": jax.nn.relu, "tanh": jnp.tanh,
           "sigmoid": jax.nn.sigmoid, None: lambda v: v}[activation]

    def fn(xv, wv, *bv):
        flat = xv.reshape(xv.shape[:num_flatten_dims] + (-1,))
        out = flat @ wv
        if bv:
            out = out + bv[0]
        return act(out)

    deps = (x, w) + (() if b is None else (b,))
    return _op(fn, *deps, shape=x.shape[:num_flatten_dims] + (size,))


def _conv_nd(x, num_filters, filter_size, stride, padding, dilation,
             groups, nd, transposed=False, output_padding=0):
    from ..nn import functional as F
    k = (filter_size,) * nd if isinstance(filter_size, int) \
        else tuple(filter_size)
    cin = int(x.shape[1])
    if transposed:
        wshape = [cin, num_filters // (groups or 1)] + list(k)
    else:
        wshape = [num_filters, cin // (groups or 1)] + list(k)
    w = create_parameter(wshape)
    b = create_parameter([num_filters])
    fns = {(2, False): F.conv2d, (3, False): F.conv3d,
           (2, True): F.conv2d_transpose, (3, True): F.conv3d_transpose}
    conv = fns[(nd, transposed)]

    def fn(xv, wv, bv):
        kw = dict(stride=stride, padding=padding, dilation=dilation,
                  groups=groups or 1)
        if transposed:
            kw["output_padding"] = output_padding
        return conv(xv, wv, bv, **kw)

    return _op(fn, x, w, b)


def conv2d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=None, param_attr=None, bias_attr=None,
           act=None, name=None, data_format="NCHW"):
    return _conv_nd(input, num_filters, filter_size, stride, padding,
                    dilation, groups, 2)


def conv3d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=None, param_attr=None, bias_attr=None,
           act=None, name=None, data_format="NCDHW"):
    return _conv_nd(input, num_filters, filter_size, stride, padding,
                    dilation, groups, 3)


def conv2d_transpose(input, num_filters, output_size=None,
                     filter_size=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=None,
                     param_attr=None, bias_attr=None, act=None,
                     name=None, data_format="NCHW"):
    return _conv_nd(input, num_filters, filter_size, stride, padding,
                    dilation, groups, 2, True, output_padding)


def conv3d_transpose(input, num_filters, output_size=None,
                     filter_size=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=None,
                     param_attr=None, bias_attr=None, act=None,
                     name=None, data_format="NCDHW"):
    return _conv_nd(input, num_filters, filter_size, stride, padding,
                    dilation, groups, 3, True, output_padding)


def _norm_params(c):
    g = create_parameter([c], default_initializer=None)
    from ..nn import initializer as I
    from .program import default_main_program
    prog = default_main_program()
    prog.state[g.name] = jnp.ones((c,), jnp.float32)
    b = create_parameter([c])
    prog.state[b.name] = jnp.zeros((c,), jnp.float32)
    return g, b


def batch_norm(input, act=None, is_test=False, momentum=0.9,
               epsilon=1e-5, param_attr=None, bias_attr=None,
               data_layout="NCHW", name=None, **_kw):
    """Batch statistics per step (ref: static/nn/common.py batch_norm;
    running-average serving stats belong to nn.BatchNorm layers)."""
    c = int(input.shape[1])
    g, b = _norm_params(c)

    def fn(xv, gv, bv):
        axes = (0,) + tuple(range(2, xv.ndim))
        mean = xv.mean(axes, keepdims=True)
        var = xv.var(axes, keepdims=True)
        shape = (1, c) + (1,) * (xv.ndim - 2)
        out = (xv - mean) / jnp.sqrt(var + epsilon)
        return out * gv.reshape(shape) + bv.reshape(shape)

    return _op(fn, input, g, b, shape=input.shape)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    d = int(np.prod(input.shape[begin_norm_axis:]))
    g, b = _norm_params(d)

    def fn(xv, gv, bv):
        shape = xv.shape
        flat = xv.reshape(shape[:begin_norm_axis] + (-1,))
        mean = flat.mean(-1, keepdims=True)
        var = flat.var(-1, keepdims=True)
        out = (flat - mean) / jnp.sqrt(var + epsilon)
        return (out * gv + bv).reshape(shape)

    return _op(fn, input, g, b, shape=input.shape)


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None):
    from ..nn import functional as F
    c = int(input.shape[1])
    g, b = _norm_params(c)
    return _op(lambda xv, gv, bv: F.instance_norm(xv, gv, bv, epsilon),
               input, g, b, shape=input.shape)


def group_norm(input, groups, epsilon=1e-5, param_attr=None,
               bias_attr=None, act=None, data_layout="NCHW", name=None):
    from ..nn import functional as F
    c = int(input.shape[1])
    g, b = _norm_params(c)
    return _op(lambda xv, gv, bv: F.group_norm(xv, groups, gv, bv,
                                               epsilon),
               input, g, b, shape=input.shape)


def data_norm(input, act=None, epsilon=1e-5, param_attr=None,
              data_layout="NCHW", in_place=False, name=None,
              moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=True, slot_dim=-1,
              sync_stats=False, summary_decay_rate=0.9999999,
              enable_scale_and_shift=False):
    """ref: static/nn/common.py data_norm (CTR normalization by
    accumulated batch summaries). TPU form: normalize by the batch's
    own statistics; the PS summary accumulators become per-step stats
    (decision: no cross-step mutable op state inside XLA programs)."""
    def fn(xv):
        mean = xv.mean(0, keepdims=True)
        var = xv.var(0, keepdims=True)
        return (xv - mean) / jnp.sqrt(var + epsilon)

    return _op(fn, input, shape=input.shape)


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32"):
    w = create_parameter(list(size), dtype=dtype)

    def fn(ids, wv):
        out = wv[ids.astype(jnp.int32)]
        if padding_idx is not None:
            pad = padding_idx if padding_idx >= 0 else size[0] + padding_idx
            out = out * (ids != pad)[..., None].astype(out.dtype)
        return out

    return _op(fn, input, w,
               shape=tuple(input.shape or ()) + (size[1],))


def sparse_embedding(input, size, padding_idx=None, is_test=False,
                     entry=None, table_class="MemorySparseTable",
                     param_attr=None, dtype="float32", slot=None):
    """ref: contrib sparse_embedding (PS path). Served by the host
    table family: nn.HostOffloadedEmbedding / nn.ShardedHostEmbedding;
    here the static-graph surface keeps a dense parameter (tables
    beyond HBM go through those layers, not Program state)."""
    return embedding(input, size, padding_idx=padding_idx, dtype=dtype)


def prelu(x, mode="all", param_attr=None, data_format="NCHW",
          name=None):
    n = {"all": 1, "channel": int(x.shape[1]),
         "element": int(np.prod(x.shape[1:]))}[mode]
    a = create_parameter([n])
    from .program import default_main_program
    default_main_program().state[a.name] = jnp.full((n,), 0.25)

    def fn(xv, av):
        if mode == "channel":
            av = av.reshape((1, -1) + (1,) * (xv.ndim - 2))
        elif mode == "element":
            av = av.reshape((1,) + xv.shape[1:])
        return jnp.where(xv >= 0, xv, av * xv)

    return _op(fn, x, a, shape=x.shape)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    from ..nn.layers.fill_r4 import SpectralNorm
    sn = SpectralNorm([int(s) for s in weight.shape], dim=dim,
                      power_iters=power_iters, eps=eps)
    return _lift(lambda w: sn(w), weight, shape=weight.shape)


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    dx, dy = int(x.shape[-1]), int(y.shape[-1])
    w = create_parameter([size, dx, dy])
    b = create_parameter([size])

    def fn(xv, yv, wv, bv):
        return jnp.einsum("bi,oij,bj->bo", xv, wv, yv) + bv

    return _op(fn, x, y, w, b, shape=(x.shape[0], size))


def deform_conv2d(x, offset, mask, num_filters, filter_size, stride=1,
                  padding=0, dilation=1, groups=1, deformable_groups=1,
                  im2col_step=1, param_attr=None, bias_attr=None,
                  name=None):
    from ..vision.ops import deform_conv2d as _dc
    k = (filter_size,) * 2 if isinstance(filter_size, int) \
        else tuple(filter_size)
    w = create_parameter([num_filters, int(x.shape[1]) // groups, *k])
    b = create_parameter([num_filters])
    return _op(lambda xv, ov, mv, wv, bv: _dc(
        xv, ov, wv, bv, stride=stride, padding=padding,
        dilation=dilation, deformable_groups=deformable_groups,
        groups=groups, mask=mv), x, offset, mask, w, b)


def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=None,
        name=None, sampler="uniform", custom_dist=None, seed=0,
        is_sparse=False):
    """Noise-contrastive estimation loss (ref: operators/nce_op.cc):
    logistic discrimination of the true class against k uniform noise
    samples — the sampled-softmax family on TPU."""
    d = int(input.shape[-1])
    k = num_neg_samples or 5
    w = create_parameter([num_total_classes, d])
    b = create_parameter([num_total_classes])

    def fn(xv, yv, wv, bv):
        y = yv.reshape(-1).astype(jnp.int32)
        pos_logit = (xv * wv[y]).sum(-1) + bv[y]
        from ..core import rng as _rng
        neg = jax.random.randint(_rng.next_key(), (xv.shape[0], k), 0,
                                 num_total_classes)
        neg_logit = jnp.einsum("bd,bkd->bk", xv, wv[neg]) \
            + jnp.take(bv, neg)
        loss = -jax.nn.log_sigmoid(pos_logit) \
            - jax.nn.log_sigmoid(-neg_logit).sum(-1)
        return loss.reshape(-1, 1)

    return _op(fn, input, label, w, b, shape=(input.shape[0], 1))


def _prior_boxes(feat_hw, image_size, min_size, max_size,
                 aspect_ratios, flip, clip, offset=0.5):
    """Prior-box generation (ref: operators/detection/prior_box_op.cc):
    per cell, one min-size square, one sqrt(min*max) square, and one
    box per aspect ratio (plus flipped)."""
    fh, fw = feat_hw
    ars = [1.0]
    for a in aspect_ratios:
        if a != 1.0:
            ars.append(a)
            if flip:
                ars.append(1.0 / a)
    whs = [(min_size * math.sqrt(a), min_size / math.sqrt(a))
           for a in ars]
    if max_size:
        s = math.sqrt(min_size * max_size)
        whs.insert(1, (s, s))
    sy, sx = image_size / fh, image_size / fw
    cy = (np.arange(fh) + offset) * sy
    cx = (np.arange(fw) + offset) * sx
    cyx = np.stack(np.meshgrid(cy, cx, indexing="ij"), -1)  # [H, W, 2]
    boxes = []
    for w, h in whs:
        b = np.concatenate([
            (cyx[..., 1] - w / 2)[..., None],
            (cyx[..., 0] - h / 2)[..., None],
            (cyx[..., 1] + w / 2)[..., None],
            (cyx[..., 0] + h / 2)[..., None]], -1) / image_size
        boxes.append(b)
    out = np.stack(boxes, 2).reshape(-1, 4).astype(np.float32)
    if clip:
        out = np.clip(out, 0.0, 1.0)
    return out


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, offset=0.5, flip=True,
                   clip=False, **_kw):
    """SSD prior-box head (ref: static/nn multi_box_head /
    operators/detection/prior_box_op): per-feature-map conv heads for
    loc/conf + generated prior boxes + variances."""
    locs, confs, boxes = [], [], []
    n_in = len(inputs)
    if min_sizes is None:
        # the reference's ratio interpolation
        min_sizes, max_sizes = [], []
        step = int(math.floor((max_ratio - min_ratio)
                              / max(n_in - 2, 1)))
        for r in range(min_ratio, max_ratio + 1, step):
            min_sizes.append(base_size * r / 100.0)
            max_sizes.append(base_size * (r + step) / 100.0)
        min_sizes = [base_size * 0.1] + min_sizes[:n_in - 1]
        max_sizes = [base_size * 0.2] + max_sizes[:n_in - 1]
    for i, feat in enumerate(inputs):
        ar = list(aspect_ratios[i])
        extra = len([a for a in ar if a != 1.0]) * (2 if flip else 1)
        n_priors = 1 + (1 if max_sizes else 0) + extra
        locs.append(conv2d(feat, n_priors * 4, 3, padding=1))
        confs.append(conv2d(feat, n_priors * num_classes, 3, padding=1))
        boxes.append(_prior_boxes(
            tuple(int(s) for s in feat.shape[2:]), base_size,
            min_sizes[i], max_sizes[i] if max_sizes else None,
            ar, flip, clip, offset))
    all_boxes = jnp.asarray(np.concatenate(boxes))
    variances = jnp.tile(jnp.asarray([0.1, 0.1, 0.2, 0.2]),
                         (all_boxes.shape[0], 1))
    return locs, confs, all_boxes, variances


def crf_decoding(input, param_attr=None, label=None, length=None):
    """Viterbi decode over CRF transitions (ref:
    operators/crf_decoding_op; the modern path is
    paddle.text.ViterbiDecoder)."""
    from ..text import viterbi_decode
    n = int(input.shape[-1])
    trans = create_parameter([n + 2, n])

    def fn(xv, tv):
        # reference layout: row 0 start, row 1 stop, rows 2.. transitions
        scores, path = viterbi_decode(
            xv[None] if xv.ndim == 2 else xv,
            tv[2:], include_bos_eos_tag=False,
            lengths=length)
        return path[0] if xv.ndim == 2 else path

    return _op(fn, input, trans)


def row_conv(input, future_context_size, param_attr=None, act=None):
    """Lookahead row convolution (ref: operators/row_conv_op — the
    DeepSpeech2 streaming op): y[t] = sum_{i<=k} w[i] * x[t+i]."""
    d = int(input.shape[-1])
    k = future_context_size + 1
    w = create_parameter([k, d])

    def fn(xv, wv):
        pads = [(0, 0)] * xv.ndim
        t_ax = xv.ndim - 2
        pads[t_ax] = (0, k - 1)
        xp = jnp.pad(xv, pads)
        out = sum(jax.lax.slice_in_dim(xp, i, i + xv.shape[t_ax],
                                       axis=t_ax) * wv[i]
                  for i in range(k))
        return out

    return _op(fn, input, w, shape=input.shape)


def py_func(func, x, out, backward_func=None,
            skip_vars_in_backward_input=None):
    from .program import py_func as _pf
    return _pf(func, x, out, backward_func, skip_vars_in_backward_input)


# ---------------------------------------------------------------------------
# public control flow (ref: operators/controlflow/; fluid/layers/
# control_flow.py cond/while_loop/case/switch_case)
# ---------------------------------------------------------------------------

def cond(pred, true_fn=None, false_fn=None, name=None):
    """lax.cond with the reference's signature (zero-arg branches)."""
    if _is_sym(pred):
        return _op(lambda p: lax.cond(jnp.asarray(p).reshape(()),
                                      lambda _: true_fn(),
                                      lambda _: false_fn(), 0), pred)
    return lax.cond(jnp.asarray(pred).reshape(()).astype(bool),
                    lambda _: true_fn(), lambda _: false_fn(), 0)


def while_loop(cond_fn, body, loop_vars, is_test=False, name=None):
    """lax.while_loop with the reference's list-of-loop-vars calling
    convention (cond/body take and return the var list)."""
    vars_t = tuple(loop_vars)
    out = lax.while_loop(lambda vs: jnp.asarray(
        cond_fn(*vs)).reshape(()).astype(bool),
        lambda vs: tuple(body(*vs)), vars_t)
    return list(out)


def case(pred_fn_pairs, default=None, name=None):
    """First-true-wins chained cond (ref: control_flow.py case)."""
    def build(i):
        if i >= len(pred_fn_pairs):
            if default is None:
                raise ValueError("case: no predicate matched and no "
                                 "default branch")
            return default()
        pred, fn = pred_fn_pairs[i]
        return lax.cond(jnp.asarray(pred).reshape(()).astype(bool),
                        lambda _: fn(), lambda _: build(i + 1), 0)

    return build(0)


def switch_case(branch_index, branch_fns, default=None, name=None):
    """lax.switch over an integer selector (ref: control_flow.py
    switch_case; branch_fns may be a dict {index: fn} or list)."""
    if isinstance(branch_fns, dict):
        keys = sorted(branch_fns)
        fns = [branch_fns[k] for k in keys]
        idx = jnp.searchsorted(jnp.asarray(keys),
                               jnp.asarray(branch_index).reshape(()))
        in_range = jnp.isin(jnp.asarray(branch_index).reshape(()),
                            jnp.asarray(keys))
    else:
        fns = list(branch_fns)
        idx = jnp.asarray(branch_index).reshape(())
        in_range = (idx >= 0) & (idx < len(fns))
    branches = [lambda _, f=f: f() for f in fns]
    if default is not None:
        branches.append(lambda _: default())
        idx = jnp.where(in_range, idx, len(fns))
    return lax.switch(jnp.clip(idx, 0, len(branches) - 1).astype(int)
                      if hasattr(idx, "astype") else idx, branches, 0)


class StaticRNN:
    """Step-scanned RNN builder (ref: fluid/layers/control_flow
    StaticRNN: step-scope program region). TPU form: record the step
    function, lower to lax.scan at output time."""

    def __init__(self, name=None):
        self._inputs: List = []
        self._memories: List = []
        self._step: Optional[Callable] = None

    def step(self):
        return self

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def step_input(self, x):
        self._inputs.append(jnp.asarray(x))
        return x

    def memory(self, init=None, shape=None, batch_ref=None,
               init_value=0.0):
        if init is None:
            b = (batch_ref.shape[1] if batch_ref is not None
                 else self._inputs[0].shape[1])
            init = jnp.full((b,) + tuple(shape or ()), init_value)
        self._memories.append(jnp.asarray(init))
        return init

    def set_step_fn(self, fn: Callable):
        """TPU-explicit API: fn(x_t, *mems) -> (out_t, *new_mems)."""
        self._step = fn

    def update_memory(self, old, new):
        self._update = (old, new)

    def step_output(self, o):
        self._out = o

    def __call__(self):
        if self._step is None:
            raise ValueError("StaticRNN: call set_step_fn(fn) with "
                             "fn(x_t, *mems) -> (out_t, *mems)")
        xs = self._inputs[0]

        def body(mems, x_t):
            out = self._step(x_t, *mems)
            return tuple(out[1:]), out[0]

        _, ys = lax.scan(body, tuple(self._memories), xs)
        return ys


# ---------------------------------------------------------------------------
# sequence ops over (padded, lengths) — ref: operators/sequence_ops/
# ---------------------------------------------------------------------------

def _len_mask(x, length, time_axis=1):
    t = x.shape[time_axis]
    if length is None:
        return jnp.ones(x.shape[:2], bool) if time_axis == 1 \
            else jnp.ones((x.shape[0], t), bool)
    length = jnp.asarray(length).reshape(-1)
    return jnp.arange(t)[None, :] < length[:, None]


def sequence_pad(x, pad_value, maxlen=None, length=None, name=None):
    """(list of [Ti, D] | padded) → (padded [B, T, D], lengths [B])
    (ref: sequence_pad_op)."""
    if isinstance(x, (list, tuple)):
        lens = np.asarray([len(s) for s in x])
        t = maxlen or int(lens.max())
        d = np.shape(x[0])[1:]
        out = np.full((len(x), t) + d, pad_value, np.float32)
        for i, s in enumerate(x):
            out[i, :len(s)] = s
        return jnp.asarray(out), jnp.asarray(lens)
    x = jnp.asarray(x)
    mask = _len_mask(x, length)
    shape = mask.shape + (1,) * (x.ndim - 2)
    out = jnp.where(mask.reshape(shape), x, pad_value)
    lens = (jnp.asarray(length) if length is not None
            else jnp.full((x.shape[0],), x.shape[1]))
    return out, lens


def sequence_unpad(x, length, name=None):
    """padded [B, T, D] + lengths → list of [Ti, D] (host-side ragged;
    ref: sequence_unpad_op)."""
    xn = np.asarray(x)
    ln = np.asarray(length).reshape(-1)
    return [xn[i, :int(ln[i])] for i in range(xn.shape[0])]


def sequence_pool(input, pool_type, is_test=False, pad_value=0.0,
                  length=None):
    """sum/average/max/sqrt/first/last over the time axis under the
    length mask (ref: sequence_pool_op)."""
    def fn(x):
        mask = _len_mask(x, length)
        m = mask.reshape(mask.shape + (1,) * (x.ndim - 2))
        pt = pool_type.lower()
        cnt = jnp.maximum(m.sum(1), 1)
        if pt == "sum":
            return jnp.where(m, x, 0).sum(1)
        if pt in ("average", "avg", "mean"):
            return jnp.where(m, x, 0).sum(1) / cnt
        if pt == "sqrt":
            return jnp.where(m, x, 0).sum(1) / jnp.sqrt(cnt)
        if pt == "max":
            return jnp.where(m, x, -jnp.inf).max(1)
        if pt == "first":
            return x[:, 0]
        if pt == "last":
            if length is None:
                return x[:, -1]
            idx = jnp.asarray(length).reshape(-1) - 1
            return jnp.take_along_axis(
                x, idx.reshape(-1, 1, *([1] * (x.ndim - 2))), axis=1
            )[:, 0]
        raise ValueError(f"unknown pool_type {pool_type}")

    return _lift(fn, input)


def sequence_first_step(input, length=None):
    return sequence_pool(input, "first", length=length)


def sequence_last_step(input, length=None):
    return sequence_pool(input, "last", length=length)


def sequence_softmax(input, use_cudnn=False, name=None, length=None):
    def fn(x):
        mask = _len_mask(x, length)
        m = mask.reshape(mask.shape + (1,) * (x.ndim - 2))
        z = jnp.where(m, x, -jnp.inf)
        return jnp.where(m, jax.nn.softmax(z, axis=1), 0.0)

    return _lift(fn, input)


def sequence_reverse(x, name=None, length=None):
    """Reverse each row WITHIN its length; padding stays in place
    (ref: sequence_reverse_op)."""
    def fn(xv):
        t = xv.shape[1]
        if length is None:
            return jnp.flip(xv, axis=1)
        ln = jnp.asarray(length).reshape(-1, 1)
        idx = jnp.arange(t)[None, :]
        src = jnp.where(idx < ln, ln - 1 - idx, idx)
        return jnp.take_along_axis(
            xv, src.reshape(src.shape + (1,) * (xv.ndim - 2)), axis=1)

    return _lift(fn, x)


def sequence_concat(input: Sequence, name=None, lengths=None):
    """Concatenate rows time-wise, packing valid prefixes first (ref:
    sequence_concat_op)."""
    if lengths is None:
        return _lift(lambda *xs: jnp.concatenate(xs, axis=1), *input)
    xs = [np.asarray(x) for x in input]
    lns = [np.asarray(l).reshape(-1) for l in lengths]
    b = xs[0].shape[0]
    total = sum(x.shape[1] for x in xs)
    d = xs[0].shape[2:]
    out = np.zeros((b, total) + d, xs[0].dtype)
    newlen = np.zeros((b,), np.int64)
    for i in range(b):
        pos = 0
        for x, ln in zip(xs, lns):
            li = int(ln[i])
            out[i, pos:pos + li] = x[i, :li]
            pos += li
        newlen[i] = pos
    return jnp.asarray(out), jnp.asarray(newlen)


def sequence_expand(x, y, ref_level=-1, name=None, y_lengths=None):
    """Repeat each row of x per the matching row-count of y (ref:
    sequence_expand_op; with padded carriers this is a repeat along
    batch)."""
    def fn(xv, yv):
        reps = yv.shape[1] if y_lengths is None else None
        if reps is not None:
            return jnp.repeat(xv, reps, axis=0)
        return xv

    if y_lengths is not None:
        xn = np.asarray(x)
        reps = np.asarray(y_lengths).reshape(-1)
        return jnp.asarray(np.repeat(xn, reps, axis=0))
    return _lift(fn, x, y)


def sequence_expand_as(x, y, name=None):
    def fn(xv, yv):
        reps = yv.shape[0] // xv.shape[0]
        return jnp.repeat(xv, reps, axis=0)

    return _lift(fn, x, y)


def sequence_reshape(input, new_dim, length=None):
    """Re-chunk the feature dim (ref: sequence_reshape_op): [B, T, D]
    → [B, T*D/new_dim, new_dim]."""
    def fn(x):
        b = x.shape[0]
        return x.reshape(b, -1, new_dim)

    return _lift(fn, input)


def sequence_slice(input, offset, length, name=None):
    """Per-row [offset, offset+length) time slice (ref:
    sequence_slice_op). Static common case: scalar offset/length;
    ragged via per-row gather."""
    def fn(x, off, ln):
        off = jnp.asarray(off).reshape(-1)
        ln_ = jnp.asarray(ln).reshape(-1)
        t_out = int(np.max(np.asarray(ln)))
        idx = off[:, None] + jnp.arange(t_out)[None, :]
        idx = jnp.minimum(idx, x.shape[1] - 1)
        out = jnp.take_along_axis(
            x, idx.reshape(idx.shape + (1,) * (x.ndim - 2)), axis=1)
        mask = jnp.arange(t_out)[None, :] < ln_[:, None]
        return out * mask.reshape(mask.shape + (1,) * (x.ndim - 2))

    return _lift(fn, input, offset, length)


def sequence_scatter(input, index, updates, name=None):
    """Scatter updates at (row, time) positions (ref:
    sequence_scatter_op; index [N, 2] of (batch, t))."""
    def fn(x, idx, upd):
        idx = jnp.asarray(idx)
        return x.at[idx[:, 0], idx[:, 1]].add(upd)

    return _lift(fn, input, index, updates)


def sequence_enumerate(input, win_size, pad_value=0, name=None):
    """Sliding windows of ids (ref: sequence_enumerate_op): [B, T] →
    [B, T, win_size], short windows padded."""
    def fn(x):
        t = x.shape[1]
        pads = [(0, 0)] * x.ndim
        pads[1] = (0, win_size - 1)
        xp = jnp.pad(x, pads, constant_values=pad_value)
        return jnp.stack([xp[:, i:i + t] for i in range(win_size)],
                         axis=-1)

    return _lift(fn, input)


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=True, padding_start=None, bias_attr=None,
                  param_attr=None, act=None, name=None):
    """Context-window 1-D conv over time (ref: sequence_conv_op): each
    step sees [t+start, t+start+filter_size) rows flattened."""
    d = int(input.shape[-1])
    w = create_parameter([filter_size * d, num_filters])
    b = create_parameter([num_filters])
    start = padding_start if padding_start is not None \
        else -(filter_size // 2)

    def fn(x, wv, bv):
        t = x.shape[1]
        before, after = max(0, -start), max(0, start + filter_size - 1)
        pads = [(0, 0), (before, after)] + [(0, 0)] * (x.ndim - 2)
        xp = jnp.pad(x, pads)
        ctx = jnp.concatenate(
            [xp[:, i:i + t] for i in range(filter_size)], axis=-1)
        return ctx @ wv + bv

    return _op(fn, input, w, b,
               shape=tuple(input.shape[:2]) + (num_filters,))
