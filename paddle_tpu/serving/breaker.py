"""Per-replica circuit breaker: closed → open → half-open → closed.

The router's view of one replica's recent behavior, separate from the
replica's own health state machine (``LLMEngine`` walks healthy →
degraded → draining from INSIDE; the breaker judges from OUTSIDE — a
crashed process can't report draining, but its connection refusals
trip the breaker just the same). Semantics are the classic ones
(Nygard's "Release It!" / Hystrix lineage):

- CLOSED: traffic flows; ``fail_threshold`` consecutive failures trip
  the breaker OPEN.
- OPEN: no traffic for ``open_for`` seconds — the replica gets quiet
  time to restart instead of a retry storm (EQuARX's byte-lean control
  plane argument applies here too: a dead replica must not eat the
  fleet's dispatch budget).
- HALF-OPEN: after the cooldown, up to ``half_open_probes`` trial
  requests are admitted. Any success closes the breaker (counters
  reset); any failure re-opens it and restarts the cooldown.

Thread-safe; time injectable (``clock=``) so tests drive transitions
without sleeping. Stdlib-only.
"""

from __future__ import annotations

import threading
import time
from typing import Callable

#: gauge encoding (docs/OBSERVABILITY.md router rows)
STATE_CODE = {"closed": 0, "half_open": 1, "open": 2}


class CircuitBreaker:
    def __init__(self, fail_threshold: int = 3, open_for: float = 2.0,
                 half_open_probes: int = 1,
                 clock: Callable[[], float] = time.monotonic):
        if fail_threshold < 1:
            raise ValueError("fail_threshold must be >= 1")
        self.fail_threshold = int(fail_threshold)
        self.open_for = float(open_for)
        self.half_open_probes = int(half_open_probes)
        self._clock = clock
        self._mu = threading.Lock()
        self._state = "closed"
        self._consec_failures = 0
        self._opened_at = 0.0
        self._probes_out = 0
        self.n_opens = 0          # cumulative trips (status surface)

    @property
    def state(self) -> str:
        with self._mu:
            return self._state_locked()

    def _state_locked(self) -> str:
        # the open→half_open edge is time-driven; materialize it on
        # read so observers and allow() agree on one transition point
        if self._state == "open" and \
                self._clock() - self._opened_at >= self.open_for:
            self._state = "half_open"
            self._probes_out = 0
        return self._state

    def allow(self) -> bool:
        """May a request (or health probe) be sent now? Half-open
        admits at most ``half_open_probes`` outstanding trials; their
        verdicts arrive via record_success/record_failure."""
        with self._mu:
            st = self._state_locked()
            if st == "closed":
                return True
            if st == "open":
                return False
            if self._probes_out >= self.half_open_probes:
                return False
            self._probes_out += 1
            return True

    def record_success(self) -> None:
        with self._mu:
            st = self._state_locked()
            self._consec_failures = 0
            if st == "half_open":
                self._state = "closed"
                self._probes_out = 0

    def record_failure(self) -> None:
        with self._mu:
            st = self._state_locked()
            self._consec_failures += 1
            if st == "half_open" or (
                    st == "closed"
                    and self._consec_failures >= self.fail_threshold):
                # a failed half-open probe re-opens immediately — the
                # replica gets another full cooldown, not a hammering
                self._state = "open"
                self._opened_at = self._clock()
                self._probes_out = 0
                self.n_opens += 1

    def reset(self) -> None:
        """Operator escape hatch (POST /reset_health routes here via
        the router): force closed, clear counters."""
        with self._mu:
            self._state = "closed"
            self._consec_failures = 0
            self._probes_out = 0

    def __repr__(self):
        return (f"CircuitBreaker(state={self.state!r}, "
                f"consec_failures={self._consec_failures}, "
                f"opens={self.n_opens})")
