"""Fleet metrics federation: one pane of glass over K replicas.

PR 6 scaled serving out, and scattered the numbers with it: every
replica exports its own isolated ``/metrics``, so "what is the fleet's
occupancy" or "did scale-out dilute the prefix-cache hit rate" meant K
scrapes and a spreadsheet. :class:`FleetScraper` closes that gap on
the router, riding the health-poll cycle it already runs:

- each poll, every replica that exposes ``metrics_text()``
  (:class:`~paddle_tpu.serving.replica.HTTPReplica` scrapes its debug
  server; :class:`LocalReplica` opts out — its series already live in
  the router's own registry) is scraped and parsed;
- the parsed series are RE-EXPORTED from the router's ``/metrics``
  under a ``fleet_`` name prefix with a ``replica`` label
  (``fleet_llm_ttft_seconds_bucket{replica="r0",le="0.05"}``) — the
  prefix keeps federated series from colliding with the same family
  names in the router process when a LocalReplica engine runs
  in-process;
- fleet-level AGGREGATES are computed into first-class gauges
  (``fleet_occupancy``, ``fleet_prefix_cache_hit_rate``,
  ``fleet_tokens_generated``, ``fleet_replicas_scraped``,
  ``fleet_mfu``, ``fleet_headroom_pages`` and
  ``fleet_goodput_fraction`` — the latter three with
  hole semantics: a down/warming replica or one without the series
  is ABSENT from the mean/sum, never a zero, with
  ``fleet_mfu_replicas``/``fleet_headroom_replicas``/
  ``fleet_goodput_replicas`` as auditable
  denominators) — the numbers ROADMAP item 2's device-resident-decode
  case and item 3's KV-page-migration routing need fleet-wide, not
  per-process;
- ``GET /fleetz`` (observability.server) renders the whole picture as
  JSON: per-replica health + breaker + key series next to the
  aggregates.

Stale data is marked, not hidden: a replica that stops answering keeps
its last snapshot with ``up: false`` and drops out of the aggregates,
so a dead replica reads as a hole, not as a zero.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

from ..observability.metrics import MetricRegistry, default_registry

# sample-name suffixes that belong to a histogram family
_HIST_SUFFIXES = ("_bucket", "_sum", "_count")


def parse_prometheus_text(text: str) -> Dict[str, dict]:
    """Parse Prometheus text exposition (the 0.0.4 subset our own
    exporter emits) into ``{family_name: {"type": kind, "samples":
    [(sample_name, labels_dict, value)]}}``. Unparseable lines are
    skipped — a half-written scrape degrades to fewer series, never an
    exception on the poll thread."""
    families: Dict[str, dict] = {}
    last_family = None
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 4 and parts[1] == "TYPE":
                families.setdefault(
                    parts[2], {"type": parts[3], "samples": []})
                last_family = parts[2]
            continue
        try:
            name_part, value_s = line.rsplit(" ", 1)
            value = float(value_s.replace("+Inf", "inf"))
        except ValueError:
            continue
        name, labels = _split_labels(name_part)
        if name is None:
            continue
        fam = _family_of(name, families, last_family)
        families.setdefault(fam, {"type": "untyped", "samples": []})
        families[fam]["samples"].append((name, labels, value))
    return families


def _split_labels(name_part: str) -> Tuple[Optional[str], Dict[str, str]]:
    if "{" not in name_part:
        return name_part.strip(), {}
    name, _, rest = name_part.partition("{")
    rest = rest.rstrip()
    if not rest.endswith("}"):
        return None, {}
    labels: Dict[str, str] = {}
    for pair in _split_label_pairs(rest[:-1]):
        k, _, v = pair.partition("=")
        if not k or len(v) < 2 or v[0] != '"' or v[-1] != '"':
            return None, {}
        labels[k] = v[1:-1]
    return name.strip(), labels


def _split_label_pairs(s: str) -> List[str]:
    """Split ``a="x",b="y,z"`` on commas outside quotes."""
    out, cur, in_q = [], [], False
    for ch in s:
        if ch == '"':
            in_q = not in_q
        if ch == "," and not in_q:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if cur:
        out.append("".join(cur))
    return [p for p in (x.strip() for x in out) if p]


def _family_of(sample_name: str, families: dict, last_family) -> str:
    """Map a sample name back to its family: histogram samples carry
    _bucket/_sum/_count suffixes on the family name."""
    if sample_name in families:
        return sample_name
    for suf in _HIST_SUFFIXES:
        if sample_name.endswith(suf) and sample_name[:-len(suf)] \
                in families:
            return sample_name[:-len(suf)]
    # untyped stray sample: its own family (or the family whose TYPE
    # line immediately preceded it)
    if last_family and sample_name.startswith(last_family):
        return last_family
    return sample_name


def _series_value(fam: Optional[dict], sample_name: str) -> Optional[float]:
    if not fam:
        return None
    for name, _labels, value in fam["samples"]:
        if name == sample_name:
            return value
    return None


class FleetScraper:
    """Router-side federation of replica ``/metrics`` scrapes.

    Owns no thread: :meth:`scrape` is called from the router's health
    poller (one cycle, one scrape per replica), keeping fleet
    observability on exactly the cadence operators already reason
    about for health. ``federate_prefixes`` bounds what is re-exported
    (default: the ``llm_`` serving series + ``process``-level basics);
    aggregates always consider the full parse."""

    AGGREGATE_SOURCES = ("llm_batch_occupancy", "llm_kv_page_utilization",
                        "llm_prefix_cache_hit_tokens",
                        "llm_prompt_tokens", "llm_tokens_generated",
                        "llm_requests_completed", "perf_mfu",
                        "perf_flops_per_second", "mem_headroom_pages",
                        "goodput_fraction", "drift_verified_total",
                        "drift_divergence_total", "brownout_level")

    def __init__(self, registry: Optional[MetricRegistry] = None,
                 federate_prefixes: Tuple[str, ...] = ("llm_", "perf_",
                                                       "mem_",
                                                       "badput_",
                                                       "kv_migrate_",
                                                       "drift_",
                                                       "brownout_",
                                                       "overload_"),
                 stale_after: float = 10.0):
        # NOTE: per-replica badput CAUSES federate
        # (fleet_badput_seconds_total{replica=,cause=}); the replica's
        # goodput_fraction gauge deliberately does NOT — its federated
        # name would collide with the fleet_goodput_fraction AGGREGATE
        # below. Per-replica fractions live on /fleetz instead.
        self.registry = registry or default_registry()
        self.federate_prefixes = tuple(federate_prefixes)
        self.stale_after = float(stale_after)
        self._mu = threading.Lock()
        # name -> {"ts": wall, "up": bool, "families": parse result}
        self._replicas: Dict[str, dict] = {}
        reg = self.registry
        self._g_scraped = reg.gauge(
            "fleet_replicas_scraped",
            "replicas whose /metrics answered the last scrape cycle")
        self._g_occ = reg.gauge(
            "fleet_occupancy",
            "mean decode-batch occupancy across scraped replicas "
            "(cumulative mean of llm_batch_occupancy per replica)")
        self._g_kv = reg.gauge(
            "fleet_kv_page_utilization",
            "mean KV-page-pool utilization across scraped replicas")
        self._g_hit = reg.gauge(
            "fleet_prefix_cache_hit_rate",
            "aggregate prefix-cache hit rate: sum(hit tokens) / "
            "sum(prompt tokens) across scraped replicas")
        self._g_tokens = reg.gauge(
            "fleet_tokens_generated",
            "tokens generated across scraped replicas (sum of the "
            "per-replica counters at last scrape)")
        self._g_completed = reg.gauge(
            "fleet_requests_completed",
            "requests completed across scraped replicas")
        self._g_up = reg.gauge(
            "fleet_replica_up",
            "1 when the replica's /metrics answered the last scrape",
            label_names=("replica",))
        self._g_mfu = reg.gauge(
            "fleet_mfu",
            "mean perf_mfu across UP replicas that export it — a down "
            "replica is a HOLE in the mean, never a zero (its capacity "
            "is gone, not idle); 0 with fleet_mfu_replicas=0 means no "
            "replica reports MFU yet")
        self._g_mfu_n = reg.gauge(
            "fleet_mfu_replicas",
            "replicas whose perf_mfu entered the fleet_mfu mean at the "
            "last scrape (the denominator that makes the hole "
            "semantics auditable)")
        self._g_fps = reg.gauge(
            "fleet_flops_per_second",
            "sum of perf_flops_per_second across scraped replicas")
        self._g_headroom = reg.gauge(
            "fleet_headroom_pages",
            "sum of mem_headroom_pages (KV pages each replica's paged "
            "pools could still hand out) across UP replicas that "
            "export it — a down or warming replica is a HOLE in the "
            "sum, never a zero (its capacity is gone, not exhausted). "
            "Per-replica values federate as "
            "fleet_mem_headroom_pages{replica=...} via the mem_ "
            "re-export prefix — the series KV-page-migration routing "
            "reads")
        self._g_headroom_n = reg.gauge(
            "fleet_headroom_replicas",
            "replicas whose mem_headroom_pages entered the "
            "fleet_headroom_pages sum at the last scrape (the "
            "auditable hole-semantics denominator, like "
            "fleet_mfu_replicas)")
        self._g_goodput = reg.gauge(
            "fleet_goodput_fraction",
            "mean goodput_fraction across UP replicas that export it "
            "— a down or never-armed (warming) replica is a HOLE in "
            "the mean, never a zero (its seconds are gone, not "
            "badput); 0 with fleet_goodput_replicas=0 means no "
            "replica has armed its time ledger yet")
        self._g_goodput_n = reg.gauge(
            "fleet_goodput_replicas",
            "replicas whose goodput_fraction entered the "
            "fleet_goodput_fraction mean at the last scrape (the "
            "auditable hole-semantics denominator, like "
            "fleet_mfu_replicas)")
        self._g_drift_ok = reg.gauge(
            "fleet_drift_verified",
            "stream-integrity checks that confirmed chain identity, "
            "summed across UP replicas that export drift_* — a down "
            "or never-armed replica is a HOLE in the sum, never a "
            "zero (its streams went unverified, not verified-clean); "
            "0 with fleet_drift_replicas=0 means no replica has "
            "armed its auditor yet")
        self._g_drift_bad = reg.gauge(
            "fleet_drift_divergences",
            "stream-integrity divergences summed across UP replicas "
            "that export drift_* (same hole semantics as "
            "fleet_drift_verified). ANY nonzero value is a fleet "
            "determinism incident — per-kind detail federates as "
            "fleet_drift_divergence_total{replica=,kind=}")
        self._g_drift_n = reg.gauge(
            "fleet_drift_replicas",
            "replicas whose drift_* counters entered the fleet_drift_"
            "sums at the last scrape (the auditable hole-semantics "
            "denominator, like fleet_mfu_replicas)")
        self._g_brownout = reg.gauge(
            "fleet_brownout_level",
            "MAX brownout_level across UP replicas that export it — "
            "the fleet is as degraded as its most-degraded member. A "
            "down or never-armed replica (no overload controller "
            "bound) is a HOLE, never a zero: 0 with "
            "fleet_brownout_replicas=0 means no replica runs a "
            "controller, not that the fleet is calm")
        self._g_brownout_n = reg.gauge(
            "fleet_brownout_replicas",
            "replicas whose brownout_level entered the "
            "fleet_brownout_level max at the last scrape (the "
            "auditable hole-semantics denominator, like "
            "fleet_mfu_replicas)")

    # -- ingestion ------------------------------------------------------
    @staticmethod
    def exports(client) -> bool:
        """True when the client is a metrics EXPORTER. Non-exporters
        (no ``metrics_text`` surface, or ``metrics_opt_out`` set —
        :class:`LocalReplica`'s same-process opt-out) stay absent from
        federation entirely: a healthy non-exporting replica must not
        read as a down one, so ``fleet_replica_up`` is only ever
        minted for exporters."""
        return getattr(client, "metrics_text", None) is not None \
            and not getattr(client, "metrics_opt_out", False)

    def scrape(self, name: str, client) -> bool:
        """Scrape one replica (called per health-poll cycle).
        Non-exporters (see :meth:`exports`) are forgotten, not marked
        down; an exporter whose scrape fails IS down (recorded via
        :meth:`record`, keeping its last snapshot out of the
        federated view)."""
        if not self.exports(client):
            self.forget(name)
            return False
        try:
            text = client.metrics_text()
        except Exception:  # noqa: BLE001 — a scrape failure is data
            text = None
        self.record(name, text)
        return text is not None

    def mark_unreachable(self, name: str, client) -> None:
        """The router's verdict for a replica whose HEALTH poll failed
        (no point timing out a second request on /metrics): exporters
        go down, non-exporters stay absent."""
        if self.exports(client):
            self.record(name, None)
        else:
            self.forget(name)

    def record(self, name: str, text: Optional[str]) -> None:
        if text is None:
            with self._mu:
                st = self._replicas.setdefault(
                    name, {"ts": 0.0, "up": False, "families": {}})
                st["up"] = False
            self._g_up.labels(name).set(0)
            self._refresh_aggregates()
            return
        families = parse_prometheus_text(text)
        with self._mu:
            self._replicas[name] = {"ts": time.time(), "up": True,
                                    "families": families}
        self._g_up.labels(name).set(1)
        self._refresh_aggregates()

    def forget(self, name: str) -> None:
        with self._mu:
            had = self._replicas.pop(name, None) is not None
        if had:
            # it WAS an exporter (detached, or re-pointed to a
            # non-exporting client): zero its liveness series rather
            # than leave a stale 1
            self._g_up.labels(name).set(0)
            self._refresh_aggregates()

    # -- aggregates -----------------------------------------------------
    def _snapshot_up(self) -> Dict[str, dict]:
        with self._mu:
            return {n: st for n, st in self._replicas.items()
                    if st["up"]}

    def _refresh_aggregates(self) -> dict:
        up = self._snapshot_up()
        occ, kv, mfu, headroom, goodput = [], [], [], [], []
        hit_tok = prompt_tok = tokens = completed = fps = 0.0
        drift_ok, drift_bad = [], []
        brownout = []
        for st in up.values():
            fams = st["families"]
            # perf federation: only replicas that EXPORT perf_mfu
            # enter the mean — a down replica (absent from `up`) or a
            # replica without the perf registry is a hole, not a zero
            m = _series_value(fams.get("perf_mfu"), "perf_mfu")
            if m is not None:
                mfu.append(m)
            # memory federation, same hole semantics: a replica whose
            # pool closed (or never opened — warming) exports no
            # mem_headroom_pages family at all and stays OUT of the
            # sum and its denominator
            hp = _series_value(fams.get("mem_headroom_pages"),
                               "mem_headroom_pages")
            if hp is not None:
                headroom.append(hp)
            # goodput federation, same hole semantics: a replica that
            # never armed its time ledger exports no goodput_fraction
            # family at all and stays OUT of the mean and denominator
            gp = _series_value(fams.get("goodput_fraction"),
                               "goodput_fraction")
            if gp is not None:
                goodput.append(gp)
            # drift federation, same hole semantics: a replica that
            # never armed its stream auditor (the counters mint at
            # FIRST record) exports no drift_* family at all and
            # stays out of both sums and the denominator — an
            # unverified fleet must read as unverified, not clean.
            # drift_divergence_total is {kind}-labeled: sum every
            # sample of the family, not just the first.
            # brownout federation, same hole semantics: a replica with
            # no overload controller bound exports no brownout_level
            # family at all and stays OUT of the max and denominator —
            # a fleet nobody governs must read as ungoverned, not calm
            bl = _series_value(fams.get("brownout_level"),
                               "brownout_level")
            if bl is not None:
                brownout.append(bl)
            dv = _series_value(fams.get("drift_verified_total"),
                               "drift_verified_total")
            if dv is not None:
                drift_ok.append(dv)
                bad_fam = fams.get("drift_divergence_total")
                drift_bad.append(sum(
                    value for _n, _l, value
                    in (bad_fam["samples"] if bad_fam else [])))
            fps += _series_value(fams.get("perf_flops_per_second"),
                                 "perf_flops_per_second") or 0.0
            o_sum = _series_value(fams.get("llm_batch_occupancy"),
                                  "llm_batch_occupancy_sum")
            o_cnt = _series_value(fams.get("llm_batch_occupancy"),
                                  "llm_batch_occupancy_count")
            if o_sum is not None and o_cnt:
                occ.append(o_sum / o_cnt)
            u = _series_value(fams.get("llm_kv_page_utilization"),
                              "llm_kv_page_utilization")
            if u is not None:
                kv.append(u)
            hit_tok += _series_value(
                fams.get("llm_prefix_cache_hit_tokens"),
                "llm_prefix_cache_hit_tokens") or 0.0
            prompt_tok += _series_value(
                fams.get("llm_prompt_tokens"), "llm_prompt_tokens") \
                or 0.0
            tokens += _series_value(
                fams.get("llm_tokens_generated"),
                "llm_tokens_generated") or 0.0
            completed += _series_value(
                fams.get("llm_requests_completed"),
                "llm_requests_completed") or 0.0
        agg = {
            "replicas_scraped": len(up),
            "occupancy": sum(occ) / len(occ) if occ else 0.0,
            "kv_page_utilization": sum(kv) / len(kv) if kv else 0.0,
            "prefix_cache_hit_rate": (hit_tok / prompt_tok
                                      if prompt_tok else 0.0),
            "tokens_generated": tokens,
            "requests_completed": completed,
            "mfu": (sum(mfu) / len(mfu)) if mfu else None,
            "mfu_replicas": len(mfu),
            "flops_per_second": fps,
            "mem_headroom_pages": sum(headroom) if headroom else None,
            "mem_headroom_replicas": len(headroom),
            "goodput_fraction": (sum(goodput) / len(goodput))
            if goodput else None,
            "goodput_replicas": len(goodput),
            "drift_verified": sum(drift_ok) if drift_ok else None,
            "drift_divergences": sum(drift_bad) if drift_ok else None,
            "drift_replicas": len(drift_ok),
            "brownout_level": max(brownout) if brownout else None,
            "brownout_replicas": len(brownout),
        }
        self._g_scraped.set(agg["replicas_scraped"])
        self._g_occ.set(agg["occupancy"])
        self._g_kv.set(agg["kv_page_utilization"])
        self._g_hit.set(agg["prefix_cache_hit_rate"])
        self._g_tokens.set(agg["tokens_generated"])
        self._g_completed.set(agg["requests_completed"])
        self._g_mfu.set(agg["mfu"] or 0.0)
        self._g_mfu_n.set(agg["mfu_replicas"])
        self._g_fps.set(agg["flops_per_second"])
        self._g_headroom.set(agg["mem_headroom_pages"] or 0.0)
        self._g_headroom_n.set(agg["mem_headroom_replicas"])
        self._g_goodput.set(agg["goodput_fraction"] or 0.0)
        self._g_goodput_n.set(agg["goodput_replicas"])
        self._g_drift_ok.set(agg["drift_verified"] or 0.0)
        self._g_drift_bad.set(agg["drift_divergences"] or 0.0)
        self._g_drift_n.set(agg["drift_replicas"])
        self._g_brownout.set(agg["brownout_level"] or 0.0)
        self._g_brownout_n.set(agg["brownout_replicas"])
        return agg

    def aggregates(self) -> dict:
        return self._refresh_aggregates()

    # -- re-export ------------------------------------------------------
    def render_prometheus(self) -> str:
        """The federated block appended to the router's /metrics:
        every matching replica series re-exported as
        ``fleet_<name>{replica="...",...}``."""
        up = self._snapshot_up()
        lines: List[str] = []
        typed = set()
        for rname in sorted(up):
            for fam_name, fam in sorted(up[rname]["families"].items()):
                if not fam_name.startswith(self.federate_prefixes):
                    continue
                if fam_name not in typed and fam["type"] != "untyped":
                    lines.append(
                        f"# TYPE fleet_{fam_name} {fam['type']}")
                    typed.add(fam_name)
                for sname, labels, value in fam["samples"]:
                    merged = {"replica": rname, **labels}
                    inner = ",".join(f'{k}="{v}"'
                                     for k, v in merged.items())
                    v = "+Inf" if value == float("inf") else repr(value)
                    lines.append(f"fleet_{sname}{{{inner}}} {v}")
        return "\n".join(lines) + ("\n" if lines else "")

    # -- /fleetz --------------------------------------------------------
    def replica_report(self) -> Dict[str, dict]:
        """Per-replica digest for /fleetz: liveness + the headline
        serving series (full detail stays on the replica's own
        /metrics, federated under fleet_*)."""
        with self._mu:
            snap = {n: dict(st) for n, st in self._replicas.items()}
        out: Dict[str, dict] = {}
        now = time.time()
        for name, st in snap.items():
            fams = st["families"]
            o_sum = _series_value(fams.get("llm_batch_occupancy"),
                                  "llm_batch_occupancy_sum")
            o_cnt = _series_value(fams.get("llm_batch_occupancy"),
                                  "llm_batch_occupancy_count")
            out[name] = {
                "up": st["up"],
                "scrape_age_s": (round(now - st["ts"], 3)
                                 if st["ts"] else None),
                "stale": bool(st["ts"]
                              and now - st["ts"] > self.stale_after),
                "occupancy": (round(o_sum / o_cnt, 4)
                              if o_sum is not None and o_cnt else None),
                "kv_page_utilization": _series_value(
                    fams.get("llm_kv_page_utilization"),
                    "llm_kv_page_utilization"),
                "prefix_cache_hit_rate": _series_value(
                    fams.get("llm_prefix_cache_hit_rate"),
                    "llm_prefix_cache_hit_rate"),
                "tokens_generated": _series_value(
                    fams.get("llm_tokens_generated"),
                    "llm_tokens_generated"),
                "requests_completed": _series_value(
                    fams.get("llm_requests_completed"),
                    "llm_requests_completed"),
                "mfu": _series_value(fams.get("perf_mfu"), "perf_mfu"),
                "mem_headroom_pages": _series_value(
                    fams.get("mem_headroom_pages"),
                    "mem_headroom_pages"),
                "goodput_fraction": _series_value(
                    fams.get("goodput_fraction"), "goodput_fraction"),
            }
        return out
