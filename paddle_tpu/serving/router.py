"""Fleet router: prefix-affinity load balancing over engine replicas
with per-replica circuit breakers and in-budget failover.

The horizontally-scaled serving tier (ROADMAP item 3): everything a
single :class:`LLMEngine` learned in PRs 2 and 5 — prefix caching,
deadlines, priorities, shed/cancel verdicts, the health state machine
— composed ACROSS processes. One router fronts K replicas (in-process
engines, spawned subprocesses, or attached multi-host endpoints;
membership via the rendezvous TCPStore) and gives clients the same
``submit(...) -> Future`` surface the engine has, with three fleet
properties layered on top:

PREFIX AFFINITY. Requests are routed by a rendezvous hash of the
prompt's first KV-page digests (the same rolling BLAKE2b chain
``prefix_cache.page_digests`` computes), so requests sharing a prefix
land on the replica most likely to already hold those pages — PR 2's
cache hit rate multiplies under scale-out instead of diluting by 1/K
(``tools/llm_bench.py --fleet`` pins affinity ≥ 1.5× round-robin).
Rendezvous hashing keeps the mapping stable under membership churn: a
replica leaving only remaps ITS keys.

HEALTH AS ROUTING INPUT. A background poll of each replica's
``/healthz`` plus in-band error verdicts drive a per-replica
:class:`CircuitBreaker` (closed → open → half-open): connection
failures and crashes trip it OPEN (quiet time, no retry storm),
half-open probes re-close it when the replica returns. A replica
reporting DRAINING (its own sticky health latch, HTTP 503) receives no
new admissions within one poll interval; its requests rebalance to
siblings without consuming failover budget.

FAILOVER INSIDE THE RETRY BUDGET. The router pins each request's
sampling nonce at admission, so a request lost to a replica crash
mid-decode is re-submitted to a sibling and — all replicas being
identically seeded — regenerates the IDENTICAL token stream (PR 5's
device-retry semantics, now across processes). The client sees
latency, never an error, while ``failover_budget`` lasts.

Per-tenant quotas and SLO classes map onto the engine's existing
priority/deadline machinery: an :class:`SLOClass` is a named
(deadline, priority) default, a :class:`TenantQuota` bounds a tenant's
in-flight requests (overflow sheds at the ROUTER — the byte-lean
control plane never even wakes a replica for it).
"""

from __future__ import annotations

import hashlib
import itertools
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Dict, Optional

from ..inference.llm import (AdmissionShed, EngineClosed,
                             OverloadShed, RequestCancelled)
from ..inference.prefix_cache import page_digests
from ..observability import audit as _audit
from ..observability import goodput as _goodput
from ..observability import metrics as _obs
from ..observability import propagation as _propagation
from ..observability import server as _dbgsrv
from ..observability import tracing as _trace
from ..observability.slo import DEFAULT_WINDOWS, SLOTracker
from ..reliability import faults as _faults
from ..reliability.retry import DeadlineExceeded, as_deadline
from .breaker import STATE_CODE, CircuitBreaker
from .fleet import FleetScraper
from .replica import HTTPReplica, ReplicaUnavailable

_HEALTH_CODE = {"healthy": 0, "degraded": 1, "draining": 2,
                "unreachable": 3, "unknown": 3}


def affinity_key(prompt, page_size: int, affinity_pages: int) -> bytes:
    """The routing key: the rolling digest of the prompt's first
    ``min(affinity_pages, full pages)`` KV pages. Prompts sharing
    their first ``affinity_pages`` pages co-locate (their tails,
    wherever they diverge beyond that, don't matter); prompts shorter
    than one page hash their tokens, so identical short prompts still
    co-locate. Small ``affinity_pages`` = coarse families (better
    sharing), large = finer spread."""
    digs = page_digests(prompt, page_size)
    if digs:
        # digest i commits to the whole history through page i — one
        # key per prefix family
        return digs[:affinity_pages][-1]
    return hashlib.blake2b(
        ",".join(map(str, prompt)).encode(), digest_size=16).digest()


def rendezvous_pick(key: bytes, names) -> Optional[str]:
    """Highest-random-weight (rendezvous) hash: the max-scoring name
    for ``key``. Stable under membership churn — removing a name only
    remaps the keys that preferred it."""
    best, best_score = None, -1
    for n in names:
        h = hashlib.blake2b(key + n.encode(), digest_size=8)
        score = int.from_bytes(h.digest(), "big")
        if score > best_score:
            best, best_score = n, score
    return best


class SLOClass:
    """A named latency tier: requests submitted under it inherit its
    deadline/priority unless they bring their own. ``target`` is the
    class's SLO success objective (fed to the router's
    :class:`~paddle_tpu.observability.slo.SLOTracker`; None uses the
    tracker's default)."""

    def __init__(self, name: str, deadline_s: Optional[float] = None,
                 priority: int = 0,
                 target: Optional[float] = None):
        self.name = name
        self.deadline_s = deadline_s
        self.priority = int(priority)
        self.target = target


class TenantQuota:
    """Per-tenant admission bound: at most ``max_inflight`` of the
    tenant's requests live in the fleet at once (None: unbounded);
    ``slo`` names the tenant's default SLO class."""

    def __init__(self, max_inflight: Optional[int] = None,
                 slo: Optional[str] = None):
        self.max_inflight = max_inflight
        self.slo = slo


def _router_metrics():
    reg = _obs.default_registry()
    return {
        "dispatches": reg.counter(
            "router_dispatches_total",
            "request dispatch attempts per replica",
            label_names=("replica",)),
        "failovers": reg.counter(
            "router_failover_total",
            "re-dispatches after a replica became unavailable "
            "mid-request (same nonce — token-identical resubmission)"),
        "rebalanced": reg.counter(
            "router_rebalanced_total",
            "dispatches rerouted off a shedding/draining replica "
            "(no failover budget consumed)"),
        "shed": reg.counter(
            "router_shed_total",
            "requests shed at the router (tenant quota, or no "
            "routable replica)"),
        "affinity_routed": reg.counter(
            "router_affinity_routed_total",
            "dispatches that landed on the prefix-affinity-preferred "
            "replica"),
        "affinity_total": reg.counter(
            "router_affinity_eligible_total",
            "dispatches that had an affinity preference (denominator "
            "of the hit rate)"),
        "affinity_rate": reg.gauge(
            "router_affinity_hit_rate",
            "cumulative affinity-preferred / eligible dispatches"),
        "breaker": reg.gauge(
            "router_breaker_state",
            "per-replica breaker: 0 closed, 1 half-open, 2 open",
            label_names=("replica",)),
        "inflight": reg.gauge(
            "router_replica_inflight",
            "requests currently dispatched to each replica (the "
            "router-side queue depth)",
            label_names=("replica",)),
        "rhealth": reg.gauge(
            "router_replica_health",
            "last polled replica health: 0 healthy, 1 degraded, "
            "2 draining, 3 unreachable",
            label_names=("replica",)),
        "latency": reg.histogram(
            "router_request_seconds",
            "router submit → resolution (failover latency included)"),
        "role_dispatches": reg.counter(
            "router_role_dispatches_total",
            "dispatch attempts by replica pool role (disaggregated "
            "fleets run 'prefill' and 'decode' pools; replicas with "
            "no declared role count as 'unified')",
            label_names=("role",)),
        "migrate_seconds": reg.histogram(
            "kv_migrate_seconds",
            "end-to-end KV-page migration wall time as the router "
            "sees it: prefill fill + page export + verified import"),
        "migrate_failed": reg.counter(
            "router_migrate_failed_total",
            "migrations abandoned mid-flight; the request fell back "
            "to nonce-pinned local recompute on its decode replica"),
    }


class _ReplicaState:
    __slots__ = ("name", "client", "breaker", "health", "inflight",
                 "dispatched", "from_membership", "info", "warming",
                 "admin_draining", "role")

    def __init__(self, name, client, breaker):
        self.name = name
        self.client = client
        self.breaker = breaker
        self.health = "unknown"   # last poll verdict (or in-band 503)
        self.inflight = 0
        self.dispatched = 0
        self.from_membership = False
        self.info: dict = {}
        # pool role in a disaggregated fleet: "prefill" replicas fill
        # KV pages and hand them off; "decode" (or None = unified)
        # replicas serve the requests themselves
        self.role = None
        # WARMING: spawned but not yet counted toward capacity (no
        # READY + healthy probe yet). A warming replica is a HOLE —
        # it absorbs no dispatches AND stays out of the occupancy
        # denominator, the same semantics PR 11 gave fleet_mfu (a
        # replica that isn't serving must neither take traffic nor
        # drag the fleet average toward a spurious scale-in).
        self.warming = False
        # ADMIN DRAINING: the autoscaler marked this replica for
        # scale-in. Routing excludes it immediately; the health poll
        # must NOT overwrite the verdict back to "healthy" while the
        # drain is in progress.
        self.admin_draining = False


class _FleetRequest:
    __slots__ = ("prompt", "max_new_tokens", "temperature", "deadline",
                 "priority", "tenant", "nonce", "future", "cancelled",
                 "span", "excluded", "t_submit", "failovers",
                 "affinity_key", "quota_held", "rr_slot", "slo_name",
                 "had_deadline", "last_dispatch", "digests", "migrate",
                 "prior_knobs", "predicted_s")

    def __init__(self, prompt, max_new_tokens, temperature):
        self.prompt = list(map(int, prompt))
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.deadline = None
        self.priority = 0
        self.tenant = None
        self.nonce = 0
        self.future: Future = Future()
        self.cancelled = False
        self.span = None
        self.excluded = set()    # replicas that shed/died THIS request
        self.t_submit = time.monotonic()
        self.failovers = 0
        self.affinity_key = b""
        self.quota_held = False   # holds one tenant-inflight slot
        self.rr_slot = 0          # round-robin seat, fixed at submit
        self.slo_name = None      # SLO class for burn-rate accounting
        self.had_deadline = False
        # (SpanContext, replica) of the previous dispatch attempt —
        # the next attempt links back to it so a failover reads as
        # one story on the merged timeline
        self.last_dispatch = None
        # full-page digest chain of the prompt (computed once at
        # submit); drives both affinity and KV-page migration
        self.digests = []
        # result of a completed migration for this request, attached
        # to the final result dict ({"seconds", "pages", "prefill"},
        # plus the fill's token-0 witness for chain verification)
        self.migrate = None
        # knob fingerprint of the replica a failed attempt ran on
        # (last known) — a failover sibling serving under DIFFERENT
        # knobs is a detected drift, not a documented hazard
        self.prior_knobs = None
        # the overload controller's admission-time service estimate —
        # the resolution latency is judged against it (the
        # overload_estimate_error_ratio histogram)
        self.predicted_s = None


class Router:
    """Load-balancing front over K engine replicas.

    ``replicas``: mapping name → replica client (:class:`LocalReplica`
    / :class:`HTTPReplica` / any object with their surface); more join
    later via :meth:`attach` or TCPStore membership
    (``store_endpoint=``, records published by
    ``distributed.tcp_store.TCPMembership`` — replicas that re-register
    under the same name keep their breaker history, so a restarted
    replica must walk open → half-open → closed like any recovering
    one).

    ``policy``: ``"affinity"`` (prefix rendezvous, the default) or
    ``"round_robin"`` (the baseline ``llm_bench --fleet`` compares
    against). Both fall back to least-loaded when no preference
    applies.
    """

    def __init__(self, replicas: Optional[Dict[str, object]] = None, *,
                 page_size: int = 16, affinity_pages: int = 2,
                 failover_budget: int = 2,
                 health_poll_interval: float = 0.25,
                 breaker_fail_threshold: int = 3,
                 breaker_open_for: float = 1.0,
                 breaker_half_open_probes: int = 1,
                 slo_classes: Optional[Dict[str, SLOClass]] = None,
                 tenants: Optional[Dict[str, TenantQuota]] = None,
                 store_endpoint: Optional[str] = None,
                 membership_stale_after: float = 2.0,
                 policy: str = "affinity",
                 max_workers: int = 32,
                 scrape_metrics: bool = True,
                 federate_prefixes=("llm_", "perf_", "mem_",
                                    "badput_", "kv_migrate_", "drift_",
                                    "brownout_", "overload_"),
                 disagg_threshold_tokens: Optional[int] = None,
                 slo_windows=DEFAULT_WINDOWS,
                 slo_default_target: float = 0.99,
                 slo_breach_threshold: float = 10.0,
                 slo_min_samples: int = 10,
                 overload=None,
                 name: str = "router"):
        if policy not in ("affinity", "round_robin"):
            raise ValueError(f"unknown routing policy {policy!r}")
        self.page_size = int(page_size)
        self.affinity_pages = int(affinity_pages)
        self.failover_budget = int(failover_budget)
        self.health_poll_interval = float(health_poll_interval)
        self.policy = policy
        self.name = name
        self._breaker_kw = dict(
            fail_threshold=breaker_fail_threshold,
            open_for=breaker_open_for,
            half_open_probes=breaker_half_open_probes)
        self.slo_classes = dict(slo_classes or {})
        self.tenants = dict(tenants or {})
        self._mu = threading.Lock()
        self._replicas: Dict[str, _ReplicaState] = {}
        # names pre-declared warming (Autoscaler.expect_warming): a
        # membership attach racing the spawner's explicit attach must
        # not slip a half-booted replica into rotation
        self._expect_warm: set = set()
        # detach tombstones: name -> detach time. A membership sync
        # whose roster SNAPSHOT predates a scale-in's withdraw+detach
        # must not resurrect the killed replica from the stale
        # snapshot (a ghost that would sit breaker-open forever —
        # roster records going stale never detaches). Entries expire
        # after membership_stale_after: by then any lingering record
        # has aged out, and a legitimately re-registered same name
        # (fresh heartbeats) attaches normally.
        self._detached_at: Dict[str, float] = {}
        # zero-arg callables run at the tail of every health-poll
        # cycle (the Autoscaler's tick rides this cadence)
        self._poll_hooks: list = []
        self._tenant_inflight: Dict[str, int] = {}
        self._by_id: Dict[int, _FleetRequest] = {}
        self._nonce_seq = itertools.count()
        self._rr_seq = itertools.count()
        self._closed = False
        self._m = _router_metrics()
        self.n_submitted = 0
        self.n_failovers = 0
        self.n_rebalanced = 0
        self.n_shed = 0
        # -- disaggregated prefill/decode fleet state --
        # migrate when the decode target would have to prefill more
        # than this many uncached tokens locally (None: 2 pages — one
        # page of savings is not worth a network round trip)
        self.disagg_threshold_tokens = disagg_threshold_tokens
        self.n_migrations = 0
        self.n_migrate_failed = 0
        self.n_pages_migrated = 0
        self.n_pages_rejected = 0
        # -- stream-integrity auditor state --
        # last-known engine knob fingerprint per replica (updated on
        # every verified completion): failover verification compares
        # the recovering sibling's knobs against the failed one's
        self._knobs: Dict[str, dict] = {}
        self.n_shadows = 0
        # optimistic per-replica digest residency: updated on every
        # completion/migration, dropped when the replica goes
        # unreachable (it may restart blank). Wrong-in-either-
        # direction is safe — a stale "resident" only re-migrates or
        # recomputes; verification on import keeps it exact.
        self._resident: Dict[str, set] = {}
        # per-replica Retry-After cooldowns: a shed response carrying
        # the header moves that replica to the back of the line until
        # the cooldown lapses (only skipped while OTHER candidates
        # exist — a cooldown must never make a fleet unroutable)
        self._retry_until: Dict[str, float] = {}
        # overload brownout controller (serving/overload.py): admission
        # verdicts pre-dispatch, AIMD concurrency bounds in _route, the
        # degradation ladder ticking on the health-poll cadence (bound
        # below, after the debug surface exists)
        self.overload = overload
        for rname, client in (replicas or {}).items():
            self.attach(rname, client)
        # TCPStore membership: poll the roster alongside health
        self._store_client = None
        self._membership_stale_after = float(membership_stale_after)
        if store_endpoint is not None:
            from ..distributed.tcp_store import TCPStoreClient
            self._store_client = TCPStoreClient(store_endpoint)
        # fleet observability: the FleetScraper federates replica
        # /metrics on the health-poll cadence; the SLOTracker turns
        # request outcomes into burn-rate gauges. Both are wired into
        # the debug surface below.
        self.scraper = FleetScraper(
            federate_prefixes=tuple(federate_prefixes)) \
            if scrape_metrics else None
        self.slo = SLOTracker(
            targets={n: c.target for n, c in self.slo_classes.items()
                     if c.target is not None},
            default_target=slo_default_target,
            windows=tuple(slo_windows),
            breach_threshold=slo_breach_threshold,
            min_samples=slo_min_samples)
        self._pool = ThreadPoolExecutor(
            max_workers=max_workers,
            thread_name_prefix=f"{name}-dispatch")
        self._stop = threading.Event()
        self._poller = threading.Thread(
            target=self._poll_loop, name=f"{name}-health", daemon=True)
        self._poller.start()
        # live-debug surface: /statusz fleet view, /fleetz federation,
        # /sloz burn rates, /healthz aggregate (+ SLO breach latch),
        # POST /reset_health → breaker + breach-latch reset (the
        # router-side half of the operator escape hatch)
        self._status_name = f"{name}_{id(self):x}"
        _dbgsrv.register_status_provider(self._status_name,
                                         self._status)
        _dbgsrv.register_health_provider(self._status_name,
                                         self._aggregate_health)
        _dbgsrv.register_reset_handler(self._status_name,
                                       self._reset_all)
        _dbgsrv.register_fleet_provider(self._status_name,
                                        self._fleetz)
        _dbgsrv.register_slo_provider(self._status_name,
                                      self._sloz)
        _dbgsrv.register_health_provider(self._status_name + "_slo",
                                         self._slo_health)
        if self.scraper is not None:
            _dbgsrv.register_scrape_provider(
                self._status_name, self._render_federated)
        if overload is not None:
            overload.bind(self)
            self.add_poll_hook(overload.tick)

    # -- membership ---------------------------------------------------------
    def attach(self, name: str, client, warming: bool = False,
               role: Optional[str] = None) -> None:
        """Add (or re-point) a replica. Re-attaching an existing name
        keeps its breaker — a restarted replica re-earns trust through
        half-open probes instead of resetting its history.
        ``warming=True`` (or a prior :meth:`expect_warming`) attaches
        it as a capacity HOLE: no dispatches, no occupancy weight,
        until :meth:`mark_ready`. ``role`` declares the replica's pool
        in a disaggregated fleet ("prefill" / "decode"; None on
        re-attach preserves the existing role)."""
        with self._mu:
            # an explicit attach overrides a detach tombstone — the
            # caller knows the replica exists
            self._detached_at.pop(name, None)
            st = self._replicas.get(name)
            if st is None:
                st = _ReplicaState(name, client,
                                   CircuitBreaker(**self._breaker_kw))
                st.warming = warming or name in self._expect_warm
                st.role = role
                self._replicas[name] = st
            else:
                st.client = client
                if warming:
                    st.warming = True
                if role is not None:
                    st.role = role

    def expect_warming(self, name: str) -> None:
        """Pre-declare ``name`` as warming BEFORE its process exists:
        whichever attach path lands first (the spawner's explicit
        :meth:`attach` or the TCPStore membership sync — a booting
        replica announces membership before it prints READY) the
        replica enters warming, never rotation. Cleared by
        :meth:`mark_ready` or :meth:`detach`."""
        with self._mu:
            self._expect_warm.add(name)
            st = self._replicas.get(name)
            if st is not None:
                st.warming = True

    def mark_ready(self, name: str) -> bool:
        """Promote a warming replica into rotation (the autoscaler
        calls this after READY + the first successful health probe).
        Returns False when the name is unknown."""
        with self._mu:
            self._expect_warm.discard(name)
            st = self._replicas.get(name)
            if st is None:
                return False
            st.warming = False
            return True

    def drain(self, name: str) -> bool:
        """Mark a replica ADMIN-DRAINING for scale-in: routing
        excludes it from the next :meth:`submit` on (nothing new is
        admitted within one poll interval — in fact immediately), and
        the health poll stops overwriting the verdict. The caller
        then waits for :meth:`inflight_of` to reach zero before
        terminating (docs/RELIABILITY.md "Autoscaling failure
        model")."""
        with self._mu:
            st = self._replicas.get(name)
            if st is None:
                return False
            st.admin_draining = True
            st.health = "draining"
            return True

    def inflight_of(self, name: str) -> Optional[int]:
        """Router-side in-flight dispatches to ``name`` (None when
        unknown) — the scale-in verify-empty check. The router is the
        replica's only admission path, so zero here means the replica
        holds no request this fleet could lose."""
        with self._mu:
            st = self._replicas.get(name)
            return None if st is None else st.inflight

    def fleet_load(self, slots_per_replica: Optional[int] = None,
                   role: Optional[str] = None) -> dict:
        """Capacity/occupancy accounting over the attached fleet.
        READY replicas (not warming, not draining, breaker not open,
        reachable) define the capacity; warming and draining replicas
        are counted but are HOLES in the occupancy denominator.
        ``occupancy`` is total ready in-flight / (slots × ready), or
        None when no ready capacity exists (a hole, not a zero — the
        autoscaler must not read an all-warming fleet as idle).
        ``role`` restricts the accounting to one pool of a
        disaggregated fleet ("unified" matches undeclared roles) —
        each pool's autoscaler sizes off its OWN burn signal."""
        with self._mu:
            states = list(self._replicas.values())
        if role is not None:
            states = [st for st in states
                      if (st.role or "unified") == role]
        ready = [st for st in states
                 if not st.warming and not st.admin_draining
                 and st.breaker.state != "open"
                 and st.health not in ("draining", "unreachable")]
        warming = sum(1 for st in states if st.warming)
        draining = sum(1 for st in states if not st.warming
                       and (st.admin_draining
                            or st.health == "draining"))
        inflight = sum(st.inflight for st in ready)
        out = {"attached": len(states), "ready": len(ready),
               "warming": warming, "draining": draining,
               "inflight": inflight,
               "ready_names": sorted(st.name for st in ready)}
        if slots_per_replica:
            cap = int(slots_per_replica) * len(ready)
            out["capacity"] = cap
            out["occupancy"] = (inflight / cap) if cap else None
        return out

    def detach(self, name: str) -> None:
        with self._mu:
            self._replicas.pop(name, None)
            self._expect_warm.discard(name)
            self._resident.pop(name, None)
            self._retry_until.pop(name, None)
            self._detached_at[name] = time.monotonic()
        if self.scraper is not None:
            self.scraper.forget(name)
        if self.overload is not None:
            self.overload.forget(name)

    # -- poll hooks ---------------------------------------------------------
    def add_poll_hook(self, fn) -> None:
        """Run ``fn()`` at the tail of every health-poll cycle — the
        cadence the Autoscaler's control loop rides (one poll, one
        health verdict, one scrape, one scaling decision)."""
        with self._mu:
            self._poll_hooks.append(fn)

    def remove_poll_hook(self, fn) -> None:
        with self._mu:
            if fn in self._poll_hooks:
                self._poll_hooks.remove(fn)

    def replica_names(self):
        with self._mu:
            return sorted(self._replicas)

    def _sync_membership(self) -> None:
        from ..distributed.tcp_store import (StoreUnavailable,
                                             TCPMembership)
        try:
            members = TCPMembership.list_members(
                self._store_client,
                stale_after=self._membership_stale_after)
        except StoreUnavailable:
            return
        now = time.monotonic()
        with self._mu:
            # tombstones expire unconditionally — most detached names
            # (fresh auto-N incarnations) never reappear in a roster,
            # so sweeping only on reappearance would grow the dict by
            # one entry per scale-in forever
            for n in [n for n, ts in self._detached_at.items()
                      if now - ts >= self._membership_stale_after]:
                del self._detached_at[n]
        for mname, info in members.items():
            with self._mu:
                if mname in self._detached_at:
                    # this roster snapshot may predate the detach
                    # (scale-in withdraw): do not resurrect a replica
                    # that was just removed
                    continue
                st = self._replicas.get(mname)
                same = st is not None and st.info == info
            if same:
                continue
            client = HTTPReplica(info["generate"], info["healthz"],
                                 metrics_url=info.get("metrics"))
            self.attach(mname, client, role=info.get("role"))
            with self._mu:
                st = self._replicas[mname]
                st.from_membership = True
                st.info = dict(info)

    # -- health / breaker maintenance ---------------------------------------
    def _poll_once(self) -> None:
        if self._store_client is not None:
            self._sync_membership()
        with self._mu:
            states = list(self._replicas.values())
        for st in states:
            if st.breaker.state != "closed":
                # open: skip (quiet time). half-open: a poll IS the
                # probe — consume a probe slot so traffic and polls
                # share one budget
                if not st.breaker.allow():
                    self._m["breaker"].labels(st.name).set(
                        STATE_CODE[st.breaker.state])
                    if self.scraper is not None:   # open = down
                        self.scraper.mark_unreachable(st.name,
                                                      st.client)
                    continue
            h = None
            try:
                if _faults.enabled():
                    _faults.check("router.healthz")
                h = st.client.health()
            except Exception:  # noqa: BLE001 — a poll failure is data
                h = None
            if not st.admin_draining:
                # an admin drain (scale-in in progress) pins the
                # verdict: the replica itself still answers "healthy"
                # right up to the kill, and one optimistic poll
                # re-admitting traffic mid-drain would break the
                # verify-empty contract
                st.health = h if h is not None else "unreachable"
            if h is None:
                st.breaker.record_failure()
                with self._mu:
                    # an unreachable replica may come back blank —
                    # drop the optimistic digest-residency view
                    self._resident.pop(st.name, None)
            else:
                # ANY answer settles as success — the breaker judges
                # reachability only; a draining verdict keeps the
                # replica out of rotation through the HEALTH filter,
                # not by re-tripping the breaker every probe cycle
                st.breaker.record_success()
            self._m["breaker"].labels(st.name).set(
                STATE_CODE[st.breaker.state])
            self._m["rhealth"].labels(st.name).set(
                _HEALTH_CODE.get(st.health, 3))
            # metrics federation rides the SAME cycle: one poll, one
            # health verdict, one scrape — an unreachable replica is
            # recorded down without a second timeout
            if self.scraper is not None:
                if h is None:
                    self.scraper.mark_unreachable(st.name, st.client)
                else:
                    self.scraper.scrape(st.name, st.client)

    def _poll_loop(self) -> None:
        while not self._stop.wait(self.health_poll_interval):
            try:
                self._poll_once()
                # windowed SLO gauges decay on the same cadence —
                # burn rates on /metrics must fall back to 0 when a
                # storm ends, not freeze at their last recorded value
                self.slo.refresh()
            except Exception:  # noqa: BLE001 — the poller must survive
                pass
            with self._mu:
                hooks = list(self._poll_hooks)
            for fn in hooks:
                try:
                    fn()
                except Exception:  # noqa: BLE001 — a broken hook must
                    pass           # not stop health polling

    def reset_breakers(self) -> None:
        """Operator escape hatch: force every breaker closed (e.g.
        after a known-good fleet restart). Reachable over HTTP via
        POST /reset_health."""
        with self._mu:
            states = list(self._replicas.values())
        for st in states:
            st.breaker.reset()
            if st.health == "draining" and not st.admin_draining:
                # an ADMIN drain is the autoscaler's scale-in in
                # progress, not sticky failure state — the operator
                # reset must not re-admit a replica mid-drain
                st.health = "unknown"   # re-polled next interval
            self._m["breaker"].labels(st.name).set(0)

    def _reset_all(self) -> None:
        """POST /reset_health verb for the router: breakers closed AND
        SLO breach latches acknowledged — one curl recovers the whole
        router-side sticky state."""
        self.reset_breakers()
        self.slo.reset_breach()

    # -- routing ------------------------------------------------------------
    _rendezvous = staticmethod(rendezvous_pick)

    def _affinity_key(self, prompt) -> bytes:
        return affinity_key(prompt, self.page_size,
                            self.affinity_pages)

    def _route(self, req: _FleetRequest):
        """(state, affinity_hit) — or (None, verdict) where verdict is
        True (every replica draining), False (none routable), or
        ``"limited"`` (routable replicas exist but all sit at their
        AIMD concurrency limit: wait, don't shed)."""
        with self._mu:
            states = dict(self._replicas)
            retry_until = dict(self._retry_until)
        # role awareness: requests DECODE on non-prefill replicas.
        # Prefill-pool replicas only enter the candidate set when no
        # non-prefill replica could possibly serve (a degraded fleet
        # must never lose a request to pool purity — the prefill
        # replica is a full engine and can decode, just wastefully).
        serving = {n: st for n, st in states.items()
                   if st.role != "prefill"}
        if any(n not in req.excluded
               and st.health != "draining"
               and not st.warming and not st.admin_draining
               and st.breaker.state != "open"
               for n, st in serving.items()):
            states = serving
        eligible = {n: st for n, st in states.items()
                    if n not in req.excluded
                    and st.health != "draining"
                    and not st.warming and not st.admin_draining}
        # Retry-After cooldowns: a replica that shed with the header
        # goes to the back of the line — but only while OTHER
        # candidates exist (a cooldown never makes a fleet unroutable)
        if retry_until:
            now = time.monotonic()
            cooling = {n for n in eligible
                       if retry_until.get(n, 0.0) > now}
            if cooling and len(cooling) < len(eligible):
                for n in cooling:
                    eligible.pop(n)
        # AIMD concurrency bound: replicas at their learned in-flight
        # limit drop out; when that empties the candidate set the
        # caller WAITS for a slot instead of shedding (the limiter
        # bounds concurrency, not admission)
        limited = False
        if self.overload is not None and eligible:
            lim = self.overload.limiter
            with_room = {n: st for n, st in eligible.items()
                         if lim.has_room(n, st.inflight)}
            if with_room:
                eligible = with_room
            else:
                limited = True
                eligible = {}
        preferred_all = self._rendezvous(req.affinity_key, states) \
            if self.policy == "affinity" else None
        while eligible:
            names = {n for n, st in eligible.items()
                     if st.breaker.state != "open"}
            if not names:
                break
            if self.policy == "affinity":
                pick = self._rendezvous(req.affinity_key, names)
            else:
                # the seat was assigned at submit time, so placement
                # is a function of ARRIVAL order, not of which pool
                # thread won the race to dispatch
                order = sorted(names)
                pick = order[req.rr_slot % len(order)]
            st = eligible[pick]
            if st.breaker.allow():
                return st, pick == preferred_all
            eligible.pop(pick)   # half-open probe budget spent
        if limited:
            return None, "limited"
        all_draining = bool(states) and all(
            st.health == "draining" for st in states.values())
        return None, all_draining

    # -- disaggregated prefill/decode migration -----------------------------
    def _migrate_threshold(self) -> int:
        if self.disagg_threshold_tokens is not None:
            return int(self.disagg_threshold_tokens)
        return 2 * self.page_size

    def _uncached_estimate(self, req: _FleetRequest, name: str) -> int:
        """Tokens ``name`` would have to prefill locally, per the
        router's optimistic residency view (the true answer lives on
        the replica; over-estimating only migrates pages that turn
        out to be duplicates, which import_pages dedups)."""
        cap = (len(req.prompt) - 1) // self.page_size
        seen = self._resident.get(name)
        n = 0
        if seen:
            for d in req.digests[:cap]:
                if d not in seen:
                    break
                n += 1
        return len(req.prompt) - n * self.page_size

    def _pick_prefill(self, req: _FleetRequest):
        """Rendezvous-choose a ready prefill-pool replica for this
        request's prefix family (same key as decode affinity: one
        family keeps hitting one prefill replica's cache). None when
        the fleet has no usable prefill pool."""
        with self._mu:
            pool = {n: st for n, st in self._replicas.items()
                    if st.role == "prefill"
                    and n not in req.excluded
                    and st.health not in ("draining", "unreachable")
                    and not st.warming and not st.admin_draining
                    and st.breaker.state != "open"}
        if not pool:
            return None
        pick = self._rendezvous(req.affinity_key, pool)
        st = pool[pick]
        return st if st.breaker.allow() else None

    def _maybe_migrate(self, req: _FleetRequest, dst: _ReplicaState,
                       dspan) -> None:
        """The disaggregation hot path: when the decode target would
        have to prefill a long uncached prompt locally, have a
        prefill-pool replica fill the pages instead (one-token
        generate, SAME nonce), pull the page run by digest, and
        install it on the decode replica via the digest-verified
        import. Every failure mode — prefill shed, replica lost
        mid-transfer, pages rejected on verify — degrades to the
        decode replica recomputing locally under the same pinned
        nonce: slower, never wrong, never a lost request."""
        if dst.role == "prefill":
            return                     # already landing on a prefill
        cap = (len(req.prompt) - 1) // self.page_size
        if cap <= 0:
            return
        if self._uncached_estimate(req, dst.name) \
                <= self._migrate_threshold():
            return
        pst = self._pick_prefill(req)
        if pst is None:
            return
        t0 = time.monotonic()
        mspan = None
        if dspan is not None:
            mspan = _trace.start_span(
                "llm.migrate", parent=dspan,
                attrs={"prefill_replica": pst.name,
                       "decode_replica": dst.name,
                       "pages_wanted": cap})
        mctx = mspan.context if mspan is not None else None
        self._m["dispatches"].labels(pst.name).inc()
        self._m["role_dispatches"].labels("prefill").inc()
        with self._mu:
            pst.dispatched += 1
            pst.inflight += 1
        self._m["inflight"].labels(pst.name).set(pst.inflight)
        try:
            if _faults.enabled():
                _faults.check("router.migrate")
            # 1. fill: one-token generate on the prefill replica
            # under the request's own nonce — its pages are the exact
            # pages the decode replica would have computed
            fill = pst.client.submit(
                req.prompt, max_new_tokens=1,
                temperature=req.temperature,
                deadline_s=(req.deadline.remaining()
                            if req.deadline is not None else None),
                nonce=req.nonce, trace_context=mctx)
            digs = req.digests[:cap]
            # 2. pull the page run from the source by digest list
            payload = pst.client.export_pages(
                [d.hex() for d in digs], trace_context=mctx)
            # 3. verified install on the decode target
            res = dst.client.import_pages(payload, trace_context=mctx)
            pst.breaker.record_success()
            dt = time.monotonic() - t0
            imported = int(res.get("imported", 0))
            dups = int(res.get("duplicates", 0))
            rejected = res.get("rejected") or []
            self._m["migrate_seconds"].observe(dt)
            with self._mu:
                self.n_migrations += 1
                self.n_pages_migrated += imported
                self.n_pages_rejected += len(rejected)
                self._resident.setdefault(pst.name, set()).update(digs)
                # the accepted run is a chain prefix; dups were
                # already resident
                self._resident.setdefault(dst.name, set()).update(
                    digs[:imported + dups])
            if _goodput.enabled():
                # migration wall time is time this request spent
                # waiting to start decoding — its own badput bucket
                # (not folded into queue_wait: a fleet drowning in
                # page transfers must not masquerade as queueing)
                _goodput.note("migration", dt)
            req.migrate = {"seconds": dt, "pages": imported,
                           "duplicates": dups,
                           "rejected": len(rejected),
                           "prefill": pst.name}
            # the fill's token-0 witness: the prefill replica decoded
            # one token under the request's own nonce, so its digest
            # must be the exact chain the decode replica's stream
            # starts with — checked in _verify_stream at resolution
            if isinstance(fill, dict) and fill.get("output_ids"):
                req.migrate["fill_token"] = int(fill["output_ids"][0])
                req.migrate["fill_digest"] = fill.get("stream_digest")
                req.migrate["fill_knobs"] = fill.get("knobs")
            if mspan is not None:
                mspan.set_attr("pages", imported)
                mspan.set_attr("duplicates", dups)
                mspan.set_attr("rejected", len(rejected))
                mspan.set_attr("seconds", round(dt, 6))
                mspan.end()
        except Exception as e:  # noqa: BLE001 — fallback, never fatal
            if isinstance(e, ReplicaUnavailable):
                # transport-level loss: charge the breaker and drop
                # the residency view (the replica may restart blank)
                pst.breaker.record_failure()
                pst.health = "unreachable"
                with self._mu:
                    self._resident.pop(pst.name, None)
            with self._mu:
                self.n_migrate_failed += 1
            self._m["migrate_failed"].inc()
            if _goodput.enabled():
                _goodput.note("migration", time.monotonic() - t0)
            if mspan is not None:
                mspan.set_attr("fallback", "local_recompute")
                mspan.set_status("error") \
                     .set_attr("error", str(e)).end()
        finally:
            with self._mu:
                pst.inflight -= 1
            self._m["inflight"].labels(pst.name).set(pst.inflight)

    # -- submission ---------------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens: int = 32,
               temperature: float = 0.0, deadline=None,
               priority: int = 0, tenant: Optional[str] = None,
               slo: Optional[str] = None,
               trace_context=None) -> Future:
        if self._closed:
            # typed like the engine's verdict: through serve_llm this
            # is a 503 (out of rotation), never a client-error 400
            raise EngineClosed("router closed")
        if not prompt_ids:
            raise ValueError("empty prompt")
        req = _FleetRequest(prompt_ids, max_new_tokens, temperature)
        req.tenant = tenant
        quota = self.tenants.get(tenant) if tenant else None
        if slo is None and quota is not None:
            slo = quota.slo
        cls = self.slo_classes.get(slo) if slo else None
        if cls is not None:
            if deadline is None:
                deadline = cls.deadline_s
            if priority == 0:
                priority = cls.priority
        req.deadline = as_deadline(deadline)
        req.priority = int(priority)
        req.had_deadline = req.deadline is not None
        req.slo_name = slo
        req.nonce = next(self._nonce_seq) & 0x7FFFFFFF
        req.future.request_id = req.nonce
        # one digest-chain walk serves both the affinity key and the
        # migration page list
        req.digests = page_digests(req.prompt, self.page_size)
        if req.digests:
            req.affinity_key = req.digests[:self.affinity_pages][-1]
        else:
            req.affinity_key = hashlib.blake2b(
                ",".join(map(str, req.prompt)).encode(),
                digest_size=16).digest()
        req.rr_slot = next(self._rr_seq)
        self.n_submitted += 1
        if _trace.enabled():
            # router.request roots here — or under a REMOTE parent
            # when the client itself propagated a traceparent (a
            # router fronted by serve_llm extends the caller's trace)
            req.span = _trace.start_span(
                "router.request",
                parent=_propagation.context_from(trace_context),
                attrs={
                    "prompt_tokens": len(req.prompt),
                    "nonce": req.nonce, "tenant": tenant or "",
                    "slo": slo or ""})
        # tenant quota: shed at the router — terminal, byte-lean (no
        # replica is woken for a request its tenant can't run)
        if quota is not None and quota.max_inflight is not None:
            with self._mu:
                cur = self._tenant_inflight.get(tenant, 0)
                over = cur >= quota.max_inflight
                if not over:
                    self._tenant_inflight[tenant] = cur + 1
                    req.quota_held = True
            if over:
                self._resolve_shed(
                    req, f"tenant {tenant!r} quota exhausted "
                    f"({cur}/{quota.max_inflight} in flight)",
                    reason="queue_full")
                return req.future
        # overload admission: the brownout controller may shed outright
        # (hopeless prediction, gold-only floor) or clamp the request
        # (bronze under L2) before any replica is woken. Gold never
        # reaches either branch — admit() passes protected classes
        # through untouched.
        if self.overload is not None:
            verdict = self.overload.admit(
                slo, len(req.prompt), req.max_new_tokens,
                req.deadline.remaining()
                if req.deadline is not None else None)
            shed = verdict.get("shed")
            if shed is not None:
                self._resolve_shed(req, str(shed), shed.reason,
                                   exc=shed)
                return req.future
            req.predicted_s = verdict.get("predicted_s")
            if "max_new_tokens" in verdict:
                req.max_new_tokens = int(verdict["max_new_tokens"])
            if req.deadline is not None \
                    and "deadline_factor" in verdict:
                req.deadline = as_deadline(
                    req.deadline.remaining()
                    * float(verdict["deadline_factor"]))
        with self._mu:
            self._by_id[req.nonce] = req
        self._pool.submit(self._run, req)
        return req.future

    def generate(self, prompts, max_new_tokens: int = 32,
                 temperature: float = 0.0, **kw):
        """Blocking batch convenience (mirrors ``LLMEngine.generate``)."""
        futs = [self.submit(p, max_new_tokens, temperature, **kw)
                for p in prompts]
        return [f.result() for f in futs]

    def cancel(self, request_id: int) -> bool:
        """Best-effort cancel: takes effect at the next routing
        boundary (pre-dispatch, or between failover attempts). Work
        already in flight on a replica runs to completion there; its
        result is discarded and the client still sees
        :class:`RequestCancelled`."""
        with self._mu:
            req = self._by_id.get(request_id)
        if req is None or req.future.done():
            return False
        req.cancelled = True
        return True

    # -- the dispatch loop (runs on the pool) -------------------------------
    def _resolve(self, req: _FleetRequest, result=None, exc=None,
                 outcome: str = "ok") -> None:
        with self._mu:
            self._by_id.pop(req.nonce, None)
            if req.quota_held:
                req.quota_held = False
                n = self._tenant_inflight.get(req.tenant, 1) - 1
                if n <= 0:
                    self._tenant_inflight.pop(req.tenant, None)
                else:
                    self._tenant_inflight[req.tenant] = n
        latency = time.monotonic() - req.t_submit
        self._m["latency"].observe(latency)
        # SLO accounting: every resolution is a burn-rate sample for
        # its class (cancelled requests are a client choice and burn
        # no budget — slo.py owns that policy)
        self.slo.record(req.slo_name, req.tenant, latency, outcome,
                        had_deadline=req.had_deadline)
        if req.span is not None:
            req.span.set_attr("outcome", outcome)
            req.span.set_attr("failovers", req.failovers)
            if exc is not None:
                req.span.set_status("error").set_attr("error", str(exc))
            req.span.end()
            req.span = None
        if req.future.done():
            return
        if exc is not None:
            req.future.set_exception(exc)
        else:
            req.future.set_result(result)

    def _resolve_shed(self, req: _FleetRequest, why: str,
                      reason: str, exc=None) -> None:
        self.n_shed += 1
        self._m["shed"].inc()
        if _goodput.enabled():
            # a shed request's whole router residency was wasted wall
            # — the ledger names it (precedence over the queue_wait it
            # overlaps), so brownout cost is visible, not hidden
            _goodput.note("shed", time.monotonic() - req.t_submit)
        self._resolve(req,
                      exc=exc or AdmissionShed(why, reason=reason),
                      outcome="shed")

    def _check_boundaries(self, req: _FleetRequest) -> bool:
        """Typed early outs at every routing boundary; True = resolved."""
        if req.cancelled:
            self._resolve(req, exc=RequestCancelled(
                f"request {req.nonce} cancelled at the router"),
                outcome="cancelled")
            return True
        if req.deadline is not None and req.deadline.expired:
            self._resolve(req, exc=DeadlineExceeded(
                f"request {req.nonce} deadline expired after "
                f"{req.failovers} failover(s)"), outcome="deadline")
            return True
        return False

    def _run(self, req: _FleetRequest) -> None:
        try:
            self._run_inner(req)
        except Exception as e:  # noqa: BLE001 — never lose a future
            self._resolve(req, exc=e, outcome="error")

    def _run_inner(self, req: _FleetRequest) -> None:
        while True:
            if self._check_boundaries(req):
                return
            st, flag = self._route(req)
            if st is None:
                if flag == "limited":
                    # routable replicas exist but every one sits at
                    # its AIMD limit: hold the request (this pool
                    # thread IS the queue slot) until a dispatch
                    # completes — bounded by the deadline boundary
                    # check above and the controller's max queue wait
                    waited = time.monotonic() - req.t_submit
                    if waited < self.overload.max_queue_wait_s:
                        time.sleep(0.01)
                        continue
                    self._resolve_shed(
                        req, f"concurrency-limited for {waited:.1f}s "
                        f"(AIMD limits {self.overload.limiter.state()})",
                        reason="limited",
                        exc=OverloadShed(
                            f"concurrency-limited for {waited:.1f}s: "
                            "no replica slot freed within "
                            f"{self.overload.max_queue_wait_s:.0f}s",
                            reason="limited",
                            retry_after_s=self.overload.retry_after_s(
                                "limited")))
                    return
                self._resolve_shed(
                    req, "no routable replica "
                    f"(tried {sorted(req.excluded)}, "
                    f"{len(self._replicas)} attached)",
                    reason="draining" if flag else "queue_full")
                return
            dspan = None
            if req.span is not None:
                dspan = _trace.start_span(
                    "router.dispatch", parent=req.span,
                    attrs={"replica": st.name,
                           "failovers": req.failovers})
                if req.last_dispatch is not None:
                    # a re-dispatch (failover or rebalance) links back
                    # to the attempt it replaces: the cross-replica
                    # retry reads as one story on a merged timeline
                    prev_ctx, prev_name = req.last_dispatch
                    dspan.add_link(prev_ctx, {
                        "relation": "retry_of",
                        "replica": prev_name})
                req.last_dispatch = (dspan.context, st.name)
            # disaggregated fleets: long-uncached prompts detour
            # through the prefill pool before this dispatch. Only the
            # first attempt migrates — a failover retry goes straight
            # to recompute (the fallback that cannot fail). Brownout
            # L1+ pauses the detour: a migration is optional latency
            # work, the first thing an overloaded fleet stops buying.
            if req.failovers == 0 and req.migrate is None \
                    and not req.excluded \
                    and (self.overload is None
                         or self.overload.allow_optional_work()):
                self._maybe_migrate(req, st, dspan)
            if self.policy == "affinity":
                self._m["affinity_total"].inc()
                if flag:
                    self._m["affinity_routed"].inc()
                fam = self._m["affinity_total"]
                self._m["affinity_rate"].set(
                    self._m["affinity_routed"].value
                    / max(1.0, fam.value))
            self._m["dispatches"].labels(st.name).inc()
            self._m["role_dispatches"].labels(
                st.role or "unified").inc()
            with self._mu:
                st.dispatched += 1
                st.inflight += 1
            self._m["inflight"].labels(st.name).set(st.inflight)
            try:
                if _faults.enabled():
                    _faults.check("router.dispatch")
                kw = {}
                if req.tenant is not None:
                    # tenant rides to the replica engine so
                    # llm_served_flops_total{tenant} attributes the
                    # request's cost where the FLOPs actually ran
                    kw["tenant"] = req.tenant
                out = st.client.submit(
                    req.prompt, max_new_tokens=req.max_new_tokens,
                    temperature=req.temperature,
                    deadline_s=(req.deadline.remaining()
                                if req.deadline is not None else None),
                    priority=req.priority, nonce=req.nonce, **kw,
                    # the dispatch span rides to the replica (HTTP
                    # header / direct SpanContext) so its llm.request
                    # tree shares this request's trace_id end to end
                    trace_context=(dspan.context
                                   if dspan is not None else None))
            except (AdmissionShed, EngineClosed) as e:
                # the replica refused — rebalance WITHOUT consuming
                # failover budget (nothing was lost). 503/draining
                # also updates the health view immediately instead of
                # waiting out a poll interval. A refusal is still a
                # RESPONSE: settle the breaker (a half-open probe that
                # drew a shed must not wedge the breaker half-open —
                # the breaker judges reachability, health judges load)
                st.breaker.record_success()
                if isinstance(e, EngineClosed) or \
                        getattr(e, "reason", "") == "draining":
                    st.health = "draining"
                # a shed response carrying Retry-After cools this
                # replica: _route prefers siblings until it lapses
                ra = getattr(e, "retry_after_s", None)
                if ra:
                    with self._mu:
                        self._retry_until[st.name] = \
                            time.monotonic() + float(ra)
                if self.overload is not None:
                    self.overload.on_outcome(st.name, "shed",
                                             None, 0.0)
                req.excluded.add(st.name)
                self.n_rebalanced += 1
                self._m["rebalanced"].inc()
                if dspan is not None:
                    dspan.set_attr("verdict", "shed")
                    dspan.set_status("error").end()
                continue
            except (ReplicaUnavailable, _faults.FaultInjected) as e:
                # the crash path: charge the breaker, fail over with
                # the SAME nonce while budget remains. Remember the
                # failed replica's last-known knob fingerprint — the
                # recovering sibling must be serving under the SAME
                # engine configuration or the retried stream cannot
                # be the stream the failed attempt was emitting
                if _audit.enabled():
                    req.prior_knobs = self._knobs.get(st.name)
                st.breaker.record_failure()
                st.health = "unreachable"
                req.excluded.add(st.name)
                with self._mu:
                    # a lost replica may restart with a blank pool
                    self._resident.pop(st.name, None)
                if dspan is not None:
                    dspan.set_attr("verdict", "unavailable")
                    dspan.set_status("error").end()
                if req.failovers >= self.failover_budget:
                    self._resolve(req, exc=ReplicaUnavailable(
                        f"request {req.nonce} lost replica {st.name} "
                        f"and exhausted its failover budget "
                        f"({self.failover_budget})"),
                        outcome="unavailable")
                    return
                req.failovers += 1
                self.n_failovers += 1
                self._m["failovers"].inc()
                continue
            except Exception as e:  # noqa: BLE001 — typed + terminal
                # the replica answered (504/499/400 are verdicts, not
                # crashes): settle the breaker like any response
                st.breaker.record_success()
                if dspan is not None:
                    dspan.set_attr("verdict", type(e).__name__)
                    dspan.set_status("error").end()
                outcome = ("deadline"
                           if isinstance(e, DeadlineExceeded)
                           else "cancelled"
                           if isinstance(e, RequestCancelled)
                           else "error")
                if self.overload is not None \
                        and outcome == "deadline":
                    self.overload.on_outcome(
                        st.name, "deadline", req.predicted_s,
                        time.monotonic() - req.t_submit)
                self._resolve(req, exc=e, outcome=outcome)
                return
            finally:
                with self._mu:
                    st.inflight -= 1
                self._m["inflight"].labels(st.name).set(st.inflight)
            st.breaker.record_success()
            if self.overload is not None:
                self.overload.on_outcome(
                    st.name, "ok", req.predicted_s,
                    time.monotonic() - req.t_submit)
            if dspan is not None:
                dspan.set_attr("verdict", "ok").end()
            if req.cancelled:
                # cancelled while the replica was generating: the
                # tokens are discarded, the promise is kept
                self._resolve(req, exc=RequestCancelled(
                    f"request {req.nonce} cancelled at the router"),
                    outcome="cancelled")
                return
            out["replica"] = st.name
            out["failovers"] = req.failovers
            out["request_id"] = req.nonce
            if req.migrate is not None:
                out["migrate_s"] = req.migrate["seconds"]
                out["migrated_pages"] = req.migrate["pages"]
                out["prefill_replica"] = req.migrate["prefill"]
            cap = (len(req.prompt) - 1) // self.page_size
            if cap > 0:
                with self._mu:
                    # the completed request computed (or re-used)
                    # every full prompt page on this replica
                    self._resident.setdefault(st.name, set()).update(
                        req.digests[:cap])
            if req.span is not None:
                # hand the client its trace id: one GET
                # /tracez?trace_id= on any fleet process pulls this
                # request's spans
                out["trace_id"] = req.span.trace_id
            if _audit.enabled():
                self._verify_stream(req, st, out)
            self._resolve(req, result=out)
            return

    # -- stream-integrity verification --------------------------------------
    def _verify_stream(self, req: _FleetRequest, st, out: dict) -> None:
        """Check every identity claim this resolution makes. The chain
        (``out["stream_digest"]``, folded over (nonce, position, token)
        by the replica's engine) is the witness:

        - ALWAYS: recompute the chain from the returned tokens under
          the request's pinned nonce; a mismatch means the stream and
          its digest disagree (corruption between engine and router).
          Counted under the claim being made (failover / migration) —
          or silently trusted when no claim is in play, because an
          unclaimed stream has no reference to diverge FROM; shadows
          provide that reference at ``audit_shadow_rate``.
        - failover (``req.failovers > 0``): the recovering sibling
          must also serve under the SAME engine-knob fingerprint as
          the replica that failed — a mismatched kv_dtype / draft
          sibling is a DETECTED divergence, not a doc caveat.
        - migration (``req.migrate`` carries a fill witness): the
          prefill's one-token fill ran under this request's nonce, so
          its digest IS the expected chain at position 0; the decode
          stream must extend it exactly.
        - shadow: at the sampled rate, re-execute OFF-PATH on the
          same replica under the same nonce and diff link by link.

        Never raises — a verification failure is a recorded verdict,
        not a request failure (the tokens already resolved)."""
        try:
            tokens = out.get("output_ids") or []
            digest_hex = out.get("stream_digest")
            knobs = out.get("knobs")
            if digest_hex is None:
                return              # replica predates the auditor
            claimed = bytes.fromhex(digest_hex)
            expected = _audit.chain_of(req.nonce, tokens)
            intact = claimed == expected
            with self._mu:
                if knobs is not None:
                    self._knobs[st.name] = knobs
            if req.failovers > 0:
                knob_ok = (req.prior_knobs is None
                           or req.prior_knobs == knobs)
                _audit.record(
                    self.name, "failover", intact and knob_ok,
                    position=None if intact else len(tokens),
                    chain_ours=expected, chain_theirs=claimed,
                    request_id=req.nonce, nonce=req.nonce,
                    knobs_ours=knobs, knobs_theirs=req.prior_knobs,
                    detail=(f"nonce-pinned failover to {st.name} "
                            f"after {req.failovers} failover(s): "
                            + ("chain intact" if intact else
                               "returned digest does not match the "
                               "returned tokens")
                            + ("" if knob_ok else
                               "; engine knob fingerprint differs "
                               "from the failed sibling's")))
            mig = req.migrate
            if mig is not None and mig.get("fill_digest") and tokens:
                fill_chain = bytes.fromhex(mig["fill_digest"])
                ok = _audit.verify_prefix(req.nonce, tokens,
                                          fill_chain, 1)
                _audit.record(
                    self.name, "migration", ok,
                    position=None if ok else 0,
                    chain_ours=_audit.chain_of(req.nonce, tokens[:1]),
                    chain_theirs=fill_chain,
                    request_id=req.nonce, nonce=req.nonce,
                    knobs_ours=knobs,
                    knobs_theirs=mig.get("fill_knobs"),
                    detail=(f"migrated-pages decode on {st.name} vs "
                            f"prefill fill on {mig['prefill']}: "
                            "the decode stream must extend the "
                            "fill's position-0 chain"))
            if _audit.sampled(req.nonce, _audit.shadow_rate()) \
                    and (self.overload is None
                         or self.overload.allow_optional_work()):
                # off-path: the caller's future resolves regardless;
                # the shadow rides the dispatch pool. Brownout L1+
                # sheds the sample — determinism proof is optional
                # work an overloaded fleet stops buying first.
                self.n_shadows += 1
                self._pool.submit(self._shadow, req, st, dict(out))
        except Exception:  # noqa: BLE001 — auditing must never
            pass           # turn a served request into a failure

    def _shadow(self, req: _FleetRequest, st, out: dict) -> None:
        """Sampled shadow re-execution: re-run the request on the SAME
        replica under the SAME nonce, directly against its client (not
        :meth:`submit` — a shadow must not be re-shadowed, shed, or
        failed over), and diff the chains link by link. The wall time
        lands in the ``audit`` badput bucket — determinism proof is a
        cost the goodput ledger must own, not hide."""
        t0 = time.monotonic()
        try:
            ref = st.client.submit(
                req.prompt, max_new_tokens=req.max_new_tokens,
                temperature=req.temperature, nonce=req.nonce)
            tokens = out.get("output_ids") or []
            ref_tokens = ref.get("output_ids") or []
            pos = _audit.first_divergence(tokens, ref_tokens)
            ours = out.get("stream_digest")
            theirs = ref.get("stream_digest")
            _audit.record(
                self.name, "shadow", pos is None and ours == theirs,
                position=pos,
                chain_ours=(bytes.fromhex(ours) if ours else None),
                chain_theirs=(bytes.fromhex(theirs) if theirs
                              else None),
                request_id=req.nonce, nonce=req.nonce,
                knobs_ours=out.get("knobs"),
                knobs_theirs=ref.get("knobs"),
                detail=(f"shadow re-execution on {st.name}: same "
                        f"replica, same nonce, chain-vs-chain"))
        except Exception:  # noqa: BLE001 — a failed shadow is a
            pass           # missed sample, never an incident
        finally:
            if _goodput.enabled():
                _goodput.note("audit", time.monotonic() - t0)

    # -- observability surfaces ---------------------------------------------
    def _status(self) -> Optional[dict]:
        if self._closed:
            return None
        with self._mu:
            states = list(self._replicas.values())
            tenants = dict(self._tenant_inflight)
        return {
            "policy": self.policy,
            "submitted": self.n_submitted,
            "failovers": self.n_failovers,
            "rebalanced": self.n_rebalanced,
            "shed": self.n_shed,
            "tenant_inflight": tenants,
            "migrations": {
                "completed": self.n_migrations,
                "failed": self.n_migrate_failed,
                "pages": self.n_pages_migrated,
                "pages_rejected": self.n_pages_rejected,
            },
            "drift": dict(_audit.instance().counts(),
                          shadows=self.n_shadows),
            "replicas": {st.name: {
                "health": st.health,
                "breaker": st.breaker.state,
                "breaker_opens": st.breaker.n_opens,
                "inflight": st.inflight,
                "dispatched": st.dispatched,
                "from_membership": st.from_membership,
                "warming": st.warming,
                "admin_draining": st.admin_draining,
                "role": st.role or "unified",
            } for st in states},
        }

    def _aggregate_health(self) -> Optional[str]:
        if self._closed:
            return None
        with self._mu:
            states = list(self._replicas.values())
        # warming replicas are expected capacity-in-progress, not
        # sickness: they neither count as routable nor drag the
        # aggregate toward degraded
        considered = [st for st in states if not st.warming]
        routable = [st for st in considered
                    if st.health != "draining"
                    and not st.admin_draining
                    and st.breaker.state != "open"]
        if not routable:
            return "draining"
        if len(routable) < len(considered):
            return "degraded"
        return "healthy"

    def _slo_health(self) -> Optional[str]:
        """The /healthz breach-latch component: a latched SLO breach
        shows as degraded until an operator acknowledges it."""
        if self._closed:
            return None
        return self.slo.health()

    def _sloz(self) -> Optional[dict]:
        if self._closed:
            return None
        return self.slo.report()

    def _render_federated(self) -> Optional[str]:
        if self._closed or self.scraper is None:
            return None
        return self.scraper.render_prometheus()

    def _fleetz(self) -> Optional[dict]:
        """The /fleetz payload: the router's per-replica view (health,
        breaker, dispatch counts) joined with the scraper's per-replica
        metrics digest, plus the computed fleet aggregates."""
        if self._closed:
            return None
        with self._mu:
            states = list(self._replicas.values())
        scraped = self.scraper.replica_report() \
            if self.scraper is not None else {}
        replicas = {}
        roles: Dict[str, dict] = {}
        for st in states:
            entry = {
                "health": st.health,
                "breaker": st.breaker.state,
                "breaker_opens": st.breaker.n_opens,
                "inflight": st.inflight,
                "dispatched": st.dispatched,
                "from_membership": st.from_membership,
                "warming": st.warming,
                "admin_draining": st.admin_draining,
                "role": st.role or "unified",
            }
            entry["metrics"] = scraped.pop(st.name, None)
            replicas[st.name] = entry
            # per-role pool state: a down replica is a DOWN count, a
            # hole in ready capacity — never a ready entry of zero
            r = roles.setdefault(st.role or "unified", {
                "attached": 0, "ready": 0, "warming": 0,
                "draining": 0, "down": 0})
            r["attached"] += 1
            if st.warming:
                r["warming"] += 1
            elif st.admin_draining or st.health == "draining":
                r["draining"] += 1
            elif st.breaker.state == "open" or \
                    st.health in ("unreachable", "unknown"):
                r["down"] += 1
            else:
                r["ready"] += 1
        # scrapes for since-detached replicas, if any, still show
        for name, digest in scraped.items():
            replicas[name] = {"health": "detached", "metrics": digest}
        out = {
            "policy": self.policy,
            "replicas": replicas,
            "roles": roles,
            "submitted": self.n_submitted,
            "failovers": self.n_failovers,
            "rebalanced": self.n_rebalanced,
            "shed": self.n_shed,
            "migrations": {
                "completed": self.n_migrations,
                "failed": self.n_migrate_failed,
                "pages": self.n_pages_migrated,
                "pages_rejected": self.n_pages_rejected,
            },
            "drift": dict(_audit.instance().counts(),
                          shadows=self.n_shadows),
        }
        if self.scraper is not None:
            out["aggregates"] = self.scraper.aggregates()
        return out

    # -- lifecycle ----------------------------------------------------------
    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self.overload is not None:
            self.remove_poll_hook(self.overload.tick)
            self.overload.unbind()
        _dbgsrv.unregister_status_provider(self._status_name)
        _dbgsrv.unregister_health_provider(self._status_name)
        _dbgsrv.unregister_health_provider(self._status_name + "_slo")
        _dbgsrv.unregister_reset_handler(self._status_name)
        _dbgsrv.unregister_fleet_provider(self._status_name)
        _dbgsrv.unregister_slo_provider(self._status_name)
        _dbgsrv.unregister_scrape_provider(self._status_name)
        self._stop.set()
        self._poller.join(timeout=10)
        # in-flight dispatches run to completion and resolve their
        # futures; new submits are already refused
        self._pool.shutdown(wait=True)
        with self._mu:
            leftovers = list(self._by_id.values())
        for req in leftovers:
            self._resolve(req, exc=EngineClosed("router closed"),
                          outcome="closed")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
