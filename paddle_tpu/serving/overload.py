"""Overload brownout controller: deadline-aware admission, adaptive
per-replica concurrency, and a reversible fleet degradation ladder.

Under sustained overload the fleet's only pre-PR-20 defense was the
engine's blunt ``max_pending`` queue-full shed: the router happily
dispatched requests that were already hopeless, every SLO class
degraded at once, and the autoscaler's WARMING gap (spawn → READY is
tens of seconds) was exactly the window where gold traffic burned its
error budget. This module makes overload a MANAGED mode — three
cooperating mechanisms behind one :class:`OverloadController`:

HOPELESS SHEDDING (:class:`ServiceTimeEstimator`). Before any prefill
work is done, predict the request's service time from the PR 11 perf
registry (realized prefill/decode token rates) plus current queue
residency, and shed requests whose deadline cannot be met with a
typed :class:`~paddle_tpu.inference.llm.OverloadShed` carrying the
prediction — shedding a doomed request in 0.1 ms is strictly better
than failing it after 2 s of stolen compute. The estimator is
CONSERVATIVE: it sheds only when ``predicted > deadline ×
safety_factor`` (default 3×), a cold start with no perf history never
sheds, and its own accuracy is a metric
(``overload_estimate_error_ratio`` histogram of realized/predicted).
Protected classes (gold) are never hopeless-shed: their failure mode
is a deadline miss the SLO tracker burns honestly, never a shed the
operator didn't choose.

ADAPTIVE CONCURRENCY (:class:`AIMDLimiter`). An AIMD limiter bounds
the router's in-flight dispatches per replica: additive raise on
clean completions, multiplicative cut on deadline misses and shed
verdicts, floor/ceiling bounds, injectable clock. A slow replica
self-throttles instead of accumulating a doomed backlog; the realized
limit is the ``overload_limit{replica}`` gauge.

BROWNOUT LADDER (:class:`BrownoutLadder`). Ordered, REVERSIBLE
degradation levels latched off the LIVE SLO burn windows
(``SLOTracker.window_status()`` — never the sticky breach latch, the
PR 12 discipline) with ElasticManager-style hysteresis/dwell so a
square-wave burn signal cannot flap the fleet:

    L0 normal
    L1 shed optional work: audit shadows off, migration detours off
    L2 clamp bronze ``max_new_tokens`` + tighten bronze deadlines
    L3 bronze shed — gold-only admission

Gold (any class in ``protected_classes``) is NEVER degraded below its
SLO by any level. Every transition logs its inputs (burn rates,
limiter state, warming count) and output (level + reason) to a
bounded log on ``GET /overloadz``; the ladder coordinates with the
autoscaler (brownout engages while replicas are WARMING; it steps
down as ``mark_ready`` capacity lands and the live windows decay) and
federates as ``fleet_brownout_level`` (max over UP replicas,
hole-not-zero).

Seeded chaos hooks: ``overload.estimate`` forces a wildly-wrong
service-time prediction; ``overload.step`` forces a spurious (but
reversible) ladder transition. Both replay from seed
(``tools/chaos_soak.py --ci --overload``).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, Optional

from ..inference.llm import OverloadShed
from ..observability import metrics as _obs
from ..observability import perf as _perf
from ..observability import server as _dbgsrv
from ..reliability import faults as _faults
from ..reliability.retry import backoff_delay

# ladder levels, in escalation order; the names are the /overloadz and
# docs vocabulary (docs/RELIABILITY.md "Overload failure model")
LEVELS = ("normal", "shed_optional", "clamp_bronze", "gold_only")
MAX_LEVEL = len(LEVELS) - 1

TRANSITION_LOG_CAP = 64


class AIMDLimiter:
    """Additive-increase / multiplicative-decrease concurrency bound,
    one limit per replica name.

    Clean completions raise the limit by ``raise_step`` (additive);
    deadline misses and shed verdicts cut it by ``cut_factor``
    (multiplicative), at most once per ``cut_interval_s`` per replica
    — a burst of misses from ONE overload event is one congestion
    signal, not N (the TCP discipline). Limits are clamped to
    [floor, ceiling]; a fresh replica starts at ``initial``
    (default: the ceiling — optimistic, the first misses pull it
    down). ``clock`` is injectable for tests."""

    def __init__(self, floor: int = 1, ceiling: int = 32,
                 initial: Optional[float] = None,
                 raise_step: float = 1.0, cut_factor: float = 0.5,
                 cut_interval_s: float = 0.25,
                 clock=time.monotonic):
        if not (0 < floor <= ceiling):
            raise ValueError(f"need 0 < floor <= ceiling, got "
                             f"{floor}/{ceiling}")
        if not (0.0 < cut_factor < 1.0):
            raise ValueError(f"cut_factor must be in (0, 1), got "
                             f"{cut_factor}")
        self.floor = int(floor)
        self.ceiling = int(ceiling)
        self.initial = float(ceiling if initial is None else initial)
        self.raise_step = float(raise_step)
        self.cut_factor = float(cut_factor)
        self.cut_interval_s = float(cut_interval_s)
        self._clock = clock
        self._mu = threading.Lock()
        self._limits: Dict[str, float] = {}
        self._last_cut: Dict[str, float] = {}
        self.n_cuts = 0

    def _clamp(self, v: float) -> float:
        return max(float(self.floor), min(float(self.ceiling), v))

    def limit(self, name: str) -> int:
        """The integer in-flight bound for ``name`` right now."""
        with self._mu:
            return int(self._limits.get(name, self.initial))

    def has_room(self, name: str, inflight: int) -> bool:
        return int(inflight) < self.limit(name)

    def on_success(self, name: str) -> None:
        """Additive raise on a clean completion."""
        with self._mu:
            cur = self._limits.get(name, self.initial)
            self._limits[name] = self._clamp(cur + self.raise_step)

    def on_miss(self, name: str) -> bool:
        """Multiplicative cut on a deadline miss / shed verdict.
        Returns True when a cut was applied (False inside the
        ``cut_interval_s`` cooldown — that miss rode an
        already-priced congestion event)."""
        now = self._clock()
        with self._mu:
            if now - self._last_cut.get(name, -1e18) \
                    < self.cut_interval_s:
                return False
            cur = self._limits.get(name, self.initial)
            self._limits[name] = self._clamp(cur * self.cut_factor)
            self._last_cut[name] = now
            self.n_cuts += 1
            return True

    def forget(self, name: str) -> None:
        """Drop a detached replica's state (a re-attached same name
        re-earns its limit from ``initial``)."""
        with self._mu:
            self._limits.pop(name, None)
            self._last_cut.pop(name, None)

    def state(self) -> Dict[str, int]:
        """Snapshot for /overloadz and the transition log."""
        with self._mu:
            return {n: int(v) for n, v in sorted(self._limits.items())}


class ServiceTimeEstimator:
    """Deadline-aware admission: predicted service seconds from the
    perf registry's realized token rates.

    ``predict`` returns None on a COLD START (perf disabled, or no
    llm prefill/decode program has accumulated ``min_busy_s`` of
    wall time yet) — a request is never shed on a guess the registry
    can't back. ``hopeless`` applies the conservative factor: shed
    only when ``predicted > deadline × safety_factor``. ``source`` is
    injectable for tests: a zero-arg callable returning
    ``(prefill_tokens_per_s, decode_tokens_per_s)`` or None."""

    def __init__(self, safety_factor: float = 3.0,
                 min_busy_s: float = 0.05, source=None):
        if safety_factor < 1.0:
            raise ValueError("safety_factor < 1 would shed requests "
                             "the estimator itself predicts feasible")
        self.safety_factor = float(safety_factor)
        self.min_busy_s = float(min_busy_s)
        self._source = source

    def rates(self):
        """(prefill_tok/s, decode_tok/s) from the perf registry, or
        None before enough history exists. Prefill falls back to the
        decode rate when only decode programs have run (shorter
        prompts than history — still conservative: prefill is the
        faster phase per token)."""
        if self._source is not None:
            return self._source()
        if not _perf.enabled():
            return None
        pre_s = pre_t = dec_s = dec_t = 0.0
        for h in _perf.instance().programs():
            if h.component != "llm":
                continue
            if h.kind.startswith("prefill"):
                pre_s += h.seconds
                pre_t += h.tokens
            elif h.kind.startswith("decode") or \
                    h.kind.startswith("spec"):
                dec_s += h.seconds
                dec_t += h.tokens
        if dec_s < self.min_busy_s or dec_t <= 0:
            return None                      # cold start: never shed
        dec_rate = dec_t / dec_s
        pre_rate = (pre_t / pre_s) \
            if (pre_s >= self.min_busy_s and pre_t > 0) else dec_rate
        return pre_rate, dec_rate

    def predict(self, prompt_len: int, max_new_tokens: int,
                queue_s: float = 0.0) -> Optional[float]:
        """Predicted wall seconds for this request: prefill + decode
        at realized rates, plus the caller's queue-residency estimate.
        None = no history (cold start). The ``overload.estimate``
        fault site distorts the prediction 1000× — chaos proof that a
        wildly-wrong estimator degrades to visible shed/miss verdicts,
        never to hangs or silent corruption."""
        r = self.rates()
        if r is None:
            return None
        pre_rate, dec_rate = r
        if pre_rate <= 0 or dec_rate <= 0:
            return None
        p = (prompt_len / pre_rate) + (max_new_tokens / dec_rate) \
            + max(0.0, float(queue_s))
        if _faults.enabled():
            try:
                _faults.check("overload.estimate")
            except _faults.FaultInjected:
                p *= 1000.0
        return p

    def hopeless(self, predicted: Optional[float],
                 deadline_s: Optional[float]) -> bool:
        if predicted is None or deadline_s is None:
            return False
        return predicted > float(deadline_s) * self.safety_factor


class BrownoutLadder:
    """The reversible degradation ladder with ElasticManager-style
    damping: one level per step, a dwell before any move, and an
    exponential backoff curve on direction FLIPS so a square-wave
    pressure signal converges instead of flapping.

    ``step(pressure, ...)`` moves at most one level toward the signal:
    up when ``pressure`` (some class's live windows all burn above
    threshold), down when clear. A move in the SAME direction as the
    last one waits its dwell (``up_dwell_s`` / ``down_dwell_s``,
    asymmetric — escalate fast, recover deliberately); a FLIP
    additionally waits ``backoff_delay(flips-1, backoff_base_s)``
    capped at ``backoff_cap_s``, so each reversal doubles the quiet
    time and the flap count under a square wave is logarithmic. The
    flip streak resets after ``healthy_dwell_s`` without any
    transition. ``clock`` is injectable."""

    def __init__(self, up_dwell_s: float = 0.5,
                 down_dwell_s: float = 2.0,
                 backoff_base_s: float = 1.0,
                 backoff_cap_s: float = 30.0,
                 healthy_dwell_s: Optional[float] = None,
                 max_level: int = MAX_LEVEL,
                 clock=time.monotonic):
        self.up_dwell_s = float(up_dwell_s)
        self.down_dwell_s = float(down_dwell_s)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self.healthy_dwell_s = float(
            2.0 * down_dwell_s if healthy_dwell_s is None
            else healthy_dwell_s)
        self.max_level = int(max_level)
        self._clock = clock
        self._mu = threading.Lock()
        self.level = 0
        self._last_change: Optional[float] = None
        self._last_dir = 0           # +1 up, -1 down, 0 never moved
        self._flips = 0              # direction reversals in a row
        self.n_transitions = 0
        self.log = deque(maxlen=TRANSITION_LOG_CAP)

    def _curve(self) -> float:
        return backoff_delay(max(self._flips - 1, 0),
                             self.backoff_base_s,
                             cap=self.backoff_cap_s)

    def _record(self, now, frm, to, reason, inputs) -> None:
        self.n_transitions += 1
        self.log.append({
            "t": round(now, 4), "from": frm, "to": to,
            "from_level": LEVELS[frm], "to_level": LEVELS[to],
            "reason": reason, "inputs": dict(inputs or {})})

    def step(self, pressure: bool, inputs: Optional[dict] = None,
             reason: str = "") -> int:
        """Advance the ladder one tick against the live signal;
        returns the (possibly unchanged) level."""
        now = self._clock()
        with self._mu:
            want = 0
            if pressure and self.level < self.max_level:
                want = 1
            elif not pressure and self.level > 0:
                want = -1
            since = (now - self._last_change) \
                if self._last_change is not None else None
            # a long quiet stretch forgives the flip history — the
            # next storm is a NEW story, not a continuation
            if since is not None and since >= self.healthy_dwell_s:
                self._flips = 0
            if want == 0:
                return self.level
            dwell = self.up_dwell_s if want > 0 else self.down_dwell_s
            if since is not None:
                need = dwell
                if want != self._last_dir and self._last_dir != 0:
                    need = max(dwell, self._curve())
                if since < need:
                    return self.level
            if want != self._last_dir and self._last_dir != 0:
                self._flips += 1
            frm = self.level
            self.level = frm + want
            self._last_dir = want
            self._last_change = now
            self._record(now, frm, self.level,
                         reason or ("burn_tripped" if want > 0
                                    else "burn_clear"), inputs)
            return self.level

    def force(self, level: int, reason: str,
              inputs: Optional[dict] = None) -> int:
        """Jump to ``level`` unconditionally (the ``overload.step``
        chaos hook and operator overrides). The jump is logged and
        REVERSIBLE — it updates the dwell clock like any transition,
        so the normal :meth:`step` hysteresis walks it back when the
        live signal disagrees."""
        level = max(0, min(self.max_level, int(level)))
        now = self._clock()
        with self._mu:
            if level == self.level:
                return self.level
            frm = self.level
            want = 1 if level > frm else -1
            if want != self._last_dir and self._last_dir != 0:
                self._flips += 1
            self.level = level
            self._last_dir = want
            self._last_change = now
            self._record(now, frm, level, reason, inputs)
            return self.level

    def transitions(self) -> list:
        with self._mu:
            return list(self.log)


def _controller_metrics(reg):
    return {
        "shed": reg.counter(
            "overload_shed_total",
            "requests shed by the overload controller, by verdict "
            "('hopeless': predicted service time cannot meet the "
            "deadline; 'brownout': ladder level admits protected "
            "classes only)",
            label_names=("reason",)),
        "limit": reg.gauge(
            "overload_limit",
            "AIMD per-replica concurrency limit the router enforces "
            "(additive raise on clean completions, multiplicative "
            "cut on deadline misses/sheds)",
            label_names=("replica",)),
        "level": reg.gauge(
            "brownout_level",
            "current degradation-ladder level: 0 normal, 1 shed "
            "optional work, 2 clamp bronze, 3 gold-only admission"),
        "err": reg.histogram(
            "overload_estimate_error_ratio",
            "realized / predicted service time for admitted requests "
            "that carried a prediction (1.0 = perfect; the hopeless-"
            "shed estimator's own accuracy)"),
    }


class OverloadController:
    """The one object the router talks to: ties the estimator, the
    limiter, and the ladder behind an ``admit()`` /
    ``on_outcome()`` / ``tick()`` surface.

    ``protected_classes`` (default ``("gold",)``) are NEVER degraded:
    no ladder level sheds or clamps them and the hopeless-shed
    estimator does not apply (their failure mode is an honest
    deadline miss, never a shed the operator didn't choose).
    ``bronze_max_new_tokens`` / ``bronze_deadline_factor`` are the L2
    clamp knobs for everything else. Constructed standalone and
    passed to :class:`~paddle_tpu.serving.Router` via ``overload=``;
    the router binds it, runs :meth:`tick` on the health-poll cadence
    and consults :meth:`admit` per submission. Disabled-path cost on
    a router WITHOUT a controller is one ``is None`` check."""

    def __init__(self, protected_classes=("gold",),
                 estimator: Optional[ServiceTimeEstimator] = None,
                 limiter: Optional[AIMDLimiter] = None,
                 ladder: Optional[BrownoutLadder] = None,
                 bronze_max_new_tokens: int = 16,
                 bronze_deadline_factor: float = 0.5,
                 max_queue_wait_s: float = 30.0,
                 retry_after_base_s: float = 0.1,
                 service_ewma_alpha: float = 0.2,
                 registry=None, clock=time.monotonic,
                 name: str = "overload"):
        self.protected = frozenset(protected_classes or ())
        self.estimator = estimator or ServiceTimeEstimator()
        self.limiter = limiter or AIMDLimiter()
        self.ladder = ladder or BrownoutLadder(clock=clock)
        self.bronze_max_new_tokens = int(bronze_max_new_tokens)
        self.bronze_deadline_factor = float(bronze_deadline_factor)
        self.max_queue_wait_s = float(max_queue_wait_s)
        self.retry_after_base_s = float(retry_after_base_s)
        self._alpha = float(service_ewma_alpha)
        self._clock = clock
        self.name = name
        self._mu = threading.Lock()
        self._router = None
        self._provider_name: Optional[str] = None
        self._m = _controller_metrics(
            registry if registry is not None
            else _obs.default_registry())
        self._ewma_service: Optional[float] = None
        self.n_shed: Dict[str, int] = {}
        self.n_ticks = 0

    # -- wiring --------------------------------------------------------------
    @property
    def level(self) -> int:
        return self.ladder.level

    def bind(self, router) -> None:
        """Attach to a router: /overloadz provider + the brownout
        gauge arm here (level 0 is a real, exported verdict from a
        BOUND controller — an unbound one exports nothing, the
        hole-not-zero discipline)."""
        self._router = router
        self._provider_name = f"{router.name}_{id(router):x}"
        _dbgsrv.register_overload_provider(self._provider_name,
                                           self._overloadz)
        self._m["level"].set(self.ladder.level)

    def unbind(self) -> None:
        if self._provider_name is not None:
            _dbgsrv.unregister_overload_provider(self._provider_name)
            self._provider_name = None
        self._router = None

    # -- the control loop (health-poll cadence) ------------------------------
    def tick(self) -> int:
        """One controller step: read the LIVE burn windows + fleet
        load, walk the ladder one level toward the signal, refresh
        gauges. Runs as a router poll hook; also callable directly
        (tests drive it with an injected clock)."""
        r = self._router
        if r is None:
            return self.ladder.level
        self.n_ticks += 1
        status = r.slo.window_status()
        load = r.fleet_load()
        tripped = sorted(c for c, s in status.items()
                         if s.get("tripped"))
        burns = {c: {w: v["burn_rate"]
                     for w, v in s["windows"].items()}
                 for c, s in status.items()}
        inputs = {"burn": burns, "tripped": tripped,
                  "warming": load.get("warming", 0),
                  "ready": load.get("ready", 0),
                  "inflight": load.get("inflight", 0),
                  "limiter": self.limiter.state()}
        if _faults.enabled():
            try:
                _faults.check("overload.step")
            except _faults.FaultInjected as e:
                # a spurious, seeded transition: one level up, logged
                # with the fault as its reason. Reversible by design —
                # the live windows disagree, so the normal hysteresis
                # walks it back down (chaos pins exactly that).
                self.ladder.force(self.ladder.level + 1,
                                  reason=f"fault_injected:{e}",
                                  inputs=inputs)
        level = self.ladder.step(bool(tripped), inputs=inputs)
        self._m["level"].set(level)
        for rname, lim in self.limiter.state().items():
            self._m["limit"].labels(rname).set(lim)
        return level

    # -- admission (router submit path) --------------------------------------
    def _count_shed(self, reason: str) -> None:
        with self._mu:
            self.n_shed[reason] = self.n_shed.get(reason, 0) + 1
        self._m["shed"].labels(reason).inc()

    def queue_estimate(self) -> float:
        """Expected queue residency: mean in-flight per ready replica
        × the EWMA of realized service time (0 before either signal
        exists — conservative, the estimator under-predicts)."""
        r = self._router
        with self._mu:
            svc = self._ewma_service
        if r is None or svc is None:
            return 0.0
        load = r.fleet_load()
        ready = load.get("ready") or 0
        if not ready:
            return 0.0
        return (load.get("inflight", 0) / ready) * svc

    def admit(self, slo: Optional[str], prompt_len: int,
              max_new_tokens: int,
              deadline_s: Optional[float]) -> dict:
        """The per-request verdict, pre-dispatch. Returns a dict:
        ``{"shed": OverloadShed}`` to refuse, else optionally
        ``max_new_tokens`` (L2 clamp), ``deadline_factor`` (L2
        tightening) and ``predicted_s`` (for the accuracy histogram).
        Protected classes pass through untouched at every level."""
        level = self.ladder.level
        out: dict = {}
        if slo in self.protected:
            return out
        if level >= 3:
            self._count_shed("brownout")
            out["shed"] = OverloadShed(
                f"brownout level {level} ({LEVELS[level]}): only "
                f"protected classes admitted "
                f"(request class {slo or 'unclassified'!r})",
                reason="brownout",
                retry_after_s=self.retry_after_s("brownout"))
            return out
        if level >= 2:
            if max_new_tokens > self.bronze_max_new_tokens:
                max_new_tokens = self.bronze_max_new_tokens
                out["max_new_tokens"] = max_new_tokens
            if deadline_s is not None:
                deadline_s = deadline_s * self.bronze_deadline_factor
                out["deadline_factor"] = self.bronze_deadline_factor
        predicted = self.estimator.predict(
            prompt_len, max_new_tokens, queue_s=self.queue_estimate())
        if predicted is not None:
            out["predicted_s"] = predicted
            if self.estimator.hopeless(predicted, deadline_s):
                self._count_shed("hopeless")
                out["shed"] = OverloadShed(
                    f"hopeless: predicted {predicted:.3f}s cannot "
                    f"meet the {deadline_s:.3f}s deadline "
                    f"(safety_factor "
                    f"{self.estimator.safety_factor:g})",
                    reason="hopeless", predicted_s=predicted,
                    deadline_s=deadline_s,
                    retry_after_s=self.retry_after_s("hopeless"))
        return out

    def allow_optional_work(self) -> bool:
        """L1 gate: audit shadows and migration detours run only at
        level 0 (cut optional work FIRST — before any client-visible
        degradation)."""
        return self.ladder.level < 1

    def retry_after_s(self, reason: str = "queue_full") -> float:
        """The Retry-After a shed response should carry: the base
        backoff doubled per ladder level — a fleet deep in brownout
        tells clients to stay away longer, which is the actual
        anti-thundering-herd mechanism (serve_llm forwards this as
        the HTTP header; HTTPReplica/router honor it)."""
        return round(self.retry_after_base_s
                     * (2.0 ** self.ladder.level), 3)

    # -- outcome feedback (router resolution path) ---------------------------
    def on_outcome(self, replica: Optional[str], outcome: str,
                   predicted_s: Optional[float],
                   latency_s: float) -> None:
        """Feedback from a resolved dispatch: AIMD raise/cut, the
        estimate-accuracy histogram, and the service-time EWMA the
        queue-residency estimate rides."""
        if replica is not None:
            if outcome == "ok":
                self.limiter.on_success(replica)
            elif outcome in ("deadline", "shed"):
                self.limiter.on_miss(replica)
        if outcome == "ok":
            with self._mu:
                if self._ewma_service is None:
                    self._ewma_service = float(latency_s)
                else:
                    self._ewma_service += self._alpha * (
                        float(latency_s) - self._ewma_service)
        if predicted_s and predicted_s > 0 \
                and outcome in ("ok", "deadline"):
            self._m["err"].observe(latency_s / predicted_s)

    def forget(self, replica: str) -> None:
        self.limiter.forget(replica)

    # -- observability -------------------------------------------------------
    def _overloadz(self) -> Optional[dict]:
        if self._router is None:
            return None
        with self._mu:
            shed = dict(self.n_shed)
            svc = self._ewma_service
        return {
            "level": self.ladder.level,
            "level_name": LEVELS[self.ladder.level],
            "levels": list(LEVELS),
            "protected_classes": sorted(self.protected),
            "ticks": self.n_ticks,
            "transitions": self.ladder.transitions(),
            "limiter": {
                "limits": self.limiter.state(),
                "floor": self.limiter.floor,
                "ceiling": self.limiter.ceiling,
                "cuts": self.limiter.n_cuts,
            },
            "estimator": {
                "safety_factor": self.estimator.safety_factor,
                "rates": (lambda r: None if r is None else
                          {"prefill_tokens_per_s": round(r[0], 2),
                           "decode_tokens_per_s": round(r[1], 2)})(
                    self._rates_safe()),
                "service_ewma_s": (round(svc, 4)
                                   if svc is not None else None),
            },
            "shed": shed,
            "retry_after_s": self.retry_after_s(),
        }

    def _rates_safe(self):
        try:
            return self.estimator.rates()
        except Exception:  # noqa: BLE001 — a status page never raises
            return None
