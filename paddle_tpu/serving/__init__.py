"""Fault-tolerant serving fleet: engine replicas + prefix-affinity
router with failover (docs/RELIABILITY.md "Fleet failure model").

The single-process :class:`~paddle_tpu.inference.LLMEngine` scales out
here: a :class:`Router` fronts K replicas (in-process engines, spawned
subprocesses, or attached endpoints; membership via the rendezvous
TCPStore), routing by prefix affinity, breaking circuits on failing
replicas, and failing crashed requests over within their retry budget
— token-identically, because the router pins each request's sampling
nonce and all replicas share weights and seed.

    from paddle_tpu.serving import Router, LocalReplica
    router = Router({"r0": LocalReplica(eng0),
                     "r1": LocalReplica(eng1)})
    out = router.submit(prompt_ids, deadline=5.0).result()
"""

from .autoscaler import (Autoscaler, SubprocessReplica,
                         make_subprocess_spawner)
from .breaker import CircuitBreaker
from .fleet import FleetScraper, parse_prometheus_text
from .overload import (AIMDLimiter, BrownoutLadder, OverloadController,
                       ServiceTimeEstimator)
from .replica import (HTTPReplica, LocalReplica, ReplicaUnavailable,
                      build_net_from_spec, make_engine_from_spec,
                      spawn_replica, terminate_replica)
from .router import Router, SLOClass, TenantQuota

__all__ = [
    "AIMDLimiter",
    "Autoscaler",
    "BrownoutLadder",
    "SubprocessReplica",
    "make_subprocess_spawner",
    "CircuitBreaker",
    "FleetScraper",
    "parse_prometheus_text",
    "HTTPReplica",
    "LocalReplica",
    "OverloadController",
    "ReplicaUnavailable",
    "Router",
    "SLOClass",
    "ServiceTimeEstimator",
    "TenantQuota",
    "build_net_from_spec",
    "make_engine_from_spec",
    "spawn_replica",
    "terminate_replica",
]
