"""Engine replicas: the router's uniform view of one serving engine.

A replica is anything with the small ``submit/health/cancel`` surface
below — the router neither knows nor cares whether the engine runs in
this process or behind an HTTP endpoint three hosts away:

- :class:`LocalReplica` — wraps an in-process :class:`LLMEngine`
  (tests, benches, single-host multi-engine layouts).
- :class:`HTTPReplica` — wraps a remote ``serve_llm`` endpoint plus
  its debug server's ``/healthz``; maps the pinned HTTP error contract
  (429/503/504/499) back to the typed exceptions, and maps transport
  failures (connection refused/reset — the crashed-replica signature)
  to :class:`ReplicaUnavailable`, the one error the router treats as
  "fail over and charge the breaker".
- :func:`spawn_replica` / ``python -m paddle_tpu.serving.replica`` —
  a self-contained replica subprocess for the fleet chaos soak and
  local scale-out: builds a model from a JSON spec, serves it, exposes
  the debug surface, registers TCPStore membership, and honors an
  injected ``replica.crash`` fault by dying hard (``os._exit``), the
  way a SIGKILL would take it.

All replicas in a fleet must be built from the same model weights and
engine ``seed`` for failover to be token-identical (the router pins
each request's sampling nonce; see ``LLMEngine.submit(nonce=)``).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Optional

from ..inference.llm import (AdmissionShed, AdmissionTimeout,
                             RequestCancelled)
from ..reliability.retry import DeadlineExceeded


class ReplicaUnavailable(RuntimeError):
    """The replica could not be reached or died mid-request
    (connection refused/reset, empty response, unexpected 5xx). The
    router's verdict for this error: charge the circuit breaker and
    fail the request over to a sibling."""


class LocalReplica:
    """In-process replica over an ``LLMEngine`` (or anything with its
    submit/cancel/health surface)."""

    def __init__(self, engine):
        self.engine = engine

    def submit(self, prompt_ids, max_new_tokens=32, temperature=0.0,
               deadline_s: Optional[float] = None, priority: int = 0,
               nonce: Optional[int] = None, trace_context=None,
               tenant: Optional[str] = None) -> dict:
        kw = {}
        if tenant is not None:
            # passed only when set so bare submit/cancel stubs (and
            # older engines) keep working tenant-less
            kw["tenant"] = tenant
        fut = self.engine.submit(
            prompt_ids, max_new_tokens=max_new_tokens,
            temperature=temperature, deadline=deadline_s,
            priority=priority, nonce=nonce,
            trace_context=trace_context, **kw)
        out = fut.result(timeout=600)
        out["request_id"] = fut.request_id
        return out

    def health(self) -> Optional[str]:
        if getattr(self.engine, "_closed", False):
            return None
        return self.engine.health

    # an in-process engine's metrics already live in this process's
    # registry — federating them again would double every series in
    # the same /metrics scrape, so local replicas OPT OUT of the
    # FleetScraper (absent from federation, never marked down; they
    # still appear in /fleetz via the router's own per-replica state)
    metrics_opt_out = True

    def metrics_text(self) -> Optional[str]:
        return None

    def cancel(self, request_id: int) -> bool:
        return self.engine.cancel(request_id)

    # KV-page migration (disaggregated fleet): direct handoff to the
    # engine's export/import surface — the same payload the HTTP
    # /kv_pages endpoint carries, minus the serialization hop
    def export_pages(self, digests, trace_context=None) -> dict:
        return self.engine.export_pages(digests)

    def import_pages(self, payload: dict, trace_context=None) -> dict:
        return self.engine.import_pages(payload)

    def close(self) -> None:
        pass   # the engine's owner closes it


class HTTPReplica:
    """Remote replica behind ``serve_llm`` + debug-server endpoints.

    ``generate_url`` is the ``serve_llm`` base (POST /generate,
    POST /cancel); ``healthz_url`` the debug server's /healthz;
    ``metrics_url`` its /metrics (derived from ``healthz_url`` when
    not given — both live on the same debug server)."""

    def __init__(self, generate_url: str, healthz_url: str,
                 timeout: float = 600.0,
                 metrics_url: Optional[str] = None):
        self.generate_url = generate_url.rstrip("/")
        self.healthz_url = healthz_url
        self.metrics_url = metrics_url or (
            healthz_url.rsplit("/healthz", 1)[0] + "/metrics")
        self.timeout = float(timeout)

    def _post(self, path: str, body: dict, timeout: float,
              trace_context=None):
        from urllib.error import HTTPError, URLError
        from urllib.request import Request, urlopen
        headers = {"Content-Type": "application/json"}
        if trace_context is not None:
            # cross-process propagation: the caller's span identity
            # rides the W3C header; a disabled-tracing caller's noop
            # context formats to None and no header is sent
            from ..observability import propagation as _prop
            tp = _prop.format_traceparent(trace_context)
            if tp is not None:
                headers[_prop.TRACEPARENT_HEADER] = tp
        req = Request(self.generate_url + path,
                      data=json.dumps(body).encode(),
                      headers=headers)
        try:
            with urlopen(req, timeout=timeout) as r:
                return r.status, json.loads(r.read() or b"{}")
        except HTTPError as e:
            try:
                payload = json.loads(e.read() or b"{}")
            except ValueError:
                payload = {}
            # backpressure contract (PR 20): a shedding replica's
            # Retry-After header names its cooldown. Captured into
            # the payload (headers win over any body field — the
            # header is the standard surface) so submit() can attach
            # it to the typed verdict and the router can honor it
            # instead of blind-retrying into the same shed.
            ra = e.headers.get("Retry-After") if e.headers else None
            if ra is not None:
                try:
                    payload["retry_after_s"] = float(ra)
                except ValueError:
                    pass      # a malformed header is no header
            return e.code, payload
        except (URLError, OSError, ValueError) as e:
            # connection refused/reset, truncated response: the
            # crashed-or-vanished replica signature
            raise ReplicaUnavailable(
                f"replica at {self.generate_url} unreachable: "
                f"{e}") from e

    def submit(self, prompt_ids, max_new_tokens=32, temperature=0.0,
               deadline_s: Optional[float] = None, priority: int = 0,
               nonce: Optional[int] = None, trace_context=None,
               tenant: Optional[str] = None) -> dict:
        body = {"prompt_ids": list(map(int, prompt_ids)),
                "max_new_tokens": int(max_new_tokens),
                "temperature": float(temperature),
                "priority": int(priority)}
        if deadline_s is not None:
            body["deadline_s"] = float(deadline_s)
        if nonce is not None:
            body["nonce"] = int(nonce)
        if tenant is not None:
            # served-FLOPs attribution label on the replica engine
            body["tenant"] = str(tenant)
        # the HTTP wait must outlive the request's own deadline so the
        # typed 504 arrives instead of a transport timeout
        timeout = self.timeout if deadline_s is None \
            else min(self.timeout, float(deadline_s) + 30.0)
        code, out = self._post("/generate", body, max(timeout, 1.0),
                               trace_context=trace_context)
        if code == 200:
            return out
        err = out.get("error", f"HTTP {code}")
        if code == 429:
            exc = AdmissionShed(err,
                                reason=out.get("reason") or "queue_full")
            # the replica's cooldown rides the verdict: the router's
            # dispatch loop reads it off the exception and keeps the
            # replica out of _route until it expires
            exc.retry_after_s = out.get("retry_after_s")
            raise exc
        if code == 503:
            exc = AdmissionShed(err, reason="draining")
            exc.retry_after_s = out.get("retry_after_s")
            raise exc
        if code == 504:
            raise DeadlineExceeded(err)
        if code == 499:
            raise RequestCancelled(err)
        if code == 400:
            raise ValueError(err)
        raise ReplicaUnavailable(
            f"replica at {self.generate_url} returned HTTP {code}: "
            f"{err}")

    def health(self, timeout: float = 2.0) -> Optional[str]:
        """"healthy"/"degraded"/"draining", or None when unreachable
        (the caller decides what unreachable means — the router
        charges the breaker)."""
        from urllib.error import HTTPError, URLError
        from urllib.request import urlopen
        try:
            with urlopen(self.healthz_url, timeout=timeout) as r:
                body = json.loads(r.read() or b"{}")
        except HTTPError as e:
            if e.code == 503:   # draining flips /healthz to 503
                try:
                    body = json.loads(e.read() or b"{}")
                except ValueError:
                    body = {}
                return body.get("status", "draining")
            return None
        except (URLError, OSError, ValueError):
            return None
        status = body.get("status", "healthy")
        return "healthy" if status == "ok" else status

    def metrics_text(self, timeout: float = 2.0) -> Optional[str]:
        """Scrape the replica's Prometheus text exposition, or None
        when unreachable (the FleetScraper marks the replica down and
        keeps its last-known series out of the federated view)."""
        from urllib.error import HTTPError, URLError
        from urllib.request import urlopen
        try:
            with urlopen(self.metrics_url, timeout=timeout) as r:
                return r.read().decode("utf-8", "replace")
        except (HTTPError, URLError, OSError, ValueError):
            return None

    def cancel(self, request_id: int, trace_context=None) -> bool:
        try:
            code, out = self._post("/cancel",
                                   {"request_id": int(request_id)}, 10.0,
                                   trace_context=trace_context)
        except ReplicaUnavailable:
            return False
        return bool(out.get("cancelled")) if code == 200 else False

    def _kv_pages(self, body: dict, trace_context=None) -> dict:
        code, out = self._post("/kv_pages", body, 60.0,
                               trace_context=trace_context)
        if code == 200:
            return out
        err = out.get("error", f"HTTP {code}")
        if code == 503:
            raise AdmissionShed(err, reason="draining")
        if code == 400:
            raise ValueError(err)
        # 404 (no KV surface), 500 (injected transfer fault), and any
        # other 5xx: the migrate step's fallback-to-recompute signal
        raise ReplicaUnavailable(
            f"replica at {self.generate_url} /kv_pages failed "
            f"(HTTP {code}): {err}")

    def export_pages(self, digests, trace_context=None) -> dict:
        hexes = [d if isinstance(d, str) else d.hex() for d in digests]
        return self._kv_pages({"digests": hexes},
                              trace_context=trace_context)

    def import_pages(self, payload: dict, trace_context=None) -> dict:
        return self._kv_pages({"payload": payload},
                              trace_context=trace_context)

    def close(self) -> None:
        pass


# ---------------------------------------------------------------------------
# spawnable replica subprocess (fleet chaos soak / local scale-out)
# ---------------------------------------------------------------------------

READY_MARK = "REPLICA_READY "


def build_net_from_spec(spec: dict):
    """A small GPT from a JSON-able spec — the one model builder the
    replica subprocess, the fleet soak parent, and the fleet bench
    share, so "same weights on every replica" is true by construction
    (same ``paddle_tpu.seed``)."""
    import paddle_tpu as pt
    from ..models.gpt import GPTForCausalLM, gpt_config
    pt.seed(int(spec.get("model_seed", 0)))
    cfg = gpt_config(
        "gpt2-small",
        num_layers=int(spec.get("layers", 2)),
        hidden_size=int(spec.get("hidden", 64)),
        num_heads=int(spec.get("heads", 4)),
        vocab_size=int(spec.get("vocab", 97)),
        max_position_embeddings=int(spec.get("max_pos", 96)),
        hidden_dropout=0.0, attention_dropout=0.0)
    return GPTForCausalLM(cfg)


def make_engine_from_spec(spec: dict):
    from ..inference.llm import LLMEngine
    net = build_net_from_spec(spec)
    ekw = dict(spec.get("engine", {}))
    ekw.setdefault("max_seqs", 4)
    ekw.setdefault("page_size", 4)
    ekw.setdefault("num_pages", 96)
    ekw.setdefault("prefill_buckets", (16,))
    ekw.setdefault("seed", 0)
    return LLMEngine(net, **ekw)


def _arm_faults(spec: dict) -> None:
    if not spec.get("faults"):
        return
    from ..reliability import faults
    faults.reset()
    faults.enable(seed=int(spec["faults"].get("seed", 0)))
    for rule in spec["faults"].get("rules", ()):
        faults.inject(rule["site"],
                      nth=rule.get("nth"), p=rule.get("p"),
                      times=rule.get("times"))


def replica_main(spec: dict) -> int:
    """Subprocess body: engine + serve_llm + debug server + optional
    TCPStore membership, announced on stdout as one READY line.

    Observability knobs in the spec:

    - ``tracing``: truthy → enable the span table (off by default,
      same one-flag-check discipline as everywhere else) so the
      router's traceparent headers land in a real tree and
      ``/tracez?trace_id=`` answers cross-process queries.
    - ``obs_dir``: base directory for this replica's observability
      artifacts — the flight recorder dumps to
      ``<obs_dir>/<name>/`` and a JSONL metrics reporter appends to
      ``<obs_dir>/<name>/metrics.jsonl``. Without it, K spawned
      replicas sharing a cwd scatter (and with unlucky pids, collide)
      their dumps where no soak can collect them; with it, the fleet
      chaos soak collects every replica's dumps from one tree.
    """
    import jax
    jax.config.update("jax_platforms", spec.get("platform", "cpu"))
    if spec.get("cache_dir"):
        # a fleet compiles K copies of the same tiny programs; the
        # persistent cache makes replica N and every respawn hit
        # replica 1's artifacts (PR 3's compilation_cache_dir wiring,
        # applied fleet-wide)
        jax.config.update("jax_compilation_cache_dir",
                          spec["cache_dir"])
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.0)
    from ..inference.llm import serve_llm
    from ..observability import server as debug
    from ..observability import tracing
    from ..reliability import faults
    from ..reliability.faults import FaultInjected

    name = spec.get("name", f"replica-{os.getpid()}")
    if spec.get("tracing"):
        tracing.enable()
    # GRACEFUL TERMINATE — the planned-departure path beside the
    # ``replica.crash`` site: SIGTERM/SIGINT set a stop event and the
    # main loop runs the same orderly teardown a clean exit would
    # (membership LEAVES the roster, engine closes, server stops).
    # Registered BEFORE the flight recorder installs its own SIGTERM
    # hook so a dump-then-chain still lands here: a preempted replica
    # dumps its flight record AND departs cleanly.
    stop_evt = threading.Event()

    def _graceful(signum, frame):  # noqa: ARG001 — signal signature
        stop_evt.set()

    try:
        signal.signal(signal.SIGTERM, _graceful)
        signal.signal(signal.SIGINT, _graceful)
    except ValueError:
        pass   # not the main thread (embedded use): kill paths only
    reporter = None
    if spec.get("obs_dir"):
        from ..observability import flight
        from ..observability.exporters import JSONLReporter
        my_dir = os.path.join(spec["obs_dir"], name)
        os.makedirs(my_dir, exist_ok=True)
        flight.install_flight_recorder(my_dir)
        reporter = JSONLReporter(
            os.path.join(my_dir, "metrics.jsonl"),
            interval=float(spec.get("jsonl_interval", 2.0)))
    _arm_faults(spec)
    eng = make_engine_from_spec(spec)
    # drift verdicts this engine records (device-retry prefix checks)
    # key the /driftz table by the replica's fleet name, not "engine"
    eng.audit_scope = name
    srv = serve_llm(eng)
    host, port = srv.server_address[:2]
    dbg = debug.start_debug_server()
    info = {"name": name,
            "generate": f"http://{host}:{port}",
            "healthz": f"{dbg.address}/healthz",
            "metrics": f"{dbg.address}/metrics",
            "tracez": f"{dbg.address}/tracez",
            "driftz": f"{dbg.address}/driftz",
            "pid": os.getpid()}
    if spec.get("role"):
        # disaggregated pool membership ("prefill" / "decode"): rides
        # the roster record so the router's membership sync attaches
        # this replica to the right pool
        info["role"] = str(spec["role"])
    member = None
    if spec.get("store"):
        from ..distributed.tcp_store import TCPMembership
        member = TCPMembership(spec["store"], name, info,
                               beat_interval=float(
                                   spec.get("beat_interval", 0.2)))
    print(READY_MARK + json.dumps(info), flush=True)
    try:
        while not stop_evt.is_set():
            time.sleep(0.05)
            if faults.enabled():
                try:
                    faults.check("replica.crash")
                except FaultInjected:
                    # die the way a SIGKILL would: no cleanup, no
                    # goodbye — the fleet must absorb exactly this
                    os._exit(137)
    except KeyboardInterrupt:
        pass
    finally:
        if member is not None:
            # planned departure: LEAVE the roster (delete the record)
            # so the router's membership sync sees this replica gone
            # on its next poll, not after stale_after — a scale-in
            # must not race a re-attach of the replica it just ended
            member.leave()
        if reporter is not None:
            reporter.stop()
        eng.close()
        srv.shutdown()
    return 0


def terminate_replica(proc, timeout: float = 15.0) -> Optional[int]:
    """Graceful terminate for a spawned replica — the scale-in path
    beside the crash site: SIGTERM (the replica leaves membership,
    closes its engine, stops serving), a bounded wait, then SIGKILL
    escalation for a wedged child. Returns the exit code (None only
    if even the SIGKILL wait timed out)."""
    if proc.poll() is None:
        proc.terminate()
        try:
            proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            proc.kill()
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                pass
    return proc.poll()


def spawn_replica(spec: dict, timeout: float = 120.0,
                  env: Optional[dict] = None):
    """Spawn ``python -m paddle_tpu.serving.replica`` and wait for its
    READY line. Returns ``(Popen, info_dict)``; the caller owns the
    process (SIGKILL it, wait() it)."""
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    child_env = dict(os.environ, JAX_PLATFORMS=spec.get(
        "platform", "cpu"), PYTHONPATH=repo)
    if env:
        child_env.update(env)
    proc = subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu.serving.replica",
         json.dumps(spec)],
        env=child_env, cwd=repo, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)

    def _pump_stderr():
        for _ in proc.stderr:
            pass

    threading.Thread(target=_pump_stderr, daemon=True).start()
    # the READY wait must hold its deadline even while BLOCKED in
    # readline (a child wedged mid-compile writes nothing): a daemon
    # reader thread signals through an Event the caller waits on with
    # the real budget
    found = {}
    ready = threading.Event()

    def _read_stdout():
        for line in proc.stdout:
            if line.startswith(READY_MARK):
                found["info"] = json.loads(line[len(READY_MARK):])
                ready.set()
                break
        ready.set()          # EOF: child exited before READY
        for _ in proc.stdout:
            pass             # keep draining so the child never blocks

    threading.Thread(target=_read_stdout, daemon=True).start()
    if not ready.wait(timeout):
        proc.kill()
        raise TimeoutError(
            f"replica {spec.get('name')} not READY in {timeout}s")
    if "info" not in found:
        raise ReplicaUnavailable(
            f"replica {spec.get('name')} exited before READY "
            f"(rc={proc.poll()})")
    return proc, found["info"]


if __name__ == "__main__":
    sys.exit(replica_main(json.loads(sys.argv[1])))
